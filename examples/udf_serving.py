"""SQL-UDF model serving, end to end — the reference's L4 flow.

Mirrors the upstream README's ``registerKerasImageUDF`` example
(``python/sparkdl/udf/keras_image_model.py``†, SURVEY.md §3.3): register a
Keras model as a named SQL UDF, then score an image view with plain SQL —
plus the ``makeGraphUDF`` analog for an arbitrary composed ``XlaFunction``.
Offline-safe: builds a tiny Keras CNN in-process.  Works on the real TPU or
the virtual CPU mesh:

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/udf_serving.py
"""

import os
import tempfile

import numpy as np
from PIL import Image

os.environ.setdefault("KERAS_BACKEND", "jax")


def make_images(root: str, n: int = 12, size: int = 32):
    rng = np.random.RandomState(0)
    for i in range(n):
        Image.fromarray(
            rng.randint(0, 255, (size, size, 3), np.uint8)
        ).save(os.path.join(root, f"img_{i}.png"))


def main():
    import keras

    from sparkdl_tpu import makeGraphUDF, registerKerasImageUDF
    from sparkdl_tpu.graph.function import XlaFunction
    from sparkdl_tpu.image import imageIO
    from sparkdl_tpu.sql.session import TPUSession

    spark = TPUSession.builder.master("local[*]").getOrCreate()
    root = tempfile.mkdtemp(prefix="udf_imgs_")
    make_images(root)
    df = imageIO.readImages(root, spark, numPartitions=2)
    df.createOrReplaceTempView("images")

    # a tiny classifier standing in for InceptionV3 (offline; same plumbing)
    keras.utils.set_random_seed(0)
    model = keras.Sequential(
        [
            keras.layers.Input(shape=(32, 32, 3)),
            keras.layers.Conv2D(8, 3, activation="relu"),
            keras.layers.GlobalAveragePooling2D(),
            keras.layers.Dense(4, activation="softmax"),
        ]
    )

    registerKerasImageUDF("my_cnn", model, session=spark)
    scored = spark.sql("SELECT my_cnn(image) AS probs FROM images").collect()
    print(f"SQL-UDF scored {len(scored)} rows; "
          f"first probs: {np.round(np.asarray(scored[0].probs.toArray()), 3)}")

    # makeGraphUDF: any XlaFunction over tensor columns (the reference's
    # TensorFrames makeGraphUDF analog) — here a composed normalize -> mean
    rng = np.random.RandomState(1)
    tensors = spark.createDataFrame(
        [{"x": rng.rand(16).astype(np.float32).tolist()} for _ in range(8)]
    )
    tensors.createOrReplaceTempView("tensors")
    norm = XlaFunction.from_callable(lambda x: x * 2.0 - 1.0, name="normalize")
    mean = XlaFunction.from_callable(lambda x: x.mean(axis=-1), name="mean")
    makeGraphUDF(norm.compose(mean), "centered_mean", session=spark)
    got = spark.sql(
        "SELECT centered_mean(x) AS m FROM tensors LIMIT 3"
    ).collect()
    print("centered means of first rows:",
          [round(float(r.m), 4) for r in got])


if __name__ == "__main__":
    main()
