"""Continuous SQL: a standing windowed query over a tailed event file.

``sparkdl_tpu.streaming`` commits *records* exactly once; the
continuous-SQL layer commits *windows* exactly once.  This example
walks the whole flow, offline-safe:

1. a producer thread appends latency observations to ``scores.jsonl``
   — the growing file a metrics shipper would write — including two
   **late** rows whose event time is far behind the stream;
2. :class:`FileTailSource` tails it and the session registers it as
   stream table ``scores`` (``session.readStream``);
3. a standing query groups rows into tumbling 2 s event-time windows
   and reduces each with ``p95`` — the latencies first pass through a
   model UDF served by a :class:`ModelServer` endpoint, so scoring
   rides the same admission queue as interactive traffic;
4. closed windows land in a :class:`JsonlSink` through the commit
   log's payload-then-marker protocol — every window exactly once —
   while the late rows are diverted to a side-output sink, counted,
   never silently dropped;
5. mid-window the process receives **SIGTERM**: the query flushes
   admitted rows into committed state and stops cleanly
   (``stop_reason="preempted"``), then a second query *resumes from
   the checkpoint* — restored window state, no re-aggregation — and
   finishes the stream.

Works on the real TPU or the virtual CPU mesh:

    JAX_PLATFORMS=cpu python examples/continuous_query.py
"""

import json
import os
import signal
import tempfile
import threading

import numpy as np

os.environ.setdefault("KERAS_BACKEND", "jax")

N_EVENTS = 80          # regular observations, 250 ms apart
WINDOW_MS = 2_000.0    # tumbling window size
LATE_AT = (60, 70)     # inject a stale row after these event indices
FLUSH_TS_MS = 60_000.0  # sentinel far in the future: closes every window

QUERY = (
    "SELECT endpoint, p95(normalize(latency)) AS p95_s, count(*) AS n "
    "FROM scores GROUP BY WINDOW(event_time_ms, '2s'), endpoint"
)


def main():
    from sparkdl_tpu import JsonlSink, StreamConfig
    from sparkdl_tpu.serving import ModelServer, ServingConfig
    from sparkdl_tpu.sql import TPUSession
    from sparkdl_tpu.sql.functions import UserDefinedFunction
    from sparkdl_tpu.streaming import FileTailSource

    workdir = tempfile.mkdtemp(prefix="continuous-query-")
    events_path = os.path.join(workdir, "scores.jsonl")
    out_path = os.path.join(workdir, "windows.jsonl")
    late_path = os.path.join(workdir, "late.jsonl")
    log_dir = os.path.join(workdir, "checkpoint")

    # -- 1. the producer: latency observations, two of them stale ------
    done_producing = threading.Event()

    def produce():
        pace = threading.Event()
        with open(events_path, "a") as fh:
            for i in range(N_EVENTS):
                fh.write(json.dumps({
                    "endpoint": "search" if i % 2 else "checkout",
                    "latency": float(i % 97),
                    "event_time_ms": 250.0 * i,
                }) + "\n")
                if i in LATE_AT:  # a straggler from a slow shipper
                    fh.write(json.dumps({
                        "endpoint": "search",
                        "latency": 999.0,
                        "event_time_ms": 0.0,
                    }) + "\n")
                fh.flush()
                pace.wait(0.02)
            # sentinel: advances the watermark past every real window
            fh.write(json.dumps({
                "endpoint": "flush",
                "latency": 0.0,
                "event_time_ms": FLUSH_TS_MS,
            }) + "\n")
            fh.flush()
        done_producing.set()

    producer = threading.Thread(target=produce, name="score-producer")
    producer.start()

    # -- 3. a served model UDF normalizes latencies in-query -----------
    with ModelServer(config=ServingConfig(max_batch=16)) as server:
        session = TPUSession.builder.appName("continuous-query").getOrCreate()
        udf = UserDefinedFunction(lambda v: v * 0.001, name="normalize")
        udf._serving_endpoint = {
            "model_id": "normalize",
            "forward": lambda batch: batch * 0.001,  # ms -> seconds
            "item_shape": (),
            "dtype": np.float32,
            "fingerprint": None,
        }
        registered = session.udf.register("normalize", udf)
        registered._serving_endpoint = udf._serving_endpoint

        def make_query():
            # a fresh tail each time: recovery seeks it to the last
            # committed byte offset and restores open-window state
            session.readStream(
                "scores",
                FileTailSource(events_path, event_time_field="event_time_ms"),
            )
            return session.sqlStream(
                QUERY,
                JsonlSink(out_path),
                log_dir,
                late_sink=JsonlSink(late_path),
                server=server,
                config=StreamConfig(
                    max_batch=8, max_wait_ms=20.0, allowed_lateness_ms=500.0
                ),
                name="p95-by-endpoint",
            )

        # -- 5a. first run, preempted mid-window by a real SIGTERM -----
        threading.Timer(
            0.5, os.kill, args=(os.getpid(), signal.SIGTERM)
        ).start()
        with make_query() as query:
            first = query.run(idle_timeout_s=10.0)
        print(
            f"first run: stop_reason={first['stop_reason']} "
            f"epochs={first['epochs']} "
            f"windows_emitted={first['windows_emitted']} "
            f"committed_offset={first['committed_offset']}"
        )
        assert first["stop_reason"] == "preempted", first

        # -- 5b. restart: resume from the checkpoint -------------------
        producer.join()
        with make_query() as query:
            second = query.run(idle_timeout_s=2.0)
        print(
            f"resumed run: stop_reason={second['stop_reason']} "
            f"windows_emitted={second['windows_emitted']} "
            f"late_rows={second['late_rows']} "
            f"watermark_ms={second['watermark_ms']}"
        )

    # -- 4. exactly-once: every window emitted once, late rows kept ----
    rows = [r for r in JsonlSink(out_path).read_all()
            if r["endpoint"] != "flush"]
    keys = [(r["window_start"], r["endpoint"]) for r in rows]
    assert len(keys) == len(set(keys)), "a window was emitted twice"
    n_windows = int(N_EVENTS * 250.0 // WINDOW_MS)
    assert len(rows) == 2 * n_windows, (n_windows, sorted(keys))
    assert sum(r["n"] for r in rows) == N_EVENTS
    for r in rows:  # the UDF really ran: p95 is in seconds, not ms
        assert 0.0 <= r["p95_s"] < 0.1, r
    late = JsonlSink(late_path).read_all()
    assert len(late) == len(LATE_AT), late
    assert all(r["input"]["latency"] == 999.0 for r in late)
    worst = max(rows, key=lambda r: r["p95_s"])
    print(
        f"worst window: endpoint={worst['endpoint']} "
        f"start={worst['window_start']:.0f}ms p95={worst['p95_s']:.4f}s"
    )
    print(
        f"closed {len(rows)} windows exactly once across a SIGTERM, "
        f"{len(late)} late rows preserved in the side output "
        f"(sink={out_path})"
    )
    print("continuous query OK")


if __name__ == "__main__":
    main()
