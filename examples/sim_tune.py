"""Record -> replay -> tune, end to end, in seconds.

ISSUE-17's closed loop over the serving plane's knobs, run against the
committed fixture trace (any ``benchmarks/bench_load.py
--record-traces`` dump works the same way):

1. **load** a recorded trace — one JSONL row per live request with its
   arrival time and per-phase latencies;
2. **replay** it against the *real* control-plane objects (router,
   micro-batcher, admission queue, SLO engine) on a virtual
   event-loop clock, 100x+ faster than the wall clock, and check the
   replay reproduces the live tail within tolerance;
3. **stress** the same trace at 4x the recorded arrival rate — the
   dial that shows where the current config runs out of headroom
   without touching production;
4. **tune**: random search + successive halving over the knob space
   against SLO burn, emitting the same artifact shape
   ``ci/perf_gate.py --sim`` regression-gates as ``ci/sim_tuned.json``.

No devices needed — the simulator never runs a forward pass:

    python examples/sim_tune.py
"""

import json

from sparkdl_tpu.sim import (
    FleetReplay,
    fidelity_report,
    load_trace,
    replay_trace,
    summarize,
)
from sparkdl_tpu.sim.tune import tune

TRACE = "tests/fixtures/sim_trace_small.jsonl"

#: the config the fixture was recorded under (the demo fleet's
#: serving/replica.py factories) — fidelity means replaying the
#: live run's own knobs, not the sim defaults
LIVE_CONFIG = {
    "replicas": 2, "max_batch": 16, "max_wait_ms": 1.0,
    "queue_capacity": 512,
}

meta, records = load_trace(TRACE)
print(f"trace: {len(records)} requests over "
      f"{records[-1].t - records[0].t:.1f}s "
      f"({meta.get('scenario')}, {meta.get('rate')} rps offered)")

# -- replay at recorded speed: does the model match the fleet? --------
report = replay_trace(records, config=LIVE_CONFIG, seed=0)
print(f"replay: {report['virtual_s']:.1f} virtual seconds in "
      f"{report['wall_s']*1000:.0f} ms wall "
      f"({report['speedup']:.0f}x real time)")

# fidelity over the steady-state window (warmup compiles are one-time)
def steady(rs):
    return summarize([r for r in rs if r.t >= 1.0])


fr = FleetReplay(records, config=LIVE_CONFIG, seed=0)
fr.run()
fid = fidelity_report(steady(records), steady(fr.results),
                      tolerance=0.15, floor_ms=0.25)
print(f"fidelity: {'PASS' if fid['pass'] else 'FAIL'} "
      f"({len(fid['rows'])} p50/p99 comparisons within 15% or 0.25ms)")
for label in ("e2e.p50", "e2e.p99"):
    row = fid["rows"][label]
    print(f"  {label}: live {row['live']:.2f}ms  sim {row['sim']:.2f}ms")

# -- stress: the same trace at 4x the recorded arrival rate -----------
stressed = replay_trace(records, config=LIVE_CONFIG, seed=0,
                        time_scale=4.0)
print(f"4x stress: p99 {report['latency_ms']['p99']:.1f}ms -> "
      f"{stressed['latency_ms']['p99']:.1f}ms, "
      f"shed {stressed['shed']}, expired {stressed['expired']} — "
      f"this config has no 4x headroom")

# -- tune: search the knob space against SLO burn under stress --------
artifact = tune(records, budget=8, seed=0, time_scale=4.0,
                trace_path=TRACE)
rec, dfl = artifact["recommended"], artifact["default"]
print(f"tuned:  burn {dfl['burn_integral']:.1f} -> "
      f"{rec['burn_integral']:.1f} "
      f"(score {dfl['score']:.2f} -> {rec['score']:.2f})")
print("recommended config:",
      json.dumps(rec["config"], sort_keys=True))
