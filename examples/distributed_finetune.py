"""Distributed fine-tuning with hyperparameter search — the training flow
the reference kept driver-local (SURVEY.md §3.2: ``collect()`` to the
driver, Keras ``model.fit`` on one machine), rebuilt as a sharded DP
program: ``KerasImageFileEstimator.fit`` runs a shard_map training step
with gradient allreduce over every local device, checkpoints via orbax,
and ``fitMultiple`` fans a param grid out for tuning.

Offline-safe (tiny Keras CNN, synthetic images).  Works on the real TPU or
the virtual mesh:

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/distributed_finetune.py

Multi-host (one process per TPU host; see tests/test_multihost.py for a
runnable 2-process template):

    SPARKDL_COORDINATOR=host0:9999 SPARKDL_NUM_PROCS=2 \
    SPARKDL_PROC_ID=<rank> python examples/distributed_finetune.py

— ``parallel.runner.initialize`` reads those env vars, forms the global
mesh, and ``fit`` feeds each host only its own data shard.
"""

import os
import tempfile

import numpy as np
from PIL import Image

os.environ.setdefault("KERAS_BACKEND", "jax")

IMAGE = 32
CLASSES = 2


def image_loader(uri):
    from PIL import Image as PILImage

    return np.asarray(PILImage.open(uri), dtype=np.float32) / 255.0


def main():
    import keras

    from sparkdl_tpu.estimators import KerasImageFileEstimator
    from sparkdl_tpu.parallel import runner
    from sparkdl_tpu.sql.session import TPUSession

    if os.environ.get("SPARKDL_COORDINATOR"):
        # initialize() reads SPARKDL_COORDINATOR / SPARKDL_NUM_PROCS /
        # SPARKDL_PROC_ID itself (on a real pod all of it is auto-discovered)
        runner.initialize()

    spark = TPUSession.builder.master("local[*]").getOrCreate()
    # a STABLE working dir (data, base model, checkpoints): the checkpoint
    # namespace includes the modelFile path, so a per-run tempdir would give
    # every run a fresh namespace and resume-after-kill could never engage
    root = os.environ.get(
        "SPARKDL_DEMO_DIR",
        os.path.join(tempfile.gettempdir(), "sparkdl_finetune_demo"),
    )
    os.makedirs(root, exist_ok=True)

    rng = np.random.RandomState(0)
    rows = []
    for i in range(48):
        label = i % CLASSES
        img = rng.randint(0, 80, (IMAGE, IMAGE, 3), np.uint8)
        img[..., label] += 120
        path = os.path.join(root, f"img_{i}.png")
        Image.fromarray(img).save(path)
        rows.append({"uri": path, "label": float(label)})
    df = spark.createDataFrame(rows)

    keras.utils.set_random_seed(0)
    model = keras.Sequential(
        [
            keras.layers.Input(shape=(IMAGE, IMAGE, 3)),
            keras.layers.Conv2D(8, 3, activation="relu"),
            keras.layers.GlobalAveragePooling2D(),
            keras.layers.Dense(CLASSES, activation="softmax"),
        ]
    )
    model_path = os.path.join(root, "base.keras")
    model.save(model_path)

    est = KerasImageFileEstimator(
        inputCol="uri",
        outputCol="preds",
        labelCol="label",
        imageLoader=image_loader,
        modelFile=model_path,
        kerasOptimizer="adam",
        kerasLoss="sparse_categorical_crossentropy",
        kerasFitParams={"epochs": 6, "batch_size": 16, "learning_rate": 1e-3},
        # under the stable root: a killed run resumes from its last
        # committed epoch on relaunch
        checkpointDir=os.path.join(root, "ckpt"),
    )

    # hyperparameter search: fitMultiple fans the grid out (the reference's
    # CrossValidator(parallelism=k) path — SURVEY.md §2)
    grid = [
        {est.kerasFitParams: {"epochs": 6, "batch_size": 16,
                              "learning_rate": lr}}
        for lr in (1e-2, 1e-3)
    ]
    # fitMultiple yields (index, model) in completion order — place by index
    models = [None] * len(grid)
    for index, m in est.fitMultiple(df, grid):
        models[index] = m
    print(f"fitMultiple trained {len(models)} models over the device mesh")

    scored = models[0].transform(df).collect()
    probs = np.stack([np.asarray(r.preds.toArray()) for r in scored])
    acc = float(
        (probs.argmax(axis=1) == np.asarray(
            [r.label for r in scored])).mean()
    )
    print(f"fine-tuned model (lr=1e-2) train accuracy: {acc:.2f}")

    vit_finetune_from_pretrained(df, root)


def vit_finetune_from_pretrained(df, root):
    """The stretch config (BASELINE.json #5): ViT fine-tune from PRETRAINED
    weights, ingested through the google-research ``.npz`` checkpoint path
    (``models/vit_port.py`` — the ViT analog of the CNN zoo's
    "weights='imagenet'" contract).

    Point ``SPARKDL_VIT_WEIGHTS`` at a real downloaded checkpoint (e.g.
    ``ViT-Ti_16.npz``) to fine-tune from it.  Offline, the example
    self-produces the artifact — from an independent HuggingFace torch ViT
    when ``transformers`` is installed (exercising the cross-framework
    port), else from a fresh Flax init — and ingests it through the
    identical ``port_vit_npz`` path a downloaded file would take.
    """
    from sparkdl_tpu.estimators.flax_image_file_estimator import (
        FlaxImageFileEstimator,
    )
    from sparkdl_tpu.models.vit import VIT_VARIANTS, ViT
    from sparkdl_tpu.models.vit_port import (
        adapt_vit_variables,
        export_vit_npz,
        port_vit_npz,
    )

    variant = "ViT-Ti/16"
    patch, dim, depth, heads, mlp_dim = VIT_VARIANTS[variant]
    weights_path = os.environ.get("SPARKDL_VIT_WEIGHTS")
    exact_gelu = False
    if not weights_path:
        weights_path = os.path.join(root, "vit_pretrained.npz")
        try:  # independent-source artifact: HF torch ViT -> npz
            import torch
            import transformers

            from sparkdl_tpu.models.vit_port import port_hf_vit

            torch.manual_seed(0)
            hf = transformers.ViTForImageClassification(
                transformers.ViTConfig(
                    hidden_size=dim, num_hidden_layers=depth,
                    num_attention_heads=heads, intermediate_size=mlp_dim,
                    image_size=IMAGE, patch_size=patch, num_labels=CLASSES,
                    layer_norm_eps=1e-6,
                )
            ).eval()
            export_vit_npz(port_hf_vit(hf), weights_path, heads=heads)
            exact_gelu = True  # HF weights were trained under erf gelu
            source = "HuggingFace torch ViT"
        except ImportError:
            import jax
            import jax.numpy as jnp

            module = ViT(variant=variant, num_classes=CLASSES,
                         image_size=IMAGE)
            init = module.init(
                jax.random.PRNGKey(0),
                jnp.zeros((1, IMAGE, IMAGE, 3), jnp.float32),
            )
            export_vit_npz(init, weights_path, heads=heads)
            source = "self-initialized Flax ViT"
        print(f"produced pretrained artifact from {source}: {weights_path}")

    variables = port_vit_npz(weights_path)
    # a real checkpoint carries 224²-geometry pos embeddings and (usually)
    # a 1000-class head: interpolate the grid embeddings to this demo's
    # resolution and zero-init a head for the demo's label set
    variables = adapt_vit_variables(
        variables, image_size=IMAGE, num_classes=CLASSES
    )
    module = ViT(variant=variant, num_classes=CLASSES, image_size=IMAGE,
                 exact_gelu=exact_gelu)
    est = FlaxImageFileEstimator(
        inputCol="uri",
        outputCol="logits",
        labelCol="label",
        imageLoader=image_loader,
        module=module,
        optimizer="adam",
        fitParams={"epochs": 2, "batch_size": 16, "learning_rate": 1e-3},
        initialVariables=variables,
    )
    fitted = est.fit(df)
    scored = fitted.transform(df).collect()
    logits = np.stack([np.asarray(r.logits.toArray()) for r in scored])
    acc = float(
        (logits.argmax(axis=1) == np.asarray(
            [r.label for r in scored])).mean()
    )
    print(f"ViT fine-tune from ported weights: train accuracy {acc:.2f}")


if __name__ == "__main__":
    main()
