"""SQL model serving + analytics — score images with a Keras model through
``registerKerasImageUDF``, JOIN the scored view against a ground-truth
table, and aggregate with the engine's SQL dialect (WHERE / JOIN / GROUP
BY / HAVING / ORDER BY), the serving-side flow the reference enabled with
TensorFrames UDFs + Spark SQL (SURVEY.md §3.3).

Offline-safe (synthetic images, tiny random-init model).  Works on the
real TPU or the virtual CPU mesh:

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/sql_analytics.py
"""

import os
import tempfile

import numpy as np
from PIL import Image

os.environ.setdefault("KERAS_BACKEND", "jax")


def main():
    import keras

    from sparkdl_tpu.image import imageIO
    from sparkdl_tpu.sql.session import TPUSession
    from sparkdl_tpu.udf.keras_image_model import registerKerasImageUDF

    spark = TPUSession.builder.master("local[*]").getOrCreate()
    root = tempfile.mkdtemp(prefix="sparkdl_sql_demo_")
    rng = np.random.RandomState(0)
    for i in range(24):
        Image.fromarray(
            (rng.rand(32, 32, 3) * 255).astype(np.uint8)
        ).save(os.path.join(root, f"img_{i:02d}.png"))

    df = imageIO.readImages(root, session=spark, numPartitions=4)
    df = df.withColumn(
        "label", lambda im: int(im["origin"][-6:-4]) % 3, "image"
    )
    df.createOrReplaceTempView("images")

    keras.utils.set_random_seed(1)
    model = keras.Sequential(
        [
            keras.layers.Input(shape=(32, 32, 3)),
            keras.layers.Conv2D(4, 3, activation="relu"),
            keras.layers.GlobalAveragePooling2D(),
            keras.layers.Dense(1),
        ]
    )
    model_path = os.path.join(root, "scorer.keras")
    model.save(model_path)
    registerKerasImageUDF("score_img", model_path)

    # score every image on-device (the UDF runs the jitted fused program,
    # pipelined decode/dispatch), keeping only big-enough images
    scored = spark.sql(
        "SELECT label, score_img(image) AS s FROM images "
        "WHERE image.height > 16"
    )
    scored = scored.withColumn(
        "score", lambda v: float(v.toArray()[0]), "s"
    )
    scored.createOrReplaceTempView("scored")

    # per-label analytics over the model outputs
    out = spark.sql(
        "SELECT label, COUNT(*) AS n, AVG(score) AS mean_score, "
        "MAX(score) AS best FROM scored "
        "GROUP BY label HAVING n > 1 ORDER BY mean_score DESC"
    ).collect()
    for r in out:
        print(
            f"label={r.label}  n={r.n}  mean={r.mean_score:.4f}  "
            f"best={r.best:.4f}"
        )
    assert len(out) == 3 and all(r.n == 8 for r in out)

    # JOIN the predictions against a metadata/ground-truth table — the
    # canonical "score then analyze" flow: which label class does each
    # annotated category score highest on?
    spark.createDataFrame(
        [(0, "landscape"), (1, "portrait"), (2, "abstract")],
        ["label", "category"],
    ).createOrReplaceTempView("categories")
    joined = spark.sql(
        "SELECT category, COUNT(*) AS n, AVG(score) AS mean_score "
        "FROM scored JOIN categories ON scored.label = categories.label "
        "GROUP BY category ORDER BY mean_score DESC"
    ).collect()
    for r in joined:
        print(f"category={r.category}  n={r.n}  mean={r.mean_score:.4f}")
    assert len(joined) == 3 and all(r.n == 8 for r in joined)
    # LEFT JOIN keeps rows whose label has no category annotation
    spark.createDataFrame(
        [(0, "landscape")], ["label", "category"]
    ).createOrReplaceTempView("sparse_categories")
    uncat = spark.sql(
        "SELECT label, category FROM scored LEFT JOIN sparse_categories "
        "ON scored.label = sparse_categories.label WHERE category IS NULL"
    ).collect()
    assert {r.label for r in uncat} == {1, 2}

    # top-K scored images per label — the canonical serving-analytics
    # idiom, a ranking window inside a derived table filtered on rank
    topk = spark.sql(
        "SELECT label, score, rn FROM ("
        "  SELECT label, score, ROW_NUMBER() OVER "
        "    (PARTITION BY label ORDER BY score DESC) AS rn FROM scored"
        ") t WHERE t.rn <= 2 ORDER BY label, rn"
    ).collect()
    assert len(topk) == 6  # 3 labels x top-2
    for r in topk:
        print(f"label={r.label}  rank={r.rn}  score={r.score:.4f}")
    # the window's #1 must agree with the aggregate MAX per label
    best_by_window = {r.label: r.score for r in topk if r.rn == 1}
    assert best_by_window == {r.label: r.best for r in out}

    # the same analytics through the pyspark-functions surface:
    # per-label share of total score, then a wide per-category pivot
    import sparkdl_tpu.sql.functions as F
    from sparkdl_tpu.sql.functions import Window, col

    scored_df = spark.table("scored")
    share = (
        scored_df
        .withColumn(
            "tot", F.sum("score").over(Window.partitionBy("label"))
        )
        .withColumn("share", col("score") / col("tot"))
    )
    assert abs(sum(r.share for r in share.collect()) - 3.0) < 1e-6
    wide = (
        spark.table("scored")
        .join(spark.table("categories"), on="label")
        .groupBy("label").pivot("category").agg(F.avg("score"))
    )
    assert wide.count() == 3 and len(wide.columns) == 4
    print("sql analytics OK")


if __name__ == "__main__":
    main()
