"""Streaming scoring: tail a growing file, score online, commit exactly-once.

The batch stack scores fixed DataFrames; :mod:`sparkdl_tpu.streaming`
scores *unbounded* sources with exactly-once delivery.  This example
walks the whole flow, offline-safe:

1. a producer thread appends JSON events to ``events.jsonl`` — the
   growing file a log shipper or feature bus would write;
2. :class:`FileTailSource` tails it by byte offset, extracting event
   times for the bounded-lateness watermark;
3. each micro-batch is scored through a registered
   :class:`ModelServer` endpoint (riding its admission control and
   micro-batcher, sharing capacity with interactive traffic);
4. scored records land in a :class:`JsonlSink` through the commit log's
   payload-then-marker protocol — every record exactly once;
5. mid-run the process receives **SIGTERM**: the runner flushes
   in-flight epochs into committed state and stops cleanly
   (``stop_reason="preempted"``), then a second runner *resumes from
   the last committed offset* and finishes the stream.

Works on the real TPU or the virtual CPU mesh:

    JAX_PLATFORMS=cpu python examples/streaming_scoring.py
"""

import json
import os
import signal
import tempfile
import threading

import numpy as np

os.environ.setdefault("KERAS_BACKEND", "jax")

N_EVENTS = 60
FEATURES = 4


def main():
    from sparkdl_tpu import JsonlSink, StreamConfig, StreamRunner
    from sparkdl_tpu.serving import ModelServer, ServingConfig
    from sparkdl_tpu.streaming import FileTailSource

    workdir = tempfile.mkdtemp(prefix="streaming-scoring-")
    events_path = os.path.join(workdir, "events.jsonl")
    scores_path = os.path.join(workdir, "scores.jsonl")
    log_dir = os.path.join(workdir, "commit-log")

    # -- 1. the producer: a log shipper appending events over time -----
    rng = np.random.RandomState(0)
    done_producing = threading.Event()

    def produce():
        pace = threading.Event()
        with open(events_path, "a") as fh:
            for i in range(N_EVENTS):
                event = {
                    "id": i,
                    "f": [round(float(v), 4) for v in rng.rand(FEATURES)],
                    "event_time_ms": 1_000.0 * i,
                }
                fh.write(json.dumps(event) + "\n")
                fh.flush()
                pace.wait(0.02)
        done_producing.set()

    producer = threading.Thread(target=produce, name="event-producer")
    producer.start()

    # -- 2/3. a registered endpoint scores the stream ------------------
    with ModelServer(config=ServingConfig(max_batch=16)) as server:
        server.register(
            "scorer",
            lambda batch: batch.sum(axis=-1),
            item_shape=(FEATURES,),
            compile=False,
        )

        def score(batch):
            futures = [
                server.submit(
                    np.asarray(rec["f"], dtype=np.float32),
                    model_id="scorer",
                )
                for rec in batch
            ]
            return [f.result() for f in futures]

        def make_runner():
            # a fresh tail each time: recovery seeks it to the last
            # committed byte offset, so restarts never re-read history
            source = FileTailSource(
                events_path, event_time_field="event_time_ms"
            )
            return StreamRunner(
                source,
                score,
                JsonlSink(scores_path),
                log_dir,
                config=StreamConfig(
                    max_batch=8, max_wait_ms=20.0, allowed_lateness_ms=500.0
                ),
                pack=False,
            )

        # -- 5a. first run, preempted mid-stream by a real SIGTERM -----
        threading.Timer(
            0.4, os.kill, args=(os.getpid(), signal.SIGTERM)
        ).start()
        with make_runner() as runner:
            first = runner.run(idle_timeout_s=10.0)
        print(
            f"first run: stop_reason={first['stop_reason']} "
            f"epochs={first['epochs']} "
            f"committed_offset={first['committed_offset']}"
        )
        assert first["stop_reason"] == "preempted", first

        # -- 5b. restart: resume from the last committed offset --------
        producer.join()
        with make_runner() as runner:
            second = runner.run(idle_timeout_s=2.0)
        print(
            f"resumed run: stop_reason={second['stop_reason']} "
            f"epochs={second['epochs']} replayed={second['replayed']} "
            f"watermark_ms={second['watermark_ms']}"
        )

    # -- 4. exactly-once: every event scored, none twice ---------------
    rows = JsonlSink(scores_path).read_all()
    ids = sorted(row["input"]["id"] for row in rows)
    assert ids == list(range(N_EVENTS)), (
        f"delivery broken: {len(ids)} rows, {len(set(ids))} unique"
    )
    for row in rows:
        expected = sum(row["input"]["f"])
        assert abs(row["output"] - expected) < 1e-4
    print(
        f"scored {len(rows)} events exactly once across a SIGTERM "
        f"(sink={scores_path})"
    )
    print("streaming scoring OK")


if __name__ == "__main__":
    main()
