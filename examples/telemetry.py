"""The telemetry plane, end to end: serve, breach an SLO, scrape live.

PR 8's observability stack in one runnable flow:

1. a :class:`ModelServer` endpoint goes up and
   :meth:`~sparkdl_tpu.serving.server.ModelServer.start_telemetry`
   attaches the whole plane — a
   :class:`~sparkdl_tpu.obs.timeseries.TimeSeriesRecorder` sampling the
   metric registry, an :class:`~sparkdl_tpu.obs.slo.SLOEngine` with the
   endpoint's latency + error-rate objectives, and the
   :class:`~sparkdl_tpu.obs.server.ObsServer` introspection HTTP server
   (``/metrics``, ``/healthz``, ``/slo``, ``/debug/*``);
2. healthy traffic flows and the live endpoints are scraped over real
   HTTP — the same requests a Prometheus scraper or an orchestrator's
   health probe would make;
3. a latency regression is induced; the fast-burn window flips the SLO
   out of ``ok`` within seconds and the flip is visible at ``/slo``,
   in the ``slo.*`` gauges on ``/metrics``, and in ``/healthz``'s
   ``slo_worst`` field;
4. a :class:`~sparkdl_tpu.obs.blackbox.FlightRecorder` rides along and
   leaves a post-mortem dump of the whole episode (spans, breadcrumbs,
   metric samples, thread stacks).

Works on the real TPU or the virtual CPU mesh:

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/telemetry.py
"""

import json
import tempfile
import time
import urllib.request

import numpy as np

DELAY = {"s": 0.0}  # the induced-regression knob the endpoint reads


def forward(x):
    if DELAY["s"]:
        time.sleep(DELAY["s"])
    return x * 2.0


def scrape(url):
    with urllib.request.urlopen(url, timeout=10.0) as resp:
        return resp.read().decode()


def main():
    from sparkdl_tpu import ModelServer, ServingConfig
    from sparkdl_tpu.obs import FlightRecorder, tracer

    blackbox_dir = tempfile.mkdtemp(prefix="sparkdl-telemetry-bb-")
    recorder = FlightRecorder(blackbox_dir, interval_s=0.2)
    recorder.start()
    tracer.enable(recorder)  # final spans land in the post-mortem ring

    server = ModelServer(ServingConfig(max_wait_ms=1.0))
    server.register("demo", forward, item_shape=(8,), compile=False)

    with server:
        obs = server.start_telemetry(
            sample_interval_s=0.05,
            slo_interval_s=0.1,
            latency_threshold_ms=50.0,   # p99 objective: under 50 ms
            fast_window_s=0.5,
            slow_window_s=5.0,
        )
        print(f"telemetry plane up at {obs.url}")

        def request():
            server.submit(
                np.ones((8,), dtype=np.float32)
            ).result(timeout=10.0)

        # -- healthy traffic, scraped live --------------------------------
        for _ in range(25):
            request()
        metrics_text = scrape(obs.url + "/metrics")
        assert "serving_requests_demo 25" in metrics_text
        health = json.loads(scrape(obs.url + "/healthz"))
        assert health["healthy"] is True
        slo = json.loads(scrape(obs.url + "/slo"))
        print(
            f"healthy: /healthz 200 (slo_worst={health['slo_worst']}), "
            f"{len(slo['slos'])} objectives registered"
        )

        # -- induced latency regression -----------------------------------
        DELAY["s"] = 0.12  # every request now far over the 50 ms objective
        recorder.note("regression_induced", delay_s=DELAY["s"])
        deadline = time.monotonic() + 30.0
        worst = "ok"
        while worst == "ok" and time.monotonic() < deadline:
            request()
            worst = json.loads(scrape(obs.url + "/slo"))["worst"]
        assert worst in ("warning", "page"), worst
        row = next(
            r for r in json.loads(scrape(obs.url + "/slo"))["slos"]
            if r["name"] == "serving.demo.latency"
        )
        print(
            f"SLO breach detected: serving.demo.latency -> {row['state']} "
            f"(burn_fast={row['burn_fast']:.0f}x budget)"
        )
        assert "slo_serving_demo_latency_state" in scrape(
            obs.url + "/metrics"
        )

        # -- the flight recorder kept the episode -------------------------
        dump_path = recorder.dump("example_episode")
        recorder.stop()
        with open(dump_path) as fh:
            dump = json.load(fh)
        assert any(
            e["name"] == "regression_induced" for e in dump["events"]
        )
        # the engine emits a span per transition; the recorder is a
        # tracer sink, so the flip itself is in the post-mortem ring
        assert any(
            s["name"] == "slo.transition" for s in dump["spans"]
        )
        print(
            f"flight recorder dump: {len(dump['spans'])} spans, "
            f"{len(dump['events'])} breadcrumbs, "
            f"{len(dump['metric_samples'])} metric samples"
        )

    print("telemetry example complete: scrape -> breach -> post-mortem")


if __name__ == "__main__":
    main()
