"""The reference's flagship flow, end to end: featurize with a pretrained
CNN, train a LogisticRegression head, evaluate, and serve via SQL UDF.

Mirrors the upstream README example (tf-flowers transfer learning —
``DeepImageFeaturizer`` + ``LogisticRegression`` in a Spark ML Pipeline)
on a synthetic dataset, so it runs offline.  Works on the real TPU or the
virtual CPU mesh:

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/transfer_learning.py

Pass ``--model`` to pick the backbone and ``--weights imagenet`` when the
Keras cache is available (offline default: deterministic random weights —
the plumbing is identical, accuracy is what suffers).
"""

import argparse
import os
import tempfile

import numpy as np
from PIL import Image


def make_dataset(root: str, n: int = 32, size: int = 96):
    """Two synthetic 'flower' classes: red-dominant vs blue-dominant."""
    rng = np.random.RandomState(0)
    rows = []
    for i in range(n):
        label = i % 2
        img = rng.randint(0, 80, (size, size, 3), np.uint8)
        img[..., 2 if label else 0] += 120  # blue vs red dominance
        path = os.path.join(root, f"flower_{i}.png")
        Image.fromarray(img).save(path)
        rows.append((path, label))
    return rows


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", default="MobileNetV2")
    ap.add_argument("--weights", default="random",
                    help="'imagenet' (needs Keras cache) or 'random'")
    ap.add_argument("--n", type=int, default=32)
    args = ap.parse_args()

    from sparkdl_tpu import DeepImageFeaturizer
    from sparkdl_tpu.image import imageIO
    from sparkdl_tpu.ml.classification import LogisticRegression
    from sparkdl_tpu.ml.evaluation import MulticlassClassificationEvaluator
    from sparkdl_tpu.ml.pipeline import Pipeline
    from sparkdl_tpu.sql.session import TPUSession

    spark = TPUSession.builder.master("local[*]").getOrCreate()

    root = tempfile.mkdtemp(prefix="flowers_")
    rows = make_dataset(root, n=args.n)
    labels = {path: label for path, label in rows}

    df = imageIO.readImages(root, spark, numPartitions=4)
    df = df.withColumn(
        "label", lambda img: labels[img["origin"]], "image"
    )
    train, test = df.randomSplit([0.75, 0.25], seed=7)

    pipeline = Pipeline(stages=[
        DeepImageFeaturizer(
            inputCol="image", outputCol="features",
            modelName=args.model, modelWeights=args.weights,
        ),
        LogisticRegression(
            featuresCol="features", labelCol="label", maxIter=30,
        ),
    ])
    model = pipeline.fit(train)

    predictions = model.transform(test)
    evaluator = MulticlassClassificationEvaluator(
        labelCol="label", predictionCol="prediction", metricName="accuracy"
    )
    acc = evaluator.evaluate(predictions)
    print(f"transfer-learning accuracy ({args.model}, "
          f"{args.weights} weights): {acc:.2f}")

    # persistence round trip — the fitted pipeline is a first-class stage
    save_dir = os.path.join(root, "fitted_pipeline")
    model.write().overwrite().save(save_dir)
    from sparkdl_tpu.ml.pipeline import PipelineModel

    reloaded = PipelineModel.load(save_dir)
    reacc = evaluator.evaluate(reloaded.transform(test))
    assert abs(reacc - acc) < 1e-9
    print(f"reloaded pipeline reproduces accuracy: {reacc:.2f}")

    n_feats = len(predictions.collect()[0]["features"])
    print(f"featurizer emits {n_feats}-d vectors; "
          f"{len(test.collect())} test rows scored via the pipeline")


if __name__ == "__main__":
    main()
