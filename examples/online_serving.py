"""Online model serving: register -> warmup -> concurrent requests -> stats.

The batch stack scores DataFrames; :mod:`sparkdl_tpu.serving` puts the
same jitted models behind an online endpoint.  This example walks the
whole flow with a tiny in-process Keras CNN (offline-safe):

1. ``registerKerasImageUDF`` registers the model as a SQL UDF — and,
   as of the serving subsystem, also exposes it as a serving endpoint;
2. ``ModelServer.from_registered_udf`` serves that exact fused forward;
3. ``warmup()`` pre-traces the shape-bucket ladder so no request pays a
   compile;
4. concurrent single-item requests coalesce into a handful of padded,
   bucketed forward calls;
5. ``status()`` reports queue depth, cache occupancy, batch occupancy,
   and p50/p95/p99 latency through ``utils/metrics.py``.

Works on the real TPU or the virtual CPU mesh:

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/online_serving.py
"""

import os
import threading

import numpy as np

os.environ.setdefault("KERAS_BACKEND", "jax")

N_REQUESTS = 24
SIZE = 32


def main():
    import keras

    from sparkdl_tpu import ModelServer, ServingConfig, registerKerasImageUDF
    from sparkdl_tpu.sql.session import TPUSession
    from sparkdl_tpu.utils.metrics import metrics

    spark = TPUSession.builder.master("local[*]").getOrCreate()

    # a tiny classifier standing in for InceptionV3 (offline; same plumbing)
    keras.utils.set_random_seed(0)
    model = keras.Sequential(
        [
            keras.layers.Input(shape=(SIZE, SIZE, 3)),
            keras.layers.Conv2D(8, 3, activation="relu"),
            keras.layers.GlobalAveragePooling2D(),
            keras.layers.Dense(4, activation="softmax"),
        ]
    )
    registerKerasImageUDF("my_cnn", model, session=spark)

    # the same registered model, now an online endpoint
    server = ModelServer.from_registered_udf(
        "my_cnn",
        session=spark,
        config=ServingConfig(max_batch=16, max_wait_ms=5.0),
    )
    warmed = server.warmup()
    print(f"warmed buckets: {warmed} "
          f"({int(metrics.counter('serving.compiles').value)} programs)")

    # concurrent single-item requests — the micro-batcher coalesces them
    rng = np.random.RandomState(0)
    images = rng.rand(N_REQUESTS, SIZE, SIZE, 3).astype(np.float32) * 255.0
    results = [None] * N_REQUESTS
    barrier = threading.Barrier(N_REQUESTS)

    def client(i):
        barrier.wait()
        results[i] = server.predict(images[i], timeout=60.0)

    threads = [
        threading.Thread(target=client, args=(i,)) for i in range(N_REQUESTS)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()

    probs = np.stack(results)
    assert probs.shape == (N_REQUESTS, 4)
    np.testing.assert_allclose(probs.sum(axis=1), 1.0, rtol=1e-4)

    st = server.status()
    m = st["metrics"]
    print(
        f"served {int(m['serving.requests'])} requests in "
        f"{int(m['serving.batches'])} batches "
        f"(mean occupancy {m['serving.batch_occupancy.mean']:.2f}); "
        f"latency p50={m['serving.latency_ms.p50']:.1f}ms "
        f"p95={m['serving.latency_ms.p95']:.1f}ms "
        f"p99={m['serving.latency_ms.p99']:.1f}ms"
    )
    print(
        f"healthy={st['healthy']} "
        f"programs_cached={st['program_cache']['programs']} "
        f"queue_depth={st['endpoints']['my_cnn']['queue_depth']}"
    )
    server.close()
    print("online serving OK")


if __name__ == "__main__":
    main()
