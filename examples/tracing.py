"""End-to-end tracing: a fit and a serve round-trip, captured as spans.

The observability subsystem (:mod:`sparkdl_tpu.obs`) answers "where did
THIS step/request spend its time" — the question the ``metrics.*``
counters alone cannot.  This example walks the whole surface, offline:

1. ``tracer.enable(JsonlTraceSink(path))`` turns tracing on (off by
   default — instrumented code paths cost one branch until then);
2. ``KerasImageFileEstimator.fit`` emits an ``estimator.fit`` root span
   with per-epoch stall-attribution events, one ``estimator.step`` span
   per optimizer step, and ``estimator.checkpoint`` spans;
3. concurrent requests against a :class:`ModelServer` emit one
   ``serving.request`` span each; every coalesced device batch emits a
   ``serving.batch`` span that RECORDS ITS MEMBERS' span ids (and each
   member a ``coalesced`` event) — the fan-in is auditable both ways;
4. a flaky dependency under :class:`RetryPolicy` + ``CircuitBreaker``
   shows retry attempts and breaker flips landing as events on the
   current span — a retry storm and its breaker trip share one trace;
5. the trace flushes to JSONL, and the same run's metrics render as
   Prometheus text via ``prometheus_text`` / ``server.metrics_text()``.

Works on the real TPU or the virtual CPU mesh:

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/tracing.py
"""

import collections
import json
import os
import tempfile
import threading

import numpy as np
from PIL import Image

os.environ.setdefault("KERAS_BACKEND", "jax")

IMAGE = 32
CLASSES = 2
N_REQUESTS = 12


def image_loader(uri):
    return np.asarray(Image.open(uri), dtype=np.float32) / 255.0


def main():
    import keras

    from sparkdl_tpu import ModelServer, ServingConfig
    from sparkdl_tpu.estimators import KerasImageFileEstimator
    from sparkdl_tpu.obs import JsonlTraceSink, prometheus_text, tracer
    from sparkdl_tpu.resilience import (
        CircuitBreaker,
        RetryPolicy,
        TransientError,
    )
    from sparkdl_tpu.sql.session import TPUSession

    root = tempfile.mkdtemp(prefix="sparkdl_tracing_")
    trace_path = os.path.join(root, "trace.jsonl")

    # 1. tracing on — everything below is captured
    sink = JsonlTraceSink(path=trace_path)
    tracer.enable(sink)

    spark = TPUSession.builder.master("local[*]").getOrCreate()

    rng = np.random.RandomState(0)
    rows = []
    for i in range(32):
        label = i % CLASSES
        img = rng.randint(0, 80, (IMAGE, IMAGE, 3), np.uint8)
        img[..., label] += 120
        path = os.path.join(root, f"img_{i}.png")
        Image.fromarray(img).save(path)
        rows.append({"uri": path, "label": float(label)})
    df = spark.createDataFrame(rows)

    keras.utils.set_random_seed(0)
    model = keras.Sequential(
        [
            keras.layers.Input(shape=(IMAGE, IMAGE, 3)),
            keras.layers.Conv2D(8, 3, activation="relu"),
            keras.layers.GlobalAveragePooling2D(),
            keras.layers.Dense(CLASSES, activation="softmax"),
        ]
    )
    model_path = os.path.join(root, "base.keras")
    model.save(model_path)

    # 2. traced fit: estimator.fit > estimator.step / estimator.checkpoint
    est = KerasImageFileEstimator(
        inputCol="uri",
        outputCol="preds",
        labelCol="label",
        imageLoader=image_loader,
        modelFile=model_path,
        kerasOptimizer="adam",
        kerasLoss="sparse_categorical_crossentropy",
        kerasFitParams={"epochs": 2, "batch_size": 16,
                        "learning_rate": 1e-3},
        checkpointDir=os.path.join(root, "ckpt"),
    )
    est.fit(df)

    # 3. traced serving: request spans fan into batch spans
    server = ModelServer.from_keras(
        model_path,
        model_id="cnn",
        config=ServingConfig(max_batch=8, max_wait_ms=25.0),
    )
    server.warmup()
    images = rng.rand(N_REQUESTS, IMAGE, IMAGE, 3).astype(np.float32)
    results = [None] * N_REQUESTS
    barrier = threading.Barrier(N_REQUESTS)

    def client(i):
        barrier.wait()
        results[i] = server.predict(images[i], timeout=60.0)

    threads = [
        threading.Thread(target=client, args=(i,)) for i in range(N_REQUESTS)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert np.stack(results).shape == (N_REQUESTS, CLASSES)

    # 4. resilience events land on the current span
    attempts = {"n": 0}

    def flaky():
        attempts["n"] += 1
        if attempts["n"] < 3:
            raise TransientError("dependency hiccup")
        return "ok"

    breaker = CircuitBreaker("demo_dep", failure_threshold=2, recovery_s=60)
    policy = RetryPolicy(max_attempts=3, base_delay_s=0.01,
                         sleep=lambda s: None)
    with tracer.span("demo.flaky_dependency") as flaky_span:
        assert policy.call(flaky) == "ok"
        for _ in range(2):  # now trip the breaker on a dead dependency
            try:
                breaker.call(lambda: (_ for _ in ()).throw(
                    TransientError("down")))
            except TransientError:
                pass
    event_names = [e["name"] for e in flaky_span.events]
    assert event_names.count("retry") == 2
    assert "breaker_state" in event_names
    print(f"flaky-dependency span events: {event_names}")

    # 5. export: JSONL trace + Prometheus text
    prom = server.metrics_text(serving_only=True)
    server.close()
    spark.stop()
    n_spans = sink.flush()

    with open(trace_path) as fh:
        spans = [json.loads(line) for line in fh]
    by_name = collections.Counter(s["name"] for s in spans)
    fit_span, = (s for s in spans if s["name"] == "estimator.fit")
    epochs = [e for e in fit_span["events"] if e["name"] == "epoch"]
    batches = [s for s in spans if s["name"] == "serving.batch"]
    requests = [s for s in spans if s["name"] == "serving.request"]
    member_ids = sorted(
        sid for b in batches for sid in b["attributes"]["member_span_ids"]
    )
    assert member_ids == sorted(r["span_id"] for r in requests)

    print(f"captured {n_spans} spans: "
          + ", ".join(f"{n}×{name}" for name, n in sorted(by_name.items())))
    print(f"fit span: {fit_span['duration_ms']:.0f}ms over "
          f"{len(epochs)} epochs; epoch 1 host stall "
          f"{epochs[0]['host_stall_ms']:.1f}ms")
    print(f"{len(requests)} request spans coalesced into "
          f"{len(batches)} batch spans (member ids recorded both ways)")
    prom_lines = [ln for ln in prom.splitlines() if not ln.startswith("#")]
    print(f"prometheus export: {len(prom_lines)} samples, e.g. "
          + "; ".join(prom_lines[:2]))
    assert "serving_requests" in prom
    assert prometheus_text(prefix="estimator.")  # fit metrics exported too
    print(f"trace written to {trace_path}")
    print("tracing OK")


if __name__ == "__main__":
    main()
