"""Trace analytics (``obs/diag.py``): JSONL ingest (torn-tail
tolerant), tree reassembly, critical-path extraction, attribution /
per-replica / rescue aggregation, exemplar resolution, and the CLI.

Everything here is synthetic span dicts — no processes, no sockets;
the bench smoke covers the live end of the pipe.
"""

import json

import pytest

from sparkdl_tpu.obs import diag
from sparkdl_tpu.obs.diag import (
    TraceTree,
    build_trees,
    diagnose,
    load_spans,
    read_jsonl,
)
from sparkdl_tpu.obs.export import JsonlTraceSink
from sparkdl_tpu.utils.metrics import MetricsRegistry, metrics


@pytest.fixture(autouse=True)
def clean_registry():
    metrics.reset()
    yield
    metrics.reset()


def _span(name, tid, sid, parent=None, start=0.0, dur=1.0, **attrs):
    return {
        "name": name, "trace_id": tid, "span_id": sid,
        "parent_id": parent, "start_unix_s": start,
        "duration_ms": dur, "attributes": attrs, "events": [],
    }


def _request(tid, e2e, phases, replica="replica-0", retries=0,
             hedged=False, hedge_won=False, error=None, serves=1):
    """One synthetic stitched request: router.request root carrying the
    phase breakdown, an attempt child, and ``serves`` replica halves."""
    attrs = dict(
        e2e_ms=e2e, phases=phases, replica=replica, retries=retries,
        hedged=hedged, hedge_won=hedge_won,
    )
    if error:
        attrs["error"] = error
    spans = [_span(diag.ROOT_SPAN, tid, 1, dur=e2e, **attrs)]
    for i in range(serves):
        spans.append(_span(
            "router.attempt", tid, 10 + i, parent=1, dur=e2e * 0.8,
        ))
        spans.append(_span(
            diag.REMOTE_SPAN, tid, 20 + i, parent=10 + i,
            dur=e2e * 0.6,
        ))
    return spans


# ----------------------------------------------------------------------
# ingest
# ----------------------------------------------------------------------
class TestIngest:
    def test_read_jsonl_roundtrip(self, tmp_path):
        path = tmp_path / "trace.jsonl"
        spans = _request(7, 10.0, {"transport": 4.0})
        path.write_text(
            "".join(json.dumps(s) + "\n" for s in spans)
        )
        got, skipped = read_jsonl(str(path))
        assert skipped == 0
        assert [s["span_id"] for s in got] == [1, 10, 20]

    def test_read_jsonl_skips_torn_tail(self, tmp_path):
        """A crash mid-flush leaves a truncated final line; ingest must
        skip and count it, never raise (the regression this guards: a
        diagnosis tool dying on the evidence of the crash)."""
        path = tmp_path / "trace.jsonl"
        spans = _request(7, 10.0, {"transport": 4.0})
        text = "".join(json.dumps(s) + "\n" for s in spans)
        # tear the last line mid-JSON, no trailing newline
        path.write_text(text[:-20])
        got, skipped = read_jsonl(str(path))
        assert skipped == 1
        assert len(got) == len(spans) - 1
        # and the report layer digests the survivors without raising
        report = diagnose(got, skipped_lines=skipped,
                          record_metrics=False)
        assert report["skipped_lines"] == 1

    def test_sink_flush_then_torn_tail(self, tmp_path):
        """End-to-end with the real writer: JsonlTraceSink.flush output
        truncated a few bytes short still ingests all-but-last span."""
        path = tmp_path / "sink.jsonl"
        sink = JsonlTraceSink(path=str(path))
        for s in _request(11, 8.0, {"forward": 5.0}):
            sink(s)
        written = sink.flush()
        assert written == 3
        raw = path.read_bytes()
        path.write_bytes(raw[:-10])  # the torn tail
        got, skipped = read_jsonl(str(path))
        assert skipped == 1
        assert len(got) == written - 1

    def test_read_jsonl_skips_non_span_objects(self, tmp_path):
        path = tmp_path / "trace.jsonl"
        path.write_text(
            json.dumps([1, 2]) + "\n"          # not a dict
            + json.dumps({"name": "x"}) + "\n"  # no trace_id
            + json.dumps(_span("a", 5, 1)) + "\n"
        )
        got, skipped = read_jsonl(str(path))
        assert skipped == 2
        assert len(got) == 1

    def test_load_spans_merges_files(self, tmp_path):
        a, b = tmp_path / "a.jsonl", tmp_path / "b.jsonl"
        a.write_text(json.dumps(_span("a", 1, 1)) + "\n")
        b.write_text(json.dumps(_span("b", 2, 1)) + "\nnot json\n")
        spans, skipped = load_spans([str(a), str(b)])
        assert {s["trace_id"] for s in spans} == {1, 2}
        assert skipped == 1


# ----------------------------------------------------------------------
# tree reassembly + critical path
# ----------------------------------------------------------------------
class TestTraceTree:
    def test_root_prefers_router_request(self):
        tree = TraceTree(1)
        tree.add(_span("replica.flush", 1, 2))
        tree.add(_span(diag.ROOT_SPAN, 1, 1))
        assert tree.root["span_id"] == 1

    def test_orphans_counted(self):
        tree = TraceTree(1)
        tree.add(_span(diag.ROOT_SPAN, 1, 1))
        tree.add(_span("child", 1, 2, parent=99))  # parent never seen
        assert tree.orphans == 1
        assert not tree.stitched

    def test_stitched_needs_remote_half(self):
        tree = TraceTree(1)
        for s in _request(1, 5.0, {}):
            tree.add(s)
        assert tree.stitched
        lonely = TraceTree(2)
        lonely.add(_span(diag.ROOT_SPAN, 2, 1))
        assert not lonely.stitched

    def test_critical_path_follows_longest_child(self):
        tree = TraceTree(1)
        tree.add(_span(diag.ROOT_SPAN, 1, 1, dur=10.0))
        tree.add(_span("fast", 1, 2, parent=1, dur=2.0))
        tree.add(_span("slow", 1, 3, parent=1, dur=7.0))
        tree.add(_span("leaf", 1, 4, parent=3, dur=6.0))
        path = tree.critical_path()
        assert [p["name"] for p in path] == \
            [diag.ROOT_SPAN, "slow", "leaf"]
        # self time: the segment's duration its children don't explain
        assert path[0]["self_ms"] == pytest.approx(10.0 - 9.0)
        assert path[1]["self_ms"] == pytest.approx(1.0)
        assert path[2]["self_ms"] == pytest.approx(6.0)

    def test_critical_path_cycle_guard(self):
        """A duplicated span id must terminate the walk, not hang it."""
        tree = TraceTree(1)
        tree.add(_span(diag.ROOT_SPAN, 1, 1, dur=10.0))
        tree.add(_span("kid", 1, 2, parent=1, dur=5.0))
        # a second span reusing id 2 parents itself under 2 — the walk
        # would revisit sid 2 forever without the seen-guard
        tree.children.setdefault(2, []).append(
            _span("kid-again", 1, 2, parent=2, dur=4.0)
        )
        path = tree.critical_path()
        assert len(path) == 2

    def test_render_includes_tags(self):
        tree = TraceTree(1)
        tree.add(_span(diag.ROOT_SPAN, 1, 1, dur=3.0,
                       replica="replica-1", retries=2))
        lines = tree.render()
        assert len(lines) == 1
        assert "replica=replica-1" in lines[0]
        assert "retries=2" in lines[0]


# ----------------------------------------------------------------------
# aggregation
# ----------------------------------------------------------------------
class TestAggregation:
    def test_attribution_coverage_and_dominance(self):
        spans = []
        for tid in range(1, 11):
            spans += _request(
                tid, 10.0,
                {"transport": 6.0, "forward": 3.0, "admission": 1.0},
            )
        report = diagnose(spans, record_metrics=False)
        attribution = report["attribution"]
        assert attribution["requests"] == 10
        assert attribution["e2e_p50_ms"] == pytest.approx(10.0)
        # phases sum exactly to e2e — coverage is 100%
        assert attribution["coverage_p50"] == pytest.approx(1.0)
        assert attribution["dominant_p50"][0] == "transport"
        # report rows keep lifecycle order, not alphabetical
        assert list(attribution["phases"]) == \
            ["admission", "transport", "forward"]

    def test_timestamp_stamps_excluded_from_phases(self):
        spans = _request(
            1, 10.0, {"transport": 4.0, "t_accepted": 1.7e9},
        )
        report = diagnose(spans, record_metrics=False)
        assert list(report["attribution"]["phases"]) == ["transport"]

    def test_errored_requests_excluded_from_attribution(self):
        spans = _request(1, 10.0, {"transport": 5.0})
        spans += _request(2, 500.0, {"transport": 499.0},
                          error="TimeoutError")
        report = diagnose(spans, record_metrics=False)
        assert report["requests"] == 2
        assert report["errored_requests"] == 1
        assert report["attribution"]["requests"] == 1
        assert report["attribution"]["e2e_p50_ms"] == \
            pytest.approx(10.0)

    def test_per_replica_queue_vs_service(self):
        spans = []
        for tid in range(1, 5):
            spans += _request(
                tid, 10.0,
                {"replica_queue": 7.0, "forward": 3.0},
                replica="replica-0",
            )
        for tid in range(5, 9):
            spans += _request(
                tid, 10.0,
                {"replica_queue": 1.0, "forward": 9.0},
                replica="replica-1",
            )
        per = diagnose(spans, record_metrics=False)["per_replica"]
        # replica-0 is *behind* (queue-dominated), replica-1 is *slow*
        assert per["replica-0"]["queue_p50_ms"] == pytest.approx(7.0)
        assert per["replica-0"]["service_p50_ms"] == pytest.approx(3.0)
        assert per["replica-1"]["queue_p50_ms"] == pytest.approx(1.0)
        assert per["replica-1"]["service_p50_ms"] == pytest.approx(9.0)

    def test_rescue_accounting_duplicate_serves(self):
        spans = _request(1, 10.0, {}, hedged=True, hedge_won=True,
                         serves=2)
        spans += _request(2, 8.0, {}, retries=2)
        rescue = diagnose(spans, record_metrics=False)["rescue"]
        assert rescue["hedged_requests"] == 1
        assert rescue["hedge_wins"] == 1
        assert rescue["retried_requests"] == 1
        assert rescue["total_retries"] == 2
        assert rescue["duplicated_serves"] == 1
        # both serves ran 6.0ms: the duplicate cost is sum - max
        assert rescue["duplicate_serve_ms"] == pytest.approx(6.0)


# ----------------------------------------------------------------------
# exemplar resolution
# ----------------------------------------------------------------------
class TestExemplars:
    def test_exemplar_resolves_to_stitched_trace(self):
        registry = MetricsRegistry()
        registry.histogram("router.e2e_ms").observe(9.5, exemplar=42)
        registry.histogram("router.other_ms").observe(1.0,
                                                      exemplar=777)
        spans = _request(42, 9.5, {"transport": 9.0})
        report = diagnose(spans, registry=registry,
                          record_metrics=False)
        rows = {r["metric"]: r for r in report["exemplars"]}
        assert rows["router.e2e_ms"]["trace_id"] == 42
        assert rows["router.e2e_ms"]["resolved"] is True
        assert rows["router.e2e_ms"]["stitched"] is True
        # an exemplar pointing at a trace the file never saw
        assert rows["router.other_ms"]["resolved"] is False
        assert rows["router.other_ms"]["stitched"] is False

    def test_no_registry_no_exemplar_section(self):
        report = diagnose(_request(1, 5.0, {}), record_metrics=False)
        assert "exemplars" not in report


# ----------------------------------------------------------------------
# the full report + metrics side channel
# ----------------------------------------------------------------------
class TestDiagnose:
    def test_slowest_drilldown_ordering(self):
        spans = []
        for tid, e2e in ((1, 5.0), (2, 50.0), (3, 20.0)):
            spans += _request(tid, e2e, {"transport": e2e - 1.0})
        report = diagnose(spans, top=2, record_metrics=False)
        slow = report["slowest"]
        assert [s["trace_id"] for s in slow] == [2, 3]
        assert slow[0]["critical_path"][0]["name"] == diag.ROOT_SPAN
        assert slow[0]["tree"]  # the rendered drill-down rides along

    def test_record_metrics_publishes_gauges(self):
        spans = _request(1, 10.0, {"transport": 10.0})
        diagnose(spans, skipped_lines=3, record_metrics=True)
        snap = metrics.snapshot(prefix="diag")
        assert snap["diag.reports"] == 1
        assert snap["diag.requests"] == 1.0
        assert snap["diag.skipped_lines"] == 3
        assert snap["diag.coverage_p50"] == pytest.approx(1.0)
        assert snap["diag.e2e_p50_ms"] == pytest.approx(10.0)

    def test_record_metrics_off_is_silent(self):
        diagnose(_request(1, 10.0, {}), record_metrics=False)
        assert metrics.snapshot(prefix="diag") == {}


# ----------------------------------------------------------------------
# CLI
# ----------------------------------------------------------------------
class TestCli:
    @pytest.fixture()
    def trace_file(self, tmp_path):
        path = tmp_path / "trace.jsonl"
        spans = _request(42, 12.0, {"transport": 7.0, "forward": 5.0})
        path.write_text(
            "".join(json.dumps(s) + "\n" for s in spans)
        )
        return str(path)

    def test_text_report(self, trace_file, capsys):
        assert diag.main([trace_file]) == 0
        out = capsys.readouterr().out
        assert "requests=1" in out
        assert "transport" in out

    def test_json_report(self, trace_file, capsys):
        assert diag.main([trace_file, "--json"]) == 0
        report = json.loads(capsys.readouterr().out)
        assert report["requests"] == 1
        assert report["attribution"]["coverage_p50"] == \
            pytest.approx(1.0)

    def test_trace_drilldown(self, trace_file, capsys):
        assert diag.main([trace_file, "--trace", "42"]) == 0
        assert diag.ROOT_SPAN in capsys.readouterr().out

    def test_trace_drilldown_missing(self, trace_file, capsys):
        assert diag.main([trace_file, "--trace", "999"]) == 1

    def test_cli_does_not_touch_process_registry(self, trace_file):
        diag.main([trace_file])
        assert metrics.snapshot(prefix="diag") == {}
