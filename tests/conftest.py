"""Test harness: virtual 8-device CPU mesh + keras-jax backend.

Reference analog: ``python/tests/tests.py``† ``SparkDLTestCase`` creates a
``local[*]`` SparkSession so distributed behavior is testable in-process
(SURVEY.md §4).  Here the analog is an 8-device virtual CPU platform
(``--xla_force_host_platform_device_count=8``) so ``Mesh``/``psum``/DP paths
are exercised without TPU hardware.  These env vars must be set before jax
initializes its backends, hence module import time in conftest.
"""

import os

os.environ.setdefault("KERAS_BACKEND", "jax")
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# The axon sitecustomize imports jax at interpreter startup with
# JAX_PLATFORMS=axon, so env vars alone are too late here — force the
# platform through the live config as well.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(scope="session")
def tpu_session():
    """A fresh engine session (SparkSession analog) shared per test session."""
    from sparkdl_tpu.sql.session import TPUSession

    return TPUSession.builder.master("local[*]").appName("tests").getOrCreate()


@pytest.fixture(scope="session")
def image_dir(tmp_path_factory):
    """Generate a handful of small JPEG/PNG fixtures (reference keeps real
    files under ``python/tests/resources/images/``†; we synthesize
    deterministically instead of committing binaries)."""
    from PIL import Image

    root = tmp_path_factory.mktemp("images")
    rng = np.random.RandomState(0)
    for i in range(6):
        arr = rng.randint(0, 255, size=(60 + 10 * i, 80, 3), dtype=np.uint8)
        img = Image.fromarray(arr)
        if i % 2 == 0:
            img.save(root / f"img_{i}.png")
        else:
            img.save(root / f"img_{i}.jpg", quality=95)
    # one grayscale
    Image.fromarray(rng.randint(0, 255, (40, 50), dtype=np.uint8)).save(
        root / "gray.png"
    )
    return str(root)
