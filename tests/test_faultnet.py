"""Byzantine-wire hardening tests (ISSUE-14).

The centerpiece is the network kill matrix
(:class:`TestNetworkKillMatrix`): a router over one replica reachable
two ways — through a :class:`faultnet.FaultProxy` injecting a byte- or
timing-level fault on *every* reply frame, and directly as the clean
survivor — must serve every request with the **correct tensor value**
(asserted by comparison, never just "no exception"): zero accepted
loss, zero silently-wrong answers.  Corrupt-body frames must be caught
by the CRC trailer specifically (``wire.crc_fail`` moves), not by
luck of the unpickler.  The shm-ring lane gets the same treatment via
the encode-side tx tap (:func:`test_shm_lane_corrupt_frame_retries`).

Around the matrix: unit coverage for the ``faultnet.request`` /
``faultnet.reply`` message-level sites on :class:`FaultyTransport`,
the hedged-request trigger (:meth:`Router._hedge_delay_s` gating),
the retry-budget token bucket (amplification cap), and end-to-end
deadline enforcement down to the replica's shed-at-the-door check.
"""

import socket
import threading
import time

import numpy as np
import pytest

from sparkdl_tpu.resilience import inject
from sparkdl_tpu.resilience.errors import is_transient
from sparkdl_tpu.serving import ModelServer, ServingConfig, faultnet, wire
from sparkdl_tpu.serving import transport
from sparkdl_tpu.serving.errors import DeadlineExceeded
from sparkdl_tpu.serving.faultnet import FaultProxy, FaultyTransport
from sparkdl_tpu.serving.replica import ReplicaService
from sparkdl_tpu.serving.router import Router, _RetryBudget
from sparkdl_tpu.utils.metrics import metrics


def plain_service():
    """In-process ReplicaService around a compile=False doubler."""
    server = ModelServer(ServingConfig(
        max_batch=8, max_wait_ms=1.0, queue_capacity=64,
    ))
    server.register(
        "ep0", lambda x: np.asarray(x) * 2.0, item_shape=(4,),
        compile=False,
    )
    return ReplicaService(server).start()


# ----------------------------------------------------------------------
# FaultyTransport: the message-level Transport seam
# ----------------------------------------------------------------------
class _StubInner(transport.Transport):
    lane = "stub"

    def __init__(self):
        self.calls = 0
        self.closed = False

    def request(self, msg, timeout_s):
        self.calls += 1
        return {"ok": True, "result": np.asarray(msg["value"]) * 2.0}

    def close(self):
        self.closed = True


class TestFaultyTransport:
    def _roundtrip(self, t):
        return t.request(
            {"op": "infer", "value": np.ones(4, np.float32)}, 1.0
        )

    def test_no_plan_is_passthrough(self):
        inner = _StubInner()
        t = FaultyTransport(inner)
        reply = self._roundtrip(t)
        np.testing.assert_array_equal(reply["result"], 2.0 * np.ones(4))
        assert t.lane == "stub"
        t.close()
        assert inner.closed

    def test_request_site_latency(self):
        plan = inject.FaultPlan().add(
            "faultnet.request", stall_s=0.15, at=1
        )
        before = metrics.counter("faultnet.injected").value
        with inject.active_plan(plan):
            t0 = time.monotonic()
            reply = self._roundtrip(FaultyTransport(_StubInner()))
        assert time.monotonic() - t0 >= 0.15
        assert reply["ok"]
        assert metrics.counter("faultnet.injected").value == before + 1

    def test_request_site_typed_error(self):
        plan = inject.FaultPlan().add(
            "faultnet.request", error="transient", at=1
        )
        with inject.active_plan(plan):
            with pytest.raises(inject.InjectedTransientError) as ei:
                self._roundtrip(FaultyTransport(_StubInner()))
        assert is_transient(ei.value)

    def test_request_site_disconnect(self):
        plan = inject.FaultPlan().add(
            "faultnet.request", act="disconnect", at=1
        )
        inner = _StubInner()
        with inject.active_plan(plan):
            with pytest.raises(ConnectionError):
                self._roundtrip(FaultyTransport(inner))
        assert inner.calls == 0  # dropped before the wire

    def test_reply_site_drop_is_slow_backend_shaped(self):
        # the replica answered — the caller just never hears it: the
        # exact shape a hedged request exists to rescue
        plan = inject.FaultPlan().add(
            "faultnet.reply", act="drop_reply", at=1
        )
        inner = _StubInner()
        with inject.active_plan(plan):
            with pytest.raises(socket.timeout):
                self._roundtrip(FaultyTransport(inner))
        assert inner.calls == 1

    def test_make_transport_wraps_under_env(self, monkeypatch):
        monkeypatch.setenv("SPARKDL_FAULTNET", "1")
        t = transport.make_transport("127.0.0.1", 1, ("tcp",))
        try:
            assert isinstance(t, FaultyTransport)
        finally:
            t.close()


# ----------------------------------------------------------------------
# retry budget: the amplification cap
# ----------------------------------------------------------------------
class TestRetryBudget:
    def test_spend_drains_then_denies(self):
        b = _RetryBudget(ratio=0.5, burst=2)
        denied = metrics.counter("router.retry_budget.denied").value
        assert b.spend() and b.spend()
        assert not b.spend()
        assert metrics.counter(
            "router.retry_budget.denied"
        ).value == denied + 1

    def test_earn_is_capped_at_burst(self):
        b = _RetryBudget(ratio=10.0, burst=3)
        for _ in range(5):
            b.earn()
        assert [b.spend() for _ in range(4)] == [True] * 3 + [False]

    def test_ratio_bounds_steady_state_amplification(self):
        b = _RetryBudget(ratio=0.5, burst=10)
        while b.spend():  # burn the one-off burst
            pass
        for _ in range(8):  # 8 admitted requests earn 4 tokens
            b.earn()
        spent = sum(1 for _ in range(8) if b.spend())
        assert spent == 4  # <= 1.5x attempts per request, by arithmetic

    def test_exhausted_budget_degrades_into_last_typed_error(self):
        svc = plain_service()
        port = svc.port
        svc.close()  # both registered backends now refuse connections
        attempts = metrics.counter("router.attempts").value
        with Router(
            retry_budget_ratio=0.0, retry_budget_burst=0.0,
            connect_timeout_s=0.2,
        ) as router:
            router.add("dead-a", "127.0.0.1", port)
            router.add("dead-b", "127.0.0.1", port)
            with pytest.raises((ConnectionError, OSError)):
                router.route(np.ones(4, np.float32), model_id="ep0")
        # one attempt, then the budget denies the retry: no storm
        assert metrics.counter("router.attempts").value == attempts + 1


# ----------------------------------------------------------------------
# hedge trigger gating
# ----------------------------------------------------------------------
class TestHedgeTrigger:
    def _warm(self, router, ms=10.0, n=50):
        for _ in range(n):
            router._observe_attempt_ms(ms)

    def test_no_hedge_when_disabled(self):
        with Router(hedge=False) as router:
            router.add("a", "127.0.0.1", 1)
            router.add("b", "127.0.0.1", 2)
            self._warm(router)
            assert router._hedge_delay_s(time.monotonic() + 10) is None

    def test_no_hedge_below_two_backends(self):
        with Router(hedge=True) as router:
            router.add("a", "127.0.0.1", 1)
            self._warm(router)
            assert router._hedge_delay_s(time.monotonic() + 10) is None

    def test_no_hedge_while_cold(self):
        with Router(hedge=True) as router:
            router.add("a", "127.0.0.1", 1)
            router.add("b", "127.0.0.1", 2)
            self._warm(router, n=5)  # below the warmup window
            assert router._hedge_delay_s(time.monotonic() + 10) is None

    def test_no_hedge_past_deadline(self):
        with Router(hedge=True) as router:
            router.add("a", "127.0.0.1", 1)
            router.add("b", "127.0.0.1", 2)
            self._warm(router)
            assert router._hedge_delay_s(time.monotonic() - 1) is None

    def test_warm_delay_is_quantile_with_floor(self):
        with Router(hedge=True) as router:
            router.add("a", "127.0.0.1", 1)
            router.add("b", "127.0.0.1", 2)
            self._warm(router, ms=40.0)
            delay = router._hedge_delay_s(time.monotonic() + 10)
            assert delay == pytest.approx(0.040, rel=0.05)
            # the floor: a uniformly-2ms window still waits >= min_ms
            self._warm(router, ms=2.0, n=300)
            delay = router._hedge_delay_s(time.monotonic() + 10)
            assert delay == pytest.approx(
                router._hedge_min_ms / 1000.0, rel=0.05
            )

    def test_delay_never_exceeds_half_the_remaining_budget(self):
        with Router(hedge=True) as router:
            router.add("a", "127.0.0.1", 1)
            router.add("b", "127.0.0.1", 2)
            self._warm(router, ms=500.0)
            delay = router._hedge_delay_s(time.monotonic() + 0.2)
            assert delay is not None and delay <= 0.1 + 0.01


# ----------------------------------------------------------------------
# end-to-end deadline enforcement
# ----------------------------------------------------------------------
class TestDeadlineEnforcement:
    def test_expired_deadline_is_typed_in_router(self):
        expired = metrics.counter("router.deadline_expired").value
        with Router() as router:
            with pytest.raises(DeadlineExceeded):
                router.route(
                    np.ones(4, np.float32), model_id="ep0",
                    deadline_ms=0.0,
                )
        assert metrics.counter(
            "router.deadline_expired"
        ).value == expired + 1

    def test_replica_sheds_work_that_arrives_expired(self):
        # the router ships *remaining* milliseconds; non-positive means
        # the answer can no longer matter — the replica must shed at
        # the door instead of burning a batch slot
        svc = plain_service()
        shed = metrics.counter("replica.expired_shed").value
        t = transport.TcpTransport("127.0.0.1", svc.port)
        try:
            reply = t.request(
                {"op": "infer", "model_id": "ep0",
                 "value": np.ones(4, np.float32), "deadline_ms": -5.0},
                5.0,
            )
            assert reply["ok"] is False
            assert isinstance(wire.decode_error(reply), DeadlineExceeded)
            assert metrics.counter(
                "replica.expired_shed"
            ).value == shed + 1
        finally:
            t.close()
            svc.close()

    def test_deadline_beats_a_stalled_socket(self):
        # one backend, stalled mid-reply far past the deadline: the
        # caller gets a typed DeadlineExceeded at ~deadline, not a hang
        svc = plain_service()
        proxy = FaultProxy("127.0.0.1", svc.port)
        plan = inject.FaultPlan().add(
            "faultnet.reply", stall_s=30.0, p=1.0
        )
        try:
            with Router() as router:
                router.add("stalled", "127.0.0.1", proxy.port)
                t0 = time.monotonic()
                with inject.active_plan(plan):
                    with pytest.raises(DeadlineExceeded):
                        router.route(
                            np.ones(4, np.float32), model_id="ep0",
                            deadline_ms=500.0,
                        )
                assert time.monotonic() - t0 < 5.0
        finally:
            proxy.close()
            svc.close()


# ----------------------------------------------------------------------
# the network kill matrix: every fault, zero loss, zero wrong answers
# ----------------------------------------------------------------------
class TestNetworkKillMatrix:
    #: (fault name, rule kwargs applied to EVERY reply frame through
    #: the proxy, whether the CRC trailer must be what catches it)
    MATRIX = [
        ("corrupt_body", dict(act="corrupt_body", p=1.0), True),
        ("corrupt_header", dict(act="corrupt_header", p=1.0), False),
        ("duplicate_reply", dict(act="dup", p=1.0), False),
        ("midframe_disconnect",
         dict(act="midframe_disconnect", p=1.0), False),
        ("stall", dict(stall_s=0.6, p=1.0), False),
    ]

    @pytest.mark.parametrize(
        "name,rule_kw,crc_expected",
        MATRIX, ids=[m[0] for m in MATRIX],
    )
    def test_fault_sweep_zero_accepted_loss(self, name, rule_kw,
                                            crc_expected):
        svc = plain_service()
        proxy = FaultProxy("127.0.0.1", svc.port)
        plan = inject.FaultPlan().add("faultnet.reply", **rule_kw)
        crc_before = metrics.counter("wire.crc_fail").value
        injected_before = metrics.counter("faultnet.injected").value
        try:
            with Router(hedge=False) as router:
                # registration order is the idle tie-break: every
                # request is PLACED on the faulty path first and must
                # survive via typed detection + retry on the clean one
                router.add("faulty", "127.0.0.1", proxy.port)
                router.add("clean", "127.0.0.1", svc.port)
                with inject.active_plan(plan):
                    for i in range(1, 7):
                        x = np.full(4, float(i), np.float32)
                        out = router.route(
                            x, model_id="ep0", timeout_s=10.0
                        )
                        np.testing.assert_array_equal(
                            np.asarray(out), x * 2.0
                        )
        finally:
            proxy.close()
            svc.close()
        assert metrics.counter(
            "faultnet.injected"
        ).value > injected_before
        crc_delta = metrics.counter("wire.crc_fail").value - crc_before
        if crc_expected:
            # a flipped tensor byte passes every structural check; only
            # the CRC trailer stands between it and a wrong answer
            assert crc_delta > 0

    def test_shm_lane_corrupt_frame_retries(self):
        # same contract on the shared-memory ring, corrupted at the
        # encode-side tap (covers ring writes and the spill lane alike)
        svc_a, svc_b = plain_service(), plain_service()
        crc_before = metrics.counter("wire.crc_fail").value
        plan = inject.FaultPlan().add(
            "faultnet.tx", act="corrupt_body", at=4, times=1
        )
        try:
            with Router() as router:
                router.add("a", "127.0.0.1", svc_a.port,
                           lanes=("shm", "tcp"))
                router.add("b", "127.0.0.1", svc_b.port,
                           lanes=("shm", "tcp"))
                with inject.active_plan(plan):
                    assert faultnet.arm()
                    try:
                        for i in range(1, 9):
                            x = np.full(4, float(i), np.float32)
                            out = router.route(
                                x, model_id="ep0", timeout_s=10.0
                            )
                            np.testing.assert_array_equal(
                                np.asarray(out), x * 2.0
                            )
                    finally:
                        faultnet.disarm()
                assert plan.count("faultnet.tx") >= 4
        finally:
            svc_a.close()
            svc_b.close()
        assert metrics.counter("wire.crc_fail").value > crc_before


# ----------------------------------------------------------------------
# hedged requests: the tail-latency rescue, measured
# ----------------------------------------------------------------------
class TestHedging:
    def test_hedge_rescues_a_stalled_backend(self, monkeypatch):
        # "slow" is registered first, so every idle-tie placement lands
        # on it; its replies stall 0.5s at the proxy.  A warm router
        # must fire a hedge at ~min_ms and let the clean backend win —
        # the caller never waits out the stall.
        monkeypatch.setenv("SPARKDL_HEDGE_MIN_MS", "10")
        monkeypatch.setenv("SPARKDL_HEDGE_QUANTILE", "0.5")
        svc = plain_service()
        proxy = FaultProxy("127.0.0.1", svc.port)
        plan = inject.FaultPlan().add(
            "faultnet.reply", stall_s=0.5, p=1.0
        )
        fired = metrics.counter("router.hedge.fired").value
        wins = metrics.counter("router.hedge.wins").value
        try:
            with Router(hedge=True) as router:
                router.add("slow", "127.0.0.1", proxy.port)
                router.add("fast", "127.0.0.1", svc.port)
                for _ in range(50):  # a warm, all-fast sample window
                    router._observe_attempt_ms(2.0)
                with inject.active_plan(plan):
                    elapsed = []
                    for i in range(1, 7):
                        x = np.full(4, float(i), np.float32)
                        t0 = time.monotonic()
                        out = router.route(
                            x, model_id="ep0", timeout_s=10.0
                        )
                        elapsed.append(time.monotonic() - t0)
                        np.testing.assert_array_equal(
                            np.asarray(out), x * 2.0
                        )
                # no caller waited out the 0.5s stall
                assert max(elapsed) < 0.45, elapsed
        finally:
            proxy.close()
            svc.close()
        assert metrics.counter("router.hedge.fired").value > fired
        assert metrics.counter("router.hedge.wins").value > wins

    def test_hedge_off_router_never_hedges(self):
        svc = plain_service()
        fired = metrics.counter("router.hedge.fired").value
        try:
            with Router(hedge=False) as router:
                router.add("a", "127.0.0.1", svc.port)
                router.add("b", "127.0.0.1", svc.port)
                for _ in range(50):
                    router._observe_attempt_ms(2.0)
                for _ in range(4):
                    out = router.route(
                        np.ones(4, np.float32), model_id="ep0"
                    )
                    np.testing.assert_array_equal(np.asarray(out), 2.0)
        finally:
            svc.close()
        assert metrics.counter("router.hedge.fired").value == fired

    def test_hedge_spends_retry_budget(self, monkeypatch):
        # a hedge IS amplification: with an empty budget the trigger
        # must decline rather than double the brownout
        monkeypatch.setenv("SPARKDL_HEDGE_MIN_MS", "10")
        monkeypatch.setenv("SPARKDL_HEDGE_QUANTILE", "0.5")
        svc = plain_service()
        proxy = FaultProxy("127.0.0.1", svc.port)
        plan = inject.FaultPlan().add(
            "faultnet.reply", stall_s=0.4, p=1.0
        )
        fired = metrics.counter("router.hedge.fired").value
        try:
            with Router(
                hedge=True, retry_budget_ratio=0.0,
                retry_budget_burst=0.0,
            ) as router:
                router.add("slow", "127.0.0.1", proxy.port)
                router.add("fast", "127.0.0.1", svc.port)
                for _ in range(50):
                    router._observe_attempt_ms(2.0)
                with inject.active_plan(plan):
                    x = np.ones(4, np.float32)
                    t0 = time.monotonic()
                    out = router.route(x, model_id="ep0", timeout_s=10.0)
                    waited = time.monotonic() - t0
                np.testing.assert_array_equal(np.asarray(out), x * 2.0)
                assert waited >= 0.4  # rode out the stall: no hedge
        finally:
            proxy.close()
            svc.close()
        assert metrics.counter("router.hedge.fired").value == fired
