"""Continuous SQL: windowed standing queries, exactly-once emission,
late-row side output, in-query model scoring, kill-matrix recovery.

The acceptance core is byte-identity: a continuous windowed query
SIGKILLed at ``streaming.window_commit`` (between the window-results
payload and its commit marker) and restarted must emit *exactly* the
window set an uninterrupted reference run emits — same windows, same
aggregate values, no duplicate, no loss, no re-scored window — with
every late row accounted for in the side-output sink."""

import json
import math
import os
import subprocess
import sys
import threading
import time

import pytest

from sparkdl_tpu.resilience import FaultPlan, active_plan
from sparkdl_tpu.sql import TPUSession
from sparkdl_tpu.sql.continuous import (
    ContinuousPlan,
    ContinuousQuery,
    ContinuousQueryError,
    StreamTableError,
)
from sparkdl_tpu.sql.window_state import (
    WINDOW_AGG_SPECS,
    WindowStateStore,
    assign_windows,
    parse_duration_ms,
)
from sparkdl_tpu.streaming import (
    FileTailSource,
    JsonlSink,
    QueueSource,
    StreamConfig,
)
from sparkdl_tpu.streaming.sources import EventTimeError

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture
def session():
    s = TPUSession.builder.getOrCreate()
    yield s
    # drop anything a test registered so sessions don't leak across tests
    for table in list(s.catalog._streams.values()):
        table.active_query = None
    s.catalog._streams.clear()


def fast_config(**overrides):
    kw = dict(max_batch=4, max_wait_ms=5.0, poll_batch=4,
              poll_interval_ms=2.0)
    kw.update(overrides)
    return StreamConfig(**kw)


# ---------------------------------------------------------------------------
# window_state unit layer
# ---------------------------------------------------------------------------


class TestDurations:
    @pytest.mark.parametrize("text,ms", [
        ("10s", 10_000.0), ("500ms", 500.0), ("2m", 120_000.0),
        ("1h", 3_600_000.0), ("250", 250.0), (" 1.5s ", 1500.0),
    ])
    def test_parse(self, text, ms):
        assert parse_duration_ms(text) == ms

    @pytest.mark.parametrize("bad", ["", "10x", "abc", "-5s", "0s", "0"])
    def test_garbage_raises(self, bad):
        with pytest.raises(ValueError):
            parse_duration_ms(bad)


class TestAssignWindows:
    def test_tumbling_is_single_window(self):
        assert assign_windows(12_345.0, 10_000.0, 10_000.0) == [
            (10_000.0, 20_000.0)
        ]
        assert assign_windows(0.0, 10_000.0, 10_000.0) == [(0.0, 10_000.0)]

    def test_sliding_overlap(self):
        # size 10s, slide 5s: every instant belongs to two windows
        assert assign_windows(12_000.0, 10_000.0, 5_000.0) == [
            (5_000.0, 15_000.0), (10_000.0, 20_000.0),
        ]

    def test_boundary_belongs_to_next_window(self):
        # [start, end): an event AT a boundary opens the next window
        assert assign_windows(10_000.0, 10_000.0, 10_000.0) == [
            (10_000.0, 20_000.0)
        ]


class TestWindowStateStore:
    def _store(self):
        return WindowStateStore([("n", "count"), ("p95_v", "p95")])

    def test_update_close_in_deterministic_order(self):
        st = self._store()
        w = (0.0, 1000.0)
        for i, key in enumerate(["b", "a", "b"]):
            st.update(w, (key,), [True, float(i)])
        st.update((1000.0, 2000.0), ("a",), [True, 9.0])
        assert st.open_windows == 3
        closed = st.close_upto(1000.0)
        # only the first window closed, keys sorted deterministically
        assert [(c["keys"][0], c["rows"]) for c in closed] == [
            ("a", 1), ("b", 2)
        ]
        assert st.open_windows == 1
        # closing again emits nothing (state was removed)
        assert st.close_upto(1000.0) == []

    def test_none_watermark_closes_nothing(self):
        st = self._store()
        st.update((0.0, 1000.0), ("k",), [True, 1.0])
        assert st.close_upto(None) == []

    def test_null_values_skipped_but_row_counted(self):
        st = WindowStateStore([("n", "count"), ("s", "sum")])
        w = (0.0, 1000.0)
        st.update(w, (), [True, 2.0])
        st.update(w, (), [True, None])  # null cell: sum skips, count=arg true
        closed = st.close_upto(1000.0)
        assert closed[0]["rows"] == 2
        assert closed[0]["aggs"] == [2, 2.0]

    def test_snapshot_restore_round_trip(self):
        st = self._store()
        st.update((0.0, 1000.0), ("a",), [True, 1.0])
        st.update((0.0, 1000.0), ("a",), [True, 5.0])
        snap = st.snapshot()
        st2 = self._store()
        st2.restore(snap)
        assert st2.snapshot() == snap
        assert st.close_upto(1000.0) == st2.close_upto(1000.0)

    def test_restore_from_different_query_fails_loudly(self):
        st = self._store()
        st.update((0.0, 1000.0), (), [True, 1.0])
        other = WindowStateStore([("total", "sum")])
        with pytest.raises(ValueError, match="different"):
            other.restore(st.snapshot())

    def test_unhashable_group_key_rejected(self):
        st = self._store()
        with pytest.raises(TypeError, match="group key"):
            st.update((0.0, 1.0), ({"a": 1},), [True, 1.0])

    def test_percentile_interpolates_like_numpy(self):
        np = pytest.importorskip("numpy")
        vals = [3.0, 1.0, 4.0, 1.0, 5.0, 9.0, 2.0, 6.0]
        spec = WINDOW_AGG_SPECS["p95"]
        acc = spec.init()
        for v in vals:
            acc = spec.update(acc, v)
        assert spec.final(acc) == pytest.approx(
            float(np.percentile(vals, 95.0))
        )
        assert WINDOW_AGG_SPECS["p50"].final(sorted(vals)) == pytest.approx(
            float(np.percentile(vals, 50.0))
        )


# ---------------------------------------------------------------------------
# the WINDOW() grammar extension
# ---------------------------------------------------------------------------


class TestContinuousPlan:
    def test_parses_window_keys_and_aggs(self, session):
        p = ContinuousPlan.parse(
            session,
            "SELECT endpoint, window_start, p95(latency) AS p95_ms, "
            "count(*) AS n FROM scores "
            "GROUP BY WINDOW(event_time_ms, '10s'), endpoint",
        )
        assert p.table == "scores"
        assert p.time_col == "event_time_ms"
        assert (p.size_ms, p.slide_ms) == (10_000.0, 10_000.0)
        assert not p.sliding
        assert p.keys == ["endpoint"]
        assert [(a.label, a.fn_key, a.arg) for a in p.aggs] == [
            ("p95_ms", "p95", "latency"), ("n", "count", "*"),
        ]

    def test_sliding_window(self, session):
        p = ContinuousPlan.parse(
            session,
            "SELECT avg(v) FROM s GROUP BY WINDOW(t, '10s', '5s')",
        )
        assert p.sliding and p.slide_ms == 5_000.0

    def test_mean_aliases_avg(self, session):
        p = ContinuousPlan.parse(
            session, "SELECT mean(v) AS m FROM s GROUP BY WINDOW(t, '1s')"
        )
        assert p.aggs[0].fn_key == "avg"

    def test_where_clause_is_captured(self, session):
        p = ContinuousPlan.parse(
            session,
            "SELECT count(*) AS n FROM s WHERE v > 3 "
            "GROUP BY WINDOW(t, '1s')",
        )
        assert p.where == "v > 3"

    @pytest.mark.parametrize("query,match", [
        ("SELECT count(*) FROM s GROUP BY WINDOW(t, '5s', '10s')",
         "slide"),
        ("SELECT count(*) FROM s GROUP BY WINDOW(t, '1s') ORDER BY n",
         "ORDER BY"),
        ("SELECT count(*) FROM s GROUP BY WINDOW(t, '1s') LIMIT 5",
         "LIMIT"),
        ("SELECT count(*) FROM s GROUP BY WINDOW(t, '1s') HAVING n > 2",
         "HAVING"),
        ("SELECT count(*) FROM a JOIN b ON a.k = b.k "
         "GROUP BY WINDOW(t, '1s')", "JOIN"),
        ("SELECT count(*) FROM s GROUP BY k", "WINDOW"),
        ("SELECT count(*) FROM s", "GROUP BY"),
        ("SELECT v FROM s GROUP BY WINDOW(t, '1s')", "neither"),
        ("SELECT stddev(v) FROM s GROUP BY WINDOW(t, '1s')",
         "not a window aggregate"),
        ("SELECT avg(*) FROM s GROUP BY WINDOW(t, '1s')", "avg"),
        ("SELECT score(v) FROM s GROUP BY WINDOW(t, '1s')",
         "not a window aggregate"),
        ("SELECT p95(nosuch(v)) FROM s GROUP BY WINDOW(t, '1s')",
         "not a registered UDF"),
        ("SELECT count(*) AS n, sum(v) AS n FROM s "
         "GROUP BY WINDOW(t, '1s')", "duplicate"),
        ("SELECT count(*) FROM s "
         "GROUP BY WINDOW(t, '1s'), WINDOW(t, '2s')", "more than one"),
        ("SELECT count(*) FROM s GROUP BY WINDOW(t, 'xyz')", "duration"),
    ])
    def test_dialect_violations_are_typed(self, session, query, match):
        with pytest.raises(ContinuousQueryError, match=match):
            ContinuousPlan.parse(session, query)

    def test_plan_fault_site_fires(self, session):
        from sparkdl_tpu.resilience.errors import TransientError

        plan = FaultPlan().add("csql.plan", error="transient", at=1)
        with active_plan(plan):
            with pytest.raises(TransientError):
                ContinuousPlan.parse(
                    session,
                    "SELECT count(*) FROM s GROUP BY WINDOW(t, '1s')",
                )
        assert plan.count("csql.plan") == 1


# ---------------------------------------------------------------------------
# catalog: stream tables vs temp views
# ---------------------------------------------------------------------------


class TestCatalogStreamTables:
    def test_list_tables_distinguishes_types(self, session):
        df = session.createDataFrame([(1,)], ["x"])
        df.createOrReplaceTempView("bounded_v")
        session.readStream("stream_t", QueueSource())
        try:
            tables = {t.name: t.tableType for t in
                      session.catalog.listTables()}
            assert tables["bounded_v"] == "TEMPORARY"
            assert tables["stream_t"] == "STREAM"
        finally:
            session.catalog.dropTempView("bounded_v")

    def test_drop_temp_view_refuses_stream_table(self, session):
        session.readStream("st", QueueSource())
        with pytest.raises(StreamTableError, match="dropStreamTable"):
            session.catalog.dropTempView("st")
        session.catalog.dropStreamTable("st")
        assert not any(
            t.name == "st" for t in session.catalog.listTables()
        )

    def test_drop_active_stream_table_names_the_query(
        self, session, tmp_path
    ):
        src = QueueSource()
        session.readStream("live", src)
        q = ContinuousQuery(
            session,
            "SELECT count(*) AS n FROM live GROUP BY WINDOW(t, '1s')",
            JsonlSink(str(tmp_path / "out.jsonl")),
            str(tmp_path / "log"),
            name="q_live",
        )
        try:
            with pytest.raises(StreamTableError, match="q_live"):
                session.catalog.dropStreamTable("live")
            # a second query on the same table is refused too (the
            # stream's read position is single-consumer)
            with pytest.raises(StreamTableError, match="q_live"):
                ContinuousQuery(
                    session,
                    "SELECT count(*) AS n FROM live "
                    "GROUP BY WINDOW(t, '1s')",
                    JsonlSink(str(tmp_path / "out2.jsonl")),
                    str(tmp_path / "log2"),
                    name="q_other",
                )
        finally:
            q.close()
        session.catalog.dropStreamTable("live")  # released by close()

    def test_stream_table_shadowing_temp_view_rejected(self, session):
        df = session.createDataFrame([(1,)], ["x"])
        df.createOrReplaceTempView("shadow_me")
        try:
            with pytest.raises(StreamTableError, match="temp view"):
                session.readStream("shadow_me", QueueSource())
        finally:
            session.catalog.dropTempView("shadow_me")

    def test_table_and_stream_table_cross_errors(self, session):
        session.readStream("only_stream", QueueSource())
        with pytest.raises(StreamTableError, match="sqlStream"):
            session.table("only_stream")
        df = session.createDataFrame([(1,)], ["x"])
        df.createOrReplaceTempView("only_view")
        try:
            with pytest.raises(StreamTableError, match="readStream"):
                session.catalog.streamTable("only_view")
        finally:
            session.catalog.dropTempView("only_view")
        with pytest.raises(StreamTableError, match="not found"):
            session.catalog.streamTable("nowhere")


# ---------------------------------------------------------------------------
# bounded-plane percentiles (shared fn keys, pinned vs window specs)
# ---------------------------------------------------------------------------


class TestBoundedPercentiles:
    def test_sql_group_by_p95(self, session):
        np = pytest.importorskip("numpy")
        rows = [("a", float(i)) for i in range(20)]
        df = session.createDataFrame(rows, ["k", "v"])
        df.createOrReplaceTempView("pvals")
        try:
            out = session.sql(
                "SELECT k, p95(v) AS p FROM pvals GROUP BY k"
            ).collect()
        finally:
            session.catalog.dropTempView("pvals")
        assert out[0]["p"] == pytest.approx(
            float(np.percentile([r[1] for r in rows], 95.0))
        )

    def test_functions_factory_matches_window_spec(self, session):
        import sparkdl_tpu.sql.functions as F

        vals = [5.0, 1.0, 9.0, 3.0, 7.0]
        df = session.createDataFrame([(v,) for v in vals], ["v"])
        got = df.groupBy().agg(F.p50("v").alias("m")).collect()[0]["m"]
        spec = WINDOW_AGG_SPECS["p50"]
        acc = spec.init()
        for v in vals:
            acc = spec.update(acc, v)
        assert got == pytest.approx(spec.final(acc))


# ---------------------------------------------------------------------------
# in-process continuous queries
# ---------------------------------------------------------------------------


def _feed(src, n=40, late_at=()):
    """n in-order rows, 500ms apart, two endpoints; indices in late_at
    instead carry an event time far behind the stream (out-of-order)."""
    for i in range(n):
        ts = 100.0 if i in late_at else i * 500.0
        src.put({
            "endpoint": "a" if i % 2 else "b",
            "latency": float(i),
            "ts": ts,
        })
    src.end()


class TestContinuousQuery:
    QUERY = (
        "SELECT endpoint, p95(latency) AS p95_ms, count(*) AS n "
        "FROM scores GROUP BY WINDOW(ts, '5s'), endpoint"
    )

    def _run(self, session, tmp_path, src, query=None, **cq_kw):
        session.readStream("scores", src)
        sink = JsonlSink(str(tmp_path / "out.jsonl"))
        late = JsonlSink(str(tmp_path / "late.jsonl"))
        q = ContinuousQuery(
            session, query or self.QUERY, sink, str(tmp_path / "log"),
            late_sink=late, config=cq_kw.pop("config", fast_config()),
            **cq_kw,
        )
        try:
            summary = q.run(idle_timeout_s=2.0)
        finally:
            q.close()
        return summary, sink.read_all(), late.read_all()

    def test_windows_close_and_emit(self, session, tmp_path):
        src = QueueSource()
        _feed(src, n=40)  # ts up to 19500: windows 0-5s .. 10-15s close
        summary, rows, late = self._run(session, tmp_path, src)
        assert summary["stop_reason"] == "source_finished"
        assert late == []
        windows = sorted({(r["window_start"], r["window_end"])
                          for r in rows})
        assert windows == [
            (0.0, 5000.0), (5000.0, 10000.0), (10000.0, 15000.0),
        ]
        # 5s windows, rows 500ms apart alternating endpoints: 5 each
        assert all(r["n"] == 5 for r in rows)
        # the open 15-20s window is state, not output
        assert summary["open_windows"] == 2
        first_a = [r for r in rows
                   if r["window_start"] == 0.0 and r["endpoint"] == "a"]
        assert len(first_a) == 1
        # endpoint a holds odd latencies [1, 3, 5, 7, 9] in the first
        # window: rank 3.8 interpolates 7 + 0.8 * (9 - 7)
        assert first_a[0]["p95_ms"] == pytest.approx(8.6)

    def test_late_rows_routed_to_side_output(self, session, tmp_path):
        src = QueueSource()
        # rows 20 and 31 arrive out-of-order far behind the watermark
        _feed(src, n=40, late_at=(20, 31))
        summary, rows, late = self._run(session, tmp_path, src)
        assert summary["late_rows"] == 2
        assert sorted(r["input"]["latency"] for r in late) == [20.0, 31.0]
        assert all(r["event_time_ms"] == 100.0 for r in late)
        # late rows joined NO window: the 0-5s windows count them absent
        w0 = {r["endpoint"]: r["n"] for r in rows
              if r["window_start"] == 0.0}
        assert w0 == {"a": 5, "b": 5}

    def test_allowed_lateness_keeps_rows_in_window(self, session, tmp_path):
        src = QueueSource()
        src.put({"endpoint": "a", "latency": 1.0, "ts": 1000.0})
        src.put({"endpoint": "a", "latency": 2.0, "ts": 9000.0})
        # 500ms behind max: within a 60s allowance, contributes normally
        src.put({"endpoint": "a", "latency": 3.0, "ts": 8500.0})
        src.put({"endpoint": "a", "latency": 4.0, "ts": 120_000.0})
        src.end()
        summary, rows, late = self._run(
            session, tmp_path, src,
            config=fast_config(allowed_lateness_ms=60_000.0),
        )
        assert late == []
        assert summary["late_rows"] == 0
        # watermark trails max event time by 60s, so the 8500ms row is
        # NOT late and contributes to its (5000, 10000) window normally
        assert {(r["window_start"], r["n"]) for r in rows} == {
            (0.0, 1), (5000.0, 2),
        }

    def test_where_filters_rows(self, session, tmp_path):
        src = QueueSource()
        _feed(src, n=40)
        query = (
            "SELECT count(*) AS n FROM scores "
            "WHERE endpoint = 'a' AND latency < 100 "
            "GROUP BY WINDOW(ts, '5s')"
        )
        _, rows, _ = self._run(session, tmp_path, src, query=query)
        assert rows and all(r["n"] == 5 for r in rows)

    def test_plain_udf_scores_in_query(self, session, tmp_path):
        session.udf.register("double_it", lambda v: v * 2.0)
        src = QueueSource()
        _feed(src, n=20)
        query = (
            "SELECT endpoint, max(double_it(latency)) AS m FROM scores "
            "GROUP BY WINDOW(ts, '5s'), endpoint"
        )
        _, rows, _ = self._run(session, tmp_path, src, query=query)
        w0 = {r["endpoint"]: r["m"] for r in rows
              if r["window_start"] == 0.0}
        assert w0 == {"a": 18.0, "b": 16.0}

    def test_serving_udf_scores_through_admission_queue(
        self, session, tmp_path
    ):
        np = pytest.importorskip("numpy")
        from sparkdl_tpu.serving import ModelServer, ServingConfig
        from sparkdl_tpu.sql.functions import UserDefinedFunction

        udf = UserDefinedFunction(lambda v: v, name="score3")
        udf._serving_endpoint = {
            "model_id": "score3",
            "forward": lambda b: b * 3.0,
            "item_shape": (),
            "dtype": np.float32,
            "fingerprint": None,
        }
        registered = session.udf.register("score3", udf)
        registered._serving_endpoint = udf._serving_endpoint
        src = QueueSource()
        _feed(src, n=20)
        query = (
            "SELECT endpoint, max(score3(latency)) AS m FROM scores "
            "GROUP BY WINDOW(ts, '5s'), endpoint"
        )
        with ModelServer(config=ServingConfig()) as server:
            _, rows, _ = self._run(
                session, tmp_path, src, query=query, server=server,
            )
        w0 = {r["endpoint"]: r["m"] for r in rows
              if r["window_start"] == 0.0}
        assert w0 == {"a": pytest.approx(27.0), "b": pytest.approx(24.0)}

    def test_row_without_event_time_is_typed_error(self, session, tmp_path):
        src = QueueSource()
        src.put({"endpoint": "a", "latency": 1.0})  # no ts column
        src.end()
        session.readStream("scores", src)
        q = ContinuousQuery(
            session, self.QUERY, JsonlSink(str(tmp_path / "out.jsonl")),
            str(tmp_path / "log"), config=fast_config(),
        )
        try:
            with pytest.raises(ContinuousQueryError, match="event time"):
                q.run(idle_timeout_s=2.0)
        finally:
            q.close()

    def test_source_event_time_binds_pseudo_column(self, session, tmp_path):
        # rows carry no "event_time_ms" column; the SOURCE extracts it
        # (satellite: Record.event_time_ms binds WINDOW() directly)
        path = tmp_path / "in.jsonl"
        with open(path, "w") as fh:
            for i in range(10):
                fh.write(json.dumps({"v": float(i), "ts": i * 1000.0})
                         + "\n")
        src = FileTailSource(str(path), event_time_field="ts")
        query = (
            "SELECT count(*) AS n FROM scores "
            "GROUP BY WINDOW(event_time_ms, '5s')"
        )
        session.readStream("scores", src)
        sink = JsonlSink(str(tmp_path / "out.jsonl"))
        q = ContinuousQuery(
            session, query, sink, str(tmp_path / "log"),
            config=fast_config(),
        )
        try:
            q.run(max_epochs=10, idle_timeout_s=1.0)
        finally:
            q.close()
        rows = sink.read_all()
        assert [(r["window_start"], r["n"]) for r in rows] == [(0.0, 5)]

    def test_preemption_flushes_then_resumes_exactly_once(
        self, session, tmp_path
    ):
        from sparkdl_tpu.resilience import preempt

        session.udf.register(
            "slow_id", lambda v: (time.sleep(0.005), v)[1]
        )
        query = (
            "SELECT count(*) AS n, max(slow_id(latency)) AS m "
            "FROM scores GROUP BY WINDOW(ts, '5s')"
        )
        src = QueueSource()
        _feed(src, n=40)
        session.readStream("scores", src)
        sink = JsonlSink(str(tmp_path / "out.jsonl"))
        q = ContinuousQuery(
            session, query, sink, str(tmp_path / "log"),
            config=fast_config(),
        )
        timer = threading.Timer(
            0.05, preempt.request_preemption, args=("test preemption",)
        )
        timer.start()
        try:
            summary = q.run(idle_timeout_s=2.0)
        finally:
            timer.cancel()
            q.close()
        # resume with a fresh query object over the same checkpoint
        q2 = ContinuousQuery(
            session, query, JsonlSink(str(tmp_path / "out.jsonl")),
            str(tmp_path / "log"), config=fast_config(),
        )
        try:
            summary2 = q2.run(idle_timeout_s=2.0)
        finally:
            q2.close()
        # whether the first run flushed everything on SIGTERM or the
        # resumed run finished the tail, the union is exactly-once:
        # every closed window emitted once, none twice
        assert summary2["stop_reason"] in (
            "source_finished", "idle_timeout"
        )
        rows = JsonlSink(str(tmp_path / "out.jsonl")).read_all()
        got = [(r["window_start"], r["n"]) for r in rows]
        assert sorted(got) == [(0.0, 10), (5000.0, 10), (10000.0, 10)]

    def test_metrics_and_spans(self, session, tmp_path):
        from sparkdl_tpu.obs import tracer
        from sparkdl_tpu.obs.export import prometheus_text
        from sparkdl_tpu.utils.metrics import metrics

        spans = []
        tracer.enable(sink=spans.append)
        try:
            src = QueueSource()
            _feed(src, n=40)
            self._run(session, tmp_path, src)
        finally:
            tracer.disable()
        by_name = {}
        for s in spans:
            by_name.setdefault(s["name"], []).append(s)
        (run,) = by_name["csql.query"]
        closes = by_name["csql.window_close"]
        assert len(closes) == 6  # 3 closed windows x 2 endpoints
        assert all(s["trace_id"] == run["trace_id"] for s in closes)
        assert by_name["csql.recover"][0]["parent_id"] == run["span_id"]
        text = prometheus_text(metrics)
        assert "csql_rows_in" in text
        assert "csql_windows_closed" in text
        assert "csql_open_windows" in text
        assert "csql_emit_latency_ms" in text
        assert metrics.counter("csql.rows_in").value >= 40


# ---------------------------------------------------------------------------
# kill matrix: SIGKILL at streaming.window_commit / csql.plan →
# restart → emitted windows byte-identical to an uninterrupted reference
# ---------------------------------------------------------------------------

N_ROWS = 36

CSQL_WORKER = """
import json, os, sys
os.environ.setdefault("KERAS_BACKEND", "jax")
import jax
jax.config.update("jax_platforms", "cpu")
sys.path.insert(0, {repo!r})
from sparkdl_tpu.sql import TPUSession
from sparkdl_tpu.streaming import FileTailSource, JsonlSink, StreamConfig
workdir = {workdir!r}
session = TPUSession.builder.getOrCreate()
source = FileTailSource(os.path.join(workdir, "in.jsonl"),
                        event_time_field="ts")
session.readStream("scores", source)
sink = JsonlSink(os.path.join(workdir, "out.jsonl"))
late = JsonlSink(os.path.join(workdir, "late.jsonl"))
query = session.sqlStream(
    "SELECT endpoint, p95(latency) AS p95_ms, count(*) AS n "
    "FROM scores GROUP BY WINDOW(ts, '2s'), endpoint",
    sink, os.path.join(workdir, "log"), late_sink=late,
    config=StreamConfig(max_batch=4, max_wait_ms=5.0, poll_batch=4,
                        poll_interval_ms=2.0),
)
summary = query.run(idle_timeout_s=1.0)
print("SUMMARY " + json.dumps(summary))
print("WORKER_FINISHED")
"""


def _write_source(workdir, n=N_ROWS, late_at=()):
    os.makedirs(workdir, exist_ok=True)
    with open(os.path.join(workdir, "in.jsonl"), "w") as fh:
        for i in range(n):
            ts = 50.0 if i in late_at else i * 250.0
            fh.write(json.dumps({
                "endpoint": "a" if i % 2 else "b",
                "latency": float(i),
                "ts": ts,
            }) + "\n")


def _run_worker(workdir, fault_plan=None, timeout=90):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("SPARKDL_FAULT_PLAN", None)
    if fault_plan is not None:
        env["SPARKDL_FAULT_PLAN"] = json.dumps(fault_plan)
    return subprocess.run(
        [sys.executable, "-c",
         CSQL_WORKER.format(repo=_REPO, workdir=workdir)],
        env=env,
        stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT,
        text=True,
        timeout=timeout,
    )


def _emitted_windows(workdir):
    """The committed window-result set, epoch numbering stripped (epochs
    legitimately differ across a restart; window CONTENT may not)."""
    out = []
    path = os.path.join(workdir, "out.jsonl")
    if not os.path.exists(path):
        return out
    with open(path) as fh:
        for line in fh:
            if not line.endswith("\n"):
                continue
            row = json.loads(line)
            row.pop("epoch", None)
            out.append(row)
    out.sort(key=lambda r: (r["window_start"], r["endpoint"]))
    return out


def _reference_run(tmp_path, late_at=()):
    refdir = str(tmp_path / "ref")
    _write_source(refdir, late_at=late_at)
    ref = _run_worker(refdir)
    assert ref.returncode == 0, ref.stdout
    windows = _emitted_windows(refdir)
    assert windows, "reference run emitted nothing"
    return windows


def test_kill_at_window_commit_then_restart_is_byte_identical(tmp_path):
    reference = _reference_run(tmp_path)
    workdir = str(tmp_path / "killed")
    _write_source(workdir)
    killed = _run_worker(
        workdir,
        fault_plan=[
            {"site": "streaming.window_commit", "kill": True, "at": 3}
        ],
    )
    assert killed.returncode == 9, killed.stdout
    assert "WORKER_FINISHED" not in killed.stdout

    from sparkdl_tpu.streaming import CommitLog

    log = CommitLog(os.path.join(workdir, "log"))
    pending_before = log.pending()
    assert pending_before, "the kill must leave a payload without marker"

    restarted = _run_worker(workdir)
    assert restarted.returncode == 0, restarted.stdout
    assert log.pending() == []
    got, want = _emitted_windows(workdir), reference
    assert json.dumps(got, sort_keys=True) == json.dumps(
        want, sort_keys=True
    ), f"emitted windows diverged:\n{got}\nvs reference\n{want}"


def test_kill_at_window_commit_late_rows_survive_in_side_output(tmp_path):
    late_at = (12, 25)
    reference = _reference_run(tmp_path, late_at=late_at)
    workdir = str(tmp_path / "killed")
    _write_source(workdir, late_at=late_at)
    killed = _run_worker(
        workdir,
        fault_plan=[
            {"site": "streaming.window_commit", "kill": True, "at": 4}
        ],
    )
    assert killed.returncode == 9, killed.stdout
    restarted = _run_worker(workdir)
    assert restarted.returncode == 0, restarted.stdout
    assert json.dumps(_emitted_windows(workdir), sort_keys=True) == \
        json.dumps(reference, sort_keys=True)
    with open(os.path.join(workdir, "late.jsonl")) as fh:
        late = [json.loads(line) for line in fh if line.endswith("\n")]
    assert sorted(r["input"]["latency"] for r in late) == [12.0, 25.0]


def test_kill_at_plan_leaves_no_partial_state(tmp_path):
    workdir = str(tmp_path / "planned")
    _write_source(workdir)
    killed = _run_worker(
        workdir, fault_plan=[{"site": "csql.plan", "kill": True, "at": 1}]
    )
    assert killed.returncode == 9, killed.stdout
    assert "SUMMARY" not in killed.stdout
    # the query died at plan time: no checkpoint dir, no sink bytes
    assert not os.path.exists(os.path.join(workdir, "log"))
    assert not os.path.exists(os.path.join(workdir, "out.jsonl"))
    # a clean restart (no plan) processes the whole stream
    restarted = _run_worker(workdir)
    assert restarted.returncode == 0, restarted.stdout
    assert _emitted_windows(workdir)


# ---------------------------------------------------------------------------
# event-time satellite: typed errors, no silent None
# ---------------------------------------------------------------------------


class TestEventTimeField:
    def test_absent_field_raises_typed(self, tmp_path):
        path = tmp_path / "in.jsonl"
        path.write_text('{"x": 1}\n')
        src = FileTailSource(str(path), event_time_field="ts")
        with pytest.raises(EventTimeError, match="absent"):
            src.poll(10)

    def test_non_numeric_field_raises_typed(self, tmp_path):
        path = tmp_path / "in.jsonl"
        path.write_text('{"x": 1, "ts": "yesterday"}\n')
        src = FileTailSource(str(path), event_time_field="ts")
        with pytest.raises(EventTimeError, match="non-numeric"):
            src.poll(10)

    def test_event_time_error_is_permanent(self):
        from sparkdl_tpu.resilience.errors import PermanentError

        assert issubclass(EventTimeError, PermanentError)
