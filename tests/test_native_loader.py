"""Single-flight contract of the native loaders (``native/__init__.py``
and ``native/pjrt.py``).

Regression for the lock-blocking finding sparkdl_check's interprocedural
pass pinned down: ``_load()`` used to hold the module lock across the
g++ subprocess and the dlopen, so *every* thread that merely asked
``is_available()`` — reachable from the transformer hot path via
``decode_image_batch`` — stalled behind a multi-second build.  The fix
mirrors ``serving/cache.py``: one thread claims the build via an Event,
the build runs with no lock held, waiters block on the Event only.
"""

import threading

import pytest

from sparkdl_tpu import native
from sparkdl_tpu.native import pjrt


@pytest.mark.parametrize("mod", [native, pjrt], ids=["batchpack", "pjrt"])
def test_load_builds_once_outside_the_lock(mod, monkeypatch, tmp_path):
    calls = []
    build_started = threading.Event()
    release_build = threading.Event()

    def slow_build():
        calls.append(1)
        build_started.set()
        assert release_build.wait(timeout=30.0), "test never released build"
        return False  # "toolchain unavailable": loader must yield None

    src = tmp_path / "src.cpp"
    src.write_text("// never compiled")
    monkeypatch.setattr(mod, "_build", slow_build)
    monkeypatch.setattr(mod, "_SRC_PATH", str(src))
    monkeypatch.setattr(mod, "_SO_PATH", str(tmp_path / "missing.so"))
    monkeypatch.setattr(mod, "_lib", None)
    monkeypatch.setattr(mod, "_tried", False)
    monkeypatch.setattr(mod, "_inflight", None)
    monkeypatch.delenv("SPARKDL_NO_NATIVE", raising=False)

    results = []
    threads = [
        threading.Thread(target=lambda: results.append(mod._load()))
        for _ in range(4)
    ]
    for t in threads:
        t.start()
    assert build_started.wait(timeout=30.0), "no thread reached the build"

    # THE regression assertion: while the build runs, the module lock is
    # free — an availability check can take it without waiting seconds
    assert mod._lock.acquire(timeout=5.0), (
        "module lock held across the native build — the single-flight "
        "pattern regressed to build-under-lock"
    )
    mod._lock.release()

    release_build.set()
    for t in threads:
        t.join(timeout=30.0)
    assert not any(t.is_alive() for t in threads)
    assert len(calls) == 1, "concurrent first callers must share one build"
    assert results == [None] * 4
    # the verdict is memoized: no second build attempt afterwards
    assert mod._load() is None
    assert len(calls) == 1
