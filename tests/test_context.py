"""Sequence/context-parallel attention oracle tests (8-device CPU mesh).

Pattern per SURVEY.md §4: framework output ≡ plain single-device oracle on
the same arrays — here sharded ring/Ulysses attention vs dense
``full_attention``, causal and not, plus gradient flow.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from sparkdl_tpu.parallel._shard_map import shard_map
from sparkdl_tpu.parallel.context import (
    full_attention,
    make_sp_attention,
    ring_attention,
    ulysses_attention,
)

BATCH, SEQ, HEADS, DIM = 2, 64, 8, 16


@pytest.fixture(scope="module")
def seq_mesh():
    return Mesh(np.asarray(jax.devices()[:8]).reshape(8), ("seq",))


def _qkv(seed=0):
    rng = np.random.RandomState(seed)
    shape = (BATCH, SEQ, HEADS, DIM)
    return tuple(
        jnp.asarray(rng.randn(*shape).astype(np.float32)) for _ in range(3)
    )


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_matches_full(seq_mesh, causal):
    q, k, v = _qkv()
    want = full_attention(q, k, v, causal=causal)
    fn = make_sp_attention(seq_mesh, impl="ring", causal=causal)
    got = np.asarray(fn(q, k, v))
    np.testing.assert_allclose(got, np.asarray(want), atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_attention_matches_full(seq_mesh, causal):
    q, k, v = _qkv(1)
    want = full_attention(q, k, v, causal=causal)
    fn = make_sp_attention(seq_mesh, impl="ulysses", causal=causal)
    got = np.asarray(fn(q, k, v))
    np.testing.assert_allclose(got, np.asarray(want), atol=2e-5)


def test_ring_attention_grads_match(seq_mesh):
    """SP must be trainable: d(loss)/d(q,k,v) through the ring equals the
    dense-attention gradients."""
    q, k, v = _qkv(2)

    def loss_full(q, k, v):
        return (full_attention(q, k, v) ** 2).sum()

    spec = P(None, "seq", None, None)

    @jax.jit
    def loss_ring(q, k, v):
        out = shard_map(
            lambda a, b, c: ring_attention(a, b, c, axis_name="seq"),
            mesh=seq_mesh,
            in_specs=(spec, spec, spec),
            out_specs=spec,
        )(q, k, v)
        return (out**2).sum()

    g_full = jax.grad(loss_full, argnums=(0, 1, 2))(q, k, v)
    g_ring = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    for gf, gr in zip(g_full, g_ring):
        np.testing.assert_allclose(
            np.asarray(gr), np.asarray(gf), atol=5e-4, rtol=1e-4
        )


def test_ring_attention_output_stays_sharded(seq_mesh):
    q, k, v = _qkv(3)
    spec = P(None, "seq", None, None)
    sharded = jax.device_put(q, NamedSharding(seq_mesh, spec))
    fn = make_sp_attention(seq_mesh, impl="ring")
    out = fn(sharded, k, v)
    assert out.sharding.spec == spec  # no implicit gather of the sequence


def test_ulysses_rejects_indivisible_heads(seq_mesh):
    rng = np.random.RandomState(0)
    shape = (1, 16, 4, 8)  # 4 heads on an 8-way axis
    q = jnp.asarray(rng.randn(*shape).astype(np.float32))
    spec = P(None, "seq", None, None)
    with pytest.raises(ValueError, match="divisible"):
        shard_map(
            lambda a, b, c: ulysses_attention(a, b, c, axis_name="seq"),
            mesh=seq_mesh,
            in_specs=(spec, spec, spec),
            out_specs=spec,
        )(q, q, q)
