"""Zero-downtime fleet tests (ISSUE-12): versioned routing, the
SLO-guarded blue/green ``RolloutController``, and its fault sites.

The controller's state machine is driven synchronously against stub
supervisor/router/engine seams with an injected clock (mirroring the
autoscaler tests); every ``rollout.*`` fault site registered in
``resilience.inject.KNOWN_SITES`` is exercised here with error
injection (a ``kill`` at these sites would take out the *controller*
process, i.e. this test — the router/replica kill matrix lives in
``test_supervisor.py``): ``rollout.shift`` / ``rollout.bake`` faults
must fail SAFE into a rollback, and a fault at ``rollout.rollback``
must never stop the rollback itself.  The one real-process test walks
a clean v2 through every stage to promotion and asserts v1's replicas
drained with exit 0 — the zero-downtime contract.
"""

import threading
import time

import numpy as np
import pytest

from sparkdl_tpu.resilience import inject
from sparkdl_tpu.utils.metrics import metrics
from sparkdl_tpu.serving import ModelServer, ServingConfig
from sparkdl_tpu.serving.errors import NoLiveReplicas
from sparkdl_tpu.serving.replica import ReplicaService, ReplicaSpec
from sparkdl_tpu.serving.rollout import (
    DEFAULT_STAGES,
    RolloutController,
    _stages_from_env,
)
from sparkdl_tpu.serving.router import (
    DEFAULT_VERSION,
    Router,
    split_versioned,
)
from sparkdl_tpu.serving.supervisor import ReplicaSupervisor

PLAIN_FACTORY = "sparkdl_tpu.serving.replica:demo_server_plain"


# ----------------------------------------------------------------------
# versioned routing (in-process replica services, real sockets)
# ----------------------------------------------------------------------
def versioned_service(counter=None, scale=2.0, fingerprint=None):
    server = ModelServer(ServingConfig(
        max_batch=8, max_wait_ms=1.0, queue_capacity=64,
    ))

    def forward(x):
        batch = np.asarray(x)
        if counter is not None:
            counter.extend([1] * batch.shape[0])
        return batch * scale

    server.register("ep0", forward, item_shape=(4,), compile=False,
                    fingerprint=fingerprint)
    return ReplicaService(server).start()


class TestSplitVersioned:
    def test_plain_id_has_no_pin(self):
        assert split_versioned("ep0") == ("ep0", None)

    def test_at_suffix_pins(self):
        assert split_versioned("ep0@v2") == ("ep0", "v2")

    def test_only_last_at_splits(self):
        assert split_versioned("a@b@v3") == ("a@b", "v3")

    def test_none_passes_through(self):
        assert split_versioned(None) == (None, None)


class TestVersionedRouter:
    def test_zero_weight_version_gets_no_unpinned_traffic(self):
        served_v1, served_v2 = [], []
        svc1 = versioned_service(served_v1)
        svc2 = versioned_service(served_v2, scale=3.0)
        with Router(seed=7) as router:
            router.add("r1", "127.0.0.1", svc1.port)
            router.add("r2", "127.0.0.1", svc2.port, version="v2")
            router.set_weights({"v1": 1.0, "v2": 0.0})
            try:
                for _ in range(20):
                    out = router.route(np.ones(4, np.float32),
                                       model_id="ep0")
                    np.testing.assert_allclose(np.asarray(out), 2.0)
                assert len(served_v1) == 20
                assert len(served_v2) == 0
            finally:
                svc1.close()
                svc2.close()

    def test_pin_overrides_weights(self):
        served_v2 = []
        svc1 = versioned_service()
        svc2 = versioned_service(served_v2, scale=3.0)
        with Router() as router:
            router.add("r1", "127.0.0.1", svc1.port)
            router.add("r2", "127.0.0.1", svc2.port, version="v2")
            router.set_weights({"v1": 1.0, "v2": 0.0})
            try:
                out = router.route(np.ones(4, np.float32),
                                   model_id="ep0@v2")
                np.testing.assert_allclose(np.asarray(out), 3.0)
                assert len(served_v2) == 1
            finally:
                svc1.close()
                svc2.close()

    def test_pin_to_absent_version_is_no_live_replicas(self):
        svc1 = versioned_service()
        with Router() as router:
            router.add("r1", "127.0.0.1", svc1.port)
            try:
                with pytest.raises(NoLiveReplicas):
                    router.route(np.ones(4, np.float32),
                                 model_id="ep0@v9")
            finally:
                svc1.close()

    def test_weights_split_traffic_roughly(self):
        served_v1, served_v2 = [], []
        svc1 = versioned_service(served_v1)
        svc2 = versioned_service(served_v2)
        with Router(seed=3) as router:
            router.add("r1", "127.0.0.1", svc1.port)
            router.add("r2", "127.0.0.1", svc2.port, version="v2")
            router.set_weights({"v1": 0.5, "v2": 0.5})
            try:
                for _ in range(60):
                    router.route(np.ones(4, np.float32), model_id="ep0")
                # seeded rng: the exact split is deterministic, but the
                # assertion only needs "both sides saw real traffic"
                assert len(served_v1) >= 10
                assert len(served_v2) >= 10
            finally:
                svc1.close()
                svc2.close()

    def test_all_zero_weights_falls_back_to_availability(self):
        # availability beats split fidelity: if every version has
        # weight 0 the router still serves (and counts the fallback)
        svc1 = versioned_service()
        with Router() as router:
            router.add("r1", "127.0.0.1", svc1.port)
            router.set_weights({"v1": 0.0})
            before = metrics.counter("router.weight_fallback").value
            try:
                out = router.route(np.ones(4, np.float32),
                                   model_id="ep0")
                np.testing.assert_allclose(np.asarray(out), 2.0)
                assert metrics.counter(
                    "router.weight_fallback"
                ).value > before
            finally:
                svc1.close()

    def test_per_version_metrics_are_attempt_level(self):
        svc2 = versioned_service(scale=3.0)
        with Router() as router:
            router.add("r2", "127.0.0.1", svc2.port, version="v2")
            before = metrics.counter("router.requests.v2").value
            try:
                router.route(np.ones(4, np.float32), model_id="ep0@v2")
                assert metrics.counter(
                    "router.requests.v2"
                ).value == before + 1
                assert metrics.histogram(
                    "router.latency_ms.v2"
                ).count > 0
            finally:
                svc2.close()

    def test_versions_and_weights_snapshots(self):
        with Router() as router:
            router.add("a", "127.0.0.1", 1, version="v1")
            router.add("b", "127.0.0.1", 2, version="v2")
            router.add("c", "127.0.0.1", 3, version="v2")
            assert router.versions() == {"v1": 1, "v2": 2}
            router.set_weights({"v2": 0.25})
            assert router.weights() == {"v2": 0.25}

    def test_rejects_negative_weight(self):
        with Router() as router:
            with pytest.raises(ValueError):
                router.set_weights({"v2": -0.1})

    def test_rollout_flip_invalidates_result_cache(self, monkeypatch):
        # ISSUE-16 invalidation-by-construction: the result-cache key
        # embeds the endpoint-version fingerprint, so promoting v2 (a
        # weight flip — exactly what RolloutController.set_primary
        # drives) retargets every lookup at v2's key space.  v1's
        # cached result must never be served for v2 traffic, with ZERO
        # manual flushes, and flipping BACK must re-serve v1's still-
        # warm entries without re-scoring.
        monkeypatch.setenv("SPARKDL_RESULT_CACHE", "1")
        served_v1, served_v2 = [], []
        svc1 = versioned_service(served_v1, scale=2.0,
                                 fingerprint="weights:v1")
        svc2 = versioned_service(served_v2, scale=3.0,
                                 fingerprint="weights:v2")
        with Router(seed=7) as router:
            router.add("r1", "127.0.0.1", svc1.port,
                       fingerprints={"ep0": "weights:v1"})
            router.add("r2", "127.0.0.1", svc2.port, version="v2",
                       fingerprints={"ep0": "weights:v2"})
            router.set_weights({"v1": 1.0, "v2": 0.0})
            x = np.ones(4, np.float32)
            try:
                # warm v1's cache entry, then serve it from cache
                for _ in range(3):
                    out = router.route(x, model_id="ep0")
                    np.testing.assert_allclose(np.asarray(out), 2.0)
                assert len(served_v1) == 1
                # the rollout flip: all weight to v2, no cache flush
                router.set_weights({"v1": 0.0, "v2": 1.0})
                for _ in range(3):
                    out = router.route(x, model_id="ep0")
                    # THE assertion: v2 traffic never sees v1's 2.0
                    np.testing.assert_allclose(np.asarray(out), 3.0)
                assert len(served_v2) == 1  # miss once, then v2 hits
                # flip back: v1's entry is still warm — zero re-scores
                router.set_weights({"v1": 1.0, "v2": 0.0})
                out = router.route(x, model_id="ep0")
                np.testing.assert_allclose(np.asarray(out), 2.0)
                assert len(served_v1) == 1
            finally:
                svc1.close()
                svc2.close()


# ----------------------------------------------------------------------
# controller state machine (stub seams, injected clock — no processes)
# ----------------------------------------------------------------------
class _StubRouter:
    def __init__(self):
        self.weights_log = []

    def set_weights(self, weights):
        self.weights_log.append(dict(weights))


class _StubSupervisor:
    def __init__(self, live_v1=2):
        self.router = _StubRouter()
        self.calls = []
        self.live = {DEFAULT_VERSION: live_v1}
        self.primary = DEFAULT_VERSION
        self.deploy_raises = None
        self.retire_raises = None

    @property
    def primary_version(self):
        return self.primary

    def live_count(self, version=None):
        if version is None:
            return sum(self.live.values())
        return self.live.get(version, 0)

    def deploy(self, version, spec, replicas=1):
        self.calls.append(("deploy", version, replicas))
        if self.deploy_raises is not None:
            raise self.deploy_raises
        self.live[version] = replicas
        return []

    def retire_version(self, version):
        self.calls.append(("retire", version))
        if self.retire_raises is not None:
            raise self.retire_raises
        n = self.live.pop(version, 0)
        return {slot: 0 for slot in range(n)}

    def set_primary(self, version):
        self.calls.append(("set_primary", version))
        self.primary = version


class _StubEngine:
    def __init__(self):
        self.current = {}

    def states(self):
        return dict(self.current)


class _StubAutoscaler:
    def __init__(self):
        self.log = []

    def pause(self):
        self.log.append("pause")

    def resume(self):
        self.log.append("resume")


def make_rollout(**kw):
    sup = _StubSupervisor(live_v1=kw.pop("live_v1", 2))
    engine = _StubEngine()
    clock = {"t": 0.0}
    ctl = RolloutController(
        sup, engine, "v2", spec=None,
        stages=kw.pop("stages", (0.01, 0.5, 1.0)),
        bake_s=kw.pop("bake_s", 10.0),
        spawn_timeout_s=kw.pop("spawn_timeout_s", 30.0),
        clock=lambda: clock["t"],
        **kw,
    )
    return ctl, sup, engine, clock


def drive(ctl, clock, dt=6.0, max_steps=30):
    """Tick the clock and step until a terminal state."""
    for _ in range(max_steps):
        clock["t"] += dt
        if ctl.step() in ("done", "rolled_back"):
            break
    return ctl.state


class TestRolloutStateMachine:
    def test_clean_canary_promotes_through_every_stage(self):
        ctl, sup, engine, clock = make_rollout()
        assert drive(ctl, clock) == "done"
        # every stage's weight reached the router, ascending
        canary = [w["v2"] for w in sup.router.weights_log if "v1" in w]
        assert canary[:3] == [0.01, 0.5, 1.0]
        # promotion order: all weight on v2 BEFORE v1 drains
        assert sup.calls[-2:] == [("set_primary", "v2"), ("retire", "v1")]
        report = ctl.report()
        assert report["verdict"] == "promoted"
        assert report["detection_s"] is None
        assert set(report["old_exits"].values()) == {0}

    def test_new_fleet_matches_old_fleet_size(self):
        ctl, sup, engine, clock = make_rollout(live_v1=3)
        drive(ctl, clock)
        assert ("deploy", "v2", 3) in sup.calls

    def test_canary_page_rolls_back_and_drains_v2(self):
        ctl, sup, engine, clock = make_rollout()
        while ctl.state != "baking":
            clock["t"] += 1.0
            ctl.step()
        engine.current = {"rollout.v2.latency": "page"}
        clock["t"] += 1.0
        assert ctl.step() == "rolled_back"
        # weight snapped back to v1, v2 drained out
        assert sup.router.weights_log[-1] == {"v1": 1.0, "v2": 0.0}
        assert ("retire", "v2") in sup.calls
        report = ctl.report()
        assert report["verdict"] == "rolled_back"
        assert "rollout.v2.latency" in report["reason"]
        assert report["detection_s"] == pytest.approx(1.0)

    def test_unwatched_slo_page_does_not_roll_back(self):
        # only the canary's own rollout.v2.* names are judged — a page
        # on an unrelated fleet SLO must not abort the rollout
        ctl, sup, engine, clock = make_rollout()
        while ctl.state != "baking":
            clock["t"] += 1.0
            ctl.step()
        engine.current = {"router.latency": "page",
                          "rollout.v2.errors": "warning"}
        assert drive(ctl, clock) == "done"

    def test_explicit_watch_list_overrides_prefix(self):
        ctl, sup, engine, clock = make_rollout(
            watch=("custom.canary",)
        )
        while ctl.state != "baking":
            clock["t"] += 1.0
            ctl.step()
        engine.current = {"custom.canary": "page"}
        clock["t"] += 1.0
        assert ctl.step() == "rolled_back"

    def test_spawn_timeout_rolls_back(self):
        ctl, sup, engine, clock = make_rollout(spawn_timeout_s=5.0)
        # deploy "succeeds" but the fleet never reports live
        orig = sup.deploy

        def deploy_dead(version, spec, replicas=1):
            orig(version, spec, replicas)
            sup.live[version] = 0

        sup.deploy = deploy_dead
        assert drive(ctl, clock, dt=3.0) == "rolled_back"
        assert "not live" in ctl.report()["reason"]

    def test_autoscaler_paused_during_shift_resumed_after(self):
        scaler = _StubAutoscaler()
        ctl, sup, engine, clock = make_rollout(autoscaler=scaler)
        drive(ctl, clock)
        assert scaler.log == ["pause", "resume"]

    def test_autoscaler_resumed_on_rollback_too(self):
        scaler = _StubAutoscaler()
        ctl, sup, engine, clock = make_rollout(autoscaler=scaler)
        while ctl.state != "baking":
            clock["t"] += 1.0
            ctl.step()
        engine.current = {"rollout.v2.latency": "page"}
        clock["t"] += 1.0
        ctl.step()
        assert scaler.log == ["pause", "resume"]

    def test_terminal_states_are_sticky(self):
        ctl, sup, engine, clock = make_rollout()
        drive(ctl, clock)
        calls = list(sup.calls)
        clock["t"] += 100.0
        assert ctl.step() == "done"
        assert sup.calls == calls

    def test_rejects_same_version_both_sides(self):
        sup = _StubSupervisor()
        with pytest.raises(ValueError):
            RolloutController(sup, _StubEngine(), DEFAULT_VERSION,
                              spec=None)

    def test_rejects_unsorted_or_out_of_range_stages(self):
        sup = _StubSupervisor()
        for bad in ((0.5, 0.1), (0.0, 1.0), (0.5, 1.5), ()):
            with pytest.raises(ValueError):
                RolloutController(sup, _StubEngine(), "v2", spec=None,
                                  stages=bad)

    def test_stages_env_knob(self, monkeypatch):
        monkeypatch.delenv("SPARKDL_ROLLOUT_STAGES", raising=False)
        assert _stages_from_env() == DEFAULT_STAGES
        monkeypatch.setenv("SPARKDL_ROLLOUT_STAGES", "0.1,1.0")
        assert _stages_from_env() == (0.1, 1.0)


# ----------------------------------------------------------------------
# rollout fault sites (error injection: a kill here would kill the
# controller process — this test — so fail-safe semantics are what the
# kill matrix means for rollout.*)
# ----------------------------------------------------------------------
class TestRolloutFaultSites:
    def test_registry_lists_rollout_sites(self):
        sites = inject.known_sites()
        for site in ("rollout.shift", "rollout.bake",
                     "rollout.rollback"):
            assert site in sites

    def test_shift_fault_fails_safe_into_rollback(self):
        ctl, sup, engine, clock = make_rollout()
        plan = inject.FaultPlan().add(
            "rollout.shift", error="transient", at=1
        )
        with inject.active_plan(plan):
            assert drive(ctl, clock, dt=1.0) == "rolled_back"
        assert "shifting" in ctl.report()["reason"]
        # the rollback still restored v1's weight
        assert sup.router.weights_log[-1] == {"v1": 1.0, "v2": 0.0}

    def test_bake_fault_fails_safe_into_rollback(self):
        ctl, sup, engine, clock = make_rollout()
        plan = inject.FaultPlan().add(
            "rollout.bake", error="transient", at=1
        )
        with inject.active_plan(plan):
            assert drive(ctl, clock, dt=1.0) == "rolled_back"
        assert "baking" in ctl.report()["reason"]
        assert ("retire", "v2") in sup.calls

    def test_rollback_fault_cannot_stop_the_rollback(self):
        ctl, sup, engine, clock = make_rollout()
        plan = inject.FaultPlan().add(
            "rollout.rollback", error="permanent", at=1
        )
        with inject.active_plan(plan):
            while ctl.state != "baking":
                clock["t"] += 1.0
                ctl.step()
            engine.current = {"rollout.v2.latency": "page"}
            clock["t"] += 1.0
            assert ctl.step() == "rolled_back"
        # despite the injected fault mid-rollback, the weights were
        # restored and the v2 fleet drained
        assert sup.router.weights_log[-1] == {"v1": 1.0, "v2": 0.0}
        assert ("retire", "v2") in sup.calls

    def test_even_retire_failure_leaves_weights_safe(self):
        ctl, sup, engine, clock = make_rollout()
        sup.retire_raises = RuntimeError("drain hung")
        while ctl.state != "baking":
            clock["t"] += 1.0
            ctl.step()
        engine.current = {"rollout.v2.errors": "page"}
        clock["t"] += 1.0
        assert ctl.step() == "rolled_back"
        assert sup.router.weights_log[-1] == {"v1": 1.0, "v2": 0.0}


# ----------------------------------------------------------------------
# canary SLO factories
# ----------------------------------------------------------------------
class TestRolloutSLOFactories:
    def test_rollout_pair_watches_per_version_series(self):
        from sparkdl_tpu.obs.slo import rollout_slos

        lat, err = rollout_slos("v2", latency_threshold_ms=50.0)
        assert lat.name == "rollout.v2.latency"
        assert lat.series == "router.latency_ms.v2.p99"
        assert err.name == "rollout.v2.errors"
        assert err.numerator == "router.errors.v2"
        assert err.denominator == "router.requests.v2"

    def test_tenant_pair_watches_tenant_series(self):
        from sparkdl_tpu.obs.slo import tenant_slos

        lat, err = tenant_slos("tenant-b")
        assert lat.name == "tenant.tenant_b.latency"
        assert lat.series == "router.tenant.tenant_b.latency_ms.p99"
        assert err.numerator == "router.tenant.tenant_b.errors"


# ----------------------------------------------------------------------
# real processes: clean v2 promotes, v1 drains with exit 0 under load
# ----------------------------------------------------------------------
def test_clean_rollout_promotes_and_v1_drains_clean():
    from sparkdl_tpu.resilience.policy import RetryPolicy

    spec = ReplicaSpec(factory=PLAIN_FACTORY)
    sup = ReplicaSupervisor(
        spec, replicas=1, monitor_interval_s=0.05,
        health_interval_s=1.0, spawn_timeout_s=120.0,
        backoff=RetryPolicy(max_attempts=8, base_delay_s=0.1,
                            multiplier=1.5, max_delay_s=0.5, jitter=0.0),
    ).start()
    try:
        assert sup.wait_live(1, 120.0)
        stop = threading.Event()
        failures = []
        served = [0]

        def traffic():
            x = np.ones(64, np.float32)
            while not stop.is_set():
                try:
                    sup.router.route(x, model_id="ep0")
                    served[0] += 1
                except Exception as exc:  # noqa: BLE001
                    failures.append(exc)
                time.sleep(0.01)

        threads = [threading.Thread(target=traffic, daemon=True)
                   for _ in range(2)]
        for t in threads:
            t.start()
        # no engine: a clean canary never needs one (states() unread
        # paths are covered by the stub tests) — watch nothing, bake
        # fast, promote for real
        ctl = RolloutController(
            sup, None, "v2", ReplicaSpec(factory=PLAIN_FACTORY),
            replicas=1, stages=(0.5, 1.0), bake_s=0.3,
            interval_s=0.05, spawn_timeout_s=120.0,
        ).start()
        state = ctl.wait(timeout_s=180.0)
        stop.set()
        for t in threads:
            t.join(timeout=10)
        assert state == "done", ctl.report()
        report = ctl.report()
        assert report["verdict"] == "promoted"
        # THE zero-downtime contract: every v1 replica drained clean
        assert set(report["old_exits"].values()) == {0}, report
        assert sup.primary_version == "v2"
        assert sup.live_count("v2") == 1
        assert sup.live_count("v1") == 0
        # traffic flowed throughout; nothing the router accepted died
        assert served[0] > 0
        assert not failures, failures[:3]
        # and the promoted fleet still serves
        out = sup.router.route(np.ones(64, np.float32), model_id="ep0")
        assert np.asarray(out).shape == (64,)
    finally:
        sup.close()
