"""Introspection server (``obs/server.py``) + the wired telemetry plane
(:meth:`ModelServer.start_telemetry`).

Everything binds ``127.0.0.1`` with an ephemeral port (``port=0`` —
``server.port`` resolves the bound one) and scrapes over real HTTP with
urllib; no fixed ports, no external processes.
"""

import json
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from sparkdl_tpu.obs import JsonlTraceSink, ObsServer, tracer
from sparkdl_tpu.obs.slo import SLO, SLOEngine
from sparkdl_tpu.obs.timeseries import TimeSeriesRecorder
from sparkdl_tpu.serving import ModelServer, ServingConfig
from sparkdl_tpu.utils.metrics import MetricsRegistry, metrics


@pytest.fixture(autouse=True)
def clean_slate():
    tracer.disable()
    metrics.reset()
    yield
    tracer.disable()
    metrics.reset()


def _get(url, timeout=10.0):
    """GET -> (status, content_type, body_bytes); 4xx/5xx are data here,
    not exceptions."""
    try:
        with urllib.request.urlopen(url, timeout=timeout) as resp:
            return resp.status, resp.headers.get("Content-Type"), resp.read()
    except urllib.error.HTTPError as err:
        return err.code, err.headers.get("Content-Type"), err.read()


def _get_json(url, timeout=10.0):
    status, _, body = _get(url, timeout=timeout)
    return status, json.loads(body)


@pytest.fixture()
def registry():
    return MetricsRegistry()


# ----------------------------------------------------------------------
# endpoint payloads
# ----------------------------------------------------------------------
class TestEndpoints:
    def test_index_lists_endpoints(self, registry):
        with ObsServer(registry=registry) as srv:
            status, payload = _get_json(srv.url + "/")
        assert status == 200
        assert "/metrics" in payload["endpoints"]
        assert "/healthz" in payload["endpoints"]

    def test_metrics_is_prometheus_text(self, registry):
        registry.counter("serving.requests").add(3)
        registry.gauge("data.queue_depth").set(2.0)
        with ObsServer(registry=registry) as srv:
            status, ctype, body = _get(srv.url + "/metrics")
        assert status == 200
        assert ctype.startswith("text/plain")
        text = body.decode()
        assert "# HELP serving_requests" in text
        assert "# TYPE serving_requests counter\nserving_requests 3" in text
        assert "data_queue_depth 2" in text

    def test_healthz_default_healthy(self, registry):
        with ObsServer(registry=registry) as srv:
            status, payload = _get_json(srv.url + "/healthz")
        assert status == 200
        assert payload["healthy"] is True
        # scraping /healthz feeds the availability series
        assert registry.snapshot()["sparkdl.up"] == 1.0

    def test_healthz_503_when_degraded(self, registry):
        health = {"healthy": True, "note": "fine"}
        with ObsServer(registry=registry,
                       health_fn=lambda: dict(health)) as srv:
            assert _get_json(srv.url + "/healthz")[0] == 200
            health["healthy"] = False
            status, payload = _get_json(srv.url + "/healthz")
        assert status == 503
        assert payload["healthy"] is False
        assert payload["note"] == "fine"  # health_fn payload passes through
        assert registry.snapshot()["sparkdl.up"] == 0.0

    def test_healthz_503_when_health_fn_raises(self, registry):
        def boom():
            raise RuntimeError("probe wedged")

        with ObsServer(registry=registry, health_fn=boom) as srv:
            status, payload = _get_json(srv.url + "/healthz")
        assert status == 503
        assert "probe wedged" in payload["error"]

    def test_healthz_includes_worst_slo_state(self, registry):
        recorder = TimeSeriesRecorder(registry=registry)
        engine = SLOEngine(recorder, registry=registry)
        engine.add(SLO(name="lat", kind="threshold", series="s",
                       threshold=1.0))
        with ObsServer(registry=registry, slo_engine=engine) as srv:
            status, payload = _get_json(srv.url + "/healthz")
        assert status == 200
        assert payload["slo_worst"] == "ok"

    def test_slo_endpoint(self, registry):
        recorder = TimeSeriesRecorder(registry=registry)
        engine = SLOEngine(recorder, registry=registry)
        engine.add(SLO(name="lat", kind="threshold", series="s",
                       threshold=1.0))
        engine.evaluate_once(now=0.0)
        with ObsServer(registry=registry, slo_engine=engine) as srv:
            status, payload = _get_json(srv.url + "/slo")
        assert status == 200
        assert payload["worst"] == "ok"
        assert [row["name"] for row in payload["slos"]] == ["lat"]

    def test_debug_spans(self, registry):
        sink = JsonlTraceSink(capacity=16)
        tracer.enable(sink)
        with tracer.span("unit.work", step=1):
            pass
        with ObsServer(registry=registry, span_sink=sink) as srv:
            status, payload = _get_json(srv.url + "/debug/spans")
        assert status == 200
        assert payload["count"] == 1
        assert payload["dropped"] == 0
        assert payload["spans"][0]["name"] == "unit.work"

    def test_debug_threads_sees_this_thread(self, registry):
        with ObsServer(registry=registry) as srv:
            status, payload = _get_json(srv.url + "/debug/threads")
        assert status == 200
        assert payload["count"] >= 2  # us + the server thread at least
        names = [t["name"] for t in payload["threads"]]
        assert "MainThread" in names
        main = next(t for t in payload["threads"]
                    if t["name"] == "MainThread")
        assert any("test_obs_server" in line for line in main["stack"])

    def test_debug_timeseries(self, registry):
        recorder = TimeSeriesRecorder(registry=registry)
        registry.counter("serving.requests").add(5)
        recorder.sample_once(now=1.0)
        with ObsServer(registry=registry, recorder=recorder) as srv:
            status, payload = _get_json(srv.url + "/debug/timeseries")
        assert status == 200
        assert payload["series"]["serving.requests"] == [[1.0, 5.0]]

    def test_unwired_endpoints_404_with_hint(self, registry):
        with ObsServer(registry=registry) as srv:
            for path in ("/slo", "/debug/spans", "/debug/timeseries"):
                status, payload = _get_json(srv.url + path)
                assert status == 404, path
                assert "error" in payload, path
            status, payload = _get_json(srv.url + "/nope")
        assert status == 404
        assert "/nope" in payload["error"]

    def test_handler_exception_is_500_not_crash(self, registry):
        class BadEngine:
            def report(self):
                raise RuntimeError("report boom")

            def worst_state(self):
                return "ok"

        with ObsServer(registry=registry, slo_engine=BadEngine()) as srv:
            status, payload = _get_json(srv.url + "/slo")
            assert status == 500
            assert "report boom" in payload["error"]
            # the server survived the handler failure
            assert _get_json(srv.url + "/healthz")[0] == 200

    def test_request_counter(self, registry):
        with ObsServer(registry=registry) as srv:
            for _ in range(3):
                _get(srv.url + "/healthz")
        assert registry.snapshot()["sparkdl.obs_requests"] == 3


# ----------------------------------------------------------------------
# diagnosis endpoints (/debug/diag, /debug/profile) + self-telemetry
# ----------------------------------------------------------------------
def _diag_span(name, tid, sid, parent=None, dur=5.0, **attrs):
    return {
        "name": name, "trace_id": tid, "span_id": sid,
        "parent_id": parent, "start_unix_s": 0.0,
        "duration_ms": dur, "attributes": attrs, "events": [],
    }


class TestDiagnosisEndpoints:
    @pytest.fixture(autouse=True)
    def unarm_profiler(self):
        from sparkdl_tpu.obs import profile

        yield
        if profile._profiler is not None:
            profile._profiler.stop()
            profile._profiler = None

    def _stitched_sink(self):
        sink = JsonlTraceSink(capacity=16)
        sink(_diag_span(
            "router.request", 42, 1, dur=10.0, e2e_ms=10.0,
            phases={"transport": 6.0, "forward": 4.0},
            replica="replica-0",
        ))
        sink(_diag_span("replica.serve", 42, 2, parent=1, dur=6.0))
        return sink

    def test_debug_diag_report(self, registry):
        sink = self._stitched_sink()
        with ObsServer(registry=registry, span_sink=sink) as srv:
            status, payload = _get_json(srv.url + "/debug/diag")
        assert status == 200
        assert payload["requests"] == 1
        assert payload["stitched_requests"] == 1
        assert payload["attribution"]["coverage_p50"] == 1.0
        assert payload["slowest"][0]["trace_id"] == 42
        # the report's headline gauges land in the process registry
        # (the wired one only resolves exemplars)
        assert metrics.snapshot()["diag.requests"] == 1.0

    def test_debug_diag_top_param(self, registry):
        sink = self._stitched_sink()
        with ObsServer(registry=registry, span_sink=sink) as srv:
            status, payload = _get_json(
                srv.url + "/debug/diag?top=0")
        assert status == 200
        assert payload["slowest"] == []

    def test_debug_diag_404_without_sink(self, registry):
        with ObsServer(registry=registry) as srv:
            status, payload = _get_json(srv.url + "/debug/diag")
        assert status == 404
        assert "span sink" in payload["error"]

    def test_debug_profile_window(self, registry):
        with ObsServer(registry=registry) as srv:
            status, payload = _get_json(
                srv.url + "/debug/profile?seconds=0.1&interval_ms=5")
        assert status == 200
        window = payload["window"]
        assert window["running"] is False
        assert window["duration_s"] >= 0.05
        # no env-armed profiler -> no "armed" section
        assert "armed" not in payload

    def test_debug_profile_reports_armed_profiler(self, registry,
                                                  monkeypatch):
        from sparkdl_tpu.obs import profile

        monkeypatch.setenv(profile.ENV_PROFILE, "1")
        profile.enable_from_env()
        with ObsServer(registry=registry) as srv:
            status, payload = _get_json(
                srv.url + "/debug/profile?seconds=0.05")
        assert status == 200
        assert payload["armed"]["running"] is True

    def test_malformed_query_params_are_400_not_500(self, registry):
        sink = self._stitched_sink()
        with ObsServer(registry=registry, span_sink=sink) as srv:
            for url in (
                "/debug/profile?seconds=banana",
                "/debug/profile?seconds=9999",   # > 60s cap
                "/debug/profile?interval_ms=0",  # below floor
                "/debug/diag?top=-5",
            ):
                status, payload = _get_json(srv.url + url)
                assert status == 400, url
                assert "query param" in payload["error"], url
            # the caller's typo never killed the server
            assert _get_json(srv.url + "/healthz")[0] == 200

    def test_per_endpoint_latency_histogram(self, registry):
        with ObsServer(registry=registry) as srv:
            _get(srv.url + "/healthz")
            _get(srv.url + "/metrics")
            _get(srv.url + "/made-up-path")
        snap = registry.snapshot(prefix="sparkdl.obs_request_ms")
        assert snap["sparkdl.obs_request_ms.healthz.count"] == 1.0
        assert snap["sparkdl.obs_request_ms.metrics.count"] == 1.0
        # unknown paths pool into "other" — a URL-scanning client
        # cannot mint unbounded label series
        assert snap["sparkdl.obs_request_ms.other.count"] == 1.0
        assert "sparkdl.obs_request_ms.healthz.p99" in snap


# ----------------------------------------------------------------------
# lifecycle
# ----------------------------------------------------------------------
class TestLifecycle:
    def test_port_resolution_and_idempotent_start(self, registry):
        srv = ObsServer(registry=registry)
        assert srv.port is None and srv.url is None
        try:
            srv.start()
            port = srv.port
            assert port and port > 0
            assert srv.start() is srv and srv.port == port
        finally:
            srv.close()
        assert srv.port is None
        srv.close()  # close is idempotent too

    def test_attach_replaces_slots(self, registry):
        recorder = TimeSeriesRecorder(registry=registry)
        registry.gauge("serving.g").set(1.0)
        recorder.sample_once(now=1.0)
        with ObsServer(registry=registry) as srv:
            assert _get_json(srv.url + "/debug/timeseries")[0] == 404
            srv.attach(recorder=recorder)
            status, payload = _get_json(srv.url + "/debug/timeseries")
            assert status == 200
            assert "serving.g" in payload["series"]

    def test_two_servers_coexist(self, registry):
        with ObsServer(registry=registry) as a, \
                ObsServer(registry=registry) as b:
            assert a.port != b.port
            assert _get_json(a.url + "/healthz")[0] == 200
            assert _get_json(b.url + "/healthz")[0] == 200


# ----------------------------------------------------------------------
# the wired plane: ModelServer.start_telemetry
# ----------------------------------------------------------------------
def _poll(fn, timeout_s=15.0, interval_s=0.05):
    """Poll ``fn`` until it returns a truthy value; fail on timeout."""
    deadline = time.monotonic() + timeout_s
    while True:
        value = fn()
        if value:
            return value
        if time.monotonic() > deadline:
            pytest.fail("condition not reached within "
                        f"{timeout_s}s: {fn}")
        time.sleep(interval_s)


class TestServingTelemetry:
    def test_end_to_end_scrape_under_traffic(self):
        server = ModelServer(ServingConfig(max_wait_ms=1.0))
        server.register("echo", lambda x: x, item_shape=(4,),
                        compile=False)
        with server:
            obs = server.start_telemetry(
                sample_interval_s=0.05, slo_interval_s=0.1,
            )
            assert server.start_telemetry() is obs  # idempotent
            url = obs.url

            for _ in range(20):
                fut = server.submit(np.ones((4,), dtype=np.float32))
                fut.result(timeout=10.0)

            # /metrics shows the per-endpoint SLO feed counters
            text = _poll(lambda: (
                lambda t: t if "serving_requests_echo 20" in t else None
            )(_get(url + "/metrics")[2].decode()))
            assert "# HELP serving_requests_echo" in text
            assert "serving_latency_ms_echo" in text

            # /healthz: healthy, with the worst SLO state folded in
            status, health = _get_json(url + "/healthz")
            assert status == 200
            assert health["healthy"] is True
            assert health["slo_worst"] in ("ok", "warning", "page")
            assert "echo" in health["endpoints"]

            # /slo: the latency + error objectives for the endpoint
            status, slo = _get_json(url + "/slo")
            assert status == 200
            assert [r["name"] for r in slo["slos"]] == [
                "serving.echo.errors", "serving.echo.latency",
            ]

            # /debug/timeseries: the sampled latency histogram series
            _poll(lambda: "serving.latency_ms.echo.p99" in
                  _get_json(url + "/debug/timeseries")[1]["series"])

            # concurrent scrape while the server is under load
            statuses, errors = [], []

            def scrape():
                try:
                    for _ in range(10):
                        for path in ("/metrics", "/healthz",
                                     "/debug/threads"):
                            statuses.append(_get(url + path)[0])
                except Exception as exc:  # noqa: BLE001 - recorded
                    errors.append(exc)

            scrapers = [threading.Thread(target=scrape) for _ in range(4)]
            for t in scrapers:
                t.start()
            futures = [server.submit(np.ones((4,), dtype=np.float32))
                       for _ in range(200)]
            for fut in futures:
                fut.result(timeout=10.0)
            for t in scrapers:
                t.join(timeout=30.0)
            assert not errors
            assert len(statuses) == 4 * 10 * 3
            assert set(statuses) == {200}
        # close() tears the plane down
        assert server.telemetry is None

    def test_induced_latency_regression_flips_fast_burn(self):
        # the ISSUE-8 acceptance scenario: healthy traffic, then a
        # latency regression; the fast-burn window must flip the SLO
        # out of "ok", visibly at /slo and in the slo.* gauges
        delay = {"s": 0.0}

        def fwd(x):
            if delay["s"]:
                time.sleep(delay["s"])
            return x

        server = ModelServer(ServingConfig(max_wait_ms=1.0))
        server.register("echo", fwd, item_shape=(4,), compile=False)
        with server:
            obs = server.start_telemetry(
                sample_interval_s=0.02,
                slo_interval_s=0.05,
                latency_threshold_ms=50.0,
                fast_window_s=0.5,
                slow_window_s=5.0,
            )
            url = obs.url

            def request():
                server.submit(
                    np.ones((4,), dtype=np.float32)
                ).result(timeout=10.0)

            for _ in range(10):  # healthy baseline, well under 50 ms
                request()
            assert _get_json(url + "/slo")[1]["worst"] == "ok"

            delay["s"] = 0.12  # regression: every request > 50 ms
            deadline = time.monotonic() + 20.0
            worst = "ok"
            while worst == "ok" and time.monotonic() < deadline:
                request()
                worst = _get_json(url + "/slo")[1]["worst"]
            assert worst in ("warning", "page")

            snap = metrics.snapshot()
            assert snap["slo.serving.echo.latency.state"] >= 1.0
            assert snap["slo.serving.echo.latency.burn_fast"] >= 6.0
            assert snap["slo.transitions"] >= 1
