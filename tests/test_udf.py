"""SQL-UDF model-serving tests (the reference's L4 layer).

Oracle pattern from the reference (``tests/udf/keras_image_model_test.py``†,
SURVEY.md §4): register the UDF, run a SQL query, compare against directly
calling the same Keras model on the same decoded arrays.
"""

import numpy as np
import pytest

from sparkdl_tpu.image import imageIO
from sparkdl_tpu.transformers.utils import device_resize, normalize_channels

INPUT_SIZE = 24


@pytest.fixture(scope="module")
def keras_model():
    import keras

    rng = np.random.RandomState(7)
    model = keras.Sequential(
        [
            keras.layers.Input((INPUT_SIZE, INPUT_SIZE, 3)),
            keras.layers.Conv2D(4, 3, activation="relu"),
            keras.layers.GlobalAveragePooling2D(),
            keras.layers.Dense(5),
        ]
    )
    # deterministic weights
    model.set_weights([rng.randn(*w.shape).astype(np.float32) * 0.1
                       for w in model.get_weights()])
    return model


@pytest.fixture(scope="module")
def keras_model_file(keras_model, tmp_path_factory):
    path = tmp_path_factory.mktemp("udf_models") / "small_cnn.keras"
    keras_model.save(path)
    return str(path)


@pytest.fixture()
def image_df(tpu_session, image_dir):
    return imageIO.readImages(image_dir, tpu_session, numPartitions=2)


def _oracle(keras_model, image_rows, input_col="image"):
    """Direct Keras on decoded BGR->RGB resized arrays."""
    arrays = [
        normalize_channels(
            imageIO.imageStructToArray(r[input_col]).astype(np.float32), 3
        )[..., ::-1]
        for r in image_rows
    ]
    batch = device_resize(arrays, (INPUT_SIZE, INPUT_SIZE))
    return np.asarray(keras_model(batch))


def test_register_keras_image_udf_sql_oracle(
    tpu_session, image_df, keras_model, keras_model_file
):
    from sparkdl_tpu.udf import registerKerasImageUDF

    registerKerasImageUDF("small_cnn_udf", keras_model_file)
    image_df.createOrReplaceTempView("images_udf")
    out = tpu_session.sql(
        "SELECT filePath, small_cnn_udf(image) AS preds FROM images_udf"
    ).collect()

    rows = image_df.collect()
    want = _oracle(keras_model, rows)
    by_path = {r.filePath: np.asarray(r.preds) for r in out}
    assert len(out) == len(rows)
    for row, w in zip(rows, want):
        np.testing.assert_allclose(by_path[row.filePath], w, rtol=1e-4,
                                   atol=1e-4)


def test_register_keras_image_udf_bfloat16_compute(
    tpu_session, image_df, keras_model, keras_model_file
):
    """computeDtype='bfloat16' serves the same predictions within bf16
    tolerance (variables stay f32; compute narrows — the serving-path
    analog of the transformer's mixed policy)."""
    from sparkdl_tpu.udf import registerKerasImageUDF

    registerKerasImageUDF(
        "small_cnn_bf16", keras_model_file, computeDtype="bfloat16"
    )
    image_df.createOrReplaceTempView("images_udf_bf16")
    out = tpu_session.sql(
        "SELECT filePath, small_cnn_bf16(image) AS preds FROM images_udf_bf16"
    ).collect()

    rows = image_df.collect()
    want = _oracle(keras_model, rows)
    by_path = {r.filePath: np.asarray(r.preds) for r in out}
    for row, w in zip(rows, want):
        np.testing.assert_allclose(
            by_path[row.filePath], w, rtol=3e-2, atol=3e-2
        )


def test_register_keras_image_udf_bf16_rejects_in_memory_model(
    tpu_session, keras_model
):
    from sparkdl_tpu.udf import registerKerasImageUDF

    with pytest.raises(ValueError, match="computeDtype"):
        registerKerasImageUDF(
            "nope", keras_model, computeDtype="bfloat16",
            session=tpu_session,
        )


def test_register_keras_image_udf_model_object(tpu_session, image_df, keras_model):
    """Registering a built in-memory model (not a file) works identically."""
    from sparkdl_tpu.udf import registerKerasImageUDF

    udf = registerKerasImageUDF("small_cnn_obj_udf", keras_model)
    out = image_df.select(udf("image").alias("preds")).collect()
    want = _oracle(keras_model, image_df.collect())
    got = np.stack([np.asarray(r.preds) for r in out])
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)


def test_register_keras_image_udf_with_preprocessor(
    tpu_session, image_dir, keras_model, keras_model_file
):
    """File-path mode: preprocessor(path) -> ndarray feeds the model raw."""
    from PIL import Image

    from sparkdl_tpu.udf import registerKerasImageUDF

    def preprocessor(path):
        img = Image.open(path).convert("RGB").resize((INPUT_SIZE, INPUT_SIZE))
        return np.asarray(img, dtype=np.float32)

    registerKerasImageUDF(
        "small_cnn_file_udf", keras_model_file, preprocessor=preprocessor
    )
    files_df = imageIO.filesToDF(tpu_session, image_dir)
    files_df.createOrReplaceTempView("files_udf")
    out = tpu_session.sql(
        "SELECT filePath, small_cnn_file_udf(filePath) AS preds FROM files_udf"
    ).collect()

    paths = [r.filePath for r in files_df.collect()]
    batch = np.stack([preprocessor(p) for p in paths])
    want = np.asarray(keras_model(batch))
    by_path = {r.filePath: np.asarray(r.preds) for r in out}
    for p, w in zip(paths, want):
        np.testing.assert_allclose(by_path[p], w, rtol=1e-4, atol=1e-4)


def test_make_graph_udf_single_output(tpu_session):
    from sparkdl_tpu.graph.function import XlaFunction
    from sparkdl_tpu.udf import makeGraphUDF

    fn = XlaFunction.from_callable(lambda x: (x * 2.0).sum(axis=-1))
    makeGraphUDF(fn, "double_sum")
    df = tpu_session.createDataFrame(
        [([1.0, 2.0, 3.0],), ([4.0, 5.0, 6.0],)], ["v"]
    )
    df.createOrReplaceTempView("vectors_udf")
    out = tpu_session.sql("SELECT double_sum(v) AS s FROM vectors_udf").collect()
    assert [r.s for r in out] == [12.0, 30.0]


def test_make_graph_udf_vector_output_and_params(tpu_session):
    import jax.numpy as jnp

    from sparkdl_tpu.graph.function import XlaFunction
    from sparkdl_tpu.udf import makeGraphUDF

    w = np.arange(6, dtype=np.float32).reshape(3, 2)
    fn = XlaFunction.from_callable(
        lambda p, x: x @ p["w"],
        params={"w": jnp.asarray(w)},
        takes_params=True,
    )
    udf = makeGraphUDF(fn, "matmul_udf", register=False)
    df = tpu_session.createDataFrame([([1.0, 0.0, 1.0],)], ["v"])
    out = df.select(udf("v").alias("y")).collect()
    np.testing.assert_allclose(
        np.asarray(out[0].y), np.array([1, 0, 1], np.float32) @ w
    )
    # register=False must not have polluted the session registry
    assert "matmul_udf" not in tpu_session.udf


def test_make_graph_udf_multi_output(tpu_session):
    from sparkdl_tpu.graph.function import XlaFunction
    from sparkdl_tpu.udf import makeGraphUDF

    fn = XlaFunction.from_callable(
        lambda x: (x.sum(axis=-1), x.max(axis=-1)),
        output_names=("total", "peak"),
    )
    makeGraphUDF(fn, "stats_udf")
    df = tpu_session.createDataFrame([([1.0, 5.0],), ([2.0, 2.0],)], ["v"])
    df.createOrReplaceTempView("stats_in")
    out = tpu_session.sql("SELECT stats_udf(v) AS st FROM stats_in").collect()
    assert out[0].st.total == 6.0 and out[0].st.peak == 5.0
    assert out[1].st.total == 4.0 and out[1].st.peak == 2.0


def test_package_export_resolves():
    """Round-1 regression: the façade advertised sparkdl_tpu.udf but the
    module didn't exist (VERDICT.md Missing #2)."""
    import sparkdl_tpu

    assert callable(sparkdl_tpu.registerKerasImageUDF)
    assert callable(sparkdl_tpu.makeGraphUDF)


class TestServingPipeline:
    """The decode/compute overlap in the serving path (VERDICT r2 weak #2):
    run_batched_rows pipelines prefetch-thread decode + one-ahead dispatch;
    results must be identical to the strict serial path."""

    def test_pipelined_equals_serial_udf(
        self, tpu_session, image_df, keras_model_file, keras_model,
        monkeypatch,
    ):
        from sparkdl_tpu.udf.keras_image_model import registerKerasImageUDF

        rows = image_df.collect()
        udf = registerKerasImageUDF(
            "pipe_udf", keras_model_file, batchSize=3
        )
        image_df.createOrReplaceTempView("pipe_images")
        got = tpu_session.sql("SELECT pipe_udf(image) AS f FROM pipe_images")
        pipelined = np.stack([np.asarray(r.f.toArray()) for r in got.collect()])

        monkeypatch.setenv("SPARKDL_SERIAL_INFERENCE", "1")
        got2 = tpu_session.sql("SELECT pipe_udf(image) AS f FROM pipe_images")
        serial = np.stack([np.asarray(r.f.toArray()) for r in got2.collect()])
        np.testing.assert_array_equal(pipelined, serial)

        want = _oracle(keras_model, rows)
        np.testing.assert_allclose(pipelined, want, rtol=1e-4, atol=1e-5)

    def test_run_batched_rows_matches_run_batched(self):
        import jax
        import jax.numpy as jnp

        from sparkdl_tpu.transformers.utils import (
            run_batched,
            run_batched_rows,
        )

        rng = np.random.RandomState(0)
        data = rng.rand(23, 6).astype(np.float32)  # ragged vs batch 4
        rows = list(range(23))

        @jax.jit
        def fn(x):
            return jnp.tanh(x) * 2.0

        want = run_batched(fn, data, 4)
        got = run_batched_rows(
            fn, rows, lambda chunk: data[np.asarray(chunk)], 4
        )
        np.testing.assert_array_equal(got, want)

    def test_run_batched_rows_decode_error_propagates(self):
        import jax.numpy as jnp

        from sparkdl_tpu.transformers.utils import run_batched_rows

        def decode(chunk):
            raise RuntimeError("decode exploded")

        with pytest.raises(RuntimeError, match="decode exploded"):
            run_batched_rows(
                lambda x: jnp.asarray(x), list(range(8)), decode, 4
            )

    def test_mixed_shape_partition_single_program(
        self, tpu_session, keras_model_file, keras_model, tmp_path
    ):
        """Mixed (H, W) partitions resize-while-packing per chunk to the
        model size; output equals the oracle on resized arrays."""
        from PIL import Image

        from sparkdl_tpu.udf.keras_image_model import registerKerasImageUDF

        rng = np.random.RandomState(5)
        sizes = [(40, 40), (56, 44), (40, 40), (64, 64), (56, 44)]
        for i, (h, w) in enumerate(sizes):
            Image.fromarray(
                (rng.rand(h, w, 3) * 255).astype(np.uint8)
            ).save(tmp_path / f"m_{i}.png")
        df = imageIO.readImages(str(tmp_path), tpu_session, numPartitions=1)
        rows = df.collect()

        registerKerasImageUDF("mix_udf", keras_model_file, batchSize=2)
        df.createOrReplaceTempView("mix_images")
        got = tpu_session.sql("SELECT mix_udf(image) AS f FROM mix_images")
        out = np.stack([np.asarray(r.f.toArray()) for r in got.collect()])
        want = _oracle(keras_model, rows)
        np.testing.assert_allclose(out, want, rtol=1e-4, atol=1e-4)

    def test_preprocessor_cross_chunk_shape_contract(
        self, tpu_session, keras_model_file, tmp_path
    ):
        """A preprocessor whose output shape changes on a CHUNK boundary
        still gets the one-fixed-shape contract error (not a raw
        concatenate failure)."""
        from sparkdl_tpu.udf.keras_image_model import registerKerasImageUDF

        calls = {"n": 0}

        def shifty(path):
            calls["n"] += 1
            side = 32 if calls["n"] <= 2 else 48  # flips exactly at chunk 2
            return np.zeros((side, side, 3), np.float32)

        udf = registerKerasImageUDF(
            "shifty_udf", keras_model_file, preprocessor=shifty, batchSize=2
        )
        df = tpu_session.createDataFrame(
            [{"path": f"p{i}"} for i in range(4)], numPartitions=1
        )
        with pytest.raises(ValueError, match="one fixed array shape"):
            df.select(udf("path")).collect()

    def test_mode_mixed_partition_one_dtype(self, tpu_session, keras_model_file,
                                            keras_model):
        """Uniform-size partition mixing uint8 and float32 OpenCV modes:
        the whole-partition decode plan must feed ONE dtype to the forward
        (a chunk-local uint8 decision would compile two programs), and the
        output must equal the oracle."""
        rng = np.random.RandomState(11)
        rows = []
        for i in range(6):
            arr = (rng.rand(INPUT_SIZE, INPUT_SIZE, 3) * 255)
            if i < 3:  # uint8 modes first (chunk-aligned with batchSize=3)
                rows.append(imageIO.imageArrayToStruct(arr.astype(np.uint8)))
            else:  # float32 mode
                rows.append(imageIO.imageArrayToStruct(arr.astype(np.float32)))
        df = tpu_session.createDataFrame([{"image": r} for r in rows],
                                         numPartitions=1)
        from sparkdl_tpu.udf.keras_image_model import registerKerasImageUDF

        udf = registerKerasImageUDF("modemix_udf", keras_model_file,
                                    batchSize=3)
        got = df.select(udf("image").alias("f")).collect()
        out = np.stack([np.asarray(r.f.toArray()) for r in got])
        want = _oracle(keras_model, [{"image": r} for r in rows])
        np.testing.assert_allclose(out, want, rtol=1e-4, atol=1e-4)


def test_scored_view_joins_labels(
    tpu_session, image_df, keras_model, keras_model_file
):
    """The reference's canonical serving-analytics flow (SURVEY.md §3.3):
    score images with a registered model UDF, then JOIN the scored view
    against a labels table and aggregate — in both the DataFrame API and
    the SQL dialect."""
    from sparkdl_tpu.udf import registerKerasImageUDF

    registerKerasImageUDF("join_cnn_udf", keras_model_file)
    image_df.createOrReplaceTempView("images_join")
    scored = tpu_session.sql(
        "SELECT filePath, join_cnn_udf(image) AS preds FROM images_join"
    )
    scored.createOrReplaceTempView("scored")

    paths = [r.filePath for r in image_df.collect()]
    labels = tpu_session.createDataFrame(
        # one known path, one unknown path, one NULL path
        [(paths[0], "cat"), ("/nope.png", "dog"), (None, "fish")],
        ["filePath", "truth"],
    )
    labels.createOrReplaceTempView("truth_tbl")

    # API form: left join keeps every scored row; only paths[0] matches
    api = scored.join(labels, on="filePath", how="left")
    rows = api.collect()
    assert len(rows) == len(paths)
    matched = [r for r in rows if r.truth is not None]
    assert [r.filePath for r in matched] == [paths[0]]
    # predictions survive the join unchanged
    want = _oracle(keras_model, image_df.collect())
    by_path = {r.filePath: np.asarray(r.preds) for r in rows}
    np.testing.assert_allclose(
        by_path[paths[0]], want[0], rtol=1e-4, atol=1e-4
    )

    # SQL form, aggregated over the joined result
    agg = tpu_session.sql(
        "SELECT truth, COUNT(*) AS n FROM scored "
        "JOIN truth_tbl ON scored.filePath = truth_tbl.filePath "
        "GROUP BY truth"
    ).collect()
    assert [(r.truth, r.n) for r in agg] == [("cat", 1)]
