"""Streaming inference: sources, watermarks, exactly-once commit, recovery.

The acceptance core is the kill matrix: a ``FaultPlan`` SIGKILLs the
runner (``os._exit(9)``) at each of ``streaming.poll`` /
``streaming.sink`` / ``streaming.commit``; a restarted runner must
resume from the last committed offset and leave the sink's record set
*exactly* the source's record set — no loss, no duplicates.  The
kill-between-payload-and-commit-marker case additionally proves the
pending epoch is replayed (not re-scored, not skipped) — the streaming
mirror of the estimator checkpoint-commit test."""

import json
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from sparkdl_tpu.data import Dataset
from sparkdl_tpu.resilience.errors import PermanentError
from sparkdl_tpu.streaming import (
    CallbackSink,
    CommitLog,
    FileTailSource,
    JsonlSink,
    QueueSource,
    Record,
    StreamConfig,
    StreamRunner,
    WatermarkTracker,
)
from sparkdl_tpu.utils.metrics import metrics

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def fast_config(**overrides):
    kw = dict(max_batch=4, max_wait_ms=5.0, poll_batch=4,
              poll_interval_ms=2.0)
    kw.update(overrides)
    return StreamConfig(**kw)


# ---------------------------------------------------------------------------
# sources
# ---------------------------------------------------------------------------


class TestQueueSource:
    def test_poll_seek_replay(self):
        src = QueueSource()
        src.put_all(["a", "b", "c", "d"])
        first = src.poll(3)
        assert [r.value for r in first] == ["a", "b", "c"]
        assert [r.offset for r in first] == [1, 2, 3]
        assert src.position() == 3
        src.seek(1)  # replay everything after record 1
        assert [r.value for r in src.poll(10)] == ["b", "c", "d"]

    def test_finished_only_after_end_and_drain(self):
        src = QueueSource()
        src.put("x")
        assert not src.finished()
        src.end()
        assert not src.finished()  # still one record to drain
        src.poll(5)
        assert src.finished()
        with pytest.raises(ValueError):
            src.put("y")

    def test_backlog(self):
        src = QueueSource()
        src.put_all(range(5))
        assert src.backlog() == 5
        src.poll(2)
        assert src.backlog() == 3


class TestFileTailSource:
    def test_tail_growing_file(self, tmp_path):
        path = tmp_path / "in.jsonl"
        src = FileTailSource(str(path))
        assert src.poll(10) == []  # not created yet: empty, not an error
        with open(path, "a") as fh:
            fh.write('{"x": 1}\n{"x": 2}\n')
        vals = src.poll(10)
        assert [r.value for r in vals] == [{"x": 1}, {"x": 2}]
        with open(path, "a") as fh:
            fh.write('{"x": 3}\n')
        assert [r.value for r in src.poll(10)] == [{"x": 3}]

    def test_partial_line_left_for_next_poll(self, tmp_path):
        path = tmp_path / "in.jsonl"
        path.write_text('{"x": 1}\n{"x": 2')  # torn write, no newline
        src = FileTailSource(str(path))
        assert [r.value for r in src.poll(10)] == [{"x": 1}]
        with open(path, "a") as fh:
            fh.write("}\n")
        assert [r.value for r in src.poll(10)] == [{"x": 2}]

    def test_byte_offsets_replay_identically(self, tmp_path):
        path = tmp_path / "in.jsonl"
        path.write_text('{"x": 1}\n{"x": 2}\n{"x": 3}\n')
        src = FileTailSource(str(path))
        recs = src.poll(2)
        # a fresh source sought to a record's offset resumes right after it
        other = FileTailSource(str(path))
        other.seek(recs[-1].offset)
        assert [r.value for r in other.poll(10)] == [{"x": 3}]

    def test_event_time_field_and_blank_lines(self, tmp_path):
        path = tmp_path / "in.jsonl"
        path.write_text('{"x": 1, "ts": 100}\n\n{"x": 2, "ts": 50}\n')
        src = FileTailSource(str(path), event_time_field="ts")
        recs = src.poll(10)
        assert [r.event_time_ms for r in recs] == [100.0, 50.0]

    def test_corrupt_line_is_permanent(self, tmp_path):
        path = tmp_path / "in.jsonl"
        path.write_text("not json\n")
        src = FileTailSource(str(path))
        with pytest.raises(PermanentError):
            src.poll(10)

    def test_raw_mode(self, tmp_path):
        path = tmp_path / "in.log"
        path.write_text("alpha\nbeta\n")
        src = FileTailSource(str(path), parse="raw")
        assert [r.value for r in src.poll(10)] == ["alpha", "beta"]


class TestWatermark:
    def test_bounded_lateness(self):
        wm = WatermarkTracker(allowed_lateness_ms=10.0)
        assert wm.observe(100.0) is False
        assert wm.watermark_ms == 90.0
        assert wm.observe(95.0) is False   # within lateness allowance
        assert wm.observe(80.0) is True    # behind the watermark: late
        assert wm.watermark_ms == 90.0     # max never decreases
        assert wm.observe(200.0) is False
        assert wm.watermark_ms == 190.0

    def test_no_event_times_no_watermark(self):
        wm = WatermarkTracker()
        assert wm.observe(None) is False
        assert wm.watermark_ms is None
        assert wm.lag_ms(1000.0) is None

    def test_lag(self):
        wm = WatermarkTracker()
        wm.observe(1000.0)
        assert wm.lag_ms(1500.0) == 500.0


# ---------------------------------------------------------------------------
# commit log + sinks
# ---------------------------------------------------------------------------


class TestCommitLog:
    def test_payload_then_marker(self, tmp_path):
        log = CommitLog(str(tmp_path / "log"))
        assert log.last_committed() is None
        assert log.resume_offset() is None
        log.write_payload(1, {"end_offset": 4, "records": [{"a": 1}]})
        assert log.pending() == [1]
        log.commit(1)
        assert log.pending() == []
        assert log.last_committed() == 1
        assert log.resume_offset() == 4
        assert log.payload(1)["records"] == [{"a": 1}]

    def test_marker_requires_payload(self, tmp_path):
        log = CommitLog(str(tmp_path / "log"))
        with pytest.raises(ValueError):
            log.commit(1)

    def test_resume_offset_prefers_highest_payload(self, tmp_path):
        # a pending (uncommitted) payload still checkpoints its offset:
        # its records replay from the payload, never from the source
        log = CommitLog(str(tmp_path / "log"))
        log.write_payload(1, {"end_offset": 4, "records": []})
        log.commit(1)
        log.write_payload(2, {"end_offset": 9, "records": []})
        assert log.pending() == [2]
        assert log.resume_offset() == 9


class TestJsonlSink:
    def test_replay_is_idempotent(self, tmp_path):
        sink = JsonlSink(str(tmp_path / "out.jsonl"))
        sink.write(1, [{"v": 1}, {"v": 2}])
        sink.write(2, [{"v": 3}])
        sink.write(2, [{"v": 3}])  # replay: exactly one copy survives
        rows = sink.read_all()
        assert [r["v"] for r in rows] == [1, 2, 3]
        assert [r["epoch"] for r in rows] == [1, 1, 2]

    def test_replay_after_reopen(self, tmp_path):
        path = str(tmp_path / "out.jsonl")
        JsonlSink(path).write(1, [{"v": 1}])
        sink = JsonlSink(path)  # fresh process: index rebuilt from disk
        sink.write(1, [{"v": 1}])
        assert [r["v"] for r in sink.read_all()] == [1]

    def test_torn_tail_truncated_on_open(self, tmp_path):
        path = tmp_path / "out.jsonl"
        path.write_bytes(b'{"epoch": 1, "v": 1}\n{"epoch": 2, "v":')
        sink = JsonlSink(str(path))
        assert [r["v"] for r in sink.read_all()] == [1]
        sink.write(2, [{"v": 2}])
        assert [r["v"] for r in sink.read_all()] == [1, 2]


class TestCallbackSink:
    def test_in_process_dedupe(self):
        got = []
        sink = CallbackSink(lambda epoch, recs: got.append((epoch, recs)))
        sink.write(1, [{"v": 1}])
        sink.write(1, [{"v": 1}])
        assert got == [(1, [{"v": 1}])]

    def test_failed_delivery_can_retry(self):
        calls = []

        def fn(epoch, recs):
            calls.append(epoch)
            if len(calls) == 1:
                raise RuntimeError("flaky consumer")

        sink = CallbackSink(fn)
        with pytest.raises(RuntimeError):
            sink.write(1, [])
        sink.write(1, [])  # the failure un-marked the epoch
        assert calls == [1, 1]


# ---------------------------------------------------------------------------
# Dataset.from_stream + unbounded batch semantics
# ---------------------------------------------------------------------------


class TestFromStream:
    def test_yields_values_until_finished(self):
        src = QueueSource()
        src.put_all(range(7))
        src.end()
        ds = Dataset.from_stream(src, poll_batch=3)
        assert ds.unbounded
        assert list(ds) == list(range(7))

    def test_max_records_window_is_bounded(self):
        src = QueueSource()
        src.put_all(range(100))
        ds = Dataset.from_stream(src, max_records=5)
        assert not ds.unbounded
        assert list(ds) == [0, 1, 2, 3, 4]

    def test_shuffle_rejected_on_unbounded(self):
        ds = Dataset.from_stream(QueueSource())
        with pytest.raises(ValueError, match="unbounded"):
            ds.shuffle(seed=0)

    def test_cyclic_pad_rejected_on_unbounded(self):
        ds = Dataset.from_stream(QueueSource())
        with pytest.raises(ValueError, match="unbounded"):
            ds.batch(4, pad="cyclic")

    def test_ragged_final_batch(self):
        src = QueueSource()
        src.put_all(range(10))
        src.end()
        batches = list(Dataset.from_stream(src).batch(4))
        assert [b.n_real for b in batches] == [4, 4, 2]

    def test_drop_remainder(self):
        src = QueueSource()
        src.put_all(range(10))
        src.end()
        batches = list(Dataset.from_stream(src).batch(4, drop_remainder=True))
        assert [b.n_real for b in batches] == [4, 4]

    def test_drop_remainder_on_bounded_dataset(self):
        ds = Dataset.from_items(list(range(9)))
        batches = list(ds.batch(4, drop_remainder=True))
        assert [list(b.items) for b in batches] == [[0, 1, 2, 3], [4, 5, 6, 7]]
        assert len(ds.batch(4, drop_remainder=True)) == 2

    def test_drop_remainder_excludes_pad(self):
        with pytest.raises(ValueError, match="mutually exclusive"):
            Dataset.from_items([1, 2]).batch(2, pad="cyclic",
                                             drop_remainder=True)

    def test_unbounded_flag_propagates(self):
        ds = Dataset.from_stream(QueueSource()).map(lambda x: x).batch(4)
        assert ds.unbounded
        with pytest.raises(TypeError):
            len(ds)


# ---------------------------------------------------------------------------
# StreamRunner in-process
# ---------------------------------------------------------------------------


def _offsets(sink_rows):
    return sorted(r["offset"] for r in sink_rows)


class TestStreamRunner:
    def test_end_to_end_exactly_once(self, tmp_path):
        src = QueueSource()
        src.put_all([[float(i)] for i in range(25)])
        src.end()
        sink = JsonlSink(str(tmp_path / "out.jsonl"))
        runner = StreamRunner(
            src, lambda x: np.asarray(x) * 2.0, sink,
            str(tmp_path / "log"), config=fast_config(),
        )
        summary = runner.run()
        assert summary["stop_reason"] == "source_finished"
        rows = sink.read_all()
        assert _offsets(rows) == list(range(1, 26))
        assert rows[0]["output"] == [0.0]
        assert rows[3]["output"] == [6.0]
        assert summary["committed_offset"] == 25
        log = CommitLog(str(tmp_path / "log"))
        assert log.pending() == []

    def test_max_epochs_stop(self, tmp_path):
        src = QueueSource()
        src.put_all([[1.0]] * 40)
        sink = JsonlSink(str(tmp_path / "out.jsonl"))
        runner = StreamRunner(
            src, lambda x: np.asarray(x), sink, str(tmp_path / "log"),
            config=fast_config(),
        )
        summary = runner.run(max_epochs=2)
        assert summary["stop_reason"] == "max_epochs"
        assert summary["epochs"] >= 2

    def test_idle_timeout_stop(self, tmp_path):
        src = QueueSource()  # never ends, never produces
        sink = CallbackSink(lambda e, r: None)
        runner = StreamRunner(
            src, lambda x: x, sink, str(tmp_path / "log"),
            config=fast_config(),
        )
        summary = runner.run(idle_timeout_s=0.1)
        assert summary["stop_reason"] == "idle_timeout"
        assert summary["epochs"] == 0

    def test_backpressure_blocks_instead_of_shedding(self, tmp_path):
        # a tiny queue + slow scorer: the poller must stall, not drop
        shed_before = metrics.counter("streaming.shed").value
        src = QueueSource()
        src.put_all([[float(i)] for i in range(60)])
        src.end()
        sink = JsonlSink(str(tmp_path / "out.jsonl"))

        def slow(x):
            time.sleep(0.002)
            return np.asarray(x)

        runner = StreamRunner(
            src, slow, sink, str(tmp_path / "log"),
            config=fast_config(queue_capacity=4, poll_batch=16,
                               offer_timeout_s=0.05),
        )
        runner.run()
        assert _offsets(sink.read_all()) == list(range(1, 61))
        assert metrics.counter("streaming.shed").value == shed_before

    def test_watermark_and_lag_metrics_in_prometheus_text(self, tmp_path):
        from sparkdl_tpu.obs.export import prometheus_text

        src = QueueSource()
        now_ms = time.time() * 1000.0
        for i in range(8):
            src.put([float(i)], event_time_ms=now_ms - 5000.0 + i)
        src.put([99.0], event_time_ms=now_ms - 50000.0)  # very late
        src.end()
        sink = CallbackSink(lambda e, r: None)
        runner = StreamRunner(
            src, lambda x: np.asarray(x), sink, str(tmp_path / "log"),
            config=fast_config(allowed_lateness_ms=1000.0),
        )
        summary = runner.run()
        assert summary["watermark_ms"] == pytest.approx(
            now_ms - 5000.0 + 7 - 1000.0
        )
        assert metrics.counter("streaming.late_records").value >= 1
        lag = metrics.gauge("streaming.watermark_lag_ms").value
        assert lag >= 5000.0
        text = prometheus_text(metrics)
        assert "streaming_watermark_lag_ms" in text
        assert "streaming_epochs_committed" in text
        assert "streaming_records_in" in text

    def test_spans_nest_across_runner_threads(self, tmp_path):
        from sparkdl_tpu.obs import tracer

        spans = []
        tracer.enable(sink=spans.append)
        try:
            src = QueueSource()
            src.put_all([[float(i)] for i in range(12)])
            src.end()
            sink = CallbackSink(lambda e, r: None)
            StreamRunner(
                src, lambda x: np.asarray(x), sink, str(tmp_path / "log"),
                config=fast_config(),
            ).run()
        finally:
            tracer.disable()
        by_name = {}
        for s in spans:
            by_name.setdefault(s["name"], []).append(s)
        run = by_name["streaming.run"]
        assert len(run) == 1
        run_id, trace_id = run[0]["span_id"], run[0]["trace_id"]
        # poll spans are created on the poller THREAD but must still nest
        # under the run span (explicit capture()/use_span propagation)
        assert by_name["streaming.poll"], "no poll spans recorded"
        for s in by_name["streaming.poll"]:
            assert s["parent_id"] == run_id
            assert s["trace_id"] == trace_id
        for s in by_name["streaming.epoch"]:
            assert s["parent_id"] == run_id
            assert s["trace_id"] == trace_id
        assert by_name["streaming.recover"][0]["parent_id"] == run_id

    def test_preemption_flushes_and_resumes(self, tmp_path):
        from sparkdl_tpu.resilience import preempt

        src = QueueSource()
        src.put_all([[float(i)] for i in range(40)])
        sink = JsonlSink(str(tmp_path / "out.jsonl"))

        def slow(x):
            time.sleep(0.002)
            return np.asarray(x)

        runner = StreamRunner(
            src, slow, sink, str(tmp_path / "log"),
            config=fast_config(max_batch=4),
        )
        timer = threading.Timer(
            0.05, preempt.request_preemption, args=("test preemption",)
        )
        timer.start()
        try:
            summary = runner.run()
        finally:
            timer.cancel()
        assert summary["stop_reason"] == "preempted"
        committed = len(sink.read_all())
        # everything admitted before the preempt was flushed + committed
        assert committed == summary["committed_offset"] or committed == 0

        # a fresh runner resumes from the committed offset: the union is
        # exactly the source, no duplicates
        src.end()
        runner2 = StreamRunner(
            src, slow, sink, str(tmp_path / "log"),
            config=fast_config(max_batch=4),
        )
        runner2.run()
        assert _offsets(sink.read_all()) == list(range(1, 41))

    def test_restart_replays_pending_epoch(self, tmp_path):
        # simulate a crash between payload write and marker: the payload
        # exists, the sink write may or may not have landed
        log = CommitLog(str(tmp_path / "log"))
        records = [{"offset": 1, "input": [1.0], "output": [2.0]}]
        log.write_payload(1, {"end_offset": 1, "records": records})
        src = QueueSource()
        src.put([1.0])  # record 1, already scored per the payload
        src.end()
        sink = JsonlSink(str(tmp_path / "out.jsonl"))
        runner = StreamRunner(
            src, lambda x: np.asarray(x), sink, str(tmp_path / "log"),
            config=fast_config(),
        )
        summary = runner.run()
        assert summary["replayed"] == 1
        rows = sink.read_all()
        # the epoch was re-emitted from the payload (bit-identical
        # outputs), the source was NOT re-polled for it
        assert len(rows) == 1
        assert rows[0]["output"] == [2.0]
        assert CommitLog(str(tmp_path / "log")).pending() == []

    def test_from_server_scores_through_endpoint(self, tmp_path):
        from sparkdl_tpu.serving import ModelServer, ServingConfig

        with ModelServer(config=ServingConfig()) as server:
            server.register(
                "double", lambda b: b * 2.0, item_shape=(2,),
                compile=False,
            )
            src = QueueSource()
            src.put_all([
                np.full((2,), float(i), dtype=np.float32) for i in range(9)
            ])
            src.end()
            sink = JsonlSink(str(tmp_path / "out.jsonl"))
            runner = StreamRunner.from_server(
                src, server, sink, str(tmp_path / "log"),
                model_id="double", config=fast_config(),
            )
            runner.run()
        rows = sink.read_all()
        assert _offsets(rows) == list(range(1, 10))
        assert rows[4]["output"] == [8.0, 8.0]


# ---------------------------------------------------------------------------
# the kill matrix: FaultPlan SIGKILL at each streaming site → restart →
# sink record set == source record set
# ---------------------------------------------------------------------------

N_RECORDS = 30

WORKER = """
import json, os, sys
os.environ.setdefault("KERAS_BACKEND", "jax")
import jax
jax.config.update("jax_platforms", "cpu")
sys.path.insert(0, {repo!r})
from sparkdl_tpu.streaming import FileTailSource, JsonlSink, StreamRunner, StreamConfig
workdir = {workdir!r}
source = FileTailSource(os.path.join(workdir, "in.jsonl"))
sink = JsonlSink(os.path.join(workdir, "out.jsonl"))
runner = StreamRunner(
    source,
    lambda xs: [x["x"] * 2 for x in xs],
    sink,
    os.path.join(workdir, "log"),
    config=StreamConfig(max_batch=4, max_wait_ms=5.0, poll_batch=4,
                        poll_interval_ms=2.0),
    pack=False,
)
summary = runner.run(idle_timeout_s=1.0)
print("SUMMARY " + json.dumps(summary))
print("WORKER_FINISHED")
"""

SIGTERM_WORKER = """
import json, os, signal, sys, threading, time
os.environ.setdefault("KERAS_BACKEND", "jax")
import jax
jax.config.update("jax_platforms", "cpu")
sys.path.insert(0, {repo!r})
import numpy as np
from sparkdl_tpu.streaming import FileTailSource, JsonlSink, StreamRunner, StreamConfig
workdir = {workdir!r}
source = FileTailSource(os.path.join(workdir, "in.jsonl"))
sink = JsonlSink(os.path.join(workdir, "out.jsonl"))

def slow(xs):
    time.sleep(0.01)
    return [x["x"] * 2 for x in xs]

runner = StreamRunner(
    source, slow, sink, os.path.join(workdir, "log"),
    config=StreamConfig(max_batch=4, max_wait_ms=5.0, poll_batch=4,
                        poll_interval_ms=2.0),
    pack=False,
)
threading.Timer(0.15, os.kill, args=(os.getpid(), signal.SIGTERM)).start()
summary = runner.run(idle_timeout_s=5.0)
print("SUMMARY " + json.dumps(summary))
"""


def _write_source(workdir, n=N_RECORDS):
    os.makedirs(workdir, exist_ok=True)
    with open(os.path.join(workdir, "in.jsonl"), "w") as fh:
        for i in range(n):
            fh.write(json.dumps({"x": i}) + "\n")


def _run_worker(workdir, script=WORKER, fault_plan=None, timeout=90):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("SPARKDL_FAULT_PLAN", None)
    if fault_plan is not None:
        env["SPARKDL_FAULT_PLAN"] = json.dumps(fault_plan)
    return subprocess.run(
        [sys.executable, "-c", script.format(repo=_REPO, workdir=workdir)],
        env=env,
        stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT,
        text=True,
        timeout=timeout,
    )


def _summary_of(proc):
    for line in proc.stdout.splitlines():
        if line.startswith("SUMMARY "):
            return json.loads(line[len("SUMMARY "):])
    raise AssertionError(f"no SUMMARY in worker output:\n{proc.stdout}")


def _assert_exactly_once(workdir):
    with open(os.path.join(workdir, "out.jsonl")) as fh:
        rows = [json.loads(line) for line in fh if line.endswith("\n")]
    inputs = [r["input"]["x"] for r in rows]
    assert sorted(inputs) == list(range(N_RECORDS)), (
        f"sink != source: {len(inputs)} rows, "
        f"dupes={len(inputs) - len(set(inputs))}"
    )
    for r in rows:
        assert r["output"] == r["input"]["x"] * 2


@pytest.mark.parametrize("site,at", [
    ("streaming.poll", 3),
    ("streaming.sink", 2),
    ("streaming.commit", 2),
])
def test_kill_at_site_then_restart_is_exactly_once(tmp_path, site, at):
    workdir = str(tmp_path)
    _write_source(workdir)
    killed = _run_worker(
        workdir, fault_plan=[{"site": site, "kill": True, "at": at}]
    )
    assert killed.returncode == 9, killed.stdout
    assert "WORKER_FINISHED" not in killed.stdout

    restarted = _run_worker(workdir)
    assert restarted.returncode == 0, restarted.stdout
    summary = _summary_of(restarted)
    assert summary["committed_offset"] is not None
    _assert_exactly_once(workdir)


def test_kill_between_payload_and_marker_replays_exactly_that_epoch(
    tmp_path,
):
    """The satellite case: death AFTER the payload write but BEFORE the
    commit marker.  The restart must replay exactly the uncertain epoch
    (from its stored payload — no re-scoring) and the sink must hold one
    copy of every record."""
    workdir = str(tmp_path)
    _write_source(workdir)
    killed = _run_worker(
        workdir,
        fault_plan=[{"site": "streaming.commit", "kill": True, "at": 2}],
    )
    assert killed.returncode == 9, killed.stdout
    from sparkdl_tpu.streaming import CommitLog as Log

    log = Log(os.path.join(workdir, "log"))
    pending_before = log.pending()
    assert pending_before, "the kill must leave a payload without marker"

    restarted = _run_worker(workdir)
    assert restarted.returncode == 0, restarted.stdout
    summary = _summary_of(restarted)
    assert summary["replayed"] == len(pending_before)
    assert log.pending() == []
    _assert_exactly_once(workdir)


def test_sigterm_flushes_inflight_epoch_and_resumes(tmp_path):
    workdir = str(tmp_path)
    _write_source(workdir)
    first = _run_worker(workdir, script=SIGTERM_WORKER, timeout=120)
    assert first.returncode == 0, first.stdout
    summary = _summary_of(first)
    if summary["stop_reason"] == "preempted":
        # resume from the last committed offset and finish the stream
        restarted = _run_worker(workdir)
        assert restarted.returncode == 0, restarted.stdout
    else:
        # the whole stream committed before the signal landed — already
        # complete; nothing to resume
        assert summary["stop_reason"] == "idle_timeout"
    _assert_exactly_once(workdir)
