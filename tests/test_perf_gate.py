"""Perf-regression gate (``ci/perf_gate.py``): shape matching,
tolerance bands, waivers, wrapper unpacking, and both CLI modes —
all over synthetic report files in a tmp repo root; no bench runs.
"""

import json

import pytest

from ci import perf_gate
from ci.perf_gate import (
    extract_reports,
    find_baseline,
    gate_fresh,
    gate_trajectory,
    load_waivers,
    shape_key,
)


def _report(**over):
    base = {
        "benchmark": "bench_load", "scenario": "kill", "replicas": 2,
        "workers": 2, "target_rps": 60.0, "duration_s": 12.0,
        "compile": False, "transport_mode": "auto", "obs": True,
        "goodput_rps": 50.0,
        "latency_ms": {"p50": 3.0, "p99": 10.0},
        "router_overhead_ms": {"p50": 1.5},
    }
    base.update(over)
    return base


def _commit(root, name, payload):
    path = root / name
    path.write_text(json.dumps(payload))
    return str(path)


class TestShapes:
    def test_obs_armed_runs_never_gate_obs_off(self):
        assert shape_key(_report(obs=True)) != \
            shape_key(_report(obs=False))
        # a "trace" section counts as obs-armed too
        assert shape_key(_report(obs=False, trace={"out": "x"})) == \
            shape_key(_report(obs=True))

    def test_extract_reports_unpacks_wrappers(self):
        plain = _report()
        assert extract_reports("/x/B.json", plain) == \
            [("B.json", plain)]
        wrapper = {
            "benchmark": "bench_load",  # wrapper, but not a report
            "profile_on": _report(goodput_rps=49.0),
            "profile_off": _report(goodput_rps=50.0),
            "hedging": {"p99_delta_ms": 1.0},
        }
        labels = [lbl for lbl, _ in
                  extract_reports("/x/B.json", wrapper)]
        assert labels == ["B.json:profile_off", "B.json:profile_on"]

    def test_find_baseline_newest_same_shape(self, tmp_path):
        _commit(tmp_path, "BENCH_LOAD_r1.json", _report(
            goodput_rps=10.0))
        _commit(tmp_path, "BENCH_LOAD_r2.json", _report(
            goodput_rps=20.0))
        _commit(tmp_path, "BENCH_LOAD_r3.json", _report(
            goodput_rps=30.0, scenario="faultnet"))  # other shape
        label, base = find_baseline(_report(), str(tmp_path))
        assert label == "BENCH_LOAD_r2.json"
        assert base["goodput_rps"] == 20.0

    def test_find_baseline_honors_exclusions(self, tmp_path):
        _commit(tmp_path, "BENCH_LOAD_r1.json", _report())
        _commit(tmp_path, "BENCH_LOAD_r2.json", _report())
        label, _ = find_baseline(
            _report(), str(tmp_path),
            exclude_labels=["BENCH_LOAD_r2.json"],
        )
        assert label == "BENCH_LOAD_r1.json"


class TestGateFresh:
    def _gate(self, tmp_path, fresh, name="fresh.json"):
        fresh_path = _commit(tmp_path, name, fresh)
        return gate_fresh(
            fresh_path, str(tmp_path),
            str(tmp_path / "waivers.json"),
        )

    def test_clean_run_passes(self, tmp_path):
        _commit(tmp_path, "BENCH_LOAD_r1.json", _report())
        verdict = self._gate(tmp_path, _report(goodput_rps=48.0))
        assert verdict["ok"]
        assert verdict["baseline"] == "BENCH_LOAD_r1.json"
        assert all(r["ok"] for r in verdict["rows"])

    def test_no_baseline_passes_with_note(self, tmp_path):
        verdict = self._gate(tmp_path, _report())
        assert verdict["ok"]
        assert verdict["baseline"] is None
        assert "no committed same-shape baseline" in verdict["note"]

    def test_doubled_p99_fails(self, tmp_path):
        _commit(tmp_path, "BENCH_LOAD_r1.json", _report())
        verdict = self._gate(
            tmp_path,
            _report(latency_ms={"p50": 3.0, "p99": 22.0}),
        )
        assert not verdict["ok"]
        bad = [r for r in verdict["rows"] if not r["ok"]]
        assert [r["metric"] for r in bad] == ["latency_ms.p99"]

    def test_noise_floor_absorbs_small_absolute_wobble(self, tmp_path):
        """+75% of a 2ms p99 is 1.5ms of scheduler noise, not a
        regression — the absolute floor must absorb it."""
        _commit(tmp_path, "BENCH_LOAD_r1.json", _report(
            latency_ms={"p50": 1.0, "p99": 2.0}))
        verdict = self._gate(
            tmp_path, _report(latency_ms={"p50": 1.8, "p99": 5.0}),
        )
        assert verdict["ok"]

    def test_goodput_collapse_fails(self, tmp_path):
        _commit(tmp_path, "BENCH_LOAD_r1.json", _report())
        verdict = self._gate(tmp_path, _report(goodput_rps=30.0))
        assert not verdict["ok"]

    def test_fresh_file_in_repo_root_never_self_gates(self, tmp_path):
        """A --out into the repo root (the pre-commit workflow) must
        gate against the PREVIOUS archive entry, not itself."""
        _commit(tmp_path, "BENCH_LOAD_r1.json", _report())
        fresh = {
            "benchmark": "bench_load",
            "profile_off": _report(goodput_rps=48.0),
            "profile_on": _report(goodput_rps=47.0),
        }
        fresh_path = _commit(tmp_path, "BENCH_LOAD_r2.json", fresh)
        verdict = gate_fresh(
            fresh_path, str(tmp_path),
            str(tmp_path / "waivers.json"),
        )
        assert verdict["baseline"] == "BENCH_LOAD_r1.json"

    def test_missing_report_raises(self, tmp_path):
        path = _commit(tmp_path, "empty.json", {"benchmark": "other"})
        with pytest.raises(ValueError):
            gate_fresh(path, str(tmp_path),
                       str(tmp_path / "waivers.json"))


class TestWaivers:
    def test_waived_breach_passes_with_reason(self, tmp_path):
        _commit(tmp_path, "BENCH_LOAD_r1.json", _report())
        waivers = _commit(tmp_path, "waivers.json", {"waivers": [
            {"metric": "latency_ms.p99",
             "reason": "tracing now on by default"},
        ]})
        fresh = _commit(tmp_path, "fresh.json", _report(
            latency_ms={"p50": 3.0, "p99": 30.0}))
        verdict = gate_fresh(fresh, str(tmp_path), waivers)
        assert verdict["ok"]
        row = next(r for r in verdict["rows"]
                   if r["metric"] == "latency_ms.p99")
        assert row["waived"] == "tracing now on by default"

    def test_waiver_scoped_to_other_baseline_does_not_apply(
            self, tmp_path):
        _commit(tmp_path, "BENCH_LOAD_r1.json", _report())
        waivers = _commit(tmp_path, "waivers.json", {"waivers": [
            {"metric": "latency_ms.p99", "reason": "x",
             "baseline": "BENCH_LOAD_r9.json"},
        ]})
        fresh = _commit(tmp_path, "fresh.json", _report(
            latency_ms={"p50": 3.0, "p99": 30.0}))
        assert not gate_fresh(fresh, str(tmp_path), waivers)["ok"]

    def test_malformed_waiver_raises(self, tmp_path):
        path = _commit(tmp_path, "waivers.json", {"waivers": [
            {"metric": "latency_ms.p99"},  # no reason
        ]})
        with pytest.raises(ValueError):
            load_waivers(path)

    def test_absent_waiver_file_is_empty(self, tmp_path):
        assert load_waivers(str(tmp_path / "nope.json")) == []


class TestTrajectory:
    def test_walks_same_shape_pairs_in_rn_order(self, tmp_path):
        _commit(tmp_path, "BENCH_LOAD_r2.json", _report(
            goodput_rps=50.0))
        _commit(tmp_path, "BENCH_LOAD_r10.json", _report(
            goodput_rps=49.0))  # lexically before r2, numerically after
        _commit(tmp_path, "BENCH_LOAD_r11.json", _report(
            scenario="steady"))  # no predecessor of its shape
        verdict = gate_trajectory(
            str(tmp_path), str(tmp_path / "waivers.json"))
        assert verdict["ok"]
        assert [(p["fresh"], p["baseline"])
                for p in verdict["pairs"]] == [
            ("BENCH_LOAD_r10.json", "BENCH_LOAD_r2.json"),
        ]

    def test_regressed_archive_entry_fails(self, tmp_path):
        _commit(tmp_path, "BENCH_LOAD_r1.json", _report())
        _commit(tmp_path, "BENCH_LOAD_r2.json", _report(
            goodput_rps=20.0))
        verdict = gate_trajectory(
            str(tmp_path), str(tmp_path / "waivers.json"))
        assert not verdict["ok"]


class TestCli:
    def test_fresh_pass_and_fail_exit_codes(self, tmp_path, capsys):
        _commit(tmp_path, "BENCH_LOAD_r1.json", _report())
        good = _commit(tmp_path, "good.json", _report())
        bad = _commit(tmp_path, "bad.json", _report(goodput_rps=5.0))
        assert perf_gate.main(
            ["--fresh", good, "--repo-root", str(tmp_path)]) == 0
        assert "PASS" in capsys.readouterr().out
        assert perf_gate.main(
            ["--fresh", bad, "--repo-root", str(tmp_path)]) == 1
        assert "FAIL" in capsys.readouterr().out

    def test_trajectory_json_mode(self, tmp_path, capsys):
        _commit(tmp_path, "BENCH_LOAD_r1.json", _report())
        _commit(tmp_path, "BENCH_LOAD_r2.json", _report())
        assert perf_gate.main(
            ["--trajectory", "--repo-root", str(tmp_path),
             "--json"]) == 0
        verdict = json.loads(capsys.readouterr().out)
        assert verdict["mode"] == "trajectory"
        assert len(verdict["pairs"]) == 1

    def test_unreadable_fresh_file_is_usage_error(self, tmp_path):
        assert perf_gate.main(
            ["--fresh", str(tmp_path / "missing.json"),
             "--repo-root", str(tmp_path)]) == 2
