"""Native PJRT runner tests — the second (non-Python) execution stack.

The dual-stack contract (SURVEY.md §2 "Scala DeepImageFeaturizer", §3.5):
a C++ executor drives a PJRT plugin directly — compile exported StableHLO,
resident params, stream batches — and must agree with the Python stack's
numerics (oracle pattern, SURVEY.md §4).

These tests need a live PJRT plugin with a device behind it (the axon TPU
plugin in this environment); they skip cleanly when it is absent.  They
run the runner's client in-process while jax stays on the CPU platform
(conftest forces JAX_PLATFORMS=cpu), so the two stacks never contend for
the TPU session.
"""

import os
import subprocess

import numpy as np
import pytest

import jax.numpy as jnp

from sparkdl_tpu.native import pjrt


def _plugin_usable() -> bool:
    if not os.path.exists(pjrt.DEFAULT_PLUGIN):
        return False
    return pjrt.is_available()


def _tunnel_responsive(timeout_s: int = 120) -> "tuple[bool, str]":
    """Bounded client-creation probe through the resilience watchdog
    (subprocess probe + hard-timeout backstop + typed error_class — see
    :mod:`sparkdl_tpu.resilience.watchdog`).  The in-process client is
    only created after the probe succeeds."""
    from sparkdl_tpu.resilience.watchdog import check_device

    record = check_device(
        timeout_s=timeout_s,
        probe_code=(
            "from sparkdl_tpu.native import pjrt\n"
            "r = pjrt.PjrtRunner()\n"
            "print('PLATFORM', r.platform())\n"
            "r.close()\n"
        ),
    )
    return record["ok"], record["detail"]


pytestmark = [
    pytest.mark.slow,
    pytest.mark.skipif(
        not _plugin_usable(),
        reason="no PJRT plugin / native runner unavailable",
    ),
]


@pytest.fixture(scope="module", autouse=True)
def _require_responsive_tunnel():
    """Probed lazily (not at collection) so healthy runs pay one quick
    subprocess client-create and wedged rigs fail loudly in bounded
    time; run-tests.sh's skip-honesty gate turns the skip into a hard
    CI failure on a full rig."""
    ok, msg = _tunnel_responsive()
    if not ok:
        pytest.skip(f"PJRT plugin present but unresponsive: {msg}")


@pytest.fixture(scope="module")
def tiny_program(tmp_path_factory):
    """Exported two-output program with resident params."""
    w = np.arange(12, dtype=np.float32).reshape(3, 4) / 10.0
    b = np.ones((4,), np.float32)

    def fn(p, x):
        return jnp.dot(x, p["w"]) + p["b"], jnp.sum(x, axis=1)

    x = np.random.RandomState(0).rand(5, 3).astype(np.float32)
    d = str(tmp_path_factory.mktemp("prog"))
    manifest = pjrt.export_program(
        fn, {"w": w, "b": b}, [x], d, input_names=["x"]
    )
    return d, manifest, w, b


def test_export_manifest(tiny_program):
    d, manifest, w, b = tiny_program
    assert [p["shape"] for p in manifest["params"]] == [[4], [3, 4]]
    assert manifest["inputs"][0]["dtype"] == "f32"
    assert [o["shape"] for o in manifest["outputs"]] == [[5, 4], [5]]
    for f in ("program.mlir", "params.bin", "compile_options.pb",
              "manifest.txt", "plugin_options.txt"):
        assert os.path.exists(os.path.join(d, f)), f


def test_native_program_matches_numpy(tiny_program):
    """In-process bridge: compile + resident params + two batches."""
    d, manifest, w, b = tiny_program
    rng = np.random.RandomState(1)
    with pjrt.NativeProgram(d) as prog:
        assert prog.runner.platform in ("tpu", "cpu", "axon")
        for _ in range(2):  # second batch reuses resident params
            x = rng.rand(5, 3).astype(np.float32)
            y, s = prog(x)
            np.testing.assert_allclose(y, x @ w + b, rtol=2e-2, atol=1e-2)
            np.testing.assert_allclose(s, x.sum(1), rtol=2e-2, atol=1e-2)


def test_cli_tool_streams_batches(tiny_program, tmp_path):
    """The standalone C++ featurizer binary: no Python in the loop."""
    from sparkdl_tpu.native.featurizer import build_tool

    d, manifest, w, b = tiny_program
    tool = build_tool()
    rng = np.random.RandomState(2)
    batches = rng.rand(3, 5, 3).astype(np.float32)
    in_path = tmp_path / "in.bin"
    out_path = tmp_path / "out.bin"
    batches.tofile(in_path)
    proc = subprocess.run(
        [tool, pjrt.DEFAULT_PLUGIN, d, str(in_path), str(out_path)],
        capture_output=True, text=True, timeout=600,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    raw = np.fromfile(out_path, np.float32)
    per_batch = 5 * 4 + 5  # out1 (5,4) + out2 (5,)
    assert raw.size == 3 * per_batch
    for i in range(3):
        rec = raw[i * per_batch:(i + 1) * per_batch]
        y = rec[:20].reshape(5, 4)
        s = rec[20:]
        np.testing.assert_allclose(
            y, batches[i] @ w + b, rtol=2e-2, atol=1e-2
        )
        np.testing.assert_allclose(
            s, batches[i].sum(1), rtol=2e-2, atol=1e-2
        )


def test_native_featurizer_oracle(tmp_path):
    """Dual-stack DeepImageFeaturizer: the exported MobileNetV2 program on
    the native stack ≡ the same fused forward in plain jax (CPU f32/bf16
    vs TPU bf16 — tolerance covers the backend matmul precision gap)."""
    import jax

    from sparkdl_tpu.models import get_keras_application_model
    from sparkdl_tpu.native.featurizer import export_featurizer
    from sparkdl_tpu.transformers.named_image import _resolve_variables
    from sparkdl_tpu.transformers.utils import cast_and_resize_on_device

    d = str(tmp_path / "feat")
    export_featurizer(
        "MobileNetV2", batch_size=2, out_dir=d, source_hw=(64, 64),
        model_weights="random",
    )
    rng = np.random.RandomState(0)
    x = rng.randint(0, 255, (2, 64, 64, 3), np.uint8)
    with pjrt.NativeProgram(d) as prog:
        got, = prog(x)

    entry = get_keras_application_model("MobileNetV2")
    module = entry.make_module(dtype=jnp.bfloat16)
    variables = _resolve_variables("MobileNetV2", "random")
    h, w = entry.input_size

    def forward(v, xx):
        xx = cast_and_resize_on_device(xx, (h, w))
        xx = entry.preprocess(xx[..., ::-1])
        out = module.apply(v, xx.astype(jnp.bfloat16), features_only=True)
        return out.reshape(out.shape[0], -1).astype(jnp.float32)

    want = np.asarray(jax.jit(forward)(variables, x))
    err = np.abs(got - want) / (np.abs(want) + 1e-3)
    assert err.max() < 0.15, f"max rel err {err.max()}"


def test_native_featurizer_stage_matches_python_stack(tmp_path, monkeypatch):
    """NativeDeepImageFeaturizer (C++ decode+pack -> C++ PJRT execute) ≡
    DeepImageFeaturizer (Python stack) on the same deterministic-random
    weights — the dual-stack agreement the reference had between its
    Scala and Python featurizers."""
    from PIL import Image

    from sparkdl_tpu import DeepImageFeaturizer, NativeDeepImageFeaturizer
    from sparkdl_tpu.image import imageIO
    from sparkdl_tpu.sql.session import TPUSession

    img_dir = tmp_path / "imgs"
    img_dir.mkdir()
    rng = np.random.RandomState(0)
    for i in range(5):  # 5 rows, batch 4 -> exercises the ragged tail
        Image.fromarray(
            rng.randint(0, 255, (224, 224, 3), np.uint8)
        ).save(img_dir / f"im{i}.png")

    spark = TPUSession.builder.getOrCreate()
    df = imageIO.readImages(str(img_dir), spark, numPartitions=2)

    monkeypatch.setenv(
        "SPARKDL_NATIVE_PROGRAM_CACHE", str(tmp_path / "progcache")
    )
    native = NativeDeepImageFeaturizer(
        inputCol="image", outputCol="f", modelName="MobileNetV2",
        modelWeights="random", batchSize=4,
    ).transform(df).collect()
    python = DeepImageFeaturizer(
        inputCol="image", outputCol="f", modelName="MobileNetV2",
        modelWeights="random", batchSize=4,
    ).transform(df).collect()

    got = np.stack([r["f"].toArray() for r in native])
    want = np.stack([r["f"].toArray() for r in python])
    assert got.shape == want.shape == (5, 1280)
    err = np.abs(got - want) / (np.abs(want) + 1e-3)
    assert err.max() < 0.15, f"max rel err {err.max()}"


def test_async_pipeline_matches_sync(tiny_program):
    """put_async/execute_async double-buffering (VERDICT r2 weak #2)
    produces the same outputs as the serialized path: enqueue batch i+1's
    transfer+execute before fetching batch i, fetch in order."""
    d, manifest, w, b = tiny_program
    rng = np.random.RandomState(3)
    batches = [rng.rand(5, 3).astype(np.float32) for _ in range(4)]
    with pjrt.NativeProgram(d) as prog:
        runner, exec_id = prog.runner, prog.exec_id
        param_ids = prog.param_ids

        in_flight = []  # (input_id, [output_ids], batch_index)
        results = {}

        def drain(entry):
            in_id, out_ids, idx = entry
            y = runner.fetch(out_ids[0], (5, 4), "f32")
            s = runner.fetch(out_ids[1], (5,), "f32")
            for oid in out_ids:
                runner.free(oid)
            runner.free(in_id)
            results[idx] = (y, s)

        for i, x in enumerate(batches):
            in_id = runner.put_async(x)
            out_ids = runner.execute_async(exec_id, param_ids + [in_id])
            in_flight.append((in_id, out_ids, i))
            if len(in_flight) > 1:  # one batch stays in flight
                drain(in_flight.pop(0))
        while in_flight:
            drain(in_flight.pop(0))

    for i, x in enumerate(batches):
        y, s = results[i]
        np.testing.assert_allclose(y, x @ w + b, rtol=2e-2, atol=1e-2)
        np.testing.assert_allclose(s, x.sum(1), rtol=2e-2, atol=1e-2)


def test_await_buffer_surfaces_readiness(tiny_program):
    d, manifest, w, b = tiny_program
    with pjrt.NativeProgram(d) as prog:
        runner = prog.runner
        x = np.random.RandomState(4).rand(5, 3).astype(np.float32)
        in_id = runner.put_async(x)
        runner.await_buffer(in_id)  # transfer completes without error
        out_ids = runner.execute_async(prog.exec_id, prog.param_ids + [in_id])
        runner.await_buffer(out_ids[0])  # compute completes
        y = runner.fetch(out_ids[0], (5, 4), "f32")
        np.testing.assert_allclose(y, x @ w + b, rtol=2e-2, atol=1e-2)
        for oid in out_ids:
            runner.free(oid)
        runner.free(in_id)


def test_native_program_stream_matches_call(tiny_program):
    """NativeProgram.stream (double-buffered generator) yields the same
    outputs, in order, as sequential __call__."""
    d, manifest, w, b = tiny_program
    rng = np.random.RandomState(5)
    batches = [rng.rand(5, 3).astype(np.float32) for _ in range(5)]
    with pjrt.NativeProgram(d) as prog:
        want = [prog(x) for x in batches]
        got = list(prog.stream(iter(batches)))
    assert len(got) == len(want)
    for g, wnt in zip(got, want):
        for ga, wa in zip(g, wnt):
            np.testing.assert_allclose(ga, wa, rtol=1e-6, atol=1e-7)


def test_native_program_stream_abandoned_frees_buffers(tiny_program):
    """Abandoning the stream generator mid-way must not leak the pending
    batch's buffers (later calls still work on the same runner)."""
    d, manifest, w, b = tiny_program
    rng = np.random.RandomState(6)
    batches = [rng.rand(5, 3).astype(np.float32) for _ in range(4)]
    with pjrt.NativeProgram(d) as prog:
        gen = prog.stream(iter(batches))
        next(gen)  # one result out, one batch still in flight
        gen.close()  # abandon
        y, s = prog(batches[0])  # runner still healthy
        np.testing.assert_allclose(
            y, batches[0] @ w + b, rtol=2e-2, atol=1e-2
        )
