"""Pallas kernel tests (oracle pattern, SURVEY.md §4): flash attention ≡
full attention.  Runs in Pallas interpret mode on the CPU test mesh — the
same kernel code that compiles via Mosaic on TPU."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from sparkdl_tpu.ops import flash_attention
from sparkdl_tpu.parallel.context import full_attention


def _qkv(b, s, h, d, seed=0):
    rng = np.random.RandomState(seed)
    mk = lambda: jnp.asarray(rng.randn(b, s, h, d), jnp.float32)
    return mk(), mk(), mk()


@pytest.mark.parametrize(
    "shape",
    [(2, 197, 3, 64),   # ViT-Ti: CLS-token seq, sub-tile head_dim
     (1, 128, 2, 32),   # exact block multiple
     (2, 300, 4, 128)], # pad-to-block seq, full-lane head_dim
)
def test_flash_matches_full(shape):
    q, k, v = _qkv(*shape)
    got = np.asarray(flash_attention(q, k, v))
    want = np.asarray(full_attention(q, k, v))
    np.testing.assert_allclose(got, want, atol=2e-4, rtol=2e-4)


def test_flash_causal():
    q, k, v = _qkv(1, 197, 2, 64)
    got = np.asarray(flash_attention(q, k, v, causal=True))
    want = np.asarray(full_attention(q, k, v, causal=True))
    np.testing.assert_allclose(got, want, atol=2e-4, rtol=2e-4)


def test_flash_kv_len_mask():
    """kv_len masks trailing keys exactly like the dense oracle."""
    q, k, v = _qkv(1, 256, 2, 64)
    got = np.asarray(flash_attention(q, k, v, kv_len=200))
    want = np.asarray(full_attention(q, k, v, kv_len=200))
    np.testing.assert_allclose(got, want, atol=2e-4, rtol=2e-4)


def test_vit_with_flash_attention():
    """The kernel drops into ViT's attn_impl slot: same params, same
    logits as the dense schedule."""
    from sparkdl_tpu.models.vit import ViT

    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.rand(2, 32, 32, 3), jnp.float32)
    dense = ViT(variant="ViT-Ti/16", num_classes=4, image_size=32)
    variables = dense.init(jax.random.PRNGKey(0), x)
    flash = ViT(
        variant="ViT-Ti/16", num_classes=4, image_size=32,
        attn_impl=flash_attention,
    )
    np.testing.assert_allclose(
        np.asarray(flash.apply(variables, x)),
        np.asarray(dense.apply(variables, x)),
        atol=5e-4, rtol=5e-3,
    )


def test_ulysses_flash_local_attention():
    """Ulysses SP with the Pallas kernel as its local dense step ≡ full
    attention over the global sequence (8-device CPU mesh)."""
    from jax.sharding import Mesh

    from sparkdl_tpu.parallel.context import make_sp_attention

    devices = np.asarray(jax.devices()[:4])
    mesh = Mesh(devices, ("seq",))
    b, s, h, d = 1, 256, 4, 64
    q, k, v = _qkv(b, s, h, d, seed=3)
    fn = make_sp_attention(mesh, "seq", impl="ulysses-flash")
    got = np.asarray(fn(q, k, v))
    want = np.asarray(full_attention(q, k, v))
    np.testing.assert_allclose(got, want, atol=2e-4, rtol=2e-4)


@pytest.mark.parametrize(
    "kwargs", [{}, {"causal": True}, {"kv_len": 200}],
    ids=["plain", "causal", "kv_len"],
)
def test_flash_backward_matches_full(kwargs):
    """The custom VJP (streaming dQ / dK+dV kernels) ≡ autodiff through
    the dense oracle, in a random cotangent direction."""
    q, k, v = _qkv(1, 256, 2, 64, seed=7)
    w = jnp.asarray(np.random.RandomState(8).randn(*q.shape), jnp.float32)

    g_flash = jax.grad(
        lambda q, k, v: (flash_attention(q, k, v, **kwargs) * w).sum(),
        argnums=(0, 1, 2),
    )(q, k, v)
    g_full = jax.grad(
        lambda q, k, v: (full_attention(q, k, v, **kwargs) * w).sum(),
        argnums=(0, 1, 2),
    )(q, k, v)
    for a, b in zip(g_flash, g_full):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-3, rtol=1e-3
        )


def test_vit_trains_through_flash():
    """A ViT training-step gradient flows through the kernel (finite loss,
    nonzero grads) — flash is training-grade, not inference-only."""
    import optax

    from sparkdl_tpu.models.vit import ViT

    rng = np.random.RandomState(0)
    m = ViT(variant="ViT-Ti/16", num_classes=4, image_size=32,
            attn_impl=flash_attention)
    x = jnp.asarray(rng.rand(4, 32, 32, 3), jnp.float32)
    y = jnp.asarray(rng.randint(0, 4, 4), jnp.int32)
    variables = m.init(jax.random.PRNGKey(0), x)

    def loss(p):
        return optax.softmax_cross_entropy_with_integer_labels(
            m.apply(p, x), y
        ).mean()

    l, g = jax.value_and_grad(loss)(variables)
    assert np.isfinite(float(l))
    gsum = jax.tree_util.tree_reduce(
        lambda a, b: a + float(jnp.abs(b).sum()), g, 0.0
    )
    assert gsum > 0
