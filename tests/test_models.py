"""Model-zoo oracle tests.

Reference test pattern (SURVEY.md §4): framework output is compared against
directly calling the same Keras model on the same arrays — the oracle is
single-process Keras (``python/tests/transformers/named_image_test.py``†).
Here the Keras models carry random (``weights=None``) initialization because
the environment has no network for pretrained downloads; the *porting map* is
what's under test, and any mis-wiring shows up as a numeric mismatch.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from sparkdl_tpu.models import (
    KERAS_APPLICATION_MODELS,
    SUPPORTED_MODELS,
    get_keras_application_model,
    port_keras_weights,
)
from sparkdl_tpu.models.registry import decode_predictions, preprocess_input

keras = pytest.importorskip("keras")

ALL_MODELS = ["InceptionV3", "Xception", "ResNet50", "VGG16", "VGG19",
              "MobileNetV2"]


@pytest.fixture(scope="module")
def oracle_cache():
    return {}


def _oracle(name, cache):
    if name not in cache:
        entry = get_keras_application_model(name)
        km = entry.keras_model(weights=None)
        cache[name] = (entry, km, entry.load_variables(km))
    return cache[name]


def test_registry_surface():
    assert set(SUPPORTED_MODELS) == set(ALL_MODELS)
    for name in SUPPORTED_MODELS:
        entry = KERAS_APPLICATION_MODELS[name]
        h, w = entry.inputShape()
        assert h == w and h in (224, 299)
        assert entry.feature_size in (1280, 2048, 4096)
    with pytest.raises(ValueError):
        get_keras_application_model("NoSuchNet")


@pytest.mark.parametrize("name", ALL_MODELS)
def test_logits_match_keras_oracle(name, oracle_cache):
    entry, km, variables = _oracle(name, oracle_cache)
    h, w = entry.input_size
    x = np.random.RandomState(0).rand(2, h, w, 3).astype("float32") * 2 - 1
    expected = np.asarray(km(x, training=False))
    fm = entry.make_module()
    got = np.asarray(jax.jit(fm.apply)(variables, jnp.asarray(x)))
    assert got.shape == (2, 1000)
    np.testing.assert_allclose(got, expected, rtol=1e-4, atol=1e-4)


@pytest.mark.parametrize("name", ["InceptionV3", "VGG16"])
def test_feature_cut_point(name, oracle_cache):
    """DeepImageFeaturizer cut points: GAP for the CNNs, fc2 for VGG."""
    entry, km, variables = _oracle(name, oracle_cache)
    h, w = entry.input_size
    x = np.random.RandomState(1).rand(1, h, w, 3).astype("float32")
    fm = entry.make_module()
    feats = np.asarray(
        jax.jit(lambda v, a: fm.apply(v, a, features_only=True))(
            variables, jnp.asarray(x)
        )
    )
    assert feats.shape == (1, entry.feature_size)
    # Keras-side oracle for the cut: penultimate layer of the same model.
    cut_layer = "avg_pool" if name != "VGG16" else "fc2"
    sub = keras.Model(km.inputs, km.get_layer(cut_layer).output)
    expected = np.asarray(sub(x, training=False))
    np.testing.assert_allclose(feats, expected, rtol=1e-4, atol=1e-4)


def test_init_shapes_match_ported_shapes(oracle_cache):
    entry, km, variables = _oracle("MobileNetV2", oracle_cache)
    fm = entry.make_module()
    init = jax.eval_shape(
        fm.init, jax.random.PRNGKey(0), jnp.zeros((1, 224, 224, 3))
    )
    got = jax.tree_util.tree_map(lambda v: tuple(v.shape), variables)
    want = jax.tree_util.tree_map(lambda v: tuple(v.shape), init)
    assert got == want


def test_preprocess_modes():
    x = jnp.full((1, 2, 2, 3), 255.0)
    tf_out = preprocess_input(x, "tf")
    np.testing.assert_allclose(np.asarray(tf_out), 1.0)
    caffe = np.asarray(preprocess_input(x, "caffe"))
    np.testing.assert_allclose(
        caffe[0, 0, 0], [255 - 103.939, 255 - 116.779, 255 - 123.68]
    )
    torch_out = np.asarray(preprocess_input(x, "torch"))
    np.testing.assert_allclose(
        torch_out[0, 0, 0], (1.0 - np.array([0.485, 0.456, 0.406]))
        / np.array([0.229, 0.224, 0.225]), rtol=1e-6
    )
    with pytest.raises(ValueError):
        preprocess_input(x, "nope")


def test_decode_predictions_fallback():
    preds = np.zeros((1, 1000), dtype=np.float32)
    preds[0, 7] = 5.0
    preds[0, 3] = 4.0
    out = decode_predictions(preds, top=2)
    assert len(out) == 1 and len(out[0]) == 2
    wnid, label, score = out[0][0]
    assert score == 5.0 and (label == "class_7" or wnid.startswith("n"))


def test_fold_bgr_flip_into_stem_is_exact():
    """Folded-stem forward on BGR input == plain forward on flipped input
    (channel-symmetric preprocessing)."""
    import jax
    import jax.numpy as jnp

    from sparkdl_tpu.models import get_keras_application_model
    from sparkdl_tpu.models.registry import fold_bgr_flip_into_stem

    entry = get_keras_application_model("MobileNetV2")  # "tf" mode
    module = entry.make_module()
    x_bgr = jnp.asarray(
        np.random.RandomState(0).rand(2, 224, 224, 3), jnp.float32
    )
    with jax.default_device(jax.local_devices(backend="cpu")[0]):
        variables = module.init(jax.random.PRNGKey(0), x_bgr)
        folded = fold_bgr_flip_into_stem(variables, entry.preprocess_mode)
        assert folded is not None
        # the gate lives in the helper: caffe-mode (channel-asymmetric
        # preprocessing) must refuse to fold
        assert fold_bgr_flip_into_stem(variables, "caffe") is None
        want = module.apply(
            variables, entry.preprocess(x_bgr[..., ::-1]), features_only=True
        )
        got = module.apply(
            folded, entry.preprocess(x_bgr), features_only=True
        )
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), atol=1e-5, rtol=1e-5
    )


def test_decode_predictions_real_labels_offline():
    """The vendored class-name list gives real ImageNet labels with no
    network and no Keras cache (VERDICT round-1 item 9)."""
    from sparkdl_tpu.models.imagenet_labels import IMAGENET_CLASS_NAMES

    assert len(IMAGENET_CLASS_NAMES) == 1000
    assert len(set(IMAGENET_CLASS_NAMES)) >= 998  # "crane"/"maillot" repeat

    preds = np.zeros((2, 1000), dtype=np.float32)
    preds[0, 281] = 9.0  # tabby
    preds[0, 285] = 5.0  # Egyptian_cat
    preds[1, 207] = 7.0  # golden_retriever
    out = decode_predictions(preds, top=2)
    labels = [[e[1] for e in row] for row in out]
    assert labels[0] == ["tabby", "Egyptian_cat"]
    assert labels[1][0] == "golden_retriever"
    # non-1000-way outputs still fall back to synthetic names
    small = np.zeros((1, 10), dtype=np.float32)
    small[0, 4] = 1.0
    assert decode_predictions(small, top=1)[0][0][1] == "class_4"


def test_xception_lane_aligned_padding(oracle_cache):
    """The registry's Xception is the 768-wide (6x128 lane-aligned)
    variant holding zero-padded Keras weights — shapes widened, pad
    regions exactly zero (variance: one), so the Keras-oracle logits
    test above doubles as the numerics proof."""
    entry, km, variables = _oracle("Xception", oracle_cache)
    assert entry.make_module().middle_width == 768
    pk = np.asarray(
        variables["params"]["block5_sepconv1"]["pointwise_kernel"]
    )
    assert pk.shape == (1, 1, 768, 768)
    assert np.all(pk[:, :, 728:, :] == 0) and np.all(pk[:, :, :, 728:] == 0)
    dw = np.asarray(
        variables["params"]["block5_sepconv1"]["depthwise_kernel"]
    )
    assert dw.shape[-1] == 768 and np.all(dw[..., 728:] == 0)
    bn_var = np.asarray(
        variables["batch_stats"]["block5_sepconv1_bn"]["var"]
    )
    assert np.all(bn_var[728:] == 1.0)
    # the exit-flow 1024-channel side is untouched
    assert variables["params"]["block13_sepconv2"][
        "pointwise_kernel"
    ].shape == (1, 1, 768, 1024)


def test_xception_width_migration_paths():
    """Pre-widening artifacts keep working: a 728-wide variables pytree
    passed as modelWeights pads up transparently, and a topless Keras
    model (no 'predictions' layer) ports without a structure error."""
    from sparkdl_tpu.models.xception import Xception
    from sparkdl_tpu.transformers.named_image import _resolve_variables

    narrow_shapes = jax.eval_shape(
        Xception(middle_width=728).init,
        jax.random.PRNGKey(0),
        jnp.zeros((1, 299, 299, 3), jnp.float32),
    )
    narrow = jax.tree_util.tree_map(
        lambda l: jnp.zeros(l.shape, l.dtype), narrow_shapes
    )
    resolved = _resolve_variables("Xception", narrow)
    assert resolved["params"]["block5_sepconv1"][
        "pointwise_kernel"
    ].shape == (1, 1, 768, 768)
    # idempotent for already-widened pytrees
    again = _resolve_variables("Xception", resolved)
    assert again["params"]["block5_sepconv1"][
        "pointwise_kernel"
    ].shape == (1, 1, 768, 768)

    km_topless = keras.applications.Xception(
        weights=None, include_top=False
    )
    variables = get_keras_application_model("Xception").load_variables(
        km_topless
    )
    assert "predictions" not in variables["params"]
    assert variables["params"]["block5_sepconv1"][
        "pointwise_kernel"
    ].shape == (1, 1, 768, 768)
