"""Replica plane tests: wire protocol, router, supervisor, autoscaler.

The centerpiece is the ISSUE-10 kill matrix
(:class:`TestKillMatrix`): with 2 replicas under sustained multi-thread
traffic, a ``FaultPlan`` kill at ``supervisor.replica_serve`` takes one
replica out **mid-request** — and the run must lose zero accepted
requests (the stranded one retries on the survivor), the supervisor
must restart the dead slot, and p99 must return to pre-kill levels
within a bounded window.  The compile-cache restart proof
(:func:`test_restart_is_cache_warm`) asserts a restarted replica's
warmup loaded every executable from ``SPARKDL_COMPILE_CACHE`` disk
instead of recompiling.

Every ``supervisor.*`` / ``router.*`` fault site registered in
``resilience.inject.KNOWN_SITES`` is exercised here (the
``fault-site-coverage`` rule cross-references these string literals):
``supervisor.replica_serve`` (kill matrix), ``supervisor.replica_warm``
(:func:`test_replica_warm_kill_restarts`), ``supervisor.spawn`` /
``supervisor.restart`` (:func:`test_spawn_and_restart_fault_sites`),
``supervisor.health`` (:func:`test_health_probe_condemns_replica`),
``router.route`` (:func:`test_route_fault_site_fires`).

Process-spawning tests pace themselves on supervisor state, not sleeps;
each replica boot pays a jax import, so the per-test replica counts are
deliberately minimal.
"""

import json
import os
import socket
import struct
import threading
import time

import numpy as np
import pytest

from sparkdl_tpu.resilience import inject
from sparkdl_tpu.utils.metrics import metrics
from sparkdl_tpu.resilience.errors import TransientError
from sparkdl_tpu.resilience.policy import RetryPolicy
from sparkdl_tpu.serving import ModelServer, ServingConfig, wire
from sparkdl_tpu.serving.errors import (
    NoLiveReplicas,
    RemoteReplicaError,
    ReplicaDraining,
    ServerOverloaded,
)
from sparkdl_tpu.serving.autoscale import Autoscaler
from sparkdl_tpu.serving.replica import ReplicaService, ReplicaSpec
from sparkdl_tpu.serving.router import Router
from sparkdl_tpu.serving.supervisor import ReplicaSupervisor

PLAIN_FACTORY = "sparkdl_tpu.serving.replica:demo_server_plain"
COMPILE_FACTORY = "sparkdl_tpu.serving.replica:demo_server"


def fast_supervisor(**kw):
    """A supervisor tuned for test latency: tight monitor ticks, fast
    deterministic backoff."""
    defaults = dict(
        replicas=1,
        monitor_interval_s=0.05,
        health_interval_s=1.0,
        spawn_timeout_s=120.0,
        backoff=RetryPolicy(
            max_attempts=8, base_delay_s=0.1, multiplier=1.5,
            max_delay_s=0.5, jitter=0.0,
        ),
    )
    spec = kw.pop("spec", None) or ReplicaSpec(factory=PLAIN_FACTORY)
    defaults.update(kw)
    return ReplicaSupervisor(spec, **defaults)


def wait_until(predicate, timeout_s=60.0, interval_s=0.05):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(interval_s)
    return predicate()


# ----------------------------------------------------------------------
# wire protocol
# ----------------------------------------------------------------------
class TestWire:
    def test_roundtrip_ndarray_frame(self):
        a, b = socket.socketpair()
        try:
            payload = {"op": "infer", "value": np.arange(8, dtype=np.float32)}
            wire.send_msg(a, payload)
            got = wire.recv_msg(b)
            assert got["op"] == "infer"
            np.testing.assert_array_equal(got["value"], payload["value"])
        finally:
            a.close()
            b.close()

    def test_clean_eof_is_none(self):
        a, b = socket.socketpair()
        a.close()
        try:
            assert wire.recv_msg(b) is None
        finally:
            b.close()

    def test_mid_frame_close_is_connection_error(self):
        a, b = socket.socketpair()
        try:
            # a frame prefix promising 100 body bytes, then death
            a.sendall(struct.pack(
                ">4sBBIQ", wire.MAGIC, wire.KIND_MSG, 0, 10, 100
            ) + b"only-a-few")
            a.close()
            with pytest.raises(ConnectionError):
                wire.recv_msg(b)
        finally:
            b.close()

    def test_oversized_frame_refused(self):
        a, b = socket.socketpair()
        try:
            a.sendall(struct.pack(
                ">4sBBIQ", wire.MAGIC, wire.KIND_MSG, 0, 16,
                wire.MAX_FRAME_BYTES + 1,
            ))
            with pytest.raises(ConnectionError):
                wire.recv_msg(b)
        finally:
            a.close()
            b.close()

    def test_typed_error_crosses_by_class(self):
        reply = wire.encode_error(ReplicaDraining("draining"))
        exc = wire.decode_error(reply)
        assert isinstance(exc, ReplicaDraining)
        assert isinstance(exc, TransientError)  # classification survives

    def test_unknown_error_class_is_permanent_remote_error(self):
        exc = wire.decode_error(
            {"ok": False, "error_class": "SomethingExotic", "error": "boom"}
        )
        assert isinstance(exc, RemoteReplicaError)
        assert "SomethingExotic" in str(exc)


# ----------------------------------------------------------------------
# router over in-process replica services
# ----------------------------------------------------------------------
def plain_service(counter=None):
    """A ReplicaService around a tiny compile=False ModelServer; if
    ``counter`` is given, the forward appends to it per call."""
    server = ModelServer(ServingConfig(
        max_batch=8, max_wait_ms=1.0, queue_capacity=64,
    ))

    def forward(x):
        batch = np.asarray(x)
        if counter is not None:
            counter.extend([1] * batch.shape[0])  # count items, not batches
        return batch * 2.0

    server.register("ep0", forward, item_shape=(4,), compile=False)
    return ReplicaService(server).start()


class TestRouter:
    def test_routes_and_returns_result(self):
        svc = plain_service()
        with Router() as router:
            router.add("r0", "127.0.0.1", svc.port)
            try:
                out = router.route(np.ones(4, np.float32), model_id="ep0")
                np.testing.assert_allclose(np.asarray(out), 2.0)
            finally:
                svc.close()

    def test_dead_replica_fails_over_to_survivor(self):
        served_b = []
        svc_a = plain_service()
        svc_b = plain_service(served_b)
        with Router() as router:
            router.add("a", "127.0.0.1", svc_a.port)
            router.add("b", "127.0.0.1", svc_b.port)
            # replica "a" dies while still registered: its port now
            # refuses connections, so every placement on it must retry
            svc_a.close()
            try:
                x = np.ones(4, np.float32)
                retries_before = metrics.counter("router.retries").value
                for _ in range(6):
                    out = router.route(x, model_id="ep0")
                    np.testing.assert_allclose(np.asarray(out), 2.0)
                # every request landed on the survivor, via retry
                assert len(served_b) >= 6
                assert metrics.counter(
                    "router.retries"
                ).value > retries_before
            finally:
                svc_b.close()

    def test_draining_replica_is_rerouted(self):
        served_b = []
        svc_a = plain_service()
        svc_b = plain_service(served_b)
        with Router() as router:
            router.add("a", "127.0.0.1", svc_a.port)
            router.add("b", "127.0.0.1", svc_b.port)
            try:
                with svc_a._lock:
                    svc_a._draining = True
                for _ in range(4):
                    out = router.route(np.ones(4, np.float32),
                                       model_id="ep0")
                    np.testing.assert_allclose(np.asarray(out), 2.0)
                assert len(served_b) >= 4
            finally:
                svc_a.close()
                svc_b.close()

    def test_no_live_replicas_is_typed(self):
        with Router() as router:
            with pytest.raises(NoLiveReplicas):
                router.route(np.ones(4, np.float32))

    def test_admission_limit_sheds_typed(self):
        svc = plain_service()
        with Router(max_inflight=0) as router:
            router.add("r0", "127.0.0.1", svc.port)
            try:
                with pytest.raises(ServerOverloaded):
                    router.route(np.ones(4, np.float32), model_id="ep0")
            finally:
                svc.close()

    def test_concurrent_load_spreads_over_replicas(self):
        served_a, served_b = [], []
        svc_a = plain_service(served_a)
        svc_b = plain_service(served_b)
        with Router() as router:
            router.add("a", "127.0.0.1", svc_a.port)
            router.add("b", "127.0.0.1", svc_b.port)
            try:
                x = np.ones(4, np.float32)
                errs = []

                def hammer():
                    for _ in range(25):
                        try:
                            router.route(x, model_id="ep0")
                        except Exception as exc:  # noqa: BLE001
                            errs.append(exc)

                threads = [
                    threading.Thread(target=hammer, daemon=True)
                    for _ in range(6)
                ]
                for t in threads:
                    t.start()
                for t in threads:
                    t.join(timeout=60)
                assert not errs
                # least-loaded placement must use both replicas under
                # concurrency
                assert len(served_a) > 0 and len(served_b) > 0
                assert len(served_a) + len(served_b) >= 150
            finally:
                svc_a.close()
                svc_b.close()

    def test_route_fault_site_fires(self):
        svc = plain_service()
        plan = inject.FaultPlan().add(
            "router.route", error="transient", at=1
        )
        with Router() as router:
            router.add("r0", "127.0.0.1", svc.port)
            try:
                with inject.active_plan(plan):
                    with pytest.raises(inject.InjectedTransientError):
                        router.route(np.ones(4, np.float32),
                                     model_id="ep0")
                    # next request is past the planned fault
                    out = router.route(np.ones(4, np.float32),
                                       model_id="ep0")
                np.testing.assert_allclose(np.asarray(out), 2.0)
            finally:
                svc.close()


# ----------------------------------------------------------------------
# replica spec
# ----------------------------------------------------------------------
class TestReplicaSpec:
    def test_json_roundtrip(self):
        spec = ReplicaSpec(
            factory="pkg.mod:make", warmup=False, port=7001,
            pythonpath=("/tmp/x",),
        )
        back = ReplicaSpec.from_json(spec.to_json())
        assert back == spec

    def test_from_env_requires_var(self, monkeypatch):
        monkeypatch.delenv("SPARKDL_REPLICA_SPEC", raising=False)
        with pytest.raises(RuntimeError):
            ReplicaSpec.from_env()

    def test_factory_must_be_module_colon_callable(self):
        with pytest.raises(ValueError):
            ReplicaSpec(factory="no_colon_here").build_server()


# ----------------------------------------------------------------------
# THE kill matrix (ISSUE-10 acceptance): FaultPlan kill at
# supervisor.replica_serve under sustained traffic
# ----------------------------------------------------------------------
class TestKillMatrix:
    # ragged slot-block dispatch defaults ON (ISSUE-20), so the two
    # lane cases already prove zero accepted loss through the ragged
    # path; the third case pins the SPARKDL_RAGGED=0 padded-ladder
    # fallback to the same contract
    @pytest.mark.parametrize("lane,ragged", [
        ("tcp", "1"), ("shm", "1"), ("shm", "0"),
    ])
    def test_replica_kill_under_load_loses_nothing(
        self, lane, ragged, monkeypatch
    ):
        monkeypatch.setenv("SPARKDL_WIRE_TRANSPORT", lane)
        monkeypatch.setenv("SPARKDL_RAGGED", ragged)
        sup = fast_supervisor(
            replicas=2,
            fault_plans={0: [{
                # slot 0 dies MID-REQUEST (os._exit) at its 150th
                # served request — the stranded request must fail over
                "site": "supervisor.replica_serve", "kill": True,
                "at": 150,
            }]},
        )
        results = []  # (t_rel, latency_s, error-or-None)
        stop = threading.Event()
        with sup:
            assert sup.wait_live(2, 120), sup.status()
            # the requested lane must actually be the one carrying
            # traffic (replicas advertise shm unless disabled)
            lanes = sup.status()["router"]["lanes"]
            assert set(lanes.values()) == {lane}, lanes
            start = time.monotonic()

            def generate():
                x = np.ones(64, np.float32)
                while not stop.is_set():
                    t0 = time.monotonic()
                    err = None
                    try:
                        sup.router.route(x, model_id="ep0",
                                         timeout_s=15.0)
                    except Exception as exc:  # noqa: BLE001
                        err = exc
                    results.append(
                        (t0 - start, time.monotonic() - t0, err)
                    )

            threads = [
                threading.Thread(target=generate, daemon=True)
                for _ in range(4)
            ]
            for t in threads:
                t.start()

            # watch for the kill and the recovery
            kill_t = recovery_t = None
            deadline = time.monotonic() + 90
            while time.monotonic() < deadline:
                status = sup.status()
                slot0 = next(
                    r for r in status["replicas"] if r["slot"] == 0
                )
                if kill_t is None and status["live"] < 2:
                    kill_t = time.monotonic() - start
                if slot0["generation"] >= 2 and status["live"] == 2:
                    recovery_t = time.monotonic() - start
                    break
                time.sleep(0.05)
            # keep traffic flowing on the recovered fleet
            time.sleep(1.5)
            stop.set()
            for t in threads:
                t.join(timeout=30)

        assert kill_t is not None, "planned kill never happened"
        assert recovery_t is not None, (
            f"slot 0 not restarted: {sup.status()}"
        )

        failures = [r for r in results if r[2] is not None]
        assert not failures, (
            "accepted requests were lost during the kill: "
            f"{[(type(e).__name__, str(e)) for _, _, e in failures[:5]]}"
        )
        assert len(results) > 300, "not enough sustained traffic"

        # p99 recovers to pre-kill levels within a bounded window: the
        # post-recovery tail must not be worse than 5x the pre-kill tail
        # (generous — CPU CI boxes jitter — but a replica that came back
        # cold or a router still timing out on the dead one blows it)
        pre = sorted(lat for t, lat, _ in results if t < kill_t)
        post = sorted(
            lat for t, lat, _ in results if t >= recovery_t + 0.5
        )
        assert pre and post
        pre_p99 = pre[min(len(pre) - 1, int(0.99 * len(pre)))]
        post_p99 = post[min(len(post) - 1, int(0.99 * len(post)))]
        assert post_p99 <= max(5 * pre_p99, 0.25), (
            f"p99 did not recover: pre={pre_p99:.4f}s "
            f"post={post_p99:.4f}s"
        )

        # shm lane hygiene: every segment this router created was
        # unlinked — a SIGKILLed replica must not leak /dev/shm entries
        from sparkdl_tpu.serving import transport as transport_mod

        assert transport_mod.active_segments() == []
        shm_dir = "/dev/shm"
        if os.path.isdir(shm_dir):
            mine = [f for f in os.listdir(shm_dir)
                    if f.startswith(f"sdw_{os.getpid()}_")]
            assert mine == [], f"leaked shm segments: {mine}"

    def test_shm_disabled_replica_falls_back_to_tcp(self, monkeypatch):
        """Transparent fallback, process-level: the operator asks for
        shm but replicas refuse (SPARKDL_WIRE_SHM_DISABLE) — traffic
        must flow over TCP with no caller-visible difference."""
        monkeypatch.setenv("SPARKDL_WIRE_TRANSPORT", "shm")
        monkeypatch.setenv("SPARKDL_WIRE_SHM_DISABLE", "1")
        fallback_before = metrics.counter("wire.shm.fallback").value
        sup = fast_supervisor(replicas=1)
        with sup:
            assert sup.wait_live(1, 120), sup.status()
            lanes = sup.status()["router"]["lanes"]
            assert set(lanes.values()) == {"tcp"}, lanes
            out = sup.router.route(
                np.ones(64, np.float32), model_id="ep0", timeout_s=15.0
            )
            assert np.asarray(out).shape == (64,)
        assert metrics.counter(
            "wire.shm.fallback"
        ).value > fallback_before


# ----------------------------------------------------------------------
# drain contract
# ----------------------------------------------------------------------
SLOW_FACTORY_SRC = '''
import time

import numpy as np

from sparkdl_tpu.serving.batcher import ServingConfig
from sparkdl_tpu.serving.server import ModelServer


def make():
    server = ModelServer(ServingConfig(
        max_batch=4, max_wait_ms=1.0, queue_capacity=32,
    ))

    def forward(x):
        time.sleep(1.0)
        return np.asarray(x) * 2.0

    server.register("slow", forward, item_shape=(4,), compile=False)
    return server
'''


def test_sigterm_drain_finishes_inflight(tmp_path):
    """Graceful stop: the in-flight request completes, the replica exits
    0 (clean drain), and the router stops placing new work there."""
    (tmp_path / "slow_replica_factory.py").write_text(SLOW_FACTORY_SRC)
    spec = ReplicaSpec(
        factory="slow_replica_factory:make",
        warmup=False,
        pythonpath=(str(tmp_path),),
    )
    sup = fast_supervisor(spec=spec, replicas=1)
    with sup:
        assert sup.wait_live(1, 120)
        outcome = {}

        def slow_request():
            try:
                outcome["result"] = np.asarray(sup.router.route(
                    np.ones(4, np.float32), model_id="slow",
                    timeout_s=30.0,
                ))
            except Exception as exc:  # noqa: BLE001
                outcome["error"] = exc

        t = threading.Thread(target=slow_request, daemon=True)
        t.start()
        time.sleep(0.4)  # let it reach the replica's 1s forward
        sup.stop_replica(0, graceful=True)  # blocks through the drain
        t.join(timeout=30)
        assert "error" not in outcome, outcome["error"]
        np.testing.assert_allclose(outcome["result"], 2.0)
        handle = sup.handles()[0]
        assert handle.state == "stopped"
        assert handle.last_exit == 0  # clean drain, not the timeout path
        with pytest.raises(NoLiveReplicas):
            sup.router.route(np.ones(4, np.float32), model_id="slow")


# ----------------------------------------------------------------------
# compile-cache-warm restart (the PR-5 graft)
# ----------------------------------------------------------------------
def test_restart_is_cache_warm(tmp_path, monkeypatch):
    """A killed replica's replacement warms every bucket from the
    persistent compile cache (source == 'disk'), not by recompiling."""
    monkeypatch.setenv("SPARKDL_COMPILE_CACHE", str(tmp_path / "cache"))
    sup = fast_supervisor(
        spec=ReplicaSpec(factory=COMPILE_FACTORY), replicas=1,
        spawn_timeout_s=300.0,
    )
    with sup:
        assert sup.wait_live(1, 300)
        handle = sup.handles()[0]
        first_sources = [
            info["source"]
            for per_model in handle.warmup["sources"].values()
            for info in per_model.values()
        ]
        assert first_sources, "first boot reported no warmup buckets"

        sup.kill_replica(0)
        assert wait_until(
            lambda: sup.handles()[0].generation >= 2
            and sup.live_count() == 1,
            timeout_s=300.0,
        ), sup.status()
        restarted_sources = [
            info["source"]
            for per_model in sup.handles()[0].warmup["sources"].values()
            for info in per_model.values()
        ]
        assert restarted_sources
        assert all(src == "disk" for src in restarted_sources), (
            f"restart recompiled instead of loading: {restarted_sources}"
        )


# ----------------------------------------------------------------------
# fault sites in the supervisor/replica processes
# ----------------------------------------------------------------------
def test_replica_warm_kill_restarts():
    """A kill at ``supervisor.replica_warm`` takes out the FIRST process
    of the slot during warmup; the supervisor backs off and the restart
    (no plan re-armed) comes up live."""
    sup = fast_supervisor(
        replicas=1,
        fault_plans={0: [{
            "site": "supervisor.replica_warm", "kill": True, "at": 1,
        }]},
    )
    with sup:
        assert sup.wait_live(1, 180), sup.status()
        handle = sup.handles()[0]
        assert handle.last_exit == 9  # the planned os._exit(9) happened
        assert handle.generation == 1  # first SUCCESSFUL spawn
        assert handle.state == "live"


def test_spawn_and_restart_fault_sites():
    """Injected faults at ``supervisor.spawn`` and then at
    ``supervisor.restart`` each count as a failed run; the loop keeps
    backing off until a clean spawn."""
    plan = (
        inject.FaultPlan()
        .add("supervisor.spawn", error="transient", at=1)
        .add("supervisor.restart", error="transient", at=1)
    )
    with inject.active_plan(plan):
        sup = fast_supervisor(replicas=1)
        with sup:
            assert sup.wait_live(1, 180), sup.status()
            # spawn #1 injected-failed; restart #1 injected-failed;
            # restart #2 -> spawn #2 succeeded
            assert plan.count("supervisor.spawn") >= 2
            assert plan.count("supervisor.restart") >= 2
            assert sup.handles()[0].attempt == 0  # reset on success


def test_health_probe_condemns_replica():
    """Consecutive failed ``supervisor.health`` probes (injected) kill
    and restart an otherwise-live replica — the gray-failure path."""
    sup = fast_supervisor(
        replicas=1, health_interval_s=0.2, health_failures=2,
    )
    with sup:
        assert sup.wait_live(1, 120)
        first_pid = sup.handles()[0].proc.pid
        plan = inject.FaultPlan().add(
            "supervisor.health", error="transient", at=1, times=2,
        )
        with inject.active_plan(plan):
            assert wait_until(
                lambda: sup.handles()[0].generation >= 2
                and sup.live_count() == 1,
                timeout_s=180.0,
            ), sup.status()
        assert sup.handles()[0].proc.pid != first_pid


def test_crash_loop_evicts_via_breaker():
    """A slot whose replica can never boot trips its CircuitBreaker and
    is evicted instead of burning spawn cycles forever."""
    spec = ReplicaSpec(
        factory="sparkdl_tpu.serving.replica:no_such_factory"
    )
    sup = fast_supervisor(spec=spec, replicas=1, breaker_threshold=2)
    with sup:
        assert wait_until(
            lambda: sup.handles()[0].state == "evicted",
            timeout_s=180.0,
        ), sup.status()
        status = sup.status()
        assert status["breakers"][0]["state"] == "open"
        assert not status["healthy"]


# ----------------------------------------------------------------------
# autoscaler control law (stub supervisor/engine — no processes)
# ----------------------------------------------------------------------
class _StubRouter:
    def __init__(self):
        self.limits = []

    def set_max_inflight(self, n):
        self.limits.append(n)


class _StubSupervisor:
    def __init__(self, live=1):
        self.router = _StubRouter()
        self.scaled = []
        self._live = live

    def live_count(self):
        return self._live

    def scale_to(self, n):
        self.scaled.append(n)
        self._live = n
        return n


class _StubEngine:
    def __init__(self):
        self.current = {}

    def states(self):
        return dict(self.current)


def make_autoscaler(**kw):
    sup = _StubSupervisor(live=kw.pop("live", 1))
    engine = _StubEngine()
    clock = {"t": 0.0}
    scaler = Autoscaler(
        sup, engine,
        min_replicas=1, max_replicas=4, interval_s=1.0,
        cooldown_s=10.0, step_up=1, ok_streak=3,
        per_replica_inflight=8, clock=lambda: clock["t"],
        **kw,
    )
    return scaler, sup, engine, clock


class TestAutoscaler:
    def test_page_scales_up_by_two_steps(self):
        scaler, sup, engine, _ = make_autoscaler()
        engine.current = {"router.latency": "page"}
        decision = scaler.evaluate_once()
        assert decision["moved"]
        assert scaler.target == 3
        assert sup.scaled == [3]
        # admission limit widened BEFORE the scale-up call
        assert sup.router.limits[-1] == 3 * 8

    def test_warning_scales_up_by_one(self):
        scaler, sup, engine, _ = make_autoscaler()
        engine.current = {"router.errors": "warning"}
        scaler.evaluate_once()
        assert scaler.target == 2

    def test_cooldown_blocks_consecutive_moves(self):
        scaler, sup, engine, clock = make_autoscaler()
        engine.current = {"router.latency": "page"}
        scaler.evaluate_once()
        clock["t"] = 5.0  # inside the 10s cooldown
        decision = scaler.evaluate_once()
        assert not decision["moved"] and decision["in_cooldown"]
        assert scaler.target == 3
        clock["t"] = 11.0  # past it
        assert scaler.evaluate_once()["moved"]
        assert scaler.target == 4  # clamped at max next time

    def test_clamped_at_max(self):
        scaler, _, engine, clock = make_autoscaler(live=4)
        engine.current = {"router.latency": "page"}
        decision = scaler.evaluate_once()
        assert not decision["moved"]
        assert scaler.target == 4

    def test_ok_streak_scales_down_one(self):
        scaler, sup, engine, clock = make_autoscaler(live=3)
        engine.current = {"router.latency": "ok"}
        for i in range(3):
            clock["t"] = float(i)
            decision = scaler.evaluate_once()
        assert decision["moved"]
        assert scaler.target == 2
        # scale-down narrows admission AFTER draining the replica
        assert sup.router.limits[-1] == 2 * 8
        # streak resets: the next two clean evals do not move again
        clock["t"] = 20.0
        assert not scaler.evaluate_once()["moved"]

    def test_floor_respected(self):
        scaler, _, engine, clock = make_autoscaler(live=1)
        engine.current = {}
        for i in range(10):
            clock["t"] = float(i * 20)
            scaler.evaluate_once()
        assert scaler.target == 1

    def test_env_knobs(self, monkeypatch):
        monkeypatch.setenv("SPARKDL_AUTOSCALE_MIN", "2")
        monkeypatch.setenv("SPARKDL_AUTOSCALE_MAX", "6")
        monkeypatch.setenv("SPARKDL_AUTOSCALE_INFLIGHT", "16")
        sup = _StubSupervisor(live=2)
        scaler = Autoscaler(sup, _StubEngine())
        assert scaler.min_replicas == 2
        assert scaler.max_replicas == 6
        assert scaler.per_replica_inflight == 16

    def test_rejects_inverted_bounds(self):
        with pytest.raises(ValueError):
            Autoscaler(
                _StubSupervisor(), _StubEngine(),
                min_replicas=5, max_replicas=2,
            )


# ----------------------------------------------------------------------
# known-sites registry
# ----------------------------------------------------------------------
def test_known_sites_registry_lists_replica_plane():
    sites = inject.known_sites()
    for site in (
        "supervisor.spawn", "supervisor.health", "supervisor.restart",
        "supervisor.replica_warm", "supervisor.replica_serve",
        "router.route",
    ):
        assert site in sites
    assert sites == tuple(sorted(sites))
