"""Continuous-batching decode plane tests (ISSUE-18).

Covers the slot pool (carry zeroing, mid-flight admission), the decode
endpoint (byte-identity between streamed and one-shot output, eos/
deadline/disconnect eviction, the ``decode.step`` / ``decode.stream``
fault sites), the wire streaming path on both lanes (gap-free
``KIND_STREAM`` frames, client-disconnect eviction), the router's
stream placement (backend pinning, retry only before the first token,
stitched ``decode.*`` spans), and the process-level kill matrix: a
``FaultPlan`` SIGKILL at ``decode.step`` mid-stream must surface as a
typed/connection-shaped error, never corrupt a completed stream, and
leave no slot state behind.
"""

import socket
import threading
import time

import numpy as np
import pytest

from sparkdl_tpu.engine.slots import SlotPool
from sparkdl_tpu.obs.export import JsonlTraceSink
from sparkdl_tpu.obs.trace import tracer
from sparkdl_tpu.resilience import inject
from sparkdl_tpu.resilience.errors import TransientError, is_transient
from sparkdl_tpu.serving import ModelServer, wire
from sparkdl_tpu.serving.decode import ClientGone, DecodeEndpoint
from sparkdl_tpu.serving.errors import (
    DeadlineExceeded,
    ServerClosed,
)
from sparkdl_tpu.serving.replica import ReplicaService
from sparkdl_tpu.serving.router import Router
from sparkdl_tpu.serving.transport import ShmTransport, TcpTransport
from sparkdl_tpu.utils.metrics import metrics


@pytest.fixture(autouse=True)
def clean_slate():
    tracer.disable()
    metrics.reset()
    yield
    tracer.disable()
    metrics.reset()


def counting_step(step_s: float = 0.0):
    """carry [acc, step] -> emit pre-step acc, add 1 to both — prompt
    summing to s streams s, s+1, s+2, ... (deterministically
    replayable, the byte-identity reference)."""

    def step_fn(carries):
        if step_s > 0.0:
            time.sleep(step_s)
        tokens = np.array(carries[:, 0], copy=True)
        return carries + np.asarray([1.0, 1.0], np.float32), tokens

    return step_fn


def sum_init(prompt):
    return np.asarray(
        [float(np.asarray(prompt, np.float64).sum()), 0.0], np.float32
    )


def make_endpoint(**kw):
    defaults = dict(
        max_steps=16, n_slots=4, compile=False, step_s=0.0,
    )
    defaults.update(kw)
    step_s = defaults.pop("step_s")
    return DecodeEndpoint(
        "dec", counting_step(step_s), sum_init, **defaults
    )


def expected_tokens(prompt_sum: float, steps: int):
    return [float(prompt_sum + i) for i in range(steps)]


# ----------------------------------------------------------------------
# slot pool
# ----------------------------------------------------------------------
class TestSlotPool:
    def test_acquire_binds_shape_release_zeroes(self):
        pool = SlotPool(3)
        s0 = pool.acquire("r0", np.asarray([5.0, 1.0], np.float32))
        assert s0 is not None and s0.index == 0
        assert pool.carry_shape == (2,)
        assert pool.n_free == 2 and pool.n_occupied == 1
        np.testing.assert_array_equal(
            pool.carries()[0], [5.0, 1.0]
        )
        pool.release(s0)
        # no state carryover: the freed row is zeroed, not stale
        np.testing.assert_array_equal(pool.carries()[0], [0.0, 0.0])
        assert pool.n_free == 3

    def test_mismatched_carry_shape_rejected(self):
        pool = SlotPool(2)
        pool.acquire("r0", np.zeros(2, np.float32))
        with pytest.raises(ValueError, match="one pool serves one"):
            pool.acquire("r1", np.zeros(3, np.float32))

    def test_release_all_returns_occupants(self):
        pool = SlotPool(2)
        pool.acquire("a", np.zeros(2, np.float32))
        pool.acquire("b", np.zeros(2, np.float32))
        evicted = pool.release_all()
        assert [s.request for s in evicted] == ["a", "b"]
        assert pool.n_occupied == 0
        np.testing.assert_array_equal(
            pool.carries(), np.zeros((2, 2), np.float32)
        )

    def test_freed_slot_is_reused_mid_flight(self):
        pool = SlotPool(2)
        a = pool.acquire("a", np.ones(2, np.float32))
        pool.acquire("b", np.ones(2, np.float32))
        assert pool.acquire("c", np.ones(2, np.float32)) is None
        pool.release(a)
        c = pool.acquire("c", np.full(2, 7.0, np.float32))
        assert c is not None and c.index == a.index
        np.testing.assert_array_equal(pool.carries()[c.index], 7.0)

    def test_mask_tracks_occupancy_by_index(self):
        pool = SlotPool(3)
        a = pool.acquire("a", np.zeros(2, np.float32))
        pool.acquire("b", np.zeros(2, np.float32))
        np.testing.assert_array_equal(
            pool.mask(), [True, True, False]
        )
        pool.release(a)
        np.testing.assert_array_equal(
            pool.mask(), [False, True, False]
        )
        assert pool.mask().dtype == bool


# ----------------------------------------------------------------------
# endpoint: streaming semantics
# ----------------------------------------------------------------------
class TestDecodeEndpoint:
    def test_stream_and_result_byte_identical(self):
        ep = make_endpoint()
        try:
            frames = []
            req = ep.submit([2.0, 1.0], emit=frames.append, max_steps=6)
            result = req.future.result(timeout=10)
            streamed = [f for f in frames if not f["final"]]
            final = [f for f in frames if f["final"]]
            # gap-free 0-based stream_seq, exactly one final frame
            assert [f["stream_seq"] for f in streamed] == list(range(6))
            assert len(final) == 1 and final[0]["stream_seq"] == 6
            np.testing.assert_array_equal(
                np.stack([f["result"] for f in streamed]), result
            )
            # the one-shot replay of the same prompt is byte-identical
            np.testing.assert_array_equal(
                ep.decode([2.0, 1.0], max_steps=6, timeout=10), result
            )
            assert result.tolist() == expected_tokens(3.0, 6)
        finally:
            ep.close()
        assert ep.slots.n_occupied == 0

    def test_eos_stops_stream_early(self):
        ep = DecodeEndpoint(
            "dec", counting_step(), sum_init, max_steps=50,
            eos_fn=lambda tok, step: float(tok) >= 4.0,
            n_slots=2, compile=False,
        )
        try:
            out = ep.decode([2.0], timeout=10)
            assert out.tolist() == [2.0, 3.0, 4.0]
        finally:
            ep.close()

    def test_max_steps_clamped_to_endpoint_cap(self):
        ep = make_endpoint(max_steps=4)
        try:
            out = ep.decode([1.0], max_steps=99, timeout=10)
            assert out.tolist() == expected_tokens(1.0, 4)
        finally:
            ep.close()

    def test_continuous_admission_short_not_stuck_behind_long(self):
        """THE acceptance property: with a long decode occupying one
        slot, a short request admitted later completes while the long
        one is still mid-flight — no barrier on the slowest sequence."""
        ep = make_endpoint(n_slots=2, max_steps=400, step_s=0.005)
        try:
            long_req = ep.submit([0.0], max_steps=400)
            short = ep.decode([1.0], max_steps=3, timeout=30)
            assert short.tolist() == expected_tokens(1.0, 3)
            assert not long_req.future.done(), (
                "short stream should finish while the long decode is "
                "still running"
            )
            long_req.cancelled.set()  # don't burn 400 steps of teardown
        finally:
            ep.close()

    def test_admission_into_freed_slot_mid_flight(self):
        """More queued streams than slots: the (n_slots+1)-th stream is
        admitted into a freed slot while others still decode."""
        ep = make_endpoint(n_slots=2, max_steps=64, step_s=0.002)
        try:
            reqs = [
                ep.submit([float(i)], max_steps=4 + 4 * i)
                for i in range(5)
            ]
            outs = [r.future.result(timeout=30) for r in reqs]
            for i, out in enumerate(outs):
                assert out.tolist() == expected_tokens(float(i), 4 + 4 * i)
        finally:
            ep.close()
        assert ep.slots.n_occupied == 0

    def test_deadline_expiry_mid_stream_evicts_typed(self):
        ep = make_endpoint(n_slots=1, max_steps=10_000, step_s=0.01)
        try:
            req = ep.submit([1.0], deadline_ms=60.0)
            with pytest.raises(DeadlineExceeded):
                req.future.result(timeout=30)
            deadline = time.monotonic() + 5
            while ep.slots.n_occupied and time.monotonic() < deadline:
                time.sleep(0.01)
            assert ep.slots.n_occupied == 0, "expired stream leaked its slot"
            # the endpoint still serves after the eviction
            assert ep.decode([2.0], max_steps=2, timeout=10).tolist() == [
                2.0, 3.0,
            ]
        finally:
            ep.close()

    def test_client_disconnect_evicts_slot(self):
        """emit returning False = client gone: the stream fails with
        ``ClientGone``, the slot frees immediately (no more device
        steps burned), and the pool keeps serving others."""
        ep = make_endpoint(n_slots=1, max_steps=1000, step_s=0.002)
        try:
            seen = []

            def flaky_emit(frame):
                seen.append(frame)
                return len(seen) < 3  # hang up after 3 frames

            req = ep.submit([5.0], emit=flaky_emit)
            with pytest.raises(ClientGone):
                req.future.result(timeout=30)
            assert metrics.counter("decode.evicted_disconnect").value == 1
            deadline = time.monotonic() + 5
            while ep.slots.n_occupied and time.monotonic() < deadline:
                time.sleep(0.01)
            assert ep.slots.n_occupied == 0
            assert metrics.gauge("decode.slots_occupied").value == 0
            # the freed slot serves the next stream with clean state
            assert ep.decode([9.0], max_steps=2, timeout=10).tolist() == [
                9.0, 10.0,
            ]
        finally:
            ep.close()

    def test_emit_raising_is_disconnect_too(self):
        ep = make_endpoint(n_slots=1, max_steps=100)
        try:
            def dead_emit(frame):
                raise ConnectionError("peer reset")

            req = ep.submit([1.0], emit=dead_emit)
            with pytest.raises(ClientGone):
                req.future.result(timeout=30)
        finally:
            ep.close()

    def test_cancel_before_admission_never_burns_a_slot(self):
        ep = make_endpoint(n_slots=1, max_steps=500, step_s=0.005)
        try:
            blocker = ep.submit([0.0], max_steps=500)
            victim = ep.submit([1.0], max_steps=500)
            victim.cancelled.set()  # client gone while still queued
            with pytest.raises(ClientGone):
                victim.future.result(timeout=30)
            blocker.cancelled.set()
        finally:
            ep.close()

    def test_failed_fused_step_fails_all_streams_typed(self):
        calls = {"n": 0}

        def exploding_step(carries):
            calls["n"] += 1
            if calls["n"] >= 3:
                raise TransientError("device poked")
            tokens = np.array(carries[:, 0], copy=True)
            return carries + 1.0, tokens

        ep = DecodeEndpoint(
            "dec", exploding_step, sum_init, max_steps=50,
            n_slots=4, compile=False,
        )
        try:
            reqs = [ep.submit([float(i)], max_steps=50) for i in range(3)]
            for req in reqs:
                with pytest.raises(TransientError):
                    req.future.result(timeout=30)
            assert ep.slots.n_occupied == 0
            assert metrics.counter("decode.errors").value == 3
        finally:
            ep.close()

    def test_drain_finishes_inflight_rejects_new(self):
        ep = make_endpoint(n_slots=2, max_steps=200, step_s=0.002)
        try:
            got_token = threading.Event()

            def emit(frame):
                got_token.set()
                return True

            req = ep.submit([1.0], max_steps=20, emit=emit)
            assert got_token.wait(timeout=10), "stream never admitted"
            assert ep.drain(timeout_s=30)
            assert req.future.result(timeout=1).tolist() == (
                expected_tokens(1.0, 20)
            )
            with pytest.raises(ServerClosed):
                ep.submit([2.0])
        finally:
            ep.close()

    def test_close_fails_queued_and_inflight(self):
        ep = make_endpoint(n_slots=1, max_steps=10_000, step_s=0.01)
        inflight = ep.submit([0.0])
        queued = ep.submit([1.0])
        ep.close()
        for req in (inflight, queued):
            with pytest.raises(ServerClosed):
                req.future.result(timeout=10)
        assert ep.slots.n_occupied == 0


# ----------------------------------------------------------------------
# fault sites (fault-site-coverage: decode.step / decode.stream)
# ----------------------------------------------------------------------
class TestDecodeFaultSites:
    def test_decode_step_fault_fails_stream_typed(self):
        plan = inject.FaultPlan().add(
            "decode.step", error="transient", at=2,
        )
        ep = make_endpoint(n_slots=2, max_steps=50)
        try:
            with inject.active_plan(plan):
                req = ep.submit([1.0], max_steps=50)
                with pytest.raises(TransientError):
                    req.future.result(timeout=30)
            assert plan.count("decode.step") >= 2
            assert ep.slots.n_occupied == 0
            # typed-transient by taxonomy: the router may re-place it
            exc = req.future.exception()
            assert is_transient(exc)
        finally:
            ep.close()

    def test_decode_stream_fault_evicts_as_disconnect(self):
        plan = inject.FaultPlan().add(
            "decode.stream", error="transient", at=3,
        )
        ep = make_endpoint(n_slots=1, max_steps=50)
        try:
            frames = []
            with inject.active_plan(plan):
                req = ep.submit(
                    [1.0], emit=frames.append, max_steps=50,
                )
                with pytest.raises(ClientGone):
                    req.future.result(timeout=30)
            # the frames delivered before the fault are intact
            assert [float(f["result"]) for f in frames] == [1.0, 2.0]
            assert ep.slots.n_occupied == 0
        finally:
            ep.close()


# ----------------------------------------------------------------------
# wire: KIND_STREAM over both lanes
# ----------------------------------------------------------------------
def decode_replica(n_slots=4, step_s=0.0):
    server = ModelServer()
    server.register_decode(
        "dec", counting_step(step_s), sum_init, max_steps=64,
        n_slots=n_slots, compile=False,
    )
    service = ReplicaService(server).start()
    return server, service


class TestDecodeWire:
    @pytest.mark.parametrize("transport_cls", [TcpTransport, ShmTransport])
    def test_stream_over_wire_matches_oneshot(self, transport_cls):
        server, service = decode_replica()
        t = transport_cls("127.0.0.1", service.port)
        try:
            frames = []
            final = t.stream(
                {"op": "decode", "model_id": "dec", "value": [2.0, 2.0],
                 "max_steps": 5},
                frames.append, timeout_s=30.0,
            )
            toks = [float(f["result"]) for f in frames]
            assert toks == expected_tokens(4.0, 5)
            assert [f["stream_seq"] for f in frames] == list(range(5))
            assert final["ok"] and final["final"]
            assert final["stream_seq"] == 5
            assert {"replica_queue", "decode"} <= set(final["phases"])
            # byte-identity against the in-process replay
            replay = server.decode([2.0, 2.0], max_steps=5)
            np.testing.assert_array_equal(np.asarray(toks), replay)
        finally:
            t.close()
            service.close()
            server.close()

    def test_typed_error_ends_stream_and_channel_survives(self):
        server, service = decode_replica()
        t = TcpTransport("127.0.0.1", service.port)
        try:
            with pytest.raises(Exception, match="no endpoint"):
                t.stream(
                    {"op": "decode", "model_id": "nope", "value": [1.0],
                     "max_steps": 2},
                    lambda f: None, timeout_s=30.0,
                )
            # the connection is still usable for the next stream
            final = t.stream(
                {"op": "decode", "model_id": "dec", "value": [1.0],
                 "max_steps": 2},
                lambda f: None, timeout_s=30.0,
            )
            assert final["ok"]
        finally:
            t.close()
            service.close()
            server.close()

    def test_expired_deadline_shed_before_decode(self):
        server, service = decode_replica()
        t = TcpTransport("127.0.0.1", service.port)
        try:
            with pytest.raises(DeadlineExceeded):
                t.stream(
                    {"op": "decode", "model_id": "dec", "value": [1.0],
                     "max_steps": 2, "deadline_ms": 0},
                    lambda f: None, timeout_s=30.0,
                )
            assert metrics.counter("replica.expired_shed").value == 1
        finally:
            t.close()
            service.close()
            server.close()

    def test_client_disconnect_over_wire_evicts_slot(self):
        """A raw client that hangs up mid-stream: the replica's next
        frame send fails, the slot evicts, and the pool serves the next
        stream — a gone client never wedges a device slot."""
        server, service = decode_replica(n_slots=1, step_s=0.005)
        try:
            sock = socket.create_connection(
                ("127.0.0.1", service.port), timeout=10,
            )
            wire.send_msg(sock, {
                "op": "decode", "model_id": "dec", "value": [1.0],
                "max_steps": 1000, "seq": 1,
            })
            kind, frame = wire.recv_any(sock)
            assert kind == wire.KIND_STREAM and not frame.get("final")
            sock.close()  # hang up mid-stream

            deadline = time.monotonic() + 15
            while (metrics.counter("decode.evicted_disconnect").value < 1
                   and time.monotonic() < deadline):
                time.sleep(0.02)
            assert metrics.counter("decode.evicted_disconnect").value == 1

            # the single slot is free again: a fresh stream completes
            t = TcpTransport("127.0.0.1", service.port)
            try:
                final = t.stream(
                    {"op": "decode", "model_id": "dec", "value": [3.0],
                     "max_steps": 3},
                    lambda f: None, timeout_s=30.0,
                )
                assert final["ok"] and final["stream_seq"] == 3
            finally:
                t.close()
            assert metrics.gauge("decode.slots_occupied").value == 0
        finally:
            service.close()
            server.close()


# ----------------------------------------------------------------------
# router: stream placement + stitched spans
# ----------------------------------------------------------------------
class TestDecodeRouter:
    def test_route_stream_end_to_end_with_stitched_spans(self):
        sink = JsonlTraceSink(capacity=4096)
        tracer.enable(sink)
        server, service = decode_replica()
        router = Router()
        router.add("r0", "127.0.0.1", service.port, lanes=("tcp",))
        try:
            frames = []
            reply = router.route_stream(
                [3.0], model_id="dec", on_frame=frames.append,
                max_steps=4,
            )
            assert reply["result"].tolist() == expected_tokens(3.0, 4)
            assert reply["steps"] == 4
            assert [float(f["result"]) for f in frames] == (
                expected_tokens(3.0, 4)
            )
            # one stitched trace: router.stream -> replica.serve ->
            # decode.request, with decode.steps groups alongside
            roots = sink.find("router.stream")
            assert len(roots) == 1
            trace_id = roots[0]["trace_id"]
            req_spans = []
            for name in ("replica.serve", "decode.request"):
                spans = [
                    s for s in sink.find(name)
                    if s["trace_id"] == trace_id
                ]
                assert spans, f"span {name} missing from stitched trace"
                req_spans.extend(spans)
            # the fused-step group spans live on the worker thread and
            # link back to the per-request spans via member_span_ids
            req_ids = {s["span_id"] for s in req_spans}
            linked = [
                s for s in sink.find("decode.steps")
                if req_ids & set(
                    s["attributes"].get("member_span_ids") or ()
                )
            ]
            assert linked, "no decode.steps group references this request"
        finally:
            router.close()
            service.close()
            server.close()

    def test_stream_retries_only_before_first_token(self):
        """A dead backend costs a retry, not a failure — but only
        because no frame was forwarded yet.  All streams land whole."""
        server, service = decode_replica()
        router = Router()
        router.add("dead", "127.0.0.1", 1, lanes=("tcp",))
        router.add("live", "127.0.0.1", service.port, lanes=("tcp",))
        try:
            for i in range(6):
                reply = router.route_stream(
                    [float(i)], model_id="dec", max_steps=3,
                )
                assert reply["result"].tolist() == (
                    expected_tokens(float(i), 3)
                )
        finally:
            router.close()
            service.close()
            server.close()

    def test_mid_stream_death_is_typed_never_spliced(self):
        """After the first forwarded frame, a dying backend must NOT be
        retried elsewhere (two half-streams can't be stitched): the
        caller gets the connection-shaped error itself."""

        class DiesAfterTwo:
            lane = "faulty"

            def stream(self, msg, on_frame, timeout_s):
                on_frame({"result": np.float32(1.0), "stream_seq": 0,
                          "final": False})
                on_frame({"result": np.float32(2.0), "stream_seq": 1,
                          "final": False})
                raise ConnectionError("replica died mid-stream")

            def request(self, msg, timeout_s):
                raise ConnectionError("one-shot not wired here")

            def close(self):
                pass

        router = Router()
        router.add("dying", "127.0.0.1", 1, transport=DiesAfterTwo())
        try:
            got = []
            with pytest.raises(ConnectionError, match="mid-stream"):
                router.route_stream(
                    [1.0], model_id="dec", on_frame=got.append,
                    max_steps=5,
                )
            assert len(got) == 2
            assert metrics.counter("router.retries").value == 0
        finally:
            router.close()

    def test_frontdoor_stream_restamps_client_seq(self):
        server, service = decode_replica()
        router = Router()
        router.add("r0", "127.0.0.1", service.port, lanes=("tcp",))
        port = router.serve()
        try:
            sock = socket.create_connection(("127.0.0.1", port), timeout=10)
            wire.send_msg(sock, {
                "op": "decode", "model_id": "dec", "value": [2.0],
                "max_steps": 3, "seq": 42,
            })
            toks, final = [], None
            while final is None:
                kind, frame = wire.recv_any(sock)
                assert kind == wire.KIND_STREAM
                assert frame["seq"] == 42
                if frame.get("final"):
                    final = frame
                else:
                    toks.append(float(frame["result"]))
            assert toks == expected_tokens(2.0, 3)
            assert final["ok"] and final["stream_seq"] == 3
            assert "frontdoor" in final["phases"]
            # one-shot ops still work on the same client connection
            wire.send_msg(sock, {"op": "ping"})
            assert wire.recv_msg(sock)["ok"]
            sock.close()
        finally:
            router.close()
            service.close()
            server.close()


# ----------------------------------------------------------------------
# kill matrix: SIGKILL mid-decode under mixed traffic
# ----------------------------------------------------------------------
DECODE_FACTORY = "sparkdl_tpu.serving.replica:demo_server_decode"


class TestDecodeKillMatrix:
    @pytest.mark.parametrize("lane", ["tcp", "shm"])
    def test_kill_mid_decode_typed_failure_no_corruption(
        self, lane, monkeypatch
    ):
        """``FaultPlan`` kill at ``decode.step`` takes slot 0 out in
        the middle of its fused step, with one-shot and streaming
        traffic interleaved.  Contract under fire:

        - one-shot traffic loses nothing (stranded requests fail over);
        - every stream that *returned* is byte-correct — tokens are
          exactly ``s, s+1, ...`` from its prompt sum, never a splice
          of two replicas;
        - interrupted streams fail TYPED (connection-shaped/transient),
          never silently truncated;
        - the supervisor restarts the slot and a burst of sequential
          post-recovery streams proves no slot leaked.
        """
        from sparkdl_tpu.serving.replica import ReplicaSpec
        from test_supervisor import fast_supervisor, wait_until

        monkeypatch.setenv("SPARKDL_WIRE_TRANSPORT", lane)
        monkeypatch.setenv("SPARKDL_DEMO_STEP_MS", "4")
        sup = fast_supervisor(
            replicas=2,
            spec=ReplicaSpec(factory=DECODE_FACTORY),
            fault_plans={0: [{
                "site": "decode.step", "kill": True, "at": 60,
            }]},
        )
        oneshot, streams = [], []  # (err, payload)
        stop = threading.Event()
        with sup:
            assert sup.wait_live(2, 120), sup.status()
            start = time.monotonic()

            def gen_oneshot():
                x = np.ones(64, np.float32)
                while not stop.is_set():
                    err = None
                    try:
                        sup.router.route(x, model_id="ep0",
                                         timeout_s=15.0)
                    except Exception as exc:  # noqa: BLE001
                        err = exc
                    oneshot.append(err)

            def gen_streams():
                i = 0
                while not stop.is_set():
                    i += 1
                    s = float(i % 7)
                    err = reply = None
                    try:
                        reply = sup.router.route_stream(
                            [s], model_id="dec0", max_steps=10,
                            timeout_s=20.0,
                        )
                    except Exception as exc:  # noqa: BLE001
                        err = exc
                    streams.append((s, err, reply))

            threads = [
                threading.Thread(target=gen_oneshot, daemon=True),
                threading.Thread(target=gen_streams, daemon=True),
                threading.Thread(target=gen_streams, daemon=True),
            ]
            for t in threads:
                t.start()

            # watch for the planned kill, then the restart
            saw_kill = wait_until(
                lambda: sup.status()["live"] < 2, timeout_s=90,
            )
            recovered = wait_until(
                lambda: (
                    sup.status()["live"] == 2
                    and next(
                        r for r in sup.status()["replicas"]
                        if r["slot"] == 0
                    )["generation"] >= 2
                ),
                timeout_s=90,
            )
            time.sleep(1.0)  # traffic on the recovered fleet
            stop.set()
            for t in threads:
                t.join(timeout=30)
            assert saw_kill, "planned decode.step kill never happened"
            assert recovered, f"slot 0 not restarted: {sup.status()}"

            # post-recovery sequential streams: a leaked slot in the
            # restarted pool (n_slots=8) would wedge this burst
            for i in range(16):
                reply = sup.router.route_stream(
                    [float(i)], model_id="dec0", max_steps=6,
                    timeout_s=30.0,
                )
                assert reply["result"].tolist() == (
                    expected_tokens(float(i), 6)
                )

        # one-shot plane: zero accepted loss (retry on the survivor)
        one_failures = [e for e in oneshot if e is not None]
        assert not one_failures, (
            f"one-shot requests lost: "
            f"{[type(e).__name__ for e in one_failures[:5]]}"
        )

        # stream plane: completed == byte-correct, failed == typed
        assert len(streams) > 20, "not enough stream traffic"
        completed = [(s, r) for s, e, r in streams if e is None]
        failed = [e for _, e, _ in streams if e is not None]
        assert completed, "no stream ever completed"
        for s, reply in completed:
            assert reply["result"].tolist() == expected_tokens(s, 10), (
                f"accepted stream corrupted for prompt sum {s}"
            )
        for exc in failed:
            assert (
                isinstance(exc, (ConnectionError, OSError, socket.timeout))
                or is_transient(exc)
            ), f"mid-kill stream failed untyped: {type(exc).__name__}: {exc}"

        # shm hygiene: a SIGKILLed replica must not leak segments
        from sparkdl_tpu.serving import transport as transport_mod

        assert transport_mod.active_segments() == []
