"""DataFrame engine tests (the Spark-substrate analog — SURVEY.md §7)."""

import numpy as np
import pytest

from sparkdl_tpu.sql import Row, TPUSession, col, lit, udf
from sparkdl_tpu.sql.functions import pandas_udf, struct


@pytest.fixture()
def df(tpu_session):
    data = [(i, f"name_{i}", float(i) * 1.5) for i in range(10)]
    return tpu_session.createDataFrame(data, ["id", "name", "score"])


def test_create_collect_count(df):
    assert df.count() == 10
    rows = df.collect()
    assert rows[0] == Row(id=0, name="name_0", score=0.0)
    assert rows[3].name == "name_3"
    assert rows[3]["score"] == 4.5
    assert df.columns == ["id", "name", "score"]


def test_partitioning(tpu_session):
    df = tpu_session.createDataFrame([(i,) for i in range(100)], ["x"], numPartitions=7)
    assert df.getNumPartitions() == 7
    assert df.count() == 100
    assert df.repartition(3).getNumPartitions() == 3
    assert sorted(r.x for r in df.repartition(3).collect()) == list(range(100))


def test_select_and_exprs(df):
    out = df.select("id", (col("score") * 2).alias("double_score"))
    rows = out.collect()
    assert out.columns == ["id", "double_score"]
    assert rows[2].double_score == 6.0


def test_with_column_and_udf(df):
    plus = udf(lambda a, b: a + b)
    out = df.withColumn("total", plus(col("id"), col("score")))
    assert out.collect()[4].total == 4 + 6.0
    # engine extension: plain callable rowwise
    out2 = df.withColumn("name_len", lambda s: len(s), "name")
    assert out2.collect()[0].name_len == 6


def test_vectorized_udf(df):
    doubler = pandas_udf(lambda xs: [x * 2 for x in xs])
    out = df.select(doubler(col("id")).alias("d"))
    assert [r.d for r in out.collect()] == [2 * i for i in range(10)]


def test_filter_where_limit(df):
    assert df.filter(col("id") >= 5).count() == 5
    assert df.where(lambda r: r.id % 2 == 0).count() == 5
    assert df.limit(3).count() == 3


def test_random_split(tpu_session):
    df = tpu_session.createDataFrame([(i,) for i in range(200)], ["x"])
    a, b = df.randomSplit([0.7, 0.3], seed=42)
    assert a.count() + b.count() == 200
    assert 100 < a.count() < 180


def test_map_partitions(df):
    def fn(part):
        return {"sum": [sum(part["id"])]}

    out = df.repartition(2).mapPartitions(fn)
    assert sum(r.sum for r in out.collect()) == sum(range(10))


def test_map_in_arrow(df):
    import pyarrow as pa

    def fn(batch):
        ids = batch.column(0)
        return pa.record_batch({"id2": pa.compute.multiply(ids, 2)})

    out = df.select("id").mapInArrow(fn)
    assert [r.id2 for r in out.collect()] == [2 * i for i in range(10)]


def test_struct_and_get_field(df):
    out = df.select(struct("id", "name").alias("s")).withColumn(
        "sid", col("s").getField("id")
    )
    assert out.collect()[7].sid == 7


def test_temp_view_and_sql(df, tpu_session):
    df.createOrReplaceTempView("people")
    tpu_session.udf.register("doubled", lambda x: x * 2)
    out = tpu_session.sql("SELECT doubled(score) AS ds, name FROM people WHERE id >= 8")
    rows = out.collect()
    assert len(rows) == 2
    assert rows[0].ds == 8 * 1.5 * 2
    out2 = tpu_session.sql("SELECT * FROM people LIMIT 4")
    assert out2.count() == 4 and out2.columns == ["id", "name", "score"]


def test_union_drop_rename(df):
    assert df.union(df).count() == 20
    assert df.drop("name").columns == ["id", "score"]
    assert df.withColumnRenamed("name", "label").columns == ["id", "label", "score"]


def test_numpy_column(tpu_session):
    arrs = [(i, np.full((3,), i, dtype=np.float32)) for i in range(6)]
    df = tpu_session.createDataFrame(arrs, ["i", "arr"])
    row = df.collect()[4]
    np.testing.assert_array_equal(row.arr, np.full((3,), 4, dtype=np.float32))


def test_to_pandas(df):
    pdf = df.toPandas()
    assert list(pdf.columns) == ["id", "name", "score"]
    assert len(pdf) == 10


def test_column_eq_returns_column_not_bool(df):
    """pyspark parity wart, pinned: Column.__eq__ builds an expression, so
    Columns are unhashable and `in` checks on Column lists are meaningless —
    use .alias()/_name comparisons instead."""
    c = col("id") == 3
    assert isinstance(c, type(col("id")))
    with pytest.raises(TypeError):
        hash(col("id"))


def test_schema_inference_skips_leading_nones(tpu_session):
    """Type inference probes for the first non-None value anywhere in the
    column (previously: first partition's first row only)."""
    from sparkdl_tpu.sql.types import infer_type

    df = tpu_session.createDataFrame(
        [(None,), (None,), (7,)], ["x"], numPartitions=2
    )
    out = df.select("x")
    want = type(infer_type(7))
    assert isinstance(out.schema["x"].dataType, want)

    out2 = df.withColumn("y", col("x") * 2)
    assert isinstance(out2.schema["y"].dataType, want)


class TestWherePredicates:
    """Compound WHERE parsing (AND/OR/NOT/IN/parens/IS NULL) — the subset of
    Catalyst's predicate surface the reference examples exercise."""

    @pytest.fixture()
    def view(self, tpu_session):
        data = [
            (i, f"name_{i}", float(i) * 1.5, i % 3 if i != 4 else None)
            for i in range(10)
        ]
        df = tpu_session.createDataFrame(
            data, ["id", "name", "score", "label"]
        )
        df.createOrReplaceTempView("preds")
        return tpu_session

    def _ids(self, session, where):
        out = session.sql(f"SELECT id FROM preds WHERE {where}")
        return sorted(r.id for r in out.collect())

    def test_and(self, view):
        assert self._ids(view, "id >= 3 AND id < 6") == [3, 4, 5]

    def test_or(self, view):
        assert self._ids(view, "id < 2 OR id > 8") == [0, 1, 9]

    def test_precedence_and_binds_tighter(self, view):
        # a OR b AND c  ==  a OR (b AND c)
        assert self._ids(view, "id = 9 OR id > 2 AND id < 5") == [3, 4, 9]

    def test_parens_override(self, view):
        assert self._ids(view, "(id = 9 OR id > 2) AND id < 5") == [3, 4]

    def test_in(self, view):
        assert self._ids(view, "id IN (1, 3, 5)") == [1, 3, 5]

    def test_in_strings(self, view):
        assert self._ids(view, "name IN ('name_2', 'name_7')") == [2, 7]

    def test_not_in(self, view):
        assert self._ids(view, "id NOT IN (0,1,2,3,4,5,6,7)") == [8, 9]

    def test_not(self, view):
        assert self._ids(view, "NOT id < 8") == [8, 9]

    def test_is_null(self, view):
        assert self._ids(view, "label IS NULL") == [4]
        assert self._ids(view, "label IS NOT NULL") == [
            0, 1, 2, 3, 5, 6, 7, 8, 9
        ]

    def test_verdict_example_shape(self, view):
        # the VERDICT r2 #8 acceptance query shape:
        #   SELECT udf(image) FROM t WHERE label IN (0,1) AND height > 100
        assert self._ids(view, "label IN (0, 1) AND score > 3") == [
            3, 6, 7, 9
        ]

    def test_float_and_negative_literals(self, view):
        assert self._ids(view, "score >= 10.5") == [7, 8, 9]
        assert self._ids(view, "id > -1 AND score < 1.0") == [0]

    def test_mixed_case_keywords(self, view):
        assert self._ids(view, "id in (1, 2) or id = 9") == [1, 2, 9]

    def test_isin_column_api(self, view):
        df = view.table("preds")
        out = df.filter(col("id").isin(2, 4, 6)).collect()
        assert sorted(r.id for r in out) == [2, 4, 6]
        out2 = df.filter(col("id").isin([7, 8])).collect()
        assert sorted(r.id for r in out2) == [7, 8]

    def test_unsupported_raises(self, view):
        with pytest.raises(ValueError):
            view.sql("SELECT id FROM preds WHERE id ~~ 3")
        with pytest.raises(ValueError):
            view.sql("SELECT id FROM preds WHERE id IN ()")
        with pytest.raises(ValueError):
            view.sql("SELECT id FROM preds WHERE (id = 1")

    def test_struct_field_reference(self, tpu_session):
        data = [
            (i, {"height": 10 * i, "width": 5}) for i in range(6)
        ]
        df = tpu_session.createDataFrame(data, ["id", "image"])
        df.createOrReplaceTempView("structs")
        out = tpu_session.sql(
            "SELECT id FROM structs WHERE image.height > 20 AND id IN (3, 4)"
        )
        assert sorted(r.id for r in out.collect()) == [3, 4]

    def test_null_three_valued_logic(self, tpu_session):
        """SQL 3VL (as in Spark/Catalyst): TRUE OR NULL = TRUE keeps the
        row; FALSE AND NULL = FALSE (not NULL)."""
        data = [(1, 0), (4, None), (9, 2)]
        df = tpu_session.createDataFrame(data, ["id", "lbl"])
        df.createOrReplaceTempView("nulls")
        out = tpu_session.sql("SELECT id FROM nulls WHERE lbl = 1 OR id = 4")
        assert sorted(r.id for r in out.collect()) == [4]
        # NULL AND TRUE = NULL; NOT NULL = NULL -> row 4 dropped (as Spark)
        out2 = tpu_session.sql(
            "SELECT id FROM nulls WHERE NOT (lbl = 1 AND id = 4)"
        )
        assert sorted(r.id for r in out2.collect()) == [1, 9]
        # NULL AND FALSE = FALSE; NOT FALSE = TRUE -> row 4 kept
        out3 = tpu_session.sql(
            "SELECT id FROM nulls WHERE NOT (lbl = 1 AND id = 5)"
        )
        assert sorted(r.id for r in out3.collect()) == [1, 4, 9]

    def test_leading_dot_float_literal(self, view):
        # regression: `score > .5` parsed before the tokenizer rewrite
        assert self._ids(view, "score > .5") == list(range(1, 10))


class TestGroupByAggregates:
    """GroupedData + the SQL GROUP BY / aggregate / ORDER BY surface."""

    @pytest.fixture()
    def gdf(self, tpu_session):
        data = [
            (i, i % 3, float(i), None if i == 4 else i * 2) for i in range(9)
        ]
        df = tpu_session.createDataFrame(
            data, ["id", "label", "score", "maybe"]
        )
        df.createOrReplaceTempView("agg_t")
        return df

    def test_grouped_data_api(self, gdf):
        out = gdf.groupBy("label").agg({"score": "avg", "*": "count"})
        rows = {r.label: r for r in out.collect()}
        assert rows[0]["count(*)"] == 3 and rows[0]["avg(score)"] == 3.0
        assert rows[1]["count(*)"] == 3 and rows[1]["avg(score)"] == 4.0

        counts = {r.label: r["count"] for r in gdf.groupBy("label").count().collect()}
        assert counts == {0: 3, 1: 3, 2: 3}

        sums = {r.label: r["sum(score)"] for r in gdf.groupBy("label").sum("score").collect()}
        assert sums == {0: 9.0, 1: 12.0, 2: 15.0}

    def test_null_excluded_from_aggregates(self, gdf):
        # id=4 (label 1) has maybe=None: COUNT(col) skips it, AVG ignores it
        out = {r.label: r for r in gdf.groupBy("label").agg(
            {"maybe": "count"}).collect()}
        assert out[1]["count(maybe)"] == 2
        avg = {r.label: r["avg(maybe)"] for r in gdf.groupBy("label").avg(
            "maybe").collect()}
        assert avg[1] == (1 * 2 + 7 * 2) / 2

    def test_sql_group_by(self, gdf, tpu_session):
        out = tpu_session.sql(
            "SELECT label, COUNT(*) AS n, AVG(score) AS m FROM agg_t "
            "WHERE id < 8 GROUP BY label ORDER BY label"
        ).collect()
        assert [r.label for r in out] == [0, 1, 2]
        assert [r.n for r in out] == [3, 3, 2]
        assert out[2].m == (2.0 + 5.0) / 2

    def test_sql_global_aggregate(self, gdf, tpu_session):
        (row,) = tpu_session.sql(
            "SELECT COUNT(*) AS n, MAX(score) AS mx FROM agg_t"
        ).collect()
        assert row.n == 9 and row.mx == 8.0

    def test_sql_order_by_desc_limit(self, gdf, tpu_session):
        out = tpu_session.sql(
            "SELECT id FROM agg_t ORDER BY id DESC LIMIT 3"
        ).collect()
        assert [r.id for r in out] == [8, 7, 6]

    def test_sql_rejects_bare_column_in_group_query(self, gdf, tpu_session):
        with pytest.raises(ValueError, match="GROUP BY key or an aggregate"):
            tpu_session.sql(
                "SELECT score, COUNT(*) FROM agg_t GROUP BY label"
            )

    def test_duplicate_aggregates_need_distinct_aliases(self, gdf, tpu_session):
        out = tpu_session.sql(
            "SELECT label, AVG(score) AS a, AVG(score) AS b FROM agg_t "
            "GROUP BY label ORDER BY label"
        ).collect()
        assert out[0].a == out[0].b == 3.0
        with pytest.raises(ValueError, match="duplicate output columns"):
            tpu_session.sql(
                "SELECT AVG(score), AVG(score) FROM agg_t GROUP BY label"
            )

    def test_global_aggregate_on_empty_view(self, tpu_session):
        df = tpu_session.createDataFrame([(1, 2.0)], ["id", "v"]).filter(
            lambda r: False
        )
        df.createOrReplaceTempView("empty_t")
        (row,) = tpu_session.sql(
            "SELECT COUNT(*) AS n, SUM(v) AS s FROM empty_t"
        ).collect()
        assert row.n == 0 and row.s is None

    def test_aggregate_unknown_column_raises(self, gdf, tpu_session):
        with pytest.raises(KeyError, match="nope"):
            tpu_session.sql("SELECT SUM(nope) FROM agg_t GROUP BY label")

    def test_order_by_non_projected_column(self, gdf, tpu_session):
        out = tpu_session.sql(
            "SELECT label FROM agg_t ORDER BY score DESC LIMIT 2"
        ).collect()
        assert [r.label for r in out] == [8 % 3, 7 % 3]
        with pytest.raises(ValueError, match="ORDER BY"):
            tpu_session.sql("SELECT label FROM agg_t ORDER BY nope")

    def test_scalar_udf_named_like_aggregate_wins_outside_group_by(
        self, gdf, tpu_session
    ):
        tpu_session.udf.register("min", lambda x: x * 10)
        try:
            out = tpu_session.sql(
                "SELECT min(score) AS m FROM agg_t LIMIT 3"
            )
            assert [r.m for r in out.collect()] == [0.0, 10.0, 20.0]
            # inside GROUP BY the call is ambiguous (SQL aggregate vs the
            # registered per-row UDF) — it used to silently resolve to the
            # aggregate; now it must refuse
            with pytest.raises(ValueError, match="ambiguous"):
                tpu_session.sql(
                    "SELECT MIN(score) AS m FROM agg_t "
                    "GROUP BY label ORDER BY m"
                )
        finally:
            del tpu_session.udf._udfs["min"]
        # with the UDF gone the aggregate resolves again
        out2 = tpu_session.sql(
            "SELECT MIN(score) AS m FROM agg_t GROUP BY label ORDER BY m"
        ).collect()
        assert [r.m for r in out2] == [0.0, 1.0, 2.0]

    def test_no_arg_sum_skips_non_numeric(self, tpu_session):
        df = tpu_session.createDataFrame(
            [(0, "a", 1.0), (0, "b", 2.0), (1, "c", 3.0)],
            ["k", "name", "v"],
        )
        out = {r.k: r["sum(v)"] for r in df.groupBy("k").sum().collect()}
        assert out == {0: 3.0, 1: 3.0}
        with pytest.raises(ValueError, match="sum\\(\\*\\) is not defined"):
            df.groupBy("k").agg({"*": "sum"})

    def test_having(self, gdf, tpu_session):
        out = tpu_session.sql(
            "SELECT label, COUNT(*) AS n, SUM(score) AS s FROM agg_t "
            "GROUP BY label HAVING s > 10 ORDER BY label"
        ).collect()
        assert [(r.label, r.s) for r in out] == [(1, 12.0), (2, 15.0)]
        with pytest.raises(ValueError, match="HAVING requires"):
            tpu_session.sql("SELECT id FROM agg_t HAVING id > 1")

    def test_having_on_non_projected_key_and_alias_hint(
        self, gdf, tpu_session
    ):
        # HAVING may reference a group key the projection drops
        out = tpu_session.sql(
            "SELECT SUM(score) AS s FROM agg_t GROUP BY label "
            "HAVING label > 0 ORDER BY s"
        ).collect()
        assert [r.s for r in out] == [12.0, 15.0]
        # direct aggregate calls in HAVING compute as hidden columns
        # (they used to require an AS alias)
        rows = tpu_session.sql(
            "SELECT label, COUNT(*) AS n FROM agg_t GROUP BY label "
            "HAVING count(*) > 1"
        ).collect()
        assert all(r.n > 1 for r in rows) and len(rows) >= 1
        assert rows and "__having_0" not in rows[0]._fields

    def test_having_unknown_column_gets_hint(self, gdf, tpu_session):
        with pytest.raises(ValueError, match="HAVING.*AS"):
            tpu_session.sql(
                "SELECT label, SUM(score) AS s FROM agg_t GROUP BY label "
                "HAVING cnt > 1"
            )


class TestJoins:
    """DataFrame.join + SQL JOIN...ON (the reference delegated joins to
    Spark SQL/Catalyst — SURVEY.md §1 L0, §3.3; semantics pinned here
    follow documented Spark behavior: USING-form key dedup with keys
    first, NULL keys never match, outer variants keep unmatched rows)."""

    @pytest.fixture()
    def preds(self, tpu_session):
        df = tpu_session.createDataFrame(
            [(1, 0.9, "cat"), (2, 0.4, "dog"), (3, 0.7, "cat"),
             (None, 0.5, "bird")],
            ["img_id", "score", "pred"],
        )
        df.createOrReplaceTempView("preds")
        return df

    @pytest.fixture()
    def labels(self, tpu_session):
        df = tpu_session.createDataFrame(
            [(1, "cat"), (2, "cat"), (4, "dog"), (None, "fish")],
            ["img_id", "truth"],
        )
        df.createOrReplaceTempView("labels")
        return df

    # -- DataFrame API ---------------------------------------------------
    def test_inner_join_dedupes_key_keys_first(self, preds, labels):
        out = preds.join(labels, on="img_id")
        assert out.columns == ["img_id", "score", "pred", "truth"]
        rows = sorted(out.collect(), key=lambda r: r.img_id)
        assert [(r.img_id, r.pred, r.truth) for r in rows] == [
            (1, "cat", "cat"), (2, "dog", "cat")
        ]

    def test_null_keys_never_match(self, preds, labels):
        # both sides have an img_id=None row; SQL equality on NULL is
        # not true, so no combined row may appear
        out = preds.join(labels, on="img_id")
        assert all(r.img_id is not None for r in out.collect())

    def test_left_outer_keeps_unmatched_and_null_keys(self, preds, labels):
        out = preds.join(labels, on="img_id", how="left")
        rows = out.collect()
        assert len(rows) == 4  # every preds row survives
        by_pred = {r.pred: r for r in rows}
        assert by_pred["cat"].truth in ("cat", None)  # img 1 or 3
        assert by_pred["bird"].img_id is None and by_pred["bird"].truth is None
        unmatched = [r for r in rows if r.truth is None]
        assert {r.score for r in unmatched} == {0.7, 0.5}

    def test_right_and_full_outer(self, preds, labels):
        right = preds.join(labels, on="img_id", how="right_outer")
        rrows = right.collect()
        assert len(rrows) == 4  # every labels row survives
        assert {r.truth for r in rrows} == {"cat", "dog", "fish"}
        # img_id=4 has no pred: left columns null, key from the right
        lbl4 = next(r for r in rrows if r.img_id == 4)
        assert lbl4.score is None and lbl4.pred is None

        full = preds.join(labels, on="img_id", how="outer")
        # 2 matches + 2 left-only (3, None) + 2 right-only (4, None)
        assert len(full.collect()) == 6

    def test_pair_keys_keep_both_columns(self, tpu_session, preds):
        meta = tpu_session.createDataFrame(
            [(1, "s3://a"), (3, "s3://b")], ["image", "origin"]
        )
        out = preds.join(meta, on=[("img_id", "image")])
        assert out.columns == ["img_id", "score", "pred", "image", "origin"]
        rows = sorted(out.collect(), key=lambda r: r.img_id)
        assert [(r.img_id, r.image, r.origin) for r in rows] == [
            (1, 1, "s3://a"), (3, 3, "s3://b")
        ]

    def test_duplicate_rows_multiply(self, tpu_session):
        a = tpu_session.createDataFrame([(1, "x"), (1, "y")], ["k", "a"])
        b = tpu_session.createDataFrame([(1, "p"), (1, "q")], ["k", "b"])
        out = a.join(b, on="k")
        assert len(out.collect()) == 4  # cross product within the key

    def test_join_errors(self, preds, labels, tpu_session):
        with pytest.raises(KeyError, match="join key 'nope'"):
            preds.join(labels, on="nope")
        with pytest.raises(ValueError, match="Unsupported join type"):
            preds.join(labels, on="img_id", how="sideways")
        # non-key name collision ('pred' vs a second 'pred') errors with
        # the offending names instead of silently shadowing
        dup = tpu_session.createDataFrame(
            [(1, "cat")], ["img_id", "pred"]
        )
        with pytest.raises(ValueError, match=r"duplicate column names \['pred'\]"):
            preds.join(dup, on="img_id")

    def test_join_partitioned_inputs(self, tpu_session):
        n = 100
        a = tpu_session.createDataFrame(
            [(i, i * 2) for i in range(n)], ["k", "a"], numPartitions=7
        )
        b = tpu_session.createDataFrame(
            [(i, i * 3) for i in range(0, n, 2)], ["k", "b"],
            numPartitions=3,
        )
        out = a.join(b, on="k")
        rows = sorted(out.collect(), key=lambda r: r.k)
        assert len(rows) == 50
        assert all(r.a == r.k * 2 and r.b == r.k * 3 for r in rows)
        assert out.getNumPartitions() == 7  # bucketed by the wider side

    # -- SQL dialect -----------------------------------------------------
    def test_sql_inner_join(self, preds, labels, tpu_session):
        out = tpu_session.sql(
            "SELECT img_id, pred, truth FROM preds "
            "JOIN labels ON preds.img_id = labels.img_id"
        )
        rows = sorted(out.collect(), key=lambda r: r.img_id)
        assert [(r.img_id, r.pred, r.truth) for r in rows] == [
            (1, "cat", "cat"), (2, "dog", "cat")
        ]

    def test_sql_left_join_with_where(self, preds, labels, tpu_session):
        out = tpu_session.sql(
            "SELECT img_id, score, truth FROM preds "
            "LEFT OUTER JOIN labels ON preds.img_id = labels.img_id "
            "WHERE truth IS NULL"
        )
        assert {r.score for r in out.collect()} == {0.7, 0.5}

    def test_sql_join_aliases(self, preds, labels, tpu_session):
        out = tpu_session.sql(
            "SELECT img_id, pred, truth FROM preds p "
            "JOIN labels l ON p.img_id = l.img_id"
        )
        assert len(out.collect()) == 2

    def test_sql_join_group_by(self, preds, labels, tpu_session):
        # accuracy-style analytics over the joined result
        out = tpu_session.sql(
            "SELECT truth, COUNT(*) AS n, AVG(score) AS mean_score "
            "FROM preds JOIN labels ON preds.img_id = labels.img_id "
            "GROUP BY truth HAVING n >= 1 ORDER BY truth"
        )
        rows = out.collect()
        assert [(r.truth, r.n) for r in rows] == [("cat", 2)]
        assert rows[0].mean_score == pytest.approx((0.9 + 0.4) / 2)

    def test_sql_three_table_chain(self, preds, labels, tpu_session):
        tpu_session.createDataFrame(
            [("cat", 1), ("dog", 2)], ["truth", "species_id"]
        ).createOrReplaceTempView("species")
        out = tpu_session.sql(
            "SELECT img_id, species_id FROM preds "
            "JOIN labels ON preds.img_id = labels.img_id "
            "JOIN species ON labels.truth = species.truth"
        )
        rows = sorted(out.collect(), key=lambda r: r.img_id)
        assert [(r.img_id, r.species_id) for r in rows] == [(1, 1), (2, 1)]

    def test_sql_self_join_with_aliases(self, preds, tpu_session):
        # aliases hide the table name (Spark semantics), so self-joins
        # with distinct aliases resolve; same-named NON-key columns
        # still collide by design (the engine's duplicate-name error),
        # so a same-table self-join keys on every shared column
        out = tpu_session.sql(
            "SELECT pred FROM preds a JOIN preds b ON a.img_id = b.img_id "
            "AND a.score = b.score AND a.pred = b.pred"
        )
        assert len(out.collect()) == 3  # 1, 2, 3 match themselves

    def test_mixed_on_list(self, tpu_session, preds):
        meta = tpu_session.createDataFrame(
            [(1, "cat", "s3://a")], ["image", "pred", "origin"]
        )
        out = preds.join(meta, on=["pred", ("img_id", "image")])
        rows = out.collect()
        assert out.columns == [
            "pred", "img_id", "score", "image", "origin"
        ]
        assert [(r.img_id, r.pred) for r in rows] == [(1, "cat")]
        with pytest.raises(ValueError, match="join key entry"):
            preds.join(meta, on=[("img_id", "image", "extra")])
        with pytest.raises(ValueError, match="Unsupported JOIN condition"):
            tpu_session.sql(
                "SELECT img_id FROM preds JOIN labels ON img_id = img_id"
            )
        with pytest.raises(ValueError, match="one side must reference"):
            tpu_session.sql(
                "SELECT img_id FROM preds "
                "JOIN labels ON mystery.img_id = labels.img_id"
            )
        with pytest.raises(ValueError, match="distinct aliases"):
            tpu_session.sql(
                "SELECT img_id FROM preds "
                "JOIN preds ON preds.img_id = preds.img_id"
            )

    def test_sql_without_join_still_parses(self, preds, tpu_session):
        # the FROM-alias and joins extensions must not disturb plain
        # queries (regression: alias regex could swallow WHERE)
        out = tpu_session.sql(
            "SELECT img_id FROM preds WHERE score > 0.5 ORDER BY img_id"
        )
        assert [r.img_id for r in out.collect()] == [1, 3]


class TestSqlExpressions:
    """Arithmetic projections/aggregate args, COUNT(DISTINCT),
    LIKE/BETWEEN (VERDICT r3 #5 — the reference had all of Spark SQL's
    expression surface; these are the reconstructed high-traffic parts)."""

    @pytest.fixture()
    def edf(self, tpu_session):
        df = tpu_session.createDataFrame(
            [("a.png", "s3", 0.2, 1), ("b.png", "s3", 0.4, 1),
             ("c.jpg", "web", 0.6, 2), ("d.jpg", "web", 0.8, 2),
             ("e.png", "web", None, 2), (None, "s3", 0.5, 3)],
            ["origin", "source", "score", "label"],
        )
        df.createOrReplaceTempView("expr_t")
        return df

    def test_arithmetic_projection(self, edf, tpu_session):
        out = tpu_session.sql(
            "SELECT origin, score * 100 AS pct, (score + 1) / 2 AS half "
            "FROM expr_t WHERE score IS NOT NULL"
        ).collect()
        assert out[0].pct == pytest.approx(20.0)
        assert out[0].half == pytest.approx(0.6)
        # NULL propagates through arithmetic
        all_rows = tpu_session.sql(
            "SELECT score * 100 AS pct FROM expr_t"
        ).collect()
        assert any(r.pct is None for r in all_rows)

    def test_default_expression_column_name(self, edf, tpu_session):
        out = tpu_session.sql("SELECT score * 100 FROM expr_t")
        assert out.columns == ["score * 100"]

    def test_arithmetic_in_where(self, edf, tpu_session):
        out = tpu_session.sql(
            "SELECT origin FROM expr_t WHERE score * 100 > 45"
        ).collect()
        assert {r.origin for r in out} == {"c.jpg", "d.jpg", None}

    def test_unary_minus_and_precedence(self, edf, tpu_session):
        out = tpu_session.sql(
            "SELECT origin FROM expr_t WHERE -score + 1 > 0.7"
        ).collect()  # 1 - score > 0.7 => score < 0.3
        assert {r.origin for r in out} == {"a.png"}
        rows = tpu_session.sql(
            "SELECT 2 + 3 * 4 AS v FROM expr_t LIMIT 1"
        ).collect()
        assert rows[0].v == 14  # * binds tighter than +

    def test_like(self, edf, tpu_session):
        out = tpu_session.sql(
            "SELECT origin FROM expr_t WHERE origin LIKE '%.png'"
        ).collect()
        assert {r.origin for r in out} == {"a.png", "b.png", "e.png"}
        # NULL LIKE -> NULL -> filtered out (3VL); NOT LIKE keeps jpgs
        out2 = tpu_session.sql(
            "SELECT origin FROM expr_t WHERE origin NOT LIKE '%.png'"
        ).collect()
        assert {r.origin for r in out2} == {"c.jpg", "d.jpg"}
        # _ matches exactly one character
        out3 = tpu_session.sql(
            "SELECT origin FROM expr_t WHERE origin LIKE '_.png'"
        ).collect()
        assert {r.origin for r in out3} == {"a.png", "b.png", "e.png"}

    def test_between(self, edf, tpu_session):
        out = tpu_session.sql(
            "SELECT origin FROM expr_t WHERE score BETWEEN 0.4 AND 0.6"
        ).collect()
        assert {r.origin for r in out} == {"b.png", "c.jpg", None}
        out2 = tpu_session.sql(
            "SELECT origin FROM expr_t "
            "WHERE score NOT BETWEEN 0.4 AND 0.6 AND score IS NOT NULL"
        ).collect()
        assert {r.origin for r in out2} == {"a.png", "d.jpg"}

    def test_count_distinct(self, edf, tpu_session):
        rows = tpu_session.sql(
            "SELECT label, COUNT(DISTINCT source) AS ns FROM expr_t "
            "GROUP BY label ORDER BY label"
        ).collect()
        assert [(r.label, r.ns) for r in rows] == [(1, 1), (2, 1), (3, 1)]
        total = tpu_session.sql(
            "SELECT COUNT(DISTINCT source) AS ns FROM expr_t"
        ).collect()
        assert total[0].ns == 2
        with pytest.raises(ValueError, match="DISTINCT is supported"):
            tpu_session.sql(
                "SELECT SUM(DISTINCT score) FROM expr_t GROUP BY label"
            )

    def test_aggregate_over_expression(self, edf, tpu_session):
        rows = tpu_session.sql(
            "SELECT label, AVG(score * 100) AS pct FROM expr_t "
            "WHERE score IS NOT NULL GROUP BY label ORDER BY label"
        ).collect()
        assert rows[0].pct == pytest.approx(30.0)  # (20+40)/2
        assert rows[1].pct == pytest.approx(70.0)  # (60+80)/2
        # derived argument columns never leak into the output
        assert not any(c.startswith("__agg_arg") for c in
                       tpu_session.sql(
                           "SELECT AVG(score * 100) AS pct FROM expr_t "
                           "GROUP BY label"
                       ).columns)

    def test_verdict_acceptance_query(self, edf, tpu_session):
        # the VERDICT r3 "done" shape: expression aggregate + HAVING with
        # a direct COUNT(DISTINCT ...) call
        rows = tpu_session.sql(
            "SELECT label, AVG(score * 100) AS pct FROM expr_t "
            "WHERE score IS NOT NULL "
            "GROUP BY label HAVING COUNT(DISTINCT origin) > 1 "
            "ORDER BY label"
        ).collect()
        assert [(r.label, round(r.pct, 6)) for r in rows] == [
            (1, 30.0), (2, 70.0)
        ]

    def test_udf_in_expression(self, edf, tpu_session):
        tpu_session.udf.register("twice", lambda v: None if v is None
                                 else v * 2)
        rows = tpu_session.sql(
            "SELECT twice(score) + 1 AS t FROM expr_t "
            "WHERE score IS NOT NULL ORDER BY t"
        ).collect()
        assert rows[0].t == pytest.approx(1.4)

    def test_aggregate_inside_expression_rejected(self, edf, tpu_session):
        with pytest.raises(ValueError, match="cannot appear inside"):
            tpu_session.sql("SELECT avg(score) + 1 FROM expr_t")


class TestSqlResolution:
    """Qualifier resolution, ORDER BY alias precedence, and parser
    robustness on malformed input."""

    @pytest.fixture()
    def views(self, tpu_session):
        tpu_session.createDataFrame(
            [(1, 0.9), (2, 0.4), (3, 0.7)], ["img_id", "score"]
        ).createOrReplaceTempView("t")
        tpu_session.createDataFrame(
            [(1, "cat"), (2, "dog")], ["img_id", "meta"]
        ).createOrReplaceTempView("m")
        return tpu_session

    def test_qualified_refs_after_join(self, views):
        # the natural Spark form: qualified columns in WHERE and the
        # projection resolve against the joined (flat) columns
        rows = views.sql(
            "SELECT t.score, m.meta FROM t JOIN m ON t.img_id = m.img_id "
            "WHERE t.score > 0.5"
        ).collect()
        assert [(r.score, r.meta) for r in rows] == [(0.9, "cat")]

    def test_qualified_refs_single_table(self, views):
        rows = views.sql(
            "SELECT t.img_id FROM t WHERE t.score >= 0.7 ORDER BY img_id"
        ).collect()
        assert [r.img_id for r in rows] == [1, 3]

    def test_order_by_alias_shadows_input_column(self, views):
        # SQL resolution: a select-list alias wins over a same-named
        # input column — sort by the NEGATED value here
        rows = views.sql(
            "SELECT img_id, -score AS score FROM t ORDER BY score"
        ).collect()
        assert [r.img_id for r in rows] == [1, 3, 2]  # -0.9 < -0.7 < -0.4

    def test_struct_column_named_like_view_keeps_field_access(
        self, tpu_session
    ):
        # a view named like one of its struct columns: column resolution
        # wins over the table qualifier, so image.height stays a
        # struct-field access (regression guard for the qualifier
        # feature)
        tpu_session.createDataFrame(
            [{"image": {"height": 120, "width": 60}, "label": 1},
             {"image": {"height": 40, "width": 20}, "label": 0}]
        ).createOrReplaceTempView("image")
        rows = tpu_session.sql(
            "SELECT label FROM image WHERE image.height > 100"
        ).collect()
        assert [r.label for r in rows] == [1]
        # same resolution inside aggregate arguments and HAVING
        agg = tpu_session.sql(
            "SELECT label, MAX(image.height) AS h FROM image "
            "GROUP BY label HAVING MAX(image.height) > 10 ORDER BY label"
        ).collect()
        assert [(r.label, r.h) for r in agg] == [(0, 40), (1, 120)]

    def test_malformed_join_query_fails_fast(self, views):
        import time

        bad = (
            "SELECT x FROM t "
            + "JOIN m ON t.img_id = m.img_id " * 24
            + "WHERE ??? BROKEN"
        )
        t0 = time.perf_counter()
        with pytest.raises((ValueError, KeyError)):
            views.sql(bad)
        assert time.perf_counter() - t0 < 1.0, "regex backtracking blowup"


class TestDistinctNaOrder:
    """distinct/dropDuplicates, df.na drop/fill, multi-key ORDER BY —
    the high-traffic pyspark surface around the serving-analytics flow."""

    @pytest.fixture()
    def ddf(self, tpu_session):
        return tpu_session.createDataFrame(
            [(1, "a", 0.5), (1, "a", 0.5), (2, "a", None),
             (2, "b", 0.7), (None, "b", 0.7)],
            ["k", "tag", "score"],
        )

    def test_distinct_and_drop_duplicates(self, ddf, tpu_session):
        assert ddf.distinct().count() == 4  # exact dup row collapses
        # subset form keeps the FIRST row per key
        firsts = ddf.dropDuplicates(["tag"]).collect()
        assert [(r.k, r.tag) for r in firsts] == [(1, "a"), (2, "b")]
        with pytest.raises(KeyError):
            ddf.dropDuplicates(["nope"])
        ddf.createOrReplaceTempView("ddup")
        rows = tpu_session.sql("SELECT DISTINCT tag FROM ddup").collect()
        assert sorted(r.tag for r in rows) == ["a", "b"]
        rows2 = tpu_session.sql(
            "SELECT DISTINCT k, tag FROM ddup WHERE k IS NOT NULL"
        ).collect()
        assert len(rows2) == 3

    def test_na_drop(self, ddf):
        assert ddf.na.drop().count() == 3  # rows with any null dropped
        assert ddf.dropna(how="all").count() == 5
        assert ddf.na.drop(subset=["score"]).count() == 4
        assert ddf.na.drop(thresh=3).count() == 3
        with pytest.raises(ValueError, match="how"):
            ddf.na.drop(how="some")

    def test_na_fill(self, ddf):
        # scalar fill touches only type-compatible columns (Spark rule)
        filled = ddf.na.fill(0.0)
        rows = filled.collect()
        assert all(r.score is not None for r in rows)
        assert any(r.k is None for r in rows) is False  # int col filled too
        # strings untouched by numeric fill
        strs = ddf.na.fill("x").collect()
        assert any(r.score is None for r in strs)  # floats untouched
        # dict form
        d = ddf.fillna({"score": -1.0}).collect()
        assert sorted(r.score for r in d)[0] == -1.0

    def test_multi_key_order_by(self, ddf, tpu_session):
        out = ddf.orderBy("tag", "score", ascending=[True, False])
        rows = out.collect()
        assert [(r.tag, r.score) for r in rows] == [
            ("a", 0.5), ("a", 0.5), ("a", None),  # desc: nulls last
            ("b", 0.7), ("b", 0.7),
        ]
        # SQL form with per-key direction
        ddf.createOrReplaceTempView("ord_t")
        got = tpu_session.sql(
            "SELECT k, tag, score FROM ord_t "
            "ORDER BY tag ASC, score DESC"
        ).collect()
        assert [(r.tag, r.score) for r in got] == [
            ("a", 0.5), ("a", 0.5), ("a", None),
            ("b", 0.7), ("b", 0.7),
        ]

    def test_order_by_null_ordering(self, tpu_session):
        df = tpu_session.createDataFrame(
            [(3,), (None,), (1,)], ["v"]
        )
        asc = [r.v for r in df.orderBy("v").collect()]
        assert asc == [None, 1, 3]  # Spark: NULLS FIRST ascending
        desc = [r.v for r in df.orderBy("v", ascending=False).collect()]
        assert desc == [3, 1, None]  # NULLS LAST descending

    def test_order_by_mixed_alias_and_hidden_input(self, tpu_session):
        tpu_session.createDataFrame(
            [(1, 0.5, "b"), (2, 0.5, "a"), (3, 0.9, "c")],
            ["k", "score", "tag"],
        ).createOrReplaceTempView("mix_t")
        # 'score' is an alias shadowing an input column (negated), 'tag'
        # is an unprojected input column — per-key resolution: alias
        # value sorts, tag rides along hidden and is dropped after
        rows = tpu_session.sql(
            "SELECT k, -score AS score FROM mix_t ORDER BY score, tag"
        ).collect()
        assert [r.k for r in rows] == [3, 2, 1]  # -0.9 < -0.5(a) < -0.5(b)
        assert rows and rows[0]._fields == ("k", "score")
        # alias-only multi-key still valid
        rows2 = tpu_session.sql(
            "SELECT score AS s, k FROM mix_t ORDER BY s, k"
        ).collect()
        assert [r.k for r in rows2] == [1, 2, 3]
        with pytest.raises(ValueError, match="select list"):
            tpu_session.sql(
                "SELECT DISTINCT k FROM mix_t ORDER BY k, tag"
            )

    def test_drop_duplicates_array_cells_full_content(self, tpu_session):
        # large arrays must fingerprint by content, not truncated repr
        a = np.zeros(2048, np.float32)
        b = np.zeros(2048, np.float32)
        b[500] = 1.0  # differs only in the repr-elided middle
        df = tpu_session.createDataFrame(
            [(1, a), (2, b), (3, a.copy())], ["k", "feat"]
        )
        out = df.distinct().collect()
        assert len(out) == 3  # k differs everywhere
        out2 = df.dropDuplicates(["feat"]).collect()
        assert [r.k for r in out2] == [1, 2]  # a == a.copy(), b distinct

    def test_na_fill_casts_to_column_type(self, tpu_session):
        df = tpu_session.createDataFrame(
            [(1, 1.5), (None, None)], ["i", "f"]
        )
        rows = df.na.fill(0.5).collect()
        filled_i = [r.i for r in rows if r.i is not None]
        assert 0 in filled_i and all(isinstance(v, int) for v in filled_i)
        assert any(r.f == 0.5 for r in rows)

    def test_distinct_order_by_unselected_always_rejected(self, tpu_session):
        tpu_session.createDataFrame(
            [(1, "a")], ["k", "tag"]
        ).createOrReplaceTempView("dgd_t")
        with pytest.raises(ValueError, match="select list"):
            tpu_session.sql("SELECT DISTINCT k FROM dgd_t ORDER BY tag")

    def test_na_fill_ignores_incompatible_columns(self, tpu_session):
        df = tpu_session.createDataFrame(
            [(1, None, None), (None, "y", 0.5)], ["i", "s", "f"]
        )
        # string fill into an int column via subset: ignored, not a crash
        rows = df.na.fill("unknown", subset=["i", "s"]).collect()
        assert any(r.i is None for r in rows)  # int column untouched
        assert all(r.s is not None for r in rows)
        # dict form likewise ignores the type mismatch
        rows2 = df.fillna({"i": "x", "f": 1}).collect()
        assert any(r.i is None for r in rows2)
        assert all(isinstance(r.f, float) for r in rows2 if r.f is not None)


class TestCaseCastBuiltins:
    """CASE WHEN / CAST / builtin scalar functions — the Spark SQL
    expression idioms serving analytics lean on (AVG(CASE WHEN ...) is
    the canonical accuracy query)."""

    @pytest.fixture()
    def cdf(self, tpu_session):
        tpu_session.createDataFrame(
            [("a.png", "cat", "cat", 0.91), ("b.png", "dog", "cat", 0.44),
             ("c.png", "cat", "cat", 0.67), ("d.png", None, "dog", None)],
            ["origin", "pred", "truth", "score"],
        ).createOrReplaceTempView("case_t")
        return tpu_session

    def test_case_when_projection(self, cdf):
        rows = cdf.sql(
            "SELECT origin, CASE WHEN pred = truth THEN 'hit' "
            "WHEN pred IS NULL THEN 'missing' ELSE 'miss' END AS outcome "
            "FROM case_t ORDER BY origin"
        ).collect()
        assert [r.outcome for r in rows] == [
            "hit", "miss", "hit", "missing"
        ]

    def test_accuracy_idiom(self, cdf):
        # the classic: per-class accuracy via AVG(CASE WHEN ...)
        rows = cdf.sql(
            "SELECT truth, AVG(CASE WHEN pred = truth THEN 1.0 "
            "ELSE 0.0 END) AS acc FROM case_t GROUP BY truth "
            "ORDER BY truth"
        ).collect()
        assert [(r.truth, round(r.acc, 4)) for r in rows] == [
            ("cat", round(2 / 3, 4)), ("dog", 0.0)
        ]

    def test_case_without_else_yields_null(self, cdf):
        rows = cdf.sql(
            "SELECT CASE WHEN score > 0.9 THEN 'high' END AS band "
            "FROM case_t"
        ).collect()
        assert sorted(str(r.band) for r in rows) == [
            "None", "None", "None", "high"
        ]

    def test_cast(self, cdf):
        rows = cdf.sql(
            "SELECT origin, CAST(score * 100 AS int) AS pct FROM case_t "
            "WHERE score IS NOT NULL ORDER BY origin"
        ).collect()
        assert [r.pct for r in rows] == [91, 44, 67]
        assert all(isinstance(r.pct, int) for r in rows)
        with pytest.raises(ValueError, match="CAST target"):
            cdf.sql("SELECT CAST(score AS blob) FROM case_t")

    def test_builtins(self, cdf):
        rows = cdf.sql(
            "SELECT UPPER(pred) AS up, LENGTH(origin) AS n, "
            "ROUND(score * 100) AS r, COALESCE(score, -1.0) AS s, "
            "ABS(-2) AS a FROM case_t ORDER BY origin"
        ).collect()
        assert rows[0].up == "CAT" and rows[0].n == 5
        assert rows[0].r == 91 and rows[0].a == 2
        # NULL propagation vs COALESCE
        assert rows[3].up is None and rows[3].s == -1.0
        with pytest.raises(KeyError, match="Undefined function"):
            cdf.sql("SELECT frobnicate(score) FROM case_t")
        # a registered UDF shadows a builtin of the same name
        cdf.udf.register("upper", lambda v: "udf!")
        got = cdf.sql("SELECT upper(pred) AS u FROM case_t LIMIT 1").collect()
        assert got[0].u == "udf!"

    def test_null_literal(self, cdf):
        rows = cdf.sql(
            "SELECT COALESCE(NULL, pred) AS p FROM case_t ORDER BY origin"
        ).collect()
        assert rows[0].p == "cat"

    def test_case_conditional_evaluation(self, tpu_session):
        # the SQL guarantee: guarded branches never evaluate on rows
        # their condition excludes (guard-then-divide must not crash)
        tpu_session.createDataFrame(
            [(100, 4), (50, 0), (30, 3)], ["total", "n"]
        ).createOrReplaceTempView("guard_t")
        rows = tpu_session.sql(
            "SELECT CASE WHEN n != 0 THEN total / n ELSE -1 END AS avg_v "
            "FROM guard_t"
        ).collect()
        assert [r.avg_v for r in rows] == [25.0, -1, 10.0]

    def test_cast_invalid_yields_null(self, tpu_session):
        tpu_session.createDataFrame(
            [("12",), ("x",), (None,), ("3.7",)], ["s"]
        ).createOrReplaceTempView("cast_t")
        rows = tpu_session.sql(
            "SELECT CAST(s AS int) AS i FROM cast_t"
        ).collect()
        assert [r.i for r in rows] == [12, None, None, 3]
        bools = tpu_session.sql(
            "SELECT CAST(s AS boolean) AS b FROM cast_t"
        ).collect()
        assert [b.b for b in bools] == [None, None, None, None]

    def test_round_half_up_and_null_digits(self, tpu_session):
        tpu_session.createDataFrame(
            [(2.5, 0), (3.5, 0), (2.345, 2), (1.0, None)],
            ["v", "d"],
        ).createOrReplaceTempView("round_t")
        rows = tpu_session.sql(
            "SELECT ROUND(v, d) AS r FROM round_t"
        ).collect()
        assert rows[0].r == 3 and rows[1].r == 4  # HALF_UP, not banker's
        assert rows[2].r == pytest.approx(2.35)
        assert rows[3].r is None  # NULL digits propagate

    def test_udf_precedence_case_insensitive(self, tpu_session):
        tpu_session.createDataFrame(
            [("a",)], ["k"]
        ).createOrReplaceTempView("ci_t")
        tpu_session.udf.register("upper", lambda v: "udf!")
        for spelling in ("upper", "UPPER", "Upper"):
            got = tpu_session.sql(
                f"SELECT {spelling}(k) AS u FROM ci_t"
            ).collect()
            assert got[0].u == "udf!", spelling


class TestAdviceR4Fixes:
    """Regression tests for the round-4 advisor findings (ADVICE.md)."""

    def test_divide_by_zero_yields_null(self, tpu_session):
        tpu_session.createDataFrame(
            [(10.0, 2.0), (5.0, 0.0), (None, 3.0)], ["a", "b"]
        ).createOrReplaceTempView("dz_t")
        rows = tpu_session.sql("SELECT a / b AS q FROM dz_t").collect()
        assert rows[0].q == 5.0
        assert rows[1].q is None  # Spark: x / 0 is NULL, not a crash
        assert rows[2].q is None

    def test_like_backslash_escapes(self, tpu_session):
        tpu_session.createDataFrame(
            [("100%",), ("100x",), ("a_b",), ("axb",)], ["s"]
        ).createOrReplaceTempView("lk_t")
        rows = tpu_session.sql(
            r"SELECT s FROM lk_t WHERE s LIKE '100\%'"
        ).collect()
        assert [r.s for r in rows] == ["100%"]
        rows = tpu_session.sql(
            r"SELECT s FROM lk_t WHERE s LIKE 'a\_b'"
        ).collect()
        assert [r.s for r in rows] == ["a_b"]
        # unescaped wildcards still behave
        assert tpu_session.sql(
            "SELECT s FROM lk_t WHERE s LIKE '100_'"
        ).count() == 2

    def test_udf_case_ambiguity_raises(self, tpu_session):
        tpu_session.udf.register("myFn", lambda v: 1)
        tpu_session.udf.register("MYFN", lambda v: 2)
        # exact spellings still resolve
        assert tpu_session.udf.resolve("myFn") is not None
        assert tpu_session.udf.resolve("MYFN") is not None
        with pytest.raises(KeyError, match="[Aa]mbiguous"):
            tpu_session.udf.resolve("myfn")

    def test_drop_duplicates_mixed_type_dict_keys(self, tpu_session):
        d1 = {1: "a", "x": "b"}  # int and str keys: bare sorted() raises
        d2 = {"x": "b", 1: "a"}  # same content, different insertion order
        d3 = {1: "a", "x": "c"}
        df = tpu_session.createDataFrame(
            [(1, d1), (2, d2), (3, d3)], ["id", "meta"]
        )
        out = df.dropDuplicates(["meta"])
        assert sorted(r.id for r in out.collect()) == [1, 3]

    def test_divide_by_zero_numpy_scalar_yields_null(self, tpu_session):
        a = np.float64(5.0)
        z = np.float64(0.0)
        tpu_session.createDataFrame(
            [(a, z), (a, np.float64(2.0))], ["x", "y"]
        ).createOrReplaceTempView("npz_t")
        rows = tpu_session.sql("SELECT x / y AS q FROM npz_t").collect()
        assert rows[0].q is None  # numpy would give inf, not raise
        assert rows[1].q == 2.5

    def test_udf_ambiguous_membership_keeps_bool_contract(self, tpu_session):
        tpu_session.udf.register("ambFn", lambda v: 1)
        tpu_session.udf.register("AMBFN", lambda v: 2)
        assert "ambfn" in tpu_session.udf  # no KeyError out of `in`

    def test_drop_duplicates_numeric_key_spellings(self, tpu_session):
        # {1: 'a', 2.0: 'b'} == {1: 'a', 2: 'b'} as Python dicts — one
        # fingerprint, one surviving row
        df = tpu_session.createDataFrame(
            [(1, {1: "a", 2.0: "b"}), (2, {1: "a", 2: "b"})], ["id", "meta"]
        )
        assert [r.id for r in df.dropDuplicates(["meta"]).collect()] == [1]


class _PoisonColumn(list):
    """A column whose DATA cannot be touched: any element access or
    iteration raises.  len() stays legal (partition row counts are
    metadata, not data)."""

    def __getitem__(self, i):
        raise AssertionError("poisoned column was materialized")

    def __iter__(self):
        raise AssertionError("poisoned column was iterated")


class TestAggregationPushdown:
    """Partial aggregation + projection pushdown (VERDICT r4 item 2)."""

    def test_group_by_never_touches_unreferenced_columns(self, tpu_session):
        df = tpu_session.createDataFrame(
            [(i % 3, float(i), b"imgbytes") for i in range(12)],
            ["label", "score", "image"],
            numPartitions=3,
        )
        for part in df._partitions:
            part["image"] = _PoisonColumn(part["image"])
        out = df.groupBy("label").agg({"score": "avg", "*": "count"})
        got = {r.label: (r["avg(score)"], r["count(*)"]) for r in out.collect()}
        assert got == {0: (4.5, 4), 1: (5.5, 4), 2: (6.5, 4)}

    def test_sql_group_by_never_touches_unreferenced_columns(
        self, tpu_session
    ):
        df = tpu_session.createDataFrame(
            [(i % 2, float(i), b"imgbytes") for i in range(8)],
            ["label", "score", "image"],
            numPartitions=2,
        )
        for part in df._partitions:
            part["image"] = _PoisonColumn(part["image"])
        df.createOrReplaceTempView("poisoned")
        rows = tpu_session.sql(
            "SELECT label, SUM(score) AS s FROM poisoned GROUP BY label"
        ).collect()
        assert {r.label: r.s for r in rows} == {0: 12.0, 1: 16.0}

    def test_partials_merge_across_partitions(self, tpu_session):
        # values deliberately split so no single partition sees the full
        # group; the merged result must equal the global aggregate
        vals = [float(v) for v in (5, 1, 9, 2, 8, 3, 7, 4, 6, 0)]
        df = tpu_session.createDataFrame(
            [(v,) for v in vals], ["x"], numPartitions=5
        )
        row = df.groupBy().agg(
            {"x": "avg"}
        ).collect()[0]
        assert row["avg(x)"] == pytest.approx(np.mean(vals))
        row = df.groupBy().agg({"x": "stddev"}).collect()[0]
        assert row["stddev(x)"] == pytest.approx(np.std(vals, ddof=1))

    def test_order_by_preserves_partitioning(self, tpu_session):
        df = tpu_session.createDataFrame(
            [(i * 7 % 10, i) for i in range(10)], ["k", "v"],
            numPartitions=4,
        )
        out = df.orderBy("k")
        assert out.getNumPartitions() == 4
        assert [r.k for r in out.collect()] == sorted(r.k for r in df.collect())
        # a downstream mapPartitions still sees 4 partitions of data
        seen = []
        out.foreachPartition(lambda p: seen.append(len(p["k"])))
        assert len(seen) == 4 and sum(seen) == 10


class TestNewAggregates:
    """stddev/variance/collect_* (VERDICT r4 item 6) + output typing
    (item 8)."""

    @pytest.fixture()
    def adf(self, tpu_session):
        data = [
            ("a", 1.0), ("a", 2.0), ("a", 4.0),
            ("b", 10.0), ("b", None),
        ]
        df = tpu_session.createDataFrame(data, ["k", "x"], numPartitions=3)
        df.createOrReplaceTempView("agg_t")
        return df

    def test_stddev_variance_vs_numpy(self, tpu_session, adf):
        a = np.array([1.0, 2.0, 4.0])
        rows = tpu_session.sql(
            "SELECT k, STDDEV(x) AS sd, VARIANCE(x) AS vr, "
            "STDDEV_POP(x) AS sdp, VAR_POP(x) AS vrp "
            "FROM agg_t GROUP BY k ORDER BY k"
        ).collect()
        ra = rows[0]
        assert ra.sd == pytest.approx(np.std(a, ddof=1))
        assert ra.vr == pytest.approx(np.var(a, ddof=1))
        assert ra.sdp == pytest.approx(np.std(a))
        assert ra.vrp == pytest.approx(np.var(a))
        rb = rows[1]  # single non-null value: sample estimator is NaN
        assert np.isnan(rb.sd) and np.isnan(rb.vr)
        assert rb.sdp == 0.0 and rb.vrp == 0.0

    def test_stddev_of_no_rows_is_null(self, tpu_session):
        tpu_session.createDataFrame(
            [(1.0,)], ["x"]
        ).createOrReplaceTempView("empty_src")
        row = tpu_session.sql(
            "SELECT STDDEV(x) AS sd FROM empty_src WHERE x > 99"
        ).collect()[0]
        assert row.sd is None

    def test_collect_list_and_set(self, tpu_session, adf):
        rows = tpu_session.sql(
            "SELECT k, COLLECT_LIST(x) AS xs FROM agg_t GROUP BY k "
            "ORDER BY k"
        ).collect()
        assert rows[0].xs == [1.0, 2.0, 4.0]
        assert rows[1].xs == [10.0]  # NULL excluded, as Spark
        df2 = tpu_session.createDataFrame(
            [("a", 1), ("a", 1), ("a", 2)], ["k", "v"]
        )
        out = df2.groupBy("k").agg({"v": "collect_set"})
        assert sorted(out.collect()[0]["collect_set(v)"]) == [1, 2]

    def test_collect_list_schema_is_array(self, tpu_session, adf):
        from sparkdl_tpu.sql.types import ArrayType, DoubleType

        out = adf.groupBy("k").agg({"x": "collect_list"})
        assert out.schema["collect_list(x)"].dataType == ArrayType(DoubleType())

    def test_aggregate_schema_from_declared_types(self, tpu_session):
        from sparkdl_tpu.sql.types import (
            DoubleType, LongType, StringType,
        )

        df = tpu_session.createDataFrame(
            [("a", 2, 1.5, "s")], ["k", "i", "f", "s"]
        )
        out = df.groupBy("k").agg(
            {"i": "sum", "f": "avg", "s": "min", "*": "count"}
        )
        assert out.schema["k"].dataType == StringType()
        assert out.schema["sum(i)"].dataType == LongType()
        assert out.schema["avg(f)"].dataType == DoubleType()
        assert out.schema["min(s)"].dataType == StringType()
        assert out.schema["count(*)"].dataType == LongType()

    def test_all_null_aggregate_column_keeps_type_and_fills(
        self, tpu_session
    ):
        from sparkdl_tpu.sql.types import DoubleType

        # a full-outer join whose right side never matches: every
        # right-origin value is NULL, but the declared type must survive
        # aggregation so fillna(0) still applies (VERDICT r4 weak #4)
        left = tpu_session.createDataFrame(
            [("a", 1.0), ("b", 2.0)], ["k", "x"]
        )
        right = tpu_session.createDataFrame(
            [("z", 9.5)], ["k", "y"]
        )
        joined = left.join(right, "k", how="full")
        agg = joined.groupBy("k").agg({"y": "max"})
        f = agg.schema["max(y)"]
        assert f.dataType == DoubleType()
        filled = agg.na.fill(0.0)
        vals = {r.k: r["max(y)"] for r in filled.collect()}
        assert vals["a"] == 0.0 and vals["b"] == 0.0 and vals["z"] == 9.5


class TestWindowFunctions:
    """ROW_NUMBER/RANK/DENSE_RANK OVER (VERDICT r4 item 1)."""

    @pytest.fixture()
    def scored(self, tpu_session):
        tpu_session.createDataFrame(
            [
                ("cat", "a.png", 0.9), ("cat", "b.png", 0.7),
                ("cat", "c.png", 0.9), ("dog", "d.png", 0.6),
                ("dog", "e.png", 0.95), ("dog", "f.png", 0.6),
            ],
            ["label", "origin", "score"], numPartitions=3,
        ).createOrReplaceTempView("win_t")

    def test_row_number_partitioned_desc(self, tpu_session, scored):
        rows = tpu_session.sql(
            "SELECT origin, ROW_NUMBER() OVER "
            "(PARTITION BY label ORDER BY score DESC) AS rn FROM win_t"
        ).collect()
        got = {r.origin: r.rn for r in rows}
        # ties broken by input order (deterministic): a before c
        assert got == {
            "a.png": 1, "c.png": 2, "b.png": 3,
            "e.png": 1, "d.png": 2, "f.png": 3,
        }

    def test_rank_vs_dense_rank_ties(self, tpu_session, scored):
        rows = tpu_session.sql(
            "SELECT origin, RANK() OVER (PARTITION BY label ORDER BY "
            "score DESC) AS rk, DENSE_RANK() OVER (PARTITION BY label "
            "ORDER BY score DESC) AS dr FROM win_t"
        ).collect()
        got = {r.origin: (r.rk, r.dr) for r in rows}
        assert got["a.png"] == (1, 1) and got["c.png"] == (1, 1)
        assert got["b.png"] == (3, 2)  # RANK gaps, DENSE_RANK doesn't
        assert got["d.png"] == (2, 2) and got["f.png"] == (2, 2)
        assert got["e.png"] == (1, 1)

    def test_window_no_partition(self, tpu_session, scored):
        rows = tpu_session.sql(
            "SELECT origin, ROW_NUMBER() OVER (ORDER BY score) AS rn "
            "FROM win_t WHERE label = 'dog'"
        ).collect()
        assert {r.origin: r.rn for r in rows} == {
            "d.png": 1, "f.png": 2, "e.png": 3,
        }

    def test_window_with_where_and_limit(self, tpu_session, scored):
        rows = tpu_session.sql(
            "SELECT origin, ROW_NUMBER() OVER (ORDER BY score DESC) AS rn "
            "FROM win_t WHERE label = 'cat' ORDER BY rn LIMIT 2"
        ).collect()
        # WHERE narrows BEFORE the window numbers rows (SQL order)
        assert [(r.origin, r.rn) for r in rows] == [
            ("a.png", 1), ("c.png", 2),
        ]

    def test_star_plus_window(self, tpu_session, scored):
        out = tpu_session.sql(
            "SELECT *, RANK() OVER (ORDER BY score DESC) AS rk FROM win_t"
        )
        assert out.columns == ["label", "origin", "score", "rk"]
        assert out.count() == 6

    def test_window_preserves_partitioning(self, tpu_session, scored):
        out = tpu_session.sql(
            "SELECT *, ROW_NUMBER() OVER (PARTITION BY label ORDER BY "
            "score) AS rn FROM win_t"
        )
        assert out.getNumPartitions() == 3

    def test_windowed_subquery_topk_per_label(self, tpu_session, scored):
        rows = tpu_session.sql(
            "SELECT label, origin FROM (SELECT label, origin, "
            "ROW_NUMBER() OVER (PARTITION BY label ORDER BY score DESC) "
            "AS rn FROM win_t) t WHERE t.rn <= 2 ORDER BY label, origin"
        ).collect()
        assert [(r.label, r.origin) for r in rows] == [
            ("cat", "a.png"), ("cat", "c.png"),
            ("dog", "d.png"), ("dog", "e.png"),
        ]

    def test_unsupported_window_fn_errors(self, tpu_session, scored):
        with pytest.raises(ValueError, match="window"):
            tpu_session.sql(
                "SELECT NTH_VALUE(score, 2) OVER (PARTITION BY label "
                "ORDER BY score) FROM win_t"
            )

    def test_window_with_group_by_errors(self, tpu_session, scored):
        with pytest.raises(ValueError, match="derived table"):
            tpu_session.sql(
                "SELECT label, ROW_NUMBER() OVER (ORDER BY label) "
                "FROM win_t GROUP BY label"
            )


class TestSubqueries:
    """Derived tables + uncorrelated IN (VERDICT r4 item 3)."""

    @pytest.fixture()
    def views(self, tpu_session):
        tpu_session.createDataFrame(
            [("a.png", "cat", 0.9), ("b.png", "dog", 0.4),
             ("c.png", "cat", 0.7), ("d.png", "owl", 0.5)],
            ["origin", "label", "score"],
        ).createOrReplaceTempView("sq_scored")
        tpu_session.createDataFrame(
            [("cat",), ("dog",)], ["label"]
        ).createOrReplaceTempView("sq_known")

    def test_derived_table(self, tpu_session, views):
        rows = tpu_session.sql(
            "SELECT origin FROM (SELECT origin, score FROM sq_scored "
            "WHERE score > 0.5) t ORDER BY origin"
        ).collect()
        assert [r.origin for r in rows] == ["a.png", "c.png"]

    def test_derived_table_aliased_and_qualified(self, tpu_session, views):
        rows = tpu_session.sql(
            "SELECT t.origin FROM (SELECT * FROM sq_scored) t "
            "WHERE t.label = 'cat' ORDER BY t.origin"
        ).collect()
        assert [r.origin for r in rows] == ["a.png", "c.png"]

    def test_join_against_derived_table(self, tpu_session, views):
        rows = tpu_session.sql(
            "SELECT s.origin, m.cnt FROM sq_scored s JOIN "
            "(SELECT label AS lbl, COUNT(*) AS cnt FROM sq_scored "
            "GROUP BY label) m ON s.label = m.lbl ORDER BY s.origin"
        ).collect()
        assert [(r.origin, r.cnt) for r in rows] == [
            ("a.png", 2), ("b.png", 1), ("c.png", 2), ("d.png", 1),
        ]

    def test_nested_derived_tables(self, tpu_session, views):
        assert tpu_session.sql(
            "SELECT origin FROM (SELECT origin FROM (SELECT * FROM "
            "sq_scored WHERE score > 0.4) a WHERE a.label = 'cat') b"
        ).count() == 2

    def test_in_subquery(self, tpu_session, views):
        rows = tpu_session.sql(
            "SELECT origin FROM sq_scored WHERE label IN "
            "(SELECT label FROM sq_known) ORDER BY origin"
        ).collect()
        assert [r.origin for r in rows] == ["a.png", "b.png", "c.png"]

    def test_not_in_subquery(self, tpu_session, views):
        rows = tpu_session.sql(
            "SELECT origin FROM sq_scored WHERE label NOT IN "
            "(SELECT label FROM sq_known)"
        ).collect()
        assert [r.origin for r in rows] == ["d.png"]

    def test_not_in_subquery_with_null_matches_nothing(
        self, tpu_session, views
    ):
        # the classic SQL trap: NOT IN against a set containing NULL is
        # never TRUE (x != NULL is unknown) — Spark returns zero rows
        tpu_session.createDataFrame(
            [("cat",), (None,)], ["label"]
        ).createOrReplaceTempView("sq_nullset")
        assert tpu_session.sql(
            "SELECT origin FROM sq_scored WHERE label NOT IN "
            "(SELECT label FROM sq_nullset)"
        ).count() == 0

    def test_in_subquery_with_null_keeps_matches(self, tpu_session, views):
        tpu_session.createDataFrame(
            [("cat",), (None,)], ["label"]
        ).createOrReplaceTempView("sq_nullset2")
        rows = tpu_session.sql(
            "SELECT origin FROM sq_scored WHERE label IN "
            "(SELECT label FROM sq_nullset2) ORDER BY origin"
        ).collect()
        assert [r.origin for r in rows] == ["a.png", "c.png"]

    def test_in_subquery_requires_single_column(self, tpu_session, views):
        with pytest.raises(ValueError, match="one column"):
            tpu_session.sql(
                "SELECT origin FROM sq_scored WHERE label IN "
                "(SELECT origin, label FROM sq_scored)"
            )

    def test_temp_subquery_views_are_cleaned_up(self, tpu_session, views):
        before = set(tpu_session.catalog.listTables())
        tpu_session.sql(
            "SELECT * FROM (SELECT * FROM sq_scored) t LIMIT 1"
        ).collect()
        assert set(tpu_session.catalog.listTables()) == before


class TestUnion:
    """UNION [ALL] in the dialect (VERDICT r4 item 6)."""

    @pytest.fixture()
    def views(self, tpu_session):
        tpu_session.createDataFrame(
            [("cat", 1), ("dog", 2)], ["label", "n"]
        ).createOrReplaceTempView("u_a")
        tpu_session.createDataFrame(
            [("cat", 1), ("owl", 3)], ["label", "n"]
        ).createOrReplaceTempView("u_b")

    def test_union_dedupes_union_all_keeps(self, tpu_session, views):
        assert tpu_session.sql(
            "SELECT label, n FROM u_a UNION SELECT label, n FROM u_b"
        ).count() == 3
        assert tpu_session.sql(
            "SELECT label, n FROM u_a UNION ALL SELECT label, n FROM u_b"
        ).count() == 4

    def test_union_positional_names_from_first_branch(
        self, tpu_session, views
    ):
        out = tpu_session.sql(
            "SELECT label AS l, n AS k FROM u_a UNION ALL "
            "SELECT n, label FROM u_b"
        )
        assert out.columns == ["l", "k"]
        assert out.count() == 4

    def test_union_tail_order_and_limit_close_the_union(
        self, tpu_session, views
    ):
        rows = tpu_session.sql(
            "SELECT label FROM u_a UNION ALL SELECT label FROM u_b "
            "ORDER BY label DESC LIMIT 2"
        ).collect()
        assert [r.label for r in rows] == ["owl", "dog"]

    def test_union_count_mismatch_errors(self, tpu_session, views):
        with pytest.raises(ValueError, match="column count"):
            tpu_session.sql(
                "SELECT label, n FROM u_a UNION SELECT label FROM u_b"
            )

    def test_three_way_mixed_union(self, tpu_session, views):
        # left-associative: (a UNION a) has 2 rows, then UNION ALL b
        assert tpu_session.sql(
            "SELECT label FROM u_a UNION SELECT label FROM u_a "
            "UNION ALL SELECT label FROM u_b"
        ).count() == 4

    def test_union_inside_derived_table(self, tpu_session, views):
        rows = tpu_session.sql(
            "SELECT COUNT(*) AS c FROM (SELECT label FROM u_a UNION "
            "SELECT label FROM u_b) t"
        ).collect()
        assert rows[0].c == 3


class TestOrderGroupExpressions:
    """ORDER BY / GROUP BY expressions + qualified names (VERDICT r4
    item 5) — all three probes the verdict verified failing."""

    @pytest.fixture()
    def view(self, tpu_session):
        tpu_session.createDataFrame(
            [("a", 2.6), ("b", 1.2), ("c", 2.4), ("d", 0.6)],
            ["k", "score"],
        ).createOrReplaceTempView("oge_t")

    def test_order_by_qualified(self, tpu_session, view):
        rows = tpu_session.sql(
            "SELECT k FROM oge_t t ORDER BY t.score"
        ).collect()
        assert [r.k for r in rows] == ["d", "b", "c", "a"]

    def test_order_by_expression(self, tpu_session, view):
        rows = tpu_session.sql(
            "SELECT k FROM oge_t ORDER BY score + 1 DESC"
        ).collect()
        assert [r.k for r in rows] == ["a", "c", "b", "d"]

    def test_order_by_builtin_call(self, tpu_session, view):
        rows = tpu_session.sql(
            "SELECT k FROM oge_t ORDER BY ABS(score - 2)"
        ).collect()
        # |score-2|: c=0.4 < a=0.6 < b=0.8 < d=1.4
        assert [r.k for r in rows] == ["c", "a", "b", "d"]

    def test_group_by_cast_expression(self, tpu_session, view):
        rows = tpu_session.sql(
            "SELECT CAST(score AS int) AS b, COUNT(*) AS c FROM oge_t "
            "GROUP BY CAST(score AS int) ORDER BY b"
        ).collect()
        assert [(r.b, r.c) for r in rows] == [(0, 1), (1, 1), (2, 2)]

    def test_group_by_qualified(self, tpu_session, view):
        rows = tpu_session.sql(
            "SELECT t.k, COUNT(*) AS c FROM oge_t t GROUP BY t.k "
            "ORDER BY t.k LIMIT 2"
        ).collect()
        assert [(r.k, r.c) for r in rows] == [("a", 1), ("b", 1)]

    def test_agg_order_by_expression_over_outputs(self, tpu_session, view):
        rows = tpu_session.sql(
            "SELECT k, SUM(score) AS s FROM oge_t GROUP BY k "
            "ORDER BY s * -1"
        ).collect()
        assert [r.k for r in rows] == ["a", "c", "b", "d"]


class TestDialectReviewFixes:
    """Regression tests for the round-5 review findings on the new
    dialect features."""

    @pytest.fixture()
    def dup_view(self, tpu_session):
        tpu_session.createDataFrame(
            [("a", 1), ("a", 1), ("b", 2)], ["k", "n"]
        ).createOrReplaceTempView("dup_t")

    def test_select_distinct_star(self, tpu_session, dup_view):
        assert tpu_session.sql("SELECT DISTINCT * FROM dup_t").count() == 2

    def test_select_distinct_star_with_order(self, tpu_session, dup_view):
        rows = tpu_session.sql(
            "SELECT DISTINCT * FROM dup_t ORDER BY n DESC"
        ).collect()
        assert [(r.k, r.n) for r in rows] == [("b", 2), ("a", 1)]

    def test_unaliased_window_projection(self, tpu_session, dup_view):
        out = tpu_session.sql(
            "SELECT k, ROW_NUMBER() OVER (ORDER BY n) FROM dup_t"
        )
        win_col = [c for c in out.columns if c != "k"][0]
        assert "ROW_NUMBER() OVER" in win_col
        assert sorted(r[win_col] for r in out.collect()) == [1, 2, 3]

    def test_in_subquery_array_values_error_not_flatten(
        self, tpu_session, dup_view
    ):
        # one row holding an array must NOT be unpacked into element
        # membership — it errors (arrays are not comparable to scalars)
        with pytest.raises(ValueError, match="hashable"):
            tpu_session.sql(
                "SELECT k FROM dup_t WHERE n IN "
                "(SELECT COLLECT_LIST(n) FROM dup_t)"
            )

    def test_group_by_expression_case_insensitive_spelling(
        self, tpu_session, dup_view
    ):
        rows = tpu_session.sql(
            "SELECT cast(n AS int) AS b, COUNT(*) AS c FROM dup_t "
            "GROUP BY CAST(n AS int) ORDER BY b"
        ).collect()
        assert [(r.b, r.c) for r in rows] == [(1, 2), (2, 1)]

    def test_multiline_window_projection_alias(self, tpu_session, dup_view):
        # triple-quoted SQL wraps window projections across lines; the
        # alias must still strip (README's own example shape)
        rows = tpu_session.sql(
            """
            SELECT k, rn FROM (
                SELECT k, ROW_NUMBER() OVER
                    (PARTITION BY k ORDER BY n DESC) AS rn
                FROM dup_t
            ) t WHERE t.rn = 1 ORDER BY k
            """
        ).collect()
        assert [(r.k, r.rn) for r in rows] == [("a", 1), ("b", 1)]


class TestAggregateWindows:
    """Aggregate/LAG/LEAD window functions (round-5 extension of the
    ranking windows — the Spark serving-analytics running-total and
    share-of-partition idioms)."""

    @pytest.fixture()
    def view(self, tpu_session):
        tpu_session.createDataFrame(
            [("cat", 1, 0.5), ("cat", 2, 0.3), ("cat", 3, 0.3),
             ("dog", 4, 0.9)],
            ["label", "i", "score"], numPartitions=2,
        ).createOrReplaceTempView("aw_t")

    def test_partition_aggregate_broadcasts(self, tpu_session, view):
        rows = tpu_session.sql(
            "SELECT i, SUM(score) OVER (PARTITION BY label) AS tot "
            "FROM aw_t"
        ).collect()
        got = {r.i: round(r.tot, 6) for r in rows}
        assert got == {1: 1.1, 2: 1.1, 3: 1.1, 4: 0.9}

    def test_running_aggregate_default_frame(self, tpu_session, view):
        rows = tpu_session.sql(
            "SELECT i, SUM(score) OVER (PARTITION BY label ORDER BY i) "
            "AS run FROM aw_t"
        ).collect()
        got = {r.i: round(r.run, 6) for r in rows}
        assert got == {1: 0.5, 2: 0.8, 3: 1.1, 4: 0.9}

    def test_running_frame_peers_share(self, tpu_session, view):
        # Spark's default RANGE frame: rows tied on the order key are
        # peers and share the frame end
        rows = tpu_session.sql(
            "SELECT i, COUNT(*) OVER (PARTITION BY label ORDER BY "
            "score) AS c FROM aw_t"
        ).collect()
        got = {r.i: r.c for r in rows}
        assert got == {1: 3, 2: 2, 3: 2, 4: 1}

    def test_count_star_over_empty_spec(self, tpu_session, view):
        rows = tpu_session.sql(
            "SELECT i, COUNT(*) OVER () AS n FROM aw_t"
        ).collect()
        assert {r.n for r in rows} == {4}

    def test_avg_window_excludes_nulls(self, tpu_session):
        tpu_session.createDataFrame(
            [("a", 2.0), ("a", None), ("a", 4.0)], ["k", "x"]
        ).createOrReplaceTempView("aw_null")
        rows = tpu_session.sql(
            "SELECT AVG(x) OVER (PARTITION BY k) AS m FROM aw_null"
        ).collect()
        assert all(r.m == 3.0 for r in rows)

    def test_lag_lead(self, tpu_session, view):
        rows = tpu_session.sql(
            "SELECT i, LAG(score) OVER (PARTITION BY label ORDER BY i) "
            "AS prev, LEAD(score, 1, -1.0) OVER (PARTITION BY label "
            "ORDER BY i) AS nxt FROM aw_t"
        ).collect()
        got = {r.i: (r.prev, r.nxt) for r in rows}
        assert got == {
            1: (None, 0.3), 2: (0.5, 0.3), 3: (0.3, -1.0),
            4: (None, -1.0),
        }

    def test_lag_lead_default_type_checked(self, tpu_session, view):
        # a default literal that cannot live in the value column's
        # declared type must be rejected up front, not silently mixed in
        with pytest.raises(ValueError, match="not compatible"):
            tpu_session.sql(
                "SELECT LEAD(score, 1, 'oops') OVER (ORDER BY i) AS nxt "
                "FROM aw_t"
            )
        with pytest.raises(ValueError, match="not compatible"):
            tpu_session.sql(
                "SELECT LAG(i, 1, 2.5) OVER (ORDER BY i) AS p FROM aw_t"
            )
        # int literal into a DOUBLE column widens fine
        rows = tpu_session.sql(
            "SELECT i, LEAD(score, 1, -1) OVER (ORDER BY i) AS nxt "
            "FROM aw_t"
        ).collect()
        assert {r.nxt for r in rows if r.i == 4} == {-1}

    def test_lag_offset_two(self, tpu_session, view):
        rows = tpu_session.sql(
            "SELECT i, LAG(score, 2) OVER (ORDER BY i) AS p2 FROM aw_t"
        ).collect()
        got = {r.i: r.p2 for r in rows}
        assert got == {1: None, 2: None, 3: 0.5, 4: 0.3}

    def test_share_of_partition_via_derived_table(self, tpu_session, view):
        rows = tpu_session.sql(
            "SELECT i, score / tot AS share FROM (SELECT i, score, "
            "SUM(score) OVER (PARTITION BY label) AS tot FROM aw_t) d "
            "ORDER BY i"
        ).collect()
        assert [round(r.share, 3) for r in rows] == [
            0.455, 0.273, 0.273, 1.0,
        ]

    def test_rank_still_requires_order(self, tpu_session, view):
        with pytest.raises(ValueError, match="ORDER BY"):
            tpu_session.sql(
                "SELECT ROW_NUMBER() OVER (PARTITION BY label) FROM aw_t"
            )

    def test_window_preserves_partitioning(self, tpu_session, view):
        out = tpu_session.sql(
            "SELECT *, SUM(score) OVER (PARTITION BY label) AS t FROM aw_t"
        )
        assert out.getNumPartitions() == 2

    def test_lag_default_must_be_single_literal(self, tpu_session, view):
        with pytest.raises(ValueError, match="single literal"):
            tpu_session.sql(
                "SELECT LAG(score, 1, 7 + 99) OVER (ORDER BY i) FROM aw_t"
            )

    def test_collect_list_window_schema_is_array(self, tpu_session, view):
        from sparkdl_tpu.sql.types import ArrayType, DoubleType

        out = tpu_session.sql(
            "SELECT i, COLLECT_LIST(score) OVER (PARTITION BY label) "
            "AS xs FROM aw_t"
        )
        assert out.schema["xs"].dataType == ArrayType(DoubleType())


class TestSetOpsAndScalarSubqueries:
    """INTERSECT/EXCEPT [ALL], scalar subqueries, GROUP BY alias
    (round-5 completion of VERDICT r4 missing #3/#4 tails)."""

    @pytest.fixture()
    def views(self, tpu_session):
        tpu_session.createDataFrame(
            [("a", 1), ("a", 1), ("b", 2), ("c", 3)], ["k", "n"]
        ).createOrReplaceTempView("so_x")
        tpu_session.createDataFrame(
            [("a", 1), ("b", 2), ("b", 2), ("d", 4)], ["k", "n"]
        ).createOrReplaceTempView("so_y")
        return tpu_session

    def test_intersect_distinct_and_all(self, views):
        s = views
        assert sorted(r.k for r in s.sql(
            "SELECT k, n FROM so_x INTERSECT SELECT k, n FROM so_y"
        ).collect()) == ["a", "b"]
        # multiset: (a,1) min(2,1)=1, (b,2) min(1,2)=1
        assert sorted(r.k for r in s.sql(
            "SELECT k, n FROM so_x INTERSECT ALL SELECT k, n FROM so_y"
        ).collect()) == ["a", "b"]

    def test_except_distinct_and_all(self, views):
        s = views
        assert [r.k for r in s.sql(
            "SELECT k, n FROM so_x EXCEPT SELECT k, n FROM so_y"
        ).collect()] == ["c"]
        # multiset: (a,1) 2-1=1 survivor, (b,2) 1-2=0, (c,3) 1
        assert sorted(r.k for r in s.sql(
            "SELECT k, n FROM so_x EXCEPT ALL SELECT k, n FROM so_y"
        ).collect()) == ["a", "c"]

    def test_intersect_binds_tighter_than_except(self, tpu_session):
        tpu_session.createDataFrame(
            [("a",), ("b",), ("c",)], ["k"]
        ).createOrReplaceTempView("p_x")
        tpu_session.createDataFrame(
            [("a",), ("b",)], ["k"]
        ).createOrReplaceTempView("p_y")
        tpu_session.createDataFrame(
            [("a",)], ["k"]
        ).createOrReplaceTempView("p_z")
        # x EXCEPT (y INTERSECT z) = {a,b,c} - {a} = {b,c};
        # left-assoc misparse would give (x-y) ∩ z = {c} ∩ {a} = {}
        rows = tpu_session.sql(
            "SELECT k FROM p_x EXCEPT SELECT k FROM p_y "
            "INTERSECT SELECT k FROM p_z"
        ).collect()
        assert sorted(r.k for r in rows) == ["b", "c"]

    def test_setops_with_trailing_order_limit(self, views):
        rows = views.sql(
            "SELECT k, n FROM so_x EXCEPT ALL SELECT k, n FROM so_y "
            "ORDER BY k DESC LIMIT 1"
        ).collect()
        assert [(r.k, r.n) for r in rows] == [("c", 3)]

    def test_dataframe_setop_methods(self, views):
        a, b = views.table("so_x"), views.table("so_y")
        assert sorted(r.k for r in a.subtract(b).collect()) == ["c"]
        assert sorted(r.k for r in a.intersect(b).collect()) == ["a", "b"]
        assert sorted(r.k for r in a.intersectAll(b).collect()) == ["a", "b"]
        assert sorted(r.k for r in a.exceptAll(b).collect()) == ["a", "c"]

    def test_scalar_subquery_in_where(self, views):
        rows = views.sql(
            "SELECT k FROM so_x WHERE n > (SELECT AVG(n) FROM so_x)"
        ).collect()
        assert sorted(r.k for r in rows) == ["b", "c"]

    def test_scalar_subquery_in_projection(self, views):
        # AVG, not MIN: an earlier test registers a scalar UDF named
        # "min" in the shared session (the documented UDF-precedence
        # rule), which would shadow the aggregate here
        rows = views.sql(
            "SELECT k, n - (SELECT AVG(n) FROM so_x) AS d FROM so_x "
            "WHERE k = 'c'"
        ).collect()
        assert [(r.k, r.d) for r in rows] == [("c", 3 - 1.75)]

    def test_scalar_subquery_zero_rows_is_null(self, views):
        rows = views.sql(
            "SELECT k FROM so_x WHERE n = (SELECT n FROM so_y "
            "WHERE k = 'zzz')"
        ).collect()
        assert rows == []  # NULL comparison matches nothing

    def test_scalar_subquery_multirow_errors(self, views):
        with pytest.raises(ValueError, match="[Ss]calar subquery"):
            views.sql(
                "SELECT k FROM so_x WHERE n > (SELECT n FROM so_y)"
            )

    def test_group_by_select_alias(self, views):
        rows = views.sql(
            "SELECT n * 10 AS b, COUNT(*) AS c FROM so_x GROUP BY b "
            "ORDER BY b"
        ).collect()
        assert [(r.b, r.c) for r in rows] == [(10, 2), (20, 1), (30, 1)]

    def test_group_by_alias_of_aggregate_errors(self, views):
        with pytest.raises(ValueError, match="aggregate"):
            views.sql(
                "SELECT COUNT(*) AS c FROM so_x GROUP BY c"
            )

    def test_group_by_real_column_beats_alias(self, views):
        # Spark resolution order: a real column named like an alias
        # wins — so GROUP BY k groups by the string column, and the
        # projection `n AS k` is then not a group key (Spark rejects
        # this query too)
        with pytest.raises(ValueError, match="GROUP BY key"):
            views.sql(
                "SELECT n AS k, COUNT(*) AS c FROM so_x GROUP BY k"
            )


class TestFunctionsSurface:
    """pyspark.sql.functions free-function parity (F.avg/F.desc/F.when/
    F.expr) + the round-5 DataFrame method batch."""

    @pytest.fixture()
    def fdf(self, tpu_session):
        return tpu_session.createDataFrame(
            [("a", 1, 0.5), ("a", 2, 1.5), ("b", 3, 2.5)],
            ["k", "n", "x"], numPartitions=2,
        )

    def test_agg_with_function_columns(self, fdf):
        import sparkdl_tpu.sql.functions as F

        out = fdf.groupBy("k").agg(
            F.avg("x").alias("m"), F.count("*"), F.countDistinct("n")
        )
        assert out.columns == ["k", "m", "count(*)", "count(DISTINCT n)"]
        got = {r.k: (r.m, r["count(*)"]) for r in out.collect()}
        assert got == {"a": (1.0, 2), "b": (2.5, 1)}

    def test_agg_rejects_non_aggregate_column(self, fdf):
        from sparkdl_tpu.sql.functions import col

        with pytest.raises(ValueError, match="not an aggregate"):
            fdf.groupBy("k").agg(col("x"))

    def test_order_by_desc_marker(self, fdf):
        import sparkdl_tpu.sql.functions as F

        assert [r.n for r in fdf.orderBy(F.desc("n")).collect()] == [3, 2, 1]
        assert [
            r.n for r in fdf.orderBy(F.asc("k"), F.desc("x")).collect()
        ] == [2, 1, 3]

    def test_when_otherwise_chain(self, fdf):
        import sparkdl_tpu.sql.functions as F
        from sparkdl_tpu.sql.functions import col

        out = fdf.withColumn(
            "sign",
            F.when(col("x") > 1, "hi").when(col("x") > 0.4, "mid")
            .otherwise("lo"),
        )
        assert [r.sign for r in out.collect()] == ["mid", "hi", "hi"]

    def test_when_guards_division(self, tpu_session):
        import sparkdl_tpu.sql.functions as F
        from sparkdl_tpu.sql.functions import col, lit

        df = tpu_session.createDataFrame([(4.0,), (0.0,)], ["d"])
        out = df.withColumn(
            "q", F.when(col("d") != 0, lit(100.0) / col("d")).otherwise(0.0)
        )
        assert [r.q for r in out.collect()] == [25.0, 0.0]

    def test_otherwise_requires_when(self, fdf):
        from sparkdl_tpu.sql.functions import col

        with pytest.raises(TypeError, match="when"):
            col("x").otherwise(0)

    def test_expr_and_select_expr(self, fdf):
        import sparkdl_tpu.sql.functions as F

        out = fdf.select(F.expr("x * 100").alias("pct"))
        assert [r.pct for r in out.collect()] == [50.0, 150.0, 250.0]
        out2 = fdf.selectExpr("k", "x * 2 AS dbl")
        assert out2.columns == ["k", "dbl"]
        assert [r.dbl for r in out2.collect()] == [1.0, 3.0, 5.0]

    def test_scalar_function_helpers(self, tpu_session):
        import sparkdl_tpu.sql.functions as F

        df = tpu_session.createDataFrame(
            [("Ab", -2, None), (None, 3, "z")], ["s", "i", "t"]
        )
        out = df.select(
            F.upper("s").alias("u"), F.abs("i").alias("a"),
            F.coalesce("s", "t").alias("c"),
            F.concat("s", "t").alias("cat"),
        )
        rows = out.collect()
        assert (rows[0].u, rows[0].a, rows[0].c) == ("AB", 2, "Ab")
        assert (rows[1].u, rows[1].a, rows[1].c) == (None, 3, "z")
        assert rows[0].cat is None  # NULL-propagating concat, as Spark
        out2 = tpu_session.createDataFrame(
            [("hello",)], ["w"]
        ).select(F.substring("w", 2, 3).alias("sub"))
        assert out2.collect()[0].sub == "ell"

    def test_cross_join(self, fdf):
        left = fdf.select("k").withColumnRenamed("k", "k1")
        out = left.crossJoin(fdf.select("n"))
        assert out.count() == 9
        assert out.columns == ["k1", "n"]
        with pytest.raises(ValueError, match="duplicate"):
            fdf.crossJoin(fdf)

    def test_sample(self, fdf):
        assert fdf.sample(1.0).count() == 3
        assert fdf.sample(0.0, 42).count() == 0
        big = fdf.sparkSession.createDataFrame(
            [(i,) for i in range(2000)], ["i"]
        )
        n = big.sample(0.5, seed=7).count()
        assert 850 < n < 1150  # Bernoulli(0.5), ~5 sigma
        m = big.sample(True, 0.5, 7).count()  # Poisson with replacement
        assert 850 < m < 1150

    def test_describe(self, fdf):
        out = fdf.describe("x")
        assert out.columns == ["summary", "x"]
        got = {r.summary: r.x for r in out.collect()}
        assert got["count"] == "3" and got["mean"] == "1.5"
        assert float(got["stddev"]) == pytest.approx(1.0)
        assert got["min"] == "0.5" and got["max"] == "2.5"

    def test_corr_cov_tail_isempty_todf(self, fdf):
        assert fdf.corr("n", "x") == pytest.approx(1.0)
        assert fdf.cov("n", "x") == pytest.approx(1.0)
        assert [r.n for r in fdf.tail(2)] == [2, 3]
        assert not fdf.isEmpty() and fdf.limit(0).isEmpty()
        assert fdf.toDF("a", "b", "c").columns == ["a", "b", "c"]

    def test_with_columns_and_sort_within_partitions(self, fdf):
        from sparkdl_tpu.sql.functions import col

        out = fdf.withColumns(
            {"y": col("x") * 2, "z": col("n") + 1}
        )
        assert out.columns == ["k", "n", "x", "y", "z"]
        import sparkdl_tpu.sql.functions as F

        sp = fdf.sortWithinPartitions(F.desc("n"))
        assert sp.getNumPartitions() == fdf.getNumPartitions()
        # each partition individually descending
        descending = []
        sp.foreachPartition(
            lambda p: descending.append(
                all(a >= b for a, b in zip(p["n"], p["n"][1:]))
            )
        )
        assert all(descending)

    def test_agg_exprs_keyword_back_compat(self, fdf):
        out = fdf.groupBy("k").agg(exprs={"x": "avg"})
        assert {r.k: r["avg(x)"] for r in out.collect()} == {
            "a": 1.0, "b": 2.5,
        }

    def test_zero_arg_scalar_fns_keep_rows(self, fdf):
        import sparkdl_tpu.sql.functions as F

        out = fdf.select(F.concat().alias("c"), F.coalesce().alias("n0"))
        rows = out.collect()
        assert len(rows) == 3
        assert all(r.c == "" and r.n0 is None for r in rows)

    def test_todf_temp_names_cannot_clobber(self, tpu_session):
        df = tpu_session.createDataFrame(
            [(1, 2)], ["b", "__tmp_0"]
        ).toDF("x", "y")
        assert df.columns == ["x", "y"]
        assert df.collect()[0] == Row(x=1, y=2)

    def test_expr_with_alias(self, fdf):
        import sparkdl_tpu.sql.functions as F

        out = fdf.select(F.expr("n AS m"))
        assert out.columns == ["m"]
        assert [r.m for r in out.collect()] == [1, 2, 3]


class TestPivot:
    """GroupedData.pivot (the pyspark wide-reshape idiom)."""

    @pytest.fixture()
    def pdf(self, tpu_session):
        return tpu_session.createDataFrame(
            [("a", "cat", 1.0), ("a", "dog", 2.0), ("b", "cat", 3.0),
             ("a", "cat", 5.0), ("b", None, 9.0)],
            ["k", "animal", "x"], numPartitions=2,
        )

    def test_pivot_single_aggregate(self, pdf):
        out = pdf.groupBy("k").pivot("animal").agg({"x": "sum"})
        # discovered values sorted ascending; NULL pivot groups dropped
        assert out.columns == ["k", "cat", "dog"]
        got = {r.k: (r.cat, r.dog) for r in out.collect()}
        assert got == {"a": (6.0, 2.0), "b": (3.0, None)}

    def test_pivot_explicit_values(self, pdf):
        out = pdf.groupBy("k").pivot("animal", ["cat", "owl"]).agg(
            {"x": "sum"}
        )
        assert out.columns == ["k", "cat", "owl"]
        got = {r.k: (r.cat, r.owl) for r in out.collect()}
        assert got == {"a": (6.0, None), "b": (3.0, None)}

    def test_pivot_multi_aggregate_names(self, pdf):
        import sparkdl_tpu.sql.functions as F

        out = pdf.groupBy("k").pivot("animal").agg(
            F.sum("x").alias("s"), F.count("*").alias("c")
        )
        assert out.columns == ["k", "cat_s", "cat_c", "dog_s", "dog_c"]
        got = {r.k: (r["cat_s"], r["cat_c"]) for r in out.collect()}
        assert got == {"a": (6.0, 2), "b": (3.0, 1)}

    def test_pivot_schema_types(self, pdf):
        from sparkdl_tpu.sql.types import DoubleType, StringType

        out = pdf.groupBy("k").pivot("animal").agg({"x": "sum"})
        assert out.schema["k"].dataType == StringType()
        assert out.schema["cat"].dataType == DoubleType()

    def test_pivot_twice_errors(self, pdf):
        with pytest.raises(ValueError, match="once"):
            pdf.groupBy("k").pivot("animal").pivot("animal")

    def test_pivot_named_helper(self, pdf):
        out = pdf.groupBy("k").pivot("animal").sum("x")
        assert out.columns == ["k", "cat", "dog"]

    def test_pivot_name_collision_raises(self, tpu_session):
        df = tpu_session.createDataFrame(
            [("a", "k", 1.0), ("b", "cat", 2.0)], ["k", "animal", "x"]
        )
        with pytest.raises(ValueError, match="duplicate"):
            df.groupBy("k").pivot("animal").agg({"x": "sum"})
        df2 = tpu_session.createDataFrame(
            [("a", 1, 1.0), ("a", "1", 2.0)], ["k", "v", "x"]
        )
        with pytest.raises(ValueError, match="duplicate"):
            df2.groupBy("k").pivot("v").agg({"x": "sum"})


class TestOrdinalsAndStringBuiltins:
    """ORDER BY / GROUP BY select-list ordinals + the string builtin
    batch (CONCAT/SUBSTRING/TRIM/REPLACE/INSTR/SPLIT)."""

    @pytest.fixture()
    def view(self, tpu_session):
        tpu_session.createDataFrame(
            [("b", 2), ("a", 1), ("c", 3), ("a", 4)], ["k", "n"]
        ).createOrReplaceTempView("ord_t")

    def test_order_by_ordinal(self, tpu_session, view):
        rows = tpu_session.sql(
            "SELECT k, n FROM ord_t ORDER BY 2 DESC"
        ).collect()
        assert [r.n for r in rows] == [4, 3, 2, 1]

    def test_order_by_ordinal_out_of_range(self, tpu_session, view):
        with pytest.raises(ValueError, match="out of range"):
            tpu_session.sql("SELECT k FROM ord_t ORDER BY 3")

    def test_group_by_ordinal(self, tpu_session, view):
        rows = tpu_session.sql(
            "SELECT k, COUNT(*) AS c FROM ord_t GROUP BY 1 ORDER BY 1"
        ).collect()
        assert [(r.k, r.c) for r in rows] == [("a", 2), ("b", 1), ("c", 1)]

    def test_group_by_ordinal_of_aggregate_errors(self, tpu_session, view):
        with pytest.raises(ValueError, match="aggregate"):
            tpu_session.sql(
                "SELECT COUNT(*) AS c, k FROM ord_t GROUP BY 1"
            )

    def test_agg_order_by_ordinal_follows_select_order(
        self, tpu_session, view
    ):
        # ordinal 1 is the aggregate (SELECT order), NOT the group key
        rows = tpu_session.sql(
            "SELECT SUM(n) AS sn, k FROM ord_t GROUP BY k ORDER BY 1 DESC"
        ).collect()
        assert [r.k for r in rows] == ["a", "c", "b"]

    def test_union_order_by_ordinal(self, tpu_session, view):
        rows = tpu_session.sql(
            "SELECT k FROM ord_t UNION SELECT k FROM ord_t "
            "ORDER BY 1 DESC LIMIT 2"
        ).collect()
        assert [r.k for r in rows] == ["c", "b"]

    def test_string_builtins(self, tpu_session):
        tpu_session.createDataFrame(
            [("  hello  ", "path/to/img.png")], ["s", "p"]
        ).createOrReplaceTempView("str_t")
        row = tpu_session.sql(
            "SELECT TRIM(s) AS t, LTRIM(s) AS lt, RTRIM(s) AS rt, "
            "CONCAT(TRIM(s), '!', 42) AS c, SUBSTRING(p, 1, 4) AS sub, "
            "SUBSTR(p, -7) AS tail7, REPLACE(p, '/', ':') AS rp, "
            "INSTR(p, 'img') AS ix, SPLIT(p, '/') AS parts FROM str_t"
        ).collect()[0]
        assert row.t == "hello"
        assert row.lt == "hello  " and row.rt == "  hello"
        assert row.c == "hello!42"
        assert row.sub == "path" and row.tail7 == "img.png"
        assert row.rp == "path:to:img.png"
        assert row.ix == 9
        assert row.parts == ["path", "to", "img.png"]

    def test_string_builtins_null_propagation(self, tpu_session):
        tpu_session.createDataFrame(
            [(None,)], ["s"]
        ).createOrReplaceTempView("str_null")
        row = tpu_session.sql(
            "SELECT CONCAT(s, 'x') AS c, TRIM(s) AS t, "
            "SPLIT(s, ',') AS sp FROM str_null"
        ).collect()[0]
        assert row.c is None and row.t is None and row.sp is None

    def test_substring_negative_start_window(self, tpu_session):
        tpu_session.createDataFrame(
            [("abc",)], ["s"]
        ).createOrReplaceTempView("sub_t")
        row = tpu_session.sql(
            "SELECT SUBSTRING(s, -5, 3) AS a, SUBSTRING(s, -2) AS b, "
            "SUBSTRING(s, -2, 1) AS c FROM sub_t"
        ).collect()[0]
        # Spark: the length window applies before clamping
        assert row.a == "a" and row.b == "bc" and row.c == "b"

    def test_replace_empty_search_is_identity(self, tpu_session):
        tpu_session.createDataFrame(
            [("b",)], ["s"]
        ).createOrReplaceTempView("rep_t")
        row = tpu_session.sql(
            "SELECT REPLACE(s, '', 'x') AS r FROM rep_t"
        ).collect()[0]
        assert row.r == "b"  # Spark: empty search leaves input unchanged

    def test_replace_two_arg_deletes(self, tpu_session):
        tpu_session.createDataFrame(
            [("path/to/img",)], ["p"]
        ).createOrReplaceTempView("rep2_t")
        row = tpu_session.sql(
            "SELECT REPLACE(p, '/') AS r FROM rep2_t"
        ).collect()[0]
        assert row.r == "pathtoimg"

    def test_f_substring_matches_sql_semantics(self, tpu_session):
        import sparkdl_tpu.sql.functions as F

        df = tpu_session.createDataFrame([("abc",)], ["s"])
        out = df.select(
            F.substring("s", -5, 3).alias("a"),
            F.substring("s", 2, 2).alias("b"),
        ).collect()[0]
        assert out.a == "a" and out.b == "bc"


class TestWindowSpecAPI:
    """pyspark Window/over() DataFrame API — the programmatic twin of
    the SQL OVER clause."""

    @pytest.fixture()
    def wdf(self, tpu_session):
        return tpu_session.createDataFrame(
            [("cat", "a", 0.9), ("cat", "b", 0.7), ("dog", "c", 0.6),
             ("dog", "d", 0.95)],
            ["label", "img", "score"], numPartitions=2,
        )

    def test_row_number_over(self, wdf):
        import sparkdl_tpu.sql.functions as F
        from sparkdl_tpu.sql.functions import Window, col

        w = Window.partitionBy("label").orderBy(F.desc("score"))
        r = wdf.withColumn("rn", F.row_number().over(w))
        assert {x.img: x.rn for x in r.collect()} == {
            "a": 1, "b": 2, "c": 2, "d": 1,
        }
        top1 = r.filter(col("rn") == 1)
        assert sorted((x.label, x.img) for x in top1.collect()) == [
            ("cat", "a"), ("dog", "d"),
        ]
        assert r.getNumPartitions() == 2

    def test_mixed_window_select(self, wdf):
        import sparkdl_tpu.sql.functions as F
        from sparkdl_tpu.sql.functions import Window

        w = Window.partitionBy("label").orderBy(F.desc("score"))
        sel = wdf.select(
            "img",
            F.rank().over(w).alias("rk"),
            F.sum("score").over(Window.partitionBy("label")).alias("tot"),
            F.lag("score").over(w).alias("prev"),
            F.lead("score", 1, -1.0).over(w).alias("nxt"),
        )
        got = {x.img: (x.rk, round(x.tot, 2), x.prev, x.nxt)
               for x in sel.collect()}
        assert got == {
            "a": (1, 1.6, None, 0.7), "b": (2, 1.6, 0.9, -1.0),
            "c": (2, 1.55, 0.95, -1.0), "d": (1, 1.55, None, 0.6),
        }

    def test_running_aggregate_over(self, wdf):
        import sparkdl_tpu.sql.functions as F
        from sparkdl_tpu.sql.functions import Window

        w = Window.partitionBy("label").orderBy("score")
        out = wdf.withColumn("run", F.sum("score").over(w))
        got = {x.img: round(x.run, 2) for x in out.collect()}
        assert got == {"a": 1.6, "b": 0.7, "c": 0.6, "d": 1.55}

    def test_errors(self, wdf):
        import sparkdl_tpu.sql.functions as F
        from sparkdl_tpu.sql.functions import Window, col

        with pytest.raises(TypeError, match="WindowSpec"):
            F.row_number().over("nope")
        with pytest.raises(ValueError, match="orderBy"):
            wdf.select(F.row_number().over(Window.partitionBy("label")))
        with pytest.raises(ValueError, match="not a window function"):
            col("score").over(Window.partitionBy("label"))
        with pytest.raises(ValueError, match="over"):
            wdf.select(F.row_number())  # unbound rank fn

    def test_window_replace_existing_column(self, wdf):
        import sparkdl_tpu.sql.functions as F
        from sparkdl_tpu.sql.functions import Window

        w = Window.orderBy("score")
        once = wdf.withColumn("rn", F.row_number().over(w))
        twice = once.withColumn("rn", F.row_number().over(
            Window.orderBy(F.desc("score"))
        ))
        a = {x.img: x.rn for x in once.collect()}
        b = {x.img: x.rn for x in twice.collect()}
        assert a["d"] == 4 and b["d"] == 1  # replaced, not duplicated
        assert twice.columns.count("rn") == 1

    def test_window_replacing_referenced_column(self, wdf):
        import sparkdl_tpu.sql.functions as F
        from sparkdl_tpu.sql.functions import Window

        # replace 'score' with a window computed FROM 'score'
        out = wdf.withColumn(
            "score", F.sum("score").over(Window.partitionBy("label"))
        )
        got = {x.img: round(x.score, 2) for x in out.collect()}
        assert got == {"a": 1.6, "b": 1.6, "c": 1.55, "d": 1.55}
        assert out.columns.count("score") == 1

    def test_shared_spec_single_sort(self, wdf, monkeypatch):
        import sparkdl_tpu.sql.functions as F
        from sparkdl_tpu.sql import dataframe as df_mod
        from sparkdl_tpu.sql.functions import Window

        w = Window.partitionBy("label").orderBy(F.desc("score"))
        sorts = {"n": 0}
        orig = list.sort

        def counting_sort(self, **kw):
            sorts["n"] += 1
            return orig(self, **kw)

        monkeypatch.setattr(
            df_mod.DataFrame, "_window_groups",
            _counting_groups(df_mod.DataFrame._window_groups, sorts),
        )
        out = wdf.select(
            "img",
            F.rank().over(w).alias("rk"),
            F.lag("score").over(w).alias("prev"),
            F.lead("score").over(w).alias("nxt"),
        )
        assert out.count() == 4
        # 3 windows over ONE spec: bucketing+sort computed once, memoized
        assert sorts["n"] == 1


def _counting_groups(orig, counter):
    def wrapped(self, partition_cols, order_cols, ascending,
                extra_cols=()):
        memo = getattr(self, "_win_memo", None)
        key = (tuple(partition_cols), tuple(order_cols), tuple(ascending))
        if memo is None or key not in memo:
            counter["n"] += 1
        return orig(self, partition_cols, order_cols, ascending,
                    extra_cols=extra_cols)
    return wrapped


class TestRankFamilyAndExists:
    """NTILE/PERCENT_RANK/CUME_DIST, FIRST/LAST aggregates, and
    uncorrelated EXISTS."""

    @pytest.fixture()
    def view(self, tpu_session):
        tpu_session.createDataFrame(
            [("a", i, float(i)) for i in range(1, 7)] + [("b", 9, 1.0)],
            ["k", "i", "x"], numPartitions=2,
        ).createOrReplaceTempView("rf_t")

    def test_ntile_percent_rank_cume_dist(self, tpu_session, view):
        rows = tpu_session.sql(
            "SELECT i, NTILE(3) OVER (PARTITION BY k ORDER BY i) AS b, "
            "PERCENT_RANK() OVER (PARTITION BY k ORDER BY i) AS pr, "
            "CUME_DIST() OVER (PARTITION BY k ORDER BY i) AS cd "
            "FROM rf_t WHERE k = 'a'"
        ).collect()
        assert [r.b for r in rows] == [1, 1, 2, 2, 3, 3]
        assert [round(r.pr, 3) for r in rows] == [
            0.0, 0.2, 0.4, 0.6, 0.8, 1.0,
        ]
        assert [round(r.cd, 3) for r in rows] == [
            round(i / 6, 3) for i in range(1, 7)
        ]

    def test_ntile_uneven_and_single_row(self, tpu_session):
        tpu_session.createDataFrame(
            [(i,) for i in range(1, 6)], ["i"]
        ).createOrReplaceTempView("nt_t")
        rows = tpu_session.sql(
            "SELECT i, NTILE(3) OVER (ORDER BY i) AS b FROM nt_t"
        ).collect()
        # 5 rows into 3 buckets: sizes 2,2,1 (first n%k get one extra)
        assert [r.b for r in rows] == [1, 1, 2, 2, 3]

    def test_cume_dist_with_ties(self, tpu_session):
        tpu_session.createDataFrame(
            [(1,), (2,), (2,), (3,)], ["v"]
        ).createOrReplaceTempView("cd_t")
        rows = tpu_session.sql(
            "SELECT v, CUME_DIST() OVER (ORDER BY v) AS cd FROM cd_t"
        ).collect()
        got = sorted((r.v, round(r.cd, 3)) for r in rows)
        # peers share the INCLUSIVE frame end: both 2s get 3/4
        assert got == [(1, 0.25), (2, 0.75), (2, 0.75), (3, 1.0)]

    def test_first_last_aggregates(self, tpu_session, view):
        rows = tpu_session.sql(
            "SELECT k, FIRST(x) AS f, LAST(x) AS l FROM rf_t "
            "GROUP BY k ORDER BY k"
        ).collect()
        assert [(r.k, r.f, r.l) for r in rows] == [
            ("a", 1.0, 6.0), ("b", 1.0, 1.0),
        ]

    def test_first_skips_nulls(self, tpu_session):
        tpu_session.createDataFrame(
            [("a", None), ("a", 2.0), ("a", 3.0)], ["k", "x"]
        ).createOrReplaceTempView("fn_t")
        row = tpu_session.sql(
            "SELECT FIRST(x) AS f FROM fn_t GROUP BY k"
        ).collect()[0]
        assert row.f == 2.0  # ignorenulls semantics, documented

    def test_first_last_ignorenulls_argument(self, tpu_session, view):
        # Spark's two-arg spelling: true matches engine semantics and is
        # accepted; false (Spark's default!) cannot be honoured — the
        # engine pre-filters NULLs — so it must fail loudly
        rows = tpu_session.sql(
            "SELECT k, FIRST(x, true) AS f, LAST(x, TRUE) AS l "
            "FROM rf_t GROUP BY k ORDER BY k"
        ).collect()
        assert [(r.k, r.f, r.l) for r in rows] == [
            ("a", 1.0, 6.0), ("b", 1.0, 1.0),
        ]
        with pytest.raises(NotImplementedError, match="ignoreNulls"):
            tpu_session.sql(
                "SELECT FIRST(x, false) AS f FROM rf_t GROUP BY k"
            )
        with pytest.raises(NotImplementedError, match="ignoreNulls"):
            tpu_session.sql(
                "SELECT LAST(x, false) AS f FROM rf_t GROUP BY k"
            )

    def test_first_last_ignorenulls_python_api(self, tpu_session, view):
        import sparkdl_tpu.sql.functions as F

        df = tpu_session.table("rf_t")
        row = (
            df.groupBy("k")
            .agg(F.first("x", ignorenulls=True))
            .orderBy("k")
            .collect()[0]
        )
        assert row["first(x)"] == 1.0
        with pytest.raises(NotImplementedError, match="ignorenulls"):
            F.first("x", ignorenulls=False)
        with pytest.raises(NotImplementedError, match="ignorenulls"):
            F.last("x", ignorenulls=False)

    def test_exists_and_not_exists(self, tpu_session, view):
        assert tpu_session.sql(
            "SELECT k FROM rf_t WHERE EXISTS "
            "(SELECT k FROM rf_t WHERE x > 5)"
        ).count() == 7
        assert tpu_session.sql(
            "SELECT k FROM rf_t WHERE NOT EXISTS "
            "(SELECT k FROM rf_t WHERE x > 99)"
        ).count() == 7
        assert tpu_session.sql(
            "SELECT k FROM rf_t WHERE EXISTS "
            "(SELECT k FROM rf_t WHERE x > 99)"
        ).count() == 0

    def test_window_api_ntile_first(self, tpu_session, view):
        import sparkdl_tpu.sql.functions as F
        from sparkdl_tpu.sql.functions import Window

        df = tpu_session.table("rf_t")
        w = Window.partitionBy("k").orderBy("i")
        out = df.select("i", F.ntile(2).over(w).alias("h"))
        got = [r.h for r in out.collect() if True]
        assert got == [1, 1, 1, 2, 2, 2, 1]
        agg = df.groupBy("k").agg(
            F.first("x").alias("f"), F.last("x").alias("l")
        )
        assert sorted((r.k, r.f, r.l) for r in agg.collect()) == [
            ("a", 1.0, 6.0), ("b", 1.0, 1.0),
        ]

    def test_ntile_requires_positive_literal(self, tpu_session, view):
        import sparkdl_tpu.sql.functions as F

        with pytest.raises(ValueError, match="NTILE"):
            tpu_session.sql(
                "SELECT NTILE(x) OVER (ORDER BY i) FROM rf_t"
            )
        with pytest.raises(ValueError, match="positive"):
            F.ntile(0)

    def test_column_named_exists_still_works(self, tpu_session):
        tpu_session.createDataFrame(
            [(1,), (2,)], ["exists"]
        ).createOrReplaceTempView("ex_t")
        assert tpu_session.sql(
            "SELECT exists FROM ex_t WHERE exists > 1"
        ).count() == 1


class TestRowsFrames:
    """Explicit ROWS BETWEEN frames (moving windows) in SQL and the
    Window spec API."""

    @pytest.fixture()
    def view(self, tpu_session):
        tpu_session.createDataFrame(
            [(i, float(i)) for i in range(1, 7)], ["i", "x"],
            numPartitions=2,
        ).createOrReplaceTempView("fr_t")

    def test_moving_average_sql(self, tpu_session, view):
        rows = tpu_session.sql(
            "SELECT i, AVG(x) OVER (ORDER BY i ROWS BETWEEN 2 "
            "PRECEDING AND CURRENT ROW) AS ma FROM fr_t"
        ).collect()
        assert [round(r.ma, 3) for r in rows] == [
            1.0, 1.5, 2.0, 3.0, 4.0, 5.0,
        ]

    def test_forward_frame_and_empty_frame_null(self, tpu_session, view):
        rows = tpu_session.sql(
            "SELECT i, SUM(x) OVER (ORDER BY i ROWS BETWEEN 1 "
            "FOLLOWING AND UNBOUNDED FOLLOWING) AS rest FROM fr_t"
        ).collect()
        got = {r.i: r.rest for r in rows}
        assert got[1] == 20.0 and got[5] == 6.0
        assert got[6] is None  # empty frame: SUM of nothing is NULL

    def test_rows_frame_is_row_based_not_peer_shared(self, tpu_session):
        tpu_session.createDataFrame(
            [(1, 1.0), (1, 2.0), (2, 4.0)], ["k", "x"]
        ).createOrReplaceTempView("peer_t")
        rows = tpu_session.sql(
            "SELECT x, COUNT(*) OVER (ORDER BY k ROWS BETWEEN "
            "UNBOUNDED PRECEDING AND CURRENT ROW) AS c FROM peer_t"
        ).collect()
        # ROWS: ties do NOT share (RANGE would give [2, 2, 3])
        assert sorted(r.c for r in rows) == [1, 2, 3]

    def test_window_api_rows_between(self, tpu_session, view):
        import sparkdl_tpu.sql.functions as F
        from sparkdl_tpu.sql.functions import Window

        df = tpu_session.table("fr_t")
        w = Window.orderBy("i").rowsBetween(-2, Window.currentRow)
        out = df.withColumn("ma", F.avg("x").over(w))
        assert [round(r.ma, 3) for r in out.collect()] == [
            1.0, 1.5, 2.0, 3.0, 4.0, 5.0,
        ]
        w2 = Window.orderBy("i").rowsBetween(
            Window.unboundedPreceding, Window.currentRow
        )
        cum = df.withColumn("c", F.count("*").over(w2))
        assert [r.c for r in cum.collect()] == [1, 2, 3, 4, 5, 6]

    def test_frame_validation(self, tpu_session, view):
        import sparkdl_tpu.sql.functions as F
        from sparkdl_tpu.sql.functions import Window

        with pytest.raises(ValueError, match="frame"):
            F.row_number().over(Window.orderBy("i").rowsBetween(-1, 0))
        with pytest.raises(ValueError, match="frame"):
            F.lag("x").over(Window.orderBy("i").rowsBetween(-1, 0))
        with pytest.raises(ValueError, match="after end"):
            Window.orderBy("i").rowsBetween(1, -1)
        with pytest.raises(ValueError, match="ORDER BY"):
            tpu_session.sql(
                "SELECT SUM(x) OVER (ROWS BETWEEN 1 PRECEDING AND "
                "CURRENT ROW) FROM fr_t"
            )

    def test_inverted_sql_frame_errors(self, tpu_session, view):
        with pytest.raises(ValueError, match="after its end"):
            tpu_session.sql(
                "SELECT SUM(x) OVER (ORDER BY i ROWS BETWEEN 2 "
                "FOLLOWING AND 1 PRECEDING) FROM fr_t"
            )

    def test_unbounded_preceding_incremental_matches_naive(
        self, tpu_session, view
    ):
        # (unbounded, -1): the lagged-cumulative shape exercises the
        # empty-frame head AND the incremental accumulator
        rows = tpu_session.sql(
            "SELECT i, SUM(x) OVER (ORDER BY i ROWS BETWEEN UNBOUNDED "
            "PRECEDING AND 1 PRECEDING) AS prior FROM fr_t"
        ).collect()
        got = {r.i: r.prior for r in rows}
        assert got == {1: None, 2: 1.0, 3: 3.0, 4: 6.0, 5: 10.0, 6: 15.0}


class TestUnionByName:
    def test_union_by_name_reorders(self, tpu_session):
        a = tpu_session.createDataFrame([(1, "x")], ["n", "s"])
        b = tpu_session.createDataFrame([("y", 2)], ["s", "n"])
        out = a.unionByName(b)
        assert out.columns == ["n", "s"]
        assert [(r.n, r.s) for r in out.collect()] == [(1, "x"), (2, "y")]

    def test_union_by_name_missing_columns(self, tpu_session):
        a = tpu_session.createDataFrame([(1, "x")], ["n", "s"])
        b = tpu_session.createDataFrame([(2,)], ["n"])
        with pytest.raises(ValueError, match="column sets differ"):
            a.unionByName(b)
        out = a.unionByName(b, allowMissingColumns=True)
        assert out.columns == ["n", "s"]
        rows = out.collect()
        assert rows[1].s is None
        from sparkdl_tpu.sql.types import StringType

        assert out.schema["s"].dataType == StringType()
