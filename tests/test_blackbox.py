"""Crash flight recorder (``obs/blackbox.py``).

The in-process tests drive the rings and dump paths directly; the
subprocess tests prove the two contracts that matter in production —
an unhandled crash leaves an exception dump, and **SIGKILL** (which no
handler can observe) still leaves the last periodic persist with final
spans and thread stacks, readable as plain JSON.  Subprocess workers
import only ``sparkdl_tpu``'s env-armed obs path (no jax), so they
start in milliseconds.
"""

import json
import os
import signal
import subprocess
import sys
import time

import pytest

from sparkdl_tpu.obs import tracer
from sparkdl_tpu.obs.blackbox import FlightRecorder
from sparkdl_tpu.utils.metrics import MetricsRegistry, metrics


@pytest.fixture(autouse=True)
def clean_slate():
    tracer.disable()
    metrics.reset()
    yield
    tracer.disable()
    metrics.reset()


@pytest.fixture()
def registry():
    return MetricsRegistry()


def _read_json(path):
    with open(path) as fh:
        return json.load(fh)


# ----------------------------------------------------------------------
# rings + dump files (in process)
# ----------------------------------------------------------------------
class TestFlightRecorder:
    def test_rings_are_bounded(self, tmp_path, registry):
        rec = FlightRecorder(
            str(tmp_path), span_capacity=4, event_capacity=3,
            sample_capacity=2, registry=registry,
        )
        for i in range(10):
            rec({"name": f"span{i}"})
            rec.note(f"event{i}", i=i)
            rec.sample_metrics()
        path = rec.dump("manual")
        payload = _read_json(path)
        assert [s["name"] for s in payload["spans"]] == [
            "span6", "span7", "span8", "span9",
        ]
        assert [e["name"] for e in payload["events"]] == [
            "event7", "event8", "event9",
        ]
        assert len(payload["metric_samples"]) == 2

    def test_dump_payload_shape(self, tmp_path, registry):
        registry.counter("serving.requests").add(7)
        rec = FlightRecorder(str(tmp_path), registry=registry)
        rec.note("breadcrumb", detail="x")
        rec.sample_metrics()
        path = rec.dump("watchdog_probe")
        assert os.path.basename(path).startswith(
            f"blackbox-{os.getpid()}-watchdog_probe-"
        )
        payload = _read_json(path)
        assert payload["reason"] == "watchdog_probe"
        assert payload["pid"] == os.getpid()
        assert payload["metrics_now"]["serving.requests"] == 7
        assert payload["metric_samples"][0]["metrics"][
            "serving.requests"] == 7
        # every dump carries all-thread stacks
        assert any("MainThread" in name for name in payload["threads"])
        stacks = list(payload["threads"].values())
        assert any(
            "test_blackbox" in line for st in stacks for line in st
        )

    def test_dump_reason_is_sanitized(self, tmp_path, registry):
        rec = FlightRecorder(str(tmp_path), registry=registry)
        path = rec.dump("breaker open: a/b")
        assert "breaker_open__a_b" in os.path.basename(path)

    def test_dump_with_exception(self, tmp_path, registry):
        rec = FlightRecorder(str(tmp_path), registry=registry)
        try:
            raise ValueError("device wedged")
        except ValueError as err:
            path = rec.dump("crash", exc=err)
        payload = _read_json(path)
        assert payload["exception"]["type"] == "ValueError"
        assert payload["exception"]["message"] == "device wedged"
        assert any(
            "device wedged" in line
            for line in payload["exception"]["traceback"]
        )

    def test_event_dumps_capped(self, tmp_path, registry):
        rec = FlightRecorder(str(tmp_path), max_dumps=3, registry=registry)
        paths = [rec.dump("crash") for _ in range(6)]
        assert sum(p is not None for p in paths) == 3
        # the periodic persist is NOT capped (it overwrites one file)
        assert rec.dump("periodic") is not None
        assert rec.dump("periodic") is not None

    def test_periodic_overwrites_single_file(self, tmp_path, registry):
        rec = FlightRecorder(str(tmp_path), registry=registry)
        rec.note("first")
        p1 = rec.dump("periodic")
        rec.note("second")
        p2 = rec.dump("periodic")
        assert p1 == p2
        names = [e["name"] for e in _read_json(p1)["events"]]
        assert names == ["first", "second"]
        assert not [f for f in os.listdir(tmp_path) if ".tmp." in f]

    def test_is_a_tracer_sink(self, tmp_path, registry):
        rec = FlightRecorder(str(tmp_path), registry=registry)
        tracer.enable(rec)
        with tracer.span("unit.work", step=3):
            pass
        payload = _read_json(rec.dump("manual"))
        assert payload["spans"][0]["name"] == "unit.work"
        assert payload["spans"][0]["attributes"]["step"] == 3

    def test_background_persist_thread(self, tmp_path, registry):
        rec = FlightRecorder(
            str(tmp_path), interval_s=0.02, registry=registry,
        )
        registry.counter("serving.requests").add(1)
        rec.start()
        try:
            path = os.path.join(tmp_path, f"blackbox-{os.getpid()}.json")
            deadline = time.monotonic() + 10.0
            while not os.path.exists(path):
                if time.monotonic() > deadline:
                    pytest.fail("periodic persist never wrote")
                time.sleep(0.01)
        finally:
            rec.stop()
        payload = _read_json(path)
        assert payload["reason"] == "periodic"
        assert payload["metric_samples"]  # sampled before persisting

    def test_validation(self, tmp_path, registry):
        with pytest.raises(ValueError):
            FlightRecorder(str(tmp_path), interval_s=0, registry=registry)

    def test_module_api_noop_while_disarmed(self):
        from sparkdl_tpu.obs import blackbox

        assert blackbox.recorder() is None
        blackbox.note("ignored")          # must not raise
        assert blackbox.dump("ignored") is None


# ----------------------------------------------------------------------
# resilience layer crossings (cold-path, armed via the module global)
# ----------------------------------------------------------------------
class TestResilienceCrossings:
    @pytest.fixture()
    def armed(self, tmp_path, registry, monkeypatch):
        from sparkdl_tpu.obs import blackbox

        rec = FlightRecorder(str(tmp_path), registry=registry)
        monkeypatch.setattr(blackbox, "_recorder", rec)
        return rec, tmp_path

    def test_breaker_open_dumps(self, armed):
        from sparkdl_tpu.resilience.policy import CircuitBreaker

        rec, out_dir = armed
        breaker = CircuitBreaker(
            name="tunnel", failure_threshold=2, recovery_s=60.0,
        )
        breaker.record_failure()
        breaker.record_failure()  # trips open -> event dump
        dumps = [f for f in os.listdir(out_dir)
                 if "breaker_open_tunnel" in f]
        assert len(dumps) == 1
        payload = _read_json(os.path.join(out_dir, dumps[0]))
        names = [e["name"] for e in payload["events"]]
        assert "breaker_open_tunnel" in names

    def test_preempted_dumps(self, armed):
        from sparkdl_tpu.resilience.preempt import PreemptionToken
        from sparkdl_tpu.resilience.errors import Preempted

        rec, out_dir = armed
        token = PreemptionToken()
        token.request("maintenance event")
        with pytest.raises(Preempted):
            token.check()
        dumps = [f for f in os.listdir(out_dir) if "preempted" in f]
        assert len(dumps) == 1


# ----------------------------------------------------------------------
# subprocess post-mortems (the production contracts)
# ----------------------------------------------------------------------
_CRASH_WORKER = """
import sparkdl_tpu  # SPARKDL_BLACKBOX_DIR arms the recorder at import
from sparkdl_tpu.obs import blackbox

assert blackbox.recorder() is not None
blackbox.note("about_to_fail", step=42)
raise RuntimeError("unhandled worker crash")
"""

_KILL_WORKER = """
import sys
import time

import sparkdl_tpu  # SPARKDL_BLACKBOX_DIR arms the recorder at import
from sparkdl_tpu.obs import blackbox, tracer

rec = blackbox.recorder()
assert rec is not None
tracer.enable()  # enable_from_env added rec as a sink; spans now flow
with tracer.span("worker.step", step=1):
    pass
blackbox.note("worker_ready")
print("READY", flush=True)
while True:  # spin until SIGKILLed; the periodic persist keeps writing
    time.sleep(0.05)
"""


_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _worker_env(out_dir):
    env = dict(os.environ)
    env.update({
        "SPARKDL_BLACKBOX_DIR": str(out_dir),
        "SPARKDL_BLACKBOX_INTERVAL_S": "0.05",
        # keep the worker light: no jax import anywhere on this path
        "JAX_PLATFORMS": "cpu",
        "PYTHONPATH": _REPO + os.pathsep + env.get("PYTHONPATH", ""),
    })
    return env


class TestSubprocessPostMortems:
    def test_unhandled_crash_leaves_exception_dump(self, tmp_path):
        proc = subprocess.run(
            [sys.executable, "-c", _CRASH_WORKER],
            capture_output=True, text=True, timeout=120,
            env=_worker_env(tmp_path), cwd="/",
        )
        assert proc.returncode != 0
        assert "unhandled worker crash" in proc.stderr  # hook chained
        dumps = [f for f in os.listdir(tmp_path)
                 if "-crash-" in f and f.endswith(".json")]
        assert len(dumps) == 1
        payload = _read_json(os.path.join(tmp_path, dumps[0]))
        assert payload["reason"] == "crash"
        assert payload["exception"]["type"] == "RuntimeError"
        assert payload["exception"]["message"] == "unhandled worker crash"
        assert [e["name"] for e in payload["events"]] == ["about_to_fail"]
        assert payload["events"][0]["step"] == 42

    def test_sigkill_leaves_readable_periodic_dump(self, tmp_path):
        # the ISSUE-8 acceptance scenario: kill -9 a worker mid-flight;
        # the periodic atomic persist must leave a parseable dump with
        # the final spans and thread stacks
        proc = subprocess.Popen(
            [sys.executable, "-c", _KILL_WORKER],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            env=_worker_env(tmp_path), cwd="/",
        )
        try:
            assert proc.stdout.readline().strip() == "READY"
            path = os.path.join(tmp_path, f"blackbox-{proc.pid}.json")
            deadline = time.monotonic() + 60.0
            while True:  # wait for a persist that includes the span
                if os.path.exists(path):
                    try:
                        if _read_json(path)["spans"]:
                            break
                    except (json.JSONDecodeError, KeyError):
                        pytest.fail("periodic dump was torn mid-write")
                if time.monotonic() > deadline:
                    pytest.fail("worker never persisted its telemetry")
                time.sleep(0.02)
            os.kill(proc.pid, signal.SIGKILL)
        finally:
            proc.kill()
            proc.wait(timeout=30)
        assert proc.returncode == -signal.SIGKILL
        payload = _read_json(path)  # still parseable after the kill
        assert payload["reason"] == "periodic"
        assert [s["name"] for s in payload["spans"]] == ["worker.step"]
        assert any(e["name"] == "worker_ready"
                   for e in payload["events"])
        assert any("MainThread" in name for name in payload["threads"])
        # the faulthandler fault file was armed alongside
        assert os.path.exists(
            os.path.join(tmp_path, f"fault-{proc.pid}.txt")
        )
