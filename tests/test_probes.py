"""The bounded liveness probe (``utils/probes.py``) and the opt-in
profiler wrapper (``utils/profiler.py``) — the two observability
helpers older than ``sparkdl_tpu/obs`` that the subsystem builds on.

The probe turns a wedged-tunnel infinite hang into a bounded loud
failure; these tests pin each of its three exits (success, nonzero,
timeout) plus the diagnostic-truncation contract.  The profiler tests
pin the no-env no-op and the first-entrant-wins reentrancy rule —
without importing jax (``maybe_trace`` must stay cheap to call from
the hot loop when profiling is off).
"""

import os

import pytest

from sparkdl_tpu.utils import profiler
from sparkdl_tpu.utils.probes import bounded_subprocess_probe


class TestBoundedSubprocessProbe:
    def test_success_returns_stdout(self):
        ok, msg = bounded_subprocess_probe(
            "print('alive on 8 devices')", timeout_s=60
        )
        assert ok
        assert msg == "alive on 8 devices"

    def test_failure_returns_stderr_diagnostic(self):
        ok, msg = bounded_subprocess_probe(
            "raise RuntimeError('no backend: relay refused')", timeout_s=60
        )
        assert not ok
        assert "no backend: relay refused" in msg

    def test_failure_prefers_stderr_but_falls_back_to_stdout(self):
        ok, msg = bounded_subprocess_probe(
            "import sys; print('detail on stdout'); sys.exit(3)",
            timeout_s=60,
        )
        assert not ok
        assert "detail on stdout" in msg

    def test_hang_is_bounded_and_says_so(self):
        ok, msg = bounded_subprocess_probe(
            "import time; time.sleep(60)", timeout_s=1
        )
        assert not ok
        assert "probe hung > 1s" in msg

    def test_diagnostic_is_truncated_to_tail(self):
        # a crashing probe can dump pages; callers embed the message in
        # status()/bench JSON so it is capped at the last 200 chars
        ok, msg = bounded_subprocess_probe(
            "raise RuntimeError('x' * 2000)", timeout_s=60
        )
        assert not ok
        assert len(msg) <= 200

    def test_probe_is_importable_without_jax(self):
        """The probe must run before any in-process device init — a jax
        import at probe time could itself wedge."""
        ok, msg = bounded_subprocess_probe(
            "import sys\n"
            "import sparkdl_tpu.utils.probes\n"
            "assert 'jax' not in sys.modules, 'probes.py imported jax'\n"
            "print('jax-free')",
            timeout_s=120,
        )
        assert ok, msg
        assert msg == "jax-free"


class TestProfiler:
    def test_maybe_trace_is_noop_without_env(self, monkeypatch):
        monkeypatch.delenv("SPARKDL_PROFILE_DIR", raising=False)
        with profiler.maybe_trace():
            pass  # nullcontext: no jax import, no capture dir

    def test_maybe_trace_env_selects_dir(self, monkeypatch):
        # don't start a real capture — just pin the routing decision
        captured = {}

        def fake_trace(log_dir):
            captured["dir"] = log_dir
            from contextlib import nullcontext
            return nullcontext()

        monkeypatch.setattr(profiler, "trace", fake_trace)
        monkeypatch.setenv("SPARKDL_PROFILE_DIR", "/tmp/prof-here")
        with profiler.maybe_trace():
            pass
        assert captured["dir"] == "/tmp/prof-here"
        # explicit argument beats the env var
        with profiler.maybe_trace("/tmp/explicit"):
            pass
        assert captured["dir"] == "/tmp/explicit"

    def test_trace_reentrancy_degrades_to_noop(self, tmp_path):
        """Only one jax profiler capture may exist per process: the
        first entrant wins, nested entry runs untraced, and the flag
        resets so a later capture can start."""
        import jax  # noqa: F401  (profiler.trace imports it lazily)

        with profiler.trace(str(tmp_path / "a")):
            assert profiler._trace_active
            with profiler.trace(str(tmp_path / "b")):
                pass  # no-op, must not raise
            assert profiler._trace_active
        assert not profiler._trace_active
        # the lock released: a fresh capture is allowed again
        with profiler.trace(str(tmp_path / "c")):
            assert profiler._trace_active
        assert not profiler._trace_active
        assert os.path.isdir(tmp_path / "a")

    def test_annotate_inside_trace(self, tmp_path):
        with profiler.trace(str(tmp_path / "t")):
            with profiler.annotate("decode_batch"):
                pass
