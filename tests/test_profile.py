"""Sampling profiler (``obs/profile.py``): folded-stack aggregation,
self-exclusion, bounded memory, the window helper, and env arming.

Tests drive :meth:`StackProfiler.sample_once` directly wherever
possible — no background thread, no timing assumptions; the few
thread-lifecycle tests use generous waits on real sleeps.
"""

import threading
import time

import pytest

from sparkdl_tpu.obs import profile
from sparkdl_tpu.obs.profile import StackProfiler, profile_for
from sparkdl_tpu.utils.metrics import metrics


@pytest.fixture(autouse=True)
def clean_registry():
    metrics.reset()
    yield
    metrics.reset()


@pytest.fixture(autouse=True)
def unarm_profiler():
    """Tests must not leak an armed process-wide profiler."""
    yield
    if profile._profiler is not None:
        profile._profiler.stop()
        profile._profiler = None


class TestFold:
    def test_fold_is_root_first_basenames(self):
        import sys
        frame = sys._getframe()
        folded = profile._fold(frame)
        parts = folded.split(";")
        # leaf-most frame is THIS function, rendered file:function
        assert parts[-1].startswith("test_profile.py:")
        assert parts[-1].endswith("test_fold_is_root_first_basenames")

    def test_fold_depth_bounded(self):
        def recurse(n):
            if n == 0:
                import sys
                return profile._fold(sys._getframe(), depth=5)
            return recurse(n - 1)

        assert len(recurse(50).split(";")) == 5


class TestSampleOnce:
    def test_sample_once_counts_live_threads(self):
        p = StackProfiler()
        n = p.sample_once()
        assert n >= 1  # at least the calling thread
        snap = p.snapshot()
        assert snap["samples"] == n
        assert snap["unique_stacks"] >= 1

    def test_excluded_idents_skipped(self):
        marker = "test_profile.py:test_excluded_idents_skipped"
        p = StackProfiler(exclude_idents=(threading.get_ident(),))
        p.sample_once()
        assert all(marker not in s for s in p.folded())
        q = StackProfiler()
        q.sample_once()
        assert any(marker in s for s in q.folded())

    def test_unique_stacks_bounded(self):
        p = StackProfiler(max_stacks=1)
        p._stacks["existing"] = 1
        p._samples = 1
        p.sample_once()  # every new stack must drop, not grow
        snap = p.snapshot()
        assert snap["unique_stacks"] == 1
        assert snap["dropped_stacks"] >= 1

    def test_folded_text_ranked_and_capped(self):
        p = StackProfiler()
        p._stacks.update({"hot": 10, "warm": 5, "cold": 1})
        p._samples = 16
        lines = p.folded_text(top=2).splitlines()
        assert lines == ["hot 10", "warm 5"]

    def test_snapshot_shares_sum_to_one(self):
        p = StackProfiler()
        p._stacks.update({"a": 3, "b": 1})
        p._samples = 4
        top = p.snapshot()["top"]
        assert sum(row["share"] for row in top) == pytest.approx(1.0)


class TestLifecycle:
    def test_start_stop_idempotent_and_self_excluding(self):
        p = StackProfiler(interval_s=0.002)
        p.start()
        p.start()  # no second thread
        assert p.running
        deadline = time.monotonic() + 5.0
        while p.snapshot()["samples"] == 0 \
                and time.monotonic() < deadline:
            time.sleep(0.01)
        p.stop()
        p.stop()
        assert not p.running
        snap = p.snapshot()
        assert snap["samples"] > 0
        # the sampler never samples itself: its own _run stack would
        # end in sample_once/_run from profile.py
        assert all(
            "profile.py:_run" not in row["stack"]
            for row in snap["top"]
        )
        # the aggregate survives stop for reading
        assert p.folded()

    def test_reset_clears_aggregate(self):
        p = StackProfiler()
        p.sample_once()
        p.reset()
        snap = p.snapshot()
        assert snap["samples"] == 0
        assert snap["unique_stacks"] == 0

    def test_metrics_move(self):
        p = StackProfiler()
        p.sample_once()
        assert metrics.snapshot()["profile.samples"] >= 1

    def test_invalid_args_raise(self):
        with pytest.raises(ValueError):
            StackProfiler(interval_s=0.0)
        with pytest.raises(ValueError):
            StackProfiler(max_stacks=0)


class TestProfileFor:
    def test_window_excludes_the_waiter(self):
        snap = profile_for(0.05, interval_s=0.005)
        assert not snap["running"]
        assert snap["duration_s"] >= 0.04
        # the calling thread only sleeps out the window; it must not
        # dominate the profile (it is excluded entirely)
        assert all(
            "test_profile.py:test_window_excludes_the_waiter"
            not in row["stack"]
            for row in snap["top"]
        )

    def test_rejects_nonpositive_window(self):
        with pytest.raises(ValueError):
            profile_for(0.0)


class TestEnvArming:
    def test_unset_env_leaves_unarmed(self, monkeypatch):
        monkeypatch.delenv(profile.ENV_PROFILE, raising=False)
        assert profile.enable_from_env() is None
        assert profile.profiler() is None

    @pytest.mark.parametrize("off", ["0", "off", "false"])
    def test_off_values_leave_unarmed(self, monkeypatch, off):
        monkeypatch.setenv(profile.ENV_PROFILE, off)
        assert profile.enable_from_env() is None

    def test_on_arms_default_period(self, monkeypatch):
        monkeypatch.setenv(profile.ENV_PROFILE, "1")
        p = profile.enable_from_env()
        assert p is not None and p.running
        assert p.interval_s == profile.DEFAULT_INTERVAL_S
        # idempotent: a second call returns the same armed instance
        assert profile.enable_from_env() is p

    def test_numeric_value_is_period_in_ms(self, monkeypatch):
        monkeypatch.setenv(profile.ENV_PROFILE, "50")
        p = profile.enable_from_env()
        assert p.interval_s == pytest.approx(0.050)

    def test_period_floor_one_ms(self, monkeypatch):
        monkeypatch.setenv(profile.ENV_PROFILE, "0.01")
        p = profile.enable_from_env()
        assert p.interval_s == pytest.approx(0.001)
