"""ViT pretrained-weight ingestion oracle tests (VERDICT r2 missing #2).

Same pattern as the CNN zoo's Keras-weight oracle (``tests/test_models.py``,
the ``keras_applications.py``† weights contract): port an independent
implementation's weights, run our Flax model on the same inputs, require
numerically equal outputs.  The independent source here is HuggingFace
``transformers``' torch ViT (random-init — no network; the mapping, not the
values, is what's under test), plus a round-trip through the
google-research ``.npz`` checkpoint naming (the artifact format actually
published for ViT).
"""

import numpy as np
import pytest

import jax

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")

from sparkdl_tpu.models.vit import VIT_VARIANTS, ViT  # noqa: E402
from sparkdl_tpu.models.vit_port import (  # noqa: E402
    export_vit_npz,
    port_hf_vit,
    port_vit_npz,
)

# tiny geometry: (patch, dim, depth, heads, mlp_dim), 32x32 input -> 5 tokens
TEST_GEOM = (16, 64, 2, 2, 128)


@pytest.fixture()
def tiny_variant():
    VIT_VARIANTS["ViT-Test"] = TEST_GEOM
    yield "ViT-Test"
    del VIT_VARIANTS["ViT-Test"]


def _hf_model(num_labels=5, with_head=True):
    patch, dim, depth, heads, mlp = TEST_GEOM
    cfg = transformers.ViTConfig(
        hidden_size=dim,
        num_hidden_layers=depth,
        num_attention_heads=heads,
        intermediate_size=mlp,
        image_size=32,
        patch_size=patch,
        num_labels=num_labels,
        layer_norm_eps=1e-6,  # match flax nn.LayerNorm's epsilon
    )
    torch.manual_seed(0)
    cls = (
        transformers.ViTForImageClassification
        if with_head
        else transformers.ViTModel
    )
    return cls(cfg).eval()


def test_hf_port_logits_match_torch_oracle(tiny_variant):
    """Ported HF weights through our ViT == the torch forward, to float32
    tolerance (exact_gelu matches HF's erf gelu)."""
    hf = _hf_model()
    variables = port_hf_vit(hf)

    rng = np.random.RandomState(0)
    x = rng.randn(3, 32, 32, 3).astype(np.float32)

    module = ViT(
        variant=tiny_variant, num_classes=5, image_size=32, exact_gelu=True
    )
    # CPU XLA convs default to a reduced-precision algorithm (~5e-3 error
    # vs a float64 oracle); pin full f32 for the comparison
    with jax.default_matmul_precision("float32"):
        got = np.asarray(module.apply(variables, x))

    with torch.no_grad():
        want = hf(
            torch.from_numpy(x.transpose(0, 3, 1, 2))
        ).logits.numpy()
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


def test_hf_port_features_match_headless_model(tiny_variant):
    """ViTModel (no classifier) ports too; features_only output equals the
    torch CLS embedding after final layernorm."""
    hf = _hf_model(with_head=False)
    variables = port_hf_vit(hf)
    assert "head" not in variables["params"]

    rng = np.random.RandomState(1)
    x = rng.randn(2, 32, 32, 3).astype(np.float32)
    module = ViT(
        variant=tiny_variant, include_top=False, image_size=32,
        exact_gelu=True,
    )
    with jax.default_matmul_precision("float32"):
        got = np.asarray(module.apply(variables, x, features_only=True))
    with torch.no_grad():
        out = hf(torch.from_numpy(x.transpose(0, 3, 1, 2)))
    want = out.last_hidden_state[:, 0].numpy()
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


def test_npz_roundtrip_identity(tiny_variant, tmp_path):
    """export_vit_npz -> port_vit_npz reproduces the exact tree (the
    offline stand-in for ingesting a downloaded ViT-B_16.npz)."""
    module = ViT(variant=tiny_variant, num_classes=5, image_size=32)
    variables = module.init(
        jax.random.PRNGKey(0), np.zeros((1, 32, 32, 3), np.float32)
    )
    path = str(tmp_path / "vit_test.npz")
    export_vit_npz(variables, path, heads=TEST_GEOM[3])
    restored = port_vit_npz(path)

    flat_a = jax.tree_util.tree_leaves_with_path(variables)
    flat_b = jax.tree_util.tree_leaves_with_path(restored)
    assert len(flat_a) == len(flat_b)
    b_map = {jax.tree_util.keystr(k): v for k, v in flat_b}
    for k, va in flat_a:
        vb = b_map[jax.tree_util.keystr(k)]
        np.testing.assert_array_equal(np.asarray(va), np.asarray(vb), err_msg=str(k))


def test_npz_port_runs_hf_oracle(tiny_variant, tmp_path):
    """HF weights -> export npz -> port npz -> logits still equal torch:
    the full artifact path a user takes (download .npz, load, serve)."""
    hf = _hf_model()
    variables = port_hf_vit(hf)
    path = str(tmp_path / "vit_hf.npz")
    export_vit_npz(variables, path, heads=TEST_GEOM[3])
    restored = port_vit_npz(path)

    rng = np.random.RandomState(2)
    x = rng.randn(2, 32, 32, 3).astype(np.float32)
    module = ViT(
        variant=tiny_variant, num_classes=5, image_size=32, exact_gelu=True
    )
    with jax.default_matmul_precision("float32"):
        got = np.asarray(module.apply(restored, x))
    with torch.no_grad():
        want = hf(torch.from_numpy(x.transpose(0, 3, 1, 2))).logits.numpy()
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


def test_npz_rejects_pre_logits(tmp_path):
    path = str(tmp_path / "bad.npz")
    np.savez(
        path,
        **{
            "pre_logits/kernel": np.zeros((4, 4), np.float32),
            "embedding/kernel": np.zeros((16, 16, 3, 4), np.float32),
        },
    )
    with pytest.raises(ValueError, match="pre_logits"):
        port_vit_npz(path)


def test_ported_weights_finetune_in_estimator(tiny_variant, tmp_path):
    """The stretch-config wiring: ported ViT weights feed
    FlaxImageFileEstimator via initialVariables and the fitted transformer
    starts from them (not random init)."""
    from PIL import Image

    from sparkdl_tpu.estimators.flax_image_file_estimator import (
        FlaxImageFileEstimator,
    )
    from sparkdl_tpu.sql.session import TPUSession

    hf = _hf_model(num_labels=2)
    variables = port_hf_vit(hf)

    rng = np.random.RandomState(0)
    uris = []
    for i in range(8):
        p = str(tmp_path / f"im_{i}.png")
        Image.fromarray(
            (rng.rand(32, 32, 3) * 255).astype(np.uint8)
        ).save(p)
        uris.append(p)

    spark = TPUSession.builder.getOrCreate()
    df = spark.createDataFrame(
        [{"uri": u, "label": i % 2} for i, u in enumerate(uris)]
    )

    def loader(u):
        return np.asarray(Image.open(u), np.float32) / 255.0

    module = ViT(
        variant=tiny_variant, num_classes=2, image_size=32, exact_gelu=True
    )
    est = FlaxImageFileEstimator(
        inputCol="uri",
        outputCol="out",
        labelCol="label",
        imageLoader=loader,
        module=module,
        optimizer="sgd",
        fitParams={"epochs": 1, "batch_size": 8, "learning_rate": 0.0},
        initialVariables=variables,
    )
    fitted = est.fit(df)
    # lr=0: the "fine-tuned" params must BE the ported pretrained params
    out_rows = fitted.transform(df).collect()
    x = np.stack([loader(u) for u in uris])
    want = np.asarray(module.apply(variables, x))
    # (transform and oracle share jax's default precision here, so no pin)
    got_arr = np.stack([np.asarray(r.out.toArray()) for r in out_rows])
    np.testing.assert_allclose(got_arr, want, rtol=1e-4, atol=1e-5)


def test_adapt_vit_variables_geometry_and_head(tiny_variant):
    """The real-checkpoint fine-tune surgeries: pos-embed grid
    interpolation to a new resolution (CLS slot untouched) and head
    replacement for a new label set."""
    from sparkdl_tpu.models.vit_port import adapt_vit_variables

    # "pretrained" at 64² (4x4 grid + CLS = 17 tokens), 1000-way head
    module64 = ViT(variant=tiny_variant, num_classes=1000, image_size=64)
    variables = module64.init(
        jax.random.PRNGKey(0), np.zeros((1, 64, 64, 3), np.float32)
    )

    adapted = adapt_vit_variables(variables, image_size=32, num_classes=2)
    p = adapted["params"]
    assert p["pos_embed"].shape == (1, 5, 64)  # 2x2 grid + CLS
    # CLS slot passes through exactly
    np.testing.assert_array_equal(
        np.asarray(p["pos_embed"][:, 0]),
        np.asarray(variables["params"]["pos_embed"][:, 0]),
    )
    # grid interpolation oracle
    src = variables["params"]["pos_embed"][:, 1:].reshape(1, 4, 4, 64)
    want = jax.image.resize(src, (1, 2, 2, 64), method="bilinear")
    np.testing.assert_allclose(
        np.asarray(p["pos_embed"][:, 1:]),
        np.asarray(want.reshape(1, 4, 64)),
        rtol=1e-6,
    )
    assert p["head"]["kernel"].shape == (64, 2)

    # the adapted tree runs in the target-geometry model
    module32 = ViT(variant=tiny_variant, num_classes=2, image_size=32)
    out = module32.apply(adapted, np.zeros((2, 32, 32, 3), np.float32))
    assert out.shape == (2, 2)

    # same geometry + same head width -> pure pass-through
    same = adapt_vit_variables(variables, image_size=64, num_classes=1000)
    np.testing.assert_array_equal(
        np.asarray(same["params"]["pos_embed"]),
        np.asarray(variables["params"]["pos_embed"]),
    )
    assert same["params"]["head"] is variables["params"]["head"]

    with pytest.raises(ValueError, match="not a multiple"):
        adapt_vit_variables(variables, image_size=30)


def test_sql_kleene_handles_numpy_bools(tpu_session):
    """Comparisons over numpy scalars yield np.True_/np.False_; the 3VL
    combinators must treat them as booleans (identity checks on Python
    True/False do not)."""
    from sparkdl_tpu.sql.functions import col

    data = [
        {"id": 1, "score": np.float64(5.0), "lbl": None},
        {"id": 2, "score": np.float64(1.0), "lbl": 1},
    ]
    df = tpu_session.createDataFrame(data)
    kept = df.filter((col("score") > 3) | (col("lbl") == 1)).collect()
    assert sorted(r.id for r in kept) == [1, 2]
