"""The CI static-analysis gate: one ``ci/sparkdl_check`` run covers the
whole repo (every rule, one AST parse per file), and each legacy lint
shim (``ci/lint_no_sleep_retry.py``, ``ci/lint_metric_names.py``,
``ci/lint_no_raw_jit.py``) still catches what it claims to.  Running
them here puts the gates in tier-1 — a blocking call under a lock, an
implicit device→host sync on a hot path, a hand-rolled retry loop, or a
bare ``jax.jit`` fails the suite, not just the CI workflow step."""

import json
import os
import subprocess
import sys
import textwrap

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_LINT = os.path.join(_REPO, "ci", "lint_no_sleep_retry.py")
_NAME_LINT = os.path.join(_REPO, "ci", "lint_metric_names.py")
_JIT_LINT = os.path.join(_REPO, "ci", "lint_no_raw_jit.py")


def run_lint(root, lint=_LINT):
    return subprocess.run(
        [sys.executable, lint, str(root)],
        capture_output=True,
        text=True,
        timeout=120,
    )


def test_repo_passes_sparkdl_check():
    """THE tier-1 static-analysis gate: all nine rules over
    ``sparkdl_tpu/`` in one framework run, failing on any finding that
    is neither suppressed inline nor grandfathered in the baseline (and
    on stale baseline entries)."""
    proc = subprocess.run(
        [sys.executable, "-m", "ci.sparkdl_check", "sparkdl_tpu/",
         "--format", "json"],
        capture_output=True, text=True, timeout=120, cwd=_REPO,
    )
    assert proc.returncode == 0, (
        f"sparkdl_check failed:\n{proc.stdout}{proc.stderr}"
    )
    doc = json.loads(proc.stdout)
    assert doc["findings"] == []
    assert doc["stale_baseline"] == []
    assert len(doc["rules"]) >= 8  # 5 new analyzers + 3 migrated lints


def test_lint_flags_planted_violation(tmp_path):
    pkg = tmp_path / "sparkdl_tpu"
    pkg.mkdir()
    (pkg / "bad.py").write_text(
        textwrap.dedent(
            """
            import time

            def poll(fn):
                while True:
                    try:
                        return fn()
                    except Exception:
                        time.sleep(1.0)
            """
        )
    )
    # an aliased import must not dodge the lint
    (pkg / "sneaky.py").write_text(
        textwrap.dedent(
            """
            from time import sleep as snooze

            def poll(items):
                for _ in items:
                    snooze(0.5)
            """
        )
    )
    # sanctioned home: same code inside resilience/ is NOT flagged
    home = pkg / "resilience"
    home.mkdir()
    (home / "policy.py").write_text(
        "import time\nwhile False:\n    time.sleep(1)\n"
    )
    # a sleep NOT in a loop is fine anywhere
    (pkg / "ok.py").write_text("import time\ntime.sleep(0)\n")

    proc = run_lint(tmp_path)
    assert proc.returncode == 1
    assert "bad.py:" in proc.stdout
    assert "sneaky.py:" in proc.stdout
    assert "resilience/policy.py" not in proc.stdout
    assert "ok.py" not in proc.stdout
    assert "RetryPolicy" in proc.stdout  # the diagnostic names the fix


def test_metric_name_lint_flags_planted_violations(tmp_path):
    pkg = tmp_path / "sparkdl_tpu"
    pkg.mkdir()
    (pkg / "bad.py").write_text(
        textwrap.dedent(
            """
            from sparkdl_tpu.utils.metrics import metrics

            metrics.counter("batches").add()          # no subsystem prefix
            metrics.gauge("Serving.Depth").set(1)     # uppercase
            metrics.timer("kernels.fuse")             # unknown subsystem
            metrics.histogram(f"{kind}.latency_ms")   # fully dynamic
            """
        )
    )
    (pkg / "ok.py").write_text(
        textwrap.dedent(
            """
            from sparkdl_tpu.utils.metrics import metrics

            metrics.counter("serving.requests").add()
            metrics.gauge(f"resilience.breaker_state.{name}").set(0)
            metrics.histogram("data.device_stall_ms", window=128)
            other.counter("NotAMetric")  # different receiver: not checked
            """
        )
    )

    proc = run_lint(tmp_path, lint=_NAME_LINT)
    assert proc.returncode == 1
    out = proc.stdout
    assert out.count("bad.py:") == 4
    assert "ok.py" not in out
    assert "subsystem prefix" in out  # the diagnostic names the fix


def test_raw_jit_lint_flags_planted_violations(tmp_path):
    pkg = tmp_path / "sparkdl_tpu"
    checked = pkg / "transformers"
    checked.mkdir(parents=True)
    (checked / "bad.py").write_text(
        textwrap.dedent(
            """
            import jax

            def build(forward):
                fitted = jax.jit(forward, donate_argnums=(0,))  # call
                alias = jax.jit                                 # aliasing
                return fitted, alias

            @jax.jit
            def decorated(x):
                return x
            """
        )
    )
    # 'from jax import jit' is the same bare jit in disguise
    (checked / "sneaky.py").write_text(
        textwrap.dedent(
            """
            from jax import jit as _j

            def build(forward):
                return _j(forward)
            """
        )
    )
    # the engine itself is the sanctioned caller — not scanned
    home = pkg / "engine"
    home.mkdir()
    (home / "core.py").write_text(
        "import jax\njitted = jax.jit(lambda x: x)\n"
    )
    # unchecked packages (estimators/) are out of scope for now
    other = pkg / "estimators"
    other.mkdir()
    (other / "est.py").write_text(
        "import jax\njitted = jax.jit(lambda x: x)\n"
    )
    # strings/comments and engine-routed code in a checked package: clean
    (checked / "ok.py").write_text(
        textwrap.dedent(
            """
            from sparkdl_tpu.engine import engine

            # jax.jit is forbidden here; see ci/lint_no_raw_jit.py
            DOC = "replaces jax.jit with engine.function"

            def build(forward):
                return engine.function(forward, name="ok")
            """
        )
    )

    proc = run_lint(tmp_path, lint=_JIT_LINT)
    assert proc.returncode == 1
    out = proc.stdout
    assert out.count("bad.py:") == 3
    assert "sneaky.py:" in out
    assert "engine/core.py" not in out
    assert "estimators/est.py" not in out
    assert "ok.py" not in out
    assert "engine.function" in out  # the diagnostic names the fix
