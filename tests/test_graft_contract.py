"""Driver-contract tests for ``__graft_entry__.dryrun_multichip``.

Round-1 failure mode (VERDICT.md Missing #1): the driver called
``dryrun_multichip(8)`` from a process whose default jax backend was already
initialized (and broken), and the in-process CPU fallback came too late —
arrays still landed on the default device.  These tests run the dryrun from
subprocesses that deliberately do NOT have conftest's forced-CPU virtual
8-device environment, so a regression in the subprocess isolation fails here
rather than only in the driver.
"""

import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _clean_env(**extra):
    env = dict(os.environ)
    # strip conftest's forcing so the child sees a "driver-like" world
    env.pop("XLA_FLAGS", None)
    env.pop("JAX_PLATFORMS", None)
    env.update(extra)
    return env


def _run(code, env):
    return subprocess.run(
        [sys.executable, "-c", code],
        cwd=REPO,
        env=env,
        capture_output=True,
        text=True,
        timeout=900,
    )


def test_dryrun_after_backend_already_initialized():
    """The exact round-1 trap: the calling process initializes a 1-device
    backend *before* calling dryrun_multichip(8). Must still pass."""
    code = (
        "import jax; "
        "jax.config.update('jax_platforms', 'cpu'); "
        "assert len(jax.devices()) == 1, jax.devices(); "
        "import __graft_entry__ as g; "
        "g.dryrun_multichip(8); "
        "print('CONTRACT-OK')"
    )
    proc = _run(code, _clean_env(JAX_PLATFORMS="cpu"))
    assert proc.returncode == 0, proc.stderr[-4000:]
    assert "CONTRACT-OK" in proc.stdout
    assert "dryrun_multichip OK" in proc.stdout


def test_dryrun_with_default_platform_env():
    """Driver-shaped call: whatever JAX_PLATFORMS the outer env carries
    (axon/tpu in production), dryrun_multichip must not touch that backend —
    the subprocess forces CPU before any jax init."""
    code = (
        "import __graft_entry__ as g; "
        "g.dryrun_multichip(8); "
        "print('CONTRACT-OK')"
    )
    proc = _run(code, _clean_env())
    assert proc.returncode == 0, proc.stderr[-4000:]
    assert "CONTRACT-OK" in proc.stdout


def test_dryrun_respects_requested_device_count():
    code = (
        "import __graft_entry__ as g; "
        "g.dryrun_multichip(4); "
        "print('CONTRACT-OK')"
    )
    proc = _run(code, _clean_env())
    assert proc.returncode == 0, proc.stderr[-4000:]
    assert "4-device mesh" in proc.stdout
