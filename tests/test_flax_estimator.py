"""FlaxImageFileEstimator: the ViT fine-tune config over the estimator API
(SURVEY.md §7 step 8).  8-device CPU mesh; tiny ViT geometry."""

import numpy as np
import pytest

# Sharded ViT fine-tunes compile for minutes on the 8-device CPU mesh;
# keep the whole module out of the quick tier (deselect with -m 'not slow').
pytestmark = pytest.mark.slow

from sparkdl_tpu.estimators import (
    FlaxImageFileEstimator,
    FlaxImageFileTransformer,
)
from sparkdl_tpu.models.vit import ViT
from sparkdl_tpu.parallel.tp import VIT_TP_RULES

IMG = 16
N = 24


@pytest.fixture()
def vector_dataset(tpu_session, tmp_path):
    """Learnable toy task: label = brightest quadrant."""
    rng = np.random.RandomState(0)
    rows = []
    for i in range(N):
        img = rng.rand(IMG, IMG, 3).astype(np.float32) * 0.2
        label = i % 2
        if label:
            img[:8, :8] += 0.7
        else:
            img[8:, 8:] += 0.7
        path = str(tmp_path / f"v{i}.npy")
        np.save(path, img)
        rows.append({"uri": path, "label": label})
    return tpu_session.createDataFrame(rows)


def _loader(uri):
    return np.load(uri)


def _estimator(**kw):
    kw.setdefault(
        "fitParams",
        {"epochs": 6, "batch_size": 16, "learning_rate": 1e-3, "seed": 0},
    )
    return FlaxImageFileEstimator(
        inputCol="uri",
        outputCol="out",
        labelCol="label",
        imageLoader=_loader,
        module=ViT(variant="ViT-Ti/16", num_classes=2, image_size=IMG),
        optimizer="adam",
        **kw,
    )


def test_vit_finetune_dp(vector_dataset):
    model = _estimator().fit(vector_dataset)
    assert isinstance(model, FlaxImageFileTransformer)
    assert np.isfinite(model._training_loss)
    out = model.transform(vector_dataset).collect()
    assert len(out) == N and len(out[0]["out"]) == 2
    # the fitted transform actually separates the two classes
    preds = [int(np.argmax(r["out"])) for r in out]
    labels = [r["label"] for r in out]
    acc = np.mean([p == l for p, l in zip(preds, labels)])
    assert acc >= 0.75, f"fine-tune did not learn (acc={acc})"


def test_vit_finetune_tp_matches_dp_loss(vector_dataset):
    """Same data/seed trained DP vs DP x TP (GSPMD Megatron rules): the
    final loss must agree — sharding is an execution detail, not math."""
    dp = _estimator().fit(vector_dataset)
    tp = _estimator(shardingRules=VIT_TP_RULES).fit(vector_dataset)
    np.testing.assert_allclose(
        tp._training_loss, dp._training_loss, rtol=5e-3, atol=5e-4
    )


def test_flax_estimator_with_flash_attention(vector_dataset):
    """FlaxImageFileEstimator fine-tunes a ViT whose attention runs
    through the Pallas flash kernel — the DP training step differentiates
    the custom VJP end-to-end."""
    from sparkdl_tpu.ops import flash_attention

    est = FlaxImageFileEstimator(
        inputCol="uri",
        outputCol="out",
        labelCol="label",
        imageLoader=_loader,
        module=ViT(variant="ViT-Ti/16", num_classes=2, image_size=IMG,
                   attn_impl=flash_attention),
        fitParams={"epochs": 1, "batch_size": 16},
    )
    model = est.fit(vector_dataset)
    assert isinstance(model, FlaxImageFileTransformer)
    assert np.isfinite(model._training_loss)


class TestFlaxCheckpointing:
    """Orbax checkpoint/resume for the Flax estimator (same contract as
    the Keras one: per-config namespace without epochs, async commits,
    epoch-capped restore, rng replay)."""

    def _fit_params(self, epochs):
        return {"epochs": epochs, "batch_size": 16, "learning_rate": 1e-3,
                "seed": 0}

    def test_refit_with_more_epochs_resumes_exactly(
        self, vector_dataset, tmp_path
    ):
        import os

        ck = str(tmp_path / "flax_ck")
        est2 = _estimator(fitParams=self._fit_params(2), checkpointDir=ck)
        est2.fit(vector_dataset)
        (ns,) = os.listdir(ck)
        assert sorted(os.listdir(os.path.join(ck, ns))) == [
            "epoch_1", "epoch_2"
        ]

        est4 = _estimator(fitParams=self._fit_params(4), checkpointDir=ck)
        resumed = est4.fit(vector_dataset)
        (ns2,) = os.listdir(ck)
        assert ns2 == ns  # extended in place, not a fresh namespace
        assert sorted(os.listdir(os.path.join(ck, ns))) == [
            "epoch_1", "epoch_2", "epoch_3", "epoch_4"
        ]

        straight = _estimator(fitParams=self._fit_params(4)).fit(
            vector_dataset
        )
        import jax

        for (ka, a), (kb, b) in zip(
            jax.tree_util.tree_leaves_with_path(resumed.variables),
            jax.tree_util.tree_leaves_with_path(straight.variables),
        ):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6,
                err_msg=str(ka),
            )

    def test_tp_checkpoint_roundtrip(self, vector_dataset, tmp_path):
        """GSPMD DP x TP state checkpoints and restores onto its
        shardings; resumed result equals the uninterrupted TP fit."""
        ck = str(tmp_path / "flax_tp_ck")
        kw = dict(shardingRules=VIT_TP_RULES, meshShape=(2, 4))
        est1 = _estimator(
            fitParams=self._fit_params(1), checkpointDir=ck, **kw
        )
        est1.fit(vector_dataset)
        est3 = _estimator(
            fitParams=self._fit_params(3), checkpointDir=ck, **kw
        )
        resumed = est3.fit(vector_dataset)
        straight = _estimator(fitParams=self._fit_params(3), **kw).fit(
            vector_dataset
        )
        import jax

        for (ka, a), (kb, b) in zip(
            jax.tree_util.tree_leaves_with_path(resumed.variables),
            jax.tree_util.tree_leaves_with_path(straight.variables),
        ):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6,
                err_msg=str(ka),
            )

    def test_namespace_stable_across_equal_objects(self):
        """Equal-but-distinct callable/optimizer objects hash to the SAME
        namespace: plain ``repr`` embeds ``at 0x...`` addresses, which
        change per process and would silently fork a fresh namespace on
        every re-fit instead of resuming (ADVICE r3)."""
        import optax

        from sparkdl_tpu.ops import flash_attention

        def make(opt):
            return FlaxImageFileEstimator(
                inputCol="uri", outputCol="out", labelCol="label",
                imageLoader=_loader,
                module=ViT(variant="ViT-Ti/16", num_classes=2,
                           image_size=IMG, attn_impl=flash_attention),
                optimizer=opt,
                fitParams=self._fit_params(2),
            )

        # two separate optax.adam calls build distinct closure objects at
        # distinct addresses — the config is identical
        a, b = make(optax.adam(1e-3)), make(optax.adam(1e-3))
        assert a._ckpt_namespace() == b._ckpt_namespace()
        # and a genuinely different optimizer still separates
        c = make(optax.sgd(1e-3))
        assert c._ckpt_namespace() != a._ckpt_namespace()
        # hyperparameters buried in nested closures (schedules, nested
        # chains) must separate too — a depth-truncated description would
        # resume the wrong trajectory
        s1 = make(optax.adam(optax.exponential_decay(1e-3, 1000, 0.9)))
        s2 = make(optax.adam(optax.exponential_decay(1e-2, 1000, 0.9)))
        s3 = make(optax.adam(optax.exponential_decay(1e-3, 1000, 0.9)))
        assert s1._ckpt_namespace() != s2._ckpt_namespace()
        assert s1._ckpt_namespace() == s3._ckpt_namespace()
        n1 = make(optax.chain(optax.clip(1.0), optax.chain(optax.adam(1e-3))))
        n2 = make(optax.chain(optax.clip(1.0), optax.chain(optax.adam(1e-2))))
        assert n1._ckpt_namespace() != n2._ckpt_namespace()
        # aliased vs rebuilt-equal configs must agree (the seen-guard is
        # path-scoped, not first-visit-wins)
        tx = optax.adam(1e-3)
        aliased = make(optax.chain(tx, tx))
        rebuilt = make(optax.chain(optax.adam(1e-3), optax.adam(1e-3)))
        assert aliased._ckpt_namespace() == rebuilt._ckpt_namespace()

    def test_namespace_sees_callable_state_and_bodies(self):
        """State-bearing callables (instances, bound methods) and
        function *bodies* participate in the namespace: hyperparameters
        on a loss object, a swapped global in a lambda, or a changed
        kw-only default each get their own trajectory."""

        class FocalLoss:
            def __init__(self, gamma):
                self.gamma = gamma

            def __call__(self, logits, labels):
                return (logits - labels).mean() * self.gamma

        def make(loss):
            return FlaxImageFileEstimator(
                inputCol="uri", outputCol="out", labelCol="label",
                imageLoader=_loader,
                module=ViT(variant="ViT-Ti/16", num_classes=2,
                           image_size=IMG),
                loss=loss,
                fitParams=self._fit_params(2),
            )

        ns = lambda e: e._ckpt_namespace()  # noqa: E731
        assert ns(make(FocalLoss(2.0))) != ns(make(FocalLoss(5.0)))
        assert ns(make(FocalLoss(2.0))) == ns(make(FocalLoss(2.0)))
        # bound methods carry __self__ state
        assert (ns(make(FocalLoss(2.0).__call__))
                != ns(make(FocalLoss(5.0).__call__)))
        # same-qualname lambdas calling different globals differ (the
        # global name lives in co_names, not co_code)
        l1 = lambda l, y: np.mean(l - y)  # noqa: E731
        l2 = lambda l, y: np.sum(l - y)  # noqa: E731
        assert ns(make(l1)) != ns(make(l2))

        def lk1(l, y, *, weight=1.0):
            return l

        def lk2(l, y, *, weight=2.0):
            return l

        lk2.__qualname__ = lk1.__qualname__
        assert ns(make(lk1)) != ns(make(lk2))

    def test_stable_description_survives_hash_randomization(self):
        """A callable whose body holds a set literal (frozenset in
        co_consts, repr order PYTHONHASHSEED-dependent) must describe
        identically across interpreter processes."""
        import subprocess
        import sys

        prog = (
            "from sparkdl_tpu.estimators.checkpointing import "
            "stable_description\n"
            "def loss(l, y, reduction='mean'):\n"
            "    if reduction in {'mean', 'sum', 'none', 'batch'}:\n"
            "        return l\n"
            "    return y\n"
            "print(stable_description(loss))\n"
        )
        outs = {
            subprocess.run(
                [sys.executable, "-c", prog],
                env={**__import__("os").environ, "PYTHONHASHSEED": seed,
                     "JAX_PLATFORMS": "cpu"},
                capture_output=True, text=True, check=True,
            ).stdout.strip()
            for seed in ("1", "2", "3")
        }
        assert len(outs) == 1, f"description varies across seeds: {outs}"

    def test_different_pretrained_weights_namespace_apart(
        self, vector_dataset, tmp_path
    ):
        import os

        import jax
        import jax.numpy as jnp

        ck = str(tmp_path / "flax_ns_ck")
        module = ViT(variant="ViT-Ti/16", num_classes=2, image_size=IMG)
        va = module.init(
            jax.random.PRNGKey(1), jnp.zeros((1, IMG, IMG, 3), jnp.float32)
        )
        vb = module.init(
            jax.random.PRNGKey(2), jnp.zeros((1, IMG, IMG, 3), jnp.float32)
        )
        _estimator(
            fitParams=self._fit_params(1), checkpointDir=ck,
            initialVariables=va,
        ).fit(vector_dataset)
        _estimator(
            fitParams=self._fit_params(1), checkpointDir=ck,
            initialVariables=vb,
        ).fit(vector_dataset)
        assert len(os.listdir(ck)) == 2  # one namespace per starting point


def test_multi_output_module_uses_first_output(vector_dataset):
    """A flax module returning a tuple keeps the engine's first-output
    semantics through the pipelined transform path."""
    import flax.linen as nn
    import jax.numpy as jnp

    class TwoHead(nn.Module):
        @nn.compact
        def __call__(self, x, features_only=False):
            h = x.reshape(x.shape[0], -1)
            a = nn.Dense(2, name="a")(h)
            b = nn.Dense(3, name="b")(h)
            return a, b

    import jax

    module = TwoHead()
    variables = module.init(
        jax.random.PRNGKey(0), np.zeros((1, IMG, IMG, 3), np.float32)
    )
    t = FlaxImageFileTransformer(
        inputCol="uri", outputCol="out", imageLoader=_loader,
        module=module, variables=variables, batchSize=16,
    )
    out = t.transform(vector_dataset).collect()
    assert len(out) == N
    assert len(out[0]["out"]) == 2  # head "a", not a mangled stack
