"""FlaxImageFileEstimator: the ViT fine-tune config over the estimator API
(SURVEY.md §7 step 8).  8-device CPU mesh; tiny ViT geometry."""

import numpy as np
import pytest

from sparkdl_tpu.estimators import (
    FlaxImageFileEstimator,
    FlaxImageFileTransformer,
)
from sparkdl_tpu.models.vit import ViT
from sparkdl_tpu.parallel.tp import VIT_TP_RULES

IMG = 16
N = 24


@pytest.fixture()
def vector_dataset(tpu_session, tmp_path):
    """Learnable toy task: label = brightest quadrant."""
    rng = np.random.RandomState(0)
    rows = []
    for i in range(N):
        img = rng.rand(IMG, IMG, 3).astype(np.float32) * 0.2
        label = i % 2
        if label:
            img[:8, :8] += 0.7
        else:
            img[8:, 8:] += 0.7
        path = str(tmp_path / f"v{i}.npy")
        np.save(path, img)
        rows.append({"uri": path, "label": label})
    return tpu_session.createDataFrame(rows)


def _loader(uri):
    return np.load(uri)


def _estimator(**kw):
    kw.setdefault(
        "fitParams",
        {"epochs": 6, "batch_size": 16, "learning_rate": 1e-3, "seed": 0},
    )
    return FlaxImageFileEstimator(
        inputCol="uri",
        outputCol="out",
        labelCol="label",
        imageLoader=_loader,
        module=ViT(variant="ViT-Ti/16", num_classes=2, image_size=IMG),
        optimizer="adam",
        **kw,
    )


def test_vit_finetune_dp(vector_dataset):
    model = _estimator().fit(vector_dataset)
    assert isinstance(model, FlaxImageFileTransformer)
    assert np.isfinite(model._training_loss)
    out = model.transform(vector_dataset).collect()
    assert len(out) == N and len(out[0]["out"]) == 2
    # the fitted transform actually separates the two classes
    preds = [int(np.argmax(r["out"])) for r in out]
    labels = [r["label"] for r in out]
    acc = np.mean([p == l for p, l in zip(preds, labels)])
    assert acc >= 0.75, f"fine-tune did not learn (acc={acc})"


def test_vit_finetune_tp_matches_dp_loss(vector_dataset):
    """Same data/seed trained DP vs DP x TP (GSPMD Megatron rules): the
    final loss must agree — sharding is an execution detail, not math."""
    dp = _estimator().fit(vector_dataset)
    tp = _estimator(shardingRules=VIT_TP_RULES).fit(vector_dataset)
    np.testing.assert_allclose(
        tp._training_loss, dp._training_loss, rtol=5e-3, atol=5e-4
    )


def test_flax_estimator_with_flash_attention(vector_dataset):
    """FlaxImageFileEstimator fine-tunes a ViT whose attention runs
    through the Pallas flash kernel — the DP training step differentiates
    the custom VJP end-to-end."""
    from sparkdl_tpu.ops import flash_attention

    est = FlaxImageFileEstimator(
        inputCol="uri",
        outputCol="out",
        labelCol="label",
        imageLoader=_loader,
        module=ViT(variant="ViT-Ti/16", num_classes=2, image_size=IMG,
                   attn_impl=flash_attention),
        fitParams={"epochs": 1, "batch_size": 16},
    )
    model = est.fit(vector_dataset)
    assert isinstance(model, FlaxImageFileTransformer)
    assert np.isfinite(model._training_loss)


class TestFlaxCheckpointing:
    """Orbax checkpoint/resume for the Flax estimator (same contract as
    the Keras one: per-config namespace without epochs, async commits,
    epoch-capped restore, rng replay)."""

    def _fit_params(self, epochs):
        return {"epochs": epochs, "batch_size": 16, "learning_rate": 1e-3,
                "seed": 0}

    def test_refit_with_more_epochs_resumes_exactly(
        self, vector_dataset, tmp_path
    ):
        import os

        ck = str(tmp_path / "flax_ck")
        est2 = _estimator(fitParams=self._fit_params(2), checkpointDir=ck)
        est2.fit(vector_dataset)
        (ns,) = os.listdir(ck)
        assert sorted(os.listdir(os.path.join(ck, ns))) == [
            "epoch_1", "epoch_2"
        ]

        est4 = _estimator(fitParams=self._fit_params(4), checkpointDir=ck)
        resumed = est4.fit(vector_dataset)
        (ns2,) = os.listdir(ck)
        assert ns2 == ns  # extended in place, not a fresh namespace
        assert sorted(os.listdir(os.path.join(ck, ns))) == [
            "epoch_1", "epoch_2", "epoch_3", "epoch_4"
        ]

        straight = _estimator(fitParams=self._fit_params(4)).fit(
            vector_dataset
        )
        import jax

        for (ka, a), (kb, b) in zip(
            jax.tree_util.tree_leaves_with_path(resumed.variables),
            jax.tree_util.tree_leaves_with_path(straight.variables),
        ):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6,
                err_msg=str(ka),
            )

    def test_tp_checkpoint_roundtrip(self, vector_dataset, tmp_path):
        """GSPMD DP x TP state checkpoints and restores onto its
        shardings; resumed result equals the uninterrupted TP fit."""
        ck = str(tmp_path / "flax_tp_ck")
        kw = dict(shardingRules=VIT_TP_RULES, meshShape=(2, 4))
        est1 = _estimator(
            fitParams=self._fit_params(1), checkpointDir=ck, **kw
        )
        est1.fit(vector_dataset)
        est3 = _estimator(
            fitParams=self._fit_params(3), checkpointDir=ck, **kw
        )
        resumed = est3.fit(vector_dataset)
        straight = _estimator(fitParams=self._fit_params(3), **kw).fit(
            vector_dataset
        )
        import jax

        for (ka, a), (kb, b) in zip(
            jax.tree_util.tree_leaves_with_path(resumed.variables),
            jax.tree_util.tree_leaves_with_path(straight.variables),
        ):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6,
                err_msg=str(ka),
            )

    def test_different_pretrained_weights_namespace_apart(
        self, vector_dataset, tmp_path
    ):
        import os

        import jax
        import jax.numpy as jnp

        ck = str(tmp_path / "flax_ns_ck")
        module = ViT(variant="ViT-Ti/16", num_classes=2, image_size=IMG)
        va = module.init(
            jax.random.PRNGKey(1), jnp.zeros((1, IMG, IMG, 3), jnp.float32)
        )
        vb = module.init(
            jax.random.PRNGKey(2), jnp.zeros((1, IMG, IMG, 3), jnp.float32)
        )
        _estimator(
            fitParams=self._fit_params(1), checkpointDir=ck,
            initialVariables=va,
        ).fit(vector_dataset)
        _estimator(
            fitParams=self._fit_params(1), checkpointDir=ck,
            initialVariables=vb,
        ).fit(vector_dataset)
        assert len(os.listdir(ck)) == 2  # one namespace per starting point


def test_multi_output_module_uses_first_output(vector_dataset):
    """A flax module returning a tuple keeps the engine's first-output
    semantics through the pipelined transform path."""
    import flax.linen as nn
    import jax.numpy as jnp

    class TwoHead(nn.Module):
        @nn.compact
        def __call__(self, x, features_only=False):
            h = x.reshape(x.shape[0], -1)
            a = nn.Dense(2, name="a")(h)
            b = nn.Dense(3, name="b")(h)
            return a, b

    import jax

    module = TwoHead()
    variables = module.init(
        jax.random.PRNGKey(0), np.zeros((1, IMG, IMG, 3), np.float32)
    )
    t = FlaxImageFileTransformer(
        inputCol="uri", outputCol="out", imageLoader=_loader,
        module=module, variables=variables, batchSize=16,
    )
    out = t.transform(vector_dataset).collect()
    assert len(out) == N
    assert len(out[0]["out"]) == 2  # head "a", not a mangled stack
