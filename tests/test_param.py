"""Params system tests (reference analog: pyspark.ml.param semantics relied
on throughout ``python/sparkdl/param/``† — SURVEY.md §2/§5.6)."""

import pytest

from sparkdl_tpu.param import (
    HasInputCol,
    HasOutputCol,
    Param,
    Params,
    TypeConverters,
    keyword_only,
)


class _Stage(HasInputCol, HasOutputCol):
    threshold = Param(
        "undefined", "threshold", "a float param", TypeConverters.toFloat
    )

    @keyword_only
    def __init__(self, inputCol=None, outputCol=None, threshold=None):
        super().__init__()
        self._setDefault(threshold=0.5, outputCol="out")
        kwargs = self._input_kwargs
        self._set(**kwargs)


def test_defaults_and_set():
    s = _Stage(inputCol="x")
    assert s.getInputCol() == "x"
    assert s.getOutputCol() == "out"
    assert s.getOrDefault("threshold") == 0.5
    s.setOutputCol("y")
    assert s.getOutputCol() == "y"
    assert s.isSet(s.outputCol)
    assert not s.isSet(s.threshold)
    assert s.isDefined(s.threshold)


def test_type_conversion_and_validation():
    s = _Stage(inputCol="x", threshold=1)
    assert isinstance(s.getOrDefault("threshold"), float)
    with pytest.raises(TypeError):
        s.set(s.threshold, "not-a-float")
    with pytest.raises(TypeError):
        _Stage(inputCol=3)


def test_copy_with_extra():
    s = _Stage(inputCol="x")
    extra = {s.threshold: 0.9}
    c = s.copy(extra)
    assert c.getOrDefault(c.threshold) == 0.9
    assert s.getOrDefault(s.threshold) == 0.5  # original untouched
    assert c.uid == s.uid
    assert c.getInputCol() == "x"
    # param identity across copies (param grid semantics)
    assert c.threshold == s.threshold


def test_param_independence_between_instances():
    a = _Stage(inputCol="a")
    b = _Stage(inputCol="b")
    a.setOutputCol("oa")
    assert b.getOutputCol() == "out"
    assert a.getInputCol() == "a" and b.getInputCol() == "b"


def test_explain_params():
    s = _Stage(inputCol="x")
    text = s.explainParams()
    assert "threshold" in text and "default: 0.5" in text


def test_keyword_only_rejects_positional():
    with pytest.raises(TypeError):
        _Stage("x")
