"""Online serving tests: dynamic micro-batching, warm program cache,
admission control, and ``serving.*`` metrics.

Acceptance shape (ISSUE): N concurrent single-item submissions coalesce
into far fewer forward calls (proved via ``serving.batches``); a warmed
endpoint serves a burst with zero new compiles (``serving.compiles``);
latency quantiles and batch occupancy export through
:mod:`sparkdl_tpu.utils.metrics`.  Load-shedding / deadline / crash
behavior lives in ``test_fault_injection.py``.
"""

import threading
import time

import numpy as np
import pytest

from sparkdl_tpu.serving import (
    ModelServer,
    ServerClosed,
    ServingConfig,
)
from sparkdl_tpu.transformers.utils import (
    bucket_ladder,
    pad_to_batch,
    shape_bucket,
)
from sparkdl_tpu.utils.metrics import metrics


@pytest.fixture(autouse=True)
def fresh_metrics():
    """Serving assertions count metric deltas from zero."""
    metrics.reset()
    yield
    metrics.reset()


def make_server(**config_kw):
    cfg = ServingConfig(**{
        "max_batch": 16, "max_wait_ms": 25.0, "queue_capacity": 64,
        **config_kw,
    })
    server = ModelServer(cfg)
    server.register("double", lambda x: x * 2.0, item_shape=(4,))
    return server


# ----------------------------------------------------------------------
# batching core (factored out of transformers/utils.py's run loops)
# ----------------------------------------------------------------------
class TestBatchingCore:
    def test_shape_bucket_rounds_to_power_of_two(self):
        assert [shape_bucket(n, 32) for n in (1, 2, 3, 5, 8, 9, 31)] == [
            1, 2, 4, 8, 8, 16, 32,
        ]

    def test_shape_bucket_caps_at_max_batch(self):
        assert shape_bucket(33, 32) == 32
        assert shape_bucket(6, 6) == 6

    def test_shape_bucket_rejects_non_positive(self):
        with pytest.raises(ValueError):
            shape_bucket(0, 32)

    def test_bucket_ladder(self):
        assert bucket_ladder(32) == (1, 2, 4, 8, 16, 32)
        assert bucket_ladder(6) == (1, 2, 4, 6)
        assert bucket_ladder(1) == (1,)

    def test_pad_to_batch_repeats_last_row(self):
        x = np.arange(6, dtype=np.float32).reshape(3, 2)
        padded = pad_to_batch(x, 5)
        assert padded.shape == (5, 2)
        np.testing.assert_array_equal(padded[:3], x)
        np.testing.assert_array_equal(padded[3], x[-1])
        np.testing.assert_array_equal(padded[4], x[-1])

    def test_pad_to_batch_noop_when_full(self):
        x = np.zeros((4, 2), np.float32)
        assert pad_to_batch(x, 4) is x
        assert pad_to_batch(x, 2) is x


# ----------------------------------------------------------------------
# coalescing + warm cache (the tentpole acceptance tests)
# ----------------------------------------------------------------------
class TestCoalescing:
    def test_concurrent_submissions_coalesce(self):
        """N concurrent single-item submissions land in ≪ N forward
        calls — the whole point of the micro-batcher."""
        n = 16
        with make_server(max_wait_ms=50.0) as server:
            server.warmup()
            batches_before = metrics.counter("serving.batches").value

            barrier = threading.Barrier(n)
            results = [None] * n

            def one(i):
                barrier.wait()
                results[i] = server.predict(
                    np.full((4,), float(i), np.float32), timeout=30.0
                )

            threads = [
                threading.Thread(target=one, args=(i,)) for i in range(n)
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join()

            for i in range(n):
                np.testing.assert_allclose(results[i], 2.0 * i)
            batches = metrics.counter("serving.batches").value - batches_before
            assert metrics.counter("serving.requests").value == n
            # all n arrive within one 50ms linger window; typical is 1-3
            # batches, and anything ≥ n/2 means no coalescing happened
            assert 1 <= batches < n / 2, f"{n} requests took {batches} batches"

    def test_zero_recompiles_after_warmup(self):
        with make_server() as server:
            assert server.warmup() == {"double": (1, 2, 4, 8, 16)}
            compiles = metrics.counter("serving.compiles").value
            assert compiles == 5  # one program per ladder bucket
            # bursts of every size bucket differently; none may retrace
            for burst in (1, 3, 7, 16):
                futs = [
                    server.submit(np.full((4,), float(i), np.float32))
                    for i in range(burst)
                ]
                for i, f in enumerate(futs):
                    np.testing.assert_allclose(f.result(30.0), 2.0 * i)
            assert metrics.counter("serving.compiles").value == compiles

    def test_results_unscrambled_across_batches(self):
        """Padding and bucketing must never leak a neighbor's row."""
        with make_server(max_batch=4, max_wait_ms=5.0) as server:
            futs = [
                server.submit(np.full((4,), float(i), np.float32))
                for i in range(23)
            ]
            for i, f in enumerate(futs):
                np.testing.assert_allclose(f.result(30.0), 2.0 * i)


class TestMetricsExport:
    def test_latency_quantiles_and_occupancy_exported(self):
        with make_server() as server:
            server.warmup()
            futs = [
                server.submit(np.ones((4,), np.float32)) for _ in range(12)
            ]
            for f in futs:
                f.result(30.0)
            snap = server.status()["metrics"]
        for q in ("p50", "p95", "p99", "mean", "count"):
            assert f"serving.latency_ms.{q}" in snap
        assert snap["serving.latency_ms.count"] == 12
        assert (
            snap["serving.latency_ms.p50"]
            <= snap["serving.latency_ms.p95"]
            <= snap["serving.latency_ms.p99"]
        )
        assert 0.0 < snap["serving.batch_occupancy.mean"] <= 1.0
        assert snap["serving.queue_depth.double"] == 0
        assert snap["serving.requests"] == 12

    def test_status_shape(self):
        server = make_server()
        try:
            st = server.status()
            assert st["healthy"] and not st["closed"]
            assert st["uptime_s"] >= 0
            ep = st["endpoints"]["double"]
            assert ep["item_shape"] == [4] and ep["dtype"] == "float32"
            assert st["program_cache"]["programs"] == 0  # nothing traced
        finally:
            server.close()
        assert server.status()["closed"]

    @pytest.mark.slow
    def test_status_probe_device(self):
        """probe_device=True runs the bounded out-of-process liveness
        probe (utils/probes.py) — healthy on a working backend."""
        with make_server() as server:
            st = server.status(probe_device=True, probe_timeout_s=120)
        assert st["device"]["ok"], st["device"]
        assert st["healthy"]


@pytest.mark.slow
def test_sustained_soak_no_recompiles_no_leaks():
    """~6s of sustained concurrent traffic: zero post-warmup compiles,
    zero sheds at a sane queue size, queue drains to empty, and lifetime
    counters stay coherent (requests == latency observations)."""
    import time

    with make_server(max_batch=8, max_wait_ms=2.0,
                     queue_capacity=256) as server:
        server.warmup()
        compiles = metrics.counter("serving.compiles").value
        stop = threading.Event()
        served = [0] * 8

        def client(i):
            x = np.full((4,), float(i), np.float32)
            while not stop.is_set():
                np.testing.assert_allclose(
                    server.predict(x, timeout=30.0), 2.0 * i
                )
                served[i] += 1

        threads = [
            threading.Thread(target=client, args=(i,)) for i in range(8)
        ]
        for t in threads:
            t.start()
        time.sleep(6.0)
        stop.set()
        for t in threads:
            t.join()

        snap = server.status()["metrics"]
        total = sum(served)
        assert total > 100
        assert metrics.counter("serving.compiles").value == compiles
        assert snap["serving.requests"] == total
        assert snap["serving.latency_ms.count"] == total
        assert snap["serving.shed"] == 0
        assert snap["serving.queue_depth.double"] == 0


# ----------------------------------------------------------------------
# endpoint contract / lifecycle
# ----------------------------------------------------------------------
class TestEndpointContract:
    def test_duplicate_register_rejected(self):
        with make_server() as server:
            with pytest.raises(ValueError, match="already registered"):
                server.register("double", lambda x: x)

    def test_item_shape_is_enforced(self):
        with make_server() as server:
            server.predict(np.ones((4,), np.float32), timeout=30.0)
            with pytest.raises(ValueError, match="shape"):
                server.submit(np.ones((5,), np.float32))

    def test_first_request_binds_shape(self):
        with ModelServer(ServingConfig(max_wait_ms=1.0)) as server:
            server.register("id", lambda x: x)  # no item_shape
            with pytest.raises(ValueError, match="no item shape"):
                server.warmup()
            out = server.predict(np.ones((3,), np.float32), timeout=30.0)
            np.testing.assert_allclose(out, 1.0)
            with pytest.raises(ValueError, match="shape"):
                server.submit(np.ones((7,), np.float32))

    def test_model_id_routing(self):
        with make_server() as server:
            server.register("triple", lambda x: x * 3.0, item_shape=(4,))
            with pytest.raises(ValueError, match="model_id is required"):
                server.submit(np.ones((4,), np.float32))
            out = server.predict(
                np.ones((4,), np.float32), model_id="triple", timeout=30.0
            )
            np.testing.assert_allclose(out, 3.0)
            with pytest.raises(KeyError, match="nope"):
                server.submit(np.ones((4,), np.float32), model_id="nope")

    def test_submit_after_close_raises(self):
        server = make_server()
        server.close()
        with pytest.raises(ServerClosed):
            server.submit(np.ones((4,), np.float32))

    def test_program_cache_lru_eviction(self):
        # cache_size=2 with a 3-bucket ladder: warmup itself evicts, and
        # the evicted bucket retraces on demand (bounded memory, still
        # correct)
        with ModelServer(
            ServingConfig(max_batch=4, max_wait_ms=1.0, cache_size=2)
        ) as server:
            server.register("d", lambda x: x * 2.0, item_shape=(2,))
            server.warmup()  # traces buckets 1, 2, 4 through a 2-slot LRU
            assert server.status()["program_cache"]["programs"] == 2
            out = server.predict(np.ones((2,), np.float32), timeout=30.0)
            np.testing.assert_allclose(out, 2.0)


# ----------------------------------------------------------------------
# program-cache single-flight: compiles happen OUTSIDE the cache lock
# (regression for the lock-blocking finding sparkdl_check surfaced:
# ProgramCache.program used to hold self._lock across a multi-second
# XLA compile, stalling stats()/status() and every other endpoint)
# ----------------------------------------------------------------------
class _SlowEngineStub:
    """Engine stand-in whose program() blocks until released, counting
    calls — lets the test hold a 'compile' in flight deterministically."""

    def __init__(self):
        self.release = threading.Event()
        self.calls = []
        self.evicted = []
        self.cache = None

    def program(self, forward, specs, fingerprint=None, donate=False,
                name=None):
        self.calls.append(name)
        if not self.release.wait(timeout=30.0):
            raise TimeoutError("slow-compile stub never released")

        class Handle:
            callable = staticmethod(forward)
            source = "compile"
            key = f"stub:{name}"

        return Handle()

    def evict(self, key):
        self.evicted.append(key)


class TestProgramCacheSingleFlight:
    def _cache(self, maxsize=4):
        from sparkdl_tpu.serving.cache import ProgramCache

        cache = ProgramCache(maxsize=maxsize)
        stub = _SlowEngineStub()
        cache._engine = stub
        return cache, stub

    def test_stats_not_blocked_while_a_compile_is_in_flight(self):
        cache, stub = self._cache()
        t = threading.Thread(
            target=cache.program,
            args=("m", lambda x: x, 4, (2,), np.float32),
            daemon=True,
        )
        t.start()
        # wait until the resolve has actually claimed the key
        deadline = time.monotonic() + 5.0
        while not stub.calls and time.monotonic() < deadline:
            time.sleep(0.005)
        assert stub.calls, "stub compile never started"
        # the health-probe path must answer while the compile hangs
        start = time.monotonic()
        stats = cache.stats()
        elapsed = time.monotonic() - start
        assert elapsed < 1.0, f"stats() stalled {elapsed:.2f}s behind compile"
        assert stats["programs"] == 0  # not admitted yet
        stub.release.set()
        t.join(timeout=10.0)
        assert not t.is_alive()
        assert cache.stats()["programs"] == 1

    def test_same_key_callers_share_one_compile(self):
        cache, stub = self._cache()
        results = []
        threads = [
            threading.Thread(
                target=lambda: results.append(
                    cache.program("m", lambda x: x, 4, (2,), np.float32)
                ),
                daemon=True,
            )
            for _ in range(4)
        ]
        for t in threads:
            t.start()
        deadline = time.monotonic() + 5.0
        while not stub.calls and time.monotonic() < deadline:
            time.sleep(0.005)
        stub.release.set()
        for t in threads:
            t.join(timeout=10.0)
        assert len(results) == 4
        assert len(stub.calls) == 1, (
            f"single-flight broken: {len(stub.calls)} compiles for one key"
        )

    def test_distinct_keys_resolve_concurrently(self):
        # a cold bucket must not serialize other buckets behind it
        cache, stub = self._cache()
        stub.release.set()  # compiles return immediately
        cache.program("m", lambda x: x, 4, (2,), np.float32)
        stub.release.clear()
        slow = threading.Thread(
            target=cache.program,
            args=("m", lambda x: x, 8, (2,), np.float32),
            daemon=True,
        )
        slow.start()
        deadline = time.monotonic() + 5.0
        while len(stub.calls) < 2 and time.monotonic() < deadline:
            time.sleep(0.005)
        # the already-cached bucket serves instantly despite the in-flight
        # compile of bucket 8
        start = time.monotonic()
        fn = cache.program("m", lambda x: x, 4, (2,), np.float32)
        assert time.monotonic() - start < 1.0
        assert fn is not None
        stub.release.set()
        slow.join(timeout=10.0)

    def test_eviction_contract_preserved(self):
        cache, stub = self._cache(maxsize=2)
        stub.release.set()
        for bucket in (1, 2, 4):
            cache.program("m", lambda x: x, bucket, (2,), np.float32)
        stats = cache.stats()
        assert stats["programs"] == 2
        assert len(stub.evicted) == 1  # LRU slot left BOTH maps


# ----------------------------------------------------------------------
# constructors: XlaFunction / registered-UDF round trips
# ----------------------------------------------------------------------
class TestConstructors:
    def test_from_xla_function(self):
        from sparkdl_tpu.graph.function import XlaFunction

        w = np.arange(12, dtype=np.float32).reshape(4, 3)
        fn = XlaFunction(
            lambda p, x: x @ p["w"], params={"w": w}, name="linear"
        )
        fn.input_specs = [((8, 4), np.float32)]
        with ModelServer.from_xla_function(
            fn, config=ServingConfig(max_wait_ms=1.0)
        ) as server:
            assert server.warmup() == {"linear": (1, 2, 4, 8, 16, 32)}
            x = np.ones((4,), np.float32)
            np.testing.assert_allclose(
                server.predict(x, timeout=30.0), x @ w, rtol=1e-6
            )

    def test_from_registered_udf_serves_model_udf(self, tpu_session):
        keras = pytest.importorskip("keras")

        rng = np.random.RandomState(3)
        model = keras.Sequential(
            [
                keras.layers.Input((8, 8, 3)),
                keras.layers.Conv2D(2, 3, activation="relu"),
                keras.layers.GlobalAveragePooling2D(),
                keras.layers.Dense(3),
            ]
        )
        model.set_weights(
            [
                rng.randn(*w.shape).astype(np.float32) * 0.1
                for w in model.get_weights()
            ]
        )
        from sparkdl_tpu.udf import registerKerasImageUDF

        udf = registerKerasImageUDF(
            "serving_rt_udf", model, session=tpu_session
        )
        # the serving hook survives the registry's re-wrap
        meta = tpu_session.udf.get("serving_rt_udf")._serving_endpoint
        assert meta["model_id"] == "serving_rt_udf"
        assert meta["item_shape"] == (8, 8, 3)
        assert udf._serving_endpoint["item_shape"] == (8, 8, 3)

        with ModelServer.from_registered_udf(
            "serving_rt_udf",
            session=tpu_session,
            config=ServingConfig(max_batch=4, max_wait_ms=1.0),
        ) as server:
            server.warmup(buckets=(1, 2))
            x = rng.rand(8, 8, 3).astype(np.float32) * 255.0
            got = server.predict(x, timeout=60.0)
            want = np.asarray(model(x[None].astype(np.float32)))[0]
            np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)

    def test_from_registered_udf_rejects_plain_udf(self, tpu_session):
        tpu_session.udf.register("plain_py_udf", lambda x: x)
        try:
            with pytest.raises(ValueError, match="registerKerasImageUDF"):
                ModelServer.from_registered_udf(
                    "plain_py_udf", session=tpu_session
                )
        finally:
            del tpu_session.udf._udfs["plain_py_udf"]


# ----------------------------------------------------------------------
# offer_wait races (ISSUE-10 satellite): a blocked backpressure poller
# must wake with a TYPED error when the server closes or the breaker
# opens underneath it — never hang on a queue nobody will drain again
# ----------------------------------------------------------------------
class TestIdleDeviceFlush:
    """ISSUE-18 regression: the coalesce linger must not hold a batch
    while the device sits idle.  Both tests run on an injectable FROZEN
    clock, so the linger's remaining-time computation never counts down
    — without the early flush they would hang, not just run slow."""

    def test_take_flush_early_cuts_linger_on_frozen_clock(self):
        from sparkdl_tpu.serving.admission import AdmissionQueue, Request

        q = AdmissionQueue(8, clock=lambda: 1000.0)
        q.offer(Request(value=np.zeros(4, np.float32),
                        enqueued_at=1000.0))
        t0 = time.monotonic()
        batch = q.take(
            max_n=8, max_wait_s=3600.0, flush_early=lambda: True,
        )
        assert len(batch) == 1
        assert time.monotonic() - t0 < 5.0
        assert metrics.counter("batcher.flush_early").value == 1

    def test_lone_request_resolves_without_serving_full_linger(self):
        """A single submission against an idle endpoint must dispatch
        immediately even with an (effectively infinite) coalesce
        window — the dispatch window is free, so waiting buys nothing."""
        from sparkdl_tpu.serving.batcher import MicroBatcher
        from sparkdl_tpu.serving.cache import ProgramCache

        batcher = MicroBatcher(
            "flush",
            lambda x: x * 2.0,
            ServingConfig(max_batch=16, max_wait_ms=3_600_000.0),
            ProgramCache(4),
            item_shape=(4,),
            compile=False,
            clock=lambda: 1000.0,
        )
        try:
            fut = batcher.submit(np.full((4,), 3.0, np.float32))
            np.testing.assert_allclose(fut.result(timeout=10.0), 6.0)
            assert metrics.counter("batcher.flush_early").value >= 1
        finally:
            batcher.close()


class TestOfferWaitRaces:
    def _full_queue(self, capacity=1):
        from sparkdl_tpu.serving.admission import AdmissionQueue, Request

        q = AdmissionQueue(capacity)
        for _ in range(capacity):
            q.offer(Request(value=np.zeros(4, np.float32)))
        return q, Request

    def test_blocked_offer_wait_wakes_on_close_with_typed_error(self):
        q, Request = self._full_queue()
        outcome = {}
        blocked = threading.Event()

        def poller():
            blocked.set()
            try:
                q.offer_wait(Request(value=np.zeros(4, np.float32)))
                outcome["returned"] = True
            except BaseException as exc:  # noqa: BLE001
                outcome["error"] = exc

        t = threading.Thread(target=poller, daemon=True)
        t.start()
        assert blocked.wait(5)
        time.sleep(0.1)  # let the poller reach the Condition wait
        assert not outcome, "poller should be blocked on the full queue"
        q.close()
        t.join(timeout=5)
        assert not t.is_alive(), "offer_wait hung across close()"
        assert isinstance(outcome.get("error"), ServerClosed)

    def test_offer_wait_timeout_on_full_queue_returns_false(self):
        q, Request = self._full_queue()
        t0 = time.monotonic()
        admitted = q.offer_wait(
            Request(value=np.zeros(4, np.float32)), timeout_s=0.2
        )
        assert admitted is False
        assert time.monotonic() - t0 < 5.0

    def test_offer_wait_unblocks_when_take_frees_space(self):
        q, Request = self._full_queue()
        result = {}

        def poller():
            result["admitted"] = q.offer_wait(
                Request(value=np.zeros(4, np.float32)), timeout_s=10.0
            )

        t = threading.Thread(target=poller, daemon=True)
        t.start()
        time.sleep(0.1)
        assert q.take(1, max_wait_s=0.0)  # frees one slot
        t.join(timeout=5)
        assert result.get("admitted") is True

    def test_blocked_offer_wait_drains_through_open_breaker(self):
        """End to end: the forward starts failing, the breaker trips,
        the worker fast-fails the backlog — and the poller blocked in
        ``offer_wait`` is admitted (queue drained) with its request
        resolved as a typed ``CircuitOpen``, not stranded."""
        from sparkdl_tpu.resilience.errors import CircuitOpen
        from sparkdl_tpu.serving.admission import Request

        gate = threading.Event()

        def failing_forward(x):
            gate.wait(30.0)
            raise RuntimeError("forward is down")

        server = ModelServer(ServingConfig(
            max_batch=1, max_wait_ms=1.0, queue_capacity=2,
            breaker_threshold=2,
        ))
        server.register(
            "down", failing_forward, item_shape=(4,), compile=False
        )
        batcher = server._endpoints["down"]
        try:
            # r1 is taken by the worker (blocked in forward on the
            # gate); r2 + r3 then fill the queue to capacity
            futures = [server.submit(np.zeros(4, np.float32))]
            deadline = time.monotonic() + 10.0
            while len(batcher._queue) and time.monotonic() < deadline:
                time.sleep(0.01)
            assert not len(batcher._queue), "worker never took r1"
            futures += [
                server.submit(np.zeros(4, np.float32)) for _ in range(2)
            ]
            blocked_req = Request(value=np.zeros(4, np.float32))
            admitted = {}

            def poller():
                admitted["ok"] = batcher._queue.offer_wait(
                    blocked_req, timeout_s=30.0
                )

            t = threading.Thread(target=poller, daemon=True)
            t.start()
            time.sleep(0.2)
            assert not admitted, "queue should be full, poller blocked"

            gate.set()  # failures flow: 2 failed batches open the breaker
            t.join(timeout=20)
            assert admitted.get("ok") is True, (
                "poller not admitted after the breaker drained the queue"
            )
            assert batcher.breaker.state == "open"
            # the admitted request is resolved, typed — not stranded
            assert isinstance(
                blocked_req.future.exception(timeout=10), CircuitOpen
            )
            # the backlog got typed failures too, not hangs
            for fut in futures:
                assert fut.exception(timeout=10) is not None
        finally:
            gate.set()
            server.close()


# ----------------------------------------------------------------------
# ragged slot-block dispatch (ISSUE-20)
# ----------------------------------------------------------------------
class TestRaggedDispatch:
    """One-shot slot-block dispatch: admission into any free slot, a
    bool occupancy mask instead of pad rows, the padded ladder kept as
    the SPARKDL_RAGGED=0 kill switch and the fallback for compiled
    endpoints without a durable fingerprint."""

    DIM = 4

    def _matrix_server(self):
        import jax.numpy as jnp

        from sparkdl_tpu.transformers.utils import make_input_prologue

        w = np.linspace(-1.0, 1.0, self.DIM * self.DIM,
                        dtype=np.float32).reshape(self.DIM, self.DIM)
        pro = make_input_prologue(preprocess=lambda x: x / 2.0)
        server = ModelServer(ServingConfig(
            max_batch=8, max_wait_ms=5.0, queue_capacity=64,
        ))
        server.register(
            "plain", lambda x, _w=w: np.tanh(np.asarray(x) @ _w),
            item_shape=(self.DIM,), compile=False,
        )
        server.register(
            "plain_pro", lambda x, _w=w: np.tanh(np.asarray(x) @ _w),
            item_shape=(self.DIM,), compile=False, prologue=pro,
        )
        server.register(
            "jit", lambda x, _w=w: jnp.tanh(x @ _w),
            item_shape=(self.DIM,), compile=True,
            fingerprint="test:ragged:jit:v1",
        )
        server.register(
            "jit_pro", lambda x, _w=w: jnp.tanh(x @ _w),
            item_shape=(self.DIM,), compile=True,
            fingerprint="test:ragged:jit-pro:v1", prologue=pro,
        )
        return server

    def test_ragged_and_padded_outputs_byte_identical(self, monkeypatch):
        """THE equivalence matrix: the same 20 inputs through plain,
        plain+prologue, compiled-fingerprinted, and compiled+prologue
        endpoints, ragged on then off — every output byte-identical.
        Dispatch shape (mask vs pad) must never leak into results."""
        rng = np.random.default_rng(7)
        xs = [rng.standard_normal(self.DIM).astype(np.float32)
              for _ in range(20)]
        outs = {}
        for mode in ("1", "0"):
            monkeypatch.setenv("SPARKDL_RAGGED", mode)
            server = self._matrix_server()
            try:
                per_ep = {}
                for ep in ("plain", "plain_pro", "jit", "jit_pro"):
                    futs = [server.submit(x, model_id=ep) for x in xs]
                    per_ep[ep] = np.stack([
                        np.asarray(f.result(timeout=30.0)) for f in futs
                    ]).tobytes()
                outs[mode] = per_ep
            finally:
                server.close()
        assert outs["1"] == outs["0"]

    def test_ragged_active_and_fallback_rules(self, monkeypatch):
        """Plain and fingerprinted-compiled endpoints serve ragged;
        unfingerprinted-compiled endpoints and SPARKDL_RAGGED=0 fall
        back to the padded ladder (and stay correct)."""
        import jax.numpy as jnp

        monkeypatch.setenv("SPARKDL_RAGGED", "1")
        server = ModelServer(ServingConfig(max_batch=4, max_wait_ms=5.0))
        server.register("plain", lambda x: np.asarray(x) * 2.0,
                        item_shape=(4,), compile=False)
        server.register("anon_jit", lambda x: jnp.asarray(x) * 2.0,
                        item_shape=(4,), compile=True)
        server.register("fp_jit", lambda x: jnp.asarray(x) * 2.0,
                        item_shape=(4,), compile=True,
                        fingerprint="test:fallback:v1")
        try:
            eps = server.status()["endpoints"]
            assert eps["plain"]["ragged"] is True
            assert eps["fp_jit"]["ragged"] is True
            # anonymous slot-block executables can't persist — padded
            assert eps["anon_jit"]["ragged"] is False
            x = np.full((4,), 1.5, np.float32)
            for ep in ("plain", "anon_jit", "fp_jit"):
                np.testing.assert_allclose(
                    server.submit(x, model_id=ep).result(timeout=30.0),
                    3.0,
                )
            monkeypatch.setenv("SPARKDL_RAGGED", "0")  # live kill switch
            eps = server.status()["endpoints"]
            assert all(not e["ragged"] for e in eps.values())
            np.testing.assert_allclose(
                server.submit(x, model_id="plain").result(timeout=30.0),
                3.0,
            )
        finally:
            server.close()

    def test_ragged_computes_no_pad_rows(self, monkeypatch):
        """rows_computed == rows_real on the ragged plain lane (pad
        fraction 0), while the padded ladder computes bucket-rounded
        rows for the same traffic."""
        monkeypatch.setenv("SPARKDL_RAGGED", "1")
        gate = threading.Event()
        server = ModelServer(ServingConfig(
            max_batch=8, max_wait_ms=5.0, queue_capacity=64,
        ))

        def forward(x):
            gate.wait(10.0)
            return np.asarray(x) * 2.0

        server.register("ep", forward, item_shape=(4,), compile=False)
        try:
            first = server.submit(np.ones(4, np.float32))
            time.sleep(0.3)  # worker blocked in forward on batch #1
            rest = [server.submit(np.ones(4, np.float32))
                    for _ in range(3)]
            gate.set()
            for f in [first] + rest:
                np.testing.assert_allclose(f.result(timeout=10.0), 2.0)
            real = metrics.counter("batcher.rows_real").value
            computed = metrics.counter("batcher.rows_computed").value
            assert real == computed == 4.0
            assert metrics.gauge("batcher.pad_fraction").value == 0.0
        finally:
            gate.set()
            server.close()

    def test_padded_ladder_counts_pad_rows(self, monkeypatch):
        monkeypatch.setenv("SPARKDL_RAGGED", "0")
        gate = threading.Event()
        server = ModelServer(ServingConfig(
            max_batch=8, max_wait_ms=5.0, queue_capacity=64,
        ))

        def forward(x):
            gate.wait(10.0)
            return np.asarray(x) * 2.0

        server.register("ep", forward, item_shape=(4,), compile=False)
        try:
            first = server.submit(np.ones(4, np.float32))
            time.sleep(0.3)
            rest = [server.submit(np.ones(4, np.float32))
                    for _ in range(2)]
            gate.set()
            for f in [first] + rest:
                np.testing.assert_allclose(f.result(timeout=10.0), 2.0)
            # batch #1: 1 row in bucket 1; batch #2: 2 rows in bucket 2
            # ... unless the two queued requests split — either way the
            # ladder computed at least the real rows, and the counters
            # agree with the gauge
            real = metrics.counter("batcher.rows_real").value
            computed = metrics.counter("batcher.rows_computed").value
            assert real == 3.0 and computed >= real
            assert metrics.gauge("batcher.pad_fraction").value == round(
                1.0 - real / computed, 4
            )
        finally:
            gate.set()
            server.close()

    def test_freed_slots_admit_waiting_requests(self, monkeypatch):
        """More requests than slots: a 2-slot pool serves 6 requests by
        admitting into freed slots, never batching beyond the pool."""
        monkeypatch.setenv("SPARKDL_RAGGED", "1")
        seen = []
        server = ModelServer(ServingConfig(
            max_batch=2, max_wait_ms=5.0, queue_capacity=64,
        ))

        def forward(x):
            x = np.asarray(x)
            seen.append(int(x.shape[0]))
            return x * 2.0

        server.register("ep", forward, item_shape=(4,), compile=False)
        try:
            futs = [server.submit(np.ones(4, np.float32))
                    for _ in range(6)]
            for f in futs:
                np.testing.assert_allclose(f.result(timeout=10.0), 2.0)
            assert sum(seen) == 6
            assert max(seen) <= 2, (
                f"dispatch exceeded the slot pool: {seen}"
            )
            snap = server.status()["endpoints"]["ep"]["slot_pool"]
            assert snap["n_slots"] == 2
        finally:
            server.close()

    def test_single_request_dispatches_without_coalesce_wait(
        self, monkeypatch
    ):
        """Slot dispatch admits the moment a request arrives — a lone
        request against an effectively-infinite coalesce window must
        still resolve immediately."""
        monkeypatch.setenv("SPARKDL_RAGGED", "1")
        server = ModelServer(ServingConfig(
            max_batch=8, max_wait_ms=3_600_000.0,
        ))
        server.register("ep", lambda x: np.asarray(x) * 2.0,
                        item_shape=(4,), compile=False)
        try:
            t0 = time.monotonic()
            fut = server.submit(np.ones(4, np.float32))
            np.testing.assert_allclose(fut.result(timeout=10.0), 2.0)
            assert time.monotonic() - t0 < 5.0
        finally:
            server.close()

    def test_prologue_fused_matches_host_application(self):
        """The fused prologue must equal applying the same callable on
        the host before the forward — one program, same bytes."""
        from sparkdl_tpu.transformers.utils import make_input_prologue

        pro = make_input_prologue(preprocess=lambda x: x / 255.0)
        x = np.arange(4, dtype=np.float32)
        server = ModelServer(ServingConfig(max_batch=4, max_wait_ms=5.0))
        server.register("fused", lambda b: np.asarray(b) + 1.0,
                        item_shape=(4,), compile=False, prologue=pro)
        server.register("host", lambda b: np.asarray(b) + 1.0,
                        item_shape=(4,), compile=False)
        try:
            fused = np.asarray(
                server.submit(x, model_id="fused").result(timeout=10.0)
            )
            host_in = np.asarray(pro(x[None]))[0]
            host = np.asarray(
                server.submit(host_in, model_id="host").result(
                    timeout=10.0
                )
            )
            assert fused.tobytes() == host.tobytes()
        finally:
            server.close()


class TestWarmStartResultIntegrity:
    """The r20 warm-start corruption regression: a disk-loaded
    executable may hand later calls the same output buffer (and
    zero-copy-alias host inputs), so fetched results must leave the
    dispatch window as owned copies — a request's future must keep its
    row even after later batches run through the same executable."""

    DIM = 4

    @pytest.mark.parametrize("ragged", ["1", "0"])
    def test_warm_loaded_endpoint_serves_correct_rows(
        self, tmp_path, monkeypatch, ragged
    ):
        import jax.numpy as jnp

        monkeypatch.setenv("SPARKDL_RAGGED", ragged)
        monkeypatch.setenv("SPARKDL_COMPILE_CACHE", str(tmp_path / "exe"))
        scale = np.linspace(0.5, 1.5, self.DIM, dtype=np.float32)
        xs = [np.full(self.DIM, float(i + 1), np.float32)
              for i in range(24)]

        def serve_all():
            server = ModelServer(ServingConfig(
                max_batch=8, max_wait_ms=2.0, queue_capacity=64,
            ))
            server.register(
                "jit", lambda x, _s=scale: jnp.tanh(x * _s),
                item_shape=(self.DIM,), compile=True,
                fingerprint="test:warmstart:v1",
            )
            try:
                futs = [server.submit(x, model_id="jit") for x in xs]
                return np.stack([
                    np.asarray(f.result(timeout=30.0)) for f in futs
                ])
            finally:
                server.close()

        expect = np.stack([np.tanh(x * scale) for x in xs])
        cold = serve_all()   # compiles, persists under the fingerprint
        warm = serve_all()   # fresh ProgramCache in-process -> disk load
        np.testing.assert_allclose(cold, expect, atol=1e-6)
        # every request reads ITS row — not a later batch's rewrite of
        # a shared output buffer
        np.testing.assert_array_equal(warm, cold)

    def test_fetched_results_own_their_memory(self):
        import jax.numpy as jnp

        from sparkdl_tpu.engine.executor import _fetch_host

        host = _fetch_host(jnp.arange(8, dtype=jnp.float32))
        assert isinstance(host, np.ndarray)
        assert host.base is None and host.flags.owndata
