"""Telemetry plane core: the bounded time-series recorder and the SLO
burn-rate engine (``obs/timeseries.py`` + ``obs/slo.py``).

Everything here drives synthetic clocks — ``sample_once(now=...)`` /
``evaluate_once(now=...)`` — so windows, burn rates, and the
``ok → warning → page`` state machine are tested deterministically, no
sleeps, no background threads (the ISSUE-8 acceptance shape for the
state machine).
"""

import time

import pytest

from sparkdl_tpu.obs.slo import (
    SLO,
    SLOEngine,
    availability_slo,
    sanitize_name,
    serving_slos,
    streaming_slos,
)
from sparkdl_tpu.obs.timeseries import TimeSeriesRecorder, _interpolated_quantile
from sparkdl_tpu.utils.metrics import MetricsRegistry


@pytest.fixture()
def registry():
    return MetricsRegistry()


@pytest.fixture()
def recorder(registry):
    return TimeSeriesRecorder(registry=registry, interval_s=1.0)


# ----------------------------------------------------------------------
# time-series recorder
# ----------------------------------------------------------------------
class TestTimeSeriesRecorder:
    def test_samples_registry_snapshot_flat_names(self, registry, recorder):
        registry.counter("serving.requests").add(3)
        registry.gauge("data.queue_depth").set(2.0)
        registry.histogram("serving.latency_ms").observe(10.0)
        n = recorder.sample_once(now=1.0)
        assert n >= 3
        names = recorder.series_names()
        assert "serving.requests" in names
        assert "data.queue_depth" in names
        # histograms land in their snapshot() expansion
        assert "serving.latency_ms.p99" in names
        assert recorder.latest("serving.requests") == 3.0

    def test_excludes_own_ts_metrics(self, registry, recorder):
        registry.counter("serving.requests").add(1)
        recorder.sample_once(now=1.0)
        recorder.sample_once(now=2.0)
        assert not any(
            n.startswith("ts.") for n in recorder.series_names()
        )
        # but the self-metrics exist in the registry
        assert registry.snapshot()["ts.samples"] == 2

    def test_window_queries(self, registry, recorder):
        c = registry.counter("serving.requests")
        for t in range(10):
            c.add(5)
            recorder.sample_once(now=float(t))
        # full window: 10 samples, 45 of increase over 9 seconds
        assert recorder.delta("serving.requests", 100.0, now=9.0) == 45.0
        assert recorder.rate("serving.requests", 100.0, now=9.0) == 5.0
        # trailing window keeps only the in-window points
        pts = recorder.points("serving.requests", 2.0, now=9.0)
        assert [p[0] for p in pts] == [7.0, 8.0, 9.0]
        assert recorder.delta("serving.requests", 2.0, now=9.0) == 10.0

    def test_windowed_queries_need_two_points(self, registry, recorder):
        registry.counter("serving.requests").add(1)
        recorder.sample_once(now=1.0)
        assert recorder.delta("serving.requests", 10.0, now=1.0) is None
        assert recorder.rate("serving.requests", 10.0, now=1.0) is None
        assert recorder.delta("nope", 10.0, now=1.0) is None
        assert recorder.latest("nope") is None

    def test_quantile_and_fraction_over_window(self, registry, recorder):
        g = registry.gauge("serving.lag")
        for t, v in enumerate([10.0, 20.0, 30.0, 40.0, 50.0]):
            g.set(v)
            recorder.sample_once(now=float(t))
        assert recorder.quantile_over_window(
            "serving.lag", 0.5, 100.0, now=4.0
        ) == 30.0
        assert recorder.fraction_where(
            "serving.lag", lambda v: v > 25.0, 100.0, now=4.0
        ) == pytest.approx(0.6)
        assert recorder.fraction_where(
            "serving.lag", lambda v: v > 25.0, 100.0, now=500.0
        ) is None  # window slid past every point

    def test_max_points_ring_drops_oldest(self, registry):
        rec = TimeSeriesRecorder(registry=registry, max_points=5)
        g = registry.gauge("serving.lag")
        for t in range(10):
            g.set(float(t))
            rec.sample_once(now=float(t))
        pts = rec.points("serving.lag")
        assert len(pts) == 5
        assert pts[0] == (5.0, 5.0)

    def test_max_series_cap_counts_drops(self, registry):
        rec = TimeSeriesRecorder(registry=registry, max_series=3)
        for i in range(6):
            registry.gauge(f"serving.g{i}").set(1.0)
        rec.sample_once(now=1.0)
        assert len(rec.series_names()) == 3
        assert registry.snapshot()["ts.series_dropped"] >= 3

    def test_snapshot_truncates(self, registry):
        rec = TimeSeriesRecorder(registry=registry, max_points=100)
        g = registry.gauge("serving.lag")
        for t in range(50):
            g.set(float(t))
            rec.sample_once(now=float(t))
        snap = rec.snapshot(max_points=10)
        assert len(snap["serving.lag"]) == 10
        assert snap["serving.lag"][-1] == [49.0, 49.0]

    def test_interpolated_quantile(self):
        assert _interpolated_quantile([], 0.5) is None
        assert _interpolated_quantile([1.0, 2.0, 3.0], 0.5) == 2.0
        assert _interpolated_quantile([1.0, 3.0], 0.5) == 2.0
        with pytest.raises(ValueError):
            _interpolated_quantile([1.0], 1.5)

    def test_quantile_empty_window_is_none(self, registry, recorder):
        """No series at all, and a window that slid past every point,
        must both read as None — never 0.0, never a raise."""
        assert recorder.quantile_over_window(
            "serving.lag", 0.99, 10.0, now=1.0
        ) is None
        g = registry.gauge("serving.lag")
        g.set(5.0)
        recorder.sample_once(now=1.0)
        assert recorder.quantile_over_window(
            "serving.lag", 0.99, 10.0, now=500.0
        ) is None

    def test_quantile_single_point_window(self, registry, recorder):
        """One in-window point: every quantile IS that point."""
        g = registry.gauge("serving.lag")
        g.set(7.0)
        recorder.sample_once(now=1.0)
        for q in (0.0, 0.5, 0.99, 1.0):
            assert recorder.quantile_over_window(
                "serving.lag", q, 10.0, now=1.0
            ) == 7.0

    def test_quantile_window_straddles_ring_drop(self, registry):
        """A window reaching back past points the bounded ring already
        dropped must quantile over the survivors only — the dropped
        prefix silently narrows the window, it must not corrupt it."""
        rec = TimeSeriesRecorder(registry=registry, max_points=5)
        g = registry.gauge("serving.lag")
        for t in range(10):  # ring keeps t=5..9 (values 5.0..9.0)
            g.set(float(t))
            rec.sample_once(now=float(t))
        # the 100s window nominally covers all ten points; only the
        # five surviving the ring participate
        assert rec.quantile_over_window(
            "serving.lag", 0.0, 100.0, now=9.0
        ) == 5.0
        assert rec.quantile_over_window(
            "serving.lag", 0.5, 100.0, now=9.0
        ) == 7.0
        assert rec.quantile_over_window(
            "serving.lag", 1.0, 100.0, now=9.0
        ) == 9.0

    def test_validation(self, registry):
        with pytest.raises(ValueError):
            TimeSeriesRecorder(registry=registry, interval_s=0)
        with pytest.raises(ValueError):
            TimeSeriesRecorder(registry=registry, max_points=1)
        with pytest.raises(ValueError):
            TimeSeriesRecorder(registry=registry, max_series=0)

    def test_background_thread_lifecycle(self, registry):
        rec = TimeSeriesRecorder(registry=registry, interval_s=0.01)
        registry.counter("serving.requests").add(1)
        rec.start()
        try:
            deadline = time.monotonic() + 5.0
            while not rec.series_names():
                if time.monotonic() > deadline:
                    pytest.fail("background sampler never sampled")
        finally:
            rec.stop()
        assert "serving.requests" in rec.series_names()


# ----------------------------------------------------------------------
# SLO declarations
# ----------------------------------------------------------------------
class TestSLODeclaration:
    def test_sanitize_name(self):
        assert sanitize_name("My-Model v2") == "my_model_v2"
        assert sanitize_name(".weird.") == "weird"
        assert sanitize_name("...") == "unnamed"

    def test_validation(self):
        with pytest.raises(ValueError, match="unknown SLO kind"):
            SLO(name="x", kind="bogus", series="s", threshold=1.0)
        with pytest.raises(ValueError, match="objective"):
            SLO(name="x", kind="threshold", series="s", threshold=1.0,
                objective=1.0)
        with pytest.raises(ValueError, match="numerator"):
            SLO(name="x", kind="error_rate")
        with pytest.raises(ValueError, match="needs a series"):
            SLO(name="x", kind="threshold", threshold=1.0)
        with pytest.raises(ValueError, match="needs a threshold"):
            SLO(name="x", kind="threshold", series="s")
        with pytest.raises(ValueError, match="fast_window_s"):
            SLO(name="x", kind="threshold", series="s", threshold=1.0,
                fast_window_s=600.0, slow_window_s=60.0)

    def test_budget(self):
        slo = SLO(name="x", kind="threshold", series="s", threshold=1.0,
                  objective=0.99)
        assert slo.budget == pytest.approx(0.01)

    def test_factories(self):
        pair = serving_slos("My Model", latency_threshold_ms=100.0)
        assert [s.name for s in pair] == [
            "serving.my_model.latency", "serving.my_model.errors",
        ]
        assert pair[0].series == "serving.latency_ms.my_model.p99"
        assert pair[1].numerator == "serving.errors.my_model"
        bundle = streaming_slos(min_commit_rate=2.0)
        assert [s.name for s in bundle] == [
            "streaming.watermark_lag", "streaming.commit_rate",
        ]
        up = availability_slo()
        assert up.kind == "availability" and up.series == "sparkdl.up"


# ----------------------------------------------------------------------
# burn-rate state machine (synthetic clock throughout)
# ----------------------------------------------------------------------
def _latency_slo(**overrides):
    """p99-latency objective: 99% of samples under 100 ms, 60s fast /
    600s slow windows, page at burn 14, warn at 6, clear after 3."""
    defaults = dict(
        name="lat", kind="threshold", series="serving.p99",
        threshold=100.0, objective=0.99,
        fast_window_s=60.0, slow_window_s=600.0,
    )
    defaults.update(overrides)
    return SLO(**defaults)


class _Plant:
    """Drive a (recorder, engine) pair: one sample + one evaluation per
    10-second tick, gauge value chosen by the caller."""

    def __init__(self, registry, slo):
        self.registry = registry
        self.recorder = TimeSeriesRecorder(registry=registry)
        self.engine = SLOEngine(
            self.recorder, registry=registry, clock=lambda: self.t
        )
        self.engine.add(slo)
        self.gauge = registry.gauge(slo.series)
        self.t = 0.0

    def tick(self, value, n=1, step_s=10.0):
        out = None
        for _ in range(n):
            self.t += step_s
            self.gauge.set(value)
            self.recorder.sample_once(now=self.t)
            out = self.engine.evaluate_once(now=self.t)
        return out


class TestBurnRateStateMachine:
    def test_healthy_stays_ok(self, registry):
        plant = _Plant(registry, _latency_slo())
        states = plant.tick(50.0, n=30)
        assert states == {"lat": "ok"}
        st = plant.engine.report()["slos"][0]
        assert st["burn_fast"] == 0.0 and st["no_data"] is False

    def test_no_data_is_ok_not_breach(self, registry):
        plant = _Plant(registry, _latency_slo())
        assert plant.engine.evaluate_once(now=0.0) == {"lat": "ok"}
        assert plant.engine.report()["slos"][0]["no_data"] is True

    def test_total_breach_pages_and_is_hysteretic(self, registry):
        plant = _Plant(registry, _latency_slo())
        plant.tick(50.0, n=30)  # 5 healthy minutes
        # latency regression: every sample lands over threshold.  Fast
        # burn saturates immediately; page waits for the slow window to
        # confirm real budget spend (burn_slow >= 6 needs >= 6% of the
        # slow window bad).
        states = plant.tick(500.0, n=1)
        assert states == {"lat": "warning"}  # fast breach, unconfirmed
        states = plant.tick(500.0, n=5)
        assert states == {"lat": "page"}
        # recovery: downgrade waits clear_after consecutive clean evals
        # per step, and steps DOWN through warning while the slow window
        # still holds the breach (hysteresis: no flapping at threshold)
        states = plant.tick(50.0, n=1)
        assert states == {"lat": "page"}
        plant.tick(50.0, n=70)  # drain both windows well past clean
        assert plant.engine.states() == {"lat": "ok"}
        trans = plant.engine.report()["slos"][0]["transitions"]
        assert [(x["from"], x["to"]) for x in trans] == [
            ("ok", "warning"), ("warning", "page"),
            ("page", "warning"), ("warning", "ok"),
        ]

    def test_partial_breach_warns_without_paging(self, registry):
        # sparse breach: every 10th sample bad.  The 7-point fast window
        # makes one bad sample burn ~14x, so pin page_burn out of reach
        # and assert the multiwindow gate holds the state at warning
        # (slow-window burn ~10 >= warn_burn 6) without ever paging
        plant = _Plant(registry, _latency_slo(page_burn=100.0))
        for _ in range(10):
            plant.tick(50.0, n=9)
            plant.tick(500.0, n=1)
        assert plant.engine.states() == {"lat": "warning"}
        assert not any(
            x["to"] == "page"
            for x in plant.engine.report()["slos"][0]["transitions"]
        )

    def test_escalation_is_immediate_not_hysteretic(self, registry):
        plant = _Plant(registry, _latency_slo(clear_after=1000))
        plant.tick(50.0, n=30)
        plant.tick(500.0, n=6)
        # huge clear_after delays downgrades, never upgrades
        assert plant.engine.states() == {"lat": "page"}

    def test_gauges_and_transition_counter_exported(self, registry):
        plant = _Plant(registry, _latency_slo())
        plant.tick(50.0, n=30)
        plant.tick(500.0, n=6)
        snap = registry.snapshot()
        assert snap["slo.lat.state"] == 2.0  # page
        assert snap["slo.lat.burn_fast"] >= 14.0
        assert snap["slo.lat.burn_slow"] >= 6.0
        assert snap["slo.transitions"] == 2  # ok->warning, warning->page

    def test_transition_callback_seam(self, registry):
        plant = _Plant(registry, _latency_slo())
        seen = []
        plant.engine.on_transition(
            lambda slo, old, new, st: seen.append((slo.name, old, new))
        )
        plant.tick(50.0, n=30)
        plant.tick(500.0, n=6)
        assert ("lat", "ok", "warning") in seen
        assert ("lat", "warning", "page") in seen

    def test_callback_errors_do_not_break_evaluation(self, registry):
        plant = _Plant(registry, _latency_slo())

        def bad_hook(*a):
            raise RuntimeError("hook boom")

        plant.engine.on_transition(bad_hook)
        plant.tick(50.0, n=30)
        assert plant.tick(500.0, n=6) == {"lat": "page"}

    def test_error_rate_kind_zero_traffic_is_zero_burn(self, registry):
        recorder = TimeSeriesRecorder(registry=registry)
        engine = SLOEngine(recorder, registry=registry)
        engine.add(SLO(
            name="err", kind="error_rate", objective=0.999,
            numerator="serving.errors.m", denominator="serving.requests.m",
        ))
        errors = registry.counter("serving.errors.m")
        requests = registry.counter("serving.requests.m")
        t = 0.0
        for _ in range(10):  # idle: counters flat
            t += 10.0
            recorder.sample_once(now=t)
        assert engine.evaluate_once(now=t) == {"err": "ok"}
        # 50% errors on live traffic with budget 0.001 -> page fast
        for _ in range(10):
            t += 10.0
            requests.add(100)
            errors.add(50)
            recorder.sample_once(now=t)
            engine.evaluate_once(now=t)
        assert engine.states() == {"err": "page"}

    def test_rate_min_kind(self, registry):
        recorder = TimeSeriesRecorder(registry=registry)
        engine = SLOEngine(recorder, registry=registry)
        engine.add(SLO(
            name="commits", kind="rate_min", objective=0.99,
            series="streaming.epochs_committed", threshold=1.0,
            fast_window_s=60.0, slow_window_s=600.0,
        ))
        committed = registry.counter("streaming.epochs_committed")
        t = 0.0
        for _ in range(30):  # 2 epochs/s >= floor of 1
            t += 10.0
            committed.add(20)
            recorder.sample_once(now=t)
            engine.evaluate_once(now=t)
        assert engine.states() == {"commits": "ok"}
        for _ in range(40):  # throughput collapses below the floor
            t += 10.0
            recorder.sample_once(now=t)
            engine.evaluate_once(now=t)
        assert engine.states() == {"commits": "page"}

    def test_availability_kind(self, registry):
        recorder = TimeSeriesRecorder(registry=registry)
        engine = SLOEngine(recorder, registry=registry)
        engine.add(availability_slo(objective=0.99))
        up = registry.gauge("sparkdl.up")
        t = 0.0
        for _ in range(30):
            t += 10.0
            up.set(1.0)
            recorder.sample_once(now=t)
            engine.evaluate_once(now=t)
        assert engine.states() == {"availability": "ok"}
        for _ in range(6):
            t += 10.0
            up.set(0.0)
            recorder.sample_once(now=t)
            engine.evaluate_once(now=t)
        assert engine.states() == {"availability": "page"}

    def test_report_shape_and_worst(self, registry):
        plant = _Plant(registry, _latency_slo())
        plant.engine.add(SLO(
            name="other", kind="threshold", series="serving.other",
            threshold=1.0,
        ))
        plant.tick(50.0, n=30)
        plant.tick(500.0, n=6)
        report = plant.engine.report()
        assert report["worst"] == "page"
        assert plant.engine.worst_state() == "page"
        row = {r["name"]: r for r in report["slos"]}["lat"]
        assert row["kind"] == "threshold"
        assert row["windows_s"] == [60.0, 600.0]
        assert row["state"] == "page"

    def test_duplicate_slo_rejected(self, registry):
        recorder = TimeSeriesRecorder(registry=registry)
        engine = SLOEngine(recorder, registry=registry)
        engine.add(_latency_slo())
        with pytest.raises(ValueError, match="already registered"):
            engine.add(_latency_slo())
