"""sparkdl_tpu.data — the async input-pipeline subsystem.

Pins the three contracts the package exists for:

- operator semantics (ordering, seeded shuffle stream, strided shard,
  cyclic-pad batching identical to the estimator path);
- clean shutdown (closing a pipeline mid-stream joins every background
  thread and shuts worker pools — no leaks, no dropped sentinels);
- instrumentation (``data.*`` metrics advance).
"""

import threading
import time

import numpy as np
import pytest

from sparkdl_tpu.data import Batch, Dataset, PrefetchIterator
from sparkdl_tpu.utils.metrics import metrics


def _thread_count():
    # settle momentarily: dying threads unwind off the active list
    for _ in range(50):
        time.sleep(0.01)
        stable = threading.active_count()
        time.sleep(0.01)
        if threading.active_count() == stable:
            return stable
    return threading.active_count()


# ---------------------------------------------------------------------------
# sources
# ---------------------------------------------------------------------------


def test_from_uris_is_lazy_and_reiterable():
    ds = Dataset.from_uris([f"file:///img_{i}.png" for i in range(5)])
    assert len(ds) == 5
    assert list(ds) == list(ds)  # re-iteration replays the source


def test_from_arrays_rows_and_tuples():
    x = np.arange(6).reshape(3, 2)
    y = np.array([10, 11, 12])
    rows = list(Dataset.from_arrays(x))
    assert len(rows) == 3 and np.array_equal(rows[1], [2, 3])
    pairs = list(Dataset.from_arrays(x, y))
    assert np.array_equal(pairs[2][0], [4, 5]) and pairs[2][1] == 12


def test_from_arrays_rejects_misaligned():
    with pytest.raises(ValueError, match="aligned"):
        Dataset.from_arrays(np.zeros(3), np.zeros(4))


def test_from_dataframe_columns():
    from sparkdl_tpu.sql.session import TPUSession

    session = TPUSession.builder.getOrCreate()
    df = session.createDataFrame(
        [("a.png", 0), ("b.png", 1), ("c.png", 2)], ["uri", "label"]
    )
    ds = Dataset.from_dataframe(df, "uri", "label")
    assert len(ds) == 3
    assert list(ds) == [("a.png", 0), ("b.png", 1), ("c.png", 2)]
    assert list(Dataset.from_dataframe(df, "label")) == [0, 1, 2]


# ---------------------------------------------------------------------------
# map
# ---------------------------------------------------------------------------


def test_map_threaded_preserves_order():
    """Worker latency inversions must not reorder the stream."""

    def slow_when_even(i):
        time.sleep(0.02 if i % 2 == 0 else 0.0)
        return i * i

    ds = Dataset.from_items(list(range(16))).map(slow_when_even, num_workers=4)
    assert list(ds) == [i * i for i in range(16)]


def test_map_threaded_shuts_pool_down():
    before = _thread_count()
    ds = Dataset.from_items(list(range(64))).map(
        lambda i: i, num_workers=4
    )
    it = iter(ds)
    next(it)
    it.close()
    assert _thread_count() <= before


def test_map_propagates_errors():
    def boom(i):
        if i == 3:
            raise RuntimeError("decode failed")
        return i

    with pytest.raises(RuntimeError, match="decode failed"):
        list(Dataset.from_items(list(range(8))).map(boom, num_workers=2))


# ---------------------------------------------------------------------------
# shuffle — the estimator permutation stream, reproduced
# ---------------------------------------------------------------------------


def test_shuffle_reproduces_estimator_rng_stream():
    """Epoch e of the dataset == the e-th ``rng.permutation`` draw of a
    ``RandomState(seed % 2**32)`` — the estimators' exact stream."""
    seed, n = 1234, 11
    ds = Dataset.from_arrays(np.arange(n)).shuffle(seed)
    rng = np.random.RandomState(seed % 2**32)
    for _ in range(3):  # three epochs, three consecutive draws
        expect = [int(v) for v in rng.permutation(n)]
        assert [int(v) for v in ds] == expect


# ---------------------------------------------------------------------------
# shard
# ---------------------------------------------------------------------------


def test_shard_strided_split_partitions_everything():
    items = list(range(10))
    shards = [
        list(Dataset.from_items(items).shard(index=i, count=3))
        for i in range(3)
    ]
    assert shards == [[0, 3, 6, 9], [1, 4, 7], [2, 5, 8]]
    assert len(Dataset.from_items(items).shard(index=1, count=3)) == 3


def test_shard_default_is_identity_when_single_process():
    assert list(Dataset.from_items([1, 2, 3]).shard()) == [1, 2, 3]


def test_shard_rejects_bad_index():
    with pytest.raises(ValueError, match="outside"):
        list(Dataset.from_items([1]).shard(index=3, count=2))


# ---------------------------------------------------------------------------
# batch — cyclic pad identical to the estimator path
# ---------------------------------------------------------------------------


def test_batch_cyclic_pad_matches_estimator_policy():
    order = np.random.RandomState(0).permutation(7)
    got = list(Dataset.from_arrays(order).batch(3, pad="cyclic"))
    assert [b.n_real for b in got] == [3, 3, 1]
    # the estimator's padding: np.concatenate([idx, np.resize(order, pad)])
    expect_last = np.concatenate([order[6:], np.resize(order, 2)])
    assert np.array_equal(got[-1].items, expect_last)


def test_batch_min_batches_emits_all_pad_batches():
    order = np.arange(3)
    got = list(
        Dataset.from_arrays(order).batch(2, pad="cyclic", min_batches=4)
    )
    assert [b.n_real for b in got] == [2, 1, 0, 0]
    # the n_real=0 batches are np.resize(order, bs) — estimator policy for
    # hosts whose shard ran out before the common step count
    assert np.array_equal(got[2].items, np.resize(order, 2))


def test_batch_without_pad_keeps_ragged_tail():
    got = list(Dataset.from_items([1, 2, 3]).batch(2))
    assert got[-1].n_real == 1 and list(got[-1].items) == [3]


# ---------------------------------------------------------------------------
# prefetch — thread hygiene
# ---------------------------------------------------------------------------


def test_prefetch_yields_everything_in_order():
    ds = Dataset.from_items(list(range(20))).prefetch(3)
    assert list(ds) == list(range(20))


def test_prefetch_early_close_joins_producer_thread():
    """Closing a pipeline mid-stream must leave no background threads —
    the regression the old spin-poll queues could not guarantee."""
    before = _thread_count()
    it = iter(Dataset.from_items(list(range(1000))).prefetch(2))
    assert next(it) == 0
    it.close()
    assert _thread_count() <= before


def test_prefetch_propagates_producer_error_and_joins():
    def explode(i):
        if i == 5:
            raise ValueError("bad row")
        return i

    before = _thread_count()
    with pytest.raises(ValueError, match="bad row"):
        list(Dataset.from_items(list(range(10))).map(explode).prefetch(2))
    assert _thread_count() <= before


def test_prefetch_iterator_close_is_idempotent():
    it = PrefetchIterator(lambda: iter(range(100)), size=2)
    next(it)
    it.close()
    it.close()
    with pytest.raises(StopIteration):
        next(it)


def test_prefetch_closes_upstream_pools():
    """The prefetch producer closes its upstream chain, so a threaded map
    under a prefetch sheds its pool when the consumer walks away."""
    before = _thread_count()
    ds = (
        Dataset.from_items(list(range(500)))
        .map(lambda i: i + 1, num_workers=4)
        .prefetch(2)
    )
    it = iter(ds)
    next(it)
    it.close()
    assert _thread_count() <= before


# ---------------------------------------------------------------------------
# prefetch_to_device
# ---------------------------------------------------------------------------


def test_prefetch_to_device_places_and_preserves_values():
    x = np.arange(12, dtype=np.float32).reshape(6, 2)
    out = list(Dataset.from_arrays(x).batch(2).prefetch_to_device())
    assert len(out) == 3
    assert all(isinstance(b, Batch) for b in out)
    import jax

    assert isinstance(out[0].items, jax.Array)
    assert np.array_equal(np.asarray(out[1].items), x[2:4])


def test_prefetch_to_device_counts_real_rows():
    metrics.reset()
    x = np.arange(10, dtype=np.float32).reshape(5, 2)
    list(Dataset.from_arrays(x).batch(2, pad="cyclic").prefetch_to_device())
    assert metrics.counter("data.rows_out").value == 5  # pad row not counted


def test_prefetch_to_device_custom_placer():
    seen = []

    def spy(batch):
        seen.append(batch)
        return batch

    out = list(
        Dataset.from_items([1, 2, 3]).prefetch_to_device(place=spy)
    )
    assert out == [1, 2, 3] and seen == [1, 2, 3]


# ---------------------------------------------------------------------------
# metrics instrumentation
# ---------------------------------------------------------------------------


def test_pipeline_advances_data_metrics():
    metrics.reset()
    list(
        Dataset.from_items(list(range(8)))
        .map(lambda i: np.full((2,), i, np.float32))
        .prefetch(2)
    )
    snap = metrics.snapshot()
    assert snap.get("data.device_stall_ms.count", 0) > 0
    assert metrics.timer("data.producer_busy").entries > 0


# ---------------------------------------------------------------------------
# StreamingShardLoader on the new machinery
# ---------------------------------------------------------------------------


def _loader_for(values):
    return lambda uri: np.full((2, 2), values[uri], np.float32)


def test_streaming_loader_epoch_matches_plan():
    uris = [f"u{i}" for i in range(5)]
    values = {u: float(i) for i, u in enumerate(uris)}
    y = np.arange(5, dtype=np.int32)
    from sparkdl_tpu.estimators.data import StreamingShardLoader

    loader = StreamingShardLoader(
        uris, y, _loader_for(values), local_bs=2, weighted=True
    )
    order = np.random.RandomState(3).permutation(5)
    batches = list(loader.epoch(order, steps=3))
    assert len(batches) == 3
    # final batch: 1 real row + cyclic pad, zero-weighted
    assert batches[-1]["w"].tolist() == [1.0, 0.0]
    expect_idx = np.concatenate([order[4:], np.resize(order, 1)])
    assert np.array_equal(batches[-1]["y"], y[expect_idx])


def test_streaming_loader_early_close_leaks_no_threads():
    """Abandoning an epoch mid-stream (a step error, a break) must join
    the prefetch producer AND shut the intra-batch pool down."""
    uris = [f"u{i}" for i in range(64)]
    values = {u: float(i) for i, u in enumerate(uris)}
    y = np.arange(64, dtype=np.int32)
    from sparkdl_tpu.estimators.data import StreamingShardLoader

    loader = StreamingShardLoader(
        uris, y, _loader_for(values), local_bs=4, weighted=False,
        max_workers=4,
    )
    before = _thread_count()
    gen = loader.epoch(np.arange(64), steps=16)
    next(gen)
    gen.close()
    assert _thread_count() <= before


def test_in_memory_epoch_dataset_matches_hand_loop():
    from sparkdl_tpu.estimators.data import in_memory_epoch_dataset

    x = np.arange(14, dtype=np.float32).reshape(7, 2)
    y = np.arange(7, dtype=np.int32)
    order = np.random.RandomState(1).permutation(7)
    local_bs, steps = 3, 3
    got = list(in_memory_epoch_dataset(order, x, y, local_bs, steps, True))
    for step_i in range(steps):
        idx = order[step_i * local_bs:(step_i + 1) * local_bs]
        k = len(idx)
        if k < local_bs:
            idx = np.concatenate([idx, np.resize(order, local_bs - k)])
        assert np.array_equal(got[step_i]["x"], x[idx])
        assert np.array_equal(got[step_i]["y"], y[idx])
        assert got[step_i]["w"].tolist() == [1.0] * k + [0.0] * (local_bs - k)
