"""Image I/O tests (reference analog: tests around ``imageIO.py``† and
``ImageUtilsSuite.scala``† — SURVEY.md §4)."""

import numpy as np
import pytest
from PIL import Image

from sparkdl_tpu.image.imageIO import (
    filesToDF,
    imageArrayToStruct,
    imageStructToArray,
    imageStructToRGBArray,
    imageType,
    readImages,
    resizeImage,
    rgbArrayToStruct,
)


def test_array_struct_roundtrip():
    arr = np.random.RandomState(0).randint(0, 255, (7, 5, 3), dtype=np.uint8)
    struct = imageArrayToStruct(arr, origin="mem")
    assert struct.height == 7 and struct.width == 5 and struct.nChannels == 3
    assert struct.mode == 16  # CV_8UC3
    np.testing.assert_array_equal(imageStructToArray(struct), arr)


def test_rgb_bgr_channel_order():
    rgb = np.zeros((2, 2, 3), dtype=np.uint8)
    rgb[..., 0] = 255  # pure red in RGB
    struct = rgbArrayToStruct(rgb)
    stored = imageStructToArray(struct)
    # stored order is BGR: red lands in the last channel
    assert stored[0, 0, 2] == 255 and stored[0, 0, 0] == 0
    np.testing.assert_array_equal(imageStructToRGBArray(struct), rgb)


def test_grayscale_roundtrip():
    arr = np.random.RandomState(1).randint(0, 255, (4, 6), dtype=np.uint8)
    struct = imageArrayToStruct(arr)
    assert struct.mode == 0 and struct.nChannels == 1
    np.testing.assert_array_equal(imageStructToArray(struct)[:, :, 0], arr)


def test_image_type_for_array_rejects_bad():
    with pytest.raises(ValueError):
        imageType.forArray(np.zeros((2, 2, 2), dtype=np.uint8))
    with pytest.raises(ValueError):
        imageType.forArray(np.zeros((2, 2, 3), dtype=np.int64))


def test_files_to_df(tpu_session, image_dir):
    df = filesToDF(tpu_session, image_dir, numPartitions=3)
    assert df.columns == ["filePath", "fileData"]
    assert df.count() == 7
    row = df.collect()[0]
    assert isinstance(row.fileData, bytes) and len(row.fileData) > 0


def test_read_images(tpu_session, image_dir):
    df = readImages(image_dir, session=tpu_session, numPartitions=2)
    assert "image" in df.columns
    rows = df.collect()
    assert len(rows) == 7
    color = [r for r in rows if r.image.nChannels == 3]
    assert len(color) == 6
    img = color[0].image
    arr = imageStructToArray(img)
    assert arr.shape == (img.height, img.width, 3)
    # decoded PNG content must match PIL ground truth (BGR stored)
    pil = np.asarray(Image.open(img.origin).convert("RGB"))
    np.testing.assert_array_equal(imageStructToRGBArray(img), pil)


def test_read_images_drops_undecodable(tpu_session, tmp_path):
    (tmp_path / "bad.png").write_bytes(b"not an image")
    arr = np.zeros((4, 4, 3), dtype=np.uint8)
    Image.fromarray(arr).save(tmp_path / "ok.png")
    df = readImages(str(tmp_path), session=tpu_session)
    assert df.count() == 1


def test_resize_udf():
    arr = np.random.RandomState(2).randint(0, 255, (10, 8, 3), dtype=np.uint8)
    struct = imageArrayToStruct(arr)
    resized = resizeImage((5, 4))(struct)
    assert (resized.height, resized.width) == (5, 4)
    out = imageStructToArray(resized)
    ref = np.asarray(
        Image.fromarray(arr, "RGB").resize((4, 5), Image.BILINEAR)
    )
    np.testing.assert_array_equal(out, ref)


def test_read_images_skip_counts_decode_errors(tpu_session, tmp_path):
    """on_error="skip" (default) drops corrupt files but advances the
    data.decode_errors counter — drops are observable, never silent."""
    from sparkdl_tpu.utils.metrics import metrics

    (tmp_path / "bad1.png").write_bytes(b"not an image")
    (tmp_path / "bad2.png").write_bytes(b"\x89PNG\r\n but truncated")
    Image.fromarray(np.zeros((4, 4, 3), np.uint8)).save(tmp_path / "ok.png")
    before = metrics.counter("data.decode_errors").value
    df = readImages(str(tmp_path), session=tpu_session)
    assert df.count() == 1
    assert metrics.counter("data.decode_errors").value == before + 2


def test_read_images_raise_names_corrupt_file(tpu_session, tmp_path):
    from sparkdl_tpu.image.imageIO import ImageDecodeError

    (tmp_path / "corrupt.png").write_bytes(b"nope")
    Image.fromarray(np.zeros((4, 4, 3), np.uint8)).save(tmp_path / "ok.png")
    # this engine's mapPartitions evaluates eagerly, so the read itself
    # raises (on Spark it would surface at the first action)
    with pytest.raises(ImageDecodeError, match="corrupt.png"):
        readImages(str(tmp_path), session=tpu_session, on_error="raise")


def test_read_images_rejects_bad_on_error(tpu_session, image_dir):
    with pytest.raises(ValueError, match="on_error"):
        readImages(image_dir, session=tpu_session, on_error="ignore")


def test_custom_decode_fn_exception_is_wrapped(tpu_session, tmp_path):
    """A decode_f that raises (instead of returning None) follows the same
    policy: counted+skipped by default, ImageDecodeError with the origin
    and cause under on_error="raise"."""
    from sparkdl_tpu.image.imageIO import ImageDecodeError, readImagesWithCustomFn

    Image.fromarray(np.zeros((4, 4, 3), np.uint8)).save(tmp_path / "a.png")

    def angry_decode(raw, origin):
        raise RuntimeError("decoder exploded")

    df = readImagesWithCustomFn(
        str(tmp_path), decode_f=angry_decode, session=tpu_session
    )
    assert df.count() == 0  # skipped, not raised

    with pytest.raises(ImageDecodeError, match="a.png") as ei:
        readImagesWithCustomFn(
            str(tmp_path), decode_f=angry_decode, session=tpu_session,
            on_error="raise",
        )
    assert isinstance(ei.value.cause, RuntimeError)
