"""Native columnar-bridge tests: C++ path ≡ Python path (oracle pattern,
SURVEY.md §4) plus the jax.image.resize numerical-parity contract that keeps
host-packed batches interchangeable with device-resized ones."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from sparkdl_tpu import native
from sparkdl_tpu.image import imageIO
from sparkdl_tpu.transformers.utils import (
    decode_image_batch,
    normalize_channels,
)

pytestmark = pytest.mark.skipif(
    not native.is_available(), reason="native bridge unavailable (no g++?)"
)


def _python_pack(rows, n_channels, out_hw, to_rgb):
    imgs = [
        normalize_channels(
            imageIO.imageStructToArray(r).astype(np.float32), n_channels
        )
        for r in rows
    ]
    if to_rgb and n_channels >= 3:
        imgs = [i[..., ::-1] for i in imgs]
    resized = [
        np.asarray(
            jax.image.resize(
                jnp.asarray(i),
                (out_hw[0], out_hw[1], i.shape[-1]),
                method="bilinear",
            )
        )
        if i.shape[:2] != tuple(out_hw)
        else i
        for i in imgs
    ]
    return np.stack(resized)


def _rows(rng):
    """Heterogeneous structs: uint8 gray/BGR/BGRA + float32 BGR, mixed sizes."""
    rows = []
    rows.append(
        imageIO.imageArrayToStruct(
            rng.randint(0, 255, (40, 50), dtype=np.uint8).astype(np.uint8)
        )
    )
    rows.append(
        imageIO.imageArrayToStruct(
            rng.randint(0, 255, (64, 48, 3), dtype=np.uint8).astype(np.uint8)
        )
    )
    rows.append(
        imageIO.imageArrayToStruct(
            rng.randint(0, 255, (30, 31, 4), dtype=np.uint8).astype(np.uint8)
        )
    )
    rows.append(
        imageIO.imageArrayToStruct(
            (rng.rand(100, 80, 3) * 255).astype(np.float32)
        )
    )
    return rows


def test_pack_matches_python_path_rgb3():
    rng = np.random.RandomState(0)
    rows = _rows(rng)
    got = native.pack_image_rows(rows, (56, 72), 3, bgr_to_rgb=True)
    want = _python_pack(rows, 3, (56, 72), to_rgb=True)
    np.testing.assert_allclose(got, want, atol=2e-2)


def test_pack_matches_python_path_gray():
    rng = np.random.RandomState(1)
    rows = _rows(rng)
    got = native.pack_image_rows(rows, (33, 44), 1, bgr_to_rgb=False)
    want = _python_pack(rows, 1, (33, 44), to_rgb=False)
    np.testing.assert_allclose(got, want, atol=2e-2)


def test_pack_no_resize_is_exact():
    rng = np.random.RandomState(2)
    arr = rng.randint(0, 255, (25, 35, 3), dtype=np.uint8)
    rows = [imageIO.imageArrayToStruct(arr.astype(np.uint8))] * 3
    got = native.pack_image_rows(rows, (25, 35), 3, bgr_to_rgb=False)
    want = np.stack([arr.astype(np.float32)] * 3)
    np.testing.assert_array_equal(got, want)


def test_resize_batch_matches_jax_bilinear():
    rng = np.random.RandomState(3)
    for (h, w), (oh, ow) in [((60, 80), (299, 299)), ((400, 300), (128, 96))]:
        x = (rng.rand(2, h, w, 3) * 255).astype(np.float32)
        got = native.resize_batch(x, (oh, ow))
        want = np.asarray(
            jax.image.resize(jnp.asarray(x), (2, oh, ow, 3), method="bilinear")
        )
        np.testing.assert_allclose(got, want, atol=1e-2)


def test_decode_image_batch_uses_native_and_matches(monkeypatch):
    """decode_image_batch gives identical results with the bridge on and off
    (partition-invariance contract of the hot path)."""
    rng = np.random.RandomState(4)
    rows = _rows(rng)
    with_native = decode_image_batch(rows, 3, (48, 48), to_rgb=True)
    monkeypatch.setenv("SPARKDL_NO_NATIVE", "1")
    monkeypatch.setattr(native, "_lib", None)
    monkeypatch.setattr(native, "_tried", False)
    without = decode_image_batch(rows, 3, (48, 48), to_rgb=True)
    monkeypatch.setattr(native, "_tried", False)
    np.testing.assert_allclose(with_native, without, atol=2e-2)


def test_unknown_mode_falls_back_to_python_error():
    bad = dict(
        origin="", height=4, width=4, nChannels=3, mode=99,
        data=bytes(4 * 4 * 3),
    )
    from sparkdl_tpu.sql.types import Row

    with pytest.raises(KeyError):
        decode_image_batch([Row(**bad)], 3, (8, 8))


def test_uint8_pack_native_and_python():
    """uint8 fast path: source-size uint8 rows pack to a uint8 batch with
    identical bytes from the native and Python paths (link-byte saver)."""
    rng = np.random.RandomState(5)
    arrs = [rng.randint(0, 255, (20, 24, 3), dtype=np.uint8) for _ in range(4)]
    rows = [imageIO.imageArrayToStruct(a) for a in arrs]

    got = native.pack_image_rows_u8(rows, (20, 24), 3, bgr_to_rgb=True)
    assert got is not None and got.dtype == np.uint8
    want = np.stack([a[..., ::-1] for a in arrs])
    np.testing.assert_array_equal(got, want)

    # decode_image_batch returns the uint8 batch when the caller opts in
    batch = decode_image_batch(rows, 3, (64, 64), to_rgb=True, prefer_uint8=True)
    assert batch.dtype == np.uint8
    np.testing.assert_array_equal(batch, want)
    # and float when a resize is required
    mixed = rows + [
        imageIO.imageArrayToStruct(
            rng.randint(0, 255, (10, 12, 3), dtype=np.uint8)
        )
    ]
    fbatch = decode_image_batch(mixed, 3, (16, 16), to_rgb=True, prefer_uint8=True)
    assert fbatch.dtype == np.float32 and fbatch.shape == (5, 16, 16, 3)


def test_uint8_pack_rejects_float_rows():
    rng = np.random.RandomState(6)
    rows = [
        imageIO.imageArrayToStruct((rng.rand(8, 8, 3) * 255).astype(np.float32))
    ]
    assert native.pack_image_rows_u8(rows, (8, 8), 3) is None
    batch = decode_image_batch(rows, 3, None, prefer_uint8=True)
    assert batch.dtype == np.float32
