"""Fault injection: SIGKILL a training run mid-job, restart, resume —
plus the online-serving failure modes (worker crash, deadline expiry,
queue-full shedding).

SURVEY.md §5.3: the reference had *no* training recovery at all (driver-local
``model.fit``); Spark only protected inference jobs.  Here mid-training
orbax checkpoints make a killed fit resumable — this test proves it with a
real process kill, not a polite exception."""

import os
import signal
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

keras = pytest.importorskip("keras")

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

WORKER = """
import os, sys
os.environ["KERAS_BACKEND"] = "jax"
import jax
jax.config.update("jax_platforms", "cpu")
sys.path.insert(0, {repo!r})
from tests.test_fault_injection import build_fixtures, make_df, make_estimator
workdir = {workdir!r}
build_fixtures(workdir)
make_estimator(workdir, epochs=120).fit(make_df(workdir))
print("WORKER_FINISHED")
"""


def build_fixtures(workdir):
    os.makedirs(workdir, exist_ok=True)
    model_path = os.path.join(workdir, "model.keras")
    if not os.path.exists(model_path):
        keras.utils.set_random_seed(0)
        model = keras.Sequential(
            [keras.layers.Input(shape=(4,)), keras.layers.Dense(1)]
        )
        model.save(model_path)
    rng = np.random.RandomState(0)
    for i in range(8):
        p = os.path.join(workdir, f"x{i}.npy")
        if not os.path.exists(p):
            np.save(p, rng.rand(4).astype(np.float32))


def load_vec(uri):
    return np.load(uri)


def make_df(workdir):
    from sparkdl_tpu.sql.session import TPUSession

    spark = TPUSession.builder.master("local[*]").getOrCreate()
    rows = [
        {"uri": os.path.join(workdir, f"x{i}.npy"), "label": [float(i % 2)]}
        for i in range(8)
    ]
    return spark.createDataFrame(rows)


def make_estimator(workdir, epochs):
    from sparkdl_tpu.estimators import KerasImageFileEstimator

    return KerasImageFileEstimator(
        inputCol="uri",
        outputCol="out",
        labelCol="label",
        imageLoader=load_vec,
        modelFile=os.path.join(workdir, "model.keras"),
        kerasOptimizer="sgd",
        kerasLoss="mse",
        kerasFitParams={
            "epochs": epochs,
            "batch_size": 8,
            "learning_rate": 0.05,
            "seed": 0,
        },
        checkpointDir=os.path.join(workdir, "ckpt"),
    )


@pytest.mark.slow
def test_sigkill_mid_training_then_resume(tmp_path, caplog):
    workdir = str(tmp_path)
    build_fixtures(workdir)

    proc = subprocess.Popen(
        [sys.executable, "-c", WORKER.format(repo=_REPO, workdir=workdir)],
        env=dict(os.environ),
        stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT,
        text=True,
    )
    # wait for the first completed epoch checkpoint, then kill hard
    ckpt_root = os.path.join(workdir, "ckpt")
    deadline = time.time() + 300
    seen = None
    try:
        while time.time() < deadline:
            if proc.poll() is not None:
                out = proc.stdout.read()
                raise AssertionError(
                    f"worker exited before kill (rc={proc.returncode}):\n"
                    f"{out[-3000:]}"
                )
            for root, dirs, _ in os.walk(ckpt_root):
                for d in dirs:
                    if d.startswith("epoch_"):
                        seen = os.path.join(root, d)
            if seen:
                break
            time.sleep(0.5)
        assert seen, "no checkpoint appeared within the deadline"
        time.sleep(1.0)  # let the checkpoint finish writing
        proc.send_signal(signal.SIGKILL)
        proc.wait(timeout=60)
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait()

    # restart in-process: must resume from the surviving checkpoint and
    # run to completion
    import logging

    with caplog.at_level(
        logging.INFO, logger="sparkdl_tpu.estimators.keras_image_file_estimator"
    ):
        # identical config: the checkpoint namespace hashes the fit params,
        # so only a same-configuration restart may resume (by design)
        est = make_estimator(workdir, epochs=120)
        model = est.fit(make_df(workdir))
    assert model is not None and np.isfinite(model._training_loss)
    assert any(
        "resuming from checkpoint" in r.message for r in caplog.records
    ), "restart did not resume from the killed run's checkpoint"


# ---------------------------------------------------------------------------
# online serving faults: every failure mode must surface as a TYPED error
# on the affected request's future, leave the worker serving, and keep the
# serving.* metrics coherent.  compile=False registration runs the forward
# as plain Python, which is what makes blocking/raising forwards
# deterministic here.
# ---------------------------------------------------------------------------


class TestServingFaults:
    @pytest.fixture(autouse=True)
    def fresh_metrics(self):
        from sparkdl_tpu.utils.metrics import metrics

        metrics.reset()
        yield
        metrics.reset()

    def _blocked_server(self, **config_kw):
        """A server whose worker parks inside the forward until released:
        the deterministic way to hold requests in the queue."""
        from sparkdl_tpu.serving import ModelServer, ServingConfig

        started = threading.Event()
        release = threading.Event()

        def blocking_forward(x):
            started.set()
            assert release.wait(timeout=30.0), "test never released worker"
            return x

        cfg = ServingConfig(**{
            "max_batch": 1, "max_wait_ms": 0.0, "queue_capacity": 2,
            **config_kw,
        })
        server = ModelServer(cfg)
        server.register(
            "blocky", blocking_forward, item_shape=(2,), compile=False
        )
        return server, started, release

    def test_worker_survives_forward_crash(self):
        from sparkdl_tpu.serving import ModelServer, ServingConfig
        from sparkdl_tpu.utils.metrics import metrics

        boom = {"on": True}

        def flaky_forward(x):
            if boom["on"]:
                raise RuntimeError("injected model crash")
            return x * 2.0

        with ModelServer(ServingConfig(max_wait_ms=1.0)) as server:
            server.register(
                "flaky", flaky_forward, item_shape=(2,), compile=False
            )
            fut = server.submit(np.ones((2,), np.float32))
            # the crash lands on the request's future, not the worker
            with pytest.raises(RuntimeError, match="injected model crash"):
                fut.result(timeout=30.0)
            assert metrics.counter("serving.errors").value == 1

            # the worker survived and the endpoint keeps serving
            boom["on"] = False
            out = server.predict(np.ones((2,), np.float32), timeout=30.0)
            np.testing.assert_allclose(out, 2.0)
            ep = server.status()["endpoints"]["flaky"]
            assert ep["worker_alive"]
        snap = metrics.snapshot()
        assert snap["serving.requests"] == 2
        assert snap["serving.batches"] == 1  # only the good batch counts

    def test_deadline_expiry_mid_queue(self):
        from sparkdl_tpu.serving import DeadlineExceeded
        from sparkdl_tpu.utils.metrics import metrics

        server, started, release = self._blocked_server()
        try:
            first = server.submit(np.zeros((2,), np.float32))
            assert started.wait(timeout=30.0)
            # worker is parked inside request 1; request 2 waits behind it
            # with a deadline that expires before the worker frees up
            doomed = server.submit(
                np.zeros((2,), np.float32), deadline_ms=20.0
            )
            time.sleep(0.05)
            release.set()
            first.result(timeout=30.0)
            with pytest.raises(DeadlineExceeded, match="expired"):
                doomed.result(timeout=30.0)
            assert metrics.counter("serving.expired").value == 1
            # expired requests never reach the model: no error counted
            assert metrics.counter("serving.errors").value == 0
        finally:
            release.set()
            server.close()

    def test_queue_full_sheds_with_typed_error(self):
        from sparkdl_tpu.serving import ServerOverloaded
        from sparkdl_tpu.utils.metrics import metrics

        server, started, release = self._blocked_server(queue_capacity=2)
        try:
            first = server.submit(np.zeros((2,), np.float32))
            assert started.wait(timeout=30.0)
            # worker busy; the bounded queue admits exactly its capacity
            queued = [
                server.submit(np.full((2,), float(i), np.float32))
                for i in range(2)
            ]
            with pytest.raises(ServerOverloaded, match="load-shedding"):
                server.submit(np.zeros((2,), np.float32))
            assert metrics.counter("serving.shed").value == 1
            assert metrics.gauge("serving.queue_depth.blocky").value == 2

            # shedding didn't corrupt anything: release and drain
            release.set()
            first.result(timeout=30.0)
            for i, f in enumerate(queued):
                np.testing.assert_allclose(f.result(timeout=30.0), float(i))
            snap = metrics.snapshot()
            # the shed request still counted as admitted traffic pressure
            assert snap["serving.requests"] == 4
            assert snap["serving.queue_depth.blocky"] == 0
        finally:
            release.set()
            server.close()
