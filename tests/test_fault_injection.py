"""Fault injection: SIGKILL a training run mid-job, restart, resume —
plus the online-serving failure modes (worker crash, deadline expiry,
queue-full shedding).

SURVEY.md §5.3: the reference had *no* training recovery at all (driver-local
``model.fit``); Spark only protected inference jobs.  Here mid-training
orbax checkpoints make a killed fit resumable — this test proves it with a
real process kill, not a polite exception."""

import os
import signal
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

keras = pytest.importorskip("keras")

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

WORKER = """
import os, sys
os.environ["KERAS_BACKEND"] = "jax"
import jax
jax.config.update("jax_platforms", "cpu")
sys.path.insert(0, {repo!r})
from tests.test_fault_injection import build_fixtures, make_df, make_estimator
workdir = {workdir!r}
build_fixtures(workdir)
make_estimator(workdir, epochs=120).fit(make_df(workdir))
print("WORKER_FINISHED")
"""


def build_fixtures(workdir, n=8):
    os.makedirs(workdir, exist_ok=True)
    model_path = os.path.join(workdir, "model.keras")
    if not os.path.exists(model_path):
        keras.utils.set_random_seed(0)
        model = keras.Sequential(
            [keras.layers.Input(shape=(4,)), keras.layers.Dense(1)]
        )
        model.save(model_path)
    rng = np.random.RandomState(0)
    for i in range(n):
        p = os.path.join(workdir, f"x{i}.npy")
        if not os.path.exists(p):
            np.save(p, rng.rand(4).astype(np.float32))


def load_vec(uri):
    return np.load(uri)


def make_df(workdir, n=8):
    from sparkdl_tpu.sql.session import TPUSession

    spark = TPUSession.builder.master("local[*]").getOrCreate()
    rows = [
        {"uri": os.path.join(workdir, f"x{i}.npy"), "label": [float(i % 2)]}
        for i in range(n)
    ]
    return spark.createDataFrame(rows)


def make_estimator(workdir, epochs, ckpt="ckpt"):
    from sparkdl_tpu.estimators import KerasImageFileEstimator

    return KerasImageFileEstimator(
        inputCol="uri",
        outputCol="out",
        labelCol="label",
        imageLoader=load_vec,
        modelFile=os.path.join(workdir, "model.keras"),
        kerasOptimizer="sgd",
        kerasLoss="mse",
        kerasFitParams={
            "epochs": epochs,
            "batch_size": 8,
            "learning_rate": 0.05,
            "seed": 0,
        },
        checkpointDir=os.path.join(workdir, ckpt),
    )


def model_weights(transformer):
    """The fitted transformer's weights, loaded back from its tuned
    model file (what a bit-identical-resume assertion compares)."""
    m = keras.saving.load_model(transformer.getModelFile())
    return [np.asarray(w) for w in m.get_weights()]


@pytest.mark.slow
def test_sigkill_mid_training_then_resume(tmp_path, caplog):
    workdir = str(tmp_path)
    build_fixtures(workdir)

    proc = subprocess.Popen(
        [sys.executable, "-c", WORKER.format(repo=_REPO, workdir=workdir)],
        env=dict(os.environ),
        stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT,
        text=True,
    )
    # wait for the first completed epoch checkpoint, then kill hard
    ckpt_root = os.path.join(workdir, "ckpt")
    deadline = time.time() + 300
    seen = None
    try:
        while time.time() < deadline:
            if proc.poll() is not None:
                out = proc.stdout.read()
                raise AssertionError(
                    f"worker exited before kill (rc={proc.returncode}):\n"
                    f"{out[-3000:]}"
                )
            for root, dirs, _ in os.walk(ckpt_root):
                for d in dirs:
                    if d.startswith("epoch_"):
                        seen = os.path.join(root, d)
            if seen:
                break
            time.sleep(0.5)
        assert seen, "no checkpoint appeared within the deadline"
        time.sleep(1.0)  # let the checkpoint finish writing
        proc.send_signal(signal.SIGKILL)
        proc.wait(timeout=60)
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait()

    # restart in-process: must resume from the surviving checkpoint and
    # run to completion
    import logging

    with caplog.at_level(
        logging.INFO, logger="sparkdl_tpu.estimators.keras_image_file_estimator"
    ):
        # identical config: the checkpoint namespace hashes the fit params,
        # so only a same-configuration restart may resume (by design)
        est = make_estimator(workdir, epochs=120)
        model = est.fit(make_df(workdir))
    assert model is not None and np.isfinite(model._training_loss)
    assert any(
        "resuming from checkpoint" in r.message for r in caplog.records
    ), "restart did not resume from the killed run's checkpoint"


# ---------------------------------------------------------------------------
# deterministic process death at the WORST instant: between the checkpoint
# payload's async save dispatch and the commit marker.  The SIGKILL test
# above kills at "some point after a checkpoint appeared"; this one uses
# the fault-injection harness's `kill` action (os._exit(9), no atexit, no
# finally) fired at the `estimator.checkpoint_saved` site — after
# save_epoch(epoch_2) dispatched but before its background commit can
# finalize — so the commit-marker protocol's "never resume an unfinalized
# epoch" guarantee is pinned exactly, not probabilistically.
# ---------------------------------------------------------------------------

KILL_AT_COMMIT_WORKER = """
import os, sys
os.environ["KERAS_BACKEND"] = "jax"
import jax
jax.config.update("jax_platforms", "cpu")
sys.path.insert(0, {repo!r})
from tests.test_fault_injection import build_fixtures, make_df, make_estimator
workdir = {workdir!r}
build_fixtures(workdir)
make_estimator(workdir, epochs=4).fit(make_df(workdir))
print("WORKER_FINISHED")
"""


def test_kill_between_payload_write_and_commit_marker(tmp_path, caplog):
    from sparkdl_tpu.estimators import checkpointing

    workdir = str(tmp_path)
    build_fixtures(workdir)

    env = dict(os.environ)
    # die on the SECOND save dispatch: epoch_1 is fully committed by then
    # (orbax serializes async saves), epoch_2's commit is in flight
    env["SPARKDL_FAULT_PLAN"] = (
        '[{"site": "estimator.checkpoint_saved", "kill": true, "at": 2}]'
    )
    proc = subprocess.run(
        [
            sys.executable,
            "-c",
            KILL_AT_COMMIT_WORKER.format(repo=_REPO, workdir=workdir),
        ],
        env=env,
        capture_output=True,
        text=True,
        timeout=300,
    )
    assert proc.returncode == 9, (
        f"worker should have died via the injected kill (rc="
        f"{proc.returncode}):\n{(proc.stdout + proc.stderr)[-3000:]}"
    )
    assert "WORKER_FINISHED" not in proc.stdout

    est = make_estimator(workdir, epochs=4)
    ckpt_dir = os.path.join(workdir, "ckpt")
    namespace = est._ckpt_namespace()
    committed = checkpointing.committed_epochs(ckpt_dir, namespace)
    assert committed == [1], (
        f"exactly epoch_1 must be committed after the mid-commit death; "
        f"got {committed}"
    )

    # restart with the identical configuration: resume must pick epoch 1,
    # never the unfinalized epoch_2 leftovers
    import logging

    with caplog.at_level(
        logging.INFO,
        logger="sparkdl_tpu.estimators.keras_image_file_estimator",
    ):
        model = est.fit(make_df(workdir))
    assert model is not None and np.isfinite(model._training_loss)
    resumes = [
        r.message for r in caplog.records
        if "resuming from checkpoint" in r.message
    ]
    assert resumes and "epoch 1" in resumes[0], (
        f"restart must resume from the committed epoch 1, got {resumes}"
    )


def test_preemption_mid_epoch_resumes_bit_identical(tmp_path, caplog):
    """Acceptance (d): a preemption delivered mid-epoch stops at the next
    safe point, the last COMPLETED epoch's checkpoint is flushed, and a
    re-fit resumes to weights bit-identical to an uninterrupted run."""
    from sparkdl_tpu.estimators import checkpointing
    from sparkdl_tpu.resilience import FaultPlan, Preempted, active_plan

    workdir = str(tmp_path)
    # 16 rows / batch_size 8 = 2 steps per epoch, so a preemption can
    # land strictly inside an epoch
    build_fixtures(workdir, n=16)
    df = make_df(workdir, n=16)

    # the uninterrupted reference: 3 epochs straight through
    baseline = make_estimator(workdir, epochs=3, ckpt="ckpt_base").fit(df)

    # preempt at global step 3 = epoch 2, step 1: the flag is set there
    # and delivered at the NEXT safe point (epoch 2, step 2), so epoch 2
    # never completes and only epoch_1 may be committed
    est = make_estimator(workdir, epochs=3, ckpt="ckpt_resume")
    plan = FaultPlan().add("estimator.step", preempt=True, at=3)
    with active_plan(plan):
        with pytest.raises(Preempted, match="injected preemption"):
            est.fit(df)

    ckpt_dir = os.path.join(workdir, "ckpt_resume")
    namespace = est._ckpt_namespace()
    assert checkpointing.committed_epochs(ckpt_dir, namespace) == [1], (
        "the preempted fit must flush exactly the last completed epoch"
    )

    import logging

    with caplog.at_level(
        logging.INFO,
        logger="sparkdl_tpu.estimators.keras_image_file_estimator",
    ):
        resumed = make_estimator(workdir, epochs=3, ckpt="ckpt_resume").fit(
            df
        )
    assert any(
        "resuming from checkpoint epoch 1" in r.message
        for r in caplog.records
    )

    # bit-identical, not allclose: epoch replay + lossless float32
    # checkpoints make the resumed run reproduce the uninterrupted one
    # exactly
    w_base = model_weights(baseline)
    w_resumed = model_weights(resumed)
    assert len(w_base) == len(w_resumed)
    for a, b in zip(w_base, w_resumed):
        np.testing.assert_array_equal(a, b)
    assert baseline._training_loss == resumed._training_loss


def test_preemption_at_epoch_boundary_resumes(tmp_path, caplog):
    """The ``estimator.epoch`` fault site (the one spot the step/commit
    tests above never hit): a preemption flagged exactly at the epoch
    boundary — after epoch 1's steps, before its checkpoint dispatch —
    must still flush epoch 1's checkpoint and let an identical re-fit
    resume from it.  Found by sparkdl_check's fault-site-coverage rule:
    every fired site needs at least one test that proves recovery."""
    from sparkdl_tpu.estimators import checkpointing
    from sparkdl_tpu.resilience import FaultPlan, Preempted, active_plan

    workdir = str(tmp_path)
    build_fixtures(workdir)
    df = make_df(workdir)

    est = make_estimator(workdir, epochs=2)
    plan = FaultPlan().add("estimator.epoch", preempt=True, at=1)
    with active_plan(plan):
        with pytest.raises(Preempted, match="injected preemption"):
            est.fit(df)

    ckpt_dir = os.path.join(workdir, "ckpt")
    namespace = est._ckpt_namespace()
    assert checkpointing.committed_epochs(ckpt_dir, namespace) == [1], (
        "the epoch-boundary preemption must still commit epoch 1"
    )

    import logging

    with caplog.at_level(
        logging.INFO,
        logger="sparkdl_tpu.estimators.keras_image_file_estimator",
    ):
        model = make_estimator(workdir, epochs=2).fit(df)
    assert model is not None and np.isfinite(model._training_loss)
    assert any(
        "resuming from checkpoint epoch 1" in r.message
        for r in caplog.records
    ), "restart did not resume from the epoch committed before preemption"


# ---------------------------------------------------------------------------
# online serving faults: every failure mode must surface as a TYPED error
# on the affected request's future, leave the worker serving, and keep the
# serving.* metrics coherent.  compile=False registration runs the forward
# as plain Python, which is what makes blocking/raising forwards
# deterministic here.
# ---------------------------------------------------------------------------


class TestServingFaults:
    @pytest.fixture(autouse=True)
    def fresh_metrics(self):
        from sparkdl_tpu.utils.metrics import metrics

        metrics.reset()
        yield
        metrics.reset()

    def _blocked_server(self, **config_kw):
        """A server whose worker parks inside the forward until released:
        the deterministic way to hold requests in the queue."""
        from sparkdl_tpu.serving import ModelServer, ServingConfig

        started = threading.Event()
        release = threading.Event()

        def blocking_forward(x):
            started.set()
            assert release.wait(timeout=30.0), "test never released worker"
            return x

        cfg = ServingConfig(**{
            "max_batch": 1, "max_wait_ms": 0.0, "queue_capacity": 2,
            **config_kw,
        })
        server = ModelServer(cfg)
        server.register(
            "blocky", blocking_forward, item_shape=(2,), compile=False
        )
        return server, started, release

    def test_worker_survives_forward_crash(self):
        from sparkdl_tpu.serving import ModelServer, ServingConfig
        from sparkdl_tpu.utils.metrics import metrics

        boom = {"on": True}

        def flaky_forward(x):
            if boom["on"]:
                raise RuntimeError("injected model crash")
            return x * 2.0

        with ModelServer(ServingConfig(max_wait_ms=1.0)) as server:
            server.register(
                "flaky", flaky_forward, item_shape=(2,), compile=False
            )
            fut = server.submit(np.ones((2,), np.float32))
            # the crash lands on the request's future, not the worker
            with pytest.raises(RuntimeError, match="injected model crash"):
                fut.result(timeout=30.0)
            assert metrics.counter("serving.errors").value == 1

            # the worker survived and the endpoint keeps serving
            boom["on"] = False
            out = server.predict(np.ones((2,), np.float32), timeout=30.0)
            np.testing.assert_allclose(out, 2.0)
            ep = server.status()["endpoints"]["flaky"]
            assert ep["worker_alive"]
        snap = metrics.snapshot()
        assert snap["serving.requests"] == 2
        assert snap["serving.batches"] == 1  # only the good batch counts

    def test_deadline_expiry_mid_queue(self):
        from sparkdl_tpu.serving import DeadlineExceeded
        from sparkdl_tpu.utils.metrics import metrics

        server, started, release = self._blocked_server()
        try:
            first = server.submit(np.zeros((2,), np.float32))
            assert started.wait(timeout=30.0)
            # worker is parked inside request 1; request 2 waits behind it
            # with a deadline that expires before the worker frees up
            doomed = server.submit(
                np.zeros((2,), np.float32), deadline_ms=20.0
            )
            time.sleep(0.05)
            release.set()
            first.result(timeout=30.0)
            with pytest.raises(DeadlineExceeded, match="expired"):
                doomed.result(timeout=30.0)
            assert metrics.counter("serving.expired").value == 1
            # expired requests never reach the model: no error counted
            assert metrics.counter("serving.errors").value == 0
        finally:
            release.set()
            server.close()

    def test_queue_full_sheds_with_typed_error(self):
        from sparkdl_tpu.serving import ServerOverloaded
        from sparkdl_tpu.utils.metrics import metrics

        server, started, release = self._blocked_server(queue_capacity=2)
        try:
            first = server.submit(np.zeros((2,), np.float32))
            assert started.wait(timeout=30.0)
            # worker busy; the bounded queue admits exactly its capacity
            queued = [
                server.submit(np.full((2,), float(i), np.float32))
                for i in range(2)
            ]
            with pytest.raises(ServerOverloaded, match="load-shedding"):
                server.submit(np.zeros((2,), np.float32))
            assert metrics.counter("serving.shed").value == 1
            assert metrics.gauge("serving.queue_depth.blocky").value == 2

            # shedding didn't corrupt anything: release and drain
            release.set()
            first.result(timeout=30.0)
            for i, f in enumerate(queued):
                np.testing.assert_allclose(f.result(timeout=30.0), float(i))
            snap = metrics.snapshot()
            # the shed request still counted as admitted traffic pressure
            assert snap["serving.requests"] == 4
            assert snap["serving.queue_depth.blocky"] == 0
        finally:
            release.set()
            server.close()
