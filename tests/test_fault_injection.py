"""Fault injection: SIGKILL a training run mid-job, restart, resume.

SURVEY.md §5.3: the reference had *no* training recovery at all (driver-local
``model.fit``); Spark only protected inference jobs.  Here mid-training
orbax checkpoints make a killed fit resumable — this test proves it with a
real process kill, not a polite exception."""

import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

keras = pytest.importorskip("keras")

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

WORKER = """
import os, sys
os.environ["KERAS_BACKEND"] = "jax"
import jax
jax.config.update("jax_platforms", "cpu")
sys.path.insert(0, {repo!r})
from tests.test_fault_injection import build_fixtures, make_df, make_estimator
workdir = {workdir!r}
build_fixtures(workdir)
make_estimator(workdir, epochs=120).fit(make_df(workdir))
print("WORKER_FINISHED")
"""


def build_fixtures(workdir):
    os.makedirs(workdir, exist_ok=True)
    model_path = os.path.join(workdir, "model.keras")
    if not os.path.exists(model_path):
        keras.utils.set_random_seed(0)
        model = keras.Sequential(
            [keras.layers.Input(shape=(4,)), keras.layers.Dense(1)]
        )
        model.save(model_path)
    rng = np.random.RandomState(0)
    for i in range(8):
        p = os.path.join(workdir, f"x{i}.npy")
        if not os.path.exists(p):
            np.save(p, rng.rand(4).astype(np.float32))


def load_vec(uri):
    return np.load(uri)


def make_df(workdir):
    from sparkdl_tpu.sql.session import TPUSession

    spark = TPUSession.builder.master("local[*]").getOrCreate()
    rows = [
        {"uri": os.path.join(workdir, f"x{i}.npy"), "label": [float(i % 2)]}
        for i in range(8)
    ]
    return spark.createDataFrame(rows)


def make_estimator(workdir, epochs):
    from sparkdl_tpu.estimators import KerasImageFileEstimator

    return KerasImageFileEstimator(
        inputCol="uri",
        outputCol="out",
        labelCol="label",
        imageLoader=load_vec,
        modelFile=os.path.join(workdir, "model.keras"),
        kerasOptimizer="sgd",
        kerasLoss="mse",
        kerasFitParams={
            "epochs": epochs,
            "batch_size": 8,
            "learning_rate": 0.05,
            "seed": 0,
        },
        checkpointDir=os.path.join(workdir, "ckpt"),
    )


@pytest.mark.slow
def test_sigkill_mid_training_then_resume(tmp_path, caplog):
    workdir = str(tmp_path)
    build_fixtures(workdir)

    proc = subprocess.Popen(
        [sys.executable, "-c", WORKER.format(repo=_REPO, workdir=workdir)],
        env=dict(os.environ),
        stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT,
        text=True,
    )
    # wait for the first completed epoch checkpoint, then kill hard
    ckpt_root = os.path.join(workdir, "ckpt")
    deadline = time.time() + 300
    seen = None
    try:
        while time.time() < deadline:
            if proc.poll() is not None:
                out = proc.stdout.read()
                raise AssertionError(
                    f"worker exited before kill (rc={proc.returncode}):\n"
                    f"{out[-3000:]}"
                )
            for root, dirs, _ in os.walk(ckpt_root):
                for d in dirs:
                    if d.startswith("epoch_"):
                        seen = os.path.join(root, d)
            if seen:
                break
            time.sleep(0.5)
        assert seen, "no checkpoint appeared within the deadline"
        time.sleep(1.0)  # let the checkpoint finish writing
        proc.send_signal(signal.SIGKILL)
        proc.wait(timeout=60)
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait()

    # restart in-process: must resume from the surviving checkpoint and
    # run to completion
    import logging

    with caplog.at_level(
        logging.INFO, logger="sparkdl_tpu.estimators.keras_image_file_estimator"
    ):
        # identical config: the checkpoint namespace hashes the fit params,
        # so only a same-configuration restart may resume (by design)
        est = make_estimator(workdir, epochs=120)
        model = est.fit(make_df(workdir))
    assert model is not None and np.isfinite(model._training_loss)
    assert any(
        "resuming from checkpoint" in r.message for r in caplog.records
    ), "restart did not resume from the killed run's checkpoint"
