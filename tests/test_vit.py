"""ViT model + tensor-parallel training tests (8-device CPU mesh).

Oracles per SURVEY.md §4: the sharded/SP variants must reproduce the plain
single-device forward and training step on the same arrays.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

import optax

from sparkdl_tpu.models.vit import VIT_VARIANTS, ViT
from sparkdl_tpu.parallel.context import ring_attention
from sparkdl_tpu.parallel.tp import (
    VIT_TP_RULES,
    init_tp_train_state,
    make_tp_train_step,
    param_path_specs,
)
from sparkdl_tpu.parallel.trainer import init_train_state

# tiny geometry so CPU tests stay fast; same code path as ViT-B/16
TINY = "ViT-Ti/16"
IMG = 32


def _tiny_vit(**kw):
    return ViT(variant=TINY, num_classes=4, image_size=IMG, **kw)


def _variables(module, seed=0):
    x = jnp.zeros((1, IMG, IMG, 3), jnp.float32)
    return module.init(jax.random.PRNGKey(seed), x)


def test_vit_shapes_and_features():
    m = _tiny_vit()
    v = _variables(m)
    x = jnp.asarray(np.random.RandomState(0).rand(2, IMG, IMG, 3), jnp.float32)
    logits = m.apply(v, x)
    feats = m.apply(v, x, features_only=True)
    dim = VIT_VARIANTS[TINY][1]
    assert logits.shape == (2, 4)
    assert feats.shape == (2, dim)


def test_vit_b16_geometry():
    """The flagship stretch variant builds with the published geometry."""
    patch, dim, depth, heads, mlp = VIT_VARIANTS["ViT-B/16"]
    assert (patch, dim, depth, heads, mlp) == (16, 768, 12, 12, 3072)
    m = ViT(variant="ViT-B/16", image_size=224)
    shapes = jax.eval_shape(
        m.init, jax.random.PRNGKey(0), jnp.zeros((1, 224, 224, 3), jnp.float32)
    )
    n_params = sum(
        np.prod(l.shape) for l in jax.tree_util.tree_leaves(shapes)
    )
    assert 85e6 < n_params < 90e6  # ViT-B/16 is ~86M params


def test_vit_sp_attention_matches_full():
    """Same params, attention swapped to sequence-parallel ring over an
    8-way seq axis: forward must match the dense forward (the checkpoint
    is schedule-independent).  A ViT's CLS token breaks seq divisibility by
    design, so the SP schedule is pad_tokens_for_sp (pad + mask + slice)."""
    from sparkdl_tpu.parallel.context import pad_tokens_for_sp

    mesh = Mesh(np.asarray(jax.devices()[:8]).reshape(8), ("seq",))

    dense = _tiny_vit()
    v = _variables(dense)
    x = jnp.asarray(np.random.RandomState(1).rand(2, IMG, IMG, 3), jnp.float32)
    want = dense.apply(v, x, features_only=True)

    sp = _tiny_vit(attn_impl=pad_tokens_for_sp(mesh, "seq", "ring"))
    got = sp.apply(v, x, features_only=True)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), atol=5e-4, rtol=1e-4
    )


def test_pad_tokens_for_sp_masks_pad_keys():
    """Zero-padded K rows would otherwise grab exp(0) softmax mass — the
    padded schedule must mask them (kv_len), reproducing dense attention
    on a 10-token sequence over an 8-way ring exactly."""
    from sparkdl_tpu.parallel.context import full_attention, pad_tokens_for_sp

    mesh = Mesh(np.asarray(jax.devices()[:8]).reshape(8), ("seq",))
    rng = np.random.RandomState(2)
    q, k, v = (
        jnp.asarray(rng.randn(1, 10, 8, 8).astype(np.float32))
        for _ in range(3)
    )
    want = full_attention(q, k, v)
    for impl in ("ring", "ulysses"):
        got = pad_tokens_for_sp(mesh, "seq", impl)(q, k, v)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), atol=2e-5
        )


def test_tp_train_step_matches_single_device():
    """DP x TP GSPMD step == unsharded step: same loss trajectory."""
    mesh = Mesh(np.asarray(jax.devices()[:8]).reshape(2, 4), ("data", "model"))
    module = _tiny_vit()
    variables = _variables(module)
    tx = optax.sgd(0.05)

    rng = np.random.RandomState(3)
    x = rng.rand(8, IMG, IMG, 3).astype(np.float32)
    y = rng.randint(0, 4, 8)

    def loss_fn(params, batch):
        logits = module.apply(params, batch["x"])
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, batch["y"]
        ).mean()

    # oracle: plain single-device training loop
    state = init_train_state(variables, tx)

    from sparkdl_tpu.parallel.trainer import TrainState

    @jax.jit
    def plain_step(state, batch):
        loss, grads = jax.value_and_grad(loss_fn)(state.params, batch)
        updates, opt_state = tx.update(grads, state.opt_state, state.params)
        params = optax.apply_updates(state.params, updates)
        return (
            TrainState(params, opt_state, state.step + 1, state.batch_stats),
            loss,
        )

    batch = {"x": jnp.asarray(x), "y": jnp.asarray(y)}
    losses_plain = []
    for _ in range(3):
        state, loss = plain_step(state, batch)
        losses_plain.append(float(loss))

    # TP: shard params by Megatron rules, batch by data axis
    specs = param_path_specs(variables, VIT_TP_RULES, model_axis="model")
    tp_state = init_tp_train_state(variables, tx, mesh, specs)
    step_fn = make_tp_train_step(loss_fn, tx, mesh, specs)
    data_sharding = NamedSharding(mesh, P("data"))
    tp_batch = {
        "x": jax.device_put(jnp.asarray(x), NamedSharding(mesh, P("data", None, None, None))),
        "y": jax.device_put(jnp.asarray(y), data_sharding),
    }
    losses_tp = []
    for _ in range(3):
        tp_state, loss = step_fn(tp_state, tp_batch)
        losses_tp.append(float(loss))

    np.testing.assert_allclose(losses_tp, losses_plain, rtol=2e-4, atol=2e-5)

    # and the sharded params really are sharded over the model axis
    qkv_kernel = tp_state.params["params"]["block_0"]["qkv"]["kernel"]
    assert qkv_kernel.sharding.spec == P(None, "model")
