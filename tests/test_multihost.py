"""Multi-host training tests: 2 processes x 4 virtual CPU devices.

The reference delegated all multi-node behavior to Spark and tested only
``local[*]`` (SURVEY.md §4); its training never left the driver at all
(§3.2).  Here the multi-host path is first-class, so it gets a real
multi-process test: two OS processes form a global 8-device mesh via
``jax.distributed`` + gloo CPU collectives, each loads only its own shard
of the dataset, and ``KerasImageFileEstimator.fit`` runs the global
shard_map step with cross-process gradient allreduce.

Oracle invariant: with a full-batch step (batch_size == n_rows) the
multi-host result must equal the single-process fit on the same data —
the gradient is the mean over the identical row set either way.
"""

import json
import os
import socket
import subprocess
import sys

import numpy as np
import pytest

keras = pytest.importorskip("keras")

_HERE = os.path.dirname(os.path.abspath(__file__))
_REPO = os.path.dirname(_HERE)

N_ROWS = 16
DIM = 4
FIT_PARAMS = {
    "epochs": 3,
    "batch_size": N_ROWS,  # full batch -> order-invariant oracle
    "learning_rate": 0.05,
    "seed": 0,
}


def _free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _make_workdir(tmp_path):
    """Deterministic (vector-file, label) dataset + tiny linear model."""
    rng = np.random.RandomState(42)
    w_true = rng.randn(DIM).astype(np.float32)
    rows = []
    for i in range(N_ROWS):
        v = rng.randn(DIM).astype(np.float32)
        path = str(tmp_path / f"x_{i}.npy")
        np.save(path, v)
        rows.append((path, float(v @ w_true)))

    keras.utils.set_random_seed(7)
    model = keras.Sequential(
        [keras.layers.Input(shape=(DIM,)), keras.layers.Dense(1)]
    )
    model_path = str(tmp_path / "model.keras")
    model.save(model_path)

    with open(tmp_path / "meta.json", "w") as f:
        json.dump({"rows": rows, "fit_params": FIT_PARAMS}, f)
    return rows, model_path


def _single_process_fit(tpu_session, rows, model_path):
    """The oracle: same fit in this (single-host, 8-device) process."""
    from sparkdl_tpu.estimators import KerasImageFileEstimator
    from tests.multihost_worker import load_vector

    df = tpu_session.createDataFrame(
        [{"uri": u, "label": [float(l)]} for u, l in rows]
    )
    est = KerasImageFileEstimator(
        inputCol="uri",
        outputCol="out",
        labelCol="label",
        imageLoader=load_vector,
        modelFile=model_path,
        kerasOptimizer="sgd",
        kerasLoss="mse",
        kerasFitParams=dict(FIT_PARAMS),
    )
    fitted = est.fit(df)
    m = keras.saving.load_model(fitted.getModelFile(), compile=False)
    return [np.asarray(w) for w in m.get_weights()]


def _launch_workers(tmp_path, port, phase, env):
    """Start the 2 worker processes with file-backed stdout (piped workers
    deadlock once output passes the 64KB pipe buffer — collectives stall
    the whole job).  Returns (procs, open log handles)."""
    logs = [
        open(tmp_path / f"{phase}_worker{pid}.log", "w+") for pid in range(2)
    ]
    procs = [
        subprocess.Popen(
            [
                sys.executable,
                os.path.join(_HERE, "multihost_worker.py"),
                str(pid), "2", str(port), str(tmp_path),
            ],
            env=env,
            stdout=logs[pid],
            stderr=subprocess.STDOUT,
            text=True,
        )
        for pid in range(2)
    ]
    return procs, logs


def _wait_workers(procs, logs, what="worker"):
    """Wait for every worker, collect its file-backed log, kill stragglers,
    and assert clean exits; returns the log texts."""
    outs = []
    try:
        for p in procs:
            p.wait(timeout=600)
        for lg in logs:
            lg.seek(0)
            outs.append(lg.read())
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait()
        for lg in logs:
            lg.close()
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"{what} {pid} failed:\n{out[-4000:]}"
        assert f"MULTIHOST_WORKER_OK {pid}" in out
    return outs


@pytest.mark.slow
def test_two_process_fit_matches_single_process(tmp_path, tpu_session):
    rows, model_path = _make_workdir(tmp_path)
    oracle = _single_process_fit(tpu_session, rows, model_path)

    port = _free_port()
    env = dict(os.environ)
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    procs, logs = _launch_workers(tmp_path, port, "fit", env)
    _wait_workers(procs, logs)

    w0 = np.load(tmp_path / "weights_proc0.npz")
    w1 = np.load(tmp_path / "weights_proc1.npz")
    # both processes hold the identical replicated result
    for k in w0.files:
        np.testing.assert_array_equal(w0[k], w1[k])
    # and it matches the single-process oracle (same global row set per
    # step; tolerance covers collective reduction-order float drift)
    for got, want in zip([w0[k] for k in w0.files], oracle):
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


@pytest.mark.slow
def test_two_process_transform_shards_match_single_process(
    tmp_path, tpu_session
):
    """Multi-host inference: each host transforms only its own row shard
    (the Spark-executor analog — embarrassingly parallel, no collectives);
    the reassembled shards must equal one single-process transform."""
    rows, model_path = _make_workdir(tmp_path)
    with open(tmp_path / "meta.json", "w") as f:
        json.dump({"rows": rows, "phase": "transform"}, f)

    # single-process oracle over the full row set
    from sparkdl_tpu.transformers.keras_image import KerasImageFileTransformer
    from tests.multihost_worker import load_vector

    df = tpu_session.createDataFrame([{"uri": u} for u, _ in rows])
    t = KerasImageFileTransformer(
        inputCol="uri", outputCol="out", modelFile=model_path,
        imageLoader=load_vector,
    )
    oracle = np.stack(
        [np.asarray(r.out.toArray()) for r in t.transform(df).collect()]
    )

    env = dict(os.environ)
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    procs, logs = _launch_workers(tmp_path, _free_port(), "transform", env)
    _wait_workers(procs, logs)

    got = np.full_like(oracle, np.nan)
    covered = np.zeros(len(rows), dtype=bool)
    for pid in range(2):
        shard = np.load(tmp_path / f"transform_proc{pid}.npz")
        got[shard["indices"]] = shard["outputs"]
        covered[shard["indices"]] = True
    assert covered.all(), "host shards must cover every row exactly"
    np.testing.assert_allclose(got, oracle, rtol=1e-5, atol=1e-6)


@pytest.mark.slow
def test_elastic_restart_resumes_multihost_fit(tmp_path):
    """Driver re-dispatch (SURVEY.md §5.3): kill one host of a 2-process
    fit mid-training, tear the job down, relaunch — the fresh job resumes
    from the surviving process-0 checkpoint instead of restarting."""
    import signal
    import time

    rows, model_path = _make_workdir(tmp_path)
    # long job with per-epoch checkpoints
    meta = {
        "rows": rows,
        "fit_params": dict(FIT_PARAMS, epochs=300),
        "checkpoint_dir": str(tmp_path / "ckpt"),
    }
    with open(tmp_path / "meta.json", "w") as f:
        json.dump(meta, f)

    env = dict(os.environ)
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")

    procs, logs = _launch_workers(tmp_path, _free_port(), "phase1", env)
    ckpt_root = tmp_path / "ckpt"
    try:
        # wait for a committed epoch checkpoint
        import re

        deadline = time.time() + 400
        seen = False
        while time.time() < deadline and not seen:
            for root, dirs, _files in os.walk(ckpt_root):
                # only a FINALIZED checkpoint counts: orbax writes
                # epoch_N.orbax-checkpoint-tmp-<ts> and renames on commit
                if any(re.fullmatch(r"epoch_\d+", d) for d in dirs):
                    seen = True
            for pid, p in enumerate(procs):
                if p.poll() is not None:
                    logs[pid].seek(0)
                    raise AssertionError(
                        "worker exited before any checkpoint:\n"
                        + logs[pid].read()[-3000:]
                    )
            time.sleep(0.5)
        assert seen, "no checkpoint appeared"
        # host failure: SIGKILL process 1; the driver (this test) detects
        # it and tears down the whole job — restart-based elasticity
        procs[1].send_signal(signal.SIGKILL)
        procs[1].wait(timeout=60)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
            p.wait()
        for lg in logs:
            lg.close()

    # re-dispatch: fresh coordinator, fresh processes, same config
    procs, logs = _launch_workers(tmp_path, _free_port(), "phase2", env)
    outs = _wait_workers(procs, logs, what="relaunched worker")
    assert any("resuming from checkpoint" in out for out in outs), (
        "relaunched job did not resume from the surviving checkpoint"
    )


@pytest.mark.slow
def test_two_process_streaming_fit_matches_in_memory(tmp_path, tpu_session):
    """The beyond-RAM pod scenario (VERDICT r2 missing #4): multi-host fit
    with the streaming loader (URIs host-side, batches loaded+prefetched on
    demand) must equal the single-process *in-memory* fit — composing the
    loaders' batch-identical contract with the DP==single-process oracle
    invariant."""
    rows, model_path = _make_workdir(tmp_path)
    oracle = _single_process_fit(tpu_session, rows, model_path)

    meta = {
        "rows": rows,
        "fit_params": dict(FIT_PARAMS, streaming=True),
    }
    with open(tmp_path / "meta.json", "w") as f:
        json.dump(meta, f)

    port = _free_port()
    env = dict(os.environ)
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    procs, logs = _launch_workers(tmp_path, port, "streamfit", env)
    _wait_workers(procs, logs, what="streaming worker")

    w0 = np.load(tmp_path / "weights_proc0.npz")
    w1 = np.load(tmp_path / "weights_proc1.npz")
    for k in w0.files:
        np.testing.assert_array_equal(w0[k], w1[k])
    for got, want in zip([w0[k] for k in w0.files], oracle):
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)
