"""Multi-host training tests: 2 processes x 4 virtual CPU devices.

The reference delegated all multi-node behavior to Spark and tested only
``local[*]`` (SURVEY.md §4); its training never left the driver at all
(§3.2).  Here the multi-host path is first-class, so it gets a real
multi-process test: two OS processes form a global 8-device mesh via
``jax.distributed`` + gloo CPU collectives, each loads only its own shard
of the dataset, and ``KerasImageFileEstimator.fit`` runs the global
shard_map step with cross-process gradient allreduce.

Oracle invariant: with a full-batch step (batch_size == n_rows) the
multi-host result must equal the single-process fit on the same data —
the gradient is the mean over the identical row set either way.
"""

import json
import os
import socket
import subprocess
import sys

import numpy as np
import pytest

keras = pytest.importorskip("keras")

_HERE = os.path.dirname(os.path.abspath(__file__))
_REPO = os.path.dirname(_HERE)

N_ROWS = 16
DIM = 4
FIT_PARAMS = {
    "epochs": 3,
    "batch_size": N_ROWS,  # full batch -> order-invariant oracle
    "learning_rate": 0.05,
    "seed": 0,
}


def _free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _make_workdir(tmp_path):
    """Deterministic (vector-file, label) dataset + tiny linear model."""
    rng = np.random.RandomState(42)
    w_true = rng.randn(DIM).astype(np.float32)
    rows = []
    for i in range(N_ROWS):
        v = rng.randn(DIM).astype(np.float32)
        path = str(tmp_path / f"x_{i}.npy")
        np.save(path, v)
        rows.append((path, float(v @ w_true)))

    keras.utils.set_random_seed(7)
    model = keras.Sequential(
        [keras.layers.Input(shape=(DIM,)), keras.layers.Dense(1)]
    )
    model_path = str(tmp_path / "model.keras")
    model.save(model_path)

    with open(tmp_path / "meta.json", "w") as f:
        json.dump({"rows": rows, "fit_params": FIT_PARAMS}, f)
    return rows, model_path


def _single_process_fit(tpu_session, rows, model_path):
    """The oracle: same fit in this (single-host, 8-device) process."""
    from sparkdl_tpu.estimators import KerasImageFileEstimator
    from tests.multihost_worker import load_vector

    df = tpu_session.createDataFrame(
        [{"uri": u, "label": [float(l)]} for u, l in rows]
    )
    est = KerasImageFileEstimator(
        inputCol="uri",
        outputCol="out",
        labelCol="label",
        imageLoader=load_vector,
        modelFile=model_path,
        kerasOptimizer="sgd",
        kerasLoss="mse",
        kerasFitParams=dict(FIT_PARAMS),
    )
    fitted = est.fit(df)
    m = keras.saving.load_model(fitted.getModelFile(), compile=False)
    return [np.asarray(w) for w in m.get_weights()]


def _launch_workers(tmp_path, port, phase, env):
    """Start the 2 worker processes with file-backed stdout (piped workers
    deadlock once output passes the 64KB pipe buffer — collectives stall
    the whole job).  Returns (procs, open log handles)."""
    logs = [
        open(tmp_path / f"{phase}_worker{pid}.log", "w+") for pid in range(2)
    ]
    procs = [
        subprocess.Popen(
            [
                sys.executable,
                os.path.join(_HERE, "multihost_worker.py"),
                str(pid), "2", str(port), str(tmp_path),
            ],
            env=env,
            stdout=logs[pid],
            stderr=subprocess.STDOUT,
            text=True,
        )
        for pid in range(2)
    ]
    return procs, logs


def _wait_workers(procs, logs, what="worker"):
    """Wait for every worker, collect its file-backed log, kill stragglers,
    and assert clean exits; returns the log texts."""
    outs = []
    try:
        for p in procs:
            p.wait(timeout=600)
        for lg in logs:
            lg.seek(0)
            outs.append(lg.read())
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait()
        for lg in logs:
            lg.close()
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"{what} {pid} failed:\n{out[-4000:]}"
        assert f"MULTIHOST_WORKER_OK {pid}" in out
    return outs


@pytest.mark.slow
def test_two_process_fit_matches_single_process(tmp_path, tpu_session):
    rows, model_path = _make_workdir(tmp_path)
    oracle = _single_process_fit(tpu_session, rows, model_path)

    port = _free_port()
    env = dict(os.environ)
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    procs, logs = _launch_workers(tmp_path, port, "fit", env)
    _wait_workers(procs, logs)

    w0 = np.load(tmp_path / "weights_proc0.npz")
    w1 = np.load(tmp_path / "weights_proc1.npz")
    # both processes hold the identical replicated result
    for k in w0.files:
        np.testing.assert_array_equal(w0[k], w1[k])
    # and it matches the single-process oracle (same global row set per
    # step; tolerance covers collective reduction-order float drift)
    for got, want in zip([w0[k] for k in w0.files], oracle):
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


@pytest.mark.slow
def test_two_process_transform_shards_match_single_process(
    tmp_path, tpu_session
):
    """Multi-host inference: each host transforms only its own row shard
    (the Spark-executor analog — embarrassingly parallel, no collectives);
    the reassembled shards must equal one single-process transform."""
    rows, model_path = _make_workdir(tmp_path)
    with open(tmp_path / "meta.json", "w") as f:
        json.dump({"rows": rows, "phase": "transform"}, f)

    # single-process oracle over the full row set
    from sparkdl_tpu.transformers.keras_image import KerasImageFileTransformer
    from tests.multihost_worker import load_vector

    df = tpu_session.createDataFrame([{"uri": u} for u, _ in rows])
    t = KerasImageFileTransformer(
        inputCol="uri", outputCol="out", modelFile=model_path,
        imageLoader=load_vector,
    )
    oracle = np.stack(
        [np.asarray(r.out.toArray()) for r in t.transform(df).collect()]
    )

    env = dict(os.environ)
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    procs, logs = _launch_workers(tmp_path, _free_port(), "transform", env)
    _wait_workers(procs, logs)

    got = np.full_like(oracle, np.nan)
    covered = np.zeros(len(rows), dtype=bool)
    for pid in range(2):
        shard = np.load(tmp_path / f"transform_proc{pid}.npz")
        got[shard["indices"]] = shard["outputs"]
        covered[shard["indices"]] = True
    assert covered.all(), "host shards must cover every row exactly"
    np.testing.assert_allclose(got, oracle, rtol=1e-5, atol=1e-6)


@pytest.mark.slow
def test_elastic_restart_resumes_multihost_fit(tmp_path):
    """Driver re-dispatch (SURVEY.md §5.3): kill one host of a 2-process
    fit mid-training, tear the job down, relaunch — the fresh job resumes
    from the surviving process-0 checkpoint instead of restarting."""
    import signal
    import time

    rows, model_path = _make_workdir(tmp_path)
    # long job with per-epoch checkpoints
    meta = {
        "rows": rows,
        "fit_params": dict(FIT_PARAMS, epochs=300),
        "checkpoint_dir": str(tmp_path / "ckpt"),
    }
    with open(tmp_path / "meta.json", "w") as f:
        json.dump(meta, f)

    env = dict(os.environ)
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")

    procs, logs = _launch_workers(tmp_path, _free_port(), "phase1", env)
    ckpt_root = tmp_path / "ckpt"
    try:
        # wait for a committed epoch checkpoint
        import re

        deadline = time.time() + 400
        seen = False
        while time.time() < deadline and not seen:
            for root, dirs, _files in os.walk(ckpt_root):
                # only a FINALIZED checkpoint counts: orbax writes
                # epoch_N.orbax-checkpoint-tmp-<ts> and renames on commit
                if any(re.fullmatch(r"epoch_\d+", d) for d in dirs):
                    seen = True
            for pid, p in enumerate(procs):
                if p.poll() is not None:
                    logs[pid].seek(0)
                    raise AssertionError(
                        "worker exited before any checkpoint:\n"
                        + logs[pid].read()[-3000:]
                    )
            time.sleep(0.5)
        assert seen, "no checkpoint appeared"
        # host failure: SIGKILL process 1; the driver (this test) detects
        # it and tears down the whole job — restart-based elasticity
        procs[1].send_signal(signal.SIGKILL)
        procs[1].wait(timeout=60)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
            p.wait()
        for lg in logs:
            lg.close()

    # re-dispatch: fresh coordinator, fresh processes, same config
    procs, logs = _launch_workers(tmp_path, _free_port(), "phase2", env)
    outs = _wait_workers(procs, logs, what="relaunched worker")
    assert any("resuming from checkpoint" in out for out in outs), (
        "relaunched job did not resume from the surviving checkpoint"
    )


@pytest.mark.slow
def test_two_process_streaming_fit_matches_in_memory(tmp_path, tpu_session):
    """The beyond-RAM pod scenario (VERDICT r2 missing #4): multi-host fit
    with the streaming loader (URIs host-side, batches loaded+prefetched on
    demand) must equal the single-process *in-memory* fit — composing the
    loaders' batch-identical contract with the DP==single-process oracle
    invariant."""
    rows, model_path = _make_workdir(tmp_path)
    oracle = _single_process_fit(tpu_session, rows, model_path)

    meta = {
        "rows": rows,
        "fit_params": dict(FIT_PARAMS, streaming=True),
    }
    with open(tmp_path / "meta.json", "w") as f:
        json.dump(meta, f)

    port = _free_port()
    env = dict(os.environ)
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    procs, logs = _launch_workers(tmp_path, port, "streamfit", env)
    _wait_workers(procs, logs, what="streaming worker")

    w0 = np.load(tmp_path / "weights_proc0.npz")
    w1 = np.load(tmp_path / "weights_proc1.npz")
    for k in w0.files:
        np.testing.assert_array_equal(w0[k], w1[k])
    for got, want in zip([w0[k] for k in w0.files], oracle):
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


@pytest.mark.slow
def test_two_process_gspmd_tp_fit_matches_single_process(
    tmp_path, tpu_session
):
    """Pod-scale DP x TP (VERDICT r3 weak #3a): 2 processes form a global
    ("data", "model") = (2, 4) mesh spanning hosts; FlaxImageFileEstimator
    trains a tiny ViT under VIT_TP_RULES with the batch assembled from
    per-host shards.  Full-batch SGD + LayerNorm-only normalization make
    the gradient order-invariant, so the result must equal the
    single-process (2, 4)-mesh fit on the same rows."""
    img, n_rows = 16, 16
    rng = np.random.RandomState(3)
    rows = []
    for i in range(n_rows):
        v = rng.rand(img, img, 3).astype(np.float32)
        label = i % 2
        if label:
            v[:8, :8] += 0.7
        else:
            v[8:, 8:] += 0.7
        path = str(tmp_path / f"img_{i}.npy")
        np.save(path, v)
        rows.append((path, label))
    fit_params = {
        "epochs": 2,
        "batch_size": n_rows,  # full batch -> order-invariant oracle
        "learning_rate": 0.05,
        "seed": 0,
    }
    with open(tmp_path / "meta.json", "w") as f:
        json.dump(
            {"phase": "flax_tp", "rows": rows, "img": img,
             "fit_params": fit_params, "mesh_shape": [2, 4]},
            f,
        )

    # single-process oracle: same module/seed/config on the local
    # 8-device (2, 4) mesh
    from sparkdl_tpu.estimators import FlaxImageFileEstimator
    from sparkdl_tpu.models.vit import ViT
    from sparkdl_tpu.parallel.tp import VIT_TP_RULES
    from tests.multihost_worker import load_vector

    df = tpu_session.createDataFrame(
        [{"uri": u, "label": int(l)} for u, l in rows]
    )
    oracle = FlaxImageFileEstimator(
        inputCol="uri", outputCol="out", labelCol="label",
        imageLoader=load_vector,
        module=ViT(variant="ViT-Ti/16", num_classes=2, image_size=img),
        optimizer="sgd", fitParams=fit_params,
        shardingRules=VIT_TP_RULES, meshShape=(2, 4),
    ).fit(df)
    import jax

    want = {
        jax.tree_util.keystr(p): np.asarray(v)
        for p, v in jax.tree_util.tree_leaves_with_path(oracle.variables)
    }

    env = dict(os.environ)
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    procs, logs = _launch_workers(tmp_path, _free_port(), "flaxtp", env)
    _wait_workers(procs, logs, what="flax-tp worker")

    w0 = np.load(tmp_path / "flax_tp_proc0.npz")
    w1 = np.load(tmp_path / "flax_tp_proc1.npz")
    assert sorted(w0.files) == sorted(want.keys())
    for k in w0.files:
        # both processes hold the identical assembled result
        np.testing.assert_array_equal(w0[k], w1[k], err_msg=k)
        # and it matches the single-process GSPMD fit (tolerance covers
        # cross-process collective reduction-order drift)
        np.testing.assert_allclose(
            w0[k], want[k], rtol=2e-4, atol=2e-5, err_msg=k
        )
    # the fit actually trained (params moved from init)
    init = ViT(variant="ViT-Ti/16", num_classes=2, image_size=img).init(
        jax.random.PRNGKey(0), np.zeros((1, img, img, 3), np.float32)
    )
    moved = [
        not np.allclose(
            np.asarray(v), want[jax.tree_util.keystr(p)], atol=1e-7
        )
        for p, v in jax.tree_util.tree_leaves_with_path(init)
    ]
    assert any(moved)


@pytest.mark.slow
def test_two_process_bn_cnn_fit_exact_oracle(tmp_path):
    """Cross-host BatchNorm (VERDICT r3 weak #3b): a 2-conv BN CNN trains
    multi-host; batch_stats must end IDENTICAL on both hosts (the classic
    DP trap is hosts holding divergent moving stats), and the whole
    trajectory must equal an independently hand-rolled oracle that
    recomputes the per-device BN batches, the global weighted-mean
    gradient, and the cross-shard pmean of the stats with plain JAX — no
    mesh, no shard_map."""
    img, n_rows = 4, 16
    rng = np.random.RandomState(11)
    w_true = rng.randn(img * img * 3).astype(np.float32)
    rows = []
    for i in range(n_rows):
        v = rng.rand(img, img, 3).astype(np.float32)
        path = str(tmp_path / f"bn_{i}.npy")
        np.save(path, v)
        rows.append((path, float(v.reshape(-1) @ w_true)))

    keras.utils.set_random_seed(5)
    model = keras.Sequential([
        keras.layers.Input(shape=(img, img, 3)),
        keras.layers.Conv2D(4, 3, padding="same"),
        keras.layers.BatchNormalization(),
        keras.layers.Conv2D(4, 3, padding="same", activation="relu"),
        keras.layers.GlobalAveragePooling2D(),
        keras.layers.Dense(1),
    ])
    model_path = str(tmp_path / "model.keras")
    model.save(model_path)

    epochs, seed, lr = 2, 0, 0.05
    fit_params = {"epochs": epochs, "batch_size": n_rows,
                  "learning_rate": lr, "seed": seed}
    with open(tmp_path / "meta.json", "w") as f:
        json.dump({"rows": rows, "fit_params": fit_params}, f)

    env = dict(os.environ)
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    procs, logs = _launch_workers(tmp_path, _free_port(), "bnfit", env)
    _wait_workers(procs, logs, what="bn worker")

    w0 = np.load(tmp_path / "weights_proc0.npz")
    w1 = np.load(tmp_path / "weights_proc1.npz")
    # 1) the classic trap, pinned: BN moving stats (and every other
    # weight) identical across hosts
    for k in w0.files:
        np.testing.assert_array_equal(w0[k], w1[k], err_msg=k)

    # 2) exact independent oracle.  Reconstruct the estimator's
    # documented semantics: strided host shards, per-host permutation
    # rng (seed * 7919 + pid), global batch = concat(host0, host1),
    # device d of 8 sees rows [2d, 2d+2); BN normalizes per device
    # batch; grads are the global weighted mean; float non-trainables
    # pmean across devices.
    import jax
    import jax.numpy as jnp

    nprocs, n_dev = 2, 8
    per_dev = n_rows // n_dev
    x_all = np.stack([np.load(u) for u, _ in rows])
    y_all = np.asarray([[l] for _, l in rows], np.float32)

    oracle = keras.saving.load_model(model_path, compile=False)
    trainable = [jnp.asarray(v.value) for v in oracle.trainable_variables]
    non_trainable = [
        jnp.asarray(v.value) for v in oracle.non_trainable_variables
    ]

    host_rows = [np.arange(pid, n_rows, nprocs) for pid in range(nprocs)]
    rngs = [
        np.random.RandomState((seed * 7919 + pid) % 2**32)
        for pid in range(nprocs)
    ]
    for _ in range(epochs):
        orders = [r.permutation(len(h)) for r, h in zip(rngs, host_rows)]
        global_idx = np.concatenate(
            [h[o] for h, o in zip(host_rows, orders)]
        )
        xb = jnp.asarray(x_all[global_idx])
        yb = jnp.asarray(y_all[global_idx])

        def global_loss(tr):
            per_dev_nts = []
            total = 0.0
            for d in range(n_dev):
                sl = slice(d * per_dev, (d + 1) * per_dev)
                out, new_nt = oracle.stateless_call(
                    tr, non_trainable, xb[sl], training=True
                )
                total = total + ((yb[sl] - out) ** 2).mean(axis=-1).sum()
                per_dev_nts.append(new_nt)
            # float stats pmean == mean over the 8 device replicas
            mean_nt = [
                jnp.mean(jnp.stack(vs), axis=0)
                if jnp.issubdtype(vs[0].dtype, jnp.floating)
                else vs[0]
                for vs in zip(*per_dev_nts)
            ]
            return total / n_rows, mean_nt

        (_, non_trainable), grads = jax.value_and_grad(
            global_loss, has_aux=True
        )(trainable)
        trainable = [t - lr * g for t, g in zip(trainable, grads)]

    got = [w0[k] for k in w0.files]
    # worker saved model.get_weights(); match by order of keras weights
    for var, val in zip(oracle.trainable_variables, trainable):
        var.assign(np.asarray(val))
    for var, val in zip(oracle.non_trainable_variables, non_trainable):
        var.assign(np.asarray(val))
    want = [np.asarray(w) for w in oracle.get_weights()]
    assert len(got) == len(want)
    moved = False
    for g, w in zip(got, want):
        np.testing.assert_allclose(g, w, rtol=1e-4, atol=1e-5)
    # 3) the BN moving stats actually moved off their init (mean 0/var 1)
    init_model = keras.saving.load_model(model_path, compile=False)
    for got_w, init_w in zip(got, init_model.get_weights()):
        if not np.allclose(got_w, np.asarray(init_w), atol=1e-7):
            moved = True
    assert moved


@pytest.mark.slow
def test_two_process_gspmd_tp_checkpoint_resume(tmp_path):
    """Multi-host DP x TP fault tolerance: a checkpointed 2-process GSPMD
    fit re-run with the same config restores its committed epoch instead
    of retraining — the restore template/placement must handle global
    arrays whose shards live on the peer host."""
    img, n_rows = 16, 8
    rng = np.random.RandomState(9)
    rows = []
    for i in range(n_rows):
        path = str(tmp_path / f"ck_{i}.npy")
        np.save(path, rng.rand(img, img, 3).astype(np.float32))
        rows.append((path, i % 2))
    meta = {
        "phase": "flax_tp",
        "rows": rows,
        "img": img,
        "fit_params": {"epochs": 2, "batch_size": n_rows,
                       "learning_rate": 0.05, "seed": 0},
        "mesh_shape": [2, 4],
        "checkpoint_dir": str(tmp_path / "ckpt"),
    }
    with open(tmp_path / "meta.json", "w") as f:
        json.dump(meta, f)

    env = dict(os.environ)
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    procs, logs = _launch_workers(tmp_path, _free_port(), "tpck1", env)
    _wait_workers(procs, logs, what="tp-ckpt worker")
    first = dict(np.load(tmp_path / "flax_tp_proc0.npz"))

    # same config again: must restore epoch 2 and return the identical
    # weights without training further
    procs, logs = _launch_workers(tmp_path, _free_port(), "tpck2", env)
    outs = _wait_workers(procs, logs, what="tp-ckpt rerun worker")
    assert any("resuming from checkpoint epoch 2" in o for o in outs), (
        "re-run did not restore the committed TP checkpoint"
    )
    second = dict(np.load(tmp_path / "flax_tp_proc0.npz"))
    for k, v in first.items():
        np.testing.assert_allclose(
            second[k], v, rtol=1e-6, atol=1e-7, err_msg=k
        )
