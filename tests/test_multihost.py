"""Multi-host training tests: 2 processes x 4 virtual CPU devices.

The reference delegated all multi-node behavior to Spark and tested only
``local[*]`` (SURVEY.md §4); its training never left the driver at all
(§3.2).  Here the multi-host path is first-class, so it gets a real
multi-process test: two OS processes form a global 8-device mesh via
``jax.distributed`` + gloo CPU collectives, each loads only its own shard
of the dataset, and ``KerasImageFileEstimator.fit`` runs the global
shard_map step with cross-process gradient allreduce.

Oracle invariant: with a full-batch step (batch_size == n_rows) the
multi-host result must equal the single-process fit on the same data —
the gradient is the mean over the identical row set either way.
"""

import json
import os
import socket
import subprocess
import sys

import numpy as np
import pytest

keras = pytest.importorskip("keras")

_HERE = os.path.dirname(os.path.abspath(__file__))
_REPO = os.path.dirname(_HERE)

N_ROWS = 16
DIM = 4
FIT_PARAMS = {
    "epochs": 3,
    "batch_size": N_ROWS,  # full batch -> order-invariant oracle
    "learning_rate": 0.05,
    "seed": 0,
}


def _free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _make_workdir(tmp_path):
    """Deterministic (vector-file, label) dataset + tiny linear model."""
    rng = np.random.RandomState(42)
    w_true = rng.randn(DIM).astype(np.float32)
    rows = []
    for i in range(N_ROWS):
        v = rng.randn(DIM).astype(np.float32)
        path = str(tmp_path / f"x_{i}.npy")
        np.save(path, v)
        rows.append((path, float(v @ w_true)))

    keras.utils.set_random_seed(7)
    model = keras.Sequential(
        [keras.layers.Input(shape=(DIM,)), keras.layers.Dense(1)]
    )
    model_path = str(tmp_path / "model.keras")
    model.save(model_path)

    with open(tmp_path / "meta.json", "w") as f:
        json.dump({"rows": rows, "fit_params": FIT_PARAMS}, f)
    return rows, model_path


def _single_process_fit(tpu_session, rows, model_path):
    """The oracle: same fit in this (single-host, 8-device) process."""
    from sparkdl_tpu.estimators import KerasImageFileEstimator
    from tests.multihost_worker import load_vector

    df = tpu_session.createDataFrame(
        [{"uri": u, "label": [float(l)]} for u, l in rows]
    )
    est = KerasImageFileEstimator(
        inputCol="uri",
        outputCol="out",
        labelCol="label",
        imageLoader=load_vector,
        modelFile=model_path,
        kerasOptimizer="sgd",
        kerasLoss="mse",
        kerasFitParams=dict(FIT_PARAMS),
    )
    fitted = est.fit(df)
    m = keras.saving.load_model(fitted.getModelFile(), compile=False)
    return [np.asarray(w) for w in m.get_weights()]


@pytest.mark.slow
def test_two_process_fit_matches_single_process(tmp_path, tpu_session):
    rows, model_path = _make_workdir(tmp_path)
    oracle = _single_process_fit(tpu_session, rows, model_path)

    port = _free_port()
    env = dict(os.environ)
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    procs = [
        subprocess.Popen(
            [
                sys.executable,
                os.path.join(_HERE, "multihost_worker.py"),
                str(pid),
                "2",
                str(port),
                str(tmp_path),
            ],
            env=env,
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
        )
        for pid in range(2)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=600)
            outs.append(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.communicate()
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {pid} failed:\n{out[-4000:]}"
        assert f"MULTIHOST_WORKER_OK {pid}" in out

    w0 = np.load(tmp_path / "weights_proc0.npz")
    w1 = np.load(tmp_path / "weights_proc1.npz")
    # both processes hold the identical replicated result
    for k in w0.files:
        np.testing.assert_array_equal(w0[k], w1[k])
    # and it matches the single-process oracle (same global row set per
    # step; tolerance covers collective reduction-order float drift)
    for got, want in zip([w0[k] for k in w0.files], oracle):
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)
