"""Execution engine (``sparkdl_tpu/engine``): content-addressed cache
keys (stable across processes, sensitive to every component), the
in-memory executable LRU, persistent disk roundtrips that survive a
fresh engine, ``engine.compile`` spans only on true compiles, the
depth-N dispatch window, and serving's compile-vs-cache-load warmup
report.

Acceptance shape (ISSUE 5): cache-key stability incl. a cross-process
check; LRU eviction under a small ``maxsize``; a second engine *loads*
a fingerprinted executable instead of recompiling (closure weights come
back intact); anonymous functions never persist; a traced warm start
shows zero ``engine.compile`` spans; ``serving.cache_load`` counts the
restart-warmup fast path.
"""

import os
import subprocess
import sys
import textwrap

import numpy as np
import pytest

import jax.numpy as jnp

from sparkdl_tpu.engine import (
    DispatchWindow,
    ExecutionEngine,
    FetchFailure,
    PersistentCompileCache,
    cache_key,
    default_cache_dir,
    dispatch_depth,
)
from sparkdl_tpu.engine.cache import _runtime_descriptor
from sparkdl_tpu.obs import JsonlTraceSink, tracer
from sparkdl_tpu.utils.metrics import metrics


@pytest.fixture(autouse=True)
def clean_slate():
    tracer.disable()
    metrics.reset()
    yield
    tracer.disable()
    metrics.reset()


_SPEC = (((8, 4), "<f4", None),)
_RUNTIME = {
    "jax": "0.0.test", "jaxlib": "0.0.test", "platform": "cpu",
    "device_kind": "cpu", "device_count": 8,
}


# ----------------------------------------------------------------------
# cache keys
# ----------------------------------------------------------------------
class TestCacheKey:
    def test_deterministic_and_hex(self):
        a = cache_key("fp:m1", _SPEC, (0,), runtime=_RUNTIME)
        b = cache_key("fp:m1", _SPEC, (0,), runtime=_RUNTIME)
        assert a == b
        assert len(a) == 64 and set(a) <= set("0123456789abcdef")

    def test_every_component_changes_the_key(self):
        base = cache_key("fp:m1", _SPEC, (0,), runtime=_RUNTIME)
        variants = [
            cache_key("fp:m2", _SPEC, (0,), runtime=_RUNTIME),
            cache_key("fp:m1", (((16, 4), "<f4", None),), (0,),
                      runtime=_RUNTIME),
            cache_key("fp:m1", (((8, 4), "<f2", None),), (0,),
                      runtime=_RUNTIME),
            cache_key(
                "fp:m1",
                (((8, 4), "<f4", {"axes": {"data": 8}, "spec": "P('data',)"}),),
                (0,), runtime=_RUNTIME,
            ),
            cache_key("fp:m1", _SPEC, (), runtime=_RUNTIME),  # donation
            cache_key("fp:m1", _SPEC, (0,),
                      runtime={**_RUNTIME, "jax": "9.9.9"}),
            cache_key("fp:m1", _SPEC, (0,),
                      runtime={**_RUNTIME, "device_count": 1}),
        ]
        assert len({base, *variants}) == len(variants) + 1

    def test_cross_process_stability(self):
        """The same components hash to the same address in a separate
        interpreter — the contract that lets a second process (or a
        restarted server) find executables this one stored."""
        code = textwrap.dedent(
            """
            from sparkdl_tpu.engine.cache import cache_key
            runtime = {
                "jax": "0.0.test", "jaxlib": "0.0.test", "platform": "cpu",
                "device_kind": "cpu", "device_count": 8,
            }
            print(cache_key(
                "fp:m1", (((8, 4), "<f4", None),), (0,), runtime=runtime
            ))
            """
        )
        proc = subprocess.run(
            [sys.executable, "-c", code],
            capture_output=True, text=True, timeout=120,
            env={**os.environ, "JAX_PLATFORMS": "cpu"},
        )
        assert proc.returncode == 0, proc.stderr
        assert proc.stdout.strip() == cache_key(
            "fp:m1", _SPEC, (0,), runtime=_RUNTIME
        )

    def test_real_runtime_descriptor_is_stable_in_process(self):
        assert cache_key("fp", _SPEC, ()) == cache_key("fp", _SPEC, ())
        rt = _runtime_descriptor()
        assert rt["platform"] == "cpu" and rt["device_count"] == 8

    def test_default_cache_dir_env(self, monkeypatch):
        monkeypatch.setenv("SPARKDL_COMPILE_CACHE", "/tmp/somewhere")
        assert default_cache_dir() == "/tmp/somewhere"
        monkeypatch.setenv("SPARKDL_COMPILE_CACHE", "off")
        assert default_cache_dir() is None


# ----------------------------------------------------------------------
# in-memory LRU
# ----------------------------------------------------------------------
class TestEngineLRU:
    def test_eviction_under_small_maxsize(self):
        eng = ExecutionEngine(maxsize=2, persistent=False)

        def fn(x):
            return x + 1.0

        keys = []
        for n in (2, 3, 4):
            h = eng.program(
                fn, (np.zeros((n,), np.float32),), fingerprint="lru:t"
            )
            assert h.source == "compile"
            keys.append(h.key)
        assert eng.stats()["programs"] == 2
        assert eng.lookup(keys[0]) is None          # oldest evicted
        assert eng.lookup(keys[1]) is not None
        assert eng.lookup(keys[2]) is not None

        # the evicted signature recompiles (no disk tier here) ...
        h = eng.program(
            fn, (np.zeros((2,), np.float32),), fingerprint="lru:t"
        )
        assert h.key == keys[0] and h.source == "compile"
        # ... which in turn evicted the now-oldest middle entry
        assert eng.lookup(keys[1]) is None

    def test_memory_hit_is_free_and_recency_updates(self):
        eng = ExecutionEngine(maxsize=2, persistent=False)

        def fn(x):
            return x * 2.0

        k2 = eng.program(fn, (np.zeros((2,), np.float32),)).key
        eng.program(fn, (np.zeros((3,), np.float32),))
        # touch k2 so it is most-recent, then insert a third program
        h = eng.program(fn, (np.zeros((2,), np.float32),))
        assert h.source == "memory" and h.seconds == 0.0
        eng.program(fn, (np.zeros((4,), np.float32),))
        assert eng.lookup(k2) is not None           # survived via recency


# ----------------------------------------------------------------------
# persistent roundtrip
# ----------------------------------------------------------------------
class TestPersistentCache:
    def test_second_engine_loads_instead_of_recompiling(self, tmp_path):
        disk = str(tmp_path / "exe")
        w = np.arange(12, dtype=np.float32).reshape(4, 3)

        def forward(x):
            return x @ w                           # closure-captured weights

        x = np.ones((2, 4), np.float32)
        e1 = ExecutionEngine(cache=PersistentCompileCache(disk))
        h1 = e1.program(forward, (x,), fingerprint="roundtrip:w:v1")
        assert h1.source == "compile"
        assert e1.cache.stats()["entries"] == 1
        assert metrics.counter("engine.cache_miss").value == 1

        e2 = ExecutionEngine(cache=PersistentCompileCache(disk))
        h2 = e2.program(forward, (x,), fingerprint="roundtrip:w:v1")
        assert h2.source == "disk"
        assert h2.key == h1.key
        assert metrics.counter("engine.cache_hit").value == 1
        np.testing.assert_allclose(
            np.asarray(h2(x)), np.asarray(h1(x)), rtol=1e-6
        )
        np.testing.assert_allclose(np.asarray(h2(x)), x @ w, rtol=1e-6)

    def test_anonymous_functions_never_persist(self, tmp_path):
        cache = PersistentCompileCache(str(tmp_path / "exe"))
        eng = ExecutionEngine(cache=cache)
        h = eng.program(lambda x: x + 1, (np.zeros((2,), np.float32),))
        assert h.source == "compile"
        assert cache.stats()["entries"] == 0
        # in-memory reuse still works for the same function object
        fn = lambda x: x * 3  # noqa: E731
        k1 = eng.program(fn, (np.zeros((2,), np.float32),)).key
        assert eng.program(fn, (np.zeros((2,), np.float32),)).source == "memory"
        assert k1 == eng.program(fn, (np.zeros((2,), np.float32),)).key

    def test_donation_changes_the_key(self, tmp_path):
        eng = ExecutionEngine(persistent=False)

        def fn(x):
            return x + 1.0

        a = eng.program(fn, (np.zeros((2,), np.float32),),
                        fingerprint="d:t", donate=False)
        b = eng.program(fn, (np.zeros((2,), np.float32),),
                        fingerprint="d:t", donate=True)
        assert a.key != b.key

    def test_corrupt_entry_is_a_miss_not_a_failure(self, tmp_path):
        disk = str(tmp_path / "exe")
        eng = ExecutionEngine(cache=PersistentCompileCache(disk))
        h = eng.program(
            lambda x: x - 1, (np.zeros((2,), np.float32),),
            fingerprint="corrupt:t",
        )
        (key, exe_path, _, _), = eng.cache.entries()
        with open(exe_path, "wb") as fh:
            fh.write(b"not a pickle")
        e2 = ExecutionEngine(cache=PersistentCompileCache(disk))
        h2 = e2.program(
            lambda x: x - 1, (np.zeros((2,), np.float32),),
            fingerprint="corrupt:t",
        )
        assert h2.key == h.key and h2.source == "compile"


# ----------------------------------------------------------------------
# spans: engine.compile only on true compiles
# ----------------------------------------------------------------------
class TestCompileSpans:
    def test_warm_start_emits_no_compile_span(self, tmp_path):
        disk = str(tmp_path / "exe")

        def fn(x):
            return jnp.tanh(x)

        cold_sink = JsonlTraceSink()
        tracer.enable(cold_sink)
        e1 = ExecutionEngine(cache=PersistentCompileCache(disk))
        e1.program(fn, (np.zeros((2,), np.float32),), fingerprint="span:t",
                   name="span_fn")
        tracer.disable()
        compiles = [
            s for s in cold_sink.spans() if s["name"] == "engine.compile"
        ]
        assert len(compiles) == 1
        assert compiles[0]["attributes"]["program"] == "span_fn"
        assert compiles[0]["attributes"]["fingerprint"] == "span:t"

        warm_sink = JsonlTraceSink()
        tracer.enable(warm_sink)
        e2 = ExecutionEngine(cache=PersistentCompileCache(disk))
        h = e2.program(fn, (np.zeros((2,), np.float32),),
                       fingerprint="span:t", name="span_fn")
        tracer.disable()
        assert h.source == "disk"
        assert not [
            s for s in warm_sink.spans() if s["name"] == "engine.compile"
        ]


# ----------------------------------------------------------------------
# dispatch window
# ----------------------------------------------------------------------
class TestDispatchWindow:
    def test_strict_order_and_meta_passthrough(self):
        window = DispatchWindow(depth=2)
        got = []
        for i in range(5):
            for host, meta in window.submit(jnp.full((3,), i), meta=i):
                got.append((host, meta))
        assert [m for _, m in got] == [0, 1, 2]      # depth 2 held back
        assert len(window) == 2
        for host, meta in window.drain():
            got.append((host, meta))
        assert [m for _, m in got] == [0, 1, 2, 3, 4]
        for host, meta in got:
            assert isinstance(host, np.ndarray)
            np.testing.assert_array_equal(host, np.full((3,), meta))
        assert metrics.gauge("engine.inflight").value == 0

    def test_depth_zero_is_serial(self):
        window = DispatchWindow(depth=0)
        out = window.submit(jnp.ones((2,)), meta="only")
        assert len(out) == 1 and out[0][1] == "only"
        assert len(window) == 0

    def test_abandon_clears_without_fetching(self):
        window = DispatchWindow(depth=4)
        for i in range(3):
            window.submit(jnp.zeros((1,)), meta=i)
        assert len(window) == 3
        window.abandon()
        assert len(window) == 0
        assert metrics.gauge("engine.inflight").value == 0
        assert list(window.drain()) == []

    def test_env_depth(self, monkeypatch):
        monkeypatch.setenv("SPARKDL_DISPATCH_DEPTH", "5")
        assert dispatch_depth() == 5
        assert DispatchWindow().depth == 5
        monkeypatch.setenv("SPARKDL_DISPATCH_DEPTH", "bogus")
        with pytest.raises(ValueError):
            dispatch_depth()

    def test_capture_errors_delivers_fetch_failure_with_meta(self):
        class Boom:
            def __array__(self, *a, **k):
                raise ValueError("device said no")

        window = DispatchWindow(depth=4, capture_errors=True)
        window.submit(jnp.ones((2,)), meta="ok")
        window.submit(Boom(), meta="doomed")
        out = list(window.drain())
        assert [m for _, m in out] == ["ok", "doomed"]
        assert isinstance(out[0][0], np.ndarray)
        failure = out[1][0]
        assert isinstance(failure, FetchFailure)
        assert "device said no" in str(failure.error)

    def test_uncaptured_fetch_failure_raises(self):
        class Boom:
            def __array__(self, *a, **k):
                raise ValueError("boom")

        window = DispatchWindow(depth=0)
        with pytest.raises(ValueError):
            window.submit(Boom(), meta=None)


# ----------------------------------------------------------------------
# serving warmup report (compile vs cache load)
# ----------------------------------------------------------------------
class TestServingWarmupReport:
    def test_restarted_cache_loads_and_reports(self, tmp_path, monkeypatch):
        from sparkdl_tpu.serving.cache import ProgramCache

        monkeypatch.setenv(
            "SPARKDL_COMPILE_CACHE", str(tmp_path / "serving-exe")
        )

        def forward(x):
            return x * 2.0

        cold = ProgramCache(
            maxsize=8, compile_counter=metrics.counter("serving.compiles")
        )
        buckets = cold.warmup(
            "m1", forward, item_shape=(4,), dtype=np.float32,
            buckets=(1, 2), fingerprint="warm:test:v1",
        )
        assert buckets == (1, 2)
        report = cold.stats()["warmup"]["m1"]
        assert {b: r["source"] for b, r in report.items()} == {
            1: "compile", 2: "compile"
        }
        assert all(r["seconds"] >= 0 for r in report.values())
        assert metrics.counter("serving.compiles").value == 2
        assert metrics.counter("serving.cache_load").value == 0

        # "restart": a fresh ProgramCache in the same process, same disk
        warm = ProgramCache(
            maxsize=8, compile_counter=metrics.counter("serving.compiles")
        )
        warm.warmup(
            "m1", forward, item_shape=(4,), dtype=np.float32,
            buckets=(1, 2), fingerprint="warm:test:v1",
        )
        report = warm.stats()["warmup"]["m1"]
        assert {b: r["source"] for b, r in report.items()} == {
            1: "disk", 2: "disk"
        }
        assert metrics.counter("serving.compiles").value == 2  # unchanged
        assert metrics.counter("serving.cache_load").value == 2
        assert warm.stats()["persistent"]["entries"] == 2

    def test_unfingerprinted_warmup_stays_off_disk(self, tmp_path,
                                                   monkeypatch):
        from sparkdl_tpu.serving.cache import ProgramCache

        monkeypatch.setenv(
            "SPARKDL_COMPILE_CACHE", str(tmp_path / "anon-exe")
        )
        cache = ProgramCache(maxsize=4)
        cache.warmup(
            "anon", lambda x: x + 1, item_shape=(3,), dtype=np.float32,
            buckets=(1,),
        )
        assert cache.stats()["persistent"]["entries"] == 0
        assert cache.stats()["warmup"]["anon"][1]["source"] == "compile"
