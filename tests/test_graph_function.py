"""Graph toolkit tests — the constructor-matrix pattern from the reference
(``python/tests/graph/test_input.py``†: every TFInputGraph constructor checked
against one numpy oracle — SURVEY.md §4), rebuilt for XlaFunction.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from sparkdl_tpu.graph import IsolatedSession, XlaFunction, pieces, utils

RNG = np.random.RandomState(7)
X = RNG.rand(4, 10).astype(np.float32)
W = RNG.rand(10, 3).astype(np.float32)
B = RNG.rand(3).astype(np.float32)
ORACLE = X @ W + B  # the single numpy oracle every constructor must match


def _linear_apply(params, x):
    return x @ params["w"] + params["b"]


def _check(fn: XlaFunction, params_included=True, atol=1e-5):
    out = np.asarray(fn(X) if params_included else fn(X, params={"w": W, "b": B}))
    np.testing.assert_allclose(out, ORACLE, atol=atol, rtol=1e-5)


# ---------------------------------------------------------------------------
# constructor matrix
# ---------------------------------------------------------------------------


def test_from_callable_with_params():
    fn = XlaFunction.from_callable(
        _linear_apply, params={"w": W, "b": B}, takes_params=True
    )
    _check(fn)


def test_from_callable_pure():
    fn = XlaFunction.from_callable(lambda x: x @ W + B)
    _check(fn)


def test_from_flax():
    import flax.linen as nn
    import jax

    class Dense(nn.Module):
        @nn.compact
        def __call__(self, x):
            return nn.Dense(3)(x)

    module = Dense()
    params = module.init(jax.random.PRNGKey(0), X)
    # inject oracle weights
    params = {"params": {"Dense_0": {"kernel": jnp.asarray(W), "bias": jnp.asarray(B)}}}
    fn = XlaFunction.from_flax(module, params)
    _check(fn)


def test_from_keras_model_and_file(tmp_path):
    keras = pytest.importorskip("keras")
    assert keras.config.backend() == "jax"
    model = keras.Sequential(
        [keras.layers.Input((10,)), keras.layers.Dense(3, name="lin")]
    )
    model.get_layer("lin").set_weights([W, B])
    fn = XlaFunction.from_keras(model)
    _check(fn)
    # file roundtrip
    path = str(tmp_path / "m.keras")
    model.save(path)
    fn2 = XlaFunction.from_keras(path)
    _check(fn2)


def test_from_npz(tmp_path):
    path = str(tmp_path / "params.npz")
    np.savez(path, **{"w": W, "b": B})
    fn = XlaFunction.from_npz(path, _linear_apply)
    _check(fn)


def test_from_checkpoint(tmp_path):
    ocp = pytest.importorskip("orbax.checkpoint")
    ckpt_dir = str(tmp_path / "ckpt")
    with ocp.StandardCheckpointer() as ckptr:
        ckptr.save(ckpt_dir, {"w": W, "b": B})
        ckptr.wait_until_finished()
    fn = XlaFunction.from_checkpoint(ckpt_dir, _linear_apply)
    _check(fn)


def test_stablehlo_roundtrip():
    fn = XlaFunction.from_callable(
        _linear_apply, params={"w": W, "b": B}, takes_params=True
    )
    blob = fn.export_stablehlo(((4, 10), np.float32))
    assert isinstance(blob, bytes) and len(blob) > 0
    fn2 = XlaFunction.from_stablehlo(blob)
    _check(fn2)
    # batch polymorphism: different batch size must work from the same export
    out = np.asarray(fn2(np.vstack([X, X])))
    np.testing.assert_allclose(out, np.vstack([ORACLE, ORACLE]), atol=1e-5)


def test_save_load_dir(tmp_path):
    fn = XlaFunction.from_callable(
        _linear_apply, params={"w": W, "b": B}, takes_params=True, name="lin"
    )
    path = str(tmp_path / "exported")
    fn.save(path, ((4, 10), np.float32))
    fn2 = XlaFunction.load(path)
    assert fn2.name == "lin"
    _check(fn2)


def test_from_saved_model(tmp_path):
    tf = pytest.importorskip("tensorflow")

    class Mod(tf.Module):
        @tf.function(input_signature=[tf.TensorSpec([None, 10], tf.float32)])
        def __call__(self, x):
            return {"out": tf.matmul(x, W) + B}

    path = str(tmp_path / "sm")
    tf.saved_model.save(Mod(), path)
    fn = XlaFunction.from_saved_model(path)
    out = fn(X)
    np.testing.assert_allclose(np.asarray(out), ORACLE, atol=1e-5, rtol=1e-5)


# ---------------------------------------------------------------------------
# composition + pieces
# ---------------------------------------------------------------------------


def test_compose_and_from_list():
    lin = XlaFunction.from_callable(
        _linear_apply, params={"w": W, "b": B}, takes_params=True
    )
    relu = XlaFunction.from_callable(lambda x: jnp.maximum(x, 0))
    double = XlaFunction.from_callable(lambda x: x * 2)
    piped = XlaFunction.from_list([lin, relu, double])
    out = np.asarray(piped(X))
    np.testing.assert_allclose(out, np.maximum(ORACLE, 0) * 2, atol=1e-5)
    # compose pairs
    out2 = np.asarray(lin.compose(relu)(X))
    np.testing.assert_allclose(out2, np.maximum(ORACLE, 0), atol=1e-5)


def test_sp_image_converter_piece():
    bgr = RNG.randint(0, 255, (2, 4, 4, 3)).astype(np.uint8)
    conv = pieces.build_sp_image_converter("BGR")
    out = np.asarray(conv(bgr))
    np.testing.assert_allclose(out, bgr[..., ::-1].astype(np.float32))


def test_flattener_piece():
    x = RNG.rand(3, 4, 5).astype(np.float32)
    out = np.asarray(pieces.build_flattener()(x))
    assert out.shape == (3, 20)


def test_resizer_piece():
    x = RNG.randint(0, 255, (2, 8, 8, 3)).astype(np.float32)
    out = np.asarray(pieces.build_resizer((4, 4))(x))
    assert out.shape == (2, 4, 4, 3)
    assert out.min() >= 0 and out.max() <= 255


def test_preprocessor_modes():
    x = np.full((1, 2, 2, 3), 255.0, dtype=np.float32)
    np.testing.assert_allclose(
        np.asarray(pieces.build_preprocessor("tf")(x)), np.ones_like(x), atol=1e-6
    )
    caffe = np.asarray(pieces.build_preprocessor("caffe")(x))
    np.testing.assert_allclose(
        caffe[0, 0, 0], 255.0 - np.array([103.939, 116.779, 123.68]), atol=1e-4
    )


def test_pipeline_converter_model_flatten():
    """The reference's flagship composition: spImageConverter → model →
    flattener (SURVEY.md §3.1)."""
    imgs = RNG.randint(0, 255, (3, 4, 4, 3)).astype(np.uint8)
    conv = pieces.build_sp_image_converter("BGR")
    model = XlaFunction.from_callable(lambda x: x.mean(axis=3, keepdims=True))
    flat = pieces.build_flattener()
    piped = XlaFunction.from_list([conv, model, flat])
    out = np.asarray(piped(imgs))
    assert out.shape == (3, 16)
    np.testing.assert_allclose(
        out, imgs[..., ::-1].astype(np.float32).mean(3).reshape(3, -1), atol=1e-4
    )


# ---------------------------------------------------------------------------
# utils + builder shim
# ---------------------------------------------------------------------------


def test_name_utils():
    assert utils.tensor_name("x") == "x:0"
    assert utils.tensor_name("x:1") == "x:1"
    assert utils.op_name("x:0") == "x"
    assert utils.op_name("x") == "x"
    with pytest.raises(ValueError):
        utils.tensor_name("x:bad")


def test_validated_io():
    fn = XlaFunction.from_callable(lambda x: x, input_names=["a"], output_names=["b"])
    assert utils.validated_input(fn, "a:0") == "a"
    assert utils.validated_output(fn, "b") == "b"
    with pytest.raises(ValueError):
        utils.validated_input(fn, "zz")
    utils.validated_graph(fn)


def test_isolated_session_shim():
    with IsolatedSession() as issn:
        gfn = issn.makeGraphFunction(lambda x: x * 3)
        imported_io = issn.importGraphFunction(gfn)
        assert imported_io == (["input"], ["output"])
        packaged = issn.asGraphFunction(["input"], ["output"])
    np.testing.assert_allclose(np.asarray(packaged(X)), X * 3, atol=1e-6)
