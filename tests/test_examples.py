"""The runnable examples must stay runnable — each is a documented user
flow (README "examples/" pointer), so rot there is a user-facing break.

Each example runs in a fresh subprocess on the virtual CPU mesh (the same
forced-platform pattern as ``__graft_entry__.dryrun_multichip``) and must
exit 0 after printing its success line.
"""

import os
import subprocess
import sys

import pytest

_HERE = os.path.dirname(os.path.abspath(__file__))
_REPO = os.path.dirname(_HERE)


def _run_example(name: str, timeout: int = 900, extra_env=None) -> str:
    env = dict(os.environ)
    env.update(extra_env or {})
    env["JAX_PLATFORMS"] = "cpu"
    env["KERAS_BACKEND"] = "jax"
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    flags = env.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        env["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8"
        ).strip()
    # the axon sitecustomize may pin the TPU platform before env vars land,
    # so force CPU through the live config first (see conftest.py)
    code = (
        "import jax; jax.config.update('jax_platforms', 'cpu'); "
        "import runpy; runpy.run_path("
        f"{os.path.join(_REPO, 'examples', name)!r}, run_name='__main__')"
    )
    proc = subprocess.run(
        [sys.executable, "-c", code],
        env=env,
        capture_output=True,
        text=True,
        timeout=timeout,
    )
    assert proc.returncode == 0, (
        f"{name} failed (rc={proc.returncode}):\n{proc.stderr[-4000:]}"
    )
    return proc.stdout


@pytest.mark.slow
def test_transfer_learning_example():
    out = _run_example("transfer_learning.py")
    assert "transfer-learning accuracy" in out
    assert "reloaded pipeline reproduces accuracy" in out


@pytest.mark.slow
def test_udf_serving_example():
    out = _run_example("udf_serving.py")
    assert "SQL-UDF scored 12 rows" in out
    assert "centered means of first rows" in out


@pytest.mark.slow
def test_distributed_finetune_example(tmp_path):
    out = _run_example(
        "distributed_finetune.py",
        extra_env={"SPARKDL_DEMO_DIR": str(tmp_path / "demo")},
    )
    assert "fitMultiple trained 2 models" in out
    assert "train accuracy" in out


@pytest.mark.slow
def test_online_serving_example():
    out = _run_example("online_serving.py")
    assert "online serving OK" in out
    assert "served 24 requests" in out


@pytest.mark.slow
def test_tracing_example():
    out = _run_example("tracing.py")
    assert "tracing OK" in out
    assert "captured" in out and "estimator.fit" in out
    assert "request spans coalesced into" in out


@pytest.mark.slow
def test_sql_analytics_example():
    out = _run_example("sql_analytics.py")
    assert "sql analytics OK" in out


@pytest.mark.slow
def test_streaming_scoring_example():
    out = _run_example("streaming_scoring.py")
    assert "streaming scoring OK" in out
    assert "stop_reason=preempted" in out
    assert "scored 60 events exactly once across a SIGTERM" in out


@pytest.mark.slow
def test_continuous_query_example():
    out = _run_example("continuous_query.py")
    assert "continuous query OK" in out
    assert "stop_reason=preempted" in out
    assert "closed 20 windows exactly once across a SIGTERM" in out
    assert "2 late rows preserved in the side output" in out


@pytest.mark.slow
def test_telemetry_example():
    out = _run_example("telemetry.py")
    assert "telemetry plane up at http://127.0.0.1:" in out
    assert "SLO breach detected: serving.demo.latency ->" in out
    assert "flight recorder dump:" in out
    assert "telemetry example complete" in out
