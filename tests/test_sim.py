"""ISSUE-17 acceptance tests for the trace-driven fleet simulator.

The contracts the sim subsystem pins:

- virtual time is monotone — the clock and event loop refuse to move
  backwards, and every replay's event log is time-ordered;
- determinism — same trace + same seed + same config produce a
  byte-identical event log (different seeds diverge);
- speed — replaying the committed fixture runs >= 100x faster than
  the wall-clock span it recorded;
- fidelity — replaying the fixture under the live fleet's config
  reproduces the live per-phase and end-to-end p50/p99 within 15%
  (0.25 ms floor) over the steady-state window;
- the tuner beats the default config on SLO burn, deterministically;
- the autoscaler and rollout controller run correctly on virtual time;
- ``ci/perf_gate.py --sim`` passes against the committed artifact.
"""

import json
import time
from pathlib import Path

import pytest

from sparkdl_tpu.sim import (
    DEFAULT_CONFIG,
    EventLoop,
    FleetReplay,
    TraceRecord,
    VirtualClock,
    fidelity_report,
    load_trace,
    summarize,
    write_trace,
)
from sparkdl_tpu.sim.clock import ClockWentBackwards
from sparkdl_tpu.sim.tune import DEFAULT_SPACE, EVAL_HARNESS, tune

_REPO = Path(__file__).resolve().parent.parent
FIXTURE = _REPO / "tests" / "fixtures" / "sim_trace_small.jsonl"

#: the demo fleet config the fixture was recorded against
#: (serving/replica.py factory defaults) — fidelity replays must match
#: the live run's knobs, not the sim's defaults
LIVE_CONFIG = {
    "replicas": 2, "max_batch": 16, "max_wait_ms": 1.0,
    "queue_capacity": 512,
}

#: the one-time warmup-compile era: its placement cascade is not
#: recoverable from the trace, so fidelity is judged on steady state
WARMUP_S = 1.0


@pytest.fixture(scope="module")
def fixture_trace():
    meta, records = load_trace(str(FIXTURE))
    assert meta.get("kind") == "sparkdl_trace"
    assert records
    return meta, records


# ---------------------------------------------------------------------------
# virtual clock discipline
# ---------------------------------------------------------------------------

def test_virtual_clock_never_goes_backwards():
    clock = VirtualClock()
    clock.advance_to(1.5)
    clock.advance_to(1.5)  # idempotent re-advance is fine
    assert clock.now == 1.5
    with pytest.raises(ClockWentBackwards):
        clock.advance_to(1.0)


def test_event_loop_rejects_scheduling_in_the_past():
    clock = VirtualClock()
    loop = EventLoop(clock)
    clock.advance_to(2.0)
    with pytest.raises(ClockWentBackwards):
        loop.schedule(1.0, lambda: None)


def test_event_loop_runs_in_time_order():
    clock = VirtualClock()
    loop = EventLoop(clock)
    seen = []
    for t in (3.0, 1.0, 2.0):
        loop.schedule(t, seen.append, t)
    loop.run()
    assert seen == [1.0, 2.0, 3.0]
    assert clock.now == 3.0


def test_replay_event_log_is_time_monotone(fixture_trace):
    _, records = fixture_trace
    fr = FleetReplay(records, config=LIVE_CONFIG, seed=0)
    fr.run()
    times = [row["t"] for row in fr.event_log]
    assert times, "replay produced no events"
    assert all(a <= b for a, b in zip(times, times[1:]))


# ---------------------------------------------------------------------------
# trace format
# ---------------------------------------------------------------------------

def test_trace_write_load_roundtrip(tmp_path):
    path = tmp_path / "t.jsonl"
    records = [
        TraceRecord(t=0.1, endpoint="ep0", tenant="a", outcome="ok",
                    latency_ms=3.2, server_ms=1.1,
                    phases={"forward": 1.0, "wire": 0.2}),
        TraceRecord(t=0.2, endpoint="ep1", outcome="shed"),
    ]
    n = write_trace(str(path), {"benchmark": "x"}, records)
    assert n == 2
    meta, loaded = load_trace(str(path))
    assert meta["kind"] == "sparkdl_trace" and meta["benchmark"] == "x"
    assert [r.to_json() for r in loaded] == [r.to_json() for r in records]


# ---------------------------------------------------------------------------
# determinism
# ---------------------------------------------------------------------------

def test_same_seed_same_trace_byte_identical_event_log(fixture_trace):
    _, records = fixture_trace
    runs = [FleetReplay(records, config=LIVE_CONFIG, seed=7)
            for _ in range(2)]
    reports = [fr.run() for fr in runs]
    assert runs[0].event_log_bytes() == runs[1].event_log_bytes()
    assert (reports[0]["event_log_sha256"]
            == reports[1]["event_log_sha256"])


def test_different_seed_diverges(fixture_trace):
    _, records = fixture_trace
    a = FleetReplay(records, config=LIVE_CONFIG, seed=0).run()
    b = FleetReplay(records, config=LIVE_CONFIG, seed=1).run()
    assert a["event_log_sha256"] != b["event_log_sha256"]


def test_replay_runs_once(fixture_trace):
    _, records = fixture_trace
    fr = FleetReplay(records[:16], config=LIVE_CONFIG, seed=0)
    fr.run()
    with pytest.raises(RuntimeError):
        fr.run()


def test_unknown_config_key_rejected(fixture_trace):
    _, records = fixture_trace
    with pytest.raises(KeyError):
        FleetReplay(records[:4], config={"max_bacth": 8})


# ---------------------------------------------------------------------------
# speed + fidelity (the ISSUE-17 acceptance numbers)
# ---------------------------------------------------------------------------

def test_replay_is_100x_faster_than_wall_clock(fixture_trace):
    _, records = fixture_trace
    # best of three: the first run pays import/alloc warmup, and CI
    # containers have noisy neighbors — the claim is about the
    # simulator, not about a contended scheduler slice
    speedups = []
    for _ in range(3):
        wall0 = time.perf_counter()
        rep = FleetReplay(records, config=LIVE_CONFIG, seed=0).run()
        wall = time.perf_counter() - wall0
        speedups.append(rep["virtual_s"] / wall)
    assert max(speedups) >= 100.0, f"speedups: {speedups}"


def test_steady_state_fidelity_within_15_percent(fixture_trace):
    _, records = fixture_trace
    fr = FleetReplay(records, config=LIVE_CONFIG, seed=0)
    fr.run()
    live_steady = summarize(
        [r for r in records if r.t >= WARMUP_S]
    )
    sim_steady = summarize(
        [r for r in fr.results if r.t >= WARMUP_S]
    )
    fid = fidelity_report(live_steady, sim_steady,
                          tolerance=0.15, floor_ms=0.25)
    failing = {k: v for k, v in fid["rows"].items() if not v["ok"]}
    assert fid["pass"], f"fidelity misses: {json.dumps(failing)}"
    # the comparison actually covered the signal, not a vacuous pass
    assert "e2e.p99" in fid["rows"]
    assert any(k.startswith("phase.") for k in fid["rows"])


def test_replay_report_shape(fixture_trace):
    _, records = fixture_trace
    rep = FleetReplay(records, config=LIVE_CONFIG, seed=0).run()
    assert rep["benchmark"] == "sim_replay" and rep["sim"] is True
    assert rep["requests"] == len(records)
    assert rep["ok"] + rep["shed"] + rep["expired"] <= rep["requests"]
    assert rep["latency_ms"]["p99"] is not None
    assert rep["slo"]["p99_threshold_ms"] > 0


# ---------------------------------------------------------------------------
# tuner
# ---------------------------------------------------------------------------

def test_tune_beats_default_on_burn_deterministically(fixture_trace):
    _, records = fixture_trace
    artifacts = [
        tune(records, space=DEFAULT_SPACE, budget=8, seed=3,
             time_scale=4.0)
        for _ in range(2)
    ]
    texts = [json.dumps(a, sort_keys=True) for a in artifacts]
    assert texts[0] == texts[1], "tune() is not deterministic"
    art = artifacts[0]
    rec, dfl = art["recommended"], art["default"]
    assert rec["burn_integral"] <= dfl["burn_integral"]
    assert rec["score"] <= dfl["score"]
    assert art["improvement"]["score"] >= 0
    # the stress dial did its job: the default config actually burns,
    # so the win is over a non-trivial baseline
    assert dfl["burn_integral"] > 0


def test_knob_space_rejects_typo():
    from sparkdl_tpu.sim.tune import Knob, KnobSpace
    with pytest.raises(KeyError):
        KnobSpace([Knob("max_bacth", "choice", choices=(8,))])


# ---------------------------------------------------------------------------
# controllers on virtual time
# ---------------------------------------------------------------------------

def test_autoscaler_scales_up_under_stress(fixture_trace):
    _, records = fixture_trace
    cfg = {
        "replicas": 1,
        "autoscale": {
            "min": 1, "max": 4, "interval_s": 0.5, "cooldown_s": 0.5,
            "step_up": 2, "ok_streak": 2, "per_replica_inflight": 8,
        },
        "tick_s": 0.25, "slo_fast_s": 1.0, "slo_slow_s": 2.5,
    }
    rep = FleetReplay(records, config=cfg, seed=0, time_scale=4.0).run()
    decisions = rep["autoscale"]["decisions"]
    assert decisions, "autoscaler never ticked"
    assert rep["autoscale"]["target"] > 1, decisions
    # targets respect the declared bounds at every decision
    assert all(
        1 <= d["replicas_after"] <= 4 for d in decisions
    ), decisions


def test_rollout_promotes_clean_canary(fixture_trace):
    _, records = fixture_trace
    cfg = {
        "rollout": {
            "new_version": "v2", "replicas": 2, "stages": (0.5, 1.0),
            "bake_s": 0.5, "interval_s": 0.25, "regress_ms": 0.0,
            # above the warmup-compile tail: a clean canary must not
            # page on the one-time first-touch compiles
            "slo_p99_ms": 300.0,
        },
        "tick_s": 0.25,
    }
    fr = FleetReplay(records, config=cfg, seed=0)
    rep = fr.run()
    assert rep["rollout"]["state"] == "done", rep["rollout"]
    assert fr.supervisor.primary_version == "v2"


def test_rollout_rolls_back_regressed_canary(fixture_trace):
    _, records = fixture_trace
    cfg = {
        "rollout": {
            "new_version": "v2", "replicas": 2, "stages": (0.5, 1.0),
            "bake_s": 0.5, "interval_s": 0.25,
            # the new version is 500 ms slower: the canary SLO pages
            "regress_ms": 500.0, "slo_p99_ms": 300.0,
        },
        "tick_s": 0.25,
    }
    fr = FleetReplay(records, config=cfg, seed=0)
    rep = fr.run()
    assert rep["rollout"]["state"] == "rolled_back", rep["rollout"]
    assert fr.supervisor.primary_version != "v2"


# ---------------------------------------------------------------------------
# CI integration
# ---------------------------------------------------------------------------

def test_perf_gate_sim_flavor_passes_on_committed_artifact():
    from ci.perf_gate import DEFAULT_SIM_ARTIFACT, DEFAULT_SIM_TRACE, gate_sim
    verdict = gate_sim(str(_REPO / DEFAULT_SIM_TRACE),
                       str(_REPO / DEFAULT_SIM_ARTIFACT))
    failing = [r for r in verdict["rows"] if not r["ok"]]
    assert verdict["ok"], failing
    metrics = {r["metric"] for r in verdict["rows"]}
    assert metrics == {
        "sim.deterministic",
        "sim.recommended_burn_vs_default",
        "sim.recommended_burn_drift",
    }


def test_shape_key_separates_sim_from_live_reports():
    from ci.perf_gate import shape_key
    base = {
        "benchmark": "bench_load", "scenario": "steady",
        "duration_s": 8, "rate": 150, "latency_ms": {"p50": 1.0},
    }
    live = shape_key(base)
    sim = shape_key({**base, "sim": True})
    assert live != sim


def test_eval_harness_keys_are_replay_config_keys():
    # the tuner merges EVAL_HARNESS over every candidate; a drifted key
    # would make _merge_config reject every trial
    assert set(EVAL_HARNESS) <= set(DEFAULT_CONFIG)
