"""KerasImageFileEstimator + tuning tests.

Reference pattern (SURVEY.md §4 ``test_keras_estimators.py``†): a tiny Keras
model over the small image fixtures, fit/fitMultiple asserting a fitted
transformer comes back with param plumbing intact, plus a CrossValidator
smoke test.  Added beyond the reference: the DP-trained loss must actually
decrease, and checkpoint/resume (which the reference lacked entirely).
"""

import os

import numpy as np
import pytest

from sparkdl_tpu.image import imageIO
from sparkdl_tpu.ml.classification import LogisticRegression
from sparkdl_tpu.ml.evaluation import MulticlassClassificationEvaluator
from sparkdl_tpu.ml.tuning import (
    CrossValidator,
    CrossValidatorModel,
    ParamGridBuilder,
)

keras = pytest.importorskip("keras")
from PIL import Image  # noqa: E402

from sparkdl_tpu.estimators import KerasImageFileEstimator  # noqa: E402
from sparkdl_tpu.transformers.keras_image import (  # noqa: E402
    KerasImageFileTransformer,
)


def _tiny_model(tmp_path, seed=0):
    keras.utils.set_random_seed(seed)
    model = keras.Sequential(
        [
            keras.layers.Input(shape=(8, 8, 3)),
            keras.layers.Flatten(),
            keras.layers.Dense(2, activation="softmax"),
        ]
    )
    path = str(tmp_path / "tiny.keras")
    model.save(path)
    return model, path


def _loader(uri):
    img = Image.open(uri).convert("RGB").resize((8, 8))
    return np.asarray(img, dtype=np.float32) / 255.0


@pytest.fixture()
def labeled_df(tpu_session, image_dir):
    df = imageIO.filesToDF(tpu_session, image_dir, numPartitions=2)
    # deterministic labels correlated with mean brightness -> learnable
    def label(uri):
        return int(_loader(uri).mean() > 0.45)

    return df.withColumn("label", label, "filePath")


def _make_estimator(model_path, **fit_params):
    return KerasImageFileEstimator(
        inputCol="filePath",
        outputCol="pred",
        labelCol="label",
        imageLoader=_loader,
        modelFile=model_path,
        kerasOptimizer="adam",
        kerasLoss="sparse_categorical_crossentropy",
        kerasFitParams={"epochs": 8, "batch_size": 8, **fit_params},
    )


def test_fit_returns_transformer_and_learns(labeled_df, tmp_path):
    model, path = _tiny_model(tmp_path)
    est = _make_estimator(path, learning_rate=0.05)
    fitted = est.fit(labeled_df)
    assert isinstance(fitted, KerasImageFileTransformer)
    assert fitted.getModelFile() != path  # tuned copy, not the original
    # the DP loop must actually have optimized something
    assert np.isfinite(fitted._training_loss)

    # and the tuned model fits the training labels
    scored = fitted.transform(labeled_df)
    rows = scored.select("label", "pred").collect()
    preds = [int(np.argmax(r["pred"])) for r in rows]
    labels = [r["label"] for r in rows]
    assert preds == labels, (preds, labels)


def test_missing_required_param_raises(labeled_df, tmp_path):
    _, path = _tiny_model(tmp_path)
    est = KerasImageFileEstimator(
        inputCol="filePath",
        outputCol="pred",
        imageLoader=_loader,
        modelFile=path,
        # labelCol and kerasLoss missing
    )
    with pytest.raises(ValueError, match="Required param"):
        est.fit(labeled_df)


def test_fit_multiple_yields_one_model_per_map(labeled_df, tmp_path):
    _, path = _tiny_model(tmp_path)
    est = _make_estimator(path)
    maps = [
        {est.kerasFitParams: {"epochs": 1, "batch_size": 8}},
        {est.kerasFitParams: {"epochs": 2, "batch_size": 8}},
    ]
    models = est.fit(labeled_df, maps)
    assert len(models) == 2
    assert all(isinstance(m, KerasImageFileTransformer) for m in models)
    assert models[0].getModelFile() != models[1].getModelFile()


def test_checkpoint_and_resume(labeled_df, tmp_path):
    _, path = _tiny_model(tmp_path)
    ckpt = str(tmp_path / "ckpts")
    est = _make_estimator(path)
    est = est.copy({est.checkpointDir: ckpt})
    est.fit(labeled_df)
    # checkpoints are namespaced per training configuration
    namespaces = os.listdir(ckpt)
    assert len(namespaces) == 1 and namespaces[0].startswith("fit_")
    saved = sorted(os.listdir(os.path.join(ckpt, namespaces[0])))
    assert "epoch_1" in saved and "epoch_8" in saved

    # resume: a fresh estimator with the same dir starts past epoch 8 and
    # trains nothing more, but still produces a fitted transformer
    est2 = _make_estimator(path).copy({est.checkpointDir: ckpt})
    fitted = est2.fit(labeled_df)
    assert isinstance(fitted, KerasImageFileTransformer)


def test_checkpoints_namespaced_by_fit_config(labeled_df, tmp_path):
    """Different param maps sharing one checkpointDir must not restore each
    other's state (previously epoch_N keys collided across configs).
    Trajectory params (batch_size here) namespace; `epochs` — a stopping
    point, not a trajectory param — deliberately does not (see
    test_refit_with_more_epochs_resumes)."""
    _, path = _tiny_model(tmp_path)
    ckpt = str(tmp_path / "shared_ckpts")
    est_a = _make_estimator(path, epochs=2, batch_size=8)
    est_a = est_a.copy({est_a.checkpointDir: ckpt})
    est_b = _make_estimator(path, epochs=3, batch_size=4)
    est_b = est_b.copy({est_b.checkpointDir: ckpt})
    est_a.fit(labeled_df)
    fitted_b = est_b.fit(labeled_df)
    namespaces = sorted(os.listdir(ckpt))
    assert len(namespaces) == 2  # one namespace per config
    # est_b trained its full 3 epochs rather than resuming est_a's epoch_2
    assert isinstance(fitted_b, KerasImageFileTransformer)
    ns_b = [
        ns for ns in namespaces
        if "epoch_3" in os.listdir(os.path.join(ckpt, ns))
    ]
    assert len(ns_b) == 1


def test_refit_with_more_epochs_resumes(labeled_df, tmp_path):
    """fit(epochs=2) then fit(epochs=4) on the same checkpointDir must
    resume — training exactly two more epochs in the same namespace — and
    produce weights identical to a single uninterrupted fit(epochs=4)
    (the rng replays restored epochs, so epoch e always sees the e-th
    permutation)."""
    _, path = _tiny_model(tmp_path)
    ckpt = str(tmp_path / "extend_ckpts")

    est2 = _make_estimator(path, epochs=2, learning_rate=0.05)
    est2 = est2.copy({est2.checkpointDir: ckpt})
    est2.fit(labeled_df)
    (ns,) = os.listdir(ckpt)
    assert sorted(os.listdir(os.path.join(ckpt, ns))) == [
        "epoch_1", "epoch_2"
    ]

    est4 = _make_estimator(path, epochs=4, learning_rate=0.05)
    est4 = est4.copy({est4.checkpointDir: ckpt})
    fitted_resumed = est4.fit(labeled_df)
    # same namespace, extended in place — not a fresh restart
    (ns_after,) = os.listdir(ckpt)
    assert ns_after == ns
    assert sorted(os.listdir(os.path.join(ckpt, ns))) == [
        "epoch_1", "epoch_2", "epoch_3", "epoch_4"
    ]

    # oracle: one uninterrupted fit(epochs=4), no checkpointing
    est_straight = _make_estimator(path, epochs=4, learning_rate=0.05)
    fitted_straight = est_straight.fit(labeled_df)

    got = keras.saving.load_model(
        fitted_resumed.getModelFile(), compile=False
    )
    want = keras.saving.load_model(
        fitted_straight.getModelFile(), compile=False
    )
    for g, w in zip(got.trainable_variables, want.trainable_variables):
        np.testing.assert_allclose(
            np.asarray(g.value), np.asarray(w.value), rtol=1e-6, atol=1e-7
        )


def test_fit_dataset_smaller_than_batch(labeled_df, tmp_path):
    """Regression: 3 rows with batch_size 32 on an 8-device mesh previously
    crashed in shard_batch (wrap-around pad produced a 6-row chunk)."""
    _, path = _tiny_model(tmp_path)
    small = labeled_df.limit(3)
    est = _make_estimator(path, epochs=1, batch_size=32)
    fitted = est.fit(small)
    assert isinstance(fitted, KerasImageFileTransformer)
    assert np.isfinite(fitted._training_loss)


def test_padded_rows_do_not_bias_gradient(labeled_df, tmp_path):
    """The ragged final batch is padded to the full batch size but masked:
    one epoch over n rows with batch_size > n must produce exactly the
    single-device full-batch SGD update on those n rows."""
    import jax
    import jax.numpy as jnp

    from sparkdl_tpu.estimators.losses import sparse_categorical_crossentropy

    model, path = _tiny_model(tmp_path, seed=3)
    rows = labeled_df.limit(5).collect()
    x = np.stack([_loader(r.filePath) for r in rows])
    y = np.asarray([r.label for r in rows], np.int32)

    lr = 0.1
    est = KerasImageFileEstimator(
        inputCol="filePath",
        outputCol="pred",
        labelCol="label",
        imageLoader=_loader,
        modelFile=path,
        kerasOptimizer="sgd",
        kerasLoss="sparse_categorical_crossentropy",
        kerasFitParams={
            "epochs": 1,
            "batch_size": 16,
            "learning_rate": lr,
            "seed": 0,
        },
    )
    fitted = est.fit(labeled_df.limit(5))

    # single-device oracle: one plain full-batch SGD step on the 5 rows
    ref = keras.saving.load_model(path, compile=False)
    trainable = [jnp.asarray(v.value) for v in ref.trainable_variables]
    non_trainable = [jnp.asarray(v.value) for v in ref.non_trainable_variables]

    def loss_fn(tr):
        out, _ = ref.stateless_call(tr, non_trainable, jnp.asarray(x),
                                    training=True)
        return sparse_categorical_crossentropy(jnp.asarray(y), out)

    grads = jax.grad(loss_fn)(trainable)
    want = [np.asarray(t - lr * g) for t, g in zip(trainable, grads)]

    tuned = keras.saving.load_model(fitted.getModelFile(), compile=False)
    got = [np.asarray(v.value) for v in tuned.trainable_variables]
    for g, w in zip(got, want):
        np.testing.assert_allclose(g, w, rtol=1e-5, atol=1e-6)


def test_param_grid_builder():
    lr = LogisticRegression()
    grid = (
        ParamGridBuilder()
        .baseOn({lr.featuresCol: "features"})
        .addGrid(lr.maxIter, [10, 50])
        .addGrid(lr.regParam, [0.0, 0.1])
        .build()
    )
    assert len(grid) == 4
    assert all(g[lr.featuresCol] == "features" for g in grid)


def test_cross_validator_picks_best(tpu_session):
    rng = np.random.RandomState(0)
    x0 = rng.randn(40, 4).astype(np.float32) + 2
    x1 = rng.randn(40, 4).astype(np.float32) - 2
    data = [{"features": v, "label": 0} for v in x0] + [
        {"features": v, "label": 1} for v in x1
    ]
    df = tpu_session.createDataFrame(data).repartition(4)
    lr = LogisticRegression(stepSize=0.5)
    grid = (
        ParamGridBuilder().addGrid(lr.maxIter, [1, 150]).build()
    )
    cv = CrossValidator(
        estimator=lr,
        estimatorParamMaps=grid,
        evaluator=MulticlassClassificationEvaluator(metricName="accuracy"),
        numFolds=3,
        parallelism=2,
        seed=7,
    )
    cv_model = cv.fit(df)
    assert isinstance(cv_model, CrossValidatorModel)
    assert len(cv_model.avgMetrics) == 2
    # 150 iterations must beat 1 iteration on separable data
    assert cv_model.avgMetrics[1] >= cv_model.avgMetrics[0]
    acc = MulticlassClassificationEvaluator(metricName="accuracy").evaluate(
        cv_model.transform(df)
    )
    assert acc == 1.0


def test_streaming_fit_identical_to_in_memory(labeled_df, tmp_path):
    """kerasFitParams streaming=True (URIs only in memory, prefetch-thread
    batch loading) produces bit-identical weights to the in-memory path:
    same permutation stream, same cyclic padding."""
    _, model_path = _tiny_model(tmp_path)

    def fit(streaming):
        est = _make_estimator(
            model_path, epochs=3, batch_size=8, learning_rate=0.05, seed=3,
            streaming=streaming,
        )
        fitted = est.fit(labeled_df)
        m = keras.saving.load_model(fitted.getModelFile(), compile=False)
        return [np.asarray(w) for w in m.get_weights()]

    for got, want in zip(fit(True), fit(False)):
        np.testing.assert_array_equal(got, want)


def test_refit_with_fewer_epochs_restores_exact_epoch(labeled_df, tmp_path):
    """fit(epochs=4) then fit(epochs=2) on the same checkpointDir must
    return the exact epoch-2 weights from disk — never the later epoch-4
    state (the restore is capped at the requested stopping point)."""
    _, path = _tiny_model(tmp_path)
    ckpt = str(tmp_path / "shrink_ckpts")

    est4 = _make_estimator(path, epochs=4, learning_rate=0.05)
    est4 = est4.copy({est4.checkpointDir: ckpt})
    fitted4 = est4.fit(labeled_df)

    est2 = _make_estimator(path, epochs=2, learning_rate=0.05)
    est2 = est2.copy({est2.checkpointDir: ckpt})
    fitted2 = est2.fit(labeled_df)

    # oracle: an uninterrupted 2-epoch fit with no checkpointing
    est_straight = _make_estimator(path, epochs=2, learning_rate=0.05)
    fitted_straight = est_straight.fit(labeled_df)

    got = keras.saving.load_model(fitted2.getModelFile(), compile=False)
    want = keras.saving.load_model(
        fitted_straight.getModelFile(), compile=False
    )
    for g, w in zip(got.trainable_variables, want.trainable_variables):
        np.testing.assert_allclose(
            np.asarray(g.value), np.asarray(w.value), rtol=1e-6, atol=1e-7
        )
    # and it is NOT the 4-epoch state
    m4 = keras.saving.load_model(fitted4.getModelFile(), compile=False)
    assert any(
        not np.allclose(np.asarray(a.value), np.asarray(b.value))
        for a, b in zip(got.trainable_variables, m4.trainable_variables)
    )


class TestTrialParallelSlices:
    """Trial-parallelism across disjoint device sub-meshes (SURVEY.md §2
    "trial-parallel across pod slices"; VERDICT r2 missing #3): 8 virtual
    devices -> 2 concurrent trials x 4-device meshes."""

    def test_partition_devices_disjoint_and_mesh_respects_slice(self):
        import jax

        from sparkdl_tpu.parallel.trainer import (
            device_slice,
            make_mesh,
            partition_devices,
        )

        slices = partition_devices(2)
        assert len(slices) == 2
        assert len(slices[0]) == len(slices[1]) == 4
        assert not (set(slices[0]) & set(slices[1]))
        assert set(slices[0]) | set(slices[1]) == set(jax.devices())

        with device_slice(slices[1]):
            mesh = make_mesh()
            assert list(mesh.devices.flat) == slices[1]
        # out of scope: back to the full mesh
        assert make_mesh().devices.size == 8

        with pytest.raises(ValueError, match="partition"):
            partition_devices(3)

    def test_concurrent_sliced_trials_match_sequential(
        self, labeled_df, tmp_path
    ):
        """Two concurrent trials on disjoint 4-device sub-meshes reproduce
        the sequential full-mesh results exactly, and genuinely overlap."""
        import time
        from concurrent.futures import ThreadPoolExecutor

        from sparkdl_tpu.parallel.trainer import (
            bind_device_slice,
            partition_devices,
        )

        _, path = _tiny_model(tmp_path)
        # batch 8 divides both the 8-dev (sequential) and 4-dev (sliced)
        # meshes -> identical global batches -> identical update math
        maps = [
            {"epochs": 2, "batch_size": 8, "learning_rate": lr, "seed": 0}
            for lr in (0.05, 0.01)
        ]

        def weights_of(fitted):
            m = keras.saving.load_model(fitted.getModelFile(), compile=False)
            return [np.asarray(v.value) for v in m.trainable_variables]

        # sequential oracle (full mesh per trial)
        sequential = []
        t0 = time.perf_counter()
        for fp in maps:
            est = _make_estimator(path, **fp)
            sequential.append(weights_of(est.fit(labeled_df)))
        seq_wall = time.perf_counter() - t0

        slices = partition_devices(2)
        windows = [None, None]

        def run_trial(i):
            bind_device_slice(slices[i])
            try:
                start = time.perf_counter()
                est = _make_estimator(path, **maps[i])
                out = weights_of(est.fit(labeled_df))
                windows[i] = (start, time.perf_counter())
                return out
            finally:
                bind_device_slice(None)

        t0 = time.perf_counter()
        with ThreadPoolExecutor(max_workers=2) as pool:
            concurrent = list(pool.map(run_trial, range(2)))
        par_wall = time.perf_counter() - t0

        for got, want in zip(concurrent, sequential):
            for g, w in zip(got, want):
                np.testing.assert_allclose(g, w, rtol=1e-5, atol=1e-6)
        # the trials actually overlapped (both started before either ended)
        (s0, e0), (s1, e1) = windows
        assert s0 < e1 and s1 < e0, (windows, seq_wall, par_wall)
        print(f"sequential {seq_wall:.1f}s vs sliced-parallel {par_wall:.1f}s")

    def test_cross_validator_partition_devices_matches_default(
        self, tpu_session
    ):
        """CrossValidator(partitionDevices=True) end-to-end: same
        avgMetrics and best model as the unpartitioned run."""
        rng = np.random.RandomState(0)
        x0 = rng.randn(30, 4).astype(np.float32) + 2
        x1 = rng.randn(30, 4).astype(np.float32) - 2
        data = [{"features": v, "label": 0} for v in x0] + [
            {"features": v, "label": 1} for v in x1
        ]
        df = tpu_session.createDataFrame(data).repartition(4)
        lr = LogisticRegression(stepSize=0.5)
        grid = ParamGridBuilder().addGrid(lr.maxIter, [1, 100]).build()

        def run(partition):
            cv = CrossValidator(
                estimator=lr,
                estimatorParamMaps=grid,
                evaluator=MulticlassClassificationEvaluator(
                    metricName="accuracy"
                ),
                numFolds=2,
                parallelism=2,
                partitionDevices=partition,
                seed=7,
            )
            return cv.fit(df)

        plain, sliced = run(False), run(True)
        np.testing.assert_allclose(sliced.avgMetrics, plain.avgMetrics)
        acc = MulticlassClassificationEvaluator(
            metricName="accuracy"
        ).evaluate(sliced.transform(df))
        assert acc == 1.0
