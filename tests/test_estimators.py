"""KerasImageFileEstimator + tuning tests.

Reference pattern (SURVEY.md §4 ``test_keras_estimators.py``†): a tiny Keras
model over the small image fixtures, fit/fitMultiple asserting a fitted
transformer comes back with param plumbing intact, plus a CrossValidator
smoke test.  Added beyond the reference: the DP-trained loss must actually
decrease, and checkpoint/resume (which the reference lacked entirely).
"""

import os

import numpy as np
import pytest

from sparkdl_tpu.image import imageIO
from sparkdl_tpu.ml.classification import LogisticRegression
from sparkdl_tpu.ml.evaluation import MulticlassClassificationEvaluator
from sparkdl_tpu.ml.tuning import (
    CrossValidator,
    CrossValidatorModel,
    ParamGridBuilder,
)

keras = pytest.importorskip("keras")
from PIL import Image  # noqa: E402

from sparkdl_tpu.estimators import KerasImageFileEstimator  # noqa: E402
from sparkdl_tpu.transformers.keras_image import (  # noqa: E402
    KerasImageFileTransformer,
)


def _tiny_model(tmp_path, seed=0):
    keras.utils.set_random_seed(seed)
    model = keras.Sequential(
        [
            keras.layers.Input(shape=(8, 8, 3)),
            keras.layers.Flatten(),
            keras.layers.Dense(2, activation="softmax"),
        ]
    )
    path = str(tmp_path / "tiny.keras")
    model.save(path)
    return model, path


def _loader(uri):
    img = Image.open(uri).convert("RGB").resize((8, 8))
    return np.asarray(img, dtype=np.float32) / 255.0


@pytest.fixture()
def labeled_df(tpu_session, image_dir):
    df = imageIO.filesToDF(tpu_session, image_dir, numPartitions=2)
    # deterministic labels correlated with mean brightness -> learnable
    def label(uri):
        return int(_loader(uri).mean() > 0.45)

    return df.withColumn("label", label, "filePath")


def _make_estimator(model_path, **fit_params):
    return KerasImageFileEstimator(
        inputCol="filePath",
        outputCol="pred",
        labelCol="label",
        imageLoader=_loader,
        modelFile=model_path,
        kerasOptimizer="adam",
        kerasLoss="sparse_categorical_crossentropy",
        kerasFitParams={"epochs": 8, "batch_size": 8, **fit_params},
    )


def test_fit_returns_transformer_and_learns(labeled_df, tmp_path):
    model, path = _tiny_model(tmp_path)
    est = _make_estimator(path, learning_rate=0.05)
    fitted = est.fit(labeled_df)
    assert isinstance(fitted, KerasImageFileTransformer)
    assert fitted.getModelFile() != path  # tuned copy, not the original
    # the DP loop must actually have optimized something
    assert np.isfinite(fitted._training_loss)

    # and the tuned model fits the training labels
    scored = fitted.transform(labeled_df)
    rows = scored.select("label", "pred").collect()
    preds = [int(np.argmax(r["pred"])) for r in rows]
    labels = [r["label"] for r in rows]
    assert preds == labels, (preds, labels)


def test_missing_required_param_raises(labeled_df, tmp_path):
    _, path = _tiny_model(tmp_path)
    est = KerasImageFileEstimator(
        inputCol="filePath",
        outputCol="pred",
        imageLoader=_loader,
        modelFile=path,
        # labelCol and kerasLoss missing
    )
    with pytest.raises(ValueError, match="Required param"):
        est.fit(labeled_df)


def test_fit_multiple_yields_one_model_per_map(labeled_df, tmp_path):
    _, path = _tiny_model(tmp_path)
    est = _make_estimator(path)
    maps = [
        {est.kerasFitParams: {"epochs": 1, "batch_size": 8}},
        {est.kerasFitParams: {"epochs": 2, "batch_size": 8}},
    ]
    models = est.fit(labeled_df, maps)
    assert len(models) == 2
    assert all(isinstance(m, KerasImageFileTransformer) for m in models)
    assert models[0].getModelFile() != models[1].getModelFile()


def test_checkpoint_and_resume(labeled_df, tmp_path):
    _, path = _tiny_model(tmp_path)
    ckpt = str(tmp_path / "ckpts")
    est = _make_estimator(path)
    est = est.copy({est.checkpointDir: ckpt})
    est.fit(labeled_df)
    # checkpoints are namespaced per training configuration
    namespaces = os.listdir(ckpt)
    assert len(namespaces) == 1 and namespaces[0].startswith("fit_")
    saved = sorted(os.listdir(os.path.join(ckpt, namespaces[0])))
    assert "epoch_1" in saved and "epoch_8" in saved

    # resume: a fresh estimator with the same dir starts past epoch 8 and
    # trains nothing more, but still produces a fitted transformer
    est2 = _make_estimator(path).copy({est.checkpointDir: ckpt})
    fitted = est2.fit(labeled_df)
    assert isinstance(fitted, KerasImageFileTransformer)


def test_checkpoints_namespaced_by_fit_config(labeled_df, tmp_path):
    """Different param maps sharing one checkpointDir must not restore each
    other's state (previously epoch_N keys collided across configs)."""
    _, path = _tiny_model(tmp_path)
    ckpt = str(tmp_path / "shared_ckpts")
    est_a = _make_estimator(path, epochs=2)
    est_a = est_a.copy({est_a.checkpointDir: ckpt})
    est_b = _make_estimator(path, epochs=3)
    est_b = est_b.copy({est_b.checkpointDir: ckpt})
    est_a.fit(labeled_df)
    fitted_b = est_b.fit(labeled_df)
    namespaces = sorted(os.listdir(ckpt))
    assert len(namespaces) == 2  # one namespace per config
    # est_b trained its full 3 epochs rather than resuming est_a's epoch_2
    assert isinstance(fitted_b, KerasImageFileTransformer)
    ns_b = [
        ns for ns in namespaces
        if "epoch_3" in os.listdir(os.path.join(ckpt, ns))
    ]
    assert len(ns_b) == 1


def test_fit_dataset_smaller_than_batch(labeled_df, tmp_path):
    """Regression: 3 rows with batch_size 32 on an 8-device mesh previously
    crashed in shard_batch (wrap-around pad produced a 6-row chunk)."""
    _, path = _tiny_model(tmp_path)
    small = labeled_df.limit(3)
    est = _make_estimator(path, epochs=1, batch_size=32)
    fitted = est.fit(small)
    assert isinstance(fitted, KerasImageFileTransformer)
    assert np.isfinite(fitted._training_loss)


def test_padded_rows_do_not_bias_gradient(labeled_df, tmp_path):
    """The ragged final batch is padded to the full batch size but masked:
    one epoch over n rows with batch_size > n must produce exactly the
    single-device full-batch SGD update on those n rows."""
    import jax
    import jax.numpy as jnp

    from sparkdl_tpu.estimators.losses import sparse_categorical_crossentropy

    model, path = _tiny_model(tmp_path, seed=3)
    rows = labeled_df.limit(5).collect()
    x = np.stack([_loader(r.filePath) for r in rows])
    y = np.asarray([r.label for r in rows], np.int32)

    lr = 0.1
    est = KerasImageFileEstimator(
        inputCol="filePath",
        outputCol="pred",
        labelCol="label",
        imageLoader=_loader,
        modelFile=path,
        kerasOptimizer="sgd",
        kerasLoss="sparse_categorical_crossentropy",
        kerasFitParams={
            "epochs": 1,
            "batch_size": 16,
            "learning_rate": lr,
            "seed": 0,
        },
    )
    fitted = est.fit(labeled_df.limit(5))

    # single-device oracle: one plain full-batch SGD step on the 5 rows
    ref = keras.saving.load_model(path, compile=False)
    trainable = [jnp.asarray(v.value) for v in ref.trainable_variables]
    non_trainable = [jnp.asarray(v.value) for v in ref.non_trainable_variables]

    def loss_fn(tr):
        out, _ = ref.stateless_call(tr, non_trainable, jnp.asarray(x),
                                    training=True)
        return sparse_categorical_crossentropy(jnp.asarray(y), out)

    grads = jax.grad(loss_fn)(trainable)
    want = [np.asarray(t - lr * g) for t, g in zip(trainable, grads)]

    tuned = keras.saving.load_model(fitted.getModelFile(), compile=False)
    got = [np.asarray(v.value) for v in tuned.trainable_variables]
    for g, w in zip(got, want):
        np.testing.assert_allclose(g, w, rtol=1e-5, atol=1e-6)


def test_param_grid_builder():
    lr = LogisticRegression()
    grid = (
        ParamGridBuilder()
        .baseOn({lr.featuresCol: "features"})
        .addGrid(lr.maxIter, [10, 50])
        .addGrid(lr.regParam, [0.0, 0.1])
        .build()
    )
    assert len(grid) == 4
    assert all(g[lr.featuresCol] == "features" for g in grid)


def test_cross_validator_picks_best(tpu_session):
    rng = np.random.RandomState(0)
    x0 = rng.randn(40, 4).astype(np.float32) + 2
    x1 = rng.randn(40, 4).astype(np.float32) - 2
    data = [{"features": v, "label": 0} for v in x0] + [
        {"features": v, "label": 1} for v in x1
    ]
    df = tpu_session.createDataFrame(data).repartition(4)
    lr = LogisticRegression(stepSize=0.5)
    grid = (
        ParamGridBuilder().addGrid(lr.maxIter, [1, 150]).build()
    )
    cv = CrossValidator(
        estimator=lr,
        estimatorParamMaps=grid,
        evaluator=MulticlassClassificationEvaluator(metricName="accuracy"),
        numFolds=3,
        parallelism=2,
        seed=7,
    )
    cv_model = cv.fit(df)
    assert isinstance(cv_model, CrossValidatorModel)
    assert len(cv_model.avgMetrics) == 2
    # 150 iterations must beat 1 iteration on separable data
    assert cv_model.avgMetrics[1] >= cv_model.avgMetrics[0]
    acc = MulticlassClassificationEvaluator(metricName="accuracy").evaluate(
        cv_model.transform(df)
    )
    assert acc == 1.0


def test_streaming_fit_identical_to_in_memory(labeled_df, tmp_path):
    """kerasFitParams streaming=True (URIs only in memory, prefetch-thread
    batch loading) produces bit-identical weights to the in-memory path:
    same permutation stream, same cyclic padding."""
    _, model_path = _tiny_model(tmp_path)

    def fit(streaming):
        est = _make_estimator(
            model_path, epochs=3, batch_size=8, learning_rate=0.05, seed=3,
            streaming=streaming,
        )
        fitted = est.fit(labeled_df)
        m = keras.saving.load_model(fitted.getModelFile(), compile=False)
        return [np.asarray(w) for w in m.get_weights()]

    for got, want in zip(fit(True), fit(False)):
        np.testing.assert_array_equal(got, want)
