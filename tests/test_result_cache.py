"""Content-addressed result cache tests (ISSUE-16).

Covers the three cache primitives in ``serving/result_cache.py``
(canonical digester, bounded-byte router LRU, replica-tier
single-flight + negative cache), the router wiring (hit path, the
unfingerprinted-is-uncacheable rule, fail-open under an injected
``cache.lookup`` fault), and the ``/debug/cache`` ObsServer pane
(including the 400-not-500 malformed-param contract from ISSUE-15).

The rollout-flip invalidation proof — a promoted v2 never serving v1's
cached bytes with zero manual flushes — lives in ``test_rollout.py``
next to the rest of the versioned-routing matrix.
"""

import json
import threading
import urllib.error
import urllib.request

import numpy as np
import pytest

from sparkdl_tpu.resilience import inject
from sparkdl_tpu.serving.result_cache import (
    NegativeCache,
    ResultCache,
    SingleFlight,
    canonical_digest,
    result_key,
)


# ----------------------------------------------------------------------
# canonical digester
# ----------------------------------------------------------------------
class TestCanonicalDigest:
    def test_strided_equal_arrays_digest_identically(self):
        # THE digester contract: layout is normalized away — a
        # C-contiguous array and its Fortran-ordered twin carry the
        # same bytes-in-math and must produce the same key
        a = np.arange(24, dtype=np.float32).reshape(4, 6)
        b = np.asfortranarray(a)
        assert not b.flags["C_CONTIGUOUS"]
        np.testing.assert_array_equal(a, b)
        assert canonical_digest(a) == canonical_digest(b)

    def test_sliced_view_digests_like_its_copy(self):
        base = np.arange(32, dtype=np.float32)
        view = base[::2]          # non-contiguous view
        copy = view.copy()        # contiguous, same values
        assert canonical_digest(view) == canonical_digest(copy)

    def test_dtype_is_part_of_the_key(self):
        a = np.ones(8, dtype=np.float32)
        b = np.ones(8, dtype=np.float64)
        assert canonical_digest(a) != canonical_digest(b)

    def test_shape_is_part_of_the_key(self):
        a = np.zeros(6, dtype=np.float32)
        b = np.zeros((2, 3), dtype=np.float32)
        assert canonical_digest(a) != canonical_digest(b)

    def test_scalar_meta_changes_the_digest(self):
        x = np.ones(4, dtype=np.float32)
        assert canonical_digest(x) != canonical_digest(
            x, meta={"tenant": "a"}
        )
        assert canonical_digest(x, meta={"k": 1}) == canonical_digest(
            x, meta={"k": 1}
        )

    def test_non_array_values_digest_stably(self):
        assert canonical_digest({"a": 1}) == canonical_digest({"a": 1})
        assert canonical_digest({"a": 1}) != canonical_digest({"a": 2})

    def test_result_key_separates_fingerprints(self):
        d = canonical_digest(np.ones(4, dtype=np.float32))
        assert result_key("model:v1", d) != result_key("model:v2", d)
        assert result_key("model:v1", d) == result_key("model:v1", d)


# ----------------------------------------------------------------------
# router-tier LRU
# ----------------------------------------------------------------------
class TestResultCache:
    def test_put_get_roundtrip_and_hit_miss_counts(self):
        rc = ResultCache(max_bytes=1 << 20)
        out = np.arange(8, dtype=np.float32)
        assert rc.get("k1") is None
        assert rc.put("k1", out)
        hit = rc.get("k1")
        np.testing.assert_array_equal(hit, out)
        snap = rc.snapshot()
        assert snap["hit"] == 1 and snap["miss"] == 1

    def test_cached_result_is_immutable_copy(self):
        rc = ResultCache(max_bytes=1 << 20)
        out = np.arange(4, dtype=np.float32)
        rc.put("k", out)
        out[0] = 99.0  # caller mutating its array must not poison
        hit = rc.get("k")
        assert hit[0] == 0.0
        with pytest.raises((ValueError, RuntimeError)):
            hit[0] = 7.0  # and hit recipients get a frozen view

    def test_byte_budget_evicts_lru(self):
        one = np.zeros(16, dtype=np.float32)  # 64 bytes each
        rc = ResultCache(max_bytes=3 * one.nbytes)
        for i in range(3):
            rc.put(f"k{i}", one)
        rc.get("k0")          # refresh k0 — k1 becomes LRU
        rc.put("k3", one)     # over budget: k1 must go
        assert rc.get("k1") is None
        assert rc.get("k0") is not None
        assert rc.get("k3") is not None
        assert rc.snapshot()["evicted"] == 1
        assert rc.bytes <= rc.snapshot()["max_bytes"]

    def test_oversized_result_is_refused_not_cached(self):
        rc = ResultCache(max_bytes=64)
        big = np.zeros(1024, dtype=np.float32)
        assert not rc.put("big", big)
        assert len(rc) == 0

    def test_put_is_idempotent(self):
        rc = ResultCache(max_bytes=1 << 20)
        out = np.ones(8, dtype=np.float32)
        rc.put("k", out)
        before = rc.bytes
        rc.put("k", out)  # hedge race: second populate is a no-op
        assert rc.bytes == before
        assert len(rc) == 1

    def test_snapshot_top_keys_ranked_by_hits(self):
        rc = ResultCache(max_bytes=1 << 20)
        for name, hits in (("hot", 5), ("warm", 2), ("cold", 0)):
            rc.put(name, np.ones(4, dtype=np.float32))
            for _ in range(hits):
                rc.get(name)
        top = rc.snapshot(top=2)["top_keys"]
        assert len(top) == 2
        assert top[0]["hits"] == 5 and top[1]["hits"] == 2

    def test_clear_empties_and_zeroes_bytes(self):
        rc = ResultCache(max_bytes=1 << 20)
        rc.put("k", np.ones(4, dtype=np.float32))
        rc.clear()
        assert len(rc) == 0 and rc.bytes == 0
        assert rc.get("k") is None


# ----------------------------------------------------------------------
# replica-tier single-flight + negative cache
# ----------------------------------------------------------------------
class TestSingleFlight:
    def test_first_claim_leads_rest_collapse(self):
        sf = SingleFlight()
        flight, leader = sf.claim("k")
        assert leader
        f2, l2 = sf.claim("k")
        assert not l2 and f2 is flight
        assert sf.stats()["collapsed"] == 1

    def test_resolve_wakes_followers_with_reply(self):
        sf = SingleFlight()
        flight, _ = sf.claim("k")
        follower, leader = sf.claim("k")
        assert not leader
        got = []

        def wait():
            follower.event.wait(5.0)
            got.append(follower.reply)

        t = threading.Thread(target=wait)
        t.start()
        sf.resolve(flight, reply={"ok": True, "result": 42})
        t.join(timeout=5.0)
        assert got and got[0]["result"] == 42

    def test_resolve_pops_before_set(self):
        # the compile-cache idiom: once resolved, the key is free — a
        # NEW claim must lead a fresh flight, never join the stale one
        sf = SingleFlight()
        flight, _ = sf.claim("k")
        sf.resolve(flight, reply={"ok": True})
        f2, leader = sf.claim("k")
        assert leader and f2 is not flight

    def test_exception_propagates_to_followers(self):
        sf = SingleFlight()
        flight, _ = sf.claim("k")
        follower, _ = sf.claim("k")
        boom = ValueError("scoring failed")
        sf.resolve(flight, exc=boom)
        assert follower.event.wait(1.0)
        assert follower.exc is boom


class TestNegativeCache:
    def test_stores_and_replays_error_reply(self):
        nc = NegativeCache(capacity=4)
        err = {"ok": False, "error": "poison", "error_class": "ValueError"}
        assert nc.get("k") is None
        nc.put("k", err)
        got = nc.get("k")
        assert got == err
        got["mutated"] = True  # replay hands out copies
        assert "mutated" not in nc.get("k")

    def test_capacity_evicts_oldest(self):
        nc = NegativeCache(capacity=2)
        for i in range(3):
            nc.put(f"k{i}", {"ok": False, "error": str(i)})
        assert nc.get("k0") is None
        assert nc.get("k2") is not None
        assert len(nc) == 2


# ----------------------------------------------------------------------
# router wiring: hit path, uncacheable rule, fail-open
# ----------------------------------------------------------------------
def _cached_service(counter=None, scale=2.0, fingerprint="m:v1"):
    from sparkdl_tpu.serving import ModelServer, ServingConfig
    from sparkdl_tpu.serving.replica import ReplicaService

    server = ModelServer(ServingConfig(
        max_batch=8, max_wait_ms=1.0, queue_capacity=64,
    ))

    def forward(x):
        batch = np.asarray(x)
        if counter is not None:
            counter.extend([1] * batch.shape[0])
        return batch * scale

    server.register("ep0", forward, item_shape=(4,), compile=False,
                    fingerprint=fingerprint)
    return ReplicaService(server).start()


@pytest.fixture
def cache_env(monkeypatch):
    monkeypatch.setenv("SPARKDL_RESULT_CACHE", "1")


class TestRouterCacheWiring:
    def test_hit_serves_without_touching_the_replica(self, cache_env):
        from sparkdl_tpu.serving.router import Router

        served = []
        svc = _cached_service(served)
        with Router(seed=5) as router:
            router.add("r1", "127.0.0.1", svc.port,
                       fingerprints={"ep0": "m:v1"})
            x = np.ones(4, np.float32)
            try:
                first = np.asarray(router.route(x, model_id="ep0"))
                second = np.asarray(router.route(x, model_id="ep0"))
                assert len(served) == 1  # the hit never hit the device
                assert second.tobytes() == first.tobytes()
                snap = router.result_cache.snapshot()
                assert snap["hit"] == 1 and snap["miss"] == 1
            finally:
                svc.close()

    def test_unfingerprinted_endpoint_is_uncacheable(self, cache_env):
        from sparkdl_tpu.serving.router import Router

        served = []
        svc = _cached_service(served, fingerprint=None)
        with Router(seed=5) as router:
            router.add("r1", "127.0.0.1", svc.port)  # no fingerprints
            x = np.ones(4, np.float32)
            try:
                router.route(x, model_id="ep0")
                router.route(x, model_id="ep0")
                # PR-5's rule at request granularity: no fingerprint,
                # no cache entry — both requests scored
                assert len(served) == 2
                snap = router.result_cache.snapshot()
                assert snap["entries"] == 0
                assert snap["uncacheable"] == 2
            finally:
                svc.close()

    def test_cache_lookup_fault_fails_open_to_scoring(self, cache_env):
        # the fail-open contract the ci/fault-suite.sh smoke also
        # proves end-to-end: an error injected at the cache.lookup
        # site degrades every request to the miss path — served
        # correctly, never an error, and nothing cached under a key
        # the faulted lookup couldn't resolve
        from sparkdl_tpu.serving.router import Router

        svc = _cached_service()
        with Router(seed=5) as router:
            router.add("r1", "127.0.0.1", svc.port,
                       fingerprints={"ep0": "m:v1"})
            x = np.ones(4, np.float32)
            plan = inject.FaultPlan().add(
                "cache.lookup", error="transient", p=1.0
            )
            try:
                with inject.active_plan(plan):
                    for _ in range(3):
                        out = router.route(x, model_id="ep0")
                        np.testing.assert_allclose(np.asarray(out), 2.0)
                snap = router.result_cache.snapshot()
                assert snap["hit"] == 0 and snap["entries"] == 0
                # fault lifted: the cache resumes without intervention
                router.route(x, model_id="ep0")
                router.route(x, model_id="ep0")
                assert router.result_cache.snapshot()["hit"] == 1
            finally:
                svc.close()

    def test_cache_site_is_registered(self):
        assert "cache.lookup" in inject.known_sites()

    def test_cache_off_by_default(self):
        from sparkdl_tpu.serving.router import Router

        with Router() as router:
            assert router.result_cache is None


# ----------------------------------------------------------------------
# replica tier through the wire: negative cache stops a stampede
# ----------------------------------------------------------------------
class TestReplicaTierWiring:
    def test_poison_input_scores_once_then_replays(self, cache_env):
        from sparkdl_tpu.serving.errors import RemoteReplicaError
        from sparkdl_tpu.serving.router import Router

        scored = []

        def poison(x):
            scored.append(1)
            raise ValueError("NaN in feature 3")

        from sparkdl_tpu.serving import ModelServer, ServingConfig
        from sparkdl_tpu.serving.replica import ReplicaService

        server = ModelServer(ServingConfig(
            max_batch=1, max_wait_ms=0.5, queue_capacity=64,
        ))
        server.register("ep0", poison, item_shape=(4,), compile=False,
                        fingerprint="m:v1")
        svc = ReplicaService(server).start()
        with Router(seed=5) as router:
            router.add("r1", "127.0.0.1", svc.port,
                       fingerprints={"ep0": "m:v1"})
            x = np.ones(4, np.float32)
            try:
                for _ in range(4):
                    with pytest.raises(RemoteReplicaError):
                        router.route(x, model_id="ep0")
                # the device saw the poison exactly once; the other
                # three replays came from the negative cache
                assert len(scored) == 1
                neg = svc.cache_snapshot()["negative"]
                assert neg["stored"] == 1 and neg["hit"] == 3
            finally:
                svc.close()

    def test_transient_errors_are_never_negative_cached(self, cache_env):
        from sparkdl_tpu.serving.result_cache import NegativeCache

        # the taxonomy guard is in ReplicaService._maybe_negative;
        # unit-check the contract it encodes: only permanent,
        # input-determined failures may replay
        from sparkdl_tpu.resilience.errors import is_transient
        from sparkdl_tpu.serving.errors import (
            DeadlineExceeded,
            ServerOverloaded,
        )

        assert is_transient(ServerOverloaded("queue full"))
        assert not isinstance(ValueError("poison"), DeadlineExceeded)


# ----------------------------------------------------------------------
# /debug/cache pane
# ----------------------------------------------------------------------
def _get(url):
    try:
        with urllib.request.urlopen(url, timeout=5.0) as resp:
            return resp.status, json.loads(resp.read().decode())
    except urllib.error.HTTPError as exc:
        return exc.code, json.loads(exc.read().decode())


class TestDebugCacheEndpoint:
    def test_snapshot_served_with_top_param(self):
        from sparkdl_tpu.obs.server import ObsServer

        rc = ResultCache(max_bytes=1 << 20)
        for name, hits in (("hot", 3), ("cold", 0)):
            rc.put(name, np.ones(4, dtype=np.float32))
            for _ in range(hits):
                rc.get(name)
        with ObsServer(port=0, cache=rc) as srv:
            status, payload = _get(f"{srv.url}/debug/cache?top=1")
            assert status == 200
            assert payload["entries"] == 2
            assert len(payload["top_keys"]) == 1
            assert payload["top_keys"][0]["hits"] == 3

    def test_callable_slot_is_duck_typed(self):
        from sparkdl_tpu.obs.server import ObsServer

        def view(top=10):
            return {"tier": "replica", "top": top}

        with ObsServer(port=0, cache=view) as srv:
            status, payload = _get(f"{srv.url}/debug/cache?top=4")
            assert status == 200
            assert payload == {"tier": "replica", "top": 4}

    def test_malformed_top_is_400_not_500(self):
        from sparkdl_tpu.obs.server import ObsServer

        with ObsServer(port=0, cache=ResultCache()) as srv:
            for bad in ("banana", "999"):
                status, payload = _get(
                    f"{srv.url}/debug/cache?top={bad}"
                )
                assert status == 400, (bad, payload)
                assert "top" in payload["error"]

    def test_unwired_cache_is_404(self):
        from sparkdl_tpu.obs.server import ObsServer

        with ObsServer(port=0) as srv:
            status, payload = _get(f"{srv.url}/debug/cache")
            assert status == 404
            assert "cache" in payload["error"]

    def test_index_lists_the_pane(self):
        from sparkdl_tpu.obs.server import ObsServer

        with ObsServer(port=0) as srv:
            status, payload = _get(f"{srv.url}/index")
            assert status == 200
            assert "/debug/cache" in payload["endpoints"]
