"""Transformer integration tests (local engine + CPU jax).

Reference pattern (SURVEY.md §4): transformer output is compared against
directly running the same model on the same decoded arrays — the oracle is
plain Keras / numpy, tolerance-based (``named_image_test.py``†,
``tf_image_test.py``†, ``keras_tensor_test.py``†).
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from sparkdl_tpu.graph.function import XlaFunction
from sparkdl_tpu.image import imageIO
from sparkdl_tpu.ml.classification import LogisticRegression
from sparkdl_tpu.ml.evaluation import MulticlassClassificationEvaluator
from sparkdl_tpu.ml.pipeline import Pipeline
from sparkdl_tpu.models import get_keras_application_model

keras = pytest.importorskip("keras")


@pytest.fixture(scope="module")
def mobilenet_oracle():
    entry = get_keras_application_model("MobileNetV2")
    km = entry.keras_model(weights=None)
    return entry, km, entry.load_variables(km)


@pytest.fixture()
def image_df(tpu_session, image_dir):
    return imageIO.readImages(image_dir, tpu_session, numPartitions=2)


def _decoded_rgb_images(df, input_col="image"):
    out = []
    for row in df.collect():
        arr = imageIO.imageStructToArray(row[input_col]).astype(np.float32)
        if arr.ndim == 2:
            arr = arr[:, :, None]
        if arr.shape[-1] == 1:
            arr = np.repeat(arr, 3, axis=-1)
        arr = arr[:, :, ::-1]  # stored BGR -> RGB
        out.append(arr)
    return out


# ---------------------------------------------------------------------------
# TFImageTransformer
# ---------------------------------------------------------------------------


def test_tf_image_transformer_vector_vs_numpy_oracle(image_df):
    from sparkdl_tpu.transformers.tf_image import TFImageTransformer

    fn = XlaFunction.from_callable(
        lambda x: jnp.mean(x, axis=(1, 2)), name="chan_mean"
    )
    t = TFImageTransformer(
        inputCol="image",
        outputCol="out",
        graph=fn,
        inputShape=(64, 64),
        channelOrder="RGB",
        batchSize=4,
    )
    result = t.transform(image_df)
    got = {r["filePath"]: np.asarray(r["out"]) for r in
           result.select("filePath", "out").collect()}

    # oracle: same decode -> same resize -> channel mean, plain jax on host
    from sparkdl_tpu.transformers.utils import normalize_channels

    rows = image_df.collect()
    for row in rows:
        arr = normalize_channels(
            imageIO.imageStructToArray(row["image"]).astype(np.float32), 3
        )
        rgb = arr[:, :, ::-1]
        resized = np.asarray(
            jax.image.resize(
                jnp.asarray(rgb)[None],
                (1, 64, 64, rgb.shape[-1]),
                "bilinear",
            )
        )[0]
        want = resized.mean(axis=(0, 1))
        np.testing.assert_allclose(
            got[row["filePath"]], want, rtol=1e-4, atol=1e-3
        )


def test_tf_image_transformer_image_output_mode(image_df):
    from sparkdl_tpu.transformers.tf_image import TFImageTransformer

    fn = XlaFunction.from_callable(lambda x: x * 0.5, name="halve")
    t = TFImageTransformer(
        inputCol="image",
        outputCol="out",
        graph=fn,
        inputShape=(32, 32),
        outputMode="image",
    )
    # only 3-channel rows: drop the grayscale fixture
    df = image_df.filter(lambda r: r["image"]["nChannels"] == 3)
    out_rows = t.transform(df).collect()
    assert out_rows
    for r in out_rows:
        struct = r["out"]
        assert struct["height"] == 32 and struct["width"] == 32
        arr = imageIO.imageStructToArray(struct)
        assert arr.dtype == np.float32


# ---------------------------------------------------------------------------
# DeepImageFeaturizer / DeepImagePredictor
# ---------------------------------------------------------------------------


def test_deep_image_featurizer_vs_keras_oracle(image_df, mobilenet_oracle):
    from sparkdl_tpu.transformers.named_image import DeepImageFeaturizer

    entry, km, variables = mobilenet_oracle
    featurizer = DeepImageFeaturizer(
        inputCol="image",
        outputCol="features",
        modelName="MobileNetV2",
        modelWeights=variables,
        computeDtype="float32",
        batchSize=4,
    )
    result = featurizer.transform(image_df)
    got = {r["filePath"]: np.asarray(r["features"]) for r in
           result.select("filePath", "features").collect()}
    assert all(v.shape == (entry.feature_size,) for v in got.values())

    # oracle: same decode -> jax resize -> preprocess -> features cut
    rows = image_df.collect()
    h, w = entry.input_size
    for row in rows:
        arr = imageIO.imageStructToArray(row["image"]).astype(np.float32)
        if arr.ndim == 2:
            arr = arr[:, :, None]
        if arr.shape[-1] == 1:
            arr = np.repeat(arr, 3, axis=-1)
        rgb = arr[:, :, ::-1]
        resized = np.asarray(
            jax.image.resize(jnp.asarray(rgb)[None], (1, h, w, 3), "bilinear")
        )
        pre = np.asarray(entry.preprocess(jnp.asarray(resized)))
        fm = entry.make_module()
        want = np.asarray(
            jax.jit(lambda v, a: fm.apply(v, a, features_only=True))(
                variables, jnp.asarray(pre)
            )
        )[0]
        np.testing.assert_allclose(
            got[row["filePath"]], want, rtol=1e-3, atol=1e-3
        )


def test_deep_image_predictor_decoded(image_df, mobilenet_oracle):
    from sparkdl_tpu.transformers.named_image import DeepImagePredictor

    entry, km, variables = mobilenet_oracle
    predictor = DeepImagePredictor(
        inputCol="image",
        outputCol="preds",
        modelName="MobileNetV2",
        modelWeights=variables,
        decodePredictions=True,
        topK=3,
        computeDtype="float32",
    )
    rows = predictor.transform(image_df).collect()
    for r in rows:
        preds = r["preds"]
        assert len(preds) == 3
        probs = [p["probability"] for p in preds]
        assert probs == sorted(probs, reverse=True)
        assert all(0.0 <= p <= 1.0 for p in probs)


def test_named_transformer_rejects_unknown_model():
    from sparkdl_tpu.transformers.named_image import DeepImageFeaturizer

    with pytest.raises(ValueError, match="Unsupported model name"):
        DeepImageFeaturizer(
            inputCol="image", outputCol="f", modelName="NopeNet"
        )._build_forward()


# ---------------------------------------------------------------------------
# TFTransformer / KerasTransformer (tensor columns)
# ---------------------------------------------------------------------------


def test_tf_transformer_mappings(tpu_session):
    from sparkdl_tpu.transformers.tf_tensor import TFTransformer

    rng = np.random.RandomState(0)
    vecs = [rng.rand(8).astype(np.float32) for _ in range(11)]
    df = tpu_session.createDataFrame([{"x": v} for v in vecs])

    fn = XlaFunction.from_callable(
        lambda x: (x * 2.0, jnp.sum(x, axis=-1)),
        input_names=("inp",),
        output_names=("doubled", "total"),
        name="double_sum",
    )
    t = TFTransformer(
        tfInputGraph=fn,
        inputMapping={"x": "inp"},
        outputMapping={"doubled": "x2", "total": "sum"},
        batchSize=4,
    )
    rows = t.transform(df).collect()
    for row, v in zip(rows, vecs):
        np.testing.assert_allclose(row["x2"], v * 2, rtol=1e-6)
        np.testing.assert_allclose(row["sum"], v.sum(), rtol=1e-5)


def test_tf_transformer_bad_mapping(tpu_session):
    from sparkdl_tpu.transformers.tf_tensor import TFTransformer

    df = tpu_session.createDataFrame([{"x": np.zeros(3, np.float32)}])
    fn = XlaFunction.from_callable(lambda x: x, name="id")
    with pytest.raises(ValueError, match="Unknown function outputs"):
        TFTransformer(
            tfInputGraph=fn,
            inputMapping={"x": "input"},
            outputMapping={"nope": "y"},
        ).transform(df)


def test_keras_transformer_vs_keras_oracle(tpu_session, tmp_path):
    from sparkdl_tpu.transformers.keras_tensor import KerasTransformer

    model = keras.Sequential(
        [
            keras.layers.Input(shape=(10,)),
            keras.layers.Dense(7, activation="relu"),
            keras.layers.Dense(3),
        ]
    )
    path = str(tmp_path / "model.keras")
    model.save(path)

    rng = np.random.RandomState(1)
    vecs = [rng.rand(10).astype(np.float32) for _ in range(9)]
    df = tpu_session.createDataFrame([{"x": v} for v in vecs])
    t = KerasTransformer(inputCol="x", outputCol="y", modelFile=path,
                         batchSize=4)
    rows = t.transform(df).collect()
    want = np.asarray(model(np.stack(vecs)))
    got = np.stack([np.asarray(r["y"]) for r in rows])
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


def test_keras_image_file_transformer(tpu_session, image_dir, tmp_path):
    from sparkdl_tpu.transformers.keras_image import KerasImageFileTransformer
    from PIL import Image

    model = keras.Sequential(
        [
            keras.layers.Input(shape=(16, 16, 3)),
            keras.layers.Flatten(),
            keras.layers.Dense(4),
        ]
    )
    path = str(tmp_path / "img_model.keras")
    model.save(path)

    def loader(uri):
        img = Image.open(uri).convert("RGB").resize((16, 16))
        return np.asarray(img, dtype=np.float32) / 255.0

    df = imageIO.filesToDF(tpu_session, image_dir, numPartitions=2)
    t = KerasImageFileTransformer(
        inputCol="filePath",
        outputCol="out",
        modelFile=path,
        imageLoader=loader,
        batchSize=4,
    )
    rows = t.transform(df).select("filePath", "out").collect()
    for r in rows:
        want = np.asarray(model(loader(r["filePath"])[None]))[0]
        np.testing.assert_allclose(
            np.asarray(r["out"]), want, rtol=1e-4, atol=1e-5
        )


def test_keras_image_file_transformer_bf16(tpu_session, image_dir, tmp_path):
    """computeDtype='bfloat16' loads the saved model under the
    mixed_bfloat16 policy; outputs match f32 within bf16 tolerance."""
    from PIL import Image

    from sparkdl_tpu.transformers.keras_image import KerasImageFileTransformer

    model = keras.Sequential(
        [
            keras.layers.Input(shape=(16, 16, 3)),
            keras.layers.Conv2D(8, 3, padding="same", activation="relu"),
            keras.layers.GlobalAveragePooling2D(),
        ]
    )
    path = str(tmp_path / "bf16_model.keras")
    model.save(path)

    def loader(uri):
        img = Image.open(uri).convert("RGB").resize((16, 16))
        return np.asarray(img, dtype=np.float32) / 255.0

    df = imageIO.filesToDF(tpu_session, image_dir, numPartitions=2)

    def run(dtype):
        t = KerasImageFileTransformer(
            inputCol="filePath", outputCol="out", modelFile=path,
            imageLoader=loader, batchSize=4, computeDtype=dtype,
        )
        rows = t.transform(df).select("filePath", "out").collect()
        return {r["filePath"]: np.asarray(r["out"]) for r in rows}

    f32 = run("float32")
    bf16 = run("bfloat16")
    assert f32.keys() == bf16.keys()
    for k in f32:
        np.testing.assert_allclose(bf16[k], f32[k], rtol=2e-2, atol=2e-2)


# ---------------------------------------------------------------------------
# LogisticRegression head + flagship pipeline slice
# ---------------------------------------------------------------------------


def test_logistic_regression_separable(tpu_session):
    rng = np.random.RandomState(0)
    x0 = rng.randn(30, 4).astype(np.float32) + 3
    x1 = rng.randn(30, 4).astype(np.float32) - 3
    data = [{"features": v, "label": 0} for v in x0] + [
        {"features": v, "label": 1} for v in x1
    ]
    df = tpu_session.createDataFrame(data).repartition(3)
    lr = LogisticRegression(maxIter=200, stepSize=0.5)
    model = lr.fit(df)
    pred = model.transform(df)
    acc = MulticlassClassificationEvaluator(metricName="accuracy").evaluate(pred)
    assert acc == 1.0
    f1 = MulticlassClassificationEvaluator(metricName="f1").evaluate(pred)
    assert f1 == 1.0


def test_flagship_pipeline_featurizer_plus_lr(image_df, mobilenet_oracle):
    """The minimum end-to-end slice (SURVEY.md §7 step 4): DeepImageFeaturizer
    -> LogisticRegression as a Pipeline, mirroring the reference's tf-flowers
    transfer-learning flow."""
    from sparkdl_tpu.transformers.named_image import DeepImageFeaturizer

    entry, km, variables = mobilenet_oracle
    labeled = image_df.withColumn(
        "label", lambda p: hash(p) % 2, "filePath"
    )
    pipeline = Pipeline(
        stages=[
            DeepImageFeaturizer(
                inputCol="image",
                outputCol="features",
                modelName="MobileNetV2",
                modelWeights=variables,
                computeDtype="float32",
            ),
            LogisticRegression(maxIter=100, stepSize=0.5),
        ]
    )
    model = pipeline.fit(labeled)
    scored = model.transform(labeled)
    assert "prediction" in scored.columns and "features" in scored.columns
    # plumbing correctness, not learning quality (random-noise fixtures give
    # near-identical GAP features — the reference's estimator tests assert
    # plumbing the same way, SURVEY.md §4)
    preds = {r["prediction"] for r in scored.collect()}
    assert preds <= {0.0, 1.0}
    acc = MulticlassClassificationEvaluator().evaluate(scored)
    assert 0.0 <= acc <= 1.0


def test_featurizer_missing_imagenet_weights_raises(image_df):
    """Offline with no Keras weight cache: default 'imagenet' weights must
    fail loudly, not silently random-initialize (random features posing as
    imagenet features look valid but are garbage)."""
    from sparkdl_tpu.transformers import named_image
    from sparkdl_tpu.transformers.named_image import DeepImageFeaturizer

    if named_image._imagenet_cache_present("MobileNetV2"):
        pytest.skip("local imagenet cache exists; raise path not reachable")
    featurizer = DeepImageFeaturizer(
        inputCol="image", outputCol="features", modelName="MobileNetV2"
    )
    with pytest.raises(RuntimeError, match="imagenet weights"):
        featurizer.transform(image_df).collect()


def test_featurizer_random_weights_opt_in(image_df):
    """modelWeights='random' is the explicit, deterministic opt-in."""
    from sparkdl_tpu.transformers.named_image import DeepImageFeaturizer

    kwargs = dict(
        inputCol="image",
        outputCol="features",
        modelName="MobileNetV2",
        modelWeights="random",
        computeDtype="float32",
        batchSize=4,
    )
    a = DeepImageFeaturizer(**kwargs).transform(image_df).collect()
    b = DeepImageFeaturizer(**kwargs).transform(image_df).collect()
    va = np.asarray(a[0]["features"])
    assert np.isfinite(va).all() and va.shape == (1280,)
    np.testing.assert_array_equal(va, np.asarray(b[0]["features"]))


def test_tf_transformer_preserves_integer_columns(tpu_session):
    """Integer tensor columns must keep integral dtype through the engine
    (previously cast to float32 silently)."""
    from sparkdl_tpu.graph.function import XlaFunction
    from sparkdl_tpu.transformers.tf_tensor import TFTransformer

    fn = XlaFunction.from_callable(
        lambda x: x * 2, input_names=("ids",), output_names=("doubled",)
    )
    df = tpu_session.createDataFrame(
        [([1, 2, 3],), ([4, 5, 6],)], ["ids"]
    )
    t = TFTransformer(
        tfInputGraph=fn,
        inputMapping={"ids": "ids"},
        outputMapping={"doubled": "out"},
    )
    rows = t.transform(df).collect()
    out = np.asarray(rows[0]["out"])
    assert np.issubdtype(out.dtype, np.integer), out.dtype
    np.testing.assert_array_equal(out, [2, 4, 6])


def test_keras_image_transformer_ragged_loader_raises(
    tpu_session, image_dir, tmp_path
):
    """A loader producing mixed shapes must fail with a named error, not a
    cryptic np.stack failure."""
    import keras

    from sparkdl_tpu.image import imageIO
    from sparkdl_tpu.transformers.keras_image import KerasImageFileTransformer

    model = keras.Sequential(
        [keras.layers.Input((8, 8, 3)), keras.layers.Flatten()]
    )
    path = str(tmp_path / "flat.keras")
    model.save(path)

    sizes = iter([(8, 8), (9, 9), (8, 8), (9, 9), (8, 8), (9, 9), (8, 8)])

    def ragged_loader(uri):
        from PIL import Image

        return np.asarray(
            Image.open(uri).convert("RGB").resize(next(sizes)),
            dtype=np.float32,
        )

    df = imageIO.filesToDF(tpu_session, image_dir, numPartitions=1)
    t = KerasImageFileTransformer(
        inputCol="filePath",
        outputCol="out",
        modelFile=path,
        imageLoader=ragged_loader,
    )
    with pytest.raises(ValueError, match="imageLoader"):
        t.transform(df).collect()


# ---------------------------------------------------------------------------
# LRUCache — eviction order (the process-lifetime program/model caches)
# ---------------------------------------------------------------------------


def test_lru_cache_evicts_least_recently_used():
    from sparkdl_tpu.transformers.utils import LRUCache

    c = LRUCache(maxsize=2)
    c["a"], c["b"] = 1, 2
    _ = c["a"]  # touch: "a" is now most recent
    c["c"] = 3  # evicts "b", not "a"
    assert "a" in c and "c" in c and "b" not in c


def test_lru_cache_setitem_refreshes_recency():
    from sparkdl_tpu.transformers.utils import LRUCache

    c = LRUCache(maxsize=2)
    c["a"], c["b"] = 1, 2
    c["a"] = 10  # overwrite counts as use
    c["c"] = 3
    assert c.get("a") == 10 and "b" not in c
    # iteration runs LRU -> MRU; the get("a") above refreshed "a"
    assert list(c) == ["c", "a"]


def test_lru_cache_eviction_is_fifo_without_touches():
    from sparkdl_tpu.transformers.utils import LRUCache

    c = LRUCache(maxsize=3)
    for i, k in enumerate("abcde"):
        c[k] = i
    assert list(c) == ["c", "d", "e"]  # a then b evicted, in order


# ---------------------------------------------------------------------------
# mixed-shape device resize through the dispatch window
# (regression for the host-sync finding sparkdl_check surfaced:
# _device_resize_timed used to np.asarray each shape group's result
# before dispatching the next, serializing the groups)
# ---------------------------------------------------------------------------


def test_mixed_shape_resize_correct_per_image_through_window():
    from sparkdl_tpu.transformers.utils import device_resize as _resize_images

    rng = np.random.default_rng(7)
    # two distinct source shapes (= _MAX_DEVICE_RESIZE_SHAPES, so the
    # device path runs) plus images already at target size, interleaved
    # so scatter order matters
    shapes = [(8, 6, 3), (4, 4, 3), (6, 8, 3), (8, 6, 3), (4, 4, 3),
              (6, 8, 3), (8, 6, 3)]
    images = [rng.uniform(0, 255, s).astype(np.float32) for s in shapes]

    out = _resize_images(images, (4, 4))
    assert out.shape == (len(images), 4, 4, 3)

    for i, img in enumerate(images):
        if img.shape[:2] == (4, 4):
            want = img
        else:
            want = np.asarray(jax.image.resize(
                jnp.asarray(img)[None], (1, 4, 4, 3), method="bilinear"
            ))[0]
        np.testing.assert_allclose(
            out[i], want, rtol=1e-5, atol=1e-4,
            err_msg=f"row {i} (source shape {img.shape}) scrambled or wrong",
        )


def test_mixed_shape_resize_window_survives_serial_mode(monkeypatch):
    # SPARKDL_SERIAL_INFERENCE=1 collapses the window to depth 0 —
    # results must be identical either way
    from sparkdl_tpu.transformers import utils as tutils

    monkeypatch.setenv("SPARKDL_SERIAL_INFERENCE", "1")
    rng = np.random.default_rng(11)
    images = [rng.uniform(0, 255, (8, 6, 3)).astype(np.float32),
              rng.uniform(0, 255, (6, 8, 3)).astype(np.float32)]
    out = tutils.device_resize(images, (4, 4))
    assert out.shape == (2, 4, 4, 3)
    for i, img in enumerate(images):
        want = np.asarray(jax.image.resize(
            jnp.asarray(img)[None], (1, 4, 4, 3), method="bilinear"
        ))[0]
        np.testing.assert_allclose(out[i], want, rtol=1e-5, atol=1e-4)
