"""Subprocess worker for ``tests/test_multihost.py``.

One process of an N-process multi-host job on the virtual CPU platform:
4 local devices per process, gloo TCP collectives between processes (the
CPU stand-in for ICI/DCN — SURVEY.md §4 "Implication", §5.8).

Phases (``meta.json`` ``"phase"``):
- ``"fit"`` (default): ``KerasImageFileEstimator.fit`` end-to-end — per-host
  data shard loading, global-mesh shard_map step, cross-process gradient psum.
- ``"transform"``: multi-host *inference*, the Spark-executor analog — each
  host transforms only its own row shard (``runner.host_shard_indices``),
  embarrassingly parallel, no collectives in the hot path; the test
  reassembles the shards and compares to a single-process transform.

Usage: ``python multihost_worker.py <pid> <nproc> <port> <workdir>``
"""

import json
import os
import sys


def load_vector(uri):
    import numpy as np

    return np.load(uri)


def main():
    pid, nproc, port, workdir = (
        int(sys.argv[1]),
        int(sys.argv[2]),
        sys.argv[3],
        sys.argv[4],
    )
    os.environ["KERAS_BACKEND"] = "jax"
    import jax

    # the axon sitecustomize may have imported jax already with the TPU
    # platform pinned — force CPU through the live config (see conftest.py)
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_num_cpu_devices", 4)

    from sparkdl_tpu.parallel import runner

    runner.initialize(
        coordinator_address=f"127.0.0.1:{port}",
        num_processes=nproc,
        process_id=pid,
    )
    assert jax.process_count() == nproc, jax.process_count()
    assert jax.device_count() == 4 * nproc, jax.device_count()
    assert runner.is_distributed()

    import numpy as np

    from sparkdl_tpu.estimators import KerasImageFileEstimator
    from sparkdl_tpu.sql.session import TPUSession

    with open(os.path.join(workdir, "meta.json")) as f:
        meta = json.load(f)
    spark = TPUSession.builder.master("local[*]").getOrCreate()

    import logging

    logging.basicConfig(level=logging.INFO, stream=sys.stdout)

    if meta.get("phase") == "transform":
        _transform_phase(pid, workdir, meta, spark, runner)
        return
    if meta.get("phase") == "flax_tp":
        _flax_tp_phase(pid, workdir, meta, spark, runner)
        return

    df = spark.createDataFrame(
        [{"uri": u, "label": [float(l)]} for u, l in meta["rows"]]
    )

    est = KerasImageFileEstimator(
        inputCol="uri",
        outputCol="out",
        labelCol="label",
        imageLoader=load_vector,
        modelFile=os.path.join(workdir, "model.keras"),
        kerasOptimizer="sgd",
        kerasLoss="mse",
        kerasFitParams=meta["fit_params"],
        checkpointDir=meta.get("checkpoint_dir"),
    )
    fitted = est.fit(df)

    import keras

    m = keras.saving.load_model(fitted.getModelFile(), compile=False)
    np.savez(
        os.path.join(workdir, f"weights_proc{pid}.npz"),
        *[np.asarray(w) for w in m.get_weights()],
    )
    runner.barrier("multihost_worker_done")
    print(f"MULTIHOST_WORKER_OK {pid}", flush=True)


def _flax_tp_phase(pid, workdir, meta, spark, runner):
    """Multi-process GSPMD DP x TP: a 2-process global ("data", "model")
    mesh trains a tiny ViT with Megatron sharding rules — the pod-scale
    configuration (VERDICT r3 weak #3a).  Each host loads its own strided
    shard; the global batch assembles from per-host rows; XLA inserts the
    cross-process collectives."""
    import jax
    import numpy as np

    from sparkdl_tpu.estimators import FlaxImageFileEstimator
    from sparkdl_tpu.models.vit import ViT
    from sparkdl_tpu.parallel.tp import VIT_TP_RULES

    rows = meta["rows"]
    df = spark.createDataFrame(
        [{"uri": u, "label": int(l)} for u, l in rows]
    )
    est = FlaxImageFileEstimator(
        inputCol="uri",
        outputCol="out",
        labelCol="label",
        imageLoader=load_vector,
        module=ViT(variant="ViT-Ti/16", num_classes=2,
                   image_size=meta["img"]),
        optimizer="sgd",
        fitParams=meta["fit_params"],
        shardingRules=VIT_TP_RULES,
        meshShape=tuple(meta["mesh_shape"]),
        checkpointDir=meta.get("checkpoint_dir"),
    )
    fitted = est.fit(df)
    leaves = jax.tree_util.tree_leaves_with_path(fitted.variables)
    np.savez(
        os.path.join(workdir, f"flax_tp_proc{pid}.npz"),
        **{jax.tree_util.keystr(p): np.asarray(v) for p, v in leaves},
    )
    runner.barrier("multihost_flax_tp_done")
    print(f"MULTIHOST_WORKER_OK {pid}", flush=True)


def _transform_phase(pid, workdir, meta, spark, runner):
    """Per-host-shard batch inference: the reference's executors-each-run-
    their-partitions flow (SURVEY.md §3.1), one host per shard."""
    import numpy as np

    from sparkdl_tpu.transformers.keras_image import KerasImageFileTransformer

    rows = meta["rows"]
    shard = runner.host_shard_indices(len(rows))
    df = spark.createDataFrame([{"uri": rows[i][0]} for i in shard])
    t = KerasImageFileTransformer(
        inputCol="uri",
        outputCol="out",
        modelFile=os.path.join(workdir, "model.keras"),
        imageLoader=load_vector,
    )
    got = t.transform(df).collect()
    np.savez(
        os.path.join(workdir, f"transform_proc{pid}.npz"),
        indices=np.asarray(shard),
        outputs=np.stack([np.asarray(r.out.toArray()) for r in got]),
    )
    runner.barrier("multihost_transform_done")
    print(f"MULTIHOST_WORKER_OK {pid}", flush=True)


if __name__ == "__main__":
    main()
