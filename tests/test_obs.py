"""Observability subsystem (``sparkdl_tpu/obs``): span nesting,
explicit cross-thread propagation through the data pipeline, serving
batch fan-in, resilience span events, and both exporters.

Acceptance shape (ISSUE): nesting/ids/attributes; propagation through
``prefetch`` survives the queue boundary; each coalesced serving batch
records its member request span ids; every ``RetryPolicy`` attempt and
``CircuitBreaker`` flip becomes a span event; Prometheus text renders
p50/p95/p99 from the sliding-window histograms; the JSONL sink's buffer
is bounded (drop-oldest + counted).
"""

import json
import threading

import numpy as np
import pytest

from sparkdl_tpu.data import Dataset
from sparkdl_tpu.obs import (
    FitProfiler,
    JsonlTraceSink,
    current_span,
    fit_profiler,
    prometheus_text,
    record_event,
    tracer,
)
from sparkdl_tpu.resilience import CircuitBreaker, RetryPolicy, TransientError
from sparkdl_tpu.utils.metrics import metrics


@pytest.fixture(autouse=True)
def tracing_off_between_tests():
    """Every test starts and ends at the pay-nothing default."""
    tracer.disable()
    metrics.reset()
    yield
    tracer.disable()
    metrics.reset()


def enabled_sink(capacity=4096):
    sink = JsonlTraceSink(capacity=capacity)
    tracer.enable(sink)
    return sink


# ----------------------------------------------------------------------
# span model
# ----------------------------------------------------------------------
class TestSpanModel:
    def test_nesting_ids_and_attributes(self):
        sink = enabled_sink()
        with tracer.span("root", job="fit") as root:
            with tracer.span("child") as child:
                child.event("tick", n=1)
            assert current_span() is root
        assert current_span() is None

        r, = sink.find("root")
        c, = sink.find("child")
        assert r["parent_id"] is None
        assert c["parent_id"] == r["span_id"]
        assert c["trace_id"] == r["trace_id"]
        assert r["attributes"] == {"job": "fit"}
        assert c["events"][0]["name"] == "tick"
        assert c["events"][0]["n"] == 1
        assert 0.0 <= c["events"][0]["offset_ms"] <= c["duration_ms"]
        # child finishes (and is delivered) before its parent
        assert sink.spans()[0]["name"] == "child"
        assert r["duration_ms"] >= c["duration_ms"] >= 0.0

    def test_sibling_roots_get_distinct_traces(self):
        sink = enabled_sink()
        with tracer.span("a"):
            pass
        with tracer.span("b"):
            pass
        a, = sink.find("a")
        b, = sink.find("b")
        assert a["trace_id"] != b["trace_id"]
        assert a["span_id"] != b["span_id"]

    def test_manual_spans_and_double_end(self):
        sink = enabled_sink()
        sp = tracer.start_span("request", model_id="m")
        sp.set_attribute("bucket", 4)
        sp.end()
        first = sink.find("request")[0]["duration_ms"]
        sp.end()  # idempotent: no second delivery, same timestamp
        assert len(sink.find("request")) == 1
        # export rounds to 4 decimals; the live value must match it
        assert sp.duration_ms == pytest.approx(first, abs=1e-4)
        assert sink.find("request")[0]["attributes"] == {
            "model_id": "m", "bucket": 4,
        }

    def test_disabled_is_a_no_op(self):
        assert not tracer.enabled
        with tracer.span("nope", k=1) as sp:
            assert sp is None
            assert current_span() is None
        assert tracer.start_span("nope") is None
        assert tracer.capture() is None
        record_event("nothing")  # must not raise with no span either

    def test_record_event_without_open_span_is_dropped(self):
        sink = enabled_sink()
        record_event("orphan")  # enabled, but no current span
        with tracer.span("s"):
            record_event("kept", x=2)
        s, = sink.find("s")
        assert [e["name"] for e in s["events"]] == ["kept"]

    def test_sink_exceptions_do_not_break_traced_code(self):
        def bad_sink(span_dict):
            raise RuntimeError("sink died")

        tracer.enable(bad_sink)
        with tracer.span("still_fine"):
            pass  # must not raise


# ----------------------------------------------------------------------
# explicit cross-thread propagation (data pipeline)
# ----------------------------------------------------------------------
class TestCrossThreadPropagation:
    def test_contextvar_does_not_leak_into_new_threads(self):
        enabled_sink()
        seen = []
        with tracer.span("outer"):
            t = threading.Thread(target=lambda: seen.append(tracer.current()))
            t.start()
            t.join()
        assert seen == [None]  # propagation is opt-in, never ambient

    def test_capture_use_span_crosses_a_thread(self):
        enabled_sink()
        seen = []
        with tracer.span("outer") as outer:
            handle = tracer.capture()

            def worker():
                with tracer.use_span(handle):
                    seen.append(tracer.current())
                seen.append(tracer.current())

            t = threading.Thread(target=worker)
            t.start()
            t.join()
        assert seen == [outer, None]
        assert not outer.ended or outer.ended  # use_span never ends it

    def test_prefetch_worker_sees_the_submitting_span(self):
        """The prefetch producer thread re-attaches the span captured
        when iteration began — events recorded inside map/decode land on
        the consumer's span across the queue boundary."""
        enabled_sink()

        def decode(x):
            record_event("decode", item=int(x))
            return x * 2

        with tracer.span("epoch") as epoch:
            ds = Dataset.from_arrays(np.arange(6)).map(decode).prefetch(2)
            assert sorted(int(v) for v in ds) == [0, 2, 4, 6, 8, 10]
        assert len(epoch.events) == 6
        assert {e["name"] for e in epoch.events} == {"decode"}

    def test_threaded_map_workers_see_the_submitting_span(self):
        enabled_sink()

        def decode(x):
            record_event("decode", item=int(x))
            return x + 1

        with tracer.span("epoch") as epoch:
            ds = Dataset.from_arrays(np.arange(8)).map(decode, num_workers=3)
            assert sorted(int(v) for v in ds) == list(range(1, 9))
        assert len(epoch.events) == 8

    def test_pipeline_untraced_when_disabled(self):
        out = list(
            Dataset.from_arrays(np.arange(4)).map(lambda x: x).prefetch(2)
        )
        assert len(out) == 4


# ----------------------------------------------------------------------
# resilience span events
# ----------------------------------------------------------------------
class TestResilienceEvents:
    def test_retry_attempts_become_span_events(self):
        enabled_sink()
        policy = RetryPolicy(max_attempts=3, base_delay_s=0.01,
                             sleep=lambda s: None)
        calls = {"n": 0}

        def flaky():
            calls["n"] += 1
            if calls["n"] < 3:
                raise TransientError("transient")
            return "ok"

        with tracer.span("step") as step:
            assert policy.call(flaky) == "ok"
        retries = [e for e in step.events if e["name"] == "retry"]
        assert [e["attempt"] for e in retries] == [1, 2]
        assert all(e["error"] == "TransientError" for e in retries)
        assert all(e["delay_s"] >= 0.0 for e in retries)

    def test_breaker_state_changes_become_span_events(self):
        enabled_sink()
        breaker = CircuitBreaker("dep", failure_threshold=2, recovery_s=60.0)

        def boom():
            raise TransientError("down")

        with tracer.span("request") as req:
            for _ in range(2):
                with pytest.raises(TransientError):
                    breaker.call(boom)
        flips = [e for e in req.events if e["name"] == "breaker_state"]
        assert len(flips) == 1
        assert flips[0]["breaker"] == "dep"
        assert flips[0]["state"] == "open"
        assert flips[0]["from_state"] == "closed"

    def test_resilience_works_untraced(self):
        policy = RetryPolicy(max_attempts=2, sleep=lambda s: None)
        calls = {"n": 0}

        def once():
            calls["n"] += 1
            if calls["n"] == 1:
                raise TransientError("x")
            return 7

        assert policy.call(once) == 7


# ----------------------------------------------------------------------
# serving fan-in
# ----------------------------------------------------------------------
class TestServingFanIn:
    def make_server(self):
        from sparkdl_tpu.serving import ModelServer, ServingConfig

        server = ModelServer(
            ServingConfig(max_batch=8, max_wait_ms=25.0, queue_capacity=64)
        )
        server.register(
            "double", lambda x: x * 2.0, item_shape=(4,), compile=False
        )
        return server

    def test_batch_span_records_member_request_spans(self):
        sink = enabled_sink()
        n = 6
        with self.make_server() as server:
            barrier = threading.Barrier(n)
            results = [None] * n

            def one(i):
                barrier.wait()
                results[i] = server.predict(
                    np.full((4,), float(i), np.float32), timeout=30.0
                )

            threads = [
                threading.Thread(target=one, args=(i,)) for i in range(n)
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
        for i in range(n):
            np.testing.assert_allclose(results[i], 2.0 * i)

        requests = sink.find("serving.request")
        batches = sink.find("serving.batch")
        assert len(requests) == n
        assert batches, "no serving.batch span captured"
        # fan-in bookkeeping is exact in both directions: every request
        # span id appears in exactly one batch's member list, and every
        # request carries a 'coalesced' event naming its batch span
        member_ids = [
            sid for b in batches for sid in b["attributes"]["member_span_ids"]
        ]
        assert sorted(member_ids) == sorted(r["span_id"] for r in requests)
        assert sum(b["attributes"]["n_real"] for b in batches) == n
        batch_ids = {b["span_id"] for b in batches}
        for r in requests:
            coalesced = [
                e for e in r["events"] if e["name"] == "coalesced"
            ]
            assert len(coalesced) == 1
            assert coalesced[0]["batch_span"] in batch_ids

    def test_request_span_records_error(self):
        sink = enabled_sink()
        from sparkdl_tpu.serving import ModelServer, ServingConfig

        def blow_up(x):
            raise ValueError("bad model")

        with ModelServer(ServingConfig(max_wait_ms=1.0)) as server:
            server.register("bad", blow_up, item_shape=(4,), compile=False)
            fut = server.submit(np.ones((4,), np.float32))
            with pytest.raises(Exception):
                fut.result(30.0)
        r, = sink.find("serving.request")
        assert r["duration_ms"] is not None
        assert "error" in r["attributes"]

    def test_serving_untraced_when_disabled(self):
        with self.make_server() as server:
            np.testing.assert_allclose(
                server.predict(np.ones((4,), np.float32), timeout=30.0), 2.0
            )

    def test_server_metrics_text_endpoint(self):
        with self.make_server() as server:
            server.predict(np.ones((4,), np.float32), timeout=30.0)
            text = server.metrics_text(serving_only=True)
        assert "# TYPE serving_requests counter" in text
        assert "serving_requests 1" in text
        assert 'serving_latency_ms{quantile="0.5"}' in text
        assert "sparkdl_" not in text  # serving_only filters other subsystems


# ----------------------------------------------------------------------
# exporters
# ----------------------------------------------------------------------
class TestJsonlSink:
    def test_buffer_is_bounded_drop_oldest(self):
        sink = enabled_sink(capacity=4)
        for i in range(10):
            with tracer.span(f"s{i}"):
                pass
        assert len(sink) == 4
        assert sink.emitted == 10
        assert sink.dropped == 6
        assert [s["name"] for s in sink.spans()] == ["s6", "s7", "s8", "s9"]

    def test_flush_appends_parseable_jsonl(self, tmp_path):
        path = tmp_path / "trace.jsonl"
        sink = JsonlTraceSink(path=str(path), capacity=16)
        tracer.enable(sink)
        with tracer.span("first", k=1):
            pass
        assert sink.flush() == 1
        assert len(sink) == 0  # flush drains
        with tracer.span("second"):
            pass
        assert sink.flush() == 1  # append mode: first survives
        lines = path.read_text().splitlines()
        assert [json.loads(ln)["name"] for ln in lines] == ["first", "second"]
        parsed = json.loads(lines[0])
        assert parsed["attributes"] == {"k": 1}
        assert parsed["duration_ms"] >= 0.0
        assert sink.flush() == 0  # empty buffer writes nothing

    def test_flush_without_path_raises(self):
        sink = JsonlTraceSink()
        sink({"name": "x"})
        with pytest.raises(ValueError):
            sink.flush()

    def test_invalid_capacity_rejected(self):
        with pytest.raises(ValueError):
            JsonlTraceSink(capacity=0)


class TestPrometheusText:
    def test_all_metric_kinds_render(self):
        metrics.counter("serving.requests").add(3)
        metrics.gauge("data.queue_depth").set(2)
        metrics.timer("estimator.step").add_seconds(0.25)
        h = metrics.histogram("serving.latency_ms")
        for v in (1.0, 2.0, 3.0, 4.0):
            h.observe(v)
        text = prometheus_text(metrics)
        assert "# TYPE serving_requests counter\nserving_requests 3" in text
        assert "# TYPE data_queue_depth gauge\ndata_queue_depth 2" in text
        assert "estimator_step_seconds_total 0.25" in text
        assert "estimator_step_entries_total 1" in text
        assert "# TYPE serving_latency_ms summary" in text
        assert 'serving_latency_ms{quantile="0.5"} 2.5' in text
        assert 'serving_latency_ms{quantile="0.95"}' in text
        assert 'serving_latency_ms{quantile="0.99"}' in text
        assert "serving_latency_ms_sum 10" in text
        assert "serving_latency_ms_count 4" in text
        assert text.endswith("\n")

    def test_prefix_filter_and_empty_registry(self):
        metrics.counter("serving.requests").add()
        metrics.counter("data.rows_out").add()
        only = prometheus_text(metrics, prefix="serving.")
        assert "serving_requests" in only and "data_rows_out" not in only
        metrics.reset()
        assert prometheus_text(metrics) == ""

    def test_snapshot_prefix_filter(self):
        metrics.counter("serving.requests").add(2)
        metrics.counter("data.rows_out").add(5)
        snap = metrics.snapshot(prefix="serving.")
        assert snap == {"serving.requests": 2.0}

    def test_histogram_exemplar_rendered_as_comment(self):
        h = metrics.histogram("serving.latency_ms")
        h.observe(2.0, exemplar=111)
        h.observe(9.0, exemplar=42)
        text = prometheus_text(metrics)
        # parse-safe comment form, not OpenMetrics mid-line syntax —
        # plain-Prometheus scrapers must keep parsing the exposition
        assert "# EXEMPLAR serving_latency_ms trace_id=42 value=9" \
            in text
        # exemplar-free histograms render no EXEMPLAR line
        metrics.reset()
        metrics.histogram("serving.latency_ms").observe(2.0)
        assert "EXEMPLAR" not in prometheus_text(metrics)


# ----------------------------------------------------------------------
# fit profiler
# ----------------------------------------------------------------------
class TestFitProfiler:
    def test_steps_epochs_checkpoints_metered_and_spanned(self):
        sink = enabled_sink()
        with fit_profiler("TestEstimator", epochs=2,
                          steps_per_epoch=3) as prof:
            assert isinstance(prof, FitProfiler)
            for epoch in range(1, 3):
                for _ in range(3):
                    with prof.step():
                        pass
                prof.epoch(epoch, loss=0.5)
                with prof.checkpoint(epoch=epoch):
                    pass

        snap = metrics.snapshot(prefix="estimator.")
        assert snap["estimator.step_ms.count"] == 6
        assert snap["estimator.checkpoint_ms.count"] == 2
        assert snap["estimator.host_stall_ms.count"] == 2
        assert snap["estimator.step.seconds"] >= 0.0

        fit, = sink.find("estimator.fit")
        assert fit["attributes"]["estimator"] == "TestEstimator"
        assert fit["attributes"]["epochs"] == 2
        epochs = [e for e in fit["events"] if e["name"] == "epoch"]
        assert [e["epoch"] for e in epochs] == [1, 2]
        assert all(e["loss"] == 0.5 for e in epochs)
        assert all("host_stall_ms" in e for e in epochs)
        steps = sink.find("estimator.step")
        assert len(steps) == 6
        assert all(s["parent_id"] == fit["span_id"] for s in steps)
        assert len(sink.find("estimator.checkpoint")) == 2

    def test_epoch_stall_attribution_is_a_delta(self):
        """Pre-fit pipeline stall must not be billed to the fit."""
        enabled_sink()
        metrics.histogram("data.device_stall_ms").observe(500.0)
        with fit_profiler("E") as prof:
            metrics.histogram("data.device_stall_ms").observe(40.0)
            prof.epoch(1)
            prof.epoch(2)  # nothing new since epoch 1
        h = metrics.histogram("estimator.host_stall_ms")
        assert h.count == 2
        assert h.quantile(1.0) == pytest.approx(40.0)
        assert h.quantile(0.0) == pytest.approx(0.0)

    def test_profiler_works_untraced(self):
        with fit_profiler("E") as prof:
            with prof.step():
                pass
            prof.epoch(1)
        assert metrics.histogram("estimator.step_ms").count == 1


# ----------------------------------------------------------------------
# env auto-enable
# ----------------------------------------------------------------------
def test_env_hook_captures_from_a_fresh_process(tmp_path):
    """SPARKDL_TRACE_OUT=<path> wires the tracer with zero code changes
    (what ci/fault-suite.sh and subprocess workers rely on)."""
    import os
    import subprocess
    import sys

    path = tmp_path / "env_trace.jsonl"
    code = (
        "import sparkdl_tpu\n"
        "from sparkdl_tpu.obs import tracer\n"
        "assert tracer.enabled\n"
        "with tracer.span('env_root', pid=1):\n"
        "    pass\n"
    )
    env = dict(os.environ, SPARKDL_TRACE_OUT=str(path),
               JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, "-c", code], env=env,
        capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 0, proc.stderr
    spans = [json.loads(ln) for ln in path.read_text().splitlines()]
    assert [s["name"] for s in spans] == ["env_root"]
    assert spans[0]["attributes"] == {"pid": 1}


# ----------------------------------------------------------------------
# tail-aware sampling (PR 8)
# ----------------------------------------------------------------------
class TestTailSampling:
    def test_rate_zero_drops_healthy_spans_and_counts(self):
        sink = enabled_sink()
        tracer.configure_sampling(0.0)
        for _ in range(5):
            with tracer.span("healthy"):
                pass
        assert sink.spans() == []
        assert metrics.snapshot()["sparkdl.spans_sampled_out"] == 5

    def test_error_spans_always_kept(self):
        sink = enabled_sink()
        tracer.configure_sampling(0.0)
        with tracer.span("failing") as sp:
            sp.set_attribute("error_class", "TransientError")
        assert [s["name"] for s in sink.spans()] == ["failing"]

    def test_slow_spans_always_kept(self):
        sink = enabled_sink()
        # slow_ms=0: every finished span qualifies as slow -> all kept
        # even at rate 0 (no sleeps needed to exercise the gate)
        tracer.configure_sampling(0.0, slow_ms=0.0)
        with tracer.span("slow"):
            pass
        assert [s["name"] for s in sink.spans()] == ["slow"]

    def test_decision_is_per_trace_not_per_span(self):
        sink = enabled_sink()
        tracer.configure_sampling(0.5)
        verdicts = []
        for _ in range(32):
            before = len(sink.spans())
            with tracer.span("root"):
                with tracer.span("child"):
                    pass
            kept = len(sink.spans()) - before
            assert kept in (0, 2)  # whole trace or nothing
            verdicts.append(kept)
        assert 0 in verdicts and 2 in verdicts  # both outcomes occur

    def test_rate_one_keeps_everything(self):
        sink = enabled_sink()
        tracer.configure_sampling(1.0)
        with tracer.span("kept"):
            pass
        assert len(sink.spans()) == 1
        assert "sparkdl.spans_sampled_out" not in metrics.snapshot()

    def test_disable_resets_sampling(self):
        tracer.configure_sampling(0.0)
        tracer.disable()
        sink = enabled_sink()
        with tracer.span("after_reset"):
            pass
        assert len(sink.spans()) == 1

    def test_validation(self):
        with pytest.raises(ValueError):
            tracer.configure_sampling(1.5)
        with pytest.raises(ValueError):
            tracer.configure_sampling(0.5, slow_ms=-1)

    def test_remove_sink(self):
        sink = enabled_sink()
        tracer.remove_sink(sink)
        with tracer.span("unseen"):
            pass
        assert sink.spans() == []
        tracer.remove_sink(sink)  # idempotent

    def test_env_arming(self, monkeypatch):
        from sparkdl_tpu import obs

        monkeypatch.setenv(obs.ENV_SAMPLE, "0.25")
        monkeypatch.setenv(obs.ENV_SLOW_MS, "500")
        monkeypatch.delenv(obs.ENV_VAR, raising=False)
        obs.enable_from_env()
        assert tracer._sample_rate == 0.25
        assert tracer._sample_slow_ms == 500.0


# ----------------------------------------------------------------------
# exposition format details (PR 8)
# ----------------------------------------------------------------------
class TestPrometheusHelpAndEscaping:
    def test_help_precedes_type_for_every_family(self):
        metrics.counter("serving.requests").add(1)
        metrics.gauge("data.queue_depth").set(2)
        metrics.timer("estimator.step").add_seconds(0.1)
        metrics.histogram("serving.latency_ms").observe(1.0)
        text = prometheus_text(metrics)
        assert ("# HELP serving_requests counter serving.requests\n"
                "# TYPE serving_requests counter") in text
        assert ("# HELP data_queue_depth gauge data.queue_depth\n"
                "# TYPE data_queue_depth gauge") in text
        assert "# HELP estimator_step_seconds_total " in text
        assert "# HELP estimator_step_entries_total " in text
        assert ("# HELP serving_latency_ms histogram serving.latency_ms\n"
                "# TYPE serving_latency_ms summary") in text
        # every TYPE line is immediately preceded by its HELP line
        lines = text.splitlines()
        for i, line in enumerate(lines):
            if line.startswith("# TYPE "):
                family = line.split()[2]
                assert lines[i - 1].startswith(f"# HELP {family} ")

    def test_label_value_escaping(self):
        from sparkdl_tpu.obs.export import _escape_help, _escape_label_value

        assert _escape_label_value('a"b') == 'a\\"b'
        assert _escape_label_value("a\\b") == "a\\\\b"
        assert _escape_label_value("a\nb") == "a\\nb"
        assert _escape_help("a\nb\\c") == "a\\nb\\\\c"

    def test_quantile_labels_still_byte_stable(self):
        h = metrics.histogram("serving.latency_ms")
        for v in (1.0, 2.0, 3.0, 4.0):
            h.observe(v)
        text = prometheus_text(metrics)
        assert 'serving_latency_ms{quantile="0.5"} 2.5' in text
