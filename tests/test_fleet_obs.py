"""Fleet-wide observability (ISSUE-13): cross-process trace
propagation over the SDW2 wire, supervisor-side metrics federation,
and the federated canary signal.

Acceptance shape:

- **stitched traces across the lane matrix** — with tracing on, one
  ``router.route`` produces a ``router.request`` root AND a
  ``replica.serve`` child that crossed a real socket (TCP lane, shm
  ring lane, shm big-frame spill, coalesced micro-batch), sharing one
  ``trace_id``; a request whose replica is gone still ends its root
  span with an ``error`` attribute (no dangling parent).  The
  mid-request SIGKILL variant runs in ``benchmarks/bench_load.py
  --smoke`` (FaultPlan ``supervisor.replica_serve``), which asserts
  stitched traces survive the kill.
- **ids** — span/trace ids are random 63-bit odd per process,
  deterministic under ``SPARKDL_TRACE_SEED``.
- **federation** — :class:`FleetCollector` scrape semantics (labels,
  sum-vs-max version aggregation, prefix filter, failure bookkeeping,
  target forgetting, the labeled Prometheus block) plus one real-HTTP
  roundtrip against an ObsServer; :meth:`TimeSeriesRecorder.record` is
  the injection seam.
- **federated canary** — the ISSUE-13 headline: a canary whose
  failures the router's retry loop masks (router-side ``rollout.v2.*``
  stays ok) still pages on its OWN scraped series, and the
  :class:`RolloutController` default watch picks exactly that
  ``fleet.rollout.v2.*`` breach.
"""

import threading
import time

import numpy as np
import pytest

from sparkdl_tpu.obs import JsonlTraceSink, ObsServer, TimeSeriesRecorder
from sparkdl_tpu.obs.fleet import FleetCollector, sanitize_label
from sparkdl_tpu.obs.slo import SLOEngine, fleet_rollout_slos, rollout_slos
from sparkdl_tpu.obs.trace import _IdSource, tracer
from sparkdl_tpu.serving import ModelServer, ServingConfig
from sparkdl_tpu.serving.replica import ReplicaService
from sparkdl_tpu.serving.rollout import RolloutController
from sparkdl_tpu.serving.router import Router
from sparkdl_tpu.utils.metrics import MetricsRegistry, metrics


@pytest.fixture(autouse=True)
def tracing_off_between_tests():
    """Every test starts and ends at the pay-nothing default."""
    tracer.disable()
    metrics.reset()
    yield
    tracer.disable()
    metrics.reset()


def enabled_sink(capacity=4096):
    sink = JsonlTraceSink(capacity=capacity)
    tracer.enable(sink)
    return sink


def plain_service(max_wait_ms=1.0, big_shape=None):
    """An in-process ReplicaService around a compile=False ModelServer
    with endpoint ``ep0`` of shape (4,); ``big_shape`` registers a
    second endpoint ``big`` (spill tests need frames larger than the
    shm ring)."""
    server = ModelServer(ServingConfig(
        max_batch=8, max_wait_ms=max_wait_ms, queue_capacity=64,
    ))
    server.register(
        "ep0", lambda x: np.asarray(x) * 2.0, item_shape=(4,),
        compile=False,
    )
    if big_shape is not None:
        server.register(
            "big", lambda x: np.asarray(x) * 2.0, item_shape=big_shape,
            compile=False,
        )
    return ReplicaService(server).start()


def assert_stitched(sink, n_roots=1):
    """Every ``router.request`` root has a ``replica.serve`` child in
    the SAME trace whose ``parent_id`` is the root's span id — the
    cross-process stitch.  Returns (roots, serves)."""
    roots = sink.find("router.request")
    serves = sink.find("replica.serve")
    assert len(roots) >= n_roots, f"got {len(roots)} roots, want {n_roots}"
    for root in roots:
        assert root["parent_id"] is None
        kids = [
            s for s in serves
            if s["trace_id"] == root["trace_id"]
            and s["parent_id"] == root["span_id"]
        ]
        assert kids, (
            f"router.request trace {root['trace_id']} has no stitched "
            "replica.serve child"
        )
    return roots, serves


# ----------------------------------------------------------------------
# cross-process trace propagation, per lane
# ----------------------------------------------------------------------
class TestStitchedTraces:
    def test_tcp_lane_stitches_parent_child(self, monkeypatch):
        monkeypatch.setenv("SPARKDL_WIRE_TRANSPORT", "tcp")
        sink = enabled_sink()
        svc = plain_service()
        with Router() as router:
            router.add("r0", "127.0.0.1", svc.port)
            try:
                assert router.lanes()["r0"] == "tcp"
                out = router.route(np.ones(4, np.float32), model_id="ep0")
                np.testing.assert_allclose(np.asarray(out), 2.0)
            finally:
                svc.close()
        roots, serves = assert_stitched(sink)
        # the reply envelope does NOT leak the piggybacked spans to the
        # caller — the router pops them into its own tracer
        assert roots[-1]["attributes"].get("replica") == "r0"

    def test_shm_ring_lane_stitches(self, monkeypatch):
        monkeypatch.setenv("SPARKDL_WIRE_TRANSPORT", "shm")
        sink = enabled_sink()
        svc = plain_service()
        with Router() as router:
            router.add("r0", "127.0.0.1", svc.port, lanes=svc.lanes)
            try:
                assert router.lanes()["r0"] == "shm"
                router.route(np.ones(4, np.float32), model_id="ep0")
            finally:
                svc.close()
        assert_stitched(sink)

    def test_shm_spill_lane_stitches(self, monkeypatch):
        # a frame bigger than the default 1 MiB ring must spill onto
        # the TCP side-channel — and the trace context rides the spill
        monkeypatch.setenv("SPARKDL_WIRE_TRANSPORT", "shm")
        sink = enabled_sink()
        svc = plain_service(big_shape=(300_000,))
        with Router() as router:
            router.add("r0", "127.0.0.1", svc.port, lanes=svc.lanes)
            try:
                assert router.lanes()["r0"] == "shm"
                before = metrics.counter("wire.shm.spill").value
                out = router.route(
                    np.ones(300_000, np.float32), model_id="big",
                )
                assert np.asarray(out).shape == (300_000,)
                assert metrics.counter("wire.shm.spill").value > before
            finally:
                svc.close()
        assert_stitched(sink)

    def test_coalesced_batch_keeps_per_request_traces(self):
        # several concurrent requests coalesce into one device batch;
        # each still gets its OWN stitched trace, and the batch span
        # records the member request span ids (the fan-in edge)
        sink = enabled_sink()
        svc = plain_service(max_wait_ms=200.0)
        n = 4
        with Router() as router:
            router.add("r0", "127.0.0.1", svc.port)
            try:
                errs = []
                barrier = threading.Barrier(n)

                def one():
                    try:
                        barrier.wait(timeout=10)
                        router.route(
                            np.ones(4, np.float32), model_id="ep0",
                        )
                    except Exception as exc:  # noqa: BLE001
                        errs.append(exc)

                threads = [
                    threading.Thread(target=one, daemon=True)
                    for _ in range(n)
                ]
                for t in threads:
                    t.start()
                for t in threads:
                    t.join(timeout=30)
                assert not errs
            finally:
                svc.close()
        roots, _ = assert_stitched(sink, n_roots=n)
        assert len({r["trace_id"] for r in roots}) == n  # distinct traces
        batches = sink.find("serving.batch")
        assert any(
            len(b["attributes"].get("member_span_ids") or []) >= 2
            for b in batches
        ), "no coalesced batch recorded >= 2 member spans"

    def test_dead_replica_terminates_root_with_error(self):
        # the replica is gone before the request: the root span must
        # still END, error-attributed — never a dangling parent whose
        # children can't be found
        sink = enabled_sink()
        svc = plain_service()
        with Router() as router:
            router.add("r0", "127.0.0.1", svc.port)
            svc.close()  # port now refuses connections
            with pytest.raises(Exception):
                router.route(np.ones(4, np.float32), model_id="ep0")
        roots = sink.find("router.request")
        assert roots, "root span never reached the sink (dangled)"
        assert roots[-1]["attributes"].get("error"), (
            "terminated request's root span carries no error attribute"
        )
        dead_trace = roots[-1]["trace_id"]
        assert not [
            s for s in sink.find("replica.serve")
            if s["trace_id"] == dead_trace
        ], "a replica span appeared for a request that never served"


class TestTraceIds:
    def test_ids_are_63_bit_odd_and_collision_free(self):
        src = _IdSource()
        ids = [src.next_id() for _ in range(4096)]
        assert len(set(ids)) == len(ids)
        assert all(0 < i < 2 ** 63 and i & 1 for i in ids)

    def test_seeded_ids_are_deterministic_per_process(self, monkeypatch):
        monkeypatch.setenv("SPARKDL_TRACE_SEED", "42")
        a = [_IdSource().next_id() for _ in range(16)]
        b = [_IdSource().next_id() for _ in range(16)]
        assert a == b
        monkeypatch.setenv("SPARKDL_TRACE_SEED", "43")
        c = [_IdSource().next_id() for _ in range(16)]
        assert c != a


# ----------------------------------------------------------------------
# recorder injection seam
# ----------------------------------------------------------------------
class TestRecorderRecord:
    def test_record_injects_points(self):
        rec = TimeSeriesRecorder(interval_s=60.0)
        assert rec.record("fleet.x", 1.0, now=10.0)
        assert rec.record("fleet.x", 3.0, now=20.0)
        assert [v for _, v in rec.points("fleet.x")] == [1.0, 3.0]
        assert rec.latest("fleet.x") == 3.0

    def test_record_respects_series_cap(self):
        rec = TimeSeriesRecorder(interval_s=60.0, max_series=1)
        assert rec.record("fleet.a", 1.0, now=1.0)
        assert not rec.record("fleet.b", 1.0, now=1.0)  # capped, dropped
        assert rec.latest("fleet.b") is None
        assert rec.record("fleet.a", 2.0, now=2.0)  # existing still fine


# ----------------------------------------------------------------------
# fleet collector
# ----------------------------------------------------------------------
def collector(targets, snaps, recorder=None):
    """A FleetCollector over synthetic targets whose ``_fetch`` serves
    canned ``/metrics.json`` payloads (raises for unknown urls — the
    failure path)."""
    rec = recorder or TimeSeriesRecorder(interval_s=60.0)
    fc = FleetCollector(
        rec, lambda: list(targets), registry=MetricsRegistry(),
    )
    fc._fetch = lambda url: dict(snaps[url])
    return fc, rec


class TestFleetCollector:
    TARGETS = [
        {"name": "replica-0", "version": "v2", "url": "http://a"},
        {"name": "replica-1", "version": "v2", "url": "http://b"},
    ]
    SNAPS = {
        "http://a": {
            "serving.requests": 5.0,
            "serving.latency_ms.p99": 10.0,
            "sparkdl.up": 1.0,
            "router.not_federated": 9.0,  # outside the prefix filter
            "serving.note": "not-a-number",
        },
        "http://b": {
            "serving.requests": 7.0,
            "serving.latency_ms.p99": 30.0,
        },
    }

    def test_scrape_federates_labeled_and_aggregated(self):
        fc, rec = collector(self.TARGETS, self.SNAPS)
        assert fc.scrape_once(now=5.0) == 2
        # per-replica ground truth, labels sanitized into segments
        assert rec.latest(
            "fleet.replica.replica_0.serving.requests"
        ) == 5.0
        assert rec.latest(
            "fleet.replica.replica_1.serving.latency_ms.p99"
        ) == 30.0
        # per-version: counters sum, quantiles max
        assert rec.latest("fleet.version.v2.serving.requests") == 12.0
        assert rec.latest(
            "fleet.version.v2.serving.latency_ms.p99"
        ) == 30.0
        # the prefix filter keeps foreign subsystems out of the caps
        assert rec.latest(
            "fleet.replica.replica_0.router.not_federated"
        ) is None
        snap = fc.snapshot()
        assert (snap["healthy"], snap["total"]) == (2, 2)

    def test_failed_target_is_bookkept_not_fatal(self):
        targets = list(self.TARGETS) + [
            {"name": "replica-9", "version": "v2", "url": "http://gone"},
        ]
        fc, rec = collector(targets, self.SNAPS)
        assert fc.scrape_once(now=1.0) == 2  # the bad target absorbed
        assert fc.scrape_once(now=2.0) == 2
        snap = fc.snapshot()
        assert (snap["healthy"], snap["total"]) == (2, 3)
        bad = snap["targets"]["replica-9"]
        assert bad["ok"] is False
        assert bad["consecutive_errors"] == 2
        assert rec.latest("fleet.replica.replica_9.sparkdl.up") is None

    def test_departed_target_is_forgotten(self):
        targets = list(self.TARGETS)
        fc, _ = collector(targets, self.SNAPS)
        fc.scrape_once(now=1.0)
        del targets[1]  # replica-1 retired
        fc.scrape_once(now=2.0)
        assert sorted(fc.snapshot()["targets"]) == ["replica-0"]

    def test_exemplar_samples_skipped(self):
        """Exemplar trace ids are links, not gauges — summing them
        across replicas (or maxing a trace id) is meaningless, so the
        federation skips the ``.exemplar_*`` snapshot keys."""
        snaps = {
            "http://a": {
                "serving.requests": 5.0,
                "serving.latency_ms.p99": 10.0,
                "serving.latency_ms.exemplar_value": 10.0,
                "serving.latency_ms.exemplar_trace_id": 12345,
            },
            "http://b": dict(self.SNAPS["http://b"]),
        }
        fc, rec = collector(self.TARGETS, snaps)
        assert fc.scrape_once(now=1.0) == 2
        assert rec.latest(
            "fleet.replica.replica_0.serving.latency_ms.p99"
        ) == 10.0
        assert rec.latest(
            "fleet.replica.replica_0.serving.latency_ms"
            ".exemplar_trace_id"
        ) is None
        assert rec.latest(
            "fleet.version.v2.serving.latency_ms.exemplar_value"
        ) is None

    def test_prometheus_block_carries_labels(self):
        fc, _ = collector(self.TARGETS, self.SNAPS)
        fc.scrape_once(now=1.0)
        block = fc.prometheus_block()
        assert 'replica="replica-0",version="v2"' in block
        assert "serving_requests" in block.replace(".", "_")

    def test_real_http_roundtrip_against_obs_server(self):
        # one end-to-end pass over a real socket: ObsServer serves its
        # registry's /metrics.json, the collector federates it
        reg = MetricsRegistry()
        reg.counter("serving.requests").add(3)
        obs = ObsServer(port=0, registry=reg).start()
        try:
            rec = TimeSeriesRecorder(interval_s=60.0)
            fc = FleetCollector(
                rec,
                lambda: [{
                    "name": "r0", "version": "v1",
                    "url": f"http://127.0.0.1:{obs.port}",
                }],
                registry=MetricsRegistry(),
            )
            assert fc.scrape_once(now=1.0) == 1
            assert rec.latest("fleet.replica.r0.serving.requests") == 3.0
            assert rec.latest("fleet.version.v1.serving.requests") == 3.0
        finally:
            obs.close()

    def test_sanitize_label(self):
        assert sanitize_label("replica-0") == "replica_0"
        assert sanitize_label("V2.Canary") == "v2_canary"
        assert sanitize_label("") == "unknown"


# ----------------------------------------------------------------------
# the federated canary signal
# ----------------------------------------------------------------------
class TestFederatedCanary:
    def test_fleet_rollout_slos_watch_federated_series(self):
        slos = fleet_rollout_slos("V2-Canary")
        by_name = {s.name: s for s in slos}
        assert set(by_name) == {
            "fleet.rollout.v2_canary.latency",
            "fleet.rollout.v2_canary.errors",
        }
        lat = by_name["fleet.rollout.v2_canary.latency"]
        assert lat.series == (
            "fleet.version.v2_canary.serving.latency_ms.p99"
        )
        err = by_name["fleet.rollout.v2_canary.errors"]
        assert err.numerator == "fleet.version.v2_canary.serving.errors"
        assert err.denominator == (
            "fleet.version.v2_canary.serving.requests"
        )

    def test_canary_pages_on_own_series_while_router_view_is_clean(self):
        # THE ISSUE-13 scenario: every request the canary serves fails,
        # but the router's retry loop re-places them on v1 — so the
        # router-side attempt series stay clean and rollout_slos alone
        # would bake a burning canary to 100%.  The federated series
        # are the canary's own numbers; they page.
        rec = TimeSeriesRecorder(interval_s=60.0)
        engine = SLOEngine(
            rec, registry=MetricsRegistry(), clock=lambda: 0.0,
        )
        engine.add(*rollout_slos(
            "v2", fast_window_s=5.0, slow_window_s=10.0,
        ))
        engine.add(*fleet_rollout_slos(
            "v2", fast_window_s=5.0, slow_window_s=10.0,
        ))
        for t in range(12):
            t = float(t)
            # router-side attempt view: traffic flows, zero errors,
            # healthy latency (the retried failures landed on v1)
            rec.record("router.requests.v2", 10.0 * t, now=t)
            rec.record("router.errors.v2", 0.0, now=t)
            rec.record("router.latency_ms.v2.p99", 5.0, now=t)
            # the canary's scraped ground truth: everything it
            # actually served errored
            rec.record(
                "fleet.version.v2.serving.requests", 10.0 * t, now=t,
            )
            rec.record(
                "fleet.version.v2.serving.errors", 10.0 * t, now=t,
            )
        states = engine.evaluate_once(now=11.0)
        assert states["rollout.v2.errors"] == "ok"
        assert states["rollout.v2.latency"] == "ok"
        assert states["fleet.rollout.v2.errors"] == "page"

        # and the controller's DEFAULT watch catches exactly that
        # federated breach — no explicit watch list required
        ctrl = RolloutController(
            object(), engine, "v2", spec=None, old_version="v1",
            replicas=1, stages=(0.05, 1.0), bake_s=1.0,
            interval_s=0.1, spawn_timeout_s=1.0,
        )
        assert ctrl._breached() == ["fleet.rollout.v2.errors"]

    def test_quiet_fleet_series_do_not_page(self):
        # no-data is no evidence: a canary that served nothing yet must
        # not page (the 1% stage may take a moment to see traffic)
        rec = TimeSeriesRecorder(interval_s=60.0)
        engine = SLOEngine(
            rec, registry=MetricsRegistry(), clock=lambda: 0.0,
        )
        engine.add(*fleet_rollout_slos(
            "v2", fast_window_s=5.0, slow_window_s=10.0,
        ))
        states = engine.evaluate_once(now=11.0)
        assert states["fleet.rollout.v2.errors"] == "ok"
        assert states["fleet.rollout.v2.latency"] == "ok"
