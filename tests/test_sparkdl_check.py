"""Per-rule coverage for the ``ci/sparkdl_check`` framework: true
positives, true negatives, inline suppression, baseline filtering, and
the stale-baseline check.  Fixtures are tiny on-disk trees (the
framework's unit is a file), run in-process via ``run_check`` — no
subprocess per case."""

import json
import subprocess
import sys
import textwrap
from pathlib import Path

import pytest

_REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(_REPO))

from ci.sparkdl_check import (  # noqa: E402
    all_rule_ids,
    load_baseline,
    run_check,
    write_baseline,
)
from ci.sparkdl_check.report import json_report, text_report  # noqa: E402


def check_snippet(tmp_path, relpath, source, rules=None, baseline=None):
    """Write one fixture file and run the framework over the tree."""
    path = tmp_path / relpath
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(textwrap.dedent(source))
    return run_check(tmp_path, rule_ids=rules, baseline=baseline)


def check_files(tmp_path, files, rules=None, baseline=None,
                cache_path=None, only_paths=None):
    """Multi-file fixture tree (cross-file rules need more than one
    file).  Imports inside fixtures must be spelled relative to the scan
    root (``from helper import f``), exactly as sparkdl_tpu modules
    import each other relative to the package root."""
    for relpath, source in files.items():
        path = tmp_path / relpath
        path.parent.mkdir(parents=True, exist_ok=True)
        path.write_text(textwrap.dedent(source))
    return run_check(tmp_path, rule_ids=rules, baseline=baseline,
                     cache_path=cache_path, only_paths=only_paths)


def build_graph(tmp_path, files):
    """A CallGraph over fixture files, for the unit tests below."""
    import ast

    from ci.sparkdl_check.callgraph import CallGraph
    from ci.sparkdl_check.core import FileContext

    ctxs = {}
    for relpath, source in files.items():
        src = textwrap.dedent(source)
        path = tmp_path / relpath
        ctxs[relpath] = FileContext(
            path, relpath, ast.parse(src), src, src.splitlines()
        )
    return CallGraph(ctxs)


def rule_lines(report, rule_id):
    return [f.line for f in report.findings if f.rule == rule_id]


# ---------------------------------------------------------------------------
# framework plumbing
# ---------------------------------------------------------------------------

def test_registry_has_all_sixteen_rules():
    assert set(all_rule_ids()) == {
        "lock-order", "lock-blocking", "host-sync", "recompile-hazard",
        "donation-safety", "contextvar-leak", "sleep-retry", "metric-name",
        "raw-jit", "exception-safety", "resource-lifecycle",
        "fault-site-coverage", "wire-envelope", "error-taxonomy",
        "raw-clock", "bucket-pad",
    }


def test_unknown_rule_id_is_an_error(tmp_path):
    (tmp_path / "m.py").write_text("x = 1\n")
    with pytest.raises(KeyError):
        run_check(tmp_path, rule_ids=["no-such-rule"])


def test_syntax_error_fails_the_run(tmp_path):
    (tmp_path / "broken.py").write_text("def f(:\n")
    report = run_check(tmp_path)
    assert report.exit_code == 1
    assert report.parse_errors and "broken.py" in report.parse_errors[0]["path"]


def test_suppression_comment_moves_finding_to_suppressed(tmp_path):
    report = check_snippet(
        tmp_path, "serving/x.py",
        """
        import jax
        def f(y):
            return jax.device_get(y)  # sparkdl: disable=host-sync
        """,
        rules=["host-sync"],
    )
    assert report.findings == []
    assert len(report.suppressed) == 1
    assert report.exit_code == 0


def test_suppression_is_rule_specific(tmp_path):
    # disabling a DIFFERENT rule does not silence this one
    report = check_snippet(
        tmp_path, "serving/x.py",
        """
        import jax
        def f(y):
            return jax.device_get(y)  # sparkdl: disable=raw-jit
        """,
        rules=["host-sync"],
    )
    assert len(report.findings) == 1


def test_suppress_all_silences_every_rule(tmp_path):
    report = check_snippet(
        tmp_path, "serving/x.py",
        """
        import jax
        def f(y):
            return jax.device_get(y)  # sparkdl: disable=all
        """,
        rules=["host-sync"],
    )
    assert report.findings == []


def test_baseline_filters_matching_finding(tmp_path):
    src = """
    import jax
    def f(y):
        return jax.device_get(y)
    """
    report = check_snippet(tmp_path, "serving/x.py", src, rules=["host-sync"])
    assert len(report.findings) == 1
    baseline = {
        "findings": [
            {
                "rule": f.rule, "path": f.path, "line": f.line,
                "message": f.message, "reason": "test",
            }
            for f in report.findings
        ]
    }
    again = check_snippet(
        tmp_path, "serving/x.py", src, rules=["host-sync"], baseline=baseline
    )
    assert again.findings == []
    assert len(again.baselined) == 1
    assert again.stale_baseline == []
    assert again.exit_code == 0


def test_baseline_survives_line_drift_but_not_message_change(tmp_path):
    src = """
    import jax
    def f(y):
        return jax.device_get(y)
    """
    report = check_snippet(tmp_path, "serving/x.py", src, rules=["host-sync"])
    entry = report.findings[0]
    baseline = {"findings": [{
        "rule": entry.rule, "path": entry.path,
        "line": entry.line + 40,  # lines are informational only
        "message": entry.message, "reason": "test",
    }]}
    drifted = check_snippet(
        tmp_path, "serving/x.py", "\n\n\n" + textwrap.dedent(src),
        rules=["host-sync"], baseline=baseline,
    )
    assert drifted.findings == []
    assert len(drifted.baselined) == 1


def test_stale_baseline_entry_fails_the_run(tmp_path):
    baseline = {"findings": [{
        "rule": "host-sync", "path": "serving/gone.py", "line": 1,
        "message": "this finding no longer fires", "reason": "stale",
    }]}
    report = check_snippet(
        tmp_path, "serving/clean.py", "x = 1\n",
        rules=["host-sync"], baseline=baseline,
    )
    assert report.findings == []
    assert len(report.stale_baseline) == 1
    assert report.exit_code == 1


def test_baseline_multiplicity(tmp_path):
    # two identical findings, one baseline entry: one stays active
    src = """
    import jax
    def f(y):
        return jax.device_get(y)
    def g(y):
        return jax.device_get(y)
    """
    report = check_snippet(tmp_path, "serving/x.py", src, rules=["host-sync"])
    assert len(report.findings) == 2
    assert report.findings[0].message == report.findings[1].message
    baseline = {"findings": [{
        "rule": report.findings[0].rule, "path": report.findings[0].path,
        "line": report.findings[0].line,
        "message": report.findings[0].message, "reason": "test",
    }]}
    again = check_snippet(
        tmp_path, "serving/x.py", src, rules=["host-sync"], baseline=baseline
    )
    assert len(again.findings) == 1
    assert len(again.baselined) == 1


def test_write_and_load_baseline_roundtrip(tmp_path):
    report = check_snippet(
        tmp_path, "serving/x.py",
        """
        import jax
        def f(y):
            return jax.device_get(y)
        """,
        rules=["host-sync"],
    )
    out = tmp_path / "baseline.json"
    write_baseline(report.findings, out)
    doc = load_baseline(out)
    assert len(doc["findings"]) == 1
    again = run_check(tmp_path, rule_ids=["host-sync"], baseline=doc)
    assert again.findings == [] and again.exit_code == 0


def test_reporters_render_both_formats(tmp_path):
    report = check_snippet(
        tmp_path, "serving/x.py",
        """
        import jax
        def f(y):
            return jax.device_get(y)
        """,
        rules=["host-sync"],
    )
    text = text_report(report)
    assert "serving/x.py" in text and "host-sync" in text
    doc = json.loads(json_report(report))
    assert doc["exit_code"] == 1
    assert doc["counts"] == {"host-sync": 1}
    assert doc["findings"][0]["rule"] == "host-sync"


# ---------------------------------------------------------------------------
# lock-blocking
# ---------------------------------------------------------------------------

LOCK_BLOCKING_TP = """
import subprocess
import threading
import time
import queue
import jax

_lock = threading.Lock()
_q = queue.Queue()

def bad_sleep():
    with _lock:
        time.sleep(1.0)

def bad_queue():
    with _lock:
        _q.put(1)
        return _q.get()

def bad_future(fut):
    with _lock:
        return fut.result()

def bad_device(x):
    with _lock:
        return jax.device_get(x)

def bad_subprocess(cmd):
    with _lock:
        subprocess.run(cmd)

def _slow():
    subprocess.run(["true"])

def bad_indirect():
    with _lock:
        _slow()
"""


def test_lock_blocking_true_positives(tmp_path):
    report = check_snippet(
        tmp_path, "serving/x.py", LOCK_BLOCKING_TP, rules=["lock-blocking"]
    )
    msgs = [f.message for f in report.findings]
    assert len(msgs) == 7, msgs  # sleep, put, get, result, device_get,
    #                              subprocess, indirect _slow()
    assert any("time.sleep" in m for m in msgs)
    assert any("Queue.put" in m for m in msgs)
    assert any("Queue.get" in m for m in msgs)
    assert any("future.result" in m for m in msgs)
    assert any("device_get" in m for m in msgs)
    assert any("_slow() runs subprocess.run" in m for m in msgs)


LOCK_BLOCKING_TN = """
import threading
import time
import queue

_lock = threading.Lock()
_q = queue.Queue()

class W:
    def __init__(self):
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._done = threading.Event()

    def ok_condition_wait(self):
        with self._cv:
            self._cv.wait()  # releases the lock — sanctioned

    def ok_timeouts(self, fut):
        with self._lock:
            _q.get(timeout=0.5)
            _q.put(1, timeout=0.5)
            fut.result(timeout=0.5)
            self._done.wait(0.5)

def ok_outside_lock(fut):
    time.sleep(0.0)
    _q.get()
    return fut.result()

def ok_nested_def():
    with _lock:
        def later():
            time.sleep(1.0)  # runs when called, not under the with
        return later
"""


def test_lock_blocking_true_negatives(tmp_path):
    report = check_snippet(
        tmp_path, "serving/x.py", LOCK_BLOCKING_TN, rules=["lock-blocking"]
    )
    assert report.findings == [], [f.message for f in report.findings]


def test_lock_blocking_engine_program_under_lock(tmp_path):
    report = check_snippet(
        tmp_path, "serving/x.py",
        """
        import threading

        class Cache:
            def __init__(self, engine):
                self._lock = threading.Lock()
                self._engine = engine

            def resolve(self, fn, spec):
                with self._lock:
                    return self._engine.program(fn, (spec,))
        """,
        rules=["lock-blocking"],
    )
    assert len(report.findings) == 1
    assert "AOT-compile" in report.findings[0].message


# ---------------------------------------------------------------------------
# lock-order
# ---------------------------------------------------------------------------

LOCK_ORDER_CYCLE = """
import threading

class S:
    def __init__(self):
        self._a = threading.Lock()
        self._b = threading.Lock()

    def forward(self):
        with self._a:
            with self._b:
                pass

    def backward(self):
        with self._b:
            with self._a:
                pass
"""


def test_lock_order_flags_abba_cycle(tmp_path):
    report = check_snippet(
        tmp_path, "serving/x.py", LOCK_ORDER_CYCLE, rules=["lock-order"]
    )
    assert len(report.findings) == 2  # both conflicting acquisitions
    assert all("deadlock" in f.message for f in report.findings)


def test_lock_order_consistent_nesting_is_clean(tmp_path):
    report = check_snippet(
        tmp_path, "serving/x.py",
        """
        import threading

        class S:
            def __init__(self):
                self._a = threading.Lock()
                self._b = threading.Lock()

            def one(self):
                with self._a:
                    with self._b:
                        pass

            def two(self):
                with self._a:
                    with self._b:
                        pass
        """,
        rules=["lock-order"],
    )
    assert report.findings == []


# ---------------------------------------------------------------------------
# host-sync
# ---------------------------------------------------------------------------

HOST_SYNC_TP = """
import jax
import numpy as np
from sparkdl_tpu.engine import engine

_fwd = engine.function(lambda x: x, fingerprint="m")
_cache = {}
_cache["k"] = engine.function(lambda x: x, fingerprint="n")

def hot(batch):
    out = np.asarray(_fwd(batch))          # sync on engine result
    loss = float(_fwd(batch))              # scalar coercion
    item = _fwd(batch).item()              # .item()
    got = jax.device_get(batch)            # bare device_get
    jax.block_until_ready(batch)           # bare block
    cached = np.asarray(_cache["k"](batch))  # via marked container
    return out, loss, item, got, cached
"""


def test_host_sync_true_positives(tmp_path):
    report = check_snippet(
        tmp_path, "serving/x.py", HOST_SYNC_TP, rules=["host-sync"]
    )
    assert len(report.findings) == 6, [f.message for f in report.findings]


HOST_SYNC_TN = """
import numpy as np
from sparkdl_tpu.engine import engine

_fwd = engine.function(lambda x: x, fingerprint="m")

def ok(batch, rows):
    dev = _fwd(batch)            # stays on device — no coercion
    host = np.asarray(rows)      # not an engine result
    n = float(len(rows))         # plain python
    return dev, host, n
"""


def test_host_sync_true_negatives(tmp_path):
    report = check_snippet(
        tmp_path, "serving/x.py", HOST_SYNC_TN, rules=["host-sync"]
    )
    assert report.findings == [], [f.message for f in report.findings]


def test_host_sync_scoped_to_hot_packages(tmp_path):
    # the same sync in estimators/ (not a hot package) is not scanned
    report = check_snippet(
        tmp_path, "estimators/x.py",
        """
        import jax
        def f(y):
            return jax.device_get(y)
        """,
        rules=["host-sync"],
    )
    assert report.findings == []


def test_host_sync_executor_is_sanctioned(tmp_path):
    report = check_snippet(
        tmp_path, "engine/executor.py",
        """
        import jax
        def fetch(y):
            return jax.device_get(y)
        """,
        rules=["host-sync"],
    )
    assert report.findings == []


# ---------------------------------------------------------------------------
# recompile-hazard
# ---------------------------------------------------------------------------

RECOMPILE_TP = """
from sparkdl_tpu.engine import engine

_fwd = engine.function(lambda x: x, fingerprint="stable")

def per_call(batch):
    f = engine.function(lambda x: x * 2)   # anon key EVERY call
    return f(batch)

def closure(batch, scale):
    def fwd(x):
        return x * scale
    g = engine.function(fwd)               # closure, no fingerprint
    return g(batch)

def scalar(batch):
    return _fwd(3.5)                       # python scalar traces as const
"""


def test_recompile_hazard_true_positives(tmp_path):
    report = check_snippet(
        tmp_path, "serving/x.py", RECOMPILE_TP, rules=["recompile-hazard"]
    )
    msgs = [f.message for f in report.findings]
    assert len(msgs) == 3, msgs
    assert sum("anonymous engine program" in m for m in msgs) == 2
    assert sum("Python scalar" in m for m in msgs) == 1
    scalar = [f for f in report.findings if "scalar" in f.message][0]
    assert scalar.severity == "warning"


RECOMPILE_TN = """
from sparkdl_tpu.engine import engine
import numpy as np

_fwd = engine.function(lambda x: x, fingerprint="stable")

def ok(batch):
    f = engine.function(lambda x: x, fingerprint="per-site-stable")
    arr = _fwd(np.float32(3.5))            # array scalar: shape-stable
    return f(batch), arr
"""


def test_recompile_hazard_true_negatives(tmp_path):
    report = check_snippet(
        tmp_path, "serving/x.py", RECOMPILE_TN, rules=["recompile-hazard"]
    )
    assert report.findings == [], [f.message for f in report.findings]


def test_recompile_module_level_lambda_is_warning(tmp_path):
    report = check_snippet(
        tmp_path, "serving/x.py",
        """
        from sparkdl_tpu.engine import engine
        _f = engine.function(lambda x: x)
        """,
        rules=["recompile-hazard"],
    )
    assert len(report.findings) == 1
    assert report.findings[0].severity == "warning"


# ---------------------------------------------------------------------------
# donation-safety
# ---------------------------------------------------------------------------

DONATION_TP = """
from sparkdl_tpu.engine import engine

_fwd = engine.function(lambda x: x, fingerprint="m", donate=True)

def bad(batch):
    out = _fwd(batch)
    return out, batch.shape    # batch's buffer now backs out
"""


def test_donation_safety_true_positive(tmp_path):
    report = check_snippet(
        tmp_path, "serving/x.py", DONATION_TP, rules=["donation-safety"]
    )
    assert len(report.findings) == 1
    assert "'batch' read after being donated" in report.findings[0].message


DONATION_TN = """
from sparkdl_tpu.engine import engine

_fwd = engine.function(lambda x: x, fingerprint="m", donate=True)
_plain = engine.function(lambda x: x, fingerprint="p")

def ok_last_use(batch):
    return _fwd(batch)         # nothing reads batch afterwards

def ok_rebound(batch):
    batch = _fwd(batch)        # rebinding kills the dead name
    return batch

def ok_not_donated(batch):
    out = _plain(batch)
    return out, batch.shape    # donate=False: batch still valid

def ok_expression(batch):
    out = _fwd(batch + 1)      # temporary donated, not the name
    return out, batch.shape
"""


def test_donation_safety_true_negatives(tmp_path):
    report = check_snippet(
        tmp_path, "serving/x.py", DONATION_TN, rules=["donation-safety"]
    )
    assert report.findings == [], [f.message for f in report.findings]


# ---------------------------------------------------------------------------
# contextvar-leak
# ---------------------------------------------------------------------------

CONTEXTVAR_TP = """
import threading
import queue

from sparkdl_tpu.obs import tracer, record_event

_q = queue.Queue()

def worker():
    span = tracer.current()        # empty context on this thread
    record_event("x")
    return span

def consumer():
    item = _q.get()
    record_event("drained", n=1)   # queue consumer, same leak
    return item

def start():
    threading.Thread(target=worker).start()
"""


def test_contextvar_leak_true_positives(tmp_path):
    report = check_snippet(
        tmp_path, "serving/x.py", CONTEXTVAR_TP, rules=["contextvar-leak"]
    )
    msgs = [f.message for f in report.findings]
    assert len(msgs) == 3, msgs
    assert any("worker" in m for m in msgs)
    assert any("consumer" in m for m in msgs)


CONTEXTVAR_TN = """
import threading

from sparkdl_tpu.obs import tracer, record_event

def start(work):
    span = tracer.capture()        # producer side: correct

    def worker():
        with tracer.use_span(span):
            record_event("x")      # guarded — sanctioned protocol
        with tracer.span("serving.worker_batch"):
            pass                   # NEW span in a worker is fine

    threading.Thread(target=worker).start()

def not_a_worker():
    return tracer.current()        # main thread: fine
"""


def test_contextvar_leak_true_negatives(tmp_path):
    report = check_snippet(
        tmp_path, "serving/x.py", CONTEXTVAR_TN, rules=["contextvar-leak"]
    )
    assert report.findings == [], [f.message for f in report.findings]


# ---------------------------------------------------------------------------
# migrated rules (full planted-violation coverage lives in test_lint.py,
# which exercises the back-compat shims; here: the framework wiring)
# ---------------------------------------------------------------------------

def test_sleep_retry_rule_on_framework(tmp_path):
    report = check_snippet(
        tmp_path, "serving/x.py",
        """
        import time
        def poll(fn):
            while True:
                time.sleep(1.0)
        """,
        rules=["sleep-retry"],
    )
    assert len(report.findings) == 1
    assert "RetryPolicy" in report.findings[0].message
    clean = check_snippet(
        tmp_path, "resilience/x.py",
        "import time\nwhile False:\n    time.sleep(1)\n",
        rules=["sleep-retry"],
    )
    assert [f for f in clean.findings if f.path.startswith("resilience/")] == []


def test_metric_name_rule_on_framework(tmp_path):
    report = check_snippet(
        tmp_path, "serving/x.py",
        """
        from sparkdl_tpu.utils.metrics import metrics
        metrics.counter("batches").add(1)
        metrics.gauge("serving.depth").set(1)
        """,
        rules=["metric-name"],
    )
    assert len(report.findings) == 1
    assert "subsystem prefix" in report.findings[0].message


def test_raw_jit_rule_on_framework(tmp_path):
    report = check_snippet(
        tmp_path, "transformers/x.py",
        """
        import jax
        fitted = jax.jit(lambda x: x)
        """,
        rules=["raw-jit"],
    )
    assert len(report.findings) == 1
    assert "engine.function" in report.findings[0].message
    # engine/ is not a checked package for raw-jit
    clean = check_snippet(
        tmp_path, "engine/x.py",
        "import jax\nfitted = jax.jit(lambda x: x)\n",
        rules=["raw-jit"],
    )
    assert [f for f in clean.findings if f.rule == "raw-jit"
            and f.path.startswith("engine/")] == []


# ---------------------------------------------------------------------------
# the real repo: CLI end-to-end + stale-baseline guard (tier-1 gate for
# the whole run lives in test_lint.py)
# ---------------------------------------------------------------------------

def test_cli_json_format_and_exit_code(tmp_path):
    pkg = tmp_path / "sparkdl_tpu" / "serving"
    pkg.mkdir(parents=True)
    (pkg / "x.py").write_text(
        "import jax\ndef f(y):\n    return jax.device_get(y)\n"
    )
    proc = subprocess.run(
        [sys.executable, "-m", "ci.sparkdl_check",
         str(tmp_path / "sparkdl_tpu"), "--format", "json", "--no-baseline"],
        capture_output=True, text=True, timeout=120, cwd=str(_REPO),
    )
    assert proc.returncode == 1
    doc = json.loads(proc.stdout)
    assert doc["counts"] == {"host-sync": 1}
    assert doc["findings"][0]["path"] == "serving/x.py"


@pytest.fixture(scope="module")
def repo_report():
    return run_check(_REPO / "sparkdl_tpu", baseline=load_baseline())


def test_repo_baseline_has_no_stale_entries(repo_report):
    """Every baseline entry must correspond to a finding that still
    fires — the run itself fails otherwise, but this test pins the
    reason down when it does."""
    assert repo_report.stale_baseline == [], repo_report.stale_baseline


def test_repo_scan_is_fast_enough(repo_report):
    """Acceptance: the full 9-rule scan completes in < 10 s on CPU."""
    assert repo_report.elapsed_s < 10.0, repo_report.elapsed_s


# ---------------------------------------------------------------------------
# PR 8: the telemetry plane joins the checked surface
# ---------------------------------------------------------------------------

def test_metric_name_rule_sanctions_telemetry_prefixes(tmp_path):
    """``slo.`` (burn-rate gauges) and ``ts.`` (recorder self-metrics)
    are sanctioned subsystem prefixes; a lookalike is not."""
    report = check_snippet(
        tmp_path, "obs/x.py",
        """
        from sparkdl_tpu.utils.metrics import metrics
        metrics.gauge("slo.latency.state").set(0)
        metrics.counter("slo.transitions").add(1)
        metrics.counter("ts.samples").add(1)
        metrics.gauge("ts.active_series").set(3)
        metrics.counter("tsx.samples").add(1)
        """,
        rules=["metric-name"],
    )
    assert len(report.findings) == 1
    assert "tsx.samples" in report.findings[0].message


def test_lock_blocking_scope_covers_obs_server(tmp_path):
    """The introspection server is in the lock-blocking rule's scope: a
    handler that renders (or joins) under a held lock must fire."""
    report = check_snippet(
        tmp_path, "obs/server.py",
        """
        import threading

        _lock = threading.Lock()

        def close(thread, fut):
            with _lock:
                thread.join()
                fut.result()
        """,
        rules=["lock-blocking"],
    )
    assert len(report.findings) == 2
    assert all(f.path == "obs/server.py" for f in report.findings)


def test_lock_blocking_scope_covers_obs_blackbox(tmp_path):
    """The flight recorder must never do file I/O under its ring lock —
    the rule watches the file that promises it."""
    report = check_snippet(
        tmp_path, "obs/blackbox.py",
        """
        import subprocess
        import threading

        _lock = threading.Lock()

        def dump(cmd):
            with _lock:
                subprocess.run(cmd)
        """,
        rules=["lock-blocking"],
    )
    assert len(report.findings) == 1
    snapshot_outside = check_snippet(
        tmp_path, "obs/blackbox2.py",
        """
        import json
        import threading

        _lock = threading.Lock()
        _ring = []

        def dump(path):
            with _lock:
                payload = list(_ring)
            with open(path, "w") as fh:
                json.dump(payload, fh)
        """,
        rules=["lock-blocking"],
    )
    assert [f for f in snapshot_outside.findings
            if f.path == "obs/blackbox2.py"] == []


def test_repo_telemetry_plane_is_clean(repo_report):
    """The shipped obs/server.py + obs/blackbox.py (new in PR 8) carry
    zero findings — copy-under-lock, render-outside is the law there."""
    dirty = [f for f in repo_report.findings
             if f.path in ("obs/server.py", "obs/blackbox.py",
                           "obs/timeseries.py", "obs/slo.py")]
    assert dirty == [], dirty


# ---------------------------------------------------------------------------
# PR 9: the whole-program call graph
# ---------------------------------------------------------------------------

def test_callgraph_resolves_import_aliases(tmp_path):
    graph = build_graph(tmp_path, {
        "helper.py": """
            def slow():
                pass
            """,
        "a.py": """
            import helper as h
            from helper import slow as renamed

            def use_module():
                h.slow()

            def use_from():
                renamed()
            """,
    })
    def callees(qname):
        return {q for _line, q in graph.info(qname).calls}

    assert "helper.py::slow" in callees("a.py::use_module")
    assert "helper.py::slow" in callees("a.py::use_from")


def test_callgraph_resolves_methods_and_instances(tmp_path):
    graph = build_graph(tmp_path, {
        "w.py": """
            import time

            class Worker:
                def run(self):
                    self.step()

                def step(self):
                    time.sleep(1.0)

            class Owner:
                def __init__(self):
                    self._w = Worker()

                def go(self):
                    self._w.run()
            """,
    })
    def callees(qname):
        return {q for _line, q in graph.info(qname).calls}

    assert "w.py::Worker.step" in callees("w.py::Worker.run")
    assert "w.py::Worker.run" in callees("w.py::Owner.go")
    # effect summaries ride on the nodes: step blocks, and the block is
    # reachable transitively from the owner
    hit = graph.transitive_effect("w.py::Owner.go", "blocks")
    assert hit is not None
    chain, reason = hit
    assert reason == "time.sleep"
    assert [i.qname for i in chain] == [
        "w.py::Owner.go", "w.py::Worker.run", "w.py::Worker.step",
    ]


def test_callgraph_tolerates_cycles(tmp_path):
    graph = build_graph(tmp_path, {
        "c.py": """
            import time

            def ping():
                pong()

            def pong():
                ping()

            def ping_blocking():
                pong_blocking()

            def pong_blocking():
                ping_blocking()
                time.sleep(1.0)
            """,
    })
    # a pure cycle with no effect terminates with no hit
    assert graph.transitive_effect("c.py::ping", "blocks") is None
    # a cycle WITH an effect still reports it exactly once
    hit = graph.transitive_effect("c.py::ping_blocking", "blocks")
    assert hit is not None and hit[1] == "time.sleep"


def test_callgraph_depth_is_bounded(tmp_path):
    from ci.sparkdl_check.callgraph import MAX_DEPTH

    chain_src = ["import time", ""]
    for i in range(6):
        chain_src += [f"def f{i}():", f"    f{i + 1}()", ""]
    chain_src += ["def f6():", "    time.sleep(1.0)", ""]
    graph = build_graph(tmp_path, {"deep.py": "\n".join(chain_src)})
    # a chain of MAX_DEPTH hops is still found...
    near = graph.transitive_effect(
        f"deep.py::f{6 - MAX_DEPTH}", "blocks"
    )
    assert near is not None and len(near[0]) == MAX_DEPTH + 1
    # ...but one hop further out the bounded search deliberately stops
    assert graph.transitive_effect(
        f"deep.py::f{5 - MAX_DEPTH}", "blocks"
    ) is None


def test_callgraph_reverse_file_dependents(tmp_path):
    graph = build_graph(tmp_path, {
        "helper.py": "def slow():\n    pass\n",
        "mid.py": "from helper import slow\ndef go():\n    slow()\n",
        "top.py": "import mid\ndef run():\n    mid.go()\n",
        "island.py": "def alone():\n    pass\n",
    })
    deps = graph.reverse_file_dependents({"helper.py"})
    assert "mid.py" in deps and "top.py" in deps
    assert "island.py" not in deps


# ---------------------------------------------------------------------------
# PR 9: interprocedural upgrades of the existing rules
# ---------------------------------------------------------------------------

CROSSFILE_HELPER = """
import subprocess

def slow_helper():
    subprocess.run(["true"])

def mid():
    slow_helper()
"""

CROSSFILE_MAIN = """
import threading
from helper import mid, slow_helper

_lock = threading.Lock()

def flush_direct():
    with _lock:
        slow_helper()

def flush_chain():
    with _lock:
        mid()
"""


def test_lock_blocking_crosses_files_with_chain(tmp_path):
    """THE fixture the old file-local check was blind to: the blocking
    call lives one import away from the `with lock:`."""
    report = check_files(
        tmp_path,
        {"helper.py": CROSSFILE_HELPER, "serving/main.py": CROSSFILE_MAIN},
        rules=["lock-blocking"],
    )
    msgs = sorted(f.message for f in report.findings)
    assert len(msgs) == 2, msgs
    assert any(
        "slow_helper() reaches subprocess.run" in m and "[helper.py]" in m
        for m in msgs
    )
    # the depth-2 chain prints every hop so the reader sees WHY; the
    # file tag lands on the hop that leaves the calling file
    assert any(
        "mid() reaches subprocess.run" in m
        and "mid() [helper.py] → slow_helper()" in m
        for m in msgs
    )


def test_lock_blocking_same_file_keeps_short_message(tmp_path):
    # depth-1 same-file findings keep the established message shape
    # (the baseline format from previous rounds)
    report = check_snippet(
        tmp_path, "serving/x.py",
        """
        import subprocess
        import threading

        _lock = threading.Lock()

        def _build():
            subprocess.run(["true"])

        def load():
            with _lock:
                _build()
        """,
        rules=["lock-blocking"],
    )
    assert len(report.findings) == 1
    assert report.findings[0].message == (
        "_build() runs subprocess.run — called while holding a lock"
    )


def test_host_sync_hidden_in_helper_file(tmp_path):
    """A hot-path call into a utils/ helper that forces a device sync:
    invisible to the old per-file scan, flagged with the chain now."""
    report = check_files(
        tmp_path,
        {
            "util_helpers.py": """
                import jax

                def fetch_scalar(x):
                    return jax.device_get(x)
                """,
            "serving/hot.py": """
                from util_helpers import fetch_scalar

                def hot(batch):
                    return fetch_scalar(batch)
                """,
        },
        rules=["host-sync"],
    )
    assert len(report.findings) == 1
    f = report.findings[0]
    assert f.path == "serving/hot.py"
    assert "forces a device→host sync" in f.message
    assert "util_helpers.py" in f.message


def test_host_sync_sanctioned_executor_not_traversed(tmp_path):
    # chains that terminate in the sanctioned synchronizer are the
    # DispatchWindow protocol working as designed, not a finding
    report = check_files(
        tmp_path,
        {
            "engine/executor.py": """
                import jax

                def fetch(x):
                    return jax.device_get(x)
                """,
            "serving/hot.py": """
                from engine.executor import fetch

                def hot(batch):
                    return fetch(batch)
                """,
        },
        rules=["host-sync"],
    )
    assert report.findings == [], [f.message for f in report.findings]


def test_recompile_hazard_transitive_anon_wrap(tmp_path):
    report = check_files(
        tmp_path,
        {
            "mathops.py": "def fwd(x):\n    return x\n",
            "wraps.py": """
                from sparkdl_tpu.engine import engine
                from mathops import fwd

                def make_program():
                    return engine.function(fwd)
                """,
            "serving/hot.py": """
                from wraps import make_program

                def per_call(batch):
                    return make_program()(batch)
                """,
        },
        rules=["recompile-hazard"],
    )
    hot = [f for f in report.findings if f.path == "serving/hot.py"]
    assert len(hot) == 1, [f.message for f in report.findings]
    assert "make_program() wraps an engine program" in hot[0].message
    assert "[wraps.py]" in hot[0].message


# ---------------------------------------------------------------------------
# PR 9: exception-safety
# ---------------------------------------------------------------------------

EXCEPTION_SAFETY_TP = """
import threading

_lock = threading.Lock()

def bad_acquire():
    _lock.acquire()
    do_work()
    _lock.release()

def bad_span_no_finally(tracer):
    sp = tracer.start_span("x")
    do_work()
    sp.end()

def bad_span_never_ended(tracer):
    sp = tracer.start_span("y")
    do_work()

def do_work():
    pass
"""


def test_exception_safety_true_positives(tmp_path):
    report = check_snippet(
        tmp_path, "serving/x.py", EXCEPTION_SAFETY_TP,
        rules=["exception-safety"],
    )
    msgs = sorted(f.message for f in report.findings)
    assert len(msgs) == 3, msgs
    assert any("_lock.acquire() without a try/finally" in m for m in msgs)
    assert any("end()ed outside any finally" in m for m in msgs)
    assert any("never end()ed and never handed off" in m for m in msgs)


EXCEPTION_SAFETY_TN = """
import threading

_lock = threading.Lock()

def ok_try_finally():
    _lock.acquire()
    try:
        do_work()
    finally:
        _lock.release()

def ok_with():
    with _lock:
        do_work()

def ok_span_in_finally(tracer):
    sp = tracer.start_span("x")
    try:
        do_work()
    finally:
        sp.end()

def ok_span_immediate(tracer):
    sp = tracer.start_span("x")
    sp.end()
    do_work()

def ok_span_returned(tracer):
    sp = tracer.start_span("x")
    return sp

def ok_span_handed_off(tracer, req, fut):
    req.span = tracer.start_span("a")
    sp = tracer.start_span("b")
    fut.add_done_callback(lambda _: sp.end())

def do_work():
    pass
"""


def test_exception_safety_true_negatives(tmp_path):
    report = check_snippet(
        tmp_path, "serving/x.py", EXCEPTION_SAFETY_TN,
        rules=["exception-safety"],
    )
    assert report.findings == [], [f.message for f in report.findings]


# ---------------------------------------------------------------------------
# PR 9: resource-lifecycle
# ---------------------------------------------------------------------------

RESOURCE_TP = """
import threading
from concurrent.futures import ThreadPoolExecutor
from http.server import ThreadingHTTPServer

def bad_thread():
    t = threading.Thread(target=work)
    t.start()

def bad_pool():
    pool = ThreadPoolExecutor(4)
    return pool.submit(work)

def bad_server(handler):
    httpd = ThreadingHTTPServer(("127.0.0.1", 0), handler)
    httpd.serve_forever()

def work():
    pass
"""


def test_resource_lifecycle_true_positives(tmp_path):
    report = check_snippet(
        tmp_path, "serving/x.py", RESOURCE_TP, rules=["resource-lifecycle"]
    )
    msgs = sorted(f.message for f in report.findings)
    assert len(msgs) == 3, msgs
    assert any("Thread created without daemon=True" in m for m in msgs)
    assert any("ThreadPoolExecutor with no shutdown path" in m for m in msgs)
    assert any("ThreadingHTTPServer with no shutdown()" in m for m in msgs)


RESOURCE_TN = """
import threading
from concurrent.futures import ThreadPoolExecutor
from http.server import ThreadingHTTPServer

class Svc:
    def start(self, handler):
        self._thread = threading.Thread(target=work)
        self._thread.daemon = True
        self._thread.start()
        self._httpd = ThreadingHTTPServer(("127.0.0.1", 0), handler)

    def stop(self):
        self._httpd.shutdown()
        self._httpd.server_close()
        t = self._thread
        t.join(timeout=5)

def ok_daemon_kwarg():
    threading.Thread(target=work, daemon=True).start()

def ok_pool_with():
    with ThreadPoolExecutor(2) as pool:
        pool.submit(work)

def ok_pool_shutdown():
    pool = ThreadPoolExecutor(2)
    try:
        return pool.submit(work)
    finally:
        pool.shutdown(wait=False)

def work():
    pass
"""


def test_resource_lifecycle_true_negatives(tmp_path):
    report = check_snippet(
        tmp_path, "serving/x.py", RESOURCE_TN, rules=["resource-lifecycle"]
    )
    assert report.findings == [], [f.message for f in report.findings]


# ---------------------------------------------------------------------------
# PR 9: fault-site-coverage (cross-tree: scanned files vs tests/)
# ---------------------------------------------------------------------------

def test_fault_site_coverage_flags_untested_site(tmp_path):
    report = check_files(
        tmp_path,
        {
            "estimators/x.py": """
                from sparkdl_tpu.resilience import inject

                def run(name):
                    inject.fire("estimator.step")
                    inject.fire("estimator.custom")
                    inject.fire(f"watchdog.{name}")
                """,
            "tests/test_faults.py": (
                'PLAN = "estimator.step"  # covered site\n'
            ),
        },
        rules=["fault-site-coverage"],
    )
    assert len(report.findings) == 1, [f.message for f in report.findings]
    f = report.findings[0]
    assert "'estimator.custom'" in f.message
    assert f.path == "estimators/x.py"
    # dynamic f-string sites are statically unknowable: exempt, and the
    # covered site is silent


def test_fault_site_coverage_silent_without_tests_tree(tmp_path):
    report = check_files(
        tmp_path,
        {
            "estimators/x.py": """
                from sparkdl_tpu.resilience import inject

                def run():
                    inject.fire("estimator.step")
                """,
        },
        rules=["fault-site-coverage"],
    )
    assert report.findings == []


# ---------------------------------------------------------------------------
# PR 9: --changed-only (only_paths) semantics
# ---------------------------------------------------------------------------

def test_changed_only_rechecks_reverse_dependents(tmp_path):
    # only helper.py "changed", but serving/main.py calls into it: the
    # cross-file finding must still surface
    report = check_files(
        tmp_path,
        {"helper.py": CROSSFILE_HELPER, "serving/main.py": CROSSFILE_MAIN},
        rules=["lock-blocking"],
        only_paths=["helper.py"],
    )
    assert report.cache_status == "changed-only"
    assert {f.path for f in report.findings} == {"serving/main.py"}


def test_changed_only_skips_unrelated_files(tmp_path):
    report = check_files(
        tmp_path,
        {
            "helper.py": CROSSFILE_HELPER,
            "serving/main.py": CROSSFILE_MAIN,
            "island.py": "def alone():\n    pass\n",
        },
        rules=["lock-blocking"],
        only_paths=["island.py"],
    )
    assert report.findings == []


def test_changed_only_does_not_enforce_stale_baseline(tmp_path):
    baseline = {"findings": [{
        "rule": "lock-blocking", "path": "serving/other.py", "line": 1,
        "message": "something that only fires on an unselected file",
        "reason": "test",
    }]}
    report = check_files(
        tmp_path,
        {"island.py": "def alone():\n    pass\n"},
        rules=["lock-blocking"], baseline=baseline,
        only_paths=["island.py"],
    )
    assert report.stale_baseline == []
    assert report.exit_code == 0


# ---------------------------------------------------------------------------
# PR 9: incremental result cache
# ---------------------------------------------------------------------------

HOT_SYNC_FIXTURE = {
    "serving/x.py": """
        import jax

        def f(y):
            return jax.device_get(y)
        """,
    "serving/clean.py": "def g():\n    return 1\n",
}


def test_cache_warm_run_replays_identical_findings(tmp_path):
    cache = tmp_path / "cache.json"
    first = check_files(
        tmp_path, HOT_SYNC_FIXTURE, rules=["host-sync"], cache_path=cache
    )
    assert first.cache_status == "cold"
    assert len(first.findings) == 1
    again = run_check(tmp_path, rule_ids=["host-sync"], cache_path=cache)
    assert again.cache_status == "warm"
    assert [f.to_dict() for f in again.findings] == \
        [f.to_dict() for f in first.findings]
    assert again.exit_code == first.exit_code


def test_cache_invalidated_by_file_edit(tmp_path):
    cache = tmp_path / "cache.json"
    check_files(
        tmp_path, HOT_SYNC_FIXTURE, rules=["host-sync"], cache_path=cache
    )
    (tmp_path / "serving/x.py").write_text(
        "import jax\n\ndef f(y):\n    return jax.device_get(y)\n\n"
        "def f2(y):\n    return jax.device_get(y)\n"
    )
    report = run_check(tmp_path, rule_ids=["host-sync"], cache_path=cache)
    assert report.cache_status in ("cold", "partial")
    assert len(report.findings) == 2


def test_cache_partial_reuse_keeps_unchanged_file_findings(tmp_path):
    cache = tmp_path / "cache.json"
    check_files(
        tmp_path, HOT_SYNC_FIXTURE, rules=["host-sync"], cache_path=cache
    )
    # edit only the CLEAN file; the dirty one is replayed from cache
    (tmp_path / "serving/clean.py").write_text("def g():\n    return 2\n")
    report = run_check(tmp_path, rule_ids=["host-sync"], cache_path=cache)
    assert report.cache_status == "partial"
    assert len(report.findings) == 1
    assert report.findings[0].path == "serving/x.py"


def test_cache_invalidated_by_rule_set_and_toolchain(tmp_path, monkeypatch):
    from ci.sparkdl_check import cache as cache_mod

    cache = tmp_path / "cache.json"
    check_files(
        tmp_path, HOT_SYNC_FIXTURE, rules=["host-sync"], cache_path=cache
    )
    # a different rule selection misses the whole-run key
    other = run_check(
        tmp_path, rule_ids=["host-sync", "lock-blocking"], cache_path=cache
    )
    assert other.cache_status != "warm"
    # a toolchain change (edited checker source) orphans the cache file
    monkeypatch.setattr(cache_mod, "_toolchain_memo", "something-else")
    cold = run_check(tmp_path, rule_ids=["host-sync"], cache_path=cache)
    assert cold.cache_status == "cold"
    assert len(cold.findings) == 1


def test_cache_invalidated_by_tests_tree_change(tmp_path):
    cache = tmp_path / "cache.json"
    files = {
        "estimators/x.py": """
            from sparkdl_tpu.resilience import inject

            def run():
                inject.fire("estimator.step")
            """,
        "tests/test_faults.py": 'PLAN = "estimator.step"\n',
    }
    first = check_files(
        tmp_path, files, rules=["fault-site-coverage"], cache_path=cache
    )
    assert first.findings == []
    # deleting the covering test MUST invalidate the warm replay
    (tmp_path / "tests/test_faults.py").write_text("PLAN = None\n")
    report = run_check(
        tmp_path, rule_ids=["fault-site-coverage"], cache_path=cache
    )
    assert report.cache_status != "warm"
    assert len(report.findings) == 1


# ---------------------------------------------------------------------------
# PR 9: timings in the report
# ---------------------------------------------------------------------------

def test_report_carries_timings(tmp_path):
    report = check_files(tmp_path, HOT_SYNC_FIXTURE, rules=["host-sync"])
    assert set(report.timings) >= {
        "rules", "parse_s", "graph_build_s", "total_s"
    }
    assert "host-sync" in report.timings["rules"]
    doc = json.loads(json_report(report))
    assert "timings" in doc and "cache_status" in doc
    assert doc["timings"]["total_s"] >= 0


def test_repo_warm_scan_is_fast(tmp_path):
    """Acceptance: the warm incremental run over the real repo stays
    well under the 10 s budget (it replays cached findings)."""
    cache = tmp_path / "repo-cache.json"
    run_check(_REPO / "sparkdl_tpu", cache_path=cache)
    warm = run_check(_REPO / "sparkdl_tpu", cache_path=cache)
    assert warm.cache_status == "warm"
    assert warm.elapsed_s < 10.0, warm.elapsed_s
    assert warm.exit_code in (0, 1)  # findings governed by the baseline


# ---------------------------------------------------------------------------
# PR 10: replica-plane scope (TCP servers, spawned processes, new
# metric prefixes)
# ---------------------------------------------------------------------------

def test_resource_lifecycle_covers_tcp_servers_and_popen(tmp_path):
    """The supervisor plane's resources are in scope: a wire-protocol
    ``ThreadingTCPServer`` needs a shutdown path and a spawned replica
    ``Popen`` needs a reap path (wait/communicate) or every restart
    cycle leaves a zombie."""
    report = check_snippet(
        tmp_path, "serving/x.py",
        """
        import socketserver
        import subprocess

        def bad_tcp(handler):
            srv = socketserver.ThreadingTCPServer(("127.0.0.1", 0), handler)
            srv.serve_forever()

        def bad_spawn(cmd):
            proc = subprocess.Popen(cmd)
            return proc.pid
        """,
        rules=["resource-lifecycle"],
    )
    msgs = sorted(f.message for f in report.findings)
    assert len(msgs) == 2, msgs
    assert any("ThreadingTCPServer with no shutdown()" in m for m in msgs)
    assert any("Popen with no wait()/communicate() reap path" in m
               for m in msgs)


def test_resource_lifecycle_tcp_and_popen_reclaim_paths(tmp_path):
    """Split lifecycles are honored: the server shut down in ``stop()``
    and the child reaped in another method are clean, as is a Popen
    context manager."""
    report = check_snippet(
        tmp_path, "serving/x.py",
        """
        import socketserver
        import subprocess

        class Sup:
            def start(self, handler, cmd):
                self._tcp = socketserver.ThreadingTCPServer(
                    ("127.0.0.1", 0), handler)
                self._proc = subprocess.Popen(cmd)

            def stop(self):
                self._tcp.shutdown()
                self._tcp.server_close()
                self._proc.wait(timeout=10)

        def ok_with(cmd):
            with subprocess.Popen(cmd) as proc:
                return proc.communicate()
        """,
        rules=["resource-lifecycle"],
    )
    assert report.findings == [], [f.message for f in report.findings]


def test_metric_name_rule_sanctions_replica_plane_prefixes(tmp_path):
    """``supervisor.`` (replica lifecycle) and ``router.`` (request
    plane) are sanctioned; a lookalike is not."""
    report = check_snippet(
        tmp_path, "serving/x.py",
        """
        from sparkdl_tpu.utils.metrics import metrics
        metrics.gauge("supervisor.replicas").set(2)
        metrics.counter("supervisor.restarts").add(1)
        metrics.counter("router.retries").add(1)
        metrics.histogram("router.latency_ms").observe(1.0)
        metrics.counter("routers.requests").add(1)
        """,
        rules=["metric-name"],
    )
    assert len(report.findings) == 1, [f.message for f in report.findings]
    assert "routers.requests" in report.findings[0].message


def test_resource_lifecycle_flags_unreclaimed_shared_memory(tmp_path):
    """A shm segment with no close()/unlink() anywhere on its spelling
    is a /dev/shm leak — the mapping pins kernel memory past the owner
    and the name survives until reboot."""
    report = check_snippet(
        tmp_path, "serving/x.py",
        """
        from multiprocessing import shared_memory

        def bad_create(name):
            seg = shared_memory.SharedMemory(create=True, name=name,
                                             size=1 << 20)
            return seg.buf
        """,
        rules=["resource-lifecycle"],
    )
    assert len(report.findings) == 1, [f.message for f in report.findings]
    assert "SharedMemory with no close()/unlink() path" in \
        report.findings[0].message


def test_resource_lifecycle_shared_memory_reclaim_paths(tmp_path):
    """Split shm lifecycles are honored: the creator that unlinks in
    ``close()`` (through the one-hop ``seg = self._seg`` alias the rule
    follows) and the attacher that only close()s are both clean."""
    report = check_snippet(
        tmp_path, "serving/x.py",
        """
        from multiprocessing import shared_memory

        class Creator:
            def open(self, name):
                self._seg = shared_memory.SharedMemory(
                    create=True, name=name, size=1 << 20)

            def close(self):
                seg = self._seg
                self._seg = None
                seg.close()
                seg.unlink()

        def attach_once(name):
            seg = shared_memory.SharedMemory(name=name)
            try:
                return bytes(seg.buf[:4])
            finally:
                seg.close()
        """,
        rules=["resource-lifecycle"],
    )
    assert report.findings == [], [f.message for f in report.findings]


def test_metric_name_rule_sanctions_wire_prefix(tmp_path):
    """``wire.`` (frame codec + transport lanes) is sanctioned; a
    lookalike is not."""
    report = check_snippet(
        tmp_path, "serving/x.py",
        """
        from sparkdl_tpu.utils.metrics import metrics
        metrics.timer("wire.serialize_seconds")
        metrics.counter("wire.shm.fallback").add(1)
        metrics.counter("wires.frames_out").add(1)
        """,
        rules=["metric-name"],
    )
    assert len(report.findings) == 1, [f.message for f in report.findings]
    assert "wires.frames_out" in report.findings[0].message


# ---------------------------------------------------------------------------
# wire-envelope
# ---------------------------------------------------------------------------

_WIRE_SCHEMA = """
    ENVELOPE_FIELDS = frozenset({
        "op", "ok", "value", "result", "error",
    })
    """

_WIRE_FIXTURES = """
    def test_roundtrip():
        msg = {"op": "infer", "value": 1}
        reply = {"ok": True, "result": 2, "error": None}
        assert msg and reply
    """


def test_wire_envelope_flags_undeclared_field(tmp_path):
    """A dict-literal envelope key absent from ``ENVELOPE_FIELDS`` is a
    schema finding — both lanes of the cross-process contract."""
    report = check_files(
        tmp_path,
        {
            "serving/wire.py": _WIRE_SCHEMA,
            "tests/test_wire.py": _WIRE_FIXTURES,
            "serving/router.py": """
                reply = {"ok": True, "result": 1, "surprise": 2}
                """,
        },
        rules=["wire-envelope"],
    )
    assert len(report.findings) == 1, [f.message for f in report.findings]
    f = report.findings[0]
    assert "'surprise'" in f.message and "ENVELOPE_FIELDS" in f.message
    assert f.path == "serving/router.py"


def test_wire_envelope_flags_unfixtured_subscript(tmp_path):
    """``reply[...] = ...`` adds a field post-construction; declared but
    never quoted in tests/test_wire.py means no roundtrip fixture."""
    report = check_files(
        tmp_path,
        {
            "serving/wire.py": _WIRE_SCHEMA,
            "tests/test_wire.py": """
                def test_roundtrip():
                    msg = {"op": "infer", "value": 1}
                    reply = {"ok": True, "result": 2}
                    assert msg and reply
                """,
            "serving/transport.py": """
                reply = {"ok": False}
                reply["error"] = "boom"
                """,
        },
        rules=["wire-envelope"],
    )
    assert len(report.findings) == 1, [f.message for f in report.findings]
    f = report.findings[0]
    assert "'error'" in f.message and "roundtrip fixture" in f.message
    assert f.path == "serving/transport.py"


def test_wire_envelope_clean_tree_is_quiet(tmp_path):
    """Declared + fixtured fields, and non-envelope dicts (no sentinel
    key), produce no findings."""
    report = check_files(
        tmp_path,
        {
            "serving/wire.py": _WIRE_SCHEMA,
            "tests/test_wire.py": _WIRE_FIXTURES,
            "serving/replica.py": """
                msg = {"op": "infer", "value": 3}
                reply = {"ok": True, "result": 4}
                reply["error"] = None
                options = {"retries": 2, "verbose": True}  # no sentinel key
                """,
            "serving/batcher.py": """
                # outside ENVELOPE_FILES: never scanned by this rule
                stray = {"op": "x", "not_a_field": 1}
                """,
        },
        rules=["wire-envelope"],
    )
    assert report.findings == [], [f.message for f in report.findings]


def test_wire_envelope_skips_without_schema_or_fixtures(tmp_path):
    """A bare fixture tree with neither ``serving/wire.py`` schema nor a
    tests/ dir stays silent — single-file scans must remain usable."""
    report = check_files(
        tmp_path,
        {
            "serving/router.py": """
                reply = {"ok": True, "whatever": 1}
                """,
        },
        rules=["wire-envelope"],
    )
    assert report.findings == [], [f.message for f in report.findings]


# ---------------------------------------------------------------------------
# error-taxonomy (cross-file: serving family vs resilience bases)
# ---------------------------------------------------------------------------

_TAXONOMY_BASES = """
    class FaultError(RuntimeError):
        pass

    class TransientError(FaultError):
        pass

    class PermanentError(FaultError):
        pass
    """

_SERVING_BASE = """
    from resilience.errors import (
        PermanentError,
        TransientError,
    )

    class ServingError(RuntimeError):
        pass

    class ServerOverloaded(ServingError, TransientError):
        pass

    class ServerClosed(ServingError, PermanentError):
        pass
    """


def test_error_taxonomy_flags_unclassified_subclass(tmp_path):
    """A ServingError subclass inheriting neither TransientError nor
    PermanentError silently classifies as permanent — flagged."""
    report = check_files(
        tmp_path,
        {
            "resilience/errors.py": _TAXONOMY_BASES,
            "serving/errors.py": _SERVING_BASE,
            "serving/extra.py": """
                from serving.errors import ServingError

                class MysteryError(ServingError):
                    pass
                """,
        },
        rules=["error-taxonomy"],
    )
    assert len(report.findings) == 1, [f.message for f in report.findings]
    f = report.findings[0]
    assert "'MysteryError'" in f.message and "neither" in f.message
    assert f.path == "serving/extra.py"


def test_error_taxonomy_flags_double_classification(tmp_path):
    """Inheriting BOTH classifications is contradictory — flagged."""
    report = check_files(
        tmp_path,
        {
            "resilience/errors.py": _TAXONOMY_BASES,
            "serving/errors.py": _SERVING_BASE,
            "serving/extra.py": """
                from resilience.errors import (
                    PermanentError,
                    TransientError,
                )
                from serving.errors import ServingError

                class ConfusedError(
                    ServingError, TransientError, PermanentError
                ):
                    pass
                """,
        },
        rules=["error-taxonomy"],
    )
    assert len(report.findings) == 1, [f.message for f in report.findings]
    assert "BOTH" in report.findings[0].message


def test_error_taxonomy_clean_family_is_quiet(tmp_path):
    """Classification through intermediate bases and import aliases
    counts: the real tree's DeadlineExceeded-as-_DeadlineExpired shape
    must pass, as must subclass-of-classified (TenantThrottled)."""
    report = check_files(
        tmp_path,
        {
            "resilience/errors.py": _TAXONOMY_BASES,
            "serving/errors.py": _SERVING_BASE,
            "resilience/extra.py": """
                from resilience.errors import PermanentError

                class DeadlineExpiredBase(PermanentError):
                    pass
                """,
            "serving/extra.py": """
                from resilience.extra import (
                    DeadlineExpiredBase as _DeadlineExpired,
                )
                from serving.errors import ServerOverloaded, ServingError

                class DeadlineExceeded(ServingError, _DeadlineExpired):
                    pass

                class TenantThrottled(ServerOverloaded):
                    pass
                """,
            "serving/other.py": """
                class NotAnError:
                    pass

                class FrameCorrupt(ConnectionError):
                    pass  # outside the ServingError family: exempt
                """,
        },
        rules=["error-taxonomy"],
    )
    assert report.findings == [], [f.message for f in report.findings]


# ---------------------------------------------------------------------------
# raw-clock (ISSUE-17)
# ---------------------------------------------------------------------------

def test_raw_clock_flags_wall_clock_call_in_controller(tmp_path):
    report = check_snippet(
        tmp_path, "serving/router.py",
        """
        import time

        class Router:
            def _admit(self):
                return time.monotonic() + 1.0
        """,
        rules=["raw-clock"],
    )
    assert rule_lines(report, "raw-clock") == [6]
    assert "virtual time" in report.findings[0].message


def test_raw_clock_allows_bare_reference_as_seam_default(tmp_path):
    """``clock=time.monotonic`` ctor defaults ARE the seam — only calls
    split the timeline."""
    report = check_snippet(
        tmp_path, "serving/batcher.py",
        """
        import time

        class AdmissionQueue:
            def __init__(self, clock=time.monotonic):
                self._clock = clock

            def now(self):
                return self._clock()
        """,
        rules=["raw-clock"],
    )
    assert report.findings == [], [f.message for f in report.findings]


def test_raw_clock_flags_from_import_alias(tmp_path):
    report = check_snippet(
        tmp_path, "serving/admission.py",
        """
        from time import monotonic as mono

        def deadline():
            return mono() + 0.5
        """,
        rules=["raw-clock"],
    )
    assert rule_lines(report, "raw-clock") == [5]


def test_raw_clock_ignores_non_controller_modules(tmp_path):
    """Benchmarks, engine code, tests: wall-clock reads are fine
    anywhere the sim does not replay."""
    report = check_snippet(
        tmp_path, "engine/runner.py",
        """
        import time

        def stamp():
            return time.time()
        """,
        rules=["raw-clock"],
    )
    assert report.findings == []


def test_raw_clock_ignores_sleep_and_perf_counter(tmp_path):
    report = check_snippet(
        tmp_path, "serving/router.py",
        """
        import time

        def pause():
            time.sleep(0.01)
            return time.perf_counter()
        """,
        rules=["raw-clock"],
    )
    assert report.findings == []


def test_raw_clock_inline_suppression(tmp_path):
    report = check_snippet(
        tmp_path, "serving/admission.py",
        """
        import time

        def expired(now=None):
            now = now if now is not None else time.monotonic()  # sparkdl: disable=raw-clock
            return now
        """,
        rules=["raw-clock"],
    )
    assert report.findings == []
    assert len(report.suppressed) == 1


# ---------------------------------------------------------------------------
# bucket-pad (ISSUE-20)
# ---------------------------------------------------------------------------

def test_bucket_pad_flags_pad_in_serving(tmp_path):
    report = check_snippet(
        tmp_path, "serving/batcher.py",
        """
        from sparkdl_tpu.transformers.utils import pad_to_batch

        def run(batch, bucket):
            return pad_to_batch(batch, bucket)
        """,
        rules=["bucket-pad"],
    )
    assert rule_lines(report, "bucket-pad") == [5]
    assert "slot block" in report.findings[0].message


def test_bucket_pad_flags_attribute_spelling(tmp_path):
    report = check_snippet(
        tmp_path, "serving/router.py",
        """
        from sparkdl_tpu.transformers import utils

        def run(batch, bucket):
            return utils.pad_to_batch(batch, bucket)
        """,
        rules=["bucket-pad"],
    )
    assert rule_lines(report, "bucket-pad") == [5]


def test_bucket_pad_sanctioned_fallback_is_suppressed(tmp_path):
    report = check_snippet(
        tmp_path, "serving/batcher.py",
        """
        from sparkdl_tpu.transformers.utils import pad_to_batch

        def run(batch, bucket):
            return pad_to_batch(  # sparkdl: disable=bucket-pad
                batch, bucket
            )
        """,
        rules=["bucket-pad"],
    )
    assert report.findings == []
    assert len(report.suppressed) == 1


def test_bucket_pad_ignores_transformers_batch_path(tmp_path):
    """Offline Spark-partition batching legitimately pads — the rule
    scopes to the serving hot path only."""
    report = check_snippet(
        tmp_path, "transformers/utils.py",
        """
        def chunked(chunks, batch_size):
            return [pad_to_batch(c, batch_size) for c in chunks]
        """,
        rules=["bucket-pad"],
    )
    assert report.findings == []
