"""Per-rule coverage for the ``ci/sparkdl_check`` framework: true
positives, true negatives, inline suppression, baseline filtering, and
the stale-baseline check.  Fixtures are tiny on-disk trees (the
framework's unit is a file), run in-process via ``run_check`` — no
subprocess per case."""

import json
import subprocess
import sys
import textwrap
from pathlib import Path

import pytest

_REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(_REPO))

from ci.sparkdl_check import (  # noqa: E402
    all_rule_ids,
    load_baseline,
    run_check,
    write_baseline,
)
from ci.sparkdl_check.report import json_report, text_report  # noqa: E402


def check_snippet(tmp_path, relpath, source, rules=None, baseline=None):
    """Write one fixture file and run the framework over the tree."""
    path = tmp_path / relpath
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(textwrap.dedent(source))
    return run_check(tmp_path, rule_ids=rules, baseline=baseline)


def rule_lines(report, rule_id):
    return [f.line for f in report.findings if f.rule == rule_id]


# ---------------------------------------------------------------------------
# framework plumbing
# ---------------------------------------------------------------------------

def test_registry_has_all_nine_rules():
    assert set(all_rule_ids()) == {
        "lock-order", "lock-blocking", "host-sync", "recompile-hazard",
        "donation-safety", "contextvar-leak", "sleep-retry", "metric-name",
        "raw-jit",
    }


def test_unknown_rule_id_is_an_error(tmp_path):
    (tmp_path / "m.py").write_text("x = 1\n")
    with pytest.raises(KeyError):
        run_check(tmp_path, rule_ids=["no-such-rule"])


def test_syntax_error_fails_the_run(tmp_path):
    (tmp_path / "broken.py").write_text("def f(:\n")
    report = run_check(tmp_path)
    assert report.exit_code == 1
    assert report.parse_errors and "broken.py" in report.parse_errors[0]["path"]


def test_suppression_comment_moves_finding_to_suppressed(tmp_path):
    report = check_snippet(
        tmp_path, "serving/x.py",
        """
        import jax
        def f(y):
            return jax.device_get(y)  # sparkdl: disable=host-sync
        """,
        rules=["host-sync"],
    )
    assert report.findings == []
    assert len(report.suppressed) == 1
    assert report.exit_code == 0


def test_suppression_is_rule_specific(tmp_path):
    # disabling a DIFFERENT rule does not silence this one
    report = check_snippet(
        tmp_path, "serving/x.py",
        """
        import jax
        def f(y):
            return jax.device_get(y)  # sparkdl: disable=raw-jit
        """,
        rules=["host-sync"],
    )
    assert len(report.findings) == 1


def test_suppress_all_silences_every_rule(tmp_path):
    report = check_snippet(
        tmp_path, "serving/x.py",
        """
        import jax
        def f(y):
            return jax.device_get(y)  # sparkdl: disable=all
        """,
        rules=["host-sync"],
    )
    assert report.findings == []


def test_baseline_filters_matching_finding(tmp_path):
    src = """
    import jax
    def f(y):
        return jax.device_get(y)
    """
    report = check_snippet(tmp_path, "serving/x.py", src, rules=["host-sync"])
    assert len(report.findings) == 1
    baseline = {
        "findings": [
            {
                "rule": f.rule, "path": f.path, "line": f.line,
                "message": f.message, "reason": "test",
            }
            for f in report.findings
        ]
    }
    again = check_snippet(
        tmp_path, "serving/x.py", src, rules=["host-sync"], baseline=baseline
    )
    assert again.findings == []
    assert len(again.baselined) == 1
    assert again.stale_baseline == []
    assert again.exit_code == 0


def test_baseline_survives_line_drift_but_not_message_change(tmp_path):
    src = """
    import jax
    def f(y):
        return jax.device_get(y)
    """
    report = check_snippet(tmp_path, "serving/x.py", src, rules=["host-sync"])
    entry = report.findings[0]
    baseline = {"findings": [{
        "rule": entry.rule, "path": entry.path,
        "line": entry.line + 40,  # lines are informational only
        "message": entry.message, "reason": "test",
    }]}
    drifted = check_snippet(
        tmp_path, "serving/x.py", "\n\n\n" + textwrap.dedent(src),
        rules=["host-sync"], baseline=baseline,
    )
    assert drifted.findings == []
    assert len(drifted.baselined) == 1


def test_stale_baseline_entry_fails_the_run(tmp_path):
    baseline = {"findings": [{
        "rule": "host-sync", "path": "serving/gone.py", "line": 1,
        "message": "this finding no longer fires", "reason": "stale",
    }]}
    report = check_snippet(
        tmp_path, "serving/clean.py", "x = 1\n",
        rules=["host-sync"], baseline=baseline,
    )
    assert report.findings == []
    assert len(report.stale_baseline) == 1
    assert report.exit_code == 1


def test_baseline_multiplicity(tmp_path):
    # two identical findings, one baseline entry: one stays active
    src = """
    import jax
    def f(y):
        return jax.device_get(y)
    def g(y):
        return jax.device_get(y)
    """
    report = check_snippet(tmp_path, "serving/x.py", src, rules=["host-sync"])
    assert len(report.findings) == 2
    assert report.findings[0].message == report.findings[1].message
    baseline = {"findings": [{
        "rule": report.findings[0].rule, "path": report.findings[0].path,
        "line": report.findings[0].line,
        "message": report.findings[0].message, "reason": "test",
    }]}
    again = check_snippet(
        tmp_path, "serving/x.py", src, rules=["host-sync"], baseline=baseline
    )
    assert len(again.findings) == 1
    assert len(again.baselined) == 1


def test_write_and_load_baseline_roundtrip(tmp_path):
    report = check_snippet(
        tmp_path, "serving/x.py",
        """
        import jax
        def f(y):
            return jax.device_get(y)
        """,
        rules=["host-sync"],
    )
    out = tmp_path / "baseline.json"
    write_baseline(report.findings, out)
    doc = load_baseline(out)
    assert len(doc["findings"]) == 1
    again = run_check(tmp_path, rule_ids=["host-sync"], baseline=doc)
    assert again.findings == [] and again.exit_code == 0


def test_reporters_render_both_formats(tmp_path):
    report = check_snippet(
        tmp_path, "serving/x.py",
        """
        import jax
        def f(y):
            return jax.device_get(y)
        """,
        rules=["host-sync"],
    )
    text = text_report(report)
    assert "serving/x.py" in text and "host-sync" in text
    doc = json.loads(json_report(report))
    assert doc["exit_code"] == 1
    assert doc["counts"] == {"host-sync": 1}
    assert doc["findings"][0]["rule"] == "host-sync"


# ---------------------------------------------------------------------------
# lock-blocking
# ---------------------------------------------------------------------------

LOCK_BLOCKING_TP = """
import subprocess
import threading
import time
import queue
import jax

_lock = threading.Lock()
_q = queue.Queue()

def bad_sleep():
    with _lock:
        time.sleep(1.0)

def bad_queue():
    with _lock:
        _q.put(1)
        return _q.get()

def bad_future(fut):
    with _lock:
        return fut.result()

def bad_device(x):
    with _lock:
        return jax.device_get(x)

def bad_subprocess(cmd):
    with _lock:
        subprocess.run(cmd)

def _slow():
    subprocess.run(["true"])

def bad_indirect():
    with _lock:
        _slow()
"""


def test_lock_blocking_true_positives(tmp_path):
    report = check_snippet(
        tmp_path, "serving/x.py", LOCK_BLOCKING_TP, rules=["lock-blocking"]
    )
    msgs = [f.message for f in report.findings]
    assert len(msgs) == 7, msgs  # sleep, put, get, result, device_get,
    #                              subprocess, indirect _slow()
    assert any("time.sleep" in m for m in msgs)
    assert any("Queue.put" in m for m in msgs)
    assert any("Queue.get" in m for m in msgs)
    assert any("future.result" in m for m in msgs)
    assert any("device_get" in m for m in msgs)
    assert any("_slow() runs subprocess.run" in m for m in msgs)


LOCK_BLOCKING_TN = """
import threading
import time
import queue

_lock = threading.Lock()
_q = queue.Queue()

class W:
    def __init__(self):
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._done = threading.Event()

    def ok_condition_wait(self):
        with self._cv:
            self._cv.wait()  # releases the lock — sanctioned

    def ok_timeouts(self, fut):
        with self._lock:
            _q.get(timeout=0.5)
            _q.put(1, timeout=0.5)
            fut.result(timeout=0.5)
            self._done.wait(0.5)

def ok_outside_lock(fut):
    time.sleep(0.0)
    _q.get()
    return fut.result()

def ok_nested_def():
    with _lock:
        def later():
            time.sleep(1.0)  # runs when called, not under the with
        return later
"""


def test_lock_blocking_true_negatives(tmp_path):
    report = check_snippet(
        tmp_path, "serving/x.py", LOCK_BLOCKING_TN, rules=["lock-blocking"]
    )
    assert report.findings == [], [f.message for f in report.findings]


def test_lock_blocking_engine_program_under_lock(tmp_path):
    report = check_snippet(
        tmp_path, "serving/x.py",
        """
        import threading

        class Cache:
            def __init__(self, engine):
                self._lock = threading.Lock()
                self._engine = engine

            def resolve(self, fn, spec):
                with self._lock:
                    return self._engine.program(fn, (spec,))
        """,
        rules=["lock-blocking"],
    )
    assert len(report.findings) == 1
    assert "AOT-compile" in report.findings[0].message


# ---------------------------------------------------------------------------
# lock-order
# ---------------------------------------------------------------------------

LOCK_ORDER_CYCLE = """
import threading

class S:
    def __init__(self):
        self._a = threading.Lock()
        self._b = threading.Lock()

    def forward(self):
        with self._a:
            with self._b:
                pass

    def backward(self):
        with self._b:
            with self._a:
                pass
"""


def test_lock_order_flags_abba_cycle(tmp_path):
    report = check_snippet(
        tmp_path, "serving/x.py", LOCK_ORDER_CYCLE, rules=["lock-order"]
    )
    assert len(report.findings) == 2  # both conflicting acquisitions
    assert all("deadlock" in f.message for f in report.findings)


def test_lock_order_consistent_nesting_is_clean(tmp_path):
    report = check_snippet(
        tmp_path, "serving/x.py",
        """
        import threading

        class S:
            def __init__(self):
                self._a = threading.Lock()
                self._b = threading.Lock()

            def one(self):
                with self._a:
                    with self._b:
                        pass

            def two(self):
                with self._a:
                    with self._b:
                        pass
        """,
        rules=["lock-order"],
    )
    assert report.findings == []


# ---------------------------------------------------------------------------
# host-sync
# ---------------------------------------------------------------------------

HOST_SYNC_TP = """
import jax
import numpy as np
from sparkdl_tpu.engine import engine

_fwd = engine.function(lambda x: x, fingerprint="m")
_cache = {}
_cache["k"] = engine.function(lambda x: x, fingerprint="n")

def hot(batch):
    out = np.asarray(_fwd(batch))          # sync on engine result
    loss = float(_fwd(batch))              # scalar coercion
    item = _fwd(batch).item()              # .item()
    got = jax.device_get(batch)            # bare device_get
    jax.block_until_ready(batch)           # bare block
    cached = np.asarray(_cache["k"](batch))  # via marked container
    return out, loss, item, got, cached
"""


def test_host_sync_true_positives(tmp_path):
    report = check_snippet(
        tmp_path, "serving/x.py", HOST_SYNC_TP, rules=["host-sync"]
    )
    assert len(report.findings) == 6, [f.message for f in report.findings]


HOST_SYNC_TN = """
import numpy as np
from sparkdl_tpu.engine import engine

_fwd = engine.function(lambda x: x, fingerprint="m")

def ok(batch, rows):
    dev = _fwd(batch)            # stays on device — no coercion
    host = np.asarray(rows)      # not an engine result
    n = float(len(rows))         # plain python
    return dev, host, n
"""


def test_host_sync_true_negatives(tmp_path):
    report = check_snippet(
        tmp_path, "serving/x.py", HOST_SYNC_TN, rules=["host-sync"]
    )
    assert report.findings == [], [f.message for f in report.findings]


def test_host_sync_scoped_to_hot_packages(tmp_path):
    # the same sync in estimators/ (not a hot package) is not scanned
    report = check_snippet(
        tmp_path, "estimators/x.py",
        """
        import jax
        def f(y):
            return jax.device_get(y)
        """,
        rules=["host-sync"],
    )
    assert report.findings == []


def test_host_sync_executor_is_sanctioned(tmp_path):
    report = check_snippet(
        tmp_path, "engine/executor.py",
        """
        import jax
        def fetch(y):
            return jax.device_get(y)
        """,
        rules=["host-sync"],
    )
    assert report.findings == []


# ---------------------------------------------------------------------------
# recompile-hazard
# ---------------------------------------------------------------------------

RECOMPILE_TP = """
from sparkdl_tpu.engine import engine

_fwd = engine.function(lambda x: x, fingerprint="stable")

def per_call(batch):
    f = engine.function(lambda x: x * 2)   # anon key EVERY call
    return f(batch)

def closure(batch, scale):
    def fwd(x):
        return x * scale
    g = engine.function(fwd)               # closure, no fingerprint
    return g(batch)

def scalar(batch):
    return _fwd(3.5)                       # python scalar traces as const
"""


def test_recompile_hazard_true_positives(tmp_path):
    report = check_snippet(
        tmp_path, "serving/x.py", RECOMPILE_TP, rules=["recompile-hazard"]
    )
    msgs = [f.message for f in report.findings]
    assert len(msgs) == 3, msgs
    assert sum("anonymous engine program" in m for m in msgs) == 2
    assert sum("Python scalar" in m for m in msgs) == 1
    scalar = [f for f in report.findings if "scalar" in f.message][0]
    assert scalar.severity == "warning"


RECOMPILE_TN = """
from sparkdl_tpu.engine import engine
import numpy as np

_fwd = engine.function(lambda x: x, fingerprint="stable")

def ok(batch):
    f = engine.function(lambda x: x, fingerprint="per-site-stable")
    arr = _fwd(np.float32(3.5))            # array scalar: shape-stable
    return f(batch), arr
"""


def test_recompile_hazard_true_negatives(tmp_path):
    report = check_snippet(
        tmp_path, "serving/x.py", RECOMPILE_TN, rules=["recompile-hazard"]
    )
    assert report.findings == [], [f.message for f in report.findings]


def test_recompile_module_level_lambda_is_warning(tmp_path):
    report = check_snippet(
        tmp_path, "serving/x.py",
        """
        from sparkdl_tpu.engine import engine
        _f = engine.function(lambda x: x)
        """,
        rules=["recompile-hazard"],
    )
    assert len(report.findings) == 1
    assert report.findings[0].severity == "warning"


# ---------------------------------------------------------------------------
# donation-safety
# ---------------------------------------------------------------------------

DONATION_TP = """
from sparkdl_tpu.engine import engine

_fwd = engine.function(lambda x: x, fingerprint="m", donate=True)

def bad(batch):
    out = _fwd(batch)
    return out, batch.shape    # batch's buffer now backs out
"""


def test_donation_safety_true_positive(tmp_path):
    report = check_snippet(
        tmp_path, "serving/x.py", DONATION_TP, rules=["donation-safety"]
    )
    assert len(report.findings) == 1
    assert "'batch' read after being donated" in report.findings[0].message


DONATION_TN = """
from sparkdl_tpu.engine import engine

_fwd = engine.function(lambda x: x, fingerprint="m", donate=True)
_plain = engine.function(lambda x: x, fingerprint="p")

def ok_last_use(batch):
    return _fwd(batch)         # nothing reads batch afterwards

def ok_rebound(batch):
    batch = _fwd(batch)        # rebinding kills the dead name
    return batch

def ok_not_donated(batch):
    out = _plain(batch)
    return out, batch.shape    # donate=False: batch still valid

def ok_expression(batch):
    out = _fwd(batch + 1)      # temporary donated, not the name
    return out, batch.shape
"""


def test_donation_safety_true_negatives(tmp_path):
    report = check_snippet(
        tmp_path, "serving/x.py", DONATION_TN, rules=["donation-safety"]
    )
    assert report.findings == [], [f.message for f in report.findings]


# ---------------------------------------------------------------------------
# contextvar-leak
# ---------------------------------------------------------------------------

CONTEXTVAR_TP = """
import threading
import queue

from sparkdl_tpu.obs import tracer, record_event

_q = queue.Queue()

def worker():
    span = tracer.current()        # empty context on this thread
    record_event("x")
    return span

def consumer():
    item = _q.get()
    record_event("drained", n=1)   # queue consumer, same leak
    return item

def start():
    threading.Thread(target=worker).start()
"""


def test_contextvar_leak_true_positives(tmp_path):
    report = check_snippet(
        tmp_path, "serving/x.py", CONTEXTVAR_TP, rules=["contextvar-leak"]
    )
    msgs = [f.message for f in report.findings]
    assert len(msgs) == 3, msgs
    assert any("worker" in m for m in msgs)
    assert any("consumer" in m for m in msgs)


CONTEXTVAR_TN = """
import threading

from sparkdl_tpu.obs import tracer, record_event

def start(work):
    span = tracer.capture()        # producer side: correct

    def worker():
        with tracer.use_span(span):
            record_event("x")      # guarded — sanctioned protocol
        with tracer.span("serving.worker_batch"):
            pass                   # NEW span in a worker is fine

    threading.Thread(target=worker).start()

def not_a_worker():
    return tracer.current()        # main thread: fine
"""


def test_contextvar_leak_true_negatives(tmp_path):
    report = check_snippet(
        tmp_path, "serving/x.py", CONTEXTVAR_TN, rules=["contextvar-leak"]
    )
    assert report.findings == [], [f.message for f in report.findings]


# ---------------------------------------------------------------------------
# migrated rules (full planted-violation coverage lives in test_lint.py,
# which exercises the back-compat shims; here: the framework wiring)
# ---------------------------------------------------------------------------

def test_sleep_retry_rule_on_framework(tmp_path):
    report = check_snippet(
        tmp_path, "serving/x.py",
        """
        import time
        def poll(fn):
            while True:
                time.sleep(1.0)
        """,
        rules=["sleep-retry"],
    )
    assert len(report.findings) == 1
    assert "RetryPolicy" in report.findings[0].message
    clean = check_snippet(
        tmp_path, "resilience/x.py",
        "import time\nwhile False:\n    time.sleep(1)\n",
        rules=["sleep-retry"],
    )
    assert [f for f in clean.findings if f.path.startswith("resilience/")] == []


def test_metric_name_rule_on_framework(tmp_path):
    report = check_snippet(
        tmp_path, "serving/x.py",
        """
        from sparkdl_tpu.utils.metrics import metrics
        metrics.counter("batches").add(1)
        metrics.gauge("serving.depth").set(1)
        """,
        rules=["metric-name"],
    )
    assert len(report.findings) == 1
    assert "subsystem prefix" in report.findings[0].message


def test_raw_jit_rule_on_framework(tmp_path):
    report = check_snippet(
        tmp_path, "transformers/x.py",
        """
        import jax
        fitted = jax.jit(lambda x: x)
        """,
        rules=["raw-jit"],
    )
    assert len(report.findings) == 1
    assert "engine.function" in report.findings[0].message
    # engine/ is not a checked package for raw-jit
    clean = check_snippet(
        tmp_path, "engine/x.py",
        "import jax\nfitted = jax.jit(lambda x: x)\n",
        rules=["raw-jit"],
    )
    assert [f for f in clean.findings if f.rule == "raw-jit"
            and f.path.startswith("engine/")] == []


# ---------------------------------------------------------------------------
# the real repo: CLI end-to-end + stale-baseline guard (tier-1 gate for
# the whole run lives in test_lint.py)
# ---------------------------------------------------------------------------

def test_cli_json_format_and_exit_code(tmp_path):
    pkg = tmp_path / "sparkdl_tpu" / "serving"
    pkg.mkdir(parents=True)
    (pkg / "x.py").write_text(
        "import jax\ndef f(y):\n    return jax.device_get(y)\n"
    )
    proc = subprocess.run(
        [sys.executable, "-m", "ci.sparkdl_check",
         str(tmp_path / "sparkdl_tpu"), "--format", "json", "--no-baseline"],
        capture_output=True, text=True, timeout=120, cwd=str(_REPO),
    )
    assert proc.returncode == 1
    doc = json.loads(proc.stdout)
    assert doc["counts"] == {"host-sync": 1}
    assert doc["findings"][0]["path"] == "serving/x.py"


@pytest.fixture(scope="module")
def repo_report():
    return run_check(_REPO / "sparkdl_tpu", baseline=load_baseline())


def test_repo_baseline_has_no_stale_entries(repo_report):
    """Every baseline entry must correspond to a finding that still
    fires — the run itself fails otherwise, but this test pins the
    reason down when it does."""
    assert repo_report.stale_baseline == [], repo_report.stale_baseline


def test_repo_scan_is_fast_enough(repo_report):
    """Acceptance: the full 9-rule scan completes in < 10 s on CPU."""
    assert repo_report.elapsed_s < 10.0, repo_report.elapsed_s


# ---------------------------------------------------------------------------
# PR 8: the telemetry plane joins the checked surface
# ---------------------------------------------------------------------------

def test_metric_name_rule_sanctions_telemetry_prefixes(tmp_path):
    """``slo.`` (burn-rate gauges) and ``ts.`` (recorder self-metrics)
    are sanctioned subsystem prefixes; a lookalike is not."""
    report = check_snippet(
        tmp_path, "obs/x.py",
        """
        from sparkdl_tpu.utils.metrics import metrics
        metrics.gauge("slo.latency.state").set(0)
        metrics.counter("slo.transitions").add(1)
        metrics.counter("ts.samples").add(1)
        metrics.gauge("ts.active_series").set(3)
        metrics.counter("tsx.samples").add(1)
        """,
        rules=["metric-name"],
    )
    assert len(report.findings) == 1
    assert "tsx.samples" in report.findings[0].message


def test_lock_blocking_scope_covers_obs_server(tmp_path):
    """The introspection server is in the lock-blocking rule's scope: a
    handler that renders (or joins) under a held lock must fire."""
    report = check_snippet(
        tmp_path, "obs/server.py",
        """
        import threading

        _lock = threading.Lock()

        def close(thread, fut):
            with _lock:
                thread.join()
                fut.result()
        """,
        rules=["lock-blocking"],
    )
    assert len(report.findings) == 2
    assert all(f.path == "obs/server.py" for f in report.findings)


def test_lock_blocking_scope_covers_obs_blackbox(tmp_path):
    """The flight recorder must never do file I/O under its ring lock —
    the rule watches the file that promises it."""
    report = check_snippet(
        tmp_path, "obs/blackbox.py",
        """
        import subprocess
        import threading

        _lock = threading.Lock()

        def dump(cmd):
            with _lock:
                subprocess.run(cmd)
        """,
        rules=["lock-blocking"],
    )
    assert len(report.findings) == 1
    snapshot_outside = check_snippet(
        tmp_path, "obs/blackbox2.py",
        """
        import json
        import threading

        _lock = threading.Lock()
        _ring = []

        def dump(path):
            with _lock:
                payload = list(_ring)
            with open(path, "w") as fh:
                json.dump(payload, fh)
        """,
        rules=["lock-blocking"],
    )
    assert [f for f in snapshot_outside.findings
            if f.path == "obs/blackbox2.py"] == []


def test_repo_telemetry_plane_is_clean(repo_report):
    """The shipped obs/server.py + obs/blackbox.py (new in PR 8) carry
    zero findings — copy-under-lock, render-outside is the law there."""
    dirty = [f for f in repo_report.findings
             if f.path in ("obs/server.py", "obs/blackbox.py",
                           "obs/timeseries.py", "obs/slo.py")]
    assert dirty == [], dirty
