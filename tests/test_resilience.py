"""The fault-tolerance subsystem: taxonomy, retry/backoff/deadline,
circuit breaking, watchdogged device calls, preemption delivery, and the
deterministic fault-injection harness — plus its integrations into the
data pipeline and online serving.

Acceptance contracts pinned here:

(a) an injected transient device error is retried to success, with the
    backoff counted in ``resilience.retries``;
(b) a permanent error fails FAST with its typed class — zero retries;
(c) an injected stall trips the watchdog within the hard timeout
    instead of hanging the caller;
(d) (in ``test_fault_injection.py``) a simulated preemption mid-epoch
    checkpoints and a re-fit resumes to bit-identical weights.
"""

import os
import signal
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from sparkdl_tpu.resilience import (
    CircuitBreaker,
    CircuitOpen,
    Deadline,
    DeadlineExceeded,
    DeviceUnresponsive,
    FaultPlan,
    PermanentError,
    Preempted,
    RetryPolicy,
    TransientError,
    active_plan,
    classify,
    is_transient,
    preemption_scope,
    request_preemption,
    watchdogged,
)
from sparkdl_tpu.resilience import errors as rerrors
from sparkdl_tpu.resilience import inject
from sparkdl_tpu.resilience.inject import (
    InjectedPermanentError,
    InjectedTransientError,
)
from sparkdl_tpu.resilience.watchdog import check_device
from sparkdl_tpu.utils.metrics import metrics


@pytest.fixture(autouse=True)
def fresh_metrics():
    metrics.reset()
    yield
    metrics.reset()


def no_sleep(_):
    """Injectable RetryPolicy sleep: record nothing, wait nothing."""


def fast_policy(**kw):
    return RetryPolicy(base_delay_s=0.001, sleep=no_sleep, **kw)


# ---------------------------------------------------------------------------
# taxonomy / classification
# ---------------------------------------------------------------------------


class TestClassify:
    def test_taxonomy_members_answer_for_themselves(self):
        assert classify(TransientError("x")) is TransientError
        assert classify(PermanentError("x")) is PermanentError
        assert classify(DeviceUnresponsive("x")) is PermanentError
        assert classify(DeadlineExceeded("x")) is PermanentError
        assert classify(CircuitOpen("x")) is TransientError

    def test_repo_exceptions_participate_via_inheritance(self):
        from sparkdl_tpu.image.imageIO import ImageDecodeError
        from sparkdl_tpu.serving.errors import (
            DeadlineExceeded as ServingDeadline,
            ServerClosed,
            ServerOverloaded,
        )

        # corrupt bytes don't heal on retry
        assert not is_transient(ImageDecodeError("f.png"))
        # shed at admission: server alive, retry elsewhere/later
        assert is_transient(ServerOverloaded("shed"))
        assert not is_transient(ServingDeadline("expired"))
        assert not is_transient(ServerClosed("closed"))
        # serving's DeadlineExceeded IS the resilience one (one type to
        # catch at either layer)
        assert issubclass(ServingDeadline, DeadlineExceeded)

    def test_xla_status_words_by_type_name(self):
        # matched by exception type NAME so the taxonomy never imports jax
        XlaRuntimeError = type("XlaRuntimeError", (RuntimeError,), {})
        assert is_transient(XlaRuntimeError("RESOURCE_EXHAUSTED: hbm oom"))
        assert is_transient(XlaRuntimeError("UNAVAILABLE: socket closed"))
        assert not is_transient(XlaRuntimeError("INVALID_ARGUMENT: shape"))
        # no status word at all = the wedged/torn-tunnel shape
        assert is_transient(XlaRuntimeError("connection reset mid-stream"))
        # same message on an unknown type stays permanent (fail-fast)
        assert not is_transient(RuntimeError("UNAVAILABLE: socket closed"))

    def test_os_error_split(self):
        assert not is_transient(FileNotFoundError("gone"))
        assert not is_transient(PermissionError("denied"))
        assert is_transient(ConnectionError("reset"))
        assert is_transient(TimeoutError("slow"))
        # residual OSError (EIO, ENOSPC...) = transient I/O
        assert is_transient(OSError("I/O error"))

    def test_unknown_is_permanent_and_register_overrides(self):
        class VendorBlip(Exception):
            pass

        assert not is_transient(VendorBlip("burp"))
        rerrors.register(VendorBlip, transient=True)
        try:
            assert is_transient(VendorBlip("burp"))
        finally:
            rerrors._REGISTERED.remove((VendorBlip, True))

    def test_error_class_is_leaf_type_name(self):
        assert rerrors.error_class(DeviceUnresponsive("x")) == (
            "DeviceUnresponsive"
        )
        assert rerrors.error_class(None) == "None"


# ---------------------------------------------------------------------------
# RetryPolicy / Deadline
# ---------------------------------------------------------------------------


class TestRetryPolicy:
    def test_transient_retried_to_success_with_metered_backoff(self):
        """Acceptance (a): transient fault -> backoff -> success, with
        the retries counted in ``resilience.retries``."""
        delays = []
        policy = RetryPolicy(
            max_attempts=4, base_delay_s=0.05, jitter=0.0,
            sleep=delays.append,
        )
        attempts = {"n": 0}

        def flaky():
            attempts["n"] += 1
            if attempts["n"] < 3:
                raise InjectedTransientError("device busy")
            return "landed"

        assert policy.call(flaky) == "landed"
        assert attempts["n"] == 3
        # exponential: 0.05, 0.10 (jitter disabled for exactness)
        assert delays == pytest.approx([0.05, 0.10])
        assert metrics.counter("resilience.retries").value == 2
        assert metrics.counter("resilience.retry_exhausted").value == 0

    def test_permanent_fails_fast_typed(self):
        """Acceptance (b): permanent error -> ONE attempt, typed class
        intact, zero retries metered."""
        attempts = {"n": 0}

        def doomed():
            attempts["n"] += 1
            raise InjectedPermanentError("bad request")

        with pytest.raises(InjectedPermanentError):
            fast_policy(max_attempts=5).call(doomed)
        assert attempts["n"] == 1
        assert metrics.counter("resilience.retries").value == 0

    def test_exhaustion_raises_last_underlying_error(self):
        def always(n={"i": 0}):
            n["i"] += 1
            raise InjectedTransientError(f"blip {n['i']}")

        with pytest.raises(InjectedTransientError, match="blip 3"):
            fast_policy(max_attempts=3).call(always)
        assert metrics.counter("resilience.retries").value == 2
        assert metrics.counter("resilience.retry_exhausted").value == 1

    def test_jitter_is_seeded_and_deterministic(self):
        p = RetryPolicy(max_attempts=5, jitter=0.5, seed=7, sleep=no_sleep)
        assert list(p.delays()) == list(p.delays())
        q = RetryPolicy(max_attempts=5, jitter=0.5, seed=8, sleep=no_sleep)
        assert list(p.delays()) != list(q.delays())

    def test_budget_caps_total_sleep(self):
        slept = []
        policy = RetryPolicy(
            max_attempts=10, base_delay_s=1.0, multiplier=1.0, jitter=0.0,
            budget_s=2.5, sleep=slept.append,
        )

        def always():
            raise InjectedTransientError("blip")

        with pytest.raises(InjectedTransientError):
            policy.call(always)
        assert sum(slept) <= 2.5 + 1e-9
        assert metrics.counter("resilience.retry_exhausted").value == 1

    def test_deadline_clips_and_stops_retries(self):
        clock = {"t": 0.0}
        deadline = Deadline(5.0, clock=lambda: clock["t"], what="req")

        def sleeper(d):
            clock["t"] += d

        policy = RetryPolicy(
            max_attempts=50, base_delay_s=2.0, multiplier=1.0, jitter=0.0,
            sleep=sleeper,
        )

        def always():
            raise InjectedTransientError("blip")

        with pytest.raises(DeadlineExceeded, match="req"):
            policy.call(always, deadline=deadline)
        # 2.0 + 2.0 + 1.0(clipped) = 5.0, then the deadline gate raises
        assert clock["t"] == pytest.approx(5.0)

    def test_expired_deadline_raises_typed_before_first_attempt(self):
        deadline = Deadline.after(-1.0, what="already late")
        with pytest.raises(DeadlineExceeded, match="already late"):
            fast_policy().call(lambda: "never", deadline=deadline)

    def test_wrap_bakes_policy_into_plain_callable(self):
        n = {"v": 0}

        def flaky(x):
            n["v"] += 1
            if n["v"] < 2:
                raise InjectedTransientError("blip")
            return x * 2

        wrapped = fast_policy().wrap(flaky)
        assert wrapped(21) == 42


class TestDeadline:
    def test_remaining_and_expiry_with_fake_clock(self):
        clock = {"t": 100.0}
        d = Deadline.after(3.0, clock=lambda: clock["t"], what="fetch")
        assert d.remaining() == pytest.approx(3.0)
        assert not d.expired()
        d.check()  # no raise
        clock["t"] += 3.5
        assert d.expired()
        with pytest.raises(DeadlineExceeded, match="fetch"):
            d.check()

    def test_unbounded(self):
        d = Deadline.after(None)
        assert d.remaining() is None and not d.expired()
        d.check()


# ---------------------------------------------------------------------------
# CircuitBreaker
# ---------------------------------------------------------------------------


class TestCircuitBreaker:
    def make(self, **kw):
        clock = {"t": 0.0}
        kw.setdefault("failure_threshold", 3)
        kw.setdefault("recovery_s", 10.0)
        br = CircuitBreaker(
            name=kw.pop("name", "dep"), clock=lambda: clock["t"], **kw
        )
        return br, clock

    def test_trips_after_consecutive_failures_only(self):
        br, _ = self.make()
        for _ in range(2):
            br.record_failure()
        br.record_success()  # success resets the consecutive count
        for _ in range(2):
            br.record_failure()
        assert br.state == "closed"
        br.record_failure()
        assert br.state == "open"
        assert metrics.counter("resilience.breaker_trips").value == 1

    def test_open_rejects_then_half_open_probe_recloses(self):
        br, clock = self.make(name="dep2")
        for _ in range(3):
            br.record_failure()
        assert not br.allow()
        with pytest.raises(CircuitOpen):
            br.check()
        assert metrics.counter("resilience.breaker_rejections").value >= 2
        clock["t"] += 10.0
        assert br.allow()  # the half-open probe slot
        assert not br.allow()  # only half_open_max=1 probe in flight
        br.record_success()
        assert br.state == "closed"
        assert br.allow()

    def test_half_open_failure_reopens(self):
        br, clock = self.make(name="dep3")
        for _ in range(3):
            br.record_failure()
        clock["t"] += 10.0
        assert br.allow()
        br.record_failure()  # the probe failed
        assert br.state == "open"
        assert not br.allow()

    def test_call_wraps_outcomes(self):
        br, _ = self.make(name="dep4", failure_threshold=1)
        with pytest.raises(ValueError):
            br.call(lambda: (_ for _ in ()).throw(ValueError("boom")))
        with pytest.raises(CircuitOpen):
            br.call(lambda: "unreached")
        snap = br.snapshot()
        assert snap["state"] == "open"
        assert snap["consecutive_failures"] == 1

    def test_state_gauge_tracks_transitions(self):
        br, clock = self.make(name="dep5", failure_threshold=1)
        g = metrics.gauge("resilience.breaker_state.dep5")
        assert g.value == 0.0
        br.record_failure()
        assert g.value == 2.0
        clock["t"] += 10.0
        br.allow()
        assert g.value == 1.0
        br.record_success()
        assert g.value == 0.0


# ---------------------------------------------------------------------------
# watchdog
# ---------------------------------------------------------------------------


class TestWatchdog:
    def test_fast_call_passes_through(self):
        assert watchdogged(lambda: 42, hard_timeout_s=30.0) == 42

    def test_worker_exception_is_relayed(self):
        def boom():
            raise InjectedPermanentError("from worker")

        with pytest.raises(InjectedPermanentError, match="from worker"):
            watchdogged(boom, hard_timeout_s=30.0)

    def test_injected_stall_trips_hard_timeout_not_a_hang(self):
        """Acceptance (c): a stalled device call raises the typed
        DeviceUnresponsive within the hard timeout — the caller's
        thread never blocks on the wedged work."""
        plan = FaultPlan().add("watchdog.stall_test", stall_s=15.0, at=1)
        start = time.monotonic()
        with active_plan(plan):
            with pytest.raises(DeviceUnresponsive, match="hard timeout"):
                watchdogged(
                    lambda: "never lands",
                    soft_timeout_s=0.05,
                    hard_timeout_s=0.6,
                    name="stall_test",
                    diagnostic_code="print('diagnostic-alive')",
                )
        elapsed = time.monotonic() - start
        assert elapsed < 10.0, f"watchdog took {elapsed:.1f}s to give up"
        assert metrics.counter(
            "resilience.watchdog_hard_timeouts"
        ).value == 1
        assert metrics.counter(
            "resilience.watchdog_soft_timeouts"
        ).value == 1

    def test_check_device_structured_record(self):
        rec = check_device(timeout_s=60, probe_code="print('cpu-ok')")
        assert rec == {"ok": True, "error_class": None, "detail": "cpu-ok"}

    def test_check_device_failure_has_error_class(self):
        rec = check_device(
            timeout_s=60, probe_code="import sys; sys.exit(3)"
        )
        assert rec["ok"] is False
        assert rec["error_class"] == "DeviceUnresponsive"


# ---------------------------------------------------------------------------
# fault-injection harness
# ---------------------------------------------------------------------------


class TestInject:
    def test_no_plan_is_a_no_op(self):
        inject.fire("anything")  # must not raise

    def test_nth_call_trigger_is_deterministic(self):
        plan = FaultPlan().add("s", error="transient", at=2, times=2)
        for _ in range(2):  # a reused plan refires identically
            with active_plan(plan):
                inject.fire("s")  # 1st: clean
                for _ in range(2):  # 2nd, 3rd: fault
                    with pytest.raises(InjectedTransientError):
                        inject.fire("s")
                inject.fire("s")  # 4th: clean again
                assert plan.count("s") == 4

    def test_probabilistic_trigger_is_seeded(self):
        def run(seed):
            plan = FaultPlan(seed=seed).add("s", error="transient", p=0.5)
            hits = []
            with active_plan(plan):
                for i in range(64):
                    try:
                        inject.fire("s")
                        hits.append(False)
                    except InjectedTransientError:
                        hits.append(True)
            return hits

        assert run(3) == run(3)
        assert run(3) != run(4)

    def test_error_shorthands(self):
        from sparkdl_tpu.image.imageIO import ImageDecodeError

        cases = {
            "transient": InjectedTransientError,
            "permanent": InjectedPermanentError,
            "device": TransientError,
            "decode": ImageDecodeError,
        }
        for shorthand, exc_type in cases.items():
            plan = FaultPlan().add("s", error=shorthand, at=1)
            with active_plan(plan), pytest.raises(exc_type):
                inject.fire("s")

    def test_rule_validation(self):
        with pytest.raises(ValueError, match="exactly one action"):
            FaultPlan().add("s", at=1)
        with pytest.raises(ValueError, match="exactly one action"):
            FaultPlan().add("s", error="transient", stall_s=1.0, at=1)
        with pytest.raises(ValueError, match="exactly one trigger"):
            FaultPlan().add("s", error="transient")
        with pytest.raises(ValueError, match="exactly one trigger"):
            FaultPlan().add("s", error="transient", at=1, p=0.5)

    def test_from_json_and_env_hook(self, monkeypatch):
        text = (
            '[{"site": "a", "error": "transient", "at": 1},'
            ' {"site": "b", "kill": true, "at": 2}]'
        )
        plan = FaultPlan.from_json(text)
        assert [r["site"] for r in plan.describe()] == ["a", "b"]
        monkeypatch.setenv(inject.ENV_VAR, text)
        env_plan = inject.plan_from_env()
        with active_plan(env_plan), pytest.raises(InjectedTransientError):
            inject.fire("a")
        monkeypatch.setenv(inject.ENV_VAR, '{"not": "a list"}')
        with pytest.raises(ValueError, match="JSON list"):
            inject.plan_from_env()

    def test_env_plan_installs_at_import_in_fresh_process(self, tmp_path):
        """The subprocess hook: a worker started with SPARKDL_FAULT_PLAN
        set runs under the plan with no code changes."""
        code = (
            "from sparkdl_tpu.resilience import inject\n"
            "try:\n"
            "    inject.fire('boot')\n"
            "    print('CLEAN')\n"
            "except Exception as e:\n"
            "    print('FAULT', type(e).__name__)\n"
        )
        env = dict(
            os.environ,
            JAX_PLATFORMS="cpu",
            SPARKDL_FAULT_PLAN=(
                '[{"site": "boot", "error": "transient", "at": 1}]'
            ),
        )
        out = subprocess.run(
            [sys.executable, "-c", code], env=env, capture_output=True,
            text=True, timeout=120, cwd=os.path.dirname(
                os.path.dirname(os.path.abspath(__file__))
            ),
        )
        assert "FAULT InjectedTransientError" in out.stdout, out.stdout

    def test_metrics_count_injected_faults(self):
        plan = FaultPlan().add("s", error="transient", at=1)
        with active_plan(plan):
            with pytest.raises(InjectedTransientError):
                inject.fire("s")
        assert metrics.counter("resilience.injected_faults").value == 1


# ---------------------------------------------------------------------------
# preemption delivery
# ---------------------------------------------------------------------------


class TestPreemption:
    def test_flag_then_safe_point_raise(self):
        with preemption_scope(install_signal_handler=False) as token:
            token.check()  # clean
            request_preemption("scheduler says so")
            assert token.requested
            with pytest.raises(Preempted, match="scheduler says so"):
                token.check()
        assert metrics.counter("resilience.preemptions").value == 1

    def test_no_scope_raises_directly(self):
        with pytest.raises(Preempted):
            request_preemption()

    def test_innermost_scope_wins(self):
        with preemption_scope(install_signal_handler=False) as outer:
            with preemption_scope(install_signal_handler=False) as inner:
                request_preemption()
                assert inner.requested and not outer.requested

    def test_preempted_escapes_broad_except_exception(self):
        try:
            try:
                raise Preempted("shutdown")
            except Exception:  # the handler that must NOT swallow it
                pytest.fail("except Exception swallowed Preempted")
        except Preempted:
            pass

    def test_sigterm_flags_token_and_disposition_restored(self):
        before = signal.getsignal(signal.SIGTERM)
        with preemption_scope() as token:
            signal.raise_signal(signal.SIGTERM)
            assert token.requested
            with pytest.raises(Preempted, match="SIGTERM"):
                token.check()
        assert signal.getsignal(signal.SIGTERM) == before


# ---------------------------------------------------------------------------
# integrations: data pipeline
# ---------------------------------------------------------------------------


class TestDataIntegration:
    def test_map_retries_injected_transients(self):
        from sparkdl_tpu.data import Dataset

        plan = FaultPlan().add("data.map", error="transient", at=2, times=2)
        ds = Dataset.from_items([1, 2, 3]).map(
            lambda v: v * 10, retry=fast_policy(max_attempts=5)
        )
        with active_plan(plan):
            assert list(ds) == [10, 20, 30]
        # the faulted item re-fires the site on each retry
        assert plan.count("data.map") == 5
        assert metrics.counter("resilience.retries").value == 2

    def test_map_threaded_retries_too(self):
        from sparkdl_tpu.data import Dataset

        plan = FaultPlan().add("data.map", error="transient", at=1)
        ds = Dataset.from_items(list(range(8))).map(
            lambda v: v + 1, num_workers=2,
            retry=fast_policy(max_attempts=3),
        )
        with active_plan(plan):
            assert list(ds) == list(range(1, 9))

    def test_map_permanent_decode_error_fails_fast(self):
        from sparkdl_tpu.data import Dataset

        plan = FaultPlan().add("data.map", error="decode", at=1)
        ds = Dataset.from_items([1]).map(
            lambda v: v, retry=fast_policy(max_attempts=5)
        )
        from sparkdl_tpu.image.imageIO import ImageDecodeError

        with active_plan(plan), pytest.raises(ImageDecodeError):
            list(ds)
        assert plan.count("data.map") == 1  # no retry burned
        assert metrics.counter("resilience.retries").value == 0

    def test_from_files_source_read_with_retry(self, tmp_path):
        from sparkdl_tpu.data import Dataset

        p = tmp_path / "blob.bin"
        p.write_bytes(b"payload")
        plan = FaultPlan().add("data.source", error="transient", at=1)
        ds = Dataset.from_files([str(p)], retry=fast_policy())
        with active_plan(plan):
            assert list(ds) == [(str(p), b"payload")]
        assert len(ds) == 1

    def test_from_files_missing_file_is_permanent(self, tmp_path):
        from sparkdl_tpu.data import Dataset

        ds = Dataset.from_files(
            [str(tmp_path / "nope.bin")], retry=fast_policy(max_attempts=4)
        )
        with pytest.raises(FileNotFoundError):
            list(ds)
        assert metrics.counter("resilience.retries").value == 0

    def test_streaming_shard_loader_retries_uri_loads(self):
        from sparkdl_tpu.estimators.data import StreamingShardLoader

        plan = FaultPlan().add("data.source", error="transient", at=2)
        loader = StreamingShardLoader(
            uris=[f"u{i}" for i in range(4)],
            y=np.arange(4, dtype=np.float32),
            loader=lambda u: np.full((2,), float(u[1:]), np.float32),
            local_bs=2,
            weighted=False,
            retry=fast_policy(),
        )
        with active_plan(plan):
            batches = list(loader.epoch(np.arange(4), steps=2))
        assert len(batches) == 2
        np.testing.assert_array_equal(
            batches[0]["x"], [[0.0, 0.0], [1.0, 1.0]]
        )
        assert metrics.counter("resilience.retries").value == 1


# ---------------------------------------------------------------------------
# integrations: online serving
# ---------------------------------------------------------------------------


class TestServingIntegration:
    def test_forward_transient_retried_under_batch_deadline(self):
        """Acceptance (a) on the serving path: the injected transient
        forward failure is retried inside the worker and the request
        still succeeds."""
        from sparkdl_tpu.serving import ModelServer, ServingConfig

        cfg = ServingConfig(
            max_wait_ms=1.0,
            retry=fast_policy(max_attempts=3),
        )
        plan = FaultPlan().add(
            "serving.forward", error="transient", at=1, times=2
        )
        with active_plan(plan):
            with ModelServer(cfg) as server:
                server.register(
                    "m", lambda x: x * 2.0, item_shape=(2,), compile=False
                )
                out = server.predict(
                    np.ones((2,), np.float32), timeout=30.0,
                    deadline_ms=30000.0,
                )
        np.testing.assert_allclose(out, 2.0)
        assert metrics.counter("resilience.retries").value == 2
        assert metrics.counter("serving.errors").value == 0

    def test_forward_permanent_fails_request_without_retry(self):
        from sparkdl_tpu.serving import ModelServer, ServingConfig

        cfg = ServingConfig(max_wait_ms=1.0, retry=fast_policy())
        plan = FaultPlan().add(
            "serving.forward", error="permanent", at=1
        )
        with active_plan(plan):
            with ModelServer(cfg) as server:
                server.register(
                    "m", lambda x: x, item_shape=(2,), compile=False
                )
                fut = server.submit(np.ones((2,), np.float32))
                with pytest.raises(InjectedPermanentError):
                    fut.result(timeout=30.0)
        assert metrics.counter("resilience.retries").value == 0
        assert metrics.counter("serving.errors").value == 1

    def test_breaker_trips_into_degraded_status(self):
        from sparkdl_tpu.serving import ModelServer, ServingConfig

        cfg = ServingConfig(
            max_batch=1, max_wait_ms=0.0,
            breaker_threshold=2, breaker_recovery_s=300.0,
        )
        with ModelServer(cfg) as server:
            server.register(
                "m",
                lambda x: (_ for _ in ()).throw(
                    InjectedPermanentError("dead forward")
                ),
                item_shape=(2,), compile=False,
            )
            for _ in range(2):
                with pytest.raises(InjectedPermanentError):
                    server.predict(np.ones((2,), np.float32), timeout=30.0)
            # circuit now open: the next batch fails FAST with the typed
            # (transient — retry later) CircuitOpen, not the model error
            with pytest.raises(CircuitOpen):
                server.predict(np.ones((2,), np.float32), timeout=30.0)

            status = server.status()
            assert status["degraded"] == ["m"]
            ep = status["endpoints"]["m"]
            assert ep["degraded"] is True
            assert ep["breaker"]["state"] == "open"
            # degraded, not dead: orchestrators restart on healthy=false
            assert status["healthy"] is True
        assert metrics.counter("resilience.breaker_trips").value == 1
        assert metrics.counter("serving.errors").value == 2

    def test_breaker_recloses_after_recovery_probe(self):
        from sparkdl_tpu.serving import ModelServer, ServingConfig

        cfg = ServingConfig(
            max_batch=1, max_wait_ms=0.0,
            breaker_threshold=1, breaker_recovery_s=0.05,
        )
        boom = {"on": True}

        def forward(x):
            if boom["on"]:
                raise InjectedPermanentError("down")
            return x + 1.0

        with ModelServer(cfg) as server:
            server.register("m", forward, item_shape=(2,), compile=False)
            with pytest.raises(InjectedPermanentError):
                server.predict(np.ones((2,), np.float32), timeout=30.0)
            assert server.status()["degraded"] == ["m"]
            boom["on"] = False
            time.sleep(0.1)  # recovery window elapses -> half-open probe
            out = server.predict(np.ones((2,), np.float32), timeout=30.0)
            np.testing.assert_allclose(out, 2.0)
            assert server.status()["degraded"] == []

    def test_status_probe_device_routes_through_watchdog(self):
        from sparkdl_tpu.serving import ModelServer

        with ModelServer() as server:
            server.register(
                "m", lambda x: x, item_shape=(2,), compile=False
            )
            status = server.status(probe_device=True, probe_timeout_s=120)
        # JAX_PLATFORMS=cpu (conftest): the probe answers "cpu"
        assert status["device"]["ok"] is True
        assert status["device"]["error_class"] is None
        assert status["healthy"] is True
