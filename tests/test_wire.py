"""Wire codec + transport seam tests (ISSUE-11).

Two halves:

- **Torn-frame fuzz** — every way a frame can arrive damaged
  (truncated prefix, truncated meta/body, descriptor/payload
  disagreement, hostile sizes, bad magic) must surface as a typed
  ``ConnectionError``/``RemoteReplicaError``, never a garbage array.
  The codec is the trust boundary between a healthy router and a
  replica that died mid-write.
- **Transport seam** — the TCP lane (pooled + coalesced) and the
  shared-memory lane against a real ``serve_connection`` loop:
  roundtrips, lane negotiation/refusal fallback, big-frame spill onto
  the TCP side-channel, peer-death detection, and ``/dev/shm`` leak
  hygiene.  The ``wire.shm`` fault site registered in
  ``resilience.inject`` is exercised here (fault-site-coverage rule).
"""

import os
import pickle
import socket
import socketserver
import struct
import threading
import time

import numpy as np
import pytest

from sparkdl_tpu.resilience import inject
from sparkdl_tpu.serving import transport, wire
from sparkdl_tpu.serving.errors import RemoteReplicaError
from sparkdl_tpu.utils.metrics import metrics

PREFIX = struct.Struct(">4sBBIQ")


def frame_bytes(obj, kind=wire.KIND_MSG) -> bytearray:
    return bytearray(
        b"".join(bytes(p) for p in wire.encode_parts(obj, kind))
    )


# ----------------------------------------------------------------------
# codec roundtrips
# ----------------------------------------------------------------------
class TestCodec:
    @pytest.mark.parametrize("dtype", [
        np.float32, np.float64, np.int32, np.int64, np.uint8,
        np.bool_, np.float16,
    ])
    def test_dtype_roundtrip(self, dtype):
        a, b = socket.socketpair()
        try:
            x = np.arange(24).astype(dtype).reshape(2, 3, 4)
            wire.send_msg(a, {"value": x})
            got = wire.recv_msg(b)
            np.testing.assert_array_equal(got["value"], x)
            assert got["value"].dtype == x.dtype
        finally:
            a.close()
            b.close()

    def test_nested_containers_and_scalars(self):
        x = np.linspace(0, 1, 8, dtype=np.float32)
        msg = {
            "op": "infer", "model_id": "ep0", "deadline_ms": 12.5,
            "value": x,
            "nest": [x * 2, {"k": (x, 7, "s")}, None, True],
        }
        kind, got = wire.decode_frame(frame_bytes(msg))
        assert kind == wire.KIND_MSG
        np.testing.assert_array_equal(got["value"], x)
        np.testing.assert_array_equal(got["nest"][0], x * 2)
        np.testing.assert_array_equal(got["nest"][1]["k"][0], x)
        assert got["nest"][1]["k"][1:] == (7, "s")
        assert got["nest"][2] is None and got["nest"][3] is True
        assert got["deadline_ms"] == 12.5

    def test_noncontiguous_zero_d_and_empty(self):
        base = np.arange(24, dtype=np.float32).reshape(4, 6)
        for arr in (base[:, ::2], np.array(3.5), np.empty((0, 4))):
            _, got = wire.decode_frame(frame_bytes({"a": arr}))
            np.testing.assert_array_equal(got["a"], arr)
            assert got["a"].shape == arr.shape

    def test_received_arrays_are_writable(self):
        # np.frombuffer over the receive *bytearray*: views must be
        # writable or every consumer pays a defensive copy
        _, got = wire.decode_frame(
            frame_bytes({"a": np.ones(4, np.float32)})
        )
        got["a"][0] = 7.0
        assert got["a"][0] == 7.0

    def test_object_dtype_rides_the_pickle_envelope(self):
        # raw bytes of an object array are pointers — must NOT be
        # zero-copy framed
        arr = np.array([{"k": 1}, [2]], dtype=object)
        _, got = wire.decode_frame(frame_bytes({"a": arr}))
        assert got["a"][0] == {"k": 1} and got["a"][1] == [2]

    def test_batch_frame_shares_one_body(self):
        msgs = [{"i": i, "v": np.full(4, i, np.float32)}
                for i in range(5)]
        kind, got = wire.decode_frame(
            frame_bytes(msgs, kind=wire.KIND_BATCH), )
        assert kind == wire.KIND_BATCH
        assert [m["i"] for m in got] == list(range(5))
        np.testing.assert_array_equal(got[3]["v"], np.full(4, 3.0))

    def test_batch_frame_on_message_channel_is_refused(self):
        a, b = socket.socketpair()
        try:
            wire.send_batch(a, [{"i": 0}])
            with pytest.raises(ConnectionError):
                wire.recv_msg(b)
        finally:
            a.close()
            b.close()


# ----------------------------------------------------------------------
# envelope schema: the cross-process contract, fixture-tested
# ----------------------------------------------------------------------
class TestEnvelopeSchema:
    """Every ``wire.ENVELOPE_FIELDS`` member round-trips here, as a
    *literal*.  The ``wire-envelope`` checker rule requires any field a
    serving module puts on the wire to appear quoted in this file; the
    completeness assertion below closes the other direction — a field
    added to the schema without a fixture fails this test.  Together
    they pin the envelope from both sides."""

    #: one fixture envelope per message family, every field literal
    FIXTURES = {
        "request": {
            "op": "infer", "model_id": "ep0", "value": None,
            "deadline_ms": 12.5, "tenant": "team-a",
            "trace": (12345, 67890), "seq": 7,
        },
        "decode_request": {
            "op": "decode", "model_id": "dec0", "value": None,
            "max_steps": 16, "seq": 9,
        },
        "stream": {
            "ok": True, "result": None, "stream_seq": 3,
            "final": False, "seq": 9, "steps": 4,
        },
        "shm_handshake": {
            "op": "shm_attach", "shm": "psm_fixture",
            "ring_bytes": 1 << 20, "efd": "sdw_efd_fixture",
        },
        "shm_handshake_reply": {
            "ok": True, "eventfd": True,
        },
        "reply": {
            "ok": True, "result": None, "server_ms": 3.25,
            "phases": {"wire": 0.1, "transport": 0.4},
            "spans": [{"name": "replica.serve", "trace_id": 12345}],
            "pid": 4242, "draining": False,
            "replicas": ("replica-0",), "seq": 7,
            "cache": "hit",
        },
        "error": {
            "ok": False, "error": "boom",
            "error_class": "ValueError",
        },
    }

    @pytest.mark.parametrize("family", sorted(FIXTURES))
    def test_envelope_roundtrip(self, family):
        env = dict(self.FIXTURES[family])
        if "value" in env:
            env["value"] = np.arange(8, dtype=np.float32)
        if "result" in env:
            env["result"] = np.arange(4, dtype=np.float32)
        _, got = wire.decode_frame(frame_bytes(env))
        assert set(got) == set(env)
        for key, want in env.items():
            if isinstance(want, np.ndarray):
                np.testing.assert_array_equal(got[key], want)
            elif isinstance(want, tuple):
                assert tuple(got[key]) == want
            else:
                assert got[key] == want

    def test_fixtures_cover_the_declared_schema(self):
        covered = set()
        for env in self.FIXTURES.values():
            covered |= set(env)
        assert covered == set(wire.ENVELOPE_FIELDS), (
            "ENVELOPE_FIELDS and the roundtrip fixtures disagree: "
            f"unfixtured={sorted(set(wire.ENVELOPE_FIELDS) - covered)}, "
            f"undeclared={sorted(covered - set(wire.ENVELOPE_FIELDS))}"
        )


# ----------------------------------------------------------------------
# KIND_STREAM frames (ISSUE-18): incremental decode replies ride the
# same framing — CRC trailer, seq echo, torn-frame typing — with a
# gap-free stream_seq and exactly one final frame per stream
# ----------------------------------------------------------------------
class TestStreamFrames:
    def test_stream_roundtrip_over_socket(self):
        a, b = socket.socketpair()
        try:
            for i in range(3):
                wire.send_stream(a, {
                    "ok": True, "stream_seq": i, "final": False,
                    "result": np.full(4, i, np.float32), "seq": 7,
                })
            wire.send_stream(
                a, {"ok": True, "stream_seq": 3, "final": True, "seq": 7}
            )
            for i in range(3):
                kind, got = wire.recv_any(b)
                assert kind == wire.KIND_STREAM
                assert got["stream_seq"] == i and got["final"] is False
                assert got["seq"] == 7
                np.testing.assert_array_equal(
                    got["result"], np.full(4, i, np.float32)
                )
            kind, got = wire.recv_any(b)
            assert kind == wire.KIND_STREAM
            assert got["final"] is True and got["stream_seq"] == 3
        finally:
            a.close()
            b.close()

    def test_stream_frame_on_message_channel_is_refused(self):
        # recv_msg is the one-shot API; a stream fragment there means
        # the caller lost track of a stream — refuse, don't misfile
        a, b = socket.socketpair()
        try:
            wire.send_stream(a, {"ok": True, "stream_seq": 0,
                                 "final": True})
            with pytest.raises(ConnectionError):
                wire.recv_msg(b)
        finally:
            a.close()
            b.close()

    def test_stream_frames_carry_and_verify_crc(self):
        raw = frame_bytes(
            {"ok": True, "stream_seq": 1, "final": False,
             "result": np.arange(32, dtype=np.float32)},
            kind=wire.KIND_STREAM,
        )
        _, flags, _, _ = wire._parse_prefix(bytes(raw[:PREFIX.size]))
        assert flags & wire.FLAG_CRC
        raw[len(raw) - wire._CRC.size - 5] ^= 0x20
        before = metrics.counter("wire.crc_fail").value
        with pytest.raises(wire.FrameCorrupt):
            wire.decode_frame(raw)
        assert metrics.counter("wire.crc_fail").value == before + 1

    def test_stream_kind_decodes_from_memory(self):
        kind, got = wire.decode_frame(frame_bytes(
            {"ok": True, "stream_seq": 0, "final": True,
             "result": np.ones(4, np.float32)},
            kind=wire.KIND_STREAM,
        ))
        assert kind == wire.KIND_STREAM
        np.testing.assert_array_equal(
            got["result"], np.ones(4, np.float32)
        )


# ----------------------------------------------------------------------
# torn-frame fuzz: damaged input must never become a garbage array
# ----------------------------------------------------------------------
class TestTornFrames:
    def recv_raises(self, raw: bytes):
        a, b = socket.socketpair()
        try:
            a.sendall(raw)
            a.close()
            with pytest.raises(ConnectionError):
                wire.recv_msg(b)
        finally:
            b.close()

    def test_truncated_prefix(self):
        whole = bytes(frame_bytes({"v": np.ones(4, np.float32)}))
        for cut in (1, 5, PREFIX.size - 1):
            self.recv_raises(whole[:cut])

    def test_truncated_meta_and_body(self):
        whole = bytes(frame_bytes({"v": np.ones(64, np.float32)}))
        for cut in (PREFIX.size + 3, len(whole) - 1, len(whole) - 100):
            self.recv_raises(whole[:cut])

    def test_bad_magic(self):
        whole = bytearray(frame_bytes({"v": np.ones(4, np.float32)}))
        whole[:4] = b"XXXX"
        self.recv_raises(bytes(whole))

    def test_unknown_kind(self):
        whole = bytearray(frame_bytes({"v": 1}))
        whole[4] = 99
        self.recv_raises(bytes(whole))

    def test_oversized_frame_refused_before_allocation(self):
        self.recv_raises(PREFIX.pack(
            wire.MAGIC, wire.KIND_MSG, 0, 16, wire.MAX_FRAME_BYTES + 1
        ))

    def test_oversized_meta_refused(self):
        self.recv_raises(PREFIX.pack(
            wire.MAGIC, wire.KIND_MSG, 0, wire.MAX_META_BYTES + 1, 0
        ))

    def _forged(self, desc, body: bytes) -> bytearray:
        meta = pickle.dumps(((wire._TENSOR_MARK, 0), [desc]))
        return bytearray(
            PREFIX.pack(wire.MAGIC, wire.KIND_MSG, 0, len(meta),
                        len(body)) + meta + body
        )

    def test_dtype_shape_payload_length_mismatch(self):
        body = np.ones(8, np.float32).tobytes()
        # descriptor claims 8 float64s (64 bytes) over a 32-byte body
        forged = self._forged(("<f8", (8,), 0, 32, True), body)
        with pytest.raises(ConnectionError):
            wire.decode_frame(forged)

    def test_descriptor_overruns_body(self):
        body = np.ones(8, np.float32).tobytes()
        forged = self._forged(("<f4", (16,), 0, 64, True), body)
        with pytest.raises(ConnectionError):
            wire.decode_frame(forged)
        forged = self._forged(("<f4", (8,), 16, 32, True), body)
        with pytest.raises(ConnectionError):
            wire.decode_frame(forged)

    def test_invalid_dtype_string(self):
        forged = self._forged(("not-a-dtype", (8,), 0, 32, True),
                              bytes(32))
        with pytest.raises(ConnectionError):
            wire.decode_frame(forged)

    def test_tensor_marker_out_of_range(self):
        meta = pickle.dumps(((wire._TENSOR_MARK, 3), []))
        raw = bytearray(
            PREFIX.pack(wire.MAGIC, wire.KIND_MSG, 0, len(meta), 0)
            + meta
        )
        with pytest.raises(ConnectionError):
            wire.decode_frame(raw)

    def test_garbage_meta_pickle(self):
        raw = bytearray(
            PREFIX.pack(wire.MAGIC, wire.KIND_MSG, 0, 8, 0)
            + b"\x00garbage"
        )
        with pytest.raises(ConnectionError):
            wire.decode_frame(raw)

    def test_unknown_remote_error_is_typed(self):
        exc = wire.decode_error(
            {"ok": False, "error_class": "Weird", "error": "boom"}
        )
        assert isinstance(exc, RemoteReplicaError)

    def test_error_registry_is_cached(self):
        assert wire._error_registry() is wire._error_registry()


# ----------------------------------------------------------------------
# transport seam against a live serve_connection loop
# ----------------------------------------------------------------------
class _EchoServer(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True


def start_echo(allow_shm=True):
    """A serve_connection loop that doubles ``value`` — the transport
    mechanics without a ModelServer underneath."""

    def handle_one(msg):
        if msg.get("op") == "boom":
            raise ValueError("planned failure")
        return {"ok": True, "result": msg["value"] * 2, "server_ms": 0.1}

    class Handler(socketserver.BaseRequestHandler):
        def handle(self):
            srv.conns.append(self.request)
            transport.serve_connection(
                self.request, handle_one, allow_shm=allow_shm
            )

    srv = _EchoServer(("127.0.0.1", 0), Handler)
    srv.conns = []
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    return srv, srv.server_address[1]


def my_shm_entries():
    shm_dir = "/dev/shm"
    if not os.path.isdir(shm_dir):
        return []
    return [f for f in os.listdir(shm_dir)
            if f.startswith(f"sdw_{os.getpid()}_")]


class TestTransports:
    def test_pooled_tcp_roundtrip(self):
        srv, port = start_echo()
        try:
            t = transport.TcpTransport("127.0.0.1", port, coalesce=False)
            x = np.arange(8, dtype=np.float32)
            reply = t.request({"op": "infer", "value": x}, 5.0)
            np.testing.assert_array_equal(reply["result"], x * 2)
            assert t.lane == "tcp"
            t.close()
        finally:
            srv.shutdown()
            srv.server_close()

    def test_coalescer_batches_concurrent_requests(self):
        srv, port = start_echo()
        try:
            t = transport.TcpTransport("127.0.0.1", port, coalesce=True)
            before = metrics.counter("wire.coalesced_msgs").value
            x = np.ones(8, np.float32)
            errs = []

            def hit(i):
                try:
                    reply = t.request({"op": "infer", "value": x + i}, 10.0)
                    np.testing.assert_array_equal(
                        reply["result"], (x + i) * 2
                    )
                except Exception as exc:  # noqa: BLE001
                    errs.append(exc)

            threads = [threading.Thread(target=hit, args=(i,))
                       for i in range(32)]
            for th in threads:
                th.start()
            for th in threads:
                th.join(timeout=30)
            assert not errs, errs[:3]
            # 32 concurrent requests over one socket MUST have shared
            # frames (greedy group commit while an RTT is in flight)
            assert metrics.counter("wire.coalesced_msgs").value > before
            t.close()
        finally:
            srv.shutdown()
            srv.server_close()

    def test_coalesced_error_reply_stays_per_message(self):
        srv, port = start_echo()
        try:
            t = transport.TcpTransport("127.0.0.1", port, coalesce=True)
            reply = t.request({"op": "boom", "value": 1}, 5.0)
            assert reply["ok"] is False
            assert reply["error_class"] == "ValueError"
            t.close()
        finally:
            srv.shutdown()
            srv.server_close()

    def test_shm_roundtrip_and_lane(self):
        srv, port = start_echo()
        try:
            t = transport.ShmTransport("127.0.0.1", port)
            x = np.arange(16, dtype=np.float32)
            for i in range(8):
                reply = t.request({"op": "infer", "value": x + i}, 5.0)
                np.testing.assert_array_equal(reply["result"], (x + i) * 2)
            assert t.lane == "shm"
            assert transport.active_segments()
            t.close()
            assert transport.active_segments() == []
            assert my_shm_entries() == []
        finally:
            srv.shutdown()
            srv.server_close()

    def test_shm_big_frame_spills_to_tcp_sidechannel(self):
        srv, port = start_echo()
        try:
            t = transport.ShmTransport("127.0.0.1", port)
            before = metrics.counter("wire.shm.spill").value
            big = np.ones((700, 700), np.float32)  # ~1.9MB > 1MB ring
            reply = t.request({"op": "infer", "value": big}, 15.0)
            np.testing.assert_array_equal(reply["result"], big * 2)
            assert metrics.counter("wire.shm.spill").value > before
            t.close()
        finally:
            srv.shutdown()
            srv.server_close()

    def test_shm_refusal_falls_back_to_tcp(self):
        srv, port = start_echo(allow_shm=False)
        try:
            before = metrics.counter("wire.shm.fallback").value
            t = transport.ShmTransport("127.0.0.1", port)
            x = np.ones(4, np.float32)
            reply = t.request({"op": "infer", "value": x}, 5.0)
            np.testing.assert_array_equal(reply["result"], x * 2)
            assert t.lane == "tcp"
            assert metrics.counter("wire.shm.fallback").value > before
            t.close()
            assert my_shm_entries() == []
        finally:
            srv.shutdown()
            srv.server_close()

    @pytest.mark.skipif(
        not (hasattr(os, "eventfd") and hasattr(socket, "send_fds")),
        reason="eventfd doorbells need os.eventfd + SCM_RIGHTS passing",
    )
    def test_shm_doorbells_ride_eventfd(self):
        """Where the platform supports it, shm doorbells are eventfd
        wakes — the socket side-channel carries zero doorbell bytes."""
        srv, port = start_echo()
        try:
            efd_before = metrics.counter("wire.doorbell.eventfd").value
            sock_before = metrics.counter("wire.doorbell.socket").value
            t = transport.ShmTransport("127.0.0.1", port)
            x = np.ones(8, np.float32)
            for i in range(6):
                reply = t.request({"op": "infer", "value": x + i}, 5.0)
                np.testing.assert_array_equal(reply["result"], (x + i) * 2)
            assert t.lane == "shm"
            t.close()
            assert metrics.counter("wire.doorbell.eventfd").value \
                > efd_before
            assert metrics.counter("wire.doorbell.socket").value \
                == sock_before
        finally:
            srv.shutdown()
            srv.server_close()

    def test_eventfd_kill_switch_forces_socket_doorbells(
        self, monkeypatch
    ):
        """SPARKDL_WIRE_EVENTFD=0 must pin every doorbell to the socket
        byte — the portable path stays exercised and killable."""
        monkeypatch.setenv("SPARKDL_WIRE_EVENTFD", "0")
        srv, port = start_echo()
        try:
            efd_before = metrics.counter("wire.doorbell.eventfd").value
            sock_before = metrics.counter("wire.doorbell.socket").value
            t = transport.ShmTransport("127.0.0.1", port)
            x = np.ones(8, np.float32)
            reply = t.request({"op": "infer", "value": x}, 5.0)
            np.testing.assert_array_equal(reply["result"], x * 2)
            assert t.lane == "shm"
            t.close()
            assert metrics.counter("wire.doorbell.eventfd").value \
                == efd_before
            assert metrics.counter("wire.doorbell.socket").value \
                > sock_before
        finally:
            srv.shutdown()
            srv.server_close()

    def test_peer_death_is_connection_error(self):
        # SIGKILL equivalent at channel level: the peer process is gone
        # (listener included) and the client must turn that into a
        # typed ConnectionError instead of spinning on a ring no one
        # will ever answer
        srv, port = start_echo()
        t = transport.ShmTransport("127.0.0.1", port)
        try:
            x = np.ones(4, np.float32)
            t.request({"op": "infer", "value": x}, 5.0)
            for conn in list(srv.conns):
                try:
                    conn.shutdown(socket.SHUT_RDWR)
                except OSError:
                    pass
            srv.shutdown()
            srv.server_close()
            with pytest.raises((ConnectionError, OSError)):
                t.request({"op": "infer", "value": x}, 2.0)
        finally:
            t.close()
        assert transport.active_segments() == []
        assert my_shm_entries() == []

    def test_replica_restart_while_pooled_recovers_transparently(self):
        # the softer death: the replica behind the name was restarted
        # while this channel sat pooled, but SOMETHING is listening
        # again — the staleness probe discards the dead channel and the
        # request rides a fresh one instead of surfacing ConnectionError
        srv, port = start_echo()
        t = transport.ShmTransport("127.0.0.1", port)
        try:
            x = np.ones(4, np.float32)
            t.request({"op": "infer", "value": x}, 5.0)
            before = metrics.counter("wire.pool.stale").value
            for conn in list(srv.conns):
                try:
                    conn.shutdown(socket.SHUT_RDWR)
                    conn.close()
                except OSError:
                    pass
            time.sleep(0.05)
            reply = t.request({"op": "infer", "value": x}, 5.0)
            np.testing.assert_array_equal(reply["result"], x * 2)
            assert metrics.counter("wire.pool.stale").value == before + 1
        finally:
            t.close()
            srv.shutdown()
            srv.server_close()
        assert transport.active_segments() == []
        assert my_shm_entries() == []

    def test_make_transport_mode_matrix(self):
        srv, port = start_echo()
        try:
            t = transport.make_transport(
                "127.0.0.1", port, lanes=("tcp", "shm"), mode="tcp"
            )
            assert isinstance(t, transport.TcpTransport)
            t.close()
            t = transport.make_transport(
                "127.0.0.1", port, lanes=("tcp",), mode="shm"
            )
            assert isinstance(t, transport.TcpTransport)  # fell back
            t.close()
            t = transport.make_transport(
                "127.0.0.1", port, lanes=("tcp", "shm"), mode="auto"
            )
            assert isinstance(t, transport.ShmTransport)
            t.close()
            with pytest.raises(ValueError):
                transport.make_transport(
                    "127.0.0.1", port, mode="carrier-pigeon"
                )
        finally:
            srv.shutdown()
            srv.server_close()

    def test_wire_shm_fault_site_fires(self):
        assert "wire.shm" in inject.known_sites()
        srv, port = start_echo()
        try:
            t = transport.ShmTransport("127.0.0.1", port)
            plan = inject.FaultPlan().add(
                "wire.shm", error="transient", at=1
            )
            with inject.active_plan(plan):
                with pytest.raises(inject.InjectedTransientError):
                    t.request(
                        {"op": "infer", "value": np.ones(4, np.float32)},
                        5.0,
                    )
            t.close()
            assert my_shm_entries() == []
        finally:
            srv.shutdown()
            srv.server_close()


class TestPoolStaleness:
    """ISSUE-12 satellite: the idle pool must never hand a request a
    socket the peer already abandoned — checkout probes (readable while
    idle == EOF/garbage) and age-gates every pooled entry first."""

    def test_healthy_idle_socket_is_reused(self):
        srv, port = start_echo()
        try:
            t = transport.TcpTransport("127.0.0.1", port, coalesce=False)
            x = np.ones(4, np.float32)
            for _ in range(3):
                reply = t.request({"op": "infer", "value": x}, 5.0)
                np.testing.assert_array_equal(reply["result"], x * 2)
            # all three rode the same connection: probe passed, no churn
            assert len(srv.conns) == 1
            t.close()
        finally:
            srv.shutdown()
            srv.server_close()

    def test_peer_closed_idle_socket_is_discarded_not_served(self):
        srv, port = start_echo()
        try:
            t = transport.TcpTransport("127.0.0.1", port, coalesce=False)
            x = np.ones(4, np.float32)
            t.request({"op": "infer", "value": x}, 5.0)
            # replica restarts during a quiet spell: the pooled socket
            # is now a dead letter the old code would try to write to
            before = metrics.counter("wire.pool.stale").value
            for conn in list(srv.conns):
                try:
                    conn.shutdown(socket.SHUT_RDWR)
                    conn.close()
                except OSError:
                    pass
            time.sleep(0.05)  # let the FIN land client-side
            reply = t.request({"op": "infer", "value": x}, 5.0)
            np.testing.assert_array_equal(reply["result"], x * 2)
            assert metrics.counter("wire.pool.stale").value == before + 1
            assert len(srv.conns) == 2  # fresh dial, not the corpse
            t.close()
        finally:
            srv.shutdown()
            srv.server_close()

    def test_idle_socket_ages_out(self, monkeypatch):
        # the knob is read at construction: set it BEFORE the transport
        monkeypatch.setenv("SPARKDL_WIRE_POOL_IDLE_S", "0.02")
        srv, port = start_echo()
        try:
            t = transport.TcpTransport("127.0.0.1", port, coalesce=False)
            x = np.ones(4, np.float32)
            t.request({"op": "infer", "value": x}, 5.0)
            before = metrics.counter("wire.pool.aged").value
            time.sleep(0.05)  # older than the 20ms budget
            reply = t.request({"op": "infer", "value": x}, 5.0)
            np.testing.assert_array_equal(reply["result"], x * 2)
            assert metrics.counter("wire.pool.aged").value == before + 1
            assert len(srv.conns) == 2
            t.close()
        finally:
            srv.shutdown()
            srv.server_close()


# ----------------------------------------------------------------------
# CRC-verified frames (ISSUE-14): a flipped byte anywhere in the frame
# is detected and typed — never decoded into a silently-wrong tensor
# ----------------------------------------------------------------------
class TestFrameCrc:
    def test_frames_carry_the_crc_flag(self):
        raw = frame_bytes({"v": np.ones(4, np.float32)})
        _, flags, _, _ = wire._parse_prefix(bytes(raw[:PREFIX.size]))
        assert flags & wire.FLAG_CRC

    @pytest.mark.parametrize("where", ["meta", "body", "trailer"])
    def test_single_flipped_byte_is_detected(self, where):
        raw = frame_bytes({"v": np.arange(64, dtype=np.float32)})
        index = {
            "meta": PREFIX.size + 2,
            "body": len(raw) - wire._CRC.size - 5,
            "trailer": len(raw) - 1,
        }[where]
        raw[index] ^= 0x40
        before = metrics.counter("wire.crc_fail").value
        with pytest.raises(wire.FrameCorrupt):
            wire.decode_frame(raw)
        assert metrics.counter("wire.crc_fail").value == before + 1

    def test_corrupt_frame_over_socket_is_typed(self):
        raw = frame_bytes({"v": np.arange(16, dtype=np.float32)})
        raw[len(raw) - wire._CRC.size - 3] ^= 0x01
        a, b = socket.socketpair()
        try:
            a.sendall(bytes(raw))
            a.close()
            with pytest.raises(ConnectionError):
                wire.recv_msg(b)
        finally:
            b.close()

    def test_crc_off_roundtrip(self, monkeypatch):
        monkeypatch.setattr(wire, "_CRC_ENABLED", False)
        raw = frame_bytes({"v": np.ones(4, np.float32)})
        _, flags, _, _ = wire._parse_prefix(bytes(raw[:PREFIX.size]))
        assert not (flags & wire.FLAG_CRC)
        _, got = wire.decode_frame(raw)
        np.testing.assert_array_equal(got["v"], np.ones(4, np.float32))

    def test_decode_honours_frame_flag_not_env(self, monkeypatch):
        # a CRC-stamped frame from a peer with the knob ON must still
        # verify locally even when THIS process has encoding turned off
        raw = frame_bytes({"v": np.arange(8, dtype=np.float32)})
        monkeypatch.setattr(wire, "_CRC_ENABLED", False)
        raw[-2] ^= 0x10
        with pytest.raises(wire.FrameCorrupt):
            wire.decode_frame(raw)

    def test_framecorrupt_is_transient_and_registry_typed(self):
        from sparkdl_tpu.resilience.errors import is_transient

        exc = wire.FrameCorrupt("x")
        assert isinstance(exc, ConnectionError)
        assert is_transient(exc)
        # the registry round-trips it (and plain connection-shaped
        # classes) typed, never as the permanent RemoteReplicaError
        for cls in ("FrameCorrupt", "ConnectionError", "TimeoutError"):
            decoded = wire.decode_error(
                {"ok": False, "error_class": cls, "error": "x"}
            )
            assert not isinstance(decoded, RemoteReplicaError), cls
        assert isinstance(
            wire.decode_error(
                {"ok": False, "error_class": "FrameCorrupt", "error": "x"}
            ),
            wire.FrameCorrupt,
        )


# ----------------------------------------------------------------------
# seq stamping: the duplicated/reordered-reply defense
# ----------------------------------------------------------------------
class TestSeqEcho:
    def test_check_seq_passes_on_echo_and_absence(self):
        assert transport._check_seq({"ok": True, "seq": 9}, 9)["ok"]
        # a peer that predates the field: absence is not a desync
        assert transport._check_seq({"ok": True}, 9)["ok"]

    def test_check_seq_raises_on_mismatch(self):
        with pytest.raises(ConnectionError, match="desync"):
            transport._check_seq({"ok": True, "seq": 8}, 9)

    def test_replies_echo_seq_end_to_end(self):
        srv, port = start_echo()
        try:
            t = transport.TcpTransport("127.0.0.1", port, coalesce=False)
            reply = t.request(
                {"op": "infer", "value": np.ones(4, np.float32)}, 5.0
            )
            assert isinstance(reply.get("seq"), int)
            t.close()
        finally:
            srv.shutdown()
            srv.server_close()

    def test_duplicated_reply_desyncs_then_recovers(self):
        # a dup'd request frame makes the server answer twice, leaving
        # a stale extra reply in the socket.  Two independent defenses
        # race to catch it — which one wins depends on whether the
        # stale bytes land before the next checkout:
        #   * pool staleness probe: readable-while-idle => the poisoned
        #     socket is discarded and the request rides a fresh dial
        #   * seq echo: the stale reply is read => typed "desync" error
        #     and the socket is dropped
        # Either way the invariant is the same: NEVER a wrong result,
        # one of the defenses provably fired, and the request after
        # that succeeds on a clean socket.
        srv, port = start_echo()
        fired = []

        def dup_once(parts):
            if not fired:
                fired.append(True)
                return list(parts) + [bytes(p) for p in parts]
            return parts

        t = transport.TcpTransport("127.0.0.1", port, coalesce=False)
        try:
            x = np.ones(4, np.float32)
            stale_before = metrics.counter("wire.pool.stale").value
            wire.set_send_tap(dup_once)
            try:
                reply = t.request({"op": "infer", "value": x}, 5.0)
                np.testing.assert_array_equal(reply["result"], x * 2)
                desynced = False
                try:
                    reply = t.request({"op": "infer", "value": x}, 5.0)
                except ConnectionError as exc:
                    desynced = True
                    assert "desync" in str(exc)
                else:
                    np.testing.assert_array_equal(reply["result"], x * 2)
            finally:
                wire.set_send_tap(None)
            probed = metrics.counter("wire.pool.stale").value > stale_before
            assert desynced or probed, (
                "duplicated reply was neither desync-detected nor "
                "discarded by the pool staleness probe"
            )
            reply = t.request({"op": "infer", "value": x}, 5.0)
            np.testing.assert_array_equal(reply["result"], x * 2)
        finally:
            t.close()
            srv.shutdown()
            srv.server_close()


# ----------------------------------------------------------------------
# injected network faults on the shm lane (ISSUE-14 satellite): ring,
# spill side-channel, and the shm->tcp fallback path all detect
# corruption typed — zero silent wrong answers
# ----------------------------------------------------------------------
class TestShmLaneFaults:
    def _plan(self, **rule_kw):
        return inject.FaultPlan().add("faultnet.tx", **rule_kw)

    def test_ring_corrupt_frame_is_detected_not_decoded(self):
        from sparkdl_tpu.serving import faultnet

        srv, port = start_echo()
        t = transport.ShmTransport("127.0.0.1", port)
        try:
            x = np.arange(16, dtype=np.float32)
            reply = t.request({"op": "infer", "value": x}, 5.0)
            np.testing.assert_array_equal(reply["result"], x * 2)
            before = metrics.counter("wire.crc_fail").value
            with inject.active_plan(self._plan(act="corrupt_body", at=1)):
                assert faultnet.arm()
                try:
                    with pytest.raises(
                        (ConnectionError, OSError, socket.timeout)
                    ):
                        t.request({"op": "infer", "value": x}, 2.0)
                finally:
                    faultnet.disarm()
            assert metrics.counter("wire.crc_fail").value > before
            # the lane heals: a fresh channel serves clean traffic
            reply = t.request({"op": "infer", "value": x}, 5.0)
            np.testing.assert_array_equal(reply["result"], x * 2)
        finally:
            t.close()
            srv.shutdown()
            srv.server_close()
        assert my_shm_entries() == []

    def test_spill_lane_corrupt_frame_is_detected(self):
        from sparkdl_tpu.serving import faultnet

        srv, port = start_echo()
        t = transport.ShmTransport("127.0.0.1", port)
        try:
            big = np.ones((700, 700), np.float32)  # > 1MB ring: spills
            reply = t.request({"op": "infer", "value": big}, 15.0)
            np.testing.assert_array_equal(reply["result"], big * 2)
            before = metrics.counter("wire.crc_fail").value
            with inject.active_plan(self._plan(act="corrupt_body", at=1)):
                assert faultnet.arm()
                try:
                    with pytest.raises(
                        (ConnectionError, OSError, socket.timeout)
                    ):
                        t.request({"op": "infer", "value": big}, 2.0)
                finally:
                    faultnet.disarm()
            assert metrics.counter("wire.crc_fail").value > before
        finally:
            t.close()
            srv.shutdown()
            srv.server_close()
        assert my_shm_entries() == []

    def test_fallback_tcp_lane_detects_corruption_too(self):
        from sparkdl_tpu.serving import faultnet

        srv, port = start_echo(allow_shm=False)  # forces shm->tcp fall
        t = transport.ShmTransport("127.0.0.1", port)
        try:
            x = np.ones(4, np.float32)
            reply = t.request({"op": "infer", "value": x}, 5.0)
            np.testing.assert_array_equal(reply["result"], x * 2)
            assert t.lane == "tcp"
            before = metrics.counter("wire.crc_fail").value
            with inject.active_plan(self._plan(act="corrupt_body", at=1)):
                assert faultnet.arm()
                try:
                    with pytest.raises(
                        (ConnectionError, OSError, socket.timeout)
                    ):
                        t.request({"op": "infer", "value": x}, 2.0)
                finally:
                    faultnet.disarm()
            assert metrics.counter("wire.crc_fail").value > before
            reply = t.request({"op": "infer", "value": x}, 5.0)
            np.testing.assert_array_equal(reply["result"], x * 2)
        finally:
            t.close()
            srv.shutdown()
            srv.server_close()
