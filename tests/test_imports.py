"""Every module imports; every advertised export resolves.

Guard for the round-1 failure class: ``sparkdl_tpu/__init__.py`` advertised
``registerKerasImageUDF`` while the implementing module did not exist, so
the package façade raised ``ModuleNotFoundError`` on first use.  Lazy (PEP
562) exports make that mistake silent until touched — so touch everything.
"""

import importlib
import pkgutil

import sparkdl_tpu


# plain ctypes shared libraries (loaded via CDLL, not importable as
# CPython extension modules) that pkgutil sees as modules
_CTYPES_LIBS = {
    "sparkdl_tpu.native._batchpack",
    "sparkdl_tpu.native._pjrt_runner",
}


def test_every_module_imports():
    failures = []
    for info in pkgutil.walk_packages(
        sparkdl_tpu.__path__,
        prefix="sparkdl_tpu.",
        # a subpackage __init__ that fails to import would otherwise have
        # its whole subtree silently skipped during the walk's recursion
        onerror=lambda name: failures.append(f"{name}: walk failed"),
    ):
        if info.name in _CTYPES_LIBS:
            continue
        try:
            importlib.import_module(info.name)
        except Exception as exc:  # noqa: BLE001 - collect all failures
            failures.append(f"{info.name}: {type(exc).__name__}: {exc}")
    assert not failures, "unimportable modules:\n" + "\n".join(failures)


def test_every_advertised_export_resolves():
    for name in sparkdl_tpu.__all__:
        obj = getattr(sparkdl_tpu, name)
        assert obj is not None, name


def test_dir_covers_exports():
    assert set(sparkdl_tpu.__all__) <= set(dir(sparkdl_tpu))


def test_data_package_public_api():
    """The input-pipeline package exports its full surface, and the
    top-level façade re-exports the Dataset entry point."""
    from sparkdl_tpu import data

    for name in data.__all__:
        assert getattr(data, name) is not None, name
    assert sparkdl_tpu.Dataset is data.Dataset
