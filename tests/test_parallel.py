"""Distributed trainer tests on the virtual 8-device CPU mesh.

What the reference never had (SURVEY.md §4 "Implication"): real multi-device
DP tests — the mesh here is the 8-way CPU platform from conftest, exercising
the same shard_map + lax.pmean path that runs over ICI on a pod.
"""

import numpy as np
import jax
import jax.numpy as jnp
import optax
import pytest

from flax import linen as nn

from sparkdl_tpu.parallel import (
    init_train_state,
    make_mesh,
    make_train_step,
    shard_batch,
)


class TinyNet(nn.Module):
    @nn.compact
    def __call__(self, x):
        x = nn.Dense(16)(x)
        x = nn.relu(x)
        return nn.Dense(4)(x)


@pytest.fixture(scope="module")
def mesh():
    assert len(jax.devices()) == 8, "conftest must provide 8 virtual devices"
    return make_mesh()


def test_dp_training_decreases_loss(mesh):
    module = TinyNet()
    rng = np.random.RandomState(0)
    params = module.init(jax.random.PRNGKey(0), jnp.zeros((1, 8)))

    def loss_fn(params, batch):
        logits = module.apply(params, batch["x"])
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, batch["y"]
        ).mean()

    tx = optax.adam(1e-2)
    state = init_train_state(params, tx)
    step = make_train_step(loss_fn, tx, mesh)

    x = rng.randn(64, 8).astype(np.float32)
    y = (x.sum(axis=1) > 0).astype(np.int32)
    batch = shard_batch({"x": jnp.asarray(x), "y": jnp.asarray(y)}, mesh)

    losses = []
    for _ in range(30):
        state, loss = step(state, batch)
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.5, losses[:3] + losses[-3:]
    assert int(state.step) == 30


def test_dp_grads_match_single_device(mesh):
    """DP over 8 shards must equal full-batch single-device gradients —
    the correctness invariant of pmean-allreduce data parallelism."""
    module = TinyNet()
    params = module.init(jax.random.PRNGKey(1), jnp.zeros((1, 8)))
    rng = np.random.RandomState(1)
    x = rng.randn(32, 8).astype(np.float32)
    y = rng.randint(0, 4, size=(32,)).astype(np.int32)

    def loss_fn(p, batch):
        logits = module.apply(p, batch["x"])
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, batch["y"]
        ).mean()

    tx = optax.sgd(0.1)
    # one DP step
    state = init_train_state(jax.tree_util.tree_map(jnp.copy, params), tx)
    step = make_train_step(loss_fn, tx, mesh, donate=False)
    batch = shard_batch({"x": jnp.asarray(x), "y": jnp.asarray(y)}, mesh)
    dp_state, dp_loss = step(state, batch)

    # single-device oracle
    loss, grads = jax.value_and_grad(loss_fn)(
        params, {"x": jnp.asarray(x), "y": jnp.asarray(y)}
    )
    updates, _ = tx.update(grads, tx.init(params), params)
    want = optax.apply_updates(params, updates)

    np.testing.assert_allclose(float(dp_loss), float(loss), rtol=1e-5)
    for a, b in zip(
        jax.tree_util.tree_leaves(dp_state.params),
        jax.tree_util.tree_leaves(want),
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-4,
                                   atol=1e-6)


def test_dp_inference_shards_batches_across_mesh(monkeypatch):
    """The transformer runtime's data-parallel inference: params replicated
    over the local-device mesh, batch leading dim sharded — output must be
    invariant to whether the mesh is used (the inference analog of the DP
    gradient invariant above)."""
    from sparkdl_tpu.transformers import utils as tu

    rng = np.random.RandomState(2)
    w = rng.randn(8, 5).astype(np.float32)
    # 37 rows: exercises the padded ragged final chunk under sharding
    data = rng.randn(37, 8).astype(np.float32)

    def run():
        params = tu.place_params({"w": jnp.asarray(w)})
        fn = jax.jit(lambda x: jnp.tanh(x @ params["w"]))
        return tu.run_batched(fn, data, batch_size=10), params

    # the mesh decision is process-cached (placement at stage-build time and
    # batch placement at call time must agree), so reset around env flips
    monkeypatch.delenv("SPARKDL_INFERENCE_DEVICES", raising=False)
    tu._reset_data_parallel_mesh_for_testing()
    try:
        mesh = tu.data_parallel_mesh()
        assert mesh is not None and int(mesh.devices.size) == 8

        out_dp, params_dp = run()
        # params actually replicated across all 8 devices
        assert len(params_dp["w"].sharding.device_set) == 8

        monkeypatch.setenv("SPARKDL_INFERENCE_DEVICES", "1")
        # without a reset the cached decision stays: registration-time and
        # call-time placements keep agreeing even if the env var drifts
        assert tu.data_parallel_mesh() is mesh
        tu._reset_data_parallel_mesh_for_testing()
        out_single, params_single = run()
        assert len(params_single["w"].sharding.device_set) == 1
    finally:
        tu._reset_data_parallel_mesh_for_testing()

    assert out_dp.shape == (37, 5)
    np.testing.assert_allclose(out_dp, out_single, rtol=1e-6, atol=1e-7)
    np.testing.assert_allclose(out_dp, np.tanh(data @ w), rtol=1e-5,
                               atol=1e-6)


def test_graft_dryrun_multichip():
    # conftest already provides the 8-device CPU platform in-process; the
    # subprocess isolation itself is covered by tests/test_graft_contract.py.
    import __graft_entry__ as graft

    graft._dryrun_multichip_inproc(8)


def test_graft_entry_lowers():
    import __graft_entry__ as graft

    fn, args = graft.entry()
    lowered = jax.jit(fn).lower(*args)  # lowering succeeded; full compile
    assert lowered.out_info is not None  # is the driver's job
