"""Metrics/profiling subsystem tests (SURVEY.md §5.1/§5.5 — the
observability the reference lacked)."""

import glob
import os
import threading

import numpy as np
import pytest

import jax.numpy as jnp

from sparkdl_tpu.transformers.utils import device_resize, run_batched
from sparkdl_tpu.utils import profiler
from sparkdl_tpu.utils.metrics import MetricsRegistry, metrics
import importlib

metrics_mod = importlib.import_module("sparkdl_tpu.utils.metrics")


def test_counter_and_timer_accumulate():
    reg = MetricsRegistry()
    reg.counter("c").add(3)
    reg.counter("c").add(2)
    assert reg.counter("c").value == 5
    assert reg.counter("c").updates == 2
    with reg.timer("t").time():
        pass
    snap = reg.snapshot()
    assert snap["c"] == 5
    assert snap["t.seconds"] >= 0
    reg.reset()
    assert reg.snapshot() == {}


def test_snapshot_prefix_filters_by_subsystem():
    reg = MetricsRegistry()
    reg.counter("serving.requests").add(4)
    reg.gauge("serving.queue_depth.m").set(1)
    reg.timer("data.producer_busy").add_seconds(0.5)
    reg.histogram("data.device_stall_ms").observe(2.0)
    serving = reg.snapshot(prefix="serving.")
    assert serving == {
        "serving.requests": 4.0,
        "serving.queue_depth.m": 1.0,
    }
    data = reg.snapshot(prefix="data.")
    assert data["data.producer_busy.seconds"] == 0.5
    assert data["data.device_stall_ms.count"] == 1.0
    assert "serving.requests" not in data
    # no prefix -> everything, same keys
    assert set(reg.snapshot()) == set(serving) | set(data)


def test_collect_is_the_typed_registry_view():
    """collect() is the sanctioned enumeration for exporters: live
    metric objects keyed by kind, insulated from later registrations."""
    reg = MetricsRegistry()
    c = reg.counter("data.rows_out")
    t = reg.timer("data.producer_busy")
    g = reg.gauge("data.queue_depth")
    h = reg.histogram("data.device_stall_ms")
    view = reg.collect()
    assert view["counters"]["data.rows_out"] is c
    assert view["timers"]["data.producer_busy"] is t
    assert view["gauges"]["data.queue_depth"] is g
    assert view["histograms"]["data.device_stall_ms"] is h
    # the view is a copy of the name->metric maps: registering after
    # collect() must not mutate an exporter's in-flight iteration
    reg.counter("data.decode_errors")
    assert "data.decode_errors" not in view["counters"]
    # but the objects stay live — updates through them are visible
    c.add(7)
    assert view["counters"]["data.rows_out"].value == 7


def test_gauge_set_add_and_snapshot():
    reg = MetricsRegistry()
    g = reg.gauge("depth")
    g.set(5)
    g.add(-2)
    assert reg.gauge("depth").value == 3
    assert reg.snapshot()["depth"] == 3
    reg.reset()
    assert reg.snapshot() == {}


def test_histogram_quantiles_and_lifetime_stats():
    reg = MetricsRegistry()
    h = reg.histogram("lat")
    for v in range(1, 101):  # 1..100
        h.observe(float(v))
    assert h.count == 100
    assert h.total == sum(range(1, 101))
    assert h.mean == pytest.approx(50.5)
    assert h.quantile(0.0) == 1.0
    assert h.quantile(1.0) == 100.0
    assert h.quantile(0.5) == pytest.approx(50.5)
    assert h.quantile(0.95) == pytest.approx(95.05)
    snap = reg.snapshot()
    assert snap["lat.count"] == 100
    assert snap["lat.p50"] == pytest.approx(50.5)
    assert snap["lat.p95"] <= snap["lat.p99"]


def test_histogram_sliding_window_vs_lifetime():
    # quantiles reflect the recent window; count/mean are lifetime
    reg = MetricsRegistry()
    h = reg.histogram("w", window=4)
    for v in (1000.0, 1000.0, 1.0, 2.0, 3.0, 4.0):
        h.observe(v)
    assert h.count == 6  # lifetime
    assert h.quantile(1.0) == 4.0  # the 1000s rolled out of the window


def test_empty_histogram_not_exported():
    reg = MetricsRegistry()
    h = reg.histogram("never")
    assert h.quantile(0.5) is None
    assert "never.count" not in reg.snapshot()


def test_histogram_exemplar_is_windowed_max():
    reg = MetricsRegistry()
    h = reg.histogram("lat", window=4)
    assert h.exemplar() is None
    h.observe(50.0)           # no exemplar attached
    assert h.exemplar() is None
    h.observe(9.0, exemplar=111)
    h.observe(30.0, exemplar=222)
    h.observe(12.0, exemplar=333)
    # of the exemplar-carrying samples, the largest value wins
    assert h.exemplar() == (30.0, 222)
    # the window slides: two more samples roll 50.0 and 111 out
    h.observe(1.0, exemplar=444)
    h.observe(2.0, exemplar=555)
    assert h.exemplar() == (30.0, 222)
    h.observe(3.0)  # now 222 itself rolled out; 333 is the window max
    assert h.exemplar() == (12.0, 333)


def test_histogram_exemplar_in_snapshot_exact_int():
    reg = MetricsRegistry()
    # trace ids are 63-bit: the snapshot must carry them as exact ints
    # (a float cast silently corrupts the low bits)
    big = (1 << 62) + 12345
    reg.histogram("lat").observe(7.5, exemplar=big)
    snap = reg.snapshot()
    assert snap["lat.exemplar_value"] == 7.5
    assert snap["lat.exemplar_trace_id"] == big
    assert isinstance(snap["lat.exemplar_trace_id"], int)


def test_histogram_without_exemplars_has_no_snapshot_keys():
    reg = MetricsRegistry()
    reg.histogram("lat").observe(7.5)
    snap = reg.snapshot()
    assert "lat.exemplar_value" not in snap
    assert "lat.exemplar_trace_id" not in snap


def test_counters_thread_safe():
    reg = MetricsRegistry()

    def bump():
        for _ in range(1000):
            reg.counter("x").add(1)

    threads = [threading.Thread(target=bump) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert reg.counter("x").value == 8000


def test_run_batched_advances_row_counter():
    before = metrics.counter("sparkdl.rows_processed").value
    before_s = metrics.timer("sparkdl.forward").seconds
    x = np.random.RandomState(0).randn(10, 4).astype(np.float32)
    run_batched(lambda a: a * 2.0, x, batch_size=4)
    assert metrics.counter("sparkdl.rows_processed").value == before + 10
    assert metrics.timer("sparkdl.forward").seconds > before_s
    assert metrics.images_per_sec() is not None


def test_device_resize_advances_stage_metrics():
    before = metrics.timer("sparkdl.resize").entries
    imgs = [np.zeros((6, 7, 3), np.float32), np.zeros((5, 4, 3), np.float32)]
    out = device_resize(imgs, (8, 8))
    assert out.shape == (2, 8, 8, 3)
    assert metrics.timer("sparkdl.resize").entries == before + 1


def test_image_transformer_advances_image_counter(tpu_session, image_dir):
    """The flagship image path advances the first-class image counter and
    the decode-stage timer (SURVEY.md §5.5 — images/sec as a real metric)."""
    from sparkdl_tpu.image import imageIO
    from sparkdl_tpu.transformers.named_image import DeepImagePredictor

    before = metrics.counter("sparkdl.images_processed").value
    before_decode = metrics.timer("sparkdl.decode").entries
    df = imageIO.readImages(image_dir, tpu_session, numPartitions=2)
    n = df.count()
    predictor = DeepImagePredictor(
        inputCol="image",
        outputCol="preds",
        modelName="MobileNetV2",
        modelWeights="random",
    )
    predictor.transform(df).collect()
    assert metrics.counter("sparkdl.images_processed").value == before + n
    assert metrics.timer("sparkdl.decode").entries > before_decode


def test_trace_is_reentrant_safe(tmp_path):
    with profiler.trace(str(tmp_path / "outer")):
        # nested trace degrades to a no-op instead of raising
        with profiler.trace(str(tmp_path / "inner")):
            jnp.ones((4,)).sum().block_until_ready()


def test_profiler_trace_writes_capture(tmp_path):
    log_dir = str(tmp_path / "trace")
    with profiler.trace(log_dir):
        with profiler.annotate("tiny_op"):
            jnp.ones((8, 8)).sum().block_until_ready()
    written = glob.glob(os.path.join(log_dir, "**", "*"), recursive=True)
    assert any(os.path.isfile(p) for p in written), written


def test_maybe_trace_env_gate(tmp_path, monkeypatch):
    # off by default: no-op context
    monkeypatch.delenv("SPARKDL_PROFILE_DIR", raising=False)
    with profiler.maybe_trace():
        pass
    # on when env var set
    log_dir = str(tmp_path / "envtrace")
    monkeypatch.setenv("SPARKDL_PROFILE_DIR", log_dir)
    with profiler.maybe_trace():
        jnp.zeros((4,)).sum().block_until_ready()
    written = glob.glob(os.path.join(log_dir, "**", "*"), recursive=True)
    assert any(os.path.isfile(p) for p in written), written


class TestMFU:
    """MFU helpers (VERDICT r2 #9): XLA-cost-model FLOPs / peak."""

    def test_compiled_flops_exact_for_matmul(self):
        import jax

        f = jax.jit(lambda a, b: a @ b)
        a = jnp.zeros((128, 64), jnp.float32)
        b = jnp.zeros((64, 32), jnp.float32)
        flops = metrics_mod.compiled_flops(f.lower(a, b).compile())
        # CPU backend may not expose cost analysis; when it does, the
        # matmul count is exact: 2*M*N*K
        if flops is not None:
            assert flops == 2 * 128 * 32 * 64

    def test_peak_flops_known_tpu_kinds(self):
        class FakeDev:
            def __init__(self, kind):
                self.device_kind = kind

        assert metrics_mod.peak_flops_per_sec(FakeDev("TPU v5 lite")) == 197e12
        assert metrics_mod.peak_flops_per_sec(FakeDev("TPU v4")) == 275e12
        assert metrics_mod.peak_flops_per_sec(FakeDev("cpu")) is None

    def test_mfu_composes_and_handles_unknown(self):
        class FakeDev:
            device_kind = "TPU v5e"

        # 197e12 flops in 2s on a 197e12-peak chip -> 0.5
        assert metrics_mod.mfu(197e12, 2.0, FakeDev()) == pytest.approx(0.5)
        assert metrics_mod.mfu(None, 1.0, FakeDev()) is None

        class Unknown:
            device_kind = "cpu"

        assert metrics_mod.mfu(1e12, 1.0, Unknown()) is None


def test_paired_trials_interleaves_and_summarizes():
    """benchlib.paired_trials: A/B interleaving within rounds (drift
    robustness), median + IQR per label."""
    from sparkdl_tpu.utils.benchlib import paired_trials

    calls = []
    trials = paired_trials(
        {
            "a": lambda: calls.append("a") or float(len(calls)),
            "b": lambda: calls.append("b") or float(len(calls)),
        },
        k=3,
    )
    # strict interleaving: a,b,a,b,a,b — each round runs every label once
    assert calls == ["a", "b", "a", "b", "a", "b"]
    assert trials["a"]["samples"] == [1.0, 3.0, 5.0]
    assert trials["b"]["samples"] == [2.0, 4.0, 6.0]
    assert trials["a"]["median"] == 3.0 and trials["b"]["median"] == 4.0
    lo, hi = trials["a"]["iqr"]
    assert lo <= trials["a"]["median"] <= hi


# ---------------------------------------------------------------------------
# Histogram — empty sliding window must not fabricate quantiles
# ---------------------------------------------------------------------------


def test_histogram_quantiles_none_when_empty():
    from sparkdl_tpu.utils.metrics import Histogram

    h = Histogram("t.empty")
    assert h.quantile(0.5) is None
    assert h.quantile(0.95) is None
    assert h.quantile(0.99) is None
    assert h.mean is None and h.count == 0


def test_snapshot_skips_empty_histogram():
    """An empty histogram contributes nothing — no p50/p95/p99 keys, no
    zero-count placeholders (a dashboard reading 0ms p99 would be a lie)."""
    r = MetricsRegistry()
    r.histogram("t.lat")
    snap = r.snapshot()
    assert not any(k.startswith("t.lat") for k in snap)
    r.histogram("t.lat").observe(5.0)
    snap = r.snapshot()
    assert snap["t.lat.count"] == 1.0
    for q in ("p50", "p95", "p99"):
        assert snap[f"t.lat.{q}"] == 5.0


def test_histogram_quantile_rejects_out_of_range():
    from sparkdl_tpu.utils.metrics import Histogram

    h = Histogram("t.range")
    with pytest.raises(ValueError, match="quantile"):
        h.quantile(1.5)


def test_timer_add_seconds_accumulates():
    from sparkdl_tpu.utils.metrics import Timer

    t = Timer("t.ext")
    t.add_seconds(0.25)
    t.add_seconds(0.75)
    assert t.seconds == 1.0 and t.entries == 2


def test_cpu_scale_shrinks_featurizer_workload(monkeypatch):
    """benchlib CPU-fallback scaling (the r05-r09 bench wedge fix):
    explicit > env > auto-detect precedence, and the scaled workload
    keeps scan >= 2 so the anti-caching methodology survives."""
    from sparkdl_tpu.utils import benchlib

    # identity below/at 1
    assert benchlib.scale_featurizer_workload(512, 24, 3, 1) == (512, 24, 3)
    # the headline shape at the default CPU scale: small but still a
    # real scan over distinct batches
    b, s, r = benchlib.scale_featurizer_workload(512, 24, 3, 32)
    assert b == 16 and s >= 2 and r == 2
    # never degenerates to zero
    b, s, r = benchlib.scale_featurizer_workload(1, 2, 1, 1000)
    assert b >= 1 and s >= 2 and r >= 1

    # precedence: explicit beats env beats auto
    monkeypatch.setenv(benchlib.CPU_SCALE_ENV, "7")
    assert benchlib.resolve_cpu_scale(3) == 3
    assert benchlib.resolve_cpu_scale() == 7
    monkeypatch.delenv(benchlib.CPU_SCALE_ENV)
    # this environment is CPU-only, so auto-detect engages the default
    assert benchlib.resolve_cpu_scale() == benchlib.DEFAULT_CPU_SCALE
