"""Per-tenant fair-share admission tests (ISSUE-12).

The contract under test: with a :class:`TenantPolicy` attached, the
admission queue drains per-tenant FIFOs by deficit round robin (service
tracks *weights*, not arrival order), one tenant's burst cannot starve
another (tenant B's latency stays bounded while tenant A saturates the
queue), and the two shed layers — global ``ServerOverloaded`` and
per-tenant :class:`TenantThrottled` — fire only at ``offer`` time:
an admitted request's future ALWAYS resolves.
"""

import time

import numpy as np
import pytest

from sparkdl_tpu.utils.metrics import metrics
from sparkdl_tpu.serving import ModelServer, ServingConfig
from sparkdl_tpu.serving.admission import (
    AdmissionQueue,
    Request,
    TenantPolicy,
)
from sparkdl_tpu.serving.errors import (
    ServerOverloaded,
    TenantThrottled,
)


def req(tenant=None):
    return Request(value=np.zeros(4, np.float32), tenant=tenant)


# ----------------------------------------------------------------------
# policy
# ----------------------------------------------------------------------
class TestTenantPolicy:
    def test_unlisted_tenant_gets_default_weight(self):
        policy = TenantPolicy(weights={"a": 3.0}, default_weight=0.5)
        assert policy.weight("a") == 3.0
        assert policy.weight("nobody") == 0.5

    def test_rejects_nonpositive_knobs(self):
        with pytest.raises(ValueError):
            TenantPolicy(weights={"a": 0.0})
        with pytest.raises(ValueError):
            TenantPolicy(default_weight=-1.0)
        with pytest.raises(ValueError):
            TenantPolicy(inflight_cap=0)

    def test_from_env_parses_weights_and_cap(self, monkeypatch):
        monkeypatch.setenv("SPARKDL_TENANT_WEIGHTS", "a:3, b:1, c")
        monkeypatch.setenv("SPARKDL_TENANT_INFLIGHT", "16")
        monkeypatch.setenv("SPARKDL_TENANT_DEFAULT_WEIGHT", "2.0")
        policy = TenantPolicy.from_env()
        assert policy.weights == {"a": 3.0, "b": 1.0, "c": 1.0}
        assert policy.inflight_cap == 16
        assert policy.default_weight == 2.0

    def test_from_env_is_none_without_knobs(self, monkeypatch):
        monkeypatch.delenv("SPARKDL_TENANT_WEIGHTS", raising=False)
        monkeypatch.delenv("SPARKDL_TENANT_INFLIGHT", raising=False)
        assert TenantPolicy.from_env() is None

    def test_from_env_cap_only(self, monkeypatch):
        monkeypatch.delenv("SPARKDL_TENANT_WEIGHTS", raising=False)
        monkeypatch.setenv("SPARKDL_TENANT_INFLIGHT", "4")
        policy = TenantPolicy.from_env()
        assert policy.inflight_cap == 4
        assert policy.weights == {}


# ----------------------------------------------------------------------
# deficit round robin
# ----------------------------------------------------------------------
class TestDeficitRoundRobin:
    def test_equal_weights_interleave(self):
        q = AdmissionQueue(64, tenant_policy=TenantPolicy())
        for _ in range(3):
            q.offer(req("a"))
            q.offer(req("b"))
        order = [r.tenant for r in q.take(6, 0.01)]
        assert order == ["a", "b", "a", "b", "a", "b"]

    def test_weights_shape_service_ratio(self):
        q = AdmissionQueue(
            64, tenant_policy=TenantPolicy(weights={"a": 2.0, "b": 1.0})
        )
        for _ in range(6):
            q.offer(req("a"))
        for _ in range(3):
            q.offer(req("b"))
        order = [r.tenant for r in q.take(9, 0.01)]
        # weight 2 vs 1: two a's per b, the whole way down
        assert order == ["a", "a", "b", "a", "a", "b", "a", "a", "b"]

    def test_fractional_weight_banks_credit(self):
        # weight 0.5 serves every OTHER ring visit — the deficit banks
        q = AdmissionQueue(
            64, tenant_policy=TenantPolicy(weights={"a": 1.0, "b": 0.5})
        )
        for _ in range(4):
            q.offer(req("a"))
            q.offer(req("b"))
        order = [r.tenant for r in q.take(8, 0.01)]
        assert order.count("a") == 4 and order.count("b") == 4
        # first three pops: a, (b banks 0.5, moves on) a, then b's
        # second visit reaches 1.0
        assert order[:3] == ["a", "a", "b"]

    def test_burst_cannot_starve_other_tenant(self):
        q = AdmissionQueue(512, tenant_policy=TenantPolicy())
        for _ in range(100):
            q.offer(req("a"))  # the burst arrives first...
        for _ in range(5):
            q.offer(req("b"))  # ...the small tenant queues behind it
        order = [r.tenant for r in q.take(100, 0.01)]
        # strict FIFO would put b's first request at position 100;
        # DRR interleaves it in immediately
        assert order.index("b") <= 2
        assert [t for t in order[:10]].count("b") >= 4

    def test_untenanted_queue_is_plain_fifo(self):
        q = AdmissionQueue(64)
        first, second = req(), req()
        q.offer(first)
        q.offer(second)
        assert q.take(2, 0.01) == [first, second]
        assert q.tenant_policy is None


# ----------------------------------------------------------------------
# the two shed layers
# ----------------------------------------------------------------------
class TestTenantThrottling:
    def test_inflight_cap_sheds_typed(self):
        q = AdmissionQueue(
            64, tenant_policy=TenantPolicy(inflight_cap=2)
        )
        q.offer(req("a"))
        q.offer(req("a"))
        with pytest.raises(TenantThrottled):
            q.offer(req("a"))
        # another tenant is untouched by a's cap
        q.offer(req("b"))

    def test_tenant_throttled_is_a_server_overloaded(self):
        # subclassing keeps every existing shed/retry classification:
        # a front-end that backs off on overload needs no new case
        assert issubclass(TenantThrottled, ServerOverloaded)

    def test_cap_releases_when_future_resolves(self):
        q = AdmissionQueue(
            64, tenant_policy=TenantPolicy(inflight_cap=1)
        )
        first = req("a")
        q.offer(first)
        with pytest.raises(TenantThrottled):
            q.offer(req("a"))
        # queued-but-unresolved still holds the slot
        (taken,) = q.take(1, 0.01)
        with pytest.raises(TenantThrottled):
            q.offer(req("a"))
        taken.future.set_result(None)
        q.offer(req("a"))  # slot released by the done callback

    def test_global_capacity_still_sheds_overloaded(self):
        q = AdmissionQueue(
            1, tenant_policy=TenantPolicy(inflight_cap=10)
        )
        q.offer(req("a"))
        with pytest.raises(ServerOverloaded) as exc_info:
            q.offer(req("b"))
        assert not isinstance(exc_info.value, TenantThrottled)

    def test_offer_wait_blocks_on_tenant_cap(self):
        q = AdmissionQueue(
            64, tenant_policy=TenantPolicy(inflight_cap=1)
        )
        q.offer(req("a"))
        assert q.offer_wait(req("a"), timeout_s=0.05) is False
        (taken,) = q.take(1, 0.01)
        taken.future.set_result(None)
        assert q.offer_wait(req("a"), timeout_s=5.0) is True

    def test_tenant_metrics_emitted_in_tenanted_mode(self):
        before_admitted = metrics.counter("tenant.a.admitted").value
        before_throttled = metrics.counter("tenant.a.throttled").value
        q = AdmissionQueue(
            64, tenant_policy=TenantPolicy(inflight_cap=1)
        )
        q.offer(req("a"))
        with pytest.raises(TenantThrottled):
            q.offer(req("a"))
        assert metrics.counter(
            "tenant.a.admitted"
        ).value == before_admitted + 1
        assert metrics.counter(
            "tenant.a.throttled"
        ).value == before_throttled + 1

    def test_tenants_snapshot(self):
        q = AdmissionQueue(
            64, tenant_policy=TenantPolicy(weights={"a": 3.0})
        )
        q.offer(req("a"))
        q.offer(req("b"))
        snap = q.tenants()
        assert snap["a"] == {"queued": 1, "inflight": 1, "weight": 3.0}
        assert snap["b"]["weight"] == 1.0


# ----------------------------------------------------------------------
# end to end through a ModelServer (the replica-side enforcement point)
# ----------------------------------------------------------------------
def make_tenant_server(tenant_policy, forward_sleep_s=0.0, **config_kw):
    cfg = ServingConfig(**{
        "max_batch": 4, "max_wait_ms": 1.0, "queue_capacity": 512,
        "tenant_policy": tenant_policy,
        **config_kw,
    })
    server = ModelServer(cfg)

    def forward(x):
        if forward_sleep_s:
            time.sleep(forward_sleep_s)
        return np.asarray(x) * 2.0

    server.register("ep", forward, item_shape=(4,), compile=False)
    return server


class TestTenantFairnessEndToEnd:
    def test_saturating_burst_keeps_other_tenants_p99_bounded(self):
        # tenant A floods 240 requests into the queue, then tenant B
        # sends 12: under strict FIFO, B's completions would land at
        # the very end of the drain; under DRR they interleave from the
        # first batch, so B's p99 stays well inside A's drain time —
        # the SLO the fairness satellite asserts
        policy = TenantPolicy()
        with make_tenant_server(policy, forward_sleep_s=0.002) as server:
            x = np.ones(4, np.float32)
            t0 = time.monotonic()
            a_futures = [
                server.submit(x, model_id="ep", tenant="a")
                for _ in range(240)
            ]
            b_futures = [
                server.submit(x, model_id="ep", tenant="b")
                for _ in range(12)
            ]
            b_done = [
                (f.result(timeout=60), time.monotonic() - t0)[1]
                for f in b_futures
            ]
            a_done = [
                (f.result(timeout=60), time.monotonic() - t0)[1]
                for f in a_futures
            ]
            b_p99 = sorted(b_done)[-1]
            a_p99 = sorted(a_done)[-1]
            # B finished while most of A's backlog was still queued
            assert b_p99 < 0.5 * a_p99, (b_p99, a_p99)

    def test_throttled_tenant_never_loses_admitted_work(self):
        # beyond its cap, tenant A's offers shed typed — but every
        # future the server DID hand back must resolve with a result
        policy = TenantPolicy(inflight_cap=8)
        with make_tenant_server(policy, forward_sleep_s=0.001) as server:
            x = np.ones(4, np.float32)
            admitted, throttled = [], 0
            for _ in range(200):
                try:
                    admitted.append(
                        server.submit(x, model_id="ep", tenant="a")
                    )
                except TenantThrottled:
                    throttled += 1
            assert throttled > 0, "burst never hit the cap"
            assert admitted, "cap admitted nothing at all"
            for f in admitted:
                np.testing.assert_allclose(
                    np.asarray(f.result(timeout=60)), 2.0
                )

    def test_describe_surfaces_tenants(self):
        policy = TenantPolicy(weights={"a": 2.0})
        with make_tenant_server(policy) as server:
            x = np.ones(4, np.float32)
            server.predict(x, model_id="ep", tenant="a")
            desc = server.status()["endpoints"]["ep"]
            assert "a" in desc["tenants"]
            assert desc["tenants"]["a"]["weight"] == 2.0

    def test_untenanted_server_describe_has_no_tenants(self):
        with make_tenant_server(None) as server:
            x = np.ones(4, np.float32)
            server.predict(x, model_id="ep")
            desc = server.status()["endpoints"]["ep"]
            assert desc["tenants"] is None

    def test_policy_from_env_reaches_the_queue(self, monkeypatch):
        monkeypatch.setenv("SPARKDL_TENANT_INFLIGHT", "3")
        with make_tenant_server(None) as server:
            x = np.ones(4, np.float32)
            # cap 3 from env: an instant burst of 50 must shed some
            throttled = 0
            futures = []
            for _ in range(50):
                try:
                    futures.append(
                        server.submit(x, model_id="ep", tenant="a")
                    )
                except TenantThrottled:
                    throttled += 1
            assert throttled > 0
            for f in futures:
                f.result(timeout=60)
