"""Stage-persistence round-trip tests (save → load → identical output).

Reference pattern: Spark ML ``DefaultParamsWritable``/``Readable`` (the
reference used it only on its Scala featurizer — SURVEY.md §2); here every
stage persists via :mod:`sparkdl_tpu.ml.util`.  Each test saves a stage,
reloads it (through the class reader and/or the generic ``load_stage``),
and asserts the reloaded stage produces identical transform output.
"""

import os

import numpy as np
import pytest

import jax.numpy as jnp

from sparkdl_tpu.graph.function import XlaFunction
from sparkdl_tpu.ml.classification import (
    LogisticRegression,
    LogisticRegressionModel,
)
from sparkdl_tpu.ml.evaluation import MulticlassClassificationEvaluator
from sparkdl_tpu.ml.pipeline import Pipeline, PipelineModel
from sparkdl_tpu.ml.tuning import (
    CrossValidator,
    CrossValidatorModel,
    ParamGridBuilder,
)
from sparkdl_tpu.ml.util import load_metadata, load_stage
from sparkdl_tpu.transformers.tf_tensor import TFTransformer

keras = pytest.importorskip("keras")

from PIL import Image  # noqa: E402

from sparkdl_tpu.estimators import KerasImageFileEstimator  # noqa: E402
from sparkdl_tpu.transformers.keras_image import (  # noqa: E402
    KerasImageFileTransformer,
)


def loader_8x8(uri):
    """Module-level so it pickles by reference across save/load."""
    img = Image.open(uri).convert("RGB").resize((8, 8))
    return np.asarray(img, dtype=np.float32) / 255.0


def _double_fn():
    fn = XlaFunction.from_callable(
        lambda x: 2.0 * x, input_names=("x",), output_names=("y",),
        name="double",
    )
    fn.input_specs = [((4, 3), np.float32)]
    return fn


@pytest.fixture()
def vector_df(tpu_session):
    rng = np.random.RandomState(0)
    rows = []
    for i in range(12):
        label = i % 2
        center = np.full(3, 5.0 * label)
        rows.append(
            {
                "features": (center + rng.rand(3)).astype(np.float32),
                "label": label,
            }
        )
    return tpu_session.createDataFrame(rows)


def _collect_col(df, col):
    return [r[col] for r in df.collect()]


# ---------------------------------------------------------------------------
# Transformers
# ---------------------------------------------------------------------------


def test_tf_transformer_roundtrip(tpu_session, tmp_path):
    t = TFTransformer(
        tfInputGraph=_double_fn(),
        inputMapping={"x": "x"},
        outputMapping={"y": "doubled"},
        batchSize=4,
    )
    df = tpu_session.createDataFrame(
        [{"x": np.full(3, float(i), np.float32)} for i in range(5)]
    )
    want = [np.asarray(v) for v in _collect_col(t.transform(df), "doubled")]

    path = str(tmp_path / "tf_transformer")
    t.save(path)
    loaded = TFTransformer.load(path)
    assert loaded.uid == t.uid
    assert loaded.getOrDefault(loaded.batchSize) == 4
    assert loaded.getOrDefault(loaded.inputMapping) == {"x": "x"}
    got = [
        np.asarray(v) for v in _collect_col(loaded.transform(df), "doubled")
    ]
    np.testing.assert_allclose(np.stack(got), np.stack(want), rtol=1e-6)


def test_tf_image_transformer_roundtrip(image_df_p, tmp_path):
    from sparkdl_tpu.transformers.tf_image import TFImageTransformer

    fn = XlaFunction.from_callable(
        lambda x: jnp.mean(x, axis=(1, 2)),
        input_names=("images",),
        output_names=("means",),
        name="chanmean",
    )
    fn.input_specs = [((2, 16, 16, 3), np.float32)]
    t = TFImageTransformer(
        inputCol="image",
        outputCol="out",
        graph=fn,
        inputShape=(16, 16),
        channelOrder="RGB",
        batchSize=2,
    )
    want = _collect_col(t.transform(image_df_p), "out")

    path = str(tmp_path / "tf_image")
    t.save(path)
    loaded = TFImageTransformer.load(path)
    assert tuple(loaded.getOrDefault(loaded.inputShape)) == (16, 16)
    got = _collect_col(loaded.transform(image_df_p), "out")
    np.testing.assert_allclose(
        np.stack([np.asarray(v) for v in got]),
        np.stack([np.asarray(v) for v in want]),
        rtol=1e-5,
        atol=1e-6,
    )


@pytest.fixture()
def image_df_p(tpu_session, image_dir):
    from sparkdl_tpu.image import imageIO

    return imageIO.readImages(image_dir, tpu_session, numPartitions=2)


def test_keras_image_file_transformer_roundtrip(
    tpu_session, image_dir, tmp_path
):
    keras.utils.set_random_seed(0)
    model = keras.Sequential(
        [
            keras.layers.Input(shape=(8, 8, 3)),
            keras.layers.Flatten(),
            keras.layers.Dense(5),
        ]
    )
    model_path = str(tmp_path / "m.keras")
    model.save(model_path)

    from sparkdl_tpu.image.imageIO import filesToDF

    df = filesToDF(tpu_session, image_dir, numPartitions=2)
    t = KerasImageFileTransformer(
        inputCol="filePath",
        outputCol="feat",
        modelFile=model_path,
        imageLoader=loader_8x8,
        batchSize=4,
    )
    want = np.stack(
        [np.asarray(v) for v in _collect_col(t.transform(df), "feat")]
    )

    path = str(tmp_path / "kift")
    t.save(path)
    # the model file is copied INTO the bundle: original can disappear
    os.remove(model_path)
    loaded = KerasImageFileTransformer.load(path)
    assert loaded.getModelFile().startswith(path)
    got = np.stack(
        [np.asarray(v) for v in _collect_col(loaded.transform(df), "feat")]
    )
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_featurizer_roundtrip_random_weights(image_df_p, tmp_path):
    from sparkdl_tpu.transformers.named_image import DeepImageFeaturizer

    t = DeepImageFeaturizer(
        inputCol="image",
        outputCol="features",
        modelName="MobileNetV2",
        modelWeights="random",
        batchSize=4,
    )
    want = np.stack(
        [
            np.asarray(v)
            for v in _collect_col(t.transform(image_df_p), "features")
        ]
    )

    path = str(tmp_path / "featurizer")
    t.save(path)
    loaded = load_stage(path)  # generic reader resolves the class
    assert isinstance(loaded, DeepImageFeaturizer)
    assert loaded.getModelName() == "MobileNetV2"
    got = np.stack(
        [
            np.asarray(v)
            for v in _collect_col(loaded.transform(image_df_p), "features")
        ]
    )
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


# ---------------------------------------------------------------------------
# Models / estimators
# ---------------------------------------------------------------------------


def test_logistic_regression_model_roundtrip(vector_df, tmp_path):
    lr = LogisticRegression(maxIter=60, stepSize=0.2)
    model = lr.fit(vector_df)
    want = _collect_col(model.transform(vector_df), "prediction")

    path = str(tmp_path / "lr_model")
    model.save(path)
    loaded = LogisticRegressionModel.load(path)
    assert loaded.numClasses == model.numClasses
    np.testing.assert_allclose(
        np.asarray(loaded.weights), np.asarray(model.weights)
    )
    got = _collect_col(loaded.transform(vector_df), "prediction")
    assert got == want


def test_lr_estimator_roundtrip(vector_df, tmp_path):
    lr = LogisticRegression(maxIter=25, regParam=0.01, stepSize=0.3)
    path = str(tmp_path / "lr_est")
    lr.save(path)
    loaded = LogisticRegression.load(path)
    assert loaded.getOrDefault(loaded.maxIter) == 25
    assert loaded.getOrDefault(loaded.regParam) == pytest.approx(0.01)
    # and it still fits
    model = loaded.fit(vector_df)
    assert isinstance(model, LogisticRegressionModel)


def test_keras_image_file_estimator_roundtrip(
    tpu_session, image_dir, tmp_path
):
    keras.utils.set_random_seed(0)
    model = keras.Sequential(
        [
            keras.layers.Input(shape=(8, 8, 3)),
            keras.layers.Flatten(),
            keras.layers.Dense(2, activation="softmax"),
        ]
    )
    model_path = str(tmp_path / "tiny.keras")
    model.save(model_path)

    est = KerasImageFileEstimator(
        inputCol="filePath",
        outputCol="pred",
        labelCol="label",
        imageLoader=loader_8x8,
        modelFile=model_path,
        kerasOptimizer="adam",
        kerasLoss="sparse_categorical_crossentropy",
        kerasFitParams={"epochs": 2, "batch_size": 8},
    )
    path = str(tmp_path / "estimator")
    est.save(path)
    loaded = KerasImageFileEstimator.load(path)
    assert loaded.getKerasLoss() == "sparse_categorical_crossentropy"
    assert loaded.getKerasFitParams()["epochs"] == 2
    assert loaded.getImageLoader() is loader_8x8  # pickled by reference
    assert loaded.getModelFile().startswith(path)

    from sparkdl_tpu.image.imageIO import filesToDF

    df = filesToDF(tpu_session, image_dir, numPartitions=2)
    df = df.withColumn(
        "label", lambda u: int(loader_8x8(u).mean() > 0.45), "filePath"
    )
    fitted = loaded.fit(df)
    assert isinstance(fitted, KerasImageFileTransformer)


# ---------------------------------------------------------------------------
# Pipeline / tuning
# ---------------------------------------------------------------------------


def test_pipeline_roundtrip_unfitted(tmp_path):
    pipe = Pipeline(
        stages=[
            TFTransformer(
                tfInputGraph=_double_fn(),
                inputMapping={"x": "x"},
                outputMapping={"y": "features"},
            ),
            LogisticRegression(maxIter=10),
        ]
    )
    path = str(tmp_path / "pipeline")
    pipe.save(path)
    loaded = Pipeline.load(path)
    stages = loaded.getStages()
    assert [type(s).__name__ for s in stages] == [
        "TFTransformer",
        "LogisticRegression",
    ]
    assert stages[1].getOrDefault(stages[1].maxIter) == 10


def test_pipeline_model_roundtrip(tpu_session, vector_df, tmp_path):
    pipe = Pipeline(
        stages=[
            TFTransformer(
                tfInputGraph=_double_fn(),
                inputMapping={"features": "x"},
                outputMapping={"y": "doubled"},
                batchSize=4,
            ),
            LogisticRegression(
                featuresCol="doubled", maxIter=40, stepSize=0.2
            ),
        ]
    )
    model = pipe.fit(vector_df)
    want = _collect_col(model.transform(vector_df), "prediction")

    path = str(tmp_path / "pipeline_model")
    model.save(path)
    loaded = PipelineModel.load(path)
    assert len(loaded.stages) == 2
    got = _collect_col(loaded.transform(vector_df), "prediction")
    assert got == want


def test_cross_validator_roundtrip(vector_df, tmp_path):
    lr = LogisticRegression(maxIter=20)
    grid = (
        ParamGridBuilder()
        .addGrid(lr.regParam, [0.0, 0.1])
        .addGrid(lr.maxIter, [10, 20])
        .build()
    )
    cv = CrossValidator(
        estimator=lr,
        estimatorParamMaps=grid,
        evaluator=MulticlassClassificationEvaluator(metricName="accuracy"),
        numFolds=2,
        parallelism=2,
        seed=7,
    )
    path = str(tmp_path / "cv")
    cv.save(path)
    loaded = CrossValidator.load(path)
    assert loaded.getOrDefault(loaded.numFolds) == 2
    assert loaded.getOrDefault(loaded.seed) == 7
    maps = loaded.getEstimatorParamMaps()
    assert len(maps) == 4
    # decoded params are re-anchored onto the restored estimator instance
    est = loaded.getEstimator()
    assert all(p.parent == est.uid for pmap in maps for p in pmap)
    values = sorted(
        tuple(sorted((p.name, v) for p, v in pmap.items())) for pmap in maps
    )
    assert values == sorted(
        tuple(sorted(d))
        for d in [
            {("regParam", 0.0), ("maxIter", 10)},
            {("regParam", 0.0), ("maxIter", 20)},
            {("regParam", 0.1), ("maxIter", 10)},
            {("regParam", 0.1), ("maxIter", 20)},
        ]
    )
    # the restored CV still fits end-to-end
    cv_model = loaded.fit(vector_df)
    assert isinstance(cv_model, CrossValidatorModel)


def test_cross_validator_model_roundtrip(vector_df, tmp_path):
    lr = LogisticRegression(maxIter=30)
    grid = ParamGridBuilder().addGrid(lr.regParam, [0.0, 0.5]).build()
    cv = CrossValidator(
        estimator=lr,
        estimatorParamMaps=grid,
        evaluator=MulticlassClassificationEvaluator(metricName="accuracy"),
        numFolds=2,
        seed=1,
    )
    model = cv.fit(vector_df)
    want = _collect_col(model.transform(vector_df), "prediction")

    path = str(tmp_path / "cv_model")
    model.save(path)
    loaded = CrossValidatorModel.load(path)
    assert loaded.avgMetrics == pytest.approx(model.avgMetrics)
    assert isinstance(loaded.bestModel, LogisticRegressionModel)
    got = _collect_col(loaded.transform(vector_df), "prediction")
    assert got == want


# ---------------------------------------------------------------------------
# Writer semantics
# ---------------------------------------------------------------------------


def test_save_refuses_existing_path_without_overwrite(tmp_path):
    lr = LogisticRegression(maxIter=5)
    path = str(tmp_path / "dup")
    lr.save(path)
    with pytest.raises(FileExistsError):
        lr.save(path)
    lr.write().overwrite().save(path)  # explicit overwrite succeeds
    assert LogisticRegression.load(path).getOrDefault(lr.maxIter) == 5


def test_reader_rejects_wrong_class(tmp_path):
    lr = LogisticRegression()
    path = str(tmp_path / "typed")
    lr.save(path)
    with pytest.raises(TypeError):
        TFTransformer.load(path)


def test_metadata_shape(tmp_path):
    lr = LogisticRegression(maxIter=5)
    path = str(tmp_path / "meta")
    lr.save(path)
    md = load_metadata(path)
    assert md["class"].endswith("LogisticRegression")
    assert md["uid"] == lr.uid
    assert md["params"]["maxIter"] == {"t": "json", "v": 5}


# ---------------------------------------------------------------------------
# Flax stages
# ---------------------------------------------------------------------------


def test_flax_image_file_transformer_roundtrip(
    tpu_session, image_dir, tmp_path
):
    """Fitted-Flax-model persistence: module + variables survive the trip
    and the reloaded transformer produces identical features."""
    import jax

    from sparkdl_tpu.estimators import FlaxImageFileTransformer
    from sparkdl_tpu.image.imageIO import filesToDF
    from sparkdl_tpu.models.vit import ViT

    module = ViT(variant="ViT-Ti/16", num_classes=3, image_size=8)
    variables = module.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8, 8, 3), jnp.float32)
    )
    t = FlaxImageFileTransformer(
        inputCol="filePath",
        outputCol="out",
        imageLoader=loader_8x8,
        module=module,
        variables=variables,
        batchSize=4,
    )
    df = filesToDF(tpu_session, image_dir, numPartitions=2)
    want = [r["out"].toArray() for r in t.transform(df).collect()]

    path = str(tmp_path / "flax_t")
    t.save(path)
    loaded = load_stage(path)
    assert isinstance(loaded, FlaxImageFileTransformer)
    assert loaded.batchSize == 4 and loaded.features_only is False
    got = [r["out"].toArray() for r in loaded.transform(df).collect()]
    np.testing.assert_allclose(
        np.stack(got), np.stack(want), rtol=1e-6, atol=1e-6
    )
