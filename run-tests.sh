#!/usr/bin/env bash
# Test runner — the reference's `python/run-tests.sh`† analog (SURVEY.md §2
# "CI" row).  The reference script exported SPARK_HOME and the assembly jar
# onto the classpath before running nose; here the equivalent environment is
# the virtual 8-device CPU mesh (conftest.py re-asserts these, so running
# bare pytest also works — this script is the pinned entry point).
#
# Usage:
#   ./run-tests.sh              # full suite
#   ./run-tests.sh -m 'not slow'  # skip multi-process tests
#   ./run-tests.sh tests/test_sql.py  # one file
set -euo pipefail
cd "$(dirname "$0")"

export KERAS_BACKEND=jax
export JAX_PLATFORMS=cpu
if [[ "${XLA_FLAGS:-}" != *xla_force_host_platform_device_count* ]]; then
  export XLA_FLAGS="${XLA_FLAGS:-} --xla_force_host_platform_device_count=8"
fi

exec python -m pytest tests/ -q "$@"
