#!/usr/bin/env bash
# Test runner — the reference's `python/run-tests.sh`† analog (SURVEY.md §2
# "CI" row).  The reference script exported SPARK_HOME and the assembly jar
# onto the classpath before running nose; here the equivalent environment is
# the virtual 8-device CPU mesh (conftest.py re-asserts these, so running
# bare pytest also works — this script is the pinned entry point).
#
# Usage:
#   ./run-tests.sh              # full suite
#   ./run-tests.sh -m 'not slow'  # skip multi-process tests
#   ./run-tests.sh tests/test_sql.py  # one file
set -euo pipefail
cd "$(dirname "$0")"

export KERAS_BACKEND=jax
export JAX_PLATFORMS=cpu
if [[ "${XLA_FLAGS:-}" != *xla_force_host_platform_device_count* ]]; then
  export XLA_FLAGS="${XLA_FLAGS:-} --xla_force_host_platform_device_count=8"
fi

log=$(mktemp)
set +e
python -m pytest tests/ -q -rs "$@" 2>&1 | tee "$log"
rc=${PIPESTATUS[0]}
set -e
if [[ $rc -ne 0 ]]; then
  rm -f "$log"
  exit "$rc"
fi

# Honesty gate (VERDICT r3 #7): a rig that ships every optional
# dependency (torch/transformers/keras/tensorflow/orbax, a C++ toolchain
# for the native targets) must report ZERO skipped tests — the suite's
# 241-passed-0-skipped signal is real; if oracle tests start silently
# skipping (a dep import regression, a guard typo), fail loudly instead
# of shrinking coverage.  Environment-INVERSE skips (tests that only run
# when a local imagenet cache is absent) are allowlisted; set
# SPARKDL_ALLOW_SKIPS=1 to disable the gate on partial rigs.
if [[ "${SPARKDL_ALLOW_SKIPS:-}" != "1" ]] && python -c '
import importlib.util as u, shutil, sys
deps = ("torch", "transformers", "keras", "tensorflow", "orbax.checkpoint")
ok = all(u.find_spec(m) for m in deps) and shutil.which("g++")
sys.exit(0 if ok else 1)
'; then
  if grep -E '^SKIPPED' "$log" | grep -vq 'imagenet cache exists'; then
    echo "run-tests: SKIPPED TESTS on a rig with all optional deps:" >&2
    grep -E '^SKIPPED|[0-9]+ skipped' "$log" | tail -20 >&2
    rm -f "$log"
    exit 1
  fi
fi
rm -f "$log"
