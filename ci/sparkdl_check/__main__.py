"""CLI: ``python -m ci.sparkdl_check [root] [options]``.

Exit status is 0 only when every finding is suppressed or baselined,
every file parsed, and no baseline entry is stale.
"""

from __future__ import annotations

import argparse
import sys
from pathlib import Path

from ci.sparkdl_check import (
    REGISTRY,
    all_rule_ids,
    load_baseline,
    run_check,
    write_baseline,
)
from ci.sparkdl_check.report import json_report, text_report


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m ci.sparkdl_check",
        description="sparkdl static-analysis: one parse, every rule.",
    )
    p.add_argument("root", nargs="?", default="sparkdl_tpu",
                   help="directory (or single file) to scan")
    p.add_argument("--rules", help="comma-separated rule ids (default: all)")
    p.add_argument("--format", choices=("text", "json"), default="text")
    p.add_argument("--baseline", type=Path, default=None,
                   help="baseline file (default: ci/sparkdl_check/baseline.json)")
    p.add_argument("--no-baseline", action="store_true",
                   help="ignore the baseline (report every finding)")
    p.add_argument("--write-baseline", action="store_true",
                   help="write current findings as the new baseline and exit 0")
    p.add_argument("--list-rules", action="store_true")
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    if args.list_rules:
        for rid in all_rule_ids():
            cls = REGISTRY[rid]
            print(f"{rid:18s} [{cls.severity}] {cls.doc}")
        return 0
    rule_ids = (
        [r.strip() for r in args.rules.split(",") if r.strip()]
        if args.rules else None
    )
    baseline = None if args.no_baseline else load_baseline(args.baseline)
    if args.write_baseline:
        # findings with no baseline applied ARE the new baseline
        report = run_check(Path(args.root), rule_ids, baseline=None)
        path = write_baseline(report.findings, args.baseline)
        print(f"wrote {len(report.findings)} finding(s) to {path}")
        return 0
    report = run_check(Path(args.root), rule_ids, baseline=baseline)
    out = json_report(report) if args.format == "json" else text_report(report)
    print(out)
    return report.exit_code


if __name__ == "__main__":
    sys.exit(main())
